"""Batched-verify acceptance smoke (the PR-16 RLC combined-check lane).

    JAX_PLATFORMS=cpu python probes/probe_batchverify.py

Runs a REAL serve.CredentialService in mode="batched" on the python
backend: 64 credentials (one forged sigma_2) submitted as ONE combined
batch, verified by a single random-linear-combination pairing check
that FAILS loudly and is then bisected (predicate="combined", fresh
per-sub-batch exponents) down to the culprit lane. Asserts the
properties ISSUE 16 promises:

  - the forged lane's future ALONE settles False; all 63 survivors
    settle True through the same batch;
  - the dead-letter record carries the program name ("verify") and the
    exact lane index of the culprit;
  - attribution is cheap: O(log B) combined re-checks, so the total
    final-exponentiation count stays well under the exact path's B;
  - a second, all-valid batch needs exactly ONE combined check and ONE
    final exponentiation — the steady-state fast path.

Prints a one-line JSON report (check/fallback/final-exp counters +
timings) for the CI log. PROBE_BATCHVERIFY_LANES overrides the batch
width (default 64). Runs on the CPU in well under a minute.
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics
from coconut_tpu.backend import get_backend
from coconut_tpu.faults import DeadLetterLog
from coconut_tpu.ops.fields import R
from coconut_tpu.params import Params
from coconut_tpu.serve.service import CredentialService
from coconut_tpu.signature import Signature, Sigkey, Verkey

LANES = int(os.environ.get("PROBE_BATCHVERIFY_LANES", "64"))
FORGED = LANES // 2 + 1  # an arbitrary interior lane
Q = 1  # single-message credentials keep the python backend fast

rng = random.Random(0xB16C64)


def _keypair(params):
    sk = Sigkey(rng.randrange(1, R), [rng.randrange(1, R) for _ in range(Q)])
    ops = params.ctx.other
    vk = Verkey(
        ops.mul(params.g_tilde, sk.x),
        [ops.mul(params.g_tilde, y) for y in sk.y],
    )
    return sk, vk


def _sign(sk, msgs, params):
    ops = params.ctx.sig
    s1 = ops.mul(params.g, rng.randrange(1, R))
    expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
    return Signature(s1, ops.mul(s1, expo))


def main():
    metrics.reset()
    t0 = time.perf_counter()
    params = Params.new(Q, b"probe-batchverify")
    sk, vk = _keypair(params)
    backend = get_backend("python")

    msgs_list = [[rng.randrange(R)] for _ in range(LANES)]
    sigs = [_sign(sk, m, params) for m in msgs_list]
    # forge ONE lane: shift sigma_2 off the PS relation by +g
    bad = sigs[FORGED]
    sigs[FORGED] = Signature(
        bad.sigma_1, params.ctx.sig.add(bad.sigma_2, params.g)
    )

    dlq = os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        "probe_batchverify_dead.%d.jsonl" % os.getpid(),
    )
    if os.path.exists(dlq):
        os.unlink(dlq)

    svc = CredentialService(
        backend,
        vk,
        params,
        mode="batched",
        max_batch=LANES,
        max_wait_ms=50.0,
        dead_letter_path=dlq,
    ).start()
    try:
        futs = [svc.submit(s, m) for s, m in zip(sigs, msgs_list)]
        verdicts = [f.result(timeout=300.0) for f in futs]
    finally:
        assert svc.drain(timeout=60.0)
    t_forged = time.perf_counter() - t0

    # the forged lane's future ALONE fails; every survivor settles True
    expected = [i != FORGED for i in range(LANES)]
    assert verdicts == expected, (
        "verdict demux broken: forged=%d got %r"
        % (FORGED, [i for i, v in enumerate(verdicts) if not v])
    )

    # dead-letter carries the program name and the exact lane index
    records = DeadLetterLog.read(dlq)
    assert len(records) == 1, records
    assert records[0]["program"] == "verify", records
    assert records[0]["credential"] == FORGED, records
    assert metrics.get_count("dead_letters") == 1

    # attribution was bisection, not per-lane: O(log B) combined checks,
    # each ONE final exponentiation — far fewer than the exact path's B
    checks = metrics.get_count("verify_batched_checks")
    fexps = metrics.get_count("verify_final_exps")
    assert checks >= 2, checks  # the batch + at least one probe
    assert fexps < LANES, (fexps, LANES)

    # steady state: an all-valid batch is ONE combined check + ONE
    # final exponentiation
    metrics.reset()
    good = [_sign(sk, m, params) for m in msgs_list]
    t1 = time.perf_counter()
    svc2 = CredentialService(
        backend, vk, params, mode="batched", max_batch=LANES,
        max_wait_ms=50.0, dead_letter_path=dlq,
    ).start()
    try:
        futs = [svc2.submit(s, m) for s, m in zip(good, msgs_list)]
        assert all(f.result(timeout=300.0) for f in futs)
    finally:
        assert svc2.drain(timeout=60.0)
    t_clean = time.perf_counter() - t1
    assert metrics.get_count("verify_batched_checks") == 1
    assert metrics.get_count("verify_final_exps") == 1
    assert len(DeadLetterLog.read(dlq)) == 1  # no new dead letters

    os.unlink(dlq)
    print(
        json.dumps(
            {
                "lanes": LANES,
                "forged_lane": FORGED,
                "bisection_checks": checks,
                "forged_final_exps": fexps,
                "clean_final_exps": 1,
                "forged_batch_s": round(t_forged, 3),
                "clean_batch_s": round(t_clean, 3),
            },
            sort_keys=True,
        )
    )
    print(
        "batchverify probe: ok (%d lanes, forged lane %d attributed in "
        "%d combined checks)" % (LANES, FORGED, checks)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
