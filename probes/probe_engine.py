"""Unified-engine acceptance smoke (the PR-12 mixed-program check).

    JAX_PLATFORMS=cpu python probes/probe_engine.py

Runs a REAL engine.ProtocolEngine — all FIVE Coconut phases (prepare,
mint, show_prove, show_verify, verify) registered on ONE engine with a
2-executor device pool and a 3-authority t=2 mint pool — on the python
backend (small 3-message params), injects ONE executor-loop crash (via
faults.FaultyBackend crash_on) into the shared pool mid-workload, and
asserts the properties ISSUE 12 promises:

  - every submitted future SETTLES, across every program, despite the
    crash (containment + redistribution keep the mixed workload whole);
  - the full session round-trips: prepared requests mint, minted
    credentials verify AND show-verify — the phases compose online;
  - the crash is contained and attributed: serve_executor_crashes >= 1
    with the batch redistributed, while every other program's traffic
    keeps flowing through the surviving executor;
  - the per-program jit-shape counters are FLAT after warmup — the
    heterogeneous batch mix never cross-program recompiles.

Prints a one-line JSON report (per-program completion counts + crash
containment counters + jit-shape counters) for the CI log. Everything
runs on the CPU in a few seconds.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.faults import FaultyBackend
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.params import Params
from coconut_tpu.sss import rand_fr

THRESHOLD, TOTAL, SESSIONS = 2, 3, 6
NAMESPACES = ("serve", "prep", "prove", "showv")


def main():
    metrics.reset()
    params = Params.new(3, b"probe-engine")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    py = get_backend("python")
    faulty = FaultyBackend(py)
    # the injected crash must land on a POOL executor: give the mint
    # resolution crypto its own clean minter, or the scheduled verify-
    # dispatch crash would fire inside minter.verify on an authority
    # thread instead
    from coconut_tpu.issue.quorum import CryptoMinter

    minter = CryptoMinter(
        THRESHOLD, {s.id: s.verkey for s in signers}, params, backend=py
    )
    engine = ProtocolEngine(
        signers,
        params,
        THRESHOLD,
        count_hidden=1,
        revealed_msg_indices=[1, 2],
        backend=faulty,
        minter=minter,
        devices=2,
        max_batch=4,
        max_wait_ms=5.0,
    ).start()
    try:
        identities = []
        for _ in range(SESSIONS):
            msgs = [rand_fr(), rand_fr(), rand_fr()]
            esk, epk = elgamal_keygen(params.ctx.sig, params.g)
            identities.append((msgs, epk, esk))

        # warmup: ONE full session so every program's serving shape is
        # compiled before the jit-shape counters are snapshotted
        msgs, epk, esk = identities[0]
        req, _ = engine.submit_prepare(msgs, epk).result(timeout=120.0)
        cred = engine.submit_mint(req, msgs, esk).result(timeout=120.0)
        assert engine.submit_verify(cred, msgs).result(timeout=120.0)
        proof, chal, rev = engine.submit_show_prove(cred, msgs).result(
            timeout=120.0
        )
        assert engine.submit_show_verify(proof, rev, chal).result(
            timeout=120.0
        )
        jit_warm = {
            ns: metrics.get_count("%s_jit_shapes" % ns) for ns in NAMESPACES
        }

        # schedule ONE executor-loop crash on the NEXT verify dispatch,
        # then drive the full mixed workload through the wounded pool
        faulty.crash_on = frozenset({faulty.dispatches})

        prep_futs = [
            engine.submit_prepare(m, pk) for m, pk, _ in identities
        ]
        prepared = [f.result(timeout=120.0) for f in prep_futs]
        mint_futs = [
            engine.submit_mint(req, m, sk)
            for (req, _), (m, _, sk) in zip(prepared, identities)
        ]
        creds = [f.result(timeout=120.0) for f in mint_futs]
        # verify + show_prove submitted TOGETHER: heterogeneous batches
        # multiplex over the same (now one-short) pool
        verify_futs = [
            engine.submit_verify(c, m)
            for c, (m, _, _) in zip(creds, identities)
        ]
        prove_futs = [
            engine.submit_show_prove(c, m)
            for c, (m, _, _) in zip(creds, identities)
        ]
        verdicts = [f.result(timeout=120.0) for f in verify_futs]
        proofs = [f.result(timeout=120.0) for f in prove_futs]
        show_futs = [
            engine.submit_show_verify(p, rev, c)
            for (p, c, rev) in proofs
        ]
        shows = [f.result(timeout=120.0) for f in show_futs]
    finally:
        assert engine.drain(timeout=60.0), "drain timed out"

    assert all(verdicts), "a minted credential failed verify: %r" % (
        verdicts,
    )
    assert all(shows), "a minted credential failed show-verify: %r" % (
        shows,
    )

    crashes = metrics.get_count("serve_executor_crashes")
    redistributed = metrics.get_count("serve_redistributed_batches")
    assert faulty.crashes == 1, "crash injection never dispatched"
    assert crashes >= 1, "the executor crash was never contained"
    jit_end = {
        ns: metrics.get_count("%s_jit_shapes" % ns) for ns in NAMESPACES
    }
    assert jit_end == jit_warm, (
        "cross-program recompile after warmup: %r -> %r"
        % (jit_warm, jit_end)
    )

    print(
        json.dumps(
            {
                "sessions": SESSIONS,
                "minted": metrics.get_count("issue_minted"),
                "prepared": metrics.get_count("prep_done"),
                "proofs": metrics.get_count("prove_done"),
                "show_valid": metrics.get_count("showv_valid"),
                "verify_valid": metrics.get_count("serve_valid"),
                "executor_crashes": crashes,
                "redistributed_batches": redistributed,
                "jit_shapes": jit_end,
            },
            sort_keys=True,
        )
    )
    print(
        "engine probe: ok (%d sessions, 5 programs, 1 crash contained)"
        % SESSIONS
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
