"""Threshold-issuance acceptance smoke (the issue lane's end-to-end check).

    JAX_PLATFORMS=cpu python probes/probe_issue.py

Runs a REAL 5-authority t=3 IssuanceService on the python backend (small
2-message params) and injects — via faults.FaultyBackend sign-path
schedules — ONE authority-loop crash and ONE hung sign dispatch on the
very first fan-out, then asserts the properties ISSUE 10 promises:

  - every submitted order MINTS: no dropped futures, no dangling quorum,
    despite 2 of 5 authorities failing mid-fan-out (first-t-of-n rides
    the 3 survivors);
  - every minted credential VERIFIES under the Lagrange-aggregated
    verkey of the surviving subset — the release gate is real;
  - the crash is contained and attributed: issue_authority_crashes >= 1
    and the culprit authority is quarantined, while the pool keeps
    minting.

Prints a one-line JSON report (mint counts + quorum-wait percentiles +
health counters) for the CI log. Everything runs on the CPU in a few
seconds; the hang is Event-released before drain so no thread outlives
the probe.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.faults import FaultyBackend
from coconut_tpu.issue import IssuanceService
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.params import Params
from coconut_tpu.signature import SignatureRequest, Verkey
from coconut_tpu.sss import rand_fr

THRESHOLD, TOTAL, ORDERS = 3, 5, 8


def main():
    metrics.reset()
    params = Params.new(2, b"probe-issue")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    py = get_backend("python")
    # authority 2 crashes on its first sign; authority 3 hangs on its
    # first sign — the pool must mint through 1, 4, 5
    crasher = FaultyBackend(py, crash_sign_on=(0,))
    hanger = FaultyBackend(py, hang_sign_on=(0,), hang_max_s=30.0)
    svc = IssuanceService(
        signers,
        params,
        THRESHOLD,
        backend="python",
        backends=[py, crasher, hanger, py, py],
        max_batch=4,
        max_wait_ms=5.0,
    ).start()
    try:
        orders = []
        for _ in range(ORDERS):
            msgs = [rand_fr(), rand_fr()]
            sk, pk = elgamal_keygen(params.ctx.sig, params.g)
            req, _ = SignatureRequest.new(msgs, 1, pk, params)
            orders.append((req, msgs, sk))
        futs = [svc.submit(req, msgs, sk) for req, msgs, sk in orders]
        creds = [fut.result(timeout=120.0) for fut in futs]
    finally:
        hanger.hang_release.set()  # free the wedged worker before drain
        assert svc.drain(timeout=60.0), "drain timed out"

    # every order minted, and every minted credential verifies under the
    # surviving subset's aggregated verkey (subset-independence: any
    # t-subset's aggregated verkey is the same group element)
    vk = Verkey.aggregate(
        THRESHOLD,
        [(s.id, s.verkey) for s in signers if s.id in (1, 4, 5)],
        ctx=params.ctx,
    )
    verified = sum(
        1
        for cred, (_, msgs, _) in zip(creds, orders)
        if cred.verify(msgs, vk, params)
    )
    assert verified == ORDERS, "only %d/%d credentials verify" % (
        verified,
        ORDERS,
    )

    minted = metrics.get_count("issue_minted")
    crashes = metrics.get_count("issue_authority_crashes")
    quarantined = metrics.get_count("issue_quarantined")
    unreachable = metrics.get_count("issue_quorum_unreachable")
    assert minted == ORDERS, "service minted %d of %d" % (minted, ORDERS)
    assert crashes >= 1, "the authority crash was never contained"
    assert crasher.crashes == 1, "crash injection never dispatched"
    assert quarantined >= 1, "the crashed authority was not quarantined"
    assert unreachable == 0, "a fan-out lost quorum with 3 live authorities"

    hist = metrics.snapshot().get("histograms", {})
    qwait = hist.get("issue_quorum_wait_s", {})
    print(
        json.dumps(
            {
                "minted": minted,
                "verified": verified,
                "authority_crashes": crashes,
                "quarantined": quarantined,
                "watchdog_timeouts": metrics.get_count(
                    "issue_watchdog_timeouts"
                ),
                "hedges": metrics.get_count("issue_hedges"),
                "partials_discarded": metrics.get_count(
                    "issue_partials_discarded"
                ),
                "quorum_wait_s": {
                    "p50": qwait.get("p50_s"),
                    "p95": qwait.get("p95_s"),
                },
            },
            sort_keys=True,
        )
    )
    print("issue probe: ok (%d/%d minted+verified)" % (verified, ORDERS))
    return 0


if __name__ == "__main__":
    sys.exit(main())
