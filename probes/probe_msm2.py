"""probe_msm2.py <window> <group:g1|g2> <B>: full comb MSM differential."""
import random, sys, time
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.tpu.backend import JaxBackend

grp = sys.argv[2]
B = int(sys.argv[3]) if len(sys.argv) > 3 else 16
k = int(sys.argv[4]) if len(sys.argv) > 4 else 7
rng = random.Random(11)
be = JaxBackend()
ops, gen, fn = (
    (g1, G1_GEN, be.msm_g1_shared) if grp == "g1" else (g2, G2_GEN, be.msm_g2_shared)
)
bases = [ops.mul(gen, rng.randrange(1, R)) for _ in range(k)]
scal = [[rng.randrange(R) for _ in range(k)] for _ in range(B)]
scal[B // 2][min(3, k - 1)] = 0
t0 = time.time()
got = fn(bases, scal)
t_build = time.time() - t0
bad = sum(g != ops.msm(bases, row) for row, g in zip(scal, got))
print("window=%s %s k=%d B=%d bad=%d build=%.1fs" % (sys.argv[1], grp, k, B, bad, t_build))
