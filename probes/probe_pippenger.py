"""probe_pippenger.py: bucketed-vs-Horner distinct-MSM micro-probe
(PR 18). Times the legacy signed-Horner schedule against the bucketed
Pippenger schedule at a sweep of (B, k, window) shapes, checks every
lane against the Python spec, and prints the per-stage split the cost
model in tpu/backend.py (_bucket_cost/_horner_cost) predicts.

Usage: python probe_pippenger.py [B] [k]   (defaults 16, 32)
PROBE_MSM_WINDOWS=3,5 limits the window sweep."""
import os
import random
import sys
import time

sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu

coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu import metrics
from coconut_tpu.ops.curve import G1_GEN, g1
from coconut_tpu.ops.fields import R
import coconut_tpu.tpu.backend as tb

B = int(sys.argv[1]) if len(sys.argv) > 1 else 16
k = int(sys.argv[2]) if len(sys.argv) > 2 else 32
windows = [
    int(w)
    for w in os.environ.get("PROBE_MSM_WINDOWS", "3,5,8").split(",")
]
rng = random.Random(31)
be = tb.JaxBackend()
pts = [
    [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(k)]
    for _ in range(B)
]
scal = [[rng.randrange(R) for _ in range(k)] for _ in range(B)]
scal[0][0] = 0
ref = [g1.msm(p, s) for p, s in zip(pts, scal)]

glv_k = 2 * k if tb._GLV_ENABLED else k
nbits = 128 if tb._GLV_ENABLED else 255
print(
    "B=%d k=%d (effective k=%d, %d-bit windows) horner-model=%.0f"
    % (B, k, glv_k, nbits, tb._horner_cost(glv_k, nbits))
)


def run(label, mode):
    tb._BUCKET_MODE = mode
    t0 = time.time()
    got = be.msm_g1_distinct(pts, scal)
    t_build = time.time() - t0
    t0 = time.time()
    got = be.msm_g1_distinct(pts, scal)
    t_warm = time.time() - t0
    bad = sum(g != r for g, r in zip(got, ref))
    print(
        "%-12s bad=%d build=%6.1fs warm=%7.3fs"
        % (label, bad, t_build, t_warm)
    )
    assert bad == 0, "%s: %d lanes diverge from spec" % (label, bad)
    return t_warm


t_h = run("horner", "off")
h0 = metrics.get_count("msm_bucketed_dispatches")
for w in windows:
    t_b = run("bucket w=%d" % w, w)
    print(
        "  model=%.0f vs horner %.0f -> speedup x%.2f (measured)"
        % (
            tb._bucket_cost(glv_k, nbits, w),
            tb._horner_cost(glv_k, nbits),
            t_h / t_b,
        )
    )
# each run() dispatches twice (build + warm)
assert metrics.get_count("msm_bucketed_dispatches") - h0 == 2 * len(windows)
tb._BUCKET_MODE = "auto"
auto_w = tb._bucket_window(glv_k, nbits)
print("auto window for effective k=%d: %s" % (glv_k, auto_w))
tb._BUCKET_MODE = None
print("parity OK")
