"""probe_pippenger.py: bucketed-vs-Horner distinct-MSM micro-probe
(PR 18). Times the legacy signed-Horner schedule against the bucketed
Pippenger schedule at a sweep of (B, k, window) shapes, checks every
lane against the Python spec, and prints the per-stage split the cost
model in tpu/backend.py (_bucket_cost/_horner_cost) predicts.

Usage: python probe_pippenger.py [B] [k]   (defaults 16, 32)
PROBE_MSM_WINDOWS=3,5 limits the window sweep.

--calibrate (PR 19, ISSUE 18 follow-on): measure the bucket-vs-Horner
crossover ON THE LIVE BACKEND instead of trusting the cost model.
Sweeps per-row base counts (PROBE_CALIB_KS, default 4,8,16,32) at
PROBE_CALIB_B rows (default 8), times the warm Horner schedule against
each swept window, reports where measurement and _bucket_cost/
_horner_cost disagree, and emits a COCONUT_MSM_WINDOW recommendation
line (=0 when Horner wins everywhere swept — the expected verdict on
the CPU test mesh, where the auto policy already forces Horner)."""
import os
import random
import sys
import time

sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu

coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu import metrics
from coconut_tpu.ops.curve import G1_GEN, g1
from coconut_tpu.ops.fields import R
import coconut_tpu.tpu.backend as tb

CALIBRATE = "--calibrate" in sys.argv[1:]
argv = [a for a in sys.argv[1:] if a != "--calibrate"]
B = int(argv[0]) if len(argv) > 0 else 16
k = int(argv[1]) if len(argv) > 1 else 32
windows = [
    int(w)
    for w in os.environ.get("PROBE_MSM_WINDOWS", "3,5,8").split(",")
]
rng = random.Random(31)
be = tb.JaxBackend()

nbits_glv = 128 if tb._GLV_ENABLED else 255


def make_case(b, kk):
    p = [
        [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(kk)]
        for _ in range(b)
    ]
    s = [[rng.randrange(R) for _ in range(kk)] for _ in range(b)]
    s[0][0] = 0
    return p, s, [g1.msm(pi, si) for pi, si in zip(p, s)]


def timed(mode, p, s, r):
    """Warm time of one schedule on (p, s); asserts spec parity."""
    tb._BUCKET_MODE = mode
    be.msm_g1_distinct(p, s)  # build/compile outside the clock
    t0 = time.time()
    got = be.msm_g1_distinct(p, s)
    t = time.time() - t0
    bad = sum(g != x for g, x in zip(got, r))
    assert bad == 0, "mode=%r: %d lanes diverge from spec" % (mode, bad)
    return t


def calibrate():
    calib_b = int(os.environ.get("PROBE_CALIB_B", "8"))
    ks = [
        int(x)
        for x in os.environ.get("PROBE_CALIB_KS", "4,8,16,32").split(",")
    ]
    print(
        "calibrating bucket-vs-Horner crossover: B=%d ks=%r windows=%r "
        "(GLV=%s -> effective k doubles, %d-bit scalars)"
        % (calib_b, ks, windows, tb._GLV_ENABLED, nbits_glv)
    )
    measured_cross = None  # smallest swept k where a bucketed window wins
    model_cross = None
    best_at_max = None  # (window, speedup) at the largest swept k
    for kk in ks:
        ek = 2 * kk if tb._GLV_ENABLED else kk
        p, s, r = make_case(calib_b, kk)
        t_h = timed("off", p, s, r)
        c_h = tb._horner_cost(ek, nbits_glv)
        best_w, best_t = None, t_h
        for w in windows:
            t_b = timed(w, p, s, r)
            verdict_m = "bucket" if t_b < t_h else "horner"
            verdict_c = (
                "bucket"
                if tb._bucket_cost(ek, nbits_glv, w) < c_h
                else "horner"
            )
            print(
                "  k=%-4d w=%d measured %7.3fs vs horner %7.3fs -> %s"
                "   (model says %s%s)"
                % (
                    kk, w, t_b, t_h, verdict_m, verdict_c,
                    "" if verdict_m == verdict_c else "  ** DISAGREE",
                )
            )
            if t_b < best_t:
                best_w, best_t = w, t_b
        if best_w is not None and measured_cross is None:
            measured_cross = kk
        if best_w is not None:
            best_at_max = (best_w, t_h / best_t)
        model_w = min(
            range(2, 9), key=lambda w: tb._bucket_cost(ek, nbits_glv, w)
        )
        if (
            model_cross is None
            and tb._bucket_cost(ek, nbits_glv, model_w) < c_h
        ):
            model_cross = kk
    print(
        "calibration: crossover_measured=%s crossover_model=%s"
        % (measured_cross or "none", model_cross or "none")
    )
    if best_at_max is not None:
        w, speedup = best_at_max
        print(
            "recommend COCONUT_MSM_WINDOW=%d for workloads at k>=%d "
            "(measured x%.2f over Horner at the largest swept shape)"
            % (w, measured_cross, speedup)
        )
    else:
        print(
            "recommend COCONUT_MSM_WINDOW=0 (Horner won every swept "
            "shape on this backend)"
        )
    tb._BUCKET_MODE = None
    print("calibration OK")


if CALIBRATE:
    calibrate()
    sys.exit(0)

pts = [
    [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(k)]
    for _ in range(B)
]
scal = [[rng.randrange(R) for _ in range(k)] for _ in range(B)]
scal[0][0] = 0
ref = [g1.msm(p, s) for p, s in zip(pts, scal)]

glv_k = 2 * k if tb._GLV_ENABLED else k
nbits = 128 if tb._GLV_ENABLED else 255
print(
    "B=%d k=%d (effective k=%d, %d-bit windows) horner-model=%.0f"
    % (B, k, glv_k, nbits, tb._horner_cost(glv_k, nbits))
)


def run(label, mode):
    tb._BUCKET_MODE = mode
    t0 = time.time()
    got = be.msm_g1_distinct(pts, scal)
    t_build = time.time() - t0
    t0 = time.time()
    got = be.msm_g1_distinct(pts, scal)
    t_warm = time.time() - t0
    bad = sum(g != r for g, r in zip(got, ref))
    print(
        "%-12s bad=%d build=%6.1fs warm=%7.3fs"
        % (label, bad, t_build, t_warm)
    )
    assert bad == 0, "%s: %d lanes diverge from spec" % (label, bad)
    return t_warm


t_h = run("horner", "off")
h0 = metrics.get_count("msm_bucketed_dispatches")
for w in windows:
    t_b = run("bucket w=%d" % w, w)
    print(
        "  model=%.0f vs horner %.0f -> speedup x%.2f (measured)"
        % (
            tb._bucket_cost(glv_k, nbits, w),
            tb._horner_cost(glv_k, nbits),
            t_h / t_b,
        )
    )
# each run() dispatches twice (build + warm)
assert metrics.get_count("msm_bucketed_dispatches") - h0 == 2 * len(windows)
tb._BUCKET_MODE = "auto"
auto_w = tb._bucket_window(glv_k, nbits)
print("auto window for effective k=%d: %s" % (glv_k, auto_w))
tb._BUCKET_MODE = None
print("parity OK")
