"""Chrome-trace/Perfetto export validator (the obs lane's smoke check).

    python probes/probe_trace.py <trace.json>    # validate an export
    python probes/probe_trace.py                 # self-test: generate one

Checks the properties tooling relies on, not just JSON well-formedness:

  - the document is valid JSON with a non-empty `traceEvents` list and
    every event carries name/ph/ts/pid/tid (complete "X" events also a
    non-negative dur) — the Perfetto loader's minimum;
  - `ts` is monotonically non-decreasing across the event stream (the
    exporter sorts; an unsorted stream renders but scrambles Perfetto's
    flow rails);
  - the span tree reconstructed from args.span_id/parent_id is
    consistent: every child starts and ends inside its parent's
    interval, and each span's dur >= the sum of its children's durs
    (children are sequential stages of their parent — if this fails the
    instrumentation double-counted a stage or leaked a clock).

Used by ci.sh's obs lane on a trace generated from a real (CPU, stub
backend) serve run with an injected fault, and imported by
tests/test_obs.py to validate in-test exports.
"""

import json
import sys

#: float-microsecond rounding slack when comparing interval arithmetic
EPS_US = 0.5


def validate(path):
    """Validate one Chrome-trace JSON file; returns a stats dict, raises
    AssertionError (with a pointed message) on the first violation."""
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list), (
        "not a Chrome trace document (want {'traceEvents': [...]})"
    )
    events = doc["traceEvents"]
    assert events, "traceEvents is empty"

    last_ts = None
    spans = {}  # span_id -> (name, ts, dur, parent_id)
    for i, ev in enumerate(events):
        for key in ("name", "ph", "ts", "pid", "tid"):
            assert key in ev, "event %d missing %r: %r" % (i, key, ev)
        assert ev["ts"] >= 0, "event %d has negative ts: %r" % (i, ev)
        if last_ts is not None:
            assert ev["ts"] >= last_ts, (
                "ts not monotonic at event %d: %r < %r"
                % (i, ev["ts"], last_ts)
            )
        last_ts = ev["ts"]
        if ev["ph"] == "X":
            assert ev.get("dur", -1) >= 0, (
                "X event %d has no/negative dur: %r" % (i, ev)
            )
            args = ev.get("args", {})
            sid = args.get("span_id")
            if sid is not None:
                spans[sid] = (
                    ev["name"],
                    ev["ts"],
                    ev["dur"],
                    args.get("parent_id"),
                )

    children = {}
    for sid, (name, ts, dur, parent) in spans.items():
        if parent is not None and parent in spans:
            children.setdefault(parent, []).append(sid)
            pname, pts, pdur, _ = spans[parent]
            assert ts >= pts - EPS_US and ts + dur <= pts + pdur + EPS_US, (
                "child span %r [%s, +%s] escapes parent %r [%s, +%s]"
                % (name, ts, dur, pname, pts, pdur)
            )
    for parent, kids in children.items():
        pname, _, pdur, _ = spans[parent]
        kid_total = sum(spans[k][2] for k in kids)
        assert pdur + EPS_US * len(kids) >= kid_total, (
            "span %r dur %s < sum of %d children %s (double-counted stage?)"
            % (pname, pdur, len(kids), kid_total)
        )
    return {
        "events": len(events),
        "spans": len(spans),
        "traces": len(
            {ev.get("args", {}).get("trace_id") for ev in events} - {None}
        ),
        "nested": sum(len(k) for k in children.values()),
    }


def _selftest():
    """Generate a small nested trace with a fake clock and validate it."""
    import os
    import tempfile

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )
    from coconut_tpu.obs import export, trace

    t = [0.0]

    def clock():
        t[0] += 0.001
        return t[0]

    tracer = trace.Tracer(clock=clock, ring=64)
    root = tracer.start("request")
    child = tracer.start("queue_wait", parent=root)
    child.event("retry", attempt=1)
    child.end()
    tracer.start("dispatch", parent=root).end()
    root.end()
    path = os.path.join(tempfile.mkdtemp(), "trace.json")
    export.write_chrome(tracer.tail(), path)
    return validate(path)


def main(argv):
    if len(argv) > 1:
        stats = validate(argv[1])
        src = argv[1]
    else:
        stats = _selftest()
        src = "selftest"
    print(
        "probe_trace: ok (%s: %d events, %d spans, %d traces, %d nested)"
        % (src, stats["events"], stats["spans"], stats["traces"], stats["nested"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
