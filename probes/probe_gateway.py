"""Fleet-gateway acceptance smoke (the PR-13 kill-a-replica check).

    JAX_PLATFORMS=cpu python probes/probe_gateway.py

Runs a REAL 3-replica fleet over loopback TCP sockets: three
engine.ProtocolEngine instances (python backend, small 3-message
params), each behind a net.Replica serve loop, fronted by a
net.ReplicaRouter with a live gossip thread polling health beacons.
Asserts the properties ISSUE 13 promises:

  - full prepare -> mint -> show sessions round-trip THROUGH the wire
    (session-affine routing, CTS-RPC/1 frames both ways);
  - per-tenant admission isolates tenants: the over-quota tenant is
    rejected with a typed TenantQuotaError while the fleet tenant's
    traffic on the SAME replica keeps flowing;
  - killing one replica mid-run (listener + connections closed) demotes
    it in the router's directory (missed beacons / data-path failure),
    and every in-flight future SETTLES via retry on the survivors —
    zero dangling futures;
  - the killed replica REJOINS via a fresh health beacon after its
    serve loop restarts, with no operator action beyond reconnecting,
    and affinity traffic returns to it.

Prints a one-line JSON report for the CI log. Everything runs on the
CPU in well under a minute.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import TenantQuotaError, TransientBackendError
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.params import Params
from coconut_tpu.retry import RetryPolicy
from coconut_tpu.sss import rand_fr

THRESHOLD, TOTAL = 2, 3
REPLICAS = 3
SESSIONS_BEFORE, SESSIONS_AFTER = 3, 3
FLEET_KEY, GREEDY_KEY = "key-fleet", "key-greedy"


def _connect(rid, replica, codec, api_key=FLEET_KEY):
    return net.GatewayClient(
        net.SocketTransport(replica.address),
        codec,
        api_key=api_key,
        session=rid,
    )


def _run_session(engine_like, params, timeout=120.0):
    """One full credential session; returns the final show verdict."""
    msgs = [rand_fr(), rand_fr(), rand_fr()]
    esk, epk = elgamal_keygen(params.ctx.sig, params.g)
    req, _ = engine_like.submit_prepare(msgs, epk).result(timeout)
    cred = engine_like.submit_mint(req, msgs, esk).result(timeout)
    proof, chal, rev = engine_like.submit_show_prove(cred, msgs).result(
        timeout
    )
    return engine_like.submit_show_verify(proof, rev, chal).result(timeout)


def _wait_state(directory, rid, want, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if directory.state(rid) == want:
            return True
        time.sleep(0.05)
    return directory.state(rid) == want


def main():
    metrics.reset()
    params = Params.new(3, b"probe-gateway")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    backend = get_backend("python")
    codec = net.WireCodec(params)

    tenants = net.TenantTable()
    tenants.provision("fleet", FLEET_KEY)
    tenants.provision("greedy", GREEDY_KEY, quota=2)

    engines, replicas = {}, {}
    for i in range(REPLICAS):
        rid = "r%d" % i
        engines[rid] = ProtocolEngine(
            signers,
            params,
            THRESHOLD,
            count_hidden=1,
            revealed_msg_indices=[1, 2],
            backend=backend,
            devices=1,
            max_batch=4,
            max_wait_ms=5.0,
        ).start()
        replicas[rid] = net.Replica(
            engines[rid], codec, tenants=tenants, replica_id=rid
        )
        replicas[rid].serve()

    clients = {
        rid: _connect(rid, rep, codec) for rid, rep in replicas.items()
    }
    router = net.ReplicaRouter(
        clients,
        retry_policy=RetryPolicy(
            max_attempts=REPLICAS + 1,
            base_delay=0.05,
            retryable=(TransientBackendError,),
        ),
    )
    # pollers read THROUGH router.clients so a rejoined replica's fresh
    # client is what the next sweep polls
    loop = net.GossipLoop(
        router.directory,
        {
            rid: (lambda r=rid: router.clients[r].poll_beacon(timeout=2.0))
            for rid in clients
        },
        interval_s=0.1,
    ).start()

    report = {"replicas": REPLICAS}
    try:
        # -- healthy fleet: session-affine full sessions ------------------
        completed = 0
        for i in range(SESSIONS_BEFORE):
            assert _run_session(
                router.bound("sess-%d" % i), params
            ), "session %d failed its show verdict" % i
            completed += 1

        # -- per-tenant isolation: over-quota tenant rejected ONLY --------
        some_rid = sorted(replicas)[0]
        greedy = _connect(
            some_rid, replicas[some_rid], codec, api_key=GREEDY_KEY
        )
        msgs = [rand_fr(), rand_fr(), rand_fr()]
        esk, epk = elgamal_keygen(params.ctx.sig, params.g)
        req, _ = greedy.submit_prepare(msgs, epk).result(120.0)
        cred = greedy.submit_mint(req, msgs, esk).result(120.0)
        quota_rejected = 0
        try:
            greedy.submit_verify(cred, msgs).result(120.0)
        except TenantQuotaError:
            quota_rejected = 1
        assert quota_rejected, "over-quota tenant was admitted"
        # the fleet tenant keeps flowing through the SAME replica
        fleet_direct = _connect(some_rid, replicas[some_rid], codec)
        assert fleet_direct.submit_verify(cred, msgs).result(120.0), (
            "fleet tenant was collaterally damaged by greedy's quota"
        )
        greedy.close()
        fleet_direct.close()

        # -- kill one replica with sessions in flight ---------------------
        victim = router.candidates("victim-probe")[0]
        # sessions whose ring PRIMARY is the victim, so the kill provably
        # forces failover (not just re-hashing onto a survivor)
        vic_sessions = [
            s
            for s in ("vic-%d" % k for k in range(500))
            if router.candidates(s)[0] == victim
        ][:6]
        assert len(vic_sessions) == 6, "ring too lopsided for the probe"
        in_flight = [
            router.submit_verify(cred, msgs, session=s)
            for s in vic_sessions[:4]
        ]
        replicas[victim].close()
        # and a couple AFTER the kill: the dead-socket path must also
        # settle via retry on the survivors
        in_flight += [
            router.submit_verify(cred, msgs, session=s)
            for s in vic_sessions[4:]
        ]
        settled = sum(1 for f in in_flight if f.result(120.0) is True)
        assert settled == len(in_flight), (
            "dangling futures after replica kill: %d of %d settled"
            % (settled, len(in_flight))
        )
        assert _wait_state(router.directory, victim, net.DOWN), (
            "router never demoted the killed replica (state=%s)"
            % router.directory.state(victim)
        )
        # sessions keep completing on the survivors
        for i in range(SESSIONS_AFTER):
            assert _run_session(
                router.bound("post-kill-%d" % i), params
            ), "post-kill session %d failed" % i
            completed += 1

        # -- rejoin via beacons -------------------------------------------
        replicas[victim].serve()
        old = router.clients[victim]
        router.clients[victim] = _connect(victim, replicas[victim], codec)
        old.close()
        assert _wait_state(router.directory, victim, net.UP), (
            "restarted replica never rejoined via beacons (state=%s)"
            % router.directory.state(victim)
        )
        assert router.route("victim-probe") == victim, (
            "affinity traffic did not return to the rejoined replica"
        )
        assert _run_session(
            router.bound("victim-probe"), params
        ), "session on the rejoined replica failed"
        completed += 1

        report.update(
            {
                "sessions_completed": completed,
                "in_flight_settled": settled,
                "quota_rejected": quota_rejected,
                "failovers": metrics.get_count("gateway_failovers"),
                "demoted": metrics.get_count("gateway_demoted"),
                "readmitted": metrics.get_count("gateway_readmitted"),
                "beacons": metrics.get_count("gateway_beacons"),
                "greedy_admitted": metrics.get_count(
                    "gateway_tenant_greedy_admitted"
                ),
                "greedy_quota_rejected": metrics.get_count(
                    "gateway_tenant_greedy_quota_rejected"
                ),
                "up_replicas": metrics.get_gauge("gateway_up_replicas"),
            }
        )
    finally:
        loop.stop(timeout=5.0)
        router.close()
        for rep in replicas.values():
            rep.close()
        for rid, eng in engines.items():
            assert eng.drain(timeout=60.0), "drain timed out on %s" % rid

    assert report["failovers"] >= 1, "kill never exercised failover"
    assert report["readmitted"] >= 1
    assert report["up_replicas"] == REPLICAS

    print(json.dumps(report, sort_keys=True))
    print(
        "gateway probe: ok (%d sessions, %d-replica fleet, 1 kill "
        "contained, rejoin via beacons)" % (
            report["sessions_completed"], REPLICAS,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
