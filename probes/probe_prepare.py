"""probe_prepare.py: cProfile the warm batch_prepare_blind_sign, and
report the host-hash vs device-hash split (PR 18). When
COCONUT_DEVICE_HASH=1 the probe ASSERTS the device hash path actually
ran (device_hash_batches counter moved, zero fallbacks).
PROBE_PREPARE_B overrides the batch size (default 1024)."""
import cProfile, os, pstats, sys, time
sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
import __graft_entry__ as ge
from coconut_tpu import metrics
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.signature import batch_prepare_blind_sign
from coconut_tpu.tpu.backend import JaxBackend

B = int(os.environ.get("PROBE_PREPARE_B", "1024"))
params, sk, vk, sigs, msgs_list = ge._fixture(batch=B)
be = JaxBackend()
esk, epk = elgamal_keygen(params.ctx.sig, params.g)
t0 = time.time()
batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
print("compile+run %.1fs" % (time.time() - t0))

hb0 = metrics.get_count("device_hash_batches")
hp0 = metrics.get_count("device_hash_points")
hf0 = metrics.get_count("device_hash_fallbacks")
best = None
for _ in range(3):
    t0 = time.time()
    batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
    dt = time.time() - t0
    best = dt if best is None else min(best, dt)
print("warm best %.3fs -> %.0f req/s" % (best, B / best))

dev_batches = metrics.get_count("device_hash_batches") - hb0
dev_points = metrics.get_count("device_hash_points") - hp0
fallbacks = metrics.get_count("device_hash_fallbacks") - hf0
host_points = 3 * B - dev_points  # 3 warm runs of B hashes each
print(
    "hash split: device=%d host=%d (batches=%d fallbacks=%d) knob=%s"
    % (
        dev_points,
        host_points,
        dev_batches,
        fallbacks,
        os.environ.get("COCONUT_DEVICE_HASH", "<unset>"),
    )
)
if os.environ.get("COCONUT_DEVICE_HASH") == "1":
    assert be.device_hash_enabled(), "knob=1 but device hash disabled"
    assert dev_batches == 3 and dev_points == 3 * B, (
        "COCONUT_DEVICE_HASH=1 but the device path did not run: "
        "batches=%d points=%d" % (dev_batches, dev_points)
    )
    assert fallbacks == 0, "%d device-hash fallbacks" % fallbacks
    print("device-path assertion OK")

pr = cProfile.Profile(); pr.enable()
batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
pr.disable()
pstats.Stats(pr).sort_stats("cumulative").print_stats(22)
