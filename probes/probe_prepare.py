"""probe_prepare.py: cProfile the warm batch_prepare_blind_sign at B=1024."""
import cProfile, pstats, sys, time
sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
import __graft_entry__ as ge
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.signature import batch_prepare_blind_sign
from coconut_tpu.tpu.backend import JaxBackend

params, sk, vk, sigs, msgs_list = ge._fixture(batch=1024)
be = JaxBackend()
esk, epk = elgamal_keygen(params.ctx.sig, params.g)
t0 = time.time()
batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
print("compile+run %.1fs" % (time.time() - t0))
best = None
for _ in range(3):
    t0 = time.time()
    batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
    dt = time.time() - t0
    best = dt if best is None else min(best, dt)
print("warm best %.3fs -> %.0f req/s" % (best, 1024 / best))
pr = cProfile.Profile(); pr.enable()
batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
pr.disable()
pstats.Stats(pr).sort_stats("cumulative").print_stats(22)
