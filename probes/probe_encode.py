"""probe_encode.py: per-stage host-encode timings (ISSUE-3 profiling aid).

Breaks the batch-encode wall into its stages so a profiling round can see
WHERE host time goes without instrumenting the backend:

  bytes-framing   fp_encode_raw_batch — to_bytes + frombuffer only (the
                  raw wire; Montgomery entry happens on device via
                  fp.to_mont)
  host-Montgomery fp_encode_batch — the bigint x*R%p + balance-carry path
                  the raw wire replaces
  digits          fr_digits_signed_np at the grouped 6-bit and comb
                  schedules
  tables          comb-table build, cold vs the static-operand/LRU caches
  full            encode_verify_batch / encode_grouped_batch, cold vs
                  cache-hot (the steady-state per-batch cost)

Host-only: no fused kernel runs (the one jitted program is the small comb
build). PROBE_BATCH overrides the 1024 default.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
if os.environ.get("JAX_PLATFORMS"):
    # the sitecustomize hook pins the tunneled-TPU platform at interpreter
    # start; config.update wins over both (same dance as tests/conftest.py)
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import coconut_tpu.tpu

coconut_tpu.tpu.enable_compile_cache()
import __graft_entry__ as ge
from coconut_tpu.ops.fields import R
from coconut_tpu.tpu import limbs
from coconut_tpu.tpu.backend import (
    _COMB_CACHE,
    _STATIC_CACHE,
    JaxBackend,
    _comb_digits,
    _comb_tables,
)


def t(label, fn, reps=3):
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    print("%-34s %8.2f ms" % (label, best * 1e3))
    return best


batch = int(os.environ.get("PROBE_BATCH", "1024"))
params, sk, vk, sigs, msgs_list = ge._fixture(batch=batch)
be = JaxBackend()
ctx = params.ctx

coords = [s.sigma_1[0] for s in sigs] + [s.sigma_1[1] for s in sigs]
coords += [s.sigma_2[0] for s in sigs] + [s.sigma_2[1] for s in sigs]
print("batch=%d  (%d Fp coords per batch upload)" % (batch, len(coords)))

t("bytes-framing (raw wire)", lambda: limbs.fp_encode_raw_batch(coords))
t("host Montgomery (legacy wire)", lambda: limbs.fp_encode_batch(coords))

scalars = [[1] + [m % R for m in msgs] for msgs in msgs_list]
t("digits: comb schedule", lambda: _comb_digits(scalars))
flat = [m % R for msgs in msgs_list for m in msgs]
t(
    "digits: grouped 6-bit (one row)",
    lambda: limbs.fr_digits_signed_np(flat[:batch], nwin=43, window=6),
)

bases = tuple([vk.X_tilde] + list(vk.Y_tilde))


def cold_tables():
    _COMB_CACHE.clear()
    _comb_tables(ctx.other, ctx.name == "G1", bases)


t("tables: comb build (cold)", cold_tables, reps=2)
t("tables: comb build (LRU hit)", lambda: _comb_tables(ctx.other, ctx.name == "G1", bases))


def cold_verify_encode():
    _COMB_CACHE.clear()
    _STATIC_CACHE.clear()
    be.encode_verify_batch(sigs, msgs_list, vk, params)


t("full: encode_verify_batch (cold)", cold_verify_encode, reps=2)
t(
    "full: encode_verify_batch (hot)",
    lambda: be.encode_verify_batch(sigs, msgs_list, vk, params),
)
t(
    "full: encode_grouped_batch (hot)",
    lambda: be.encode_grouped_batch(sigs, msgs_list, vk, params),
)

from coconut_tpu import metrics

snap = metrics.snapshot()["counters"]
print(
    "encode_cache_hits=%d encode_cache_misses=%d"
    % (snap.get("encode_cache_hits", 0), snap.get("encode_cache_misses", 0))
)
