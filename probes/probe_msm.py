"""Probe: full shared-comb MSM differential vs spec at the production base
count (k=7), all lanes checked. Usage: python probe_msm.py <window> <B>"""
import random
import sys
import time

import coconut_tpu.tpu

coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.curve import G2_GEN, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.tpu.backend import JaxBackend

B = int(sys.argv[2]) if len(sys.argv) > 2 else 16
rng = random.Random(11)
be = JaxBackend()
bases = [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(7)]
scal = [[rng.randrange(R) for _ in range(7)] for _ in range(B)]
scal[B // 2][3] = 0
t0 = time.time()
got = be.msm_g2_shared(bases, scal)
t_build = time.time() - t0
t0 = time.time()
got = be.msm_g2_shared(bases, scal)
t_warm = time.time() - t0
bad = sum(g != g2.msm(bases, row) for row, g in zip(scal, got))
print(
    "window=%s k=7 B=%d bad=%d build=%.1fs warm=%.2fs"
    % (sys.argv[1], B, bad, t_build, t_warm)
)
