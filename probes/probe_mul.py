"""probe_mul.py: ns/lane-mul of the Pallas Montgomery multiply, dependent
chain, on the chip. Env: COCONUT_PALLAS_KARATSUBA levels."""
import os, time
import numpy as np
import jax, jax.numpy as jnp
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.fields import P
from coconut_tpu.tpu import fp
from coconut_tpu.tpu.limbs import MONT_R, balanced_limbs_batch

N = 8192
CHAIN = 64
rng = np.random.default_rng(1)
vals = [int(x) % P for x in rng.integers(1, 2**63, size=N)]
a = jnp.asarray(balanced_limbs_batch([v * MONT_R % P for v in vals]))
b = jnp.asarray(balanced_limbs_batch([(v * 31 + 7) % P * MONT_R % P for v in vals]))

@jax.jit
def chain(a, b):
    x = a
    for _ in range(CHAIN):
        x = fp.mul(x, b)
    return x.sum()

out = chain(a, b); out.block_until_ready()
best = None
for _ in range(5):
    t0 = time.time(); _ = np.asarray(chain(a, b)); dt = time.time() - t0
    best = dt if best is None else min(best, dt)
print("levels=%s ns/lane-mul=%.1f (N=%d chain=%d best=%.4fs)" % (
    os.environ.get("COCONUT_PALLAS_KARATSUBA", "2"), best / (N * CHAIN) * 1e9, N, CHAIN, best))
