"""probe_show.py: cProfile the warm batch_show at B=1024 on the chip."""
import cProfile, pstats, sys, time
import sys; sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
sys.path.insert(0, "/root/repo")
import __graft_entry__ as ge
from coconut_tpu.pok_sig import batch_show
from coconut_tpu.tpu.backend import JaxBackend

params, sk, vk, sigs, msgs_list = ge._fixture(batch=1024)
be = JaxBackend()
t0 = time.time()
batch_show(sigs, vk, params, msgs_list, {2, 3, 4, 5}, backend=be)
print("compile+run %.1fs" % (time.time() - t0))
best = None
for _ in range(3):
    t0 = time.time()
    batch_show(sigs, vk, params, msgs_list, {2, 3, 4, 5}, backend=be)
    dt = time.time() - t0
    best = dt if best is None else min(best, dt)
print("warm best %.3fs -> %.0f/s" % (best, 1024 / best))
pr = cProfile.Profile()
pr.enable()
batch_show(sigs, vk, params, msgs_list, {2, 3, 4, 5}, backend=be)
pr.disable()
st = pstats.Stats(pr)
st.sort_stats("cumulative").print_stats(28)
