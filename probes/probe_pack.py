"""probe_pack.py [n]: pack_canon48 bit-exactness at wide lane counts on the
chip — the carry scan stacks a [52, n] output; the comb-build scan family
corrupts above ~1028 lanes (probes/README.md), so the pack scan's safe
width must be established empirically, all lanes checked."""
import sys
import numpy as np
import jax, jax.numpy as jnp

sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.fields import P
from coconut_tpu.tpu import fp
from coconut_tpu.tpu.limbs import MONT_R, balanced_limbs_batch, fp_decode_batch

n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
rng = np.random.default_rng(42)
ints = [int(x) % P for x in rng.integers(1, 2**63, size=n)]
ints[0] = 0
ints[1] = P - 1
a = balanced_limbs_batch([v * MONT_R % P for v in ints])
b = balanced_limbs_batch([(P - v) % P * MONT_R % P for v in ints])
lazy = a - 2.0 * b  # negative-value lazy combination, |value| < 2p
packed = jax.jit(fp.pack_canon48)(jnp.asarray(lazy))
got = fp_decode_batch(np.asarray(packed))
bad = sum(g != (3 * v) % P for g, v in zip(got, ints))
print("pack_canon48 n=%d bad=%d" % (n, bad))
