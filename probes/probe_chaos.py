"""Self-healing acceptance smoke (the chaos lane's end-to-end check).

    JAX_PLATFORMS=cpu python probes/probe_chaos.py

Runs a REAL CredentialService over an 8-executor stub-device pool with a
fast real-clock watchdog, then injects — via faults.ChaosSchedule-style
mutable schedules on one FaultyBackend — ONE executor crash and ONE hung
dispatch mid-run, and asserts the properties ISSUE 9 promises:

  - every submitted future settles (none dropped, none dangling), with
    zero verdict errors, in every phase — before, during, and after the
    faults;
  - the culprit executors are quarantined (crash + watchdog-timeout paths
    both fire: serve_executor_crashes >= 1, serve_watchdog_timeouts >= 1,
    serve_quarantined >= 2);
  - goodput RECOVERS: the post-fault phase delivers at least half the
    pre-fault goodput (the pool re-admits probed executors instead of
    bleeding capacity).

Prints a one-line JSON report (phases + recovery ratio + health counters)
for the CI log. Everything runs on the CPU in a few seconds; the hang is
Event-released before drain so no thread outlives the probe.
"""

import json
import os
import sys
import time
from types import SimpleNamespace

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics
from coconut_tpu.faults import FaultyBackend
from coconut_tpu.serve import CredentialService, run_loadgen
from coconut_tpu.serve.health import HealthPolicy, Watchdog


class StubPerCred:
    """Stub device: verdict is the credential's own ok flag."""

    def batch_verify(self, sigs, msgs, vk, params):
        return [s.sigma_1 is not None and bool(s.ok) for s in sigs]


def _cred(ok=True):
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)


def _phase(svc, pool, duration_s):
    report = run_loadgen(
        svc,
        pool,
        duration_s=duration_s,
        arrival="closed",
        concurrency=8,
        result_timeout=30.0,
    )
    # the contract under chaos: every accepted future SETTLED, correctly
    assert report["dropped_futures"] == 0, report
    assert report["errors"] == 0, report
    assert report["verdict_mismatches"] == 0, report
    settled = report["completed"]
    accepted = report["submitted"] - report["rejected"] - report["shed"]
    assert settled == accepted, report
    assert report["completed"] > 0, report
    return report


def main():
    metrics.reset()
    fb = FaultyBackend(StubPerCred())
    svc = CredentialService(
        fb,
        None,
        None,
        max_batch=4,
        max_wait_ms=2.0,
        max_depth=512,
        devices=8,
        # fast real-clock self-healing so the whole experiment fits in a
        # few CI seconds: tight watchdog budgets, short cooldown, one
        # probe closes the breaker
        watchdog=Watchdog(
            k=3.0, min_timeout_s=0.2, initial_timeout_s=0.5, max_timeout_s=1.0
        ),
        watchdog_interval_s=0.05,
        health_policy=HealthPolicy(probe_after_s=0.3, probe_successes=1),
    ).start()
    pool = [(_cred(), [0], True), (_cred(ok=False), [1], False)]

    before = _phase(svc, pool, 0.6)

    # schedule one executor-loop crash and one hung dispatch at
    # near-future dispatch indices (the schedule attributes are mutable —
    # the single dispatch counter makes the injection deterministic in
    # INDEX even though thread interleaving picks the executor)
    fb.crash_on = frozenset({fb.dispatches + 2})
    fb.hang_on = frozenset({fb.dispatches + 40})
    during = _phase(svc, pool, 1.2)
    assert fb.crashes == 1, fb.crashes
    assert fb.hang_entered.wait(5.0), "hang injection never dispatched"
    fb.hang_release.set()  # free the abandoned worker before measuring

    # give the probation ladder one cooldown's room, then measure recovery
    time.sleep(0.4)
    after = _phase(svc, pool, 0.6)

    assert svc.drain(timeout=30.0), "drain timed out"

    crashes = metrics.get_count("serve_executor_crashes")
    timeouts = metrics.get_count("serve_watchdog_timeouts")
    quarantined = metrics.get_count("serve_quarantined")
    recovered = metrics.get_count("serve_recovered")
    redistributed = metrics.get_count("serve_redistributed_batches")
    assert crashes >= 1, "executor crash was never contained"
    assert timeouts >= 1, "the hung dispatch was never expired"
    assert quarantined >= 2, "culprit executors were not quarantined"
    assert redistributed >= 1, "no unsettled batch was redistributed"
    ratio = after["goodput_per_s"] / max(before["goodput_per_s"], 1e-9)
    assert ratio >= 0.5, (
        "goodput did not recover: before %.1f/s after %.1f/s"
        % (before["goodput_per_s"], after["goodput_per_s"])
    )

    print(
        json.dumps(
            {
                "goodput_per_s": {
                    "before": before["goodput_per_s"],
                    "during": during["goodput_per_s"],
                    "after": after["goodput_per_s"],
                },
                "recovery_ratio": round(ratio, 3),
                "completed": {
                    "before": before["completed"],
                    "during": during["completed"],
                    "after": after["completed"],
                },
                "executor_crashes": crashes,
                "watchdog_timeouts": timeouts,
                "quarantined": quarantined,
                "recovered": recovered,
                "redistributed_batches": redistributed,
            },
            sort_keys=True,
        )
    )
    print("chaos probe: ok (recovery ratio %.2f)" % ratio)
    return 0


if __name__ == "__main__":
    sys.exit(main())
