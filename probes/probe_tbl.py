"""probe_tbl.py <window> <k>: decode chunked G2 comb-table entries vs spec."""
import random, sys
import jax
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.curve import G2_GEN, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.tpu.backend import _comb_tables, _comb_schedule
from coconut_tpu.tpu import curve as cv, tower as tw

k = int(sys.argv[2])
rng = random.Random(11)
window, nwin, entries = _comb_schedule()
bases = [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(k)]
wt = _comb_tables(g2, True, bases)
bad = []
checks = []
for j in range(k):
    checks += [(j, nwin - 1, 1), (j, 0, 1), (j, nwin // 2, entries - 1)]
for (j, w, d) in checks:
    sel = jax.tree_util.tree_map(lambda t: t[j, w, d], wt)
    ax, ay, ainf = jax.jit(lambda p: cv.to_affine(cv.FP2, p))(sel)
    got = (
        tw.decode_batch(jax.tree_util.tree_map(lambda t: t[None], ax))[0],
        tw.decode_batch(jax.tree_util.tree_map(lambda t: t[None], ay))[0],
    )
    want = g2.mul(bases[j], d * pow(1 << window, nwin - 1 - w, R) % R)
    if got != want:
        bad.append((j, w, d))
print("window=%d k=%d G2 table bad=%d %r" % (window, k, len(bad), bad[:8]))
