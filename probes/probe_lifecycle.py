"""Zero-downtime lifecycle acceptance smoke (the PR-14 rolling-restart
drill over REAL TCP).

    JAX_PLATFORMS=cpu python probes/probe_lifecycle.py

Runs a 3-replica fleet (engine.ProtocolEngine behind net.Replica, real
loopback TCP sockets, live gossip thread) under continuous mixed
loadgen traffic, then restarts every replica IN SEQUENCE: graceful
drain (begin_drain -> shape manifest saved), a fresh engine + replica
booted through a LifecycleController (beacon reports WARMING until the
manifest replay finished), rejoin via beacons. Asserts the properties
ISSUE 14 promises:

  - zero dangling futures and zero NON-RETRYABLE client errors across
    all three restarts (drain refusals and torn sockets are retryable
    handoffs the router resubmits on ring successors);
  - the router provably never places a request on a WARMING or
    DRAINING replica: the "gateway_placed_warming" and
    "gateway_placed_draining" audit counters stay at ZERO;
  - every drain persists a non-empty shape manifest and every
    successor replays it (warmed + skipped == manifest size) before
    advertising readiness;
  - each restart's restart-to-first-SLO-compliant-response, read from
    the loadgen report's availability timeline, stays bounded.

Prints a one-line JSON report for the CI log. Everything runs on the
CPU in well under a minute. LIFECYCLE_DRILL_SECONDS stretches the
traffic window (default 20)."""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import LifecycleController, ProtocolEngine
from coconut_tpu.engine.lifecycle import ShapeManifest
from coconut_tpu.errors import ServiceClosedError, TransientBackendError
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.params import Params
from coconut_tpu.retry import RetryPolicy
from coconut_tpu.serve.loadgen import restart_to_first_slo, run_loadgen
from coconut_tpu.sss import rand_fr

THRESHOLD, TOTAL = 2, 3
REPLICAS = 3
FLEET_KEY = "key-fleet"
DRILL_SECONDS = float(os.environ.get("LIFECYCLE_DRILL_SECONDS", "20"))
#: generous for a shared CI box — the python backend settles a verify in
#: well under a second; the bound is "bounded", not "fast"
SLO_S = 5.0


def _mk_engine(signers, params, backend):
    return ProtocolEngine(
        signers,
        params,
        THRESHOLD,
        count_hidden=1,
        revealed_msg_indices=[1, 2],
        backend=backend,
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
    ).start()


def _connect(replica, codec):
    return net.GatewayClient(
        net.SocketTransport(replica.address), codec, api_key=FLEET_KEY
    )


def _wait(predicate, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    assert predicate(), "timed out waiting for %s" % what


class _SessionSpread:
    """run_loadgen drives a single `submit` surface; spread its traffic
    over many sessions round-robin so every replica owns live flows when
    its restart comes."""

    def __init__(self, router, n_sessions=24):
        self._router = router
        self._sessions = ["drill-%d" % i for i in range(n_sessions)]
        self._lock = threading.Lock()
        self._i = 0

    def submit(self, sig, messages, lane="interactive", max_wait_ms=None):
        with self._lock:
            session = self._sessions[self._i % len(self._sessions)]
            self._i += 1
        return self._router.submit_verify(
            sig, messages, lane=lane, session=session
        )


def main():
    metrics.reset()
    params = Params.new(3, b"probe-lifecycle")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    backend = get_backend("python")
    codec = net.WireCodec(params)
    tenants = net.TenantTable()
    tenants.provision("fleet", FLEET_KEY)
    manifest_dir = tempfile.mkdtemp(prefix="coconut-lifecycle-")

    engines, lifecycles, replicas = {}, {}, {}

    def bring_up(rid):
        """One replica's boot sequence: WARMING until the manifest
        replay finished, then serving."""
        eng = _mk_engine(signers, params, backend)
        lc = LifecycleController(
            eng,
            manifest_path=os.path.join(manifest_dir, "%s.json" % rid),
        )
        rep = net.Replica(
            eng, codec, tenants=tenants, replica_id=rid, lifecycle=lc
        )
        rep.serve()
        engines[rid], lifecycles[rid], replicas[rid] = eng, lc, rep
        return rep

    for i in range(REPLICAS):
        bring_up("r%d" % i)

    clients = {rid: _connect(rep, codec) for rid, rep in replicas.items()}
    router = net.ReplicaRouter(
        clients,
        retry_policy=RetryPolicy(
            max_attempts=REPLICAS + 2,
            base_delay=0.05,
            retryable=(TransientBackendError, ServiceClosedError),
        ),
    )
    # first boots are cold (no manifest yet) but still gate readiness
    for rid, lc in lifecycles.items():
        assert lc.boot() is not None
        assert lc.ready(), "%s not ready after boot" % rid
    loop = net.GossipLoop(
        router.directory,
        {
            rid: (lambda r=rid: router.clients[r].poll_beacon(timeout=2.0))
            for rid in clients
        },
        interval_s=0.1,
    ).start()
    _wait(
        lambda: all(
            s == net.UP for s in router.directory.states().values()
        ),
        what="initial fleet UP",
    )

    # one real credential for the verify pool
    msgs = [rand_fr(), rand_fr(), rand_fr()]
    esk, epk = elgamal_keygen(params.ctx.sig, params.g)
    req, _ = router.bound("seed").submit_prepare(msgs, epk).result(120.0)
    cred = router.bound("seed").submit_mint(req, msgs, esk).result(120.0)
    pool = [(cred, msgs, True)]

    report_box = {}
    t0 = time.monotonic()

    def drive():
        report_box["report"] = run_loadgen(
            _SessionSpread(router),
            pool,
            duration_s=DRILL_SECONDS,
            arrival="closed",
            concurrency=4,
            transport="rpc",
            result_timeout=60.0,
        )

    loadgen = threading.Thread(target=drive, name="lifecycle-loadgen")
    loadgen.start()

    restart_marks = {}
    manifest_sizes = {}
    warm_totals = {}
    try:
        time.sleep(2.0)  # steady state before the first restart
        for rid in sorted(replicas):
            restart_marks[rid] = time.monotonic() - t0
            # 1) graceful drain: in-flight settles, manifest persists
            assert replicas[rid].begin_drain(timeout=30.0), (
                "drain of %s timed out" % rid
            )
            manifest = ShapeManifest.load(
                os.path.join(manifest_dir, "%s.json" % rid)
            )
            manifest_sizes[rid] = len(manifest)
            assert len(manifest) >= 1, (
                "drain of %s saved an empty shape manifest" % rid
            )
            _wait(
                lambda r=rid: router.directory.state(r)
                in (net.DRAINING, net.DOWN),
                what="%s leaving the routable pool" % rid,
            )

            # 2) restart: fresh engine + controller behind the same rid
            bring_up(rid)
            old_client = router.clients[rid]
            router.clients[rid] = _connect(replicas[rid], codec)
            old_client.close()
            _wait(
                lambda r=rid: router.directory.state(r) == net.WARMING,
                what="%s beaconing WARMING" % rid,
            )

            # 3) warm boot: replay the predecessor's manifest, then UP
            warmed, skipped = lifecycles[rid].boot()
            warm_totals[rid] = (warmed, skipped)
            assert warmed + skipped == manifest_sizes[rid], (
                "%s replayed %d+%d of a %d-shape manifest"
                % (rid, warmed, skipped, manifest_sizes[rid])
            )
            _wait(
                lambda r=rid: router.directory.state(r) == net.UP,
                what="%s rejoining UP" % rid,
            )
        loadgen.join(timeout=DRILL_SECONDS + 90.0)
        assert not loadgen.is_alive(), "loadgen never finished"
    finally:
        loop.stop(timeout=5.0)
        router.close()
        for rep in replicas.values():
            rep.close()
        for rid, eng in engines.items():
            eng.drain(timeout=60.0)

    report = report_box["report"]
    last_mark = max(restart_marks.values())
    assert report["duration_s"] > last_mark, (
        "traffic window ended before the last restart — raise "
        "LIFECYCLE_DRILL_SECONDS (duration %.1fs, last mark %.1fs)"
        % (report["duration_s"], last_mark)
    )

    # -- the drill's verdicts -------------------------------------------------
    placed_warming = metrics.get_count("gateway_placed_warming")
    placed_draining = metrics.get_count("gateway_placed_draining")
    recoveries = {
        rid: restart_to_first_slo(report["availability"], mark, SLO_S)
        for rid, mark in restart_marks.items()
    }
    assert report["dropped_futures"] == 0, "dangling futures in the drill"
    assert report["errors_terminal"] == 0, (
        "%d NON-RETRYABLE client errors leaked through the restarts"
        % report["errors_terminal"]
    )
    assert report["completed"] > 0 and report["verdict_mismatches"] == 0
    assert placed_warming == 0 and placed_draining == 0, (
        "router placed traffic on a warming/draining replica "
        "(warming=%d draining=%d)" % (placed_warming, placed_draining)
    )
    for rid, rec in recoveries.items():
        assert rec is not None, (
            "no SLO-compliant response followed the restart of %s" % rid
        )
        assert rec <= 15.0, (
            "restart of %s took %.1fs to the first SLO-compliant "
            "response" % (rid, rec)
        )
    assert all(
        s == net.UP for s in router.directory.states().values()
    ), "fleet did not end fully UP: %s" % (router.directory.states(),)

    out = {
        "replicas": REPLICAS,
        "restarts": len(restart_marks),
        "completed": report["completed"],
        "errors_retryable": report["errors_retryable"],
        "errors_terminal": report["errors_terminal"],
        "dropped_futures": report["dropped_futures"],
        "drain_handoffs": metrics.get_count("gateway_drain_handoffs"),
        "failovers": metrics.get_count("gateway_failovers"),
        "placed_warming": placed_warming,
        "placed_draining": placed_draining,
        "warmed_beacons": metrics.get_count("gateway_warmed"),
        "error_free_seconds": report["availability"]["error_free_seconds"],
        "seconds": report["availability"]["seconds"],
        "manifest_shapes": manifest_sizes,
        "restart_to_first_slo_s": {
            rid: round(v, 3) for rid, v in recoveries.items()
        },
        "p99_s": report["latency_s"]["p99"],
    }
    print(json.dumps(out, sort_keys=True))
    print(
        "lifecycle probe: ok (%d restarts, %d completed, 0 terminal "
        "errors, 0 misplaced sessions)"
        % (out["restarts"], out["completed"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
