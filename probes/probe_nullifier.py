"""Durable-state acceptance smoke (the PR-17 kill-the-witness drill).

    JAX_PLATFORMS=cpu python probes/probe_nullifier.py

Runs a REAL 3-replica fleet over loopback TCP sockets, each replica
with its own state.StateStore (per-replica WAL + snapshot) and a
state.StateReplicator pulling anti-entropy gaps from its peers, the
gaps advertised by per-keyspace high-water marks on the health beacon.
Asserts the properties ISSUE 17 promises:

  - a full credential session round-trips THROUGH the wire and its
    accepted show commits a nullifier to the witness's WAL before the
    client's future resolves;
  - the fact REPLICATES: both non-witness replicas converge on the
    nullifier via beacon marks + anti-entropy pulls over real sockets;
  - the witnessing replica is KILLED (listener and connections torn
    down, engine NOT drained — the in-memory set is gone with the
    process); replaying the same show against each survivor is still
    rejected with the typed, wire-coded DoubleSpendError carrying the
    nullifier digest;
  - the witness RESTARTS over the same data directory: a fresh
    StateStore replays its WAL and the reborn replica rejects the
    replay too — no operator action, no peer round-trip needed;
  - a FRESH re-randomized show of the same credential still verifies
    (double-spend detection never collapses into linkability).

Prints a one-line JSON report for the CI log. Everything runs on the
CPU in well under a minute.
"""

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import DoubleSpendError
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.params import Params
from coconut_tpu.sss import rand_fr
from coconut_tpu.state import StateReplicator, StateStore, nullifier_of

THRESHOLD, TOTAL = 2, 3
REPLICAS = ("rA", "rB", "rC")
WITNESS = "rA"


def _engine(signers, params, backend, store):
    return ProtocolEngine(
        signers,
        params,
        THRESHOLD,
        count_hidden=1,
        revealed_msg_indices=[1, 2],
        backend=backend,
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
        state_store=store,
    ).start()


def _connect(rid, replica, codec):
    return net.GatewayClient(
        net.SocketTransport(replica.address), codec, session=rid
    )


def _wait(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def main():
    metrics.reset()
    params = Params.new(3, b"probe-nullifier")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    backend = get_backend("python")
    codec = net.WireCodec(params)
    root = tempfile.mkdtemp(prefix="probe-nullifier-")

    stores, engines, replicas, clients, reps = {}, {}, {}, {}, {}
    directory = net.HealthDirectory()
    report = {"replicas": len(REPLICAS)}
    loop = None
    try:
        for rid in REPLICAS:
            stores[rid] = StateStore(
                os.path.join(root, rid), replica_id=rid
            )
            engines[rid] = _engine(signers, params, backend, stores[rid])
            replicas[rid] = net.Replica(
                engines[rid], codec, replica_id=rid
            )
            replicas[rid].serve()
            clients[rid] = _connect(rid, replicas[rid], codec)
        # one gossip thread feeds beacons (and their state marks) into
        # the shared directory; one replicator per replica pulls gaps
        # from every peer over the real sockets
        loop = net.GossipLoop(
            directory,
            {
                rid: (lambda r=rid: clients[r].poll_beacon(timeout=2.0))
                for rid in REPLICAS
            },
            interval_s=0.1,
        ).start()
        for rid in REPLICAS:
            peers = {p: clients[p] for p in REPLICAS if p != rid}
            reps[rid] = StateReplicator(
                stores[rid], directory, peers, interval_s=0.1
            )
            reps[rid].start()

        # -- 1. the witness accepts a show and journals the nullifier -----
        msgs = [rand_fr(), rand_fr(), rand_fr()]
        esk, epk = elgamal_keygen(params.ctx.sig, params.g)
        w = clients[WITNESS]
        req, _ = w.submit_prepare(msgs, epk).result(120.0)
        cred = w.submit_mint(req, msgs, esk).result(120.0)
        proof, chal, rev = w.submit_show_prove(cred, msgs).result(120.0)
        assert w.submit_show_verify(proof, rev, chal).result(120.0) is True
        digest = nullifier_of(proof, chal, None, params)
        assert stores[WITNESS].seen("nullifier/0", digest), (
            "witness accepted the show without journaling its nullifier"
        )

        # -- 2. the fact replicates to both survivors over real TCP -------
        survivors = [r for r in REPLICAS if r != WITNESS]
        for rid in survivors:
            assert _wait(
                lambda r=rid: stores[r].seen("nullifier/0", digest)
            ), "nullifier never replicated to %s" % rid
        report["antientropy_pulls"] = metrics.get_count(
            "state_antientropy_pulls"
        )

        # -- 3. KILL the witness (no drain: in-memory state is gone) ------
        replicas[WITNESS].close()
        clients[WITNESS].close()

        # -- 4. survivors reject the replayed show, typed -----------------
        rejected = 0
        for rid in survivors:
            try:
                clients[rid].submit_show_verify(
                    proof, rev, chal
                ).result(120.0)
            except DoubleSpendError as e:
                assert e.nullifier == digest, (
                    "survivor %s rejected with the wrong nullifier" % rid
                )
                rejected += 1
        assert rejected == len(survivors), (
            "only %d of %d survivors rejected the replay"
            % (rejected, len(survivors))
        )

        # -- 5. the witness restarts: WAL replay, rejects locally ---------
        assert engines[WITNESS].drain(timeout=60.0)
        stores[WITNESS].close()
        stores[WITNESS] = StateStore(
            os.path.join(root, WITNESS), replica_id=WITNESS
        )
        assert stores[WITNESS].seen("nullifier/0", digest), (
            "WAL replay lost the acknowledged nullifier"
        )
        engines[WITNESS] = _engine(
            signers, params, backend, stores[WITNESS]
        )
        replicas[WITNESS] = net.Replica(
            engines[WITNESS], codec, replica_id=WITNESS
        )
        replicas[WITNESS].serve()
        clients[WITNESS] = _connect(WITNESS, replicas[WITNESS], codec)
        restart_rejected = 0
        try:
            clients[WITNESS].submit_show_verify(
                proof, rev, chal
            ).result(120.0)
        except DoubleSpendError:
            restart_rejected = 1
        assert restart_rejected, (
            "restarted witness forgot the nullifier it acknowledged"
        )

        # -- 6. a FRESH show of the same credential still verifies --------
        proof2, chal2, rev2 = clients[WITNESS].submit_show_prove(
            cred, msgs
        ).result(120.0)
        assert (
            clients[WITNESS]
            .submit_show_verify(proof2, rev2, chal2)
            .result(120.0)
            is True
        ), "double-spend detection broke honest re-shows"

        report.update(
            {
                "nullifier": digest,
                "survivors_rejected": rejected,
                "restart_rejected": restart_rejected,
                "fresh_show_accepted": 1,
                "commits": metrics.get_count("nullifier_commits"),
                "double_spends": metrics.get_count(
                    "nullifier_double_spends"
                ),
                "wal_replayed": metrics.get_count("wal_replayed_records"),
                "wal_fsyncs": metrics.get_count("wal_fsyncs"),
            }
        )
    finally:
        if loop is not None:
            loop.stop(timeout=5.0)
        for rep in reps.values():
            rep.stop()
        for c in clients.values():
            c.close()
        for r in replicas.values():
            r.close()
        for rid, eng in engines.items():
            assert eng.drain(timeout=60.0), "drain timed out on %s" % rid
        for st in stores.values():
            st.close()
        shutil.rmtree(root, ignore_errors=True)

    assert report["wal_replayed"] >= 1, "restart never replayed the WAL"
    assert report["double_spends"] >= 3  # 2 survivors + restarted witness

    print(json.dumps(report, sort_keys=True))
    print(
        "nullifier probe: ok (witness killed, %d survivors rejected the "
        "replay, restart replayed %d WAL records and rejected it too, "
        "fresh show accepted)"
        % (report["survivors_rejected"], report["wal_replayed"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
