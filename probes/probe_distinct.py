"""probe_distinct.py <B> <k> [g2]: distinct-base MSM differential vs spec,
ALL lanes checked — the issuance-shape scan (build_tables_device carries
[B, k] lanes) at full width."""
import random, sys, time
import coconut_tpu.tpu
coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.tpu.backend import JaxBackend

B = int(sys.argv[1]); k = int(sys.argv[2])
grp = sys.argv[3] if len(sys.argv) > 3 else "g1"
rng = random.Random(5)
be = JaxBackend()
ops, gen, fn = (
    (g1, G1_GEN, be.msm_g1_distinct) if grp == "g1" else (g2, G2_GEN, be.msm_g2_distinct)
)
pts = [[ops.mul(gen, rng.randrange(1, R)) for _ in range(k)] for _ in range(B)]
scal = [[rng.randrange(R) for _ in range(k)] for _ in range(B)]
t0 = time.time()
got = fn(pts, scal)
t_run = time.time() - t0
bad = [i for i, (row_p, row_s, g) in enumerate(zip(pts, scal, got)) if g != ops.msm(row_p, row_s)]
print("%s distinct B=%d k=%d bad=%d %r run=%.1fs" % (grp, B, k, len(bad), bad[:10], t_run))
