"""Probe: comb-table scan build correctness vs (window, k) on the chip."""
import functools
import random
import sys

import jax

import coconut_tpu.tpu

coconut_tpu.tpu.enable_compile_cache()
from coconut_tpu.ops.curve import G1_GEN, g1
from coconut_tpu.ops.fields import R
from coconut_tpu.tpu import curve as cv, tower as tw
from coconut_tpu.tpu.backend import _build_tables

window = int(sys.argv[1])
k = int(sys.argv[2])
nwin = -(-255 // window)
entries = (1 << (window - 1)) + 1
rng = random.Random(7)
bases = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(k)]
t_e = _build_tables(g1, bases, entries=entries)
wt = jax.jit(
    functools.partial(cv.build_comb_tables, cv.FP, nwin=nwin, window=window)
)(t_e)
bad = 0
checks = [(0, nwin - 1, 1), (k - 1, nwin - 1, entries - 1), (0, 0, 1),
          (k - 1, 0, entries - 1), (k // 2, nwin // 2, entries // 2)]
for (j, w, d) in checks:
    sel = jax.tree_util.tree_map(lambda t: t[j, w, d], wt)
    ax, ay, ainf = jax.jit(lambda p: cv.to_affine(cv.FP, p))(sel)
    if d == 0:
        got = None if bool(ainf) else "pt"
        want = None
    else:
        got = (
            tw.decode_batch(jax.tree_util.tree_map(lambda t: t[None], ax))[0],
            tw.decode_batch(jax.tree_util.tree_map(lambda t: t[None], ay))[0],
        )
        want = g1.mul(bases[j], d * pow(1 << window, nwin - 1 - w, R) % R)
    bad += got != want
print("window=%d k=%d lanes=%d bad=%d" % (window, k, k * entries, bad))
