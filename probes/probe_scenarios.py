"""Application-scenario acceptance smoke (the PR-19 fleet drill).

    JAX_PLATFORMS=cpu python probes/probe_scenarios.py

Runs a REAL 3-replica fleet over loopback TCP sockets — each replica a
ProtocolEngine with its own durable StateStore, anti-entropy
replication pulling over the same sockets, a GossipLoop feeding the
router's health directory — and drives a MIXED petition/e-cash/access
population through a ReplicaRouter for a compressed "day" with one
flash crowd composed onto the diurnal curve. Asserts the scenario
layer's acceptance bar:

  - every started workflow reaches exactly one terminal outcome and
    the run drains clean: zero `failed` (unattributed errors), zero
    `cancelled` (dangling futures);
  - the traffic is honest (resign_p = double_spend_p = 0), so zero
    rejections too — the flash crowd must be ABSORBED (completed or
    counted as retries/deferrals), never misattributed;
  - goodput is nonzero and the per-second availability timeline spans
    the run, flash window included.

Prints a one-line JSON report for the CI log. Python backend, CPU,
well under two minutes.
"""

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.params import Params
from coconut_tpu.scenarios import (
    AccessScenario,
    DiurnalCurve,
    EcashScenario,
    FlashCrowd,
    PetitionScenario,
    Population,
    PopulationDriver,
    RateSchedule,
    ScenarioReport,
)
from coconut_tpu.state import StateReplicator, StateStore

THRESHOLD, TOTAL = 2, 3
REPLICAS = ("rA", "rB", "rC")
DURATION_S = 20.0


def _engine(signers, params, backend, store):
    return ProtocolEngine(
        signers,
        params,
        THRESHOLD,
        count_hidden=1,
        revealed_msg_indices=[1, 2],
        backend=backend,
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
        state_store=store,
    ).start()


def main():
    metrics.reset()
    params = Params.new(3, b"probe-scenarios")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    backend = get_backend("python")
    codec = net.WireCodec(params)
    root = tempfile.mkdtemp(prefix="probe-scenarios-")

    stores, engines, replicas, clients, reps = {}, {}, {}, {}, {}
    loop = None
    try:
        for rid in REPLICAS:
            stores[rid] = StateStore(
                os.path.join(root, rid), replica_id=rid
            )
            engines[rid] = _engine(signers, params, backend, stores[rid])
            replicas[rid] = net.Replica(
                engines[rid], codec, replica_id=rid
            )
            replicas[rid].serve()
            clients[rid] = net.GatewayClient(
                net.SocketTransport(replicas[rid].address),
                codec,
                session=rid,
            )
        router = net.ReplicaRouter(clients)
        loop = router.gossip_loop(interval_s=0.2).start()
        directory = router.directory
        for rid in REPLICAS:
            peers = {p: clients[p] for p in REPLICAS if p != rid}
            reps[rid] = StateReplicator(
                stores[rid], directory, peers, interval_s=0.25
            )
            reps[rid].start()

        # honest mixed traffic: the protections must never fire, so a
        # nonzero rejected/failed count is a detector false positive
        mix = [
            (2.0, PetitionScenario(
                router.bound("petition"), params,
                campaigns=4, resign_p=0.0,
            )),
            (2.0, EcashScenario(
                router.bound("ecash"), params, double_spend_p=0.0,
            )),
            (1.0, AccessScenario(
                router.bound("access"), params, session_range=(2, 3),
            )),
        ]
        crowd = FlashCrowd(
            at_s=8.0, duration_s=4.0, multiplier=2.5, ramp_s=2.0
        )
        schedule = RateSchedule(
            DiurnalCurve(0.6, 2.0, DURATION_S), [crowd]
        )
        report = ScenarioReport(slo_s=8.0, flash_window=crowd.window())
        driver = PopulationDriver(
            Population(64, n_tenants=8, seed=19),
            mix,
            schedule,
            DURATION_S,
            max_in_flight=48,
            seed=19,
            report=report,
            drain_timeout_s=90.0,
        )
        out = driver.run()

        totals = out["totals"]
        assert totals["failed"] == 0, (
            "unattributed errors: %r" % (out["error_codes"],)
        )
        assert totals["cancelled"] == 0, "dangling futures on drain"
        assert totals["completed"] > 0, "no workflow completed"
        assert not out["rejections"], (
            "honest traffic drew rejections: %r" % (out["rejections"],)
        )
        avail = out["availability"]
        # the pump stops at the LAST arrival (plus drain), which can
        # land a second or two short of the nominal day length
        assert avail["seconds"] >= int(DURATION_S) - 3
        assert sum(avail["per_second_goodput"]) == totals["completed"]
        flash_arrivals = sum(
            1 for s in out["timeline"] if 8.0 <= s["t"] <= 12.0
        )
        assert flash_arrivals >= 1, "no samples through the flash window"

        line = {
            "replicas": len(REPLICAS),
            "arrivals": out["driver"]["arrivals"],
            "completed": totals["completed"],
            "retries": totals["retries"],
            "deferred": out["driver"]["deferred"],
            "failed": totals["failed"],
            "cancelled": totals["cancelled"],
            "goodput_per_s": out["goodput_per_s"],
            "p99_s": out["slo"]["p99_s"],
            "flash_p99_s": out["slo"]["flash_p99_s"],
            "slo_attainment": out["slo"]["attainment"],
            "users": out["driver"]["users_materialized"],
        }
    finally:
        if loop is not None:
            loop.stop(timeout=5.0)
        for rep in reps.values():
            rep.stop()
        for c in clients.values():
            c.close()
        for r in replicas.values():
            r.close()
        for rid, eng in engines.items():
            assert eng.drain(timeout=60.0), "drain timed out on %s" % rid
        for st in stores.values():
            st.close()
        shutil.rmtree(root, ignore_errors=True)

    print(json.dumps(line, sort_keys=True))
    print(
        "scenarios probe: ok (%d arrivals -> %d completed through one "
        "flash crowd, %d retries, 0 failed, 0 cancelled)"
        % (line["arrivals"], line["completed"], line["retries"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
