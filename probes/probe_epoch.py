"""Key-lifecycle acceptance smoke (the PR-15 rollover-under-traffic check).

    JAX_PLATFORMS=cpu python probes/probe_epoch.py

Runs a REAL 5-authority fleet over a loopback TCP socket: the authorities
are born from an ONLINE DKG (no dealer, no in-process master secret), the
engine serves full credential sessions through a net.Replica wire loop,
and the key lifecycle rolls over underneath live traffic:

  - DKG bootstraps epoch 1 with a deliberately corrupt dealer, who is
    complained against BY NAME and excluded from QUAL — and still
    receives signing shares;
  - concurrent client threads run prepare -> mint -> verify ->
    show_prove -> show_verify sessions nonstop while the lifecycle takes
    ONE proactive refresh (same verkey bit-for-bit, every share changed)
    and ONE t/n reshare (3-of-5 -> 2-of-5, new epoch) — zero dangling
    futures, zero terminal errors across the whole run;
  - every pre-rollover credential verifies post-rollover under its MINT
    epoch, over the wire, while new mints land on the new epoch;
  - the replica's health beacon advertises the live epoch window through
    each transition (1 active -> 1 retiring + 2 active).

Prints a one-line JSON report for the CI log. Everything runs on the
CPU in well under a minute.
"""

import json
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.keylife import ACTIVE, RETIRING, KeyLifecycleManager
from coconut_tpu.params import Params
from coconut_tpu.sss import rand_fr

THRESHOLD, TOTAL = 3, 5
MSGS = 3
TRAFFIC_THREADS = 3


def _corrupt_dealer(d, r, dim, share):
    """Dealer 2 hands recipient 4 a share off the committed polynomial."""
    if (d, r, dim) == (2, 4, 0):
        return (share[0] + 1, share[1])
    return None


def _run_session(client, params, creds, timeout=120.0):
    """One full credential session; records the minted credential."""
    msgs = [rand_fr() for _ in range(MSGS)]
    esk, epk = elgamal_keygen(params.ctx.sig, params.g)
    req, _ = client.submit_prepare(msgs, epk).result(timeout)
    cred = client.submit_mint(req, msgs, esk).result(timeout)
    assert client.submit_verify(cred, msgs).result(timeout) is True
    proof, chal, rev = client.submit_show_prove(cred, msgs).result(timeout)
    ok = client.submit_show_verify(
        proof, rev, chal, epoch=cred.epoch
    ).result(timeout)
    assert ok is True, "show_verify verdict False mid-traffic"
    creds.append((cred, msgs))


def main():
    metrics.reset()
    params = Params.new(MSGS, b"probe-epoch")
    codec = net.WireCodec(params)

    # -- online DKG: corrupt dealer named + excluded, no master secret ---
    mgr = KeyLifecycleManager(params, label=b"probe-epoch", window=3)
    ks1 = mgr.bootstrap(THRESHOLD, TOTAL, tamper=_corrupt_dealer)
    assert mgr.last_round.complaints == {2: (4,)}, (
        "complaints misattributed: %r" % (mgr.last_round.complaints,)
    )
    assert ks1.excluded == (2,)
    assert sorted(s.id for s in ks1.signers) == [1, 2, 3, 4, 5]

    eng = ProtocolEngine(
        [ks1.signer(i) for i in range(1, TOTAL + 1)],
        params,
        THRESHOLD,
        count_hidden=1,
        revealed_msg_indices=[1, 2],
        vk=ks1.vk,
        backend=get_backend("python"),
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
        keychain=mgr.registry,
    ).start()
    mgr.attach(eng)
    replica = net.Replica(eng, codec, replica_id="r0")
    replica.serve()

    def connect(session):
        return net.GatewayClient(
            net.SocketTransport(replica.address), codec, session=session
        )

    report = {"authorities": TOTAL, "threshold_before": THRESHOLD}
    clients = []
    try:
        beacon_client = connect("beacon")
        clients.append(beacon_client)
        epochs = beacon_client.poll_beacon(timeout=5.0).epochs
        assert epochs == ((1, ACTIVE),), (
            "beacon window wrong at bootstrap: %r" % (epochs,)
        )

        # -- nonstop traffic while the lifecycle rolls over --------------
        creds, errors = [], []
        stop = threading.Event()

        def pump(tid):
            client = connect("pump-%d" % tid)
            clients.append(client)
            try:
                while not stop.is_set():
                    _run_session(client, params, creds)
            except Exception as e:  # terminal error: the probe fails
                errors.append("pump-%d: %r" % (tid, e))

        pumps = [
            threading.Thread(target=pump, args=(t,), daemon=True)
            for t in range(TRAFFIC_THREADS)
        ]
        for p in pumps:
            p.start()
        while len(creds) < 4 and not errors:  # pre-rollover corpus
            stop.wait(0.05)
        pre = list(creds)

        before = {
            s.id: (s.sigkey.x, tuple(s.sigkey.y)) for s in ks1.signers
        }
        ks1r = mgr.refresh()  # under traffic
        assert ks1r.vk.to_bytes(params.ctx) == ks1.vk.to_bytes(params.ctx)
        assert all(
            before[s.id] != (s.sigkey.x, tuple(s.sigkey.y))
            for s in ks1r.signers
        ), "refresh left a share unchanged"
        while len(creds) < len(pre) + 2 and not errors:
            stop.wait(0.05)

        ks2 = mgr.reshare(threshold=2, total=TOTAL)  # under traffic
        assert ks2.epoch == 2
        while len(creds) < len(pre) + 4 and not errors:
            stop.wait(0.05)
        stop.set()
        for p in pumps:
            p.join(120.0)
            assert not p.is_alive(), "traffic pump hung (dangling futures)"
        assert not errors, "terminal errors mid-rollover: %s" % errors

        epochs = beacon_client.poll_beacon(timeout=5.0).epochs
        assert epochs == ((1, RETIRING), (2, ACTIVE)), (
            "beacon window wrong after reshare: %r" % (epochs,)
        )

        # -- every pre-rollover credential verifies under its mint epoch -
        check = connect("post-check")
        clients.append(check)
        for cred, msgs in pre:
            assert cred.epoch == 1, "pre-rollover cred stamped %d" % (
                cred.epoch,
            )
            assert check.submit_verify(cred, msgs).result(120.0) is True, (
                "pre-rollover credential failed post-rollover"
            )
        post_epochs = sorted({c.epoch for c, _ in creds[len(pre):]})
        fresh = []
        _run_session(check, params, fresh)
        assert fresh[0][0].epoch == 2, "new mints not on the new epoch"

        report.update(
            {
                "threshold_after": 2,
                "sessions_completed": len(creds) + 1,
                "pre_rollover_verified": len(pre),
                "mid_rollover_epochs": post_epochs,
                "corrupt_dealer_excluded": list(ks1.excluded),
                "refreshes": metrics.get_count("keylife_refreshes"),
                "reshares": metrics.get_count("keylife_reshares"),
                "gateway_errors": metrics.get_count("gateway_errors"),
                "live_epochs": metrics.get_gauge("keylife_live_epochs"),
            }
        )
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        replica.close()
        assert eng.drain(timeout=60.0), "engine drain timed out"

    assert report["gateway_errors"] == 0, "engine-side terminal errors"
    for e in (1, 2):
        assert mgr.registry.pin_count(e) == 0, "leaked epoch pin"

    print(json.dumps(report, sort_keys=True))
    print(
        "epoch probe: ok (%d sessions through 1 refresh + 1 reshare, "
        "%d pre-rollover creds verified post-rollover, dealer 2 excluded)"
        % (report["sessions_completed"], report["pre_rollover_verified"])
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
