"""probe_ct.py: timing-independence measurement of the device issuance
path over adversarial signer secrets (CONSTTIME.md's data source).

Measures, per secret pattern:
  - host encode time (digit recode + GLV split — the only host work that
    touches secret values), and
  - end-to-end batch_blind_sign wall time (best and median of REPS),
on the SAME fixed request batch. Patterns span the digit-value extremes
the gather indices take. Run on the real chip:
    python probes/probe_ct.py [batch] [reps]
"""
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")
import coconut_tpu.tpu

coconut_tpu.tpu.enable_compile_cache()
import __graft_entry__ as ge
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.ops.fields import R
from coconut_tpu.signature import Sigkey, batch_blind_sign, batch_prepare_blind_sign
from coconut_tpu.tpu.backend import JaxBackend, _signed_digits
from coconut_tpu.tpu import glv

B = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 9

params, _, vk, sigs, msgs_list = ge._fixture(batch=B)
be = JaxBackend()
esk, epk = elgamal_keygen(params.ctx.sig, params.g)
out = batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
reqs = [r for r, _ in out]

# digit-extreme scalar: every signed 5-bit digit at max magnitude
DENSE = sum(16 * (32**i) for i in range(51)) % R
PATTERNS = {
    "zeros": Sigkey(0, [0] * ge.MSG_COUNT),
    "ones": Sigkey(1, [1] * ge.MSG_COUNT),
    "dense_max_digits": Sigkey(DENSE, [DENSE] * ge.MSG_COUNT),
    "r_minus_1": Sigkey(R - 1, [R - 1] * ge.MSG_COUNT),
    "random": Sigkey(
        0x6A09E667F3BCC908 * 0x243F6A8885A308D3 % R,
        [(0x9E3779B97F4A7C15 * (i + 1) ** 5) % R for i in range(ge.MSG_COUNT)],
    ),
}

# untimed warmup: numpy/CPython allocator first-touch costs otherwise land
# on whichever pattern runs first and masquerade as data dependence
_warm = [[1, 2, 3]] * (2 * B)
_ = [[h for s in row for h in glv.decompose(s)] for row in _warm]
_signed_digits(_, nwin=glv.NWIN_5)

print("pattern, host_encode_ms, wall_best_s, wall_median_s (B=%d)" % B)
for name, sk in PATTERNS.items():
    # host-side secret handling in isolation: GLV split + digit recode of
    # the 2B scalar rows the fused blind-sign program uploads
    scal_rows = [list(sk.y[:2]) + [0]] * B + [list(sk.y[:2]) + [sk.x]] * B
    t0 = time.perf_counter()
    split = [[h for s in row for h in glv.decompose(s)] for row in scal_rows]
    _signed_digits(split, nwin=glv.NWIN_5)
    host_ms = (time.perf_counter() - t0) * 1e3

    batch_blind_sign(reqs, sk, params, backend=be)  # warm/compile
    walls = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        batch_blind_sign(reqs, sk, params, backend=be)
        walls.append(time.perf_counter() - t0)
    print(
        "%-18s %8.1f %10.4f %10.4f"
        % (name, host_ms, min(walls), statistics.median(walls))
    )
