// ccbls — native BLS12-381 core for the coconut_tpu framework.
//
// SURVEY.md §7 stage 1: the from-scratch equivalent of the reference's
// amcl/amcl_wrapper curve layer (reference Cargo.toml:16-19; call sites
// signature.rs:157,424-428,465,513,521 and the pairing check reached via
// signature.rs:472-478). Design follows the framework's own Python spec
// (coconut_tpu/ops/fields.py, curve.py, pairing.py) — results are
// bit-identical to the spec on canonical (affine / boolean) outputs, which
// tests/test_backends.py enforces differentially for every backend.
//
// Layout of the file: Fp (6x64 Montgomery) -> Fp2/Fp6/Fp12 tower -> G1/G2
// Jacobian points -> shared-base windowed MSM (var-time, public data, and a
// fixed-window masked-lookup variant for secret scalars) -> projective
// Miller loop + final exponentiation -> batch C ABI.
//
// Wire codec (the C ABI boundary): Fp = 48 bytes little-endian canonical;
// Fp2 = c0 || c1; affine points = x || y with the point at infinity encoded
// as all-zero bytes (not a curve point: 0^3 + 4 != 0); scalars = 32 bytes
// little-endian canonical Fr.

#include <cstdint>
#include <cstring>
#include <vector>

using u64 = uint64_t;
using u128 = unsigned __int128;

// ---------------------------------------------------------------------------
// Fp: base field, 6x64-bit limbs, Montgomery domain (R = 2^384)
// ---------------------------------------------------------------------------

static const u64 PL[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
// -p^{-1} mod 2^64
static const u64 P_N0 = 0x89f3fffcfffcfffdULL;
// R^2 mod p (enters the Montgomery domain)
static const u64 RR[6] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};

struct Fp {
  u64 v[6];
};

static inline bool fp_is_zero_raw(const Fp &a) {
  u64 t = 0;
  for (int i = 0; i < 6; i++) t |= a.v[i];
  return t == 0;
}

static inline bool fp_eq_raw(const Fp &a, const Fp &b) {
  u64 t = 0;
  for (int i = 0; i < 6; i++) t |= a.v[i] ^ b.v[i];
  return t == 0;
}

static inline int fp_cmp_p(const Fp &a) {  // a ? p  -> -1,0,1
  for (int i = 5; i >= 0; i--) {
    if (a.v[i] < PL[i]) return -1;
    if (a.v[i] > PL[i]) return 1;
  }
  return 0;
}

static inline void fp_sub_p(Fp &a) {
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a.v[i] - PL[i] - borrow;
    a.v[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
}

static inline Fp fp_add(const Fp &a, const Fp &b) {
  Fp r;
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a.v[i] + b.v[i] + carry;
    r.v[i] = (u64)s;
    carry = s >> 64;
  }
  if (carry || fp_cmp_p(r) >= 0) fp_sub_p(r);
  return r;
}

static inline Fp fp_sub(const Fp &a, const Fp &b) {
  Fp r;
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  if (borrow) {
    u128 carry = 0;
    for (int i = 0; i < 6; i++) {
      u128 s = (u128)r.v[i] + PL[i] + carry;
      r.v[i] = (u64)s;
      carry = s >> 64;
    }
  }
  return r;
}

static inline Fp fp_neg(const Fp &a) {
  if (fp_is_zero_raw(a)) return a;
  Fp p;
  memcpy(p.v, PL, sizeof(PL));
  return fp_sub(p, a);
}

static inline Fp fp_dbl(const Fp &a) { return fp_add(a, a); }

// CIOS Montgomery multiplication: r = a*b*R^{-1} mod p
static inline Fp fp_mul(const Fp &a, const Fp &b) {
  u64 t[8] = {0};
  for (int i = 0; i < 6; i++) {
    u128 carry = 0;
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)t[j] + (u128)a.v[i] * b.v[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[6] + carry;
    t[6] = (u64)s;
    t[7] = (u64)(s >> 64);

    u64 m = t[0] * P_N0;
    carry = ((u128)t[0] + (u128)m * PL[0]) >> 64;
    for (int j = 1; j < 6; j++) {
      u128 s2 = (u128)t[j] + (u128)m * PL[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    s = (u128)t[6] + carry;
    t[5] = (u64)s;
    t[6] = t[7] + (u64)(s >> 64);
    t[7] = 0;
  }
  Fp r;
  memcpy(r.v, t, 48);
  if (t[6] || fp_cmp_p(r) >= 0) fp_sub_p(r);
  return r;
}

static inline Fp fp_sq(const Fp &a) { return fp_mul(a, a); }

static inline Fp fp_mul_small(const Fp &a, u64 k) {
  Fp r = {{0, 0, 0, 0, 0, 0}};
  Fp base = a;
  while (k) {
    if (k & 1) r = fp_add(r, base);
    k >>= 1;
    if (k) base = fp_dbl(base);
  }
  return r;
}

static const Fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static Fp FP_ONE;  // R mod p, set in init

static Fp fp_from_le(const uint8_t *b) {  // canonical LE bytes -> Montgomery
  Fp a;
  for (int i = 0; i < 6; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w |= (u64)b[i * 8 + j] << (8 * j);
    a.v[i] = w;
  }
  Fp rr;
  memcpy(rr.v, RR, 48);
  return fp_mul(a, rr);
}

static void fp_to_le(const Fp &a, uint8_t *b) {  // Montgomery -> canonical LE
  Fp one = {{1, 0, 0, 0, 0, 0}};
  Fp c = fp_mul(a, one);  // divides by R
  for (int i = 0; i < 6; i++)
    for (int j = 0; j < 8; j++) b[i * 8 + j] = (uint8_t)(c.v[i] >> (8 * j));
}

// a^e for big-endian limb exponent (var-time; used for inversion & init pows)
static Fp fp_pow(const Fp &a, const u64 *e, int nlimbs) {
  Fp r = FP_ONE;
  bool started = false;
  for (int i = nlimbs - 1; i >= 0; i--) {
    for (int bit = 63; bit >= 0; bit--) {
      if (started) r = fp_sq(r);
      if ((e[i] >> bit) & 1) {
        if (!started) {
          r = a;
          started = true;
        } else {
          r = fp_mul(r, a);
        }
      }
    }
  }
  return r;
}

static Fp fp_inv(const Fp &a) {  // a^{p-2}
  u64 e[6];
  memcpy(e, PL, 48);
  u128 d = (u128)e[0] - 2;
  e[0] = (u64)d;  // p-2 (no borrow: p odd, > 2)
  return fp_pow(a, e, 6);
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1); Fp6 = Fp2[v]/(v^3 - (u+1)); Fp12 = Fp6[w]/(w^2 - v)
// (the spec's tower, ops/fields.py)
// ---------------------------------------------------------------------------

struct Fp2 {
  Fp c0, c1;
};

static inline Fp2 fp2_add(const Fp2 &a, const Fp2 &b) {
  return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
static inline Fp2 fp2_sub(const Fp2 &a, const Fp2 &b) {
  return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
static inline Fp2 fp2_neg(const Fp2 &a) { return {fp_neg(a.c0), fp_neg(a.c1)}; }
static inline Fp2 fp2_conj(const Fp2 &a) { return {a.c0, fp_neg(a.c1)}; }

static inline Fp2 fp2_mul(const Fp2 &a, const Fp2 &b) {
  Fp t0 = fp_mul(a.c0, b.c0);
  Fp t1 = fp_mul(a.c1, b.c1);
  Fp t2 = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
  return {fp_sub(t0, t1), fp_sub(fp_sub(t2, t0), t1)};
}

static inline Fp2 fp2_sq(const Fp2 &a) {
  return {fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1)),
          fp_dbl(fp_mul(a.c0, a.c1))};
}

static inline Fp2 fp2_mul_fp(const Fp2 &a, const Fp &s) {
  return {fp_mul(a.c0, s), fp_mul(a.c1, s)};
}

static inline Fp2 fp2_mul_small(const Fp2 &a, u64 k) {
  return {fp_mul_small(a.c0, k), fp_mul_small(a.c1, k)};
}

static inline Fp2 fp2_mul_xi(const Fp2 &a) {  // * (u+1)
  return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

static inline Fp2 fp2_inv(const Fp2 &a) {
  Fp norm = fp_add(fp_sq(a.c0), fp_sq(a.c1));
  Fp ni = fp_inv(norm);
  return {fp_mul(a.c0, ni), fp_neg(fp_mul(a.c1, ni))};
}

static inline bool fp2_is_zero(const Fp2 &a) {
  return fp_is_zero_raw(a.c0) && fp_is_zero_raw(a.c1);
}
static inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
  return fp_eq_raw(a.c0, b.c0) && fp_eq_raw(a.c1, b.c1);
}

static const Fp2 FP2_ZERO = {FP_ZERO, FP_ZERO};
static Fp2 FP2_ONE;  // set in init

static Fp2 fp2_pow(const Fp2 &a, const u64 *e, int nlimbs) {
  Fp2 r = FP2_ONE;
  bool started = false;
  for (int i = nlimbs - 1; i >= 0; i--)
    for (int bit = 63; bit >= 0; bit--) {
      if (started) r = fp2_sq(r);
      if ((e[i] >> bit) & 1) {
        if (!started) {
          r = a;
          started = true;
        } else {
          r = fp2_mul(r, a);
        }
      }
    }
  return r;
}

struct Fp6 {
  Fp2 c0, c1, c2;
};

static inline Fp6 fp6_add(const Fp6 &a, const Fp6 &b) {
  return {fp2_add(a.c0, b.c0), fp2_add(a.c1, b.c1), fp2_add(a.c2, b.c2)};
}
static inline Fp6 fp6_sub(const Fp6 &a, const Fp6 &b) {
  return {fp2_sub(a.c0, b.c0), fp2_sub(a.c1, b.c1), fp2_sub(a.c2, b.c2)};
}
static inline Fp6 fp6_neg(const Fp6 &a) {
  return {fp2_neg(a.c0), fp2_neg(a.c1), fp2_neg(a.c2)};
}

static inline Fp6 fp6_mul(const Fp6 &a, const Fp6 &b) {
  Fp2 t0 = fp2_mul(a.c0, b.c0);
  Fp2 t1 = fp2_mul(a.c1, b.c1);
  Fp2 t2 = fp2_mul(a.c2, b.c2);
  Fp2 c0 = fp2_add(
      t0, fp2_mul_xi(fp2_sub(
              fp2_sub(fp2_mul(fp2_add(a.c1, a.c2), fp2_add(b.c1, b.c2)), t1),
              t2)));
  Fp2 c1 = fp2_add(
      fp2_sub(fp2_sub(fp2_mul(fp2_add(a.c0, a.c1), fp2_add(b.c0, b.c1)), t0),
              t1),
      fp2_mul_xi(t2));
  Fp2 c2 = fp2_add(
      fp2_sub(fp2_sub(fp2_mul(fp2_add(a.c0, a.c2), fp2_add(b.c0, b.c2)), t0),
              t2),
      t1);
  return {c0, c1, c2};
}

static inline Fp6 fp6_mul_by_01(const Fp6 &a, const Fp2 &s0, const Fp2 &s1) {
  return {fp2_add(fp2_mul(a.c0, s0), fp2_mul_xi(fp2_mul(a.c2, s1))),
          fp2_add(fp2_mul(a.c1, s0), fp2_mul(a.c0, s1)),
          fp2_add(fp2_mul(a.c2, s0), fp2_mul(a.c1, s1))};
}

static inline Fp6 fp6_mul_by_1(const Fp6 &a, const Fp2 &s1) {
  return {fp2_mul_xi(fp2_mul(a.c2, s1)), fp2_mul(a.c0, s1), fp2_mul(a.c1, s1)};
}

static inline Fp6 fp6_mul_by_v(const Fp6 &a) {
  return {fp2_mul_xi(a.c2), a.c0, a.c1};
}

static inline Fp6 fp6_inv(const Fp6 &a) {
  Fp2 c0 = fp2_sub(fp2_sq(a.c0), fp2_mul_xi(fp2_mul(a.c1, a.c2)));
  Fp2 c1 = fp2_sub(fp2_mul_xi(fp2_sq(a.c2)), fp2_mul(a.c0, a.c1));
  Fp2 c2 = fp2_sub(fp2_sq(a.c1), fp2_mul(a.c0, a.c2));
  Fp2 t = fp2_add(fp2_mul_xi(fp2_add(fp2_mul(a.c2, c1), fp2_mul(a.c1, c2))),
                  fp2_mul(a.c0, c0));
  Fp2 ti = fp2_inv(t);
  return {fp2_mul(c0, ti), fp2_mul(c1, ti), fp2_mul(c2, ti)};
}

static const Fp6 FP6_ZERO = {FP2_ZERO, FP2_ZERO, FP2_ZERO};
static Fp6 FP6_ONE;

struct Fp12 {
  Fp6 c0, c1;
};

static Fp12 FP12_ONE;

static inline Fp12 fp12_mul(const Fp12 &a, const Fp12 &b) {
  Fp6 t0 = fp6_mul(a.c0, b.c0);
  Fp6 t1 = fp6_mul(a.c1, b.c1);
  Fp6 c0 = fp6_add(t0, fp6_mul_by_v(t1));
  Fp6 c1 =
      fp6_sub(fp6_sub(fp6_mul(fp6_add(a.c0, a.c1), fp6_add(b.c0, b.c1)), t0),
              t1);
  return {c0, c1};
}

static inline Fp12 fp12_sq(const Fp12 &a) {
  Fp6 t = fp6_mul(a.c0, a.c1);
  Fp6 c0 = fp6_sub(
      fp6_sub(fp6_mul(fp6_add(a.c0, a.c1), fp6_add(a.c0, fp6_mul_by_v(a.c1))),
              t),
      fp6_mul_by_v(t));
  Fp6 c1 = fp6_add(t, t);
  return {c0, c1};
}

static inline Fp12 fp12_conj(const Fp12 &a) { return {a.c0, fp6_neg(a.c1)}; }

static inline Fp12 fp12_inv(const Fp12 &a) {
  Fp6 t = fp6_sub(fp6_mul(a.c0, a.c0), fp6_mul_by_v(fp6_mul(a.c1, a.c1)));
  Fp6 ti = fp6_inv(t);
  return {fp6_mul(a.c0, ti), fp6_neg(fp6_mul(a.c1, ti))};
}

// f * (lA + lB w^2 + lC w^3): the Miller-loop sparse product
// (spec ops/pairing.py line_to_fp12 + tower mul_line)
static inline Fp12 fp12_mul_line(const Fp12 &f, const Fp2 &lA, const Fp2 &lB,
                                 const Fp2 &lC) {
  Fp6 t0 = fp6_mul_by_01(f.c0, lA, lB);
  Fp6 t1 = fp6_mul_by_1(f.c1, lC);
  Fp6 c0 = fp6_add(t0, fp6_mul_by_v(t1));
  Fp6 mixed = fp6_mul_by_01(fp6_add(f.c0, f.c1), lA, fp2_add(lB, lC));
  Fp6 c1 = fp6_sub(fp6_sub(mixed, t0), t1);
  return {c0, c1};
}

// Frobenius coefficients (computed at init: gamma1[i] = xi^{i(p-1)/6},
// gamma2[i] = gamma1[i] * conj(gamma1[i]), mirroring the spec's
// ops/fields.py _GAMMA1/_GAMMA2)
static Fp2 G1C[6];
static Fp2 G2C[6];

static inline Fp12 fp12_frobenius(const Fp12 &a) {
  Fp12 r;
  r.c0.c0 = fp2_conj(a.c0.c0);
  r.c0.c1 = fp2_mul(fp2_conj(a.c0.c1), G1C[2]);
  r.c0.c2 = fp2_mul(fp2_conj(a.c0.c2), G1C[4]);
  r.c1.c0 = fp2_mul(fp2_conj(a.c1.c0), G1C[1]);
  r.c1.c1 = fp2_mul(fp2_conj(a.c1.c1), G1C[3]);
  r.c1.c2 = fp2_mul(fp2_conj(a.c1.c2), G1C[5]);
  return r;
}

static inline Fp12 fp12_frobenius2(const Fp12 &a) {
  Fp12 r;
  r.c0.c0 = a.c0.c0;
  r.c0.c1 = fp2_mul(a.c0.c1, G2C[2]);
  r.c0.c2 = fp2_mul(a.c0.c2, G2C[4]);
  r.c1.c0 = fp2_mul(a.c1.c0, G2C[1]);
  r.c1.c1 = fp2_mul(a.c1.c1, G2C[3]);
  r.c1.c2 = fp2_mul(a.c1.c2, G2C[5]);
  return r;
}

static inline bool fp2_is_one(const Fp2 &a) {
  return fp_eq_raw(a.c0, FP_ONE) && fp_is_zero_raw(a.c1);
}

static inline bool fp12_eq_one(const Fp12 &a) {
  return fp2_is_one(a.c0.c0) && fp2_is_zero(a.c0.c1) && fp2_is_zero(a.c0.c2) &&
         fp2_is_zero(a.c1.c0) && fp2_is_zero(a.c1.c1) && fp2_is_zero(a.c1.c2);
}

// ---------------------------------------------------------------------------
// Curve points (Jacobian), generic over the coordinate field
// ---------------------------------------------------------------------------

template <typename F>
struct FieldOps;  // add/sub/mul/sq/neg/dbl/small/inv/zero/one/is_zero/eq

template <>
struct FieldOps<Fp> {
  static Fp add(const Fp &a, const Fp &b) { return fp_add(a, b); }
  static Fp sub(const Fp &a, const Fp &b) { return fp_sub(a, b); }
  static Fp mul(const Fp &a, const Fp &b) { return fp_mul(a, b); }
  static Fp sq(const Fp &a) { return fp_sq(a); }
  static Fp neg(const Fp &a) { return fp_neg(a); }
  static Fp small(const Fp &a, u64 k) { return fp_mul_small(a, k); }
  static Fp inv(const Fp &a) { return fp_inv(a); }
  static Fp zero() { return FP_ZERO; }
  static Fp one() { return FP_ONE; }
  static bool is_zero(const Fp &a) { return fp_is_zero_raw(a); }
  static bool eq(const Fp &a, const Fp &b) { return fp_eq_raw(a, b); }
};

template <>
struct FieldOps<Fp2> {
  static Fp2 add(const Fp2 &a, const Fp2 &b) { return fp2_add(a, b); }
  static Fp2 sub(const Fp2 &a, const Fp2 &b) { return fp2_sub(a, b); }
  static Fp2 mul(const Fp2 &a, const Fp2 &b) { return fp2_mul(a, b); }
  static Fp2 sq(const Fp2 &a) { return fp2_sq(a); }
  static Fp2 neg(const Fp2 &a) { return fp2_neg(a); }
  static Fp2 small(const Fp2 &a, u64 k) { return fp2_mul_small(a, k); }
  static Fp2 inv(const Fp2 &a) { return fp2_inv(a); }
  static Fp2 zero() { return FP2_ZERO; }
  static Fp2 one() { return FP2_ONE; }
  static bool is_zero(const Fp2 &a) { return fp2_is_zero(a); }
  static bool eq(const Fp2 &a, const Fp2 &b) { return fp2_eq(a, b); }
};

template <typename F>
struct Jac {
  F X, Y, Z;
};

template <typename F>
static inline bool jac_is_inf(const Jac<F> &p) {
  return FieldOps<F>::is_zero(p.Z);
}

template <typename F>
static inline Jac<F> jac_inf() {
  return {FieldOps<F>::one(), FieldOps<F>::one(), FieldOps<F>::zero()};
}

// Same formulas as the spec (ops/curve.py:95-113)
template <typename F>
static Jac<F> jac_double(const Jac<F> &p) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z) || O::is_zero(p.Y)) return jac_inf<F>();
  F A = O::sq(p.X);
  F B = O::sq(p.Y);
  F C = O::sq(B);
  F D = O::sub(O::sub(O::sq(O::add(p.X, B)), A), C);
  D = O::add(D, D);
  F E = O::small(A, 3);
  F Fv = O::sq(E);
  F X3 = O::sub(Fv, O::add(D, D));
  F Y3 = O::sub(O::mul(E, O::sub(D, X3)), O::small(C, 8));
  F Z3 = O::mul(O::add(p.Y, p.Y), p.Z);
  return {X3, Y3, Z3};
}

// Same formulas as the spec (ops/curve.py:115-143)
template <typename F>
static Jac<F> jac_add(const Jac<F> &p, const Jac<F> &q) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z)) return q;
  if (O::is_zero(q.Z)) return p;
  F Z1Z1 = O::sq(p.Z);
  F Z2Z2 = O::sq(q.Z);
  F U1 = O::mul(p.X, Z2Z2);
  F U2 = O::mul(q.X, Z1Z1);
  F S1 = O::mul(p.Y, O::mul(q.Z, Z2Z2));
  F S2 = O::mul(q.Y, O::mul(p.Z, Z1Z1));
  F H = O::sub(U2, U1);
  F rr = O::sub(S2, S1);
  if (O::is_zero(H)) {
    if (O::is_zero(rr)) return jac_double(p);
    return jac_inf<F>();
  }
  rr = O::add(rr, rr);
  F I = O::sq(O::add(H, H));
  F J = O::mul(H, I);
  F V = O::mul(U1, I);
  F X3 = O::sub(O::sub(O::sq(rr), J), O::add(V, V));
  F S1J = O::mul(S1, J);
  F Y3 = O::sub(O::mul(rr, O::sub(V, X3)), O::add(S1J, S1J));
  F Z3 = O::mul(O::mul(p.Z, q.Z), H);
  Z3 = O::add(Z3, Z3);
  return {X3, Y3, Z3};
}

// Mixed addition q affine (Z=1) — saves ~4 muls in the MSM inner loop
template <typename F>
static Jac<F> jac_add_affine(const Jac<F> &p, const F &qx, const F &qy,
                             bool q_inf) {
  using O = FieldOps<F>;
  if (q_inf) return p;
  if (O::is_zero(p.Z)) return {qx, qy, O::one()};
  F Z1Z1 = O::sq(p.Z);
  F U2 = O::mul(qx, Z1Z1);
  F S2 = O::mul(qy, O::mul(p.Z, Z1Z1));
  F H = O::sub(U2, p.X);
  F rr = O::sub(S2, p.Y);
  if (O::is_zero(H)) {
    if (O::is_zero(rr)) return jac_double(p);
    return jac_inf<F>();
  }
  rr = O::add(rr, rr);
  F I = O::sq(O::add(H, H));
  F J = O::mul(H, I);
  F V = O::mul(p.X, I);
  F X3 = O::sub(O::sub(O::sq(rr), J), O::add(V, V));
  F S1J = O::mul(p.Y, J);
  S1J = O::add(S1J, S1J);
  F Y3 = O::sub(O::mul(rr, O::sub(V, X3)), S1J);
  F Z3 = O::mul(p.Z, H);
  Z3 = O::add(Z3, Z3);
  return {X3, Y3, Z3};
}

template <typename F>
static void jac_to_affine(const Jac<F> &p, F &x, F &y, bool &inf) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z)) {
    inf = true;
    x = O::zero();
    y = O::zero();
    return;
  }
  inf = false;
  F zi = O::inv(p.Z);
  F zi2 = O::sq(zi);
  x = O::mul(p.X, zi2);
  y = O::mul(p.Y, O::mul(zi2, zi));
}

// ---------------------------------------------------------------------------
// Shared-base windowed MSM (matches the TPU kernel's schedule: 4-bit
// windows msb-first over 256-bit scalars, per-base 16-entry tables)
// ---------------------------------------------------------------------------

struct Scalar {
  u64 v[4];
};  // 256-bit LE canonical

static inline unsigned scalar_window(const Scalar &s, int w) {
  // w = window index from msb: bits [252-4w .. 255-4w]
  int lo = 252 - 4 * w;
  return (unsigned)((s.v[lo / 64] >> (lo % 64)) & 0xf);
}

template <typename F>
static void msm_tables(const F *bx, const F *by, const bool *binf, int k,
                       std::vector<Jac<F>> &tables) {
  tables.assign((size_t)k * 16, jac_inf<F>());
  for (int j = 0; j < k; j++) {
    Jac<F> *row = &tables[(size_t)j * 16];
    row[0] = jac_inf<F>();
    if (binf[j]) {
      for (int d = 1; d < 16; d++) row[d] = jac_inf<F>();
      continue;
    }
    Jac<F> base = {bx[j], by[j], FieldOps<F>::one()};
    row[1] = base;
    for (int d = 2; d < 16; d++) row[d] = jac_add(row[d - 1], base);
  }
}

// One batch row: acc = sum_j s[j] * base[j], var-time (public data — the
// verify-side split the reference also makes, signature.rs:465 vs :513)
template <typename F>
static Jac<F> msm_row(const std::vector<Jac<F>> &tables, const Scalar *s,
                      int k) {
  Jac<F> acc = jac_inf<F>();
  for (int w = 0; w < 64; w++) {
    if (w) {
      acc = jac_double(acc);
      acc = jac_double(acc);
      acc = jac_double(acc);
      acc = jac_double(acc);
    }
    for (int j = 0; j < k; j++) {
      unsigned d = scalar_window(s[j], w);
      if (d) acc = jac_add(acc, tables[(size_t)j * 16 + d]);
    }
  }
  return acc;
}

// Fixed-window masked-lookup variant for secret scalars (issuance side:
// const-time MSM call sites signature.rs:157,424-428). Every table entry is
// read and every add executed; selection is by byte masks.
template <typename F>
static Jac<F> msm_row_ct(const std::vector<Jac<F>> &tables, const Scalar *s,
                         int k) {
  using O = FieldOps<F>;
  Jac<F> acc = jac_inf<F>();
  for (int w = 0; w < 64; w++) {
    if (w) {
      acc = jac_double(acc);
      acc = jac_double(acc);
      acc = jac_double(acc);
      acc = jac_double(acc);
    }
    for (int j = 0; j < k; j++) {
      unsigned d = scalar_window(s[j], w);
      // masked gather of tables[j][d]
      Jac<F> e = jac_inf<F>();
      const u64 *src0 = (const u64 *)&tables[(size_t)j * 16];
      u64 *dst = (u64 *)&e;
      size_t words = sizeof(Jac<F>) / 8;
      for (unsigned t = 0; t < 16; t++) {
        u64 mask = (u64)0 - (u64)(t == d);
        const u64 *src = src0 + (size_t)t * words;
        for (size_t q = 0; q < words; q++) dst[q] = (dst[q] & ~mask) | (src[q] & mask);
      }
      acc = jac_add(acc, e);  // NOTE: add itself branches on edge cases;
      // full constant-time completeness is documented as a caveat in
      // coconut_tpu/native.py (the verify hot path never uses this variant).
    }
  }
  return acc;
}

// ---------------------------------------------------------------------------
// Pairing: projective Miller loop + final exponentiation
// (structure mirrors the spec ops/pairing.py miller_loop_projective /
// final_exp_chain and the TPU kernel tpu/pairing.py — same line coeffs,
// same x-power chain)
// ---------------------------------------------------------------------------

static const u64 BLS_X_ABS = 0xd201000000010000ULL;  // |x|, x < 0

struct ProjT {
  Fp2 X, Y, Z;
};

static inline void proj_double_step(ProjT &T, Fp2 &lA, Fp2 &lB, Fp2 &lC) {
  Fp2 A = fp2_sq(T.X);
  Fp2 B = fp2_sq(T.Y);
  Fp2 C = fp2_sq(T.Z);
  Fp2 D = fp2_mul(fp2_mul(T.X, B), T.Z);
  Fp2 Fv = fp2_sub(fp2_mul_small(fp2_sq(A), 9), fp2_mul_small(D, 8));
  Fp2 YZ = fp2_mul(T.Y, T.Z);
  Fp2 X3 = fp2_mul(fp2_mul_small(YZ, 2), Fv);
  Fp2 Y3 = fp2_sub(
      fp2_mul(fp2_mul_small(A, 3), fp2_sub(fp2_mul_small(D, 4), Fv)),
      fp2_mul_small(fp2_mul(fp2_sq(B), C), 8));
  Fp2 t = fp2_mul_small(YZ, 2);
  Fp2 Z3 = fp2_mul(fp2_sq(t), t);
  lA = fp2_sub(fp2_mul(T.X, A), fp2_mul_small(fp2_mul_xi(fp2_mul(T.Z, C)), 8));
  lB = fp2_neg(fp2_mul_small(fp2_mul(A, T.Z), 3));
  lC = fp2_mul_small(fp2_mul(T.Y, C), 2);
  T = {X3, Y3, Z3};
}

static inline void proj_add_step(ProjT &T, const Fp2 &qx, const Fp2 &qy,
                                 Fp2 &lA, Fp2 &lB, Fp2 &lC) {
  Fp2 theta = fp2_sub(T.Y, fp2_mul(qy, T.Z));
  Fp2 lam = fp2_sub(T.X, fp2_mul(qx, T.Z));
  Fp2 lam2 = fp2_sq(lam);
  Fp2 lam3 = fp2_mul(lam2, lam);
  Fp2 H = fp2_sub(fp2_mul(fp2_sq(theta), T.Z),
                  fp2_mul(lam2, fp2_add(T.X, fp2_mul(qx, T.Z))));
  Fp2 X3 = fp2_mul(lam, H);
  Fp2 Y3 = fp2_sub(fp2_mul(theta, fp2_sub(fp2_mul(lam2, T.X), H)),
                   fp2_mul(lam3, T.Y));
  Fp2 Z3 = fp2_mul(lam3, T.Z);
  lA = fp2_sub(fp2_mul(theta, qx), fp2_mul(lam, qy));
  lB = fp2_neg(theta);
  lC = lam;
  T = {X3, Y3, Z3};
}

// Accumulate one pair's Miller factor into f. P=(px,py) G1 affine,
// Q=(qx,qy) twist affine; both non-infinite (caller filters).
static void miller_accumulate(Fp12 &f, const Fp &px, const Fp &py,
                              const Fp2 &qx, const Fp2 &qy) {
  ProjT T = {qx, qy, FP2_ONE};
  Fp2 lA, lB, lC;
  // msb-first over |x| bits, skipping the leading 1
  int top = 63;
  while (!((BLS_X_ABS >> top) & 1)) top--;
  Fp12 g = FP12_ONE;
  for (int i = top - 1; i >= 0; i--) {
    g = fp12_sq(g);
    proj_double_step(T, lA, lB, lC);
    g = fp12_mul_line(g, lA, fp2_mul_fp(lB, px), fp2_mul_fp(lC, py));
    if ((BLS_X_ABS >> i) & 1) {
      proj_add_step(T, qx, qy, lA, lB, lC);
      g = fp12_mul_line(g, lA, fp2_mul_fp(lB, px), fp2_mul_fp(lC, py));
    }
  }
  g = fp12_conj(g);  // x < 0
  f = fp12_mul(f, g);
}

// NOTE: squaring the per-pair factor separately then multiplying loses the
// shared-squaring optimization of a true multi-Miller loop; the batch API
// below instead interleaves pairs inside ONE loop:

static Fp12 multi_miller(const Fp *pxs, const Fp *pys, const Fp2 *qxs,
                         const Fp2 *qys, const bool *skip, int n) {
  std::vector<ProjT> T(n);
  for (int i = 0; i < n; i++)
    if (!skip[i]) T[i] = {qxs[i], qys[i], FP2_ONE};
  int top = 63;
  while (!((BLS_X_ABS >> top) & 1)) top--;
  Fp12 f = FP12_ONE;
  Fp2 lA, lB, lC;
  for (int i = top - 1; i >= 0; i--) {
    f = fp12_sq(f);
    for (int j = 0; j < n; j++) {
      if (skip[j]) continue;
      proj_double_step(T[j], lA, lB, lC);
      f = fp12_mul_line(f, lA, fp2_mul_fp(lB, pxs[j]), fp2_mul_fp(lC, pys[j]));
    }
    if ((BLS_X_ABS >> i) & 1) {
      for (int j = 0; j < n; j++) {
        if (skip[j]) continue;
        proj_add_step(T[j], qxs[j], qys[j], lA, lB, lC);
        f = fp12_mul_line(f, lA, fp2_mul_fp(lB, pxs[j]),
                          fp2_mul_fp(lC, pys[j]));
      }
    }
  }
  return fp12_conj(f);  // x < 0
}

static Fp12 fp12_pow_x_abs(const Fp12 &m) {
  int top = 63;
  while (!((BLS_X_ABS >> top) & 1)) top--;
  Fp12 acc = m;
  for (int i = top - 1; i >= 0; i--) {
    acc = fp12_sq(acc);
    if ((BLS_X_ABS >> i) & 1) acc = fp12_mul(acc, m);
  }
  return acc;
}

static inline Fp12 fp12_pow_x_neg(const Fp12 &m) {
  return fp12_conj(fp12_pow_x_abs(m));
}

// Identical chain to the spec's final_exp_chain (ops/pairing.py:269-289)
static Fp12 final_exp(const Fp12 &f) {
  Fp12 m = fp12_mul(fp12_conj(f), fp12_inv(f));
  m = fp12_mul(fp12_frobenius2(m), m);
  Fp12 t0 = fp12_mul(fp12_pow_x_neg(m), fp12_conj(m));
  Fp12 t1 = fp12_mul(fp12_pow_x_neg(t0), fp12_conj(t0));
  Fp12 t2 = fp12_mul(fp12_pow_x_neg(t1), fp12_frobenius(t1));
  Fp12 t3 = fp12_mul(fp12_mul(fp12_pow_x_neg(fp12_pow_x_neg(t2)),
                              fp12_frobenius2(t2)),
                     fp12_conj(t2));
  return fp12_mul(t3, fp12_mul(fp12_sq(m), m));
}

// ---------------------------------------------------------------------------
// Codec helpers for the C ABI
// ---------------------------------------------------------------------------

static bool g1_load(const uint8_t *b, Fp &x, Fp &y) {  // returns inf flag
  bool allz = true;
  for (int i = 0; i < 96; i++)
    if (b[i]) {
      allz = false;
      break;
    }
  if (allz) {
    x = FP_ZERO;
    y = FP_ZERO;
    return true;
  }
  x = fp_from_le(b);
  y = fp_from_le(b + 48);
  return false;
}

static void g1_store(uint8_t *b, const Fp &x, const Fp &y, bool inf) {
  if (inf) {
    memset(b, 0, 96);
    return;
  }
  fp_to_le(x, b);
  fp_to_le(y, b + 48);
}

static bool g2_load(const uint8_t *b, Fp2 &x, Fp2 &y) {
  bool allz = true;
  for (int i = 0; i < 192; i++)
    if (b[i]) {
      allz = false;
      break;
    }
  if (allz) {
    x = FP2_ZERO;
    y = FP2_ZERO;
    return true;
  }
  x.c0 = fp_from_le(b);
  x.c1 = fp_from_le(b + 48);
  y.c0 = fp_from_le(b + 96);
  y.c1 = fp_from_le(b + 144);
  return false;
}

static void g2_store(uint8_t *b, const Fp2 &x, const Fp2 &y, bool inf) {
  if (inf) {
    memset(b, 0, 192);
    return;
  }
  fp_to_le(x.c0, b);
  fp_to_le(x.c1, b + 48);
  fp_to_le(y.c0, b + 96);
  fp_to_le(y.c1, b + 144);
}

static Scalar scalar_load(const uint8_t *b) {
  Scalar s;
  for (int i = 0; i < 4; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w |= (u64)b[i * 8 + j] << (8 * j);
    s.v[i] = w;
  }
  return s;
}

// ---------------------------------------------------------------------------
// Init
// ---------------------------------------------------------------------------

static void ccbls_init() {
  static bool done = false;
  if (done) return;
  done = true;
  // FP_ONE = R mod p = mont(1): compute from RR via mont-mul with 1
  Fp raw1 = {{1, 0, 0, 0, 0, 0}};
  Fp rr;
  memcpy(rr.v, RR, 48);
  FP_ONE = fp_mul(raw1, rr);
  FP2_ONE = {FP_ONE, FP_ZERO};
  FP6_ONE = {FP2_ONE, FP2_ZERO, FP2_ZERO};
  FP12_ONE = {FP6_ONE, FP6_ZERO};

  // (p-1)/6 as limbs for the gamma pows
  u64 e[6];
  memcpy(e, PL, 48);
  e[0] -= 1;  // p-1 (p odd)
  // divide by 6
  u128 rem = 0;
  u64 q6[6];
  for (int i = 5; i >= 0; i--) {
    u128 cur = (rem << 64) | e[i];
    q6[i] = (u64)(cur / 6);
    rem = cur % 6;
  }
  Fp2 xi = {FP_ONE, FP_ONE};
  G1C[0] = FP2_ONE;
  G1C[1] = fp2_pow(xi, q6, 6);
  for (int i = 2; i < 6; i++) G1C[i] = fp2_mul(G1C[i - 1], G1C[1]);
  for (int i = 0; i < 6; i++) G2C[i] = fp2_mul(G1C[i], fp2_conj(G1C[i]));
}

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Shared-base batched MSM in G1. bases: k*96B affine; scalars: B*k*32B;
// out: B*96B affine. ct != 0 selects the masked-lookup schedule.
void cc_msm_g1(const uint8_t *bases, const uint8_t *scalars, int k, int B,
               uint8_t *out, int ct) {
  ccbls_init();
  std::vector<Fp> bx(k), by(k);
  std::vector<bool> binfv(k);
  std::vector<char> binf(k);
  for (int j = 0; j < k; j++) {
    binf[j] = g1_load(bases + (size_t)j * 96, bx[j], by[j]);
  }
  std::vector<Jac<Fp>> tables;
  msm_tables<Fp>(bx.data(), by.data(), (const bool *)binf.data(), k, tables);
  std::vector<Scalar> srow(k);
  for (int i = 0; i < B; i++) {
    for (int j = 0; j < k; j++)
      srow[j] = scalar_load(scalars + ((size_t)i * k + j) * 32);
    Jac<Fp> acc = ct ? msm_row_ct<Fp>(tables, srow.data(), k)
                     : msm_row<Fp>(tables, srow.data(), k);
    Fp x, y;
    bool inf;
    jac_to_affine(acc, x, y, inf);
    g1_store(out + (size_t)i * 96, x, y, inf);
  }
}

void cc_msm_g2(const uint8_t *bases, const uint8_t *scalars, int k, int B,
               uint8_t *out, int ct) {
  ccbls_init();
  std::vector<Fp2> bx(k), by(k);
  std::vector<char> binf(k);
  for (int j = 0; j < k; j++) {
    binf[j] = g2_load(bases + (size_t)j * 192, bx[j], by[j]);
  }
  std::vector<Jac<Fp2>> tables;
  msm_tables<Fp2>(bx.data(), by.data(), (const bool *)binf.data(), k, tables);
  std::vector<Scalar> srow(k);
  for (int i = 0; i < B; i++) {
    for (int j = 0; j < k; j++)
      srow[j] = scalar_load(scalars + ((size_t)i * k + j) * 32);
    Jac<Fp2> acc = ct ? msm_row_ct<Fp2>(tables, srow.data(), k)
                      : msm_row<Fp2>(tables, srow.data(), k);
    Fp2 x, y;
    bool inf;
    jac_to_affine(acc, x, y, inf);
    g2_store(out + (size_t)i * 192, x, y, inf);
  }
}

// Batched pairing-product check: for each row i of n pairs,
// out[i] = (prod_j e(P_ij, Q_ij) == 1). Pairs with either side infinite
// contribute the factor 1 (the spec's None convention).
void cc_pairing_product_is_one(const uint8_t *ps, const uint8_t *qs, int n,
                               int B, uint8_t *out) {
  ccbls_init();
  std::vector<Fp> pxs(n), pys(n);
  std::vector<Fp2> qxs(n), qys(n);
  std::vector<char> skip(n);
  for (int i = 0; i < B; i++) {
    for (int j = 0; j < n; j++) {
      bool pinf = g1_load(ps + ((size_t)i * n + j) * 96, pxs[j], pys[j]);
      bool qinf = g2_load(qs + ((size_t)i * n + j) * 192, qxs[j], qys[j]);
      skip[j] = pinf || qinf;
    }
    Fp12 f = multi_miller(pxs.data(), pys.data(), qxs.data(), qys.data(),
                          (const bool *)skip.data(), n);
    out[i] = fp12_eq_one(final_exp(f)) ? 1 : 0;
  }
}

// Single scalar mults (protocol-layer helpers): B points x B scalars.
void cc_g1_mul(const uint8_t *pts, const uint8_t *scalars, int B,
               uint8_t *out) {
  ccbls_init();
  for (int i = 0; i < B; i++) {
    Fp x, y;
    bool inf = g1_load(pts + (size_t)i * 96, x, y);
    Scalar s = scalar_load(scalars + (size_t)i * 32);
    if (inf) {
      g1_store(out + (size_t)i * 96, FP_ZERO, FP_ZERO, true);
      continue;
    }
    Jac<Fp> acc = jac_inf<Fp>();
    for (int w = 0; w < 64; w++) {
      if (w)
        for (int d = 0; d < 4; d++) acc = jac_double(acc);
      unsigned dg = scalar_window(s, w);
      if (dg) {
        Jac<Fp> base = {x, y, FP_ONE};
        Jac<Fp> t = jac_inf<Fp>();
        for (unsigned b = 0; b < dg; b++) t = jac_add_affine(t, x, y, false);
        acc = jac_add(acc, t);
      }
    }
    Fp ox, oy;
    bool oinf;
    jac_to_affine(acc, ox, oy, oinf);
    g1_store(out + (size_t)i * 96, ox, oy, oinf);
  }
}

int cc_selftest() {
  ccbls_init();
  // 1 in, 1 out through the Montgomery codec
  uint8_t buf[48] = {0};
  buf[0] = 5;
  Fp a = fp_from_le(buf);
  Fp b = fp_mul(a, fp_inv(a));
  if (!fp_eq_raw(b, FP_ONE)) return 1;
  // frobenius consistency: frob applied 12x is identity on a random-ish elt
  Fp12 x = FP12_ONE;
  x.c1.c1 = {a, fp_add(a, FP_ONE)};
  x.c0.c2 = {fp_sq(a), a};
  Fp12 y = x;
  for (int i = 0; i < 12; i++) y = fp12_frobenius(y);
  const u64 *xa = (const u64 *)&x, *ya = (const u64 *)&y;
  for (size_t i = 0; i < sizeof(Fp12) / 8; i++)
    if (xa[i] != ya[i]) return 2;
  // frob2 == frob applied twice
  Fp12 f2a = fp12_frobenius2(x);
  Fp12 f2b = fp12_frobenius(fp12_frobenius(x));
  const u64 *pa = (const u64 *)&f2a, *pb = (const u64 *)&f2b;
  for (size_t i = 0; i < sizeof(Fp12) / 8; i++)
    if (pa[i] != pb[i]) return 3;
  return 0;
}

}  // extern "C"
