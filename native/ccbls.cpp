// ccbls — native BLS12-381 core for the coconut_tpu framework.
//
// SURVEY.md §7 stage 1: the from-scratch equivalent of the reference's
// amcl/amcl_wrapper curve layer (reference Cargo.toml:16-19; call sites
// signature.rs:157,424-428,465,513,521 and the pairing check reached via
// signature.rs:472-478). Design follows the framework's own Python spec
// (coconut_tpu/ops/fields.py, curve.py, pairing.py) — results are
// bit-identical to the spec on canonical (affine / boolean) outputs, which
// tests/test_backends.py enforces differentially for every backend.
//
// Layout of the file: Fp (6x64 Montgomery) -> Fp2/Fp6/Fp12 tower -> G1/G2
// Jacobian points -> shared-base windowed MSM (var-time, public data, and a
// fixed-window masked-lookup variant for secret scalars) -> projective
// Miller loop + final exponentiation -> batch C ABI.
//
// Wire codec (the C ABI boundary): Fp = 48 bytes little-endian canonical;
// Fp2 = c0 || c1; affine points = x || y with the point at infinity encoded
// as all-zero bytes (not a curve point: 0^3 + 4 != 0); scalars = 32 bytes
// little-endian canonical Fr.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <sys/random.h>
#include <vector>

using u64 = uint64_t;
using u128 = unsigned __int128;

// ---------------------------------------------------------------------------
// Fp: base field, 6x64-bit limbs, Montgomery domain (R = 2^384)
// ---------------------------------------------------------------------------

static const u64 PL[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};
// -p^{-1} mod 2^64
static const u64 P_N0 = 0x89f3fffcfffcfffdULL;
// R^2 mod p (enters the Montgomery domain)
static const u64 RR[6] = {
    0xf4df1f341c341746ULL, 0x0a76e6a609d104f1ULL, 0x8de5476c4c95b6d5ULL,
    0x67eb88a9939d83c0ULL, 0x9a793e85b519952dULL, 0x11988fe592cae3aaULL};

struct Fp {
  u64 v[6];
};

static inline bool fp_is_zero_raw(const Fp &a) {
  u64 t = 0;
  for (int i = 0; i < 6; i++) t |= a.v[i];
  return t == 0;
}

static inline bool fp_eq_raw(const Fp &a, const Fp &b) {
  u64 t = 0;
  for (int i = 0; i < 6; i++) t |= a.v[i] ^ b.v[i];
  return t == 0;
}

// Branchless normalization: every conditional reduction below is a masked
// select, so field-op timing is independent of VALUES (not just of the MSM
// schedule) — the property the const-time issuance path (msm_row_ct)
// inherits; the reference gets the same from amcl's CT normalization.

// r = r - p if (force || r >= p), as one masked pass
static inline void fp_cond_sub_p(Fp &r, u64 force) {
  u64 t[6];
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)r.v[i] - PL[i] - borrow;
    t[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  u64 mask = (u64)0 - (force | (u64)(1 - (u64)borrow));  // sub if no borrow
  for (int i = 0; i < 6; i++) r.v[i] = (r.v[i] & ~mask) | (t[i] & mask);
}

static inline Fp fp_add(const Fp &a, const Fp &b) {
  Fp r;
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a.v[i] + b.v[i] + carry;
    r.v[i] = (u64)s;
    carry = s >> 64;
  }
  fp_cond_sub_p(r, (u64)carry);
  return r;
}

static inline Fp fp_sub(const Fp &a, const Fp &b) {
  Fp r;
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  // add p back iff it underflowed, masked
  u64 mask = (u64)0 - (u64)borrow;
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)r.v[i] + (PL[i] & mask) + carry;
    r.v[i] = (u64)s;
    carry = s >> 64;
  }
  return r;
}

static inline Fp fp_neg(const Fp &a) {
  // p - a, then zero the result iff a == 0 (masked, branch-free)
  Fp p;
  memcpy(p.v, PL, sizeof(PL));
  Fp r = fp_sub(p, a);
  u64 nz = 0;
  for (int i = 0; i < 6; i++) nz |= a.v[i];
  u64 mask = (u64)0 - ((nz | ((u64)0 - nz)) >> 63);  // -1 iff a != 0
  for (int i = 0; i < 6; i++) r.v[i] &= mask;
  return r;
}

static inline Fp fp_dbl(const Fp &a) { return fp_add(a, a); }

// CIOS Montgomery multiplication: r = a*b*R^{-1} mod p
static inline Fp fp_mul(const Fp &a, const Fp &b) {
  u64 t[8] = {0};
  for (int i = 0; i < 6; i++) {
    u128 carry = 0;
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)t[j] + (u128)a.v[i] * b.v[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[6] + carry;
    t[6] = (u64)s;
    t[7] = (u64)(s >> 64);

    u64 m = t[0] * P_N0;
    carry = ((u128)t[0] + (u128)m * PL[0]) >> 64;
    for (int j = 1; j < 6; j++) {
      u128 s2 = (u128)t[j] + (u128)m * PL[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    s = (u128)t[6] + carry;
    t[5] = (u64)s;
    t[6] = t[7] + (u64)(s >> 64);
    t[7] = 0;
  }
  Fp r;
  memcpy(r.v, t, 48);
  fp_cond_sub_p(r, (u64)(t[6] != 0));
  return r;
}

static inline Fp fp_sq(const Fp &a) { return fp_mul(a, a); }

static inline Fp fp_mul_small(const Fp &a, u64 k) {
  Fp r = {{0, 0, 0, 0, 0, 0}};
  Fp base = a;
  while (k) {
    if (k & 1) r = fp_add(r, base);
    k >>= 1;
    if (k) base = fp_dbl(base);
  }
  return r;
}

static const Fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static Fp FP_ONE;  // R mod p, set in init

static Fp fp_from_le(const uint8_t *b) {  // canonical LE bytes -> Montgomery
  Fp a;
  for (int i = 0; i < 6; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w |= (u64)b[i * 8 + j] << (8 * j);
    a.v[i] = w;
  }
  Fp rr;
  memcpy(rr.v, RR, 48);
  return fp_mul(a, rr);
}

static void fp_to_le(const Fp &a, uint8_t *b) {  // Montgomery -> canonical LE
  Fp one = {{1, 0, 0, 0, 0, 0}};
  Fp c = fp_mul(a, one);  // divides by R
  for (int i = 0; i < 6; i++)
    for (int j = 0; j < 8; j++) b[i * 8 + j] = (uint8_t)(c.v[i] >> (8 * j));
}

// a^e for big-endian limb exponent (var-time; used for inversion & init pows)
static Fp fp_pow(const Fp &a, const u64 *e, int nlimbs) {
  Fp r = FP_ONE;
  bool started = false;
  for (int i = nlimbs - 1; i >= 0; i--) {
    for (int bit = 63; bit >= 0; bit--) {
      if (started) r = fp_sq(r);
      if ((e[i] >> bit) & 1) {
        if (!started) {
          r = a;
          started = true;
        } else {
          r = fp_mul(r, a);
        }
      }
    }
  }
  return r;
}

static Fp fp_inv(const Fp &a) {  // a^{p-2}
  u64 e[6];
  memcpy(e, PL, 48);
  u128 d = (u128)e[0] - 2;
  e[0] = (u64)d;  // p-2 (no borrow: p odd, > 2)
  return fp_pow(a, e, 6);
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1); Fp6 = Fp2[v]/(v^3 - (u+1)); Fp12 = Fp6[w]/(w^2 - v)
// (the spec's tower, ops/fields.py)
// ---------------------------------------------------------------------------

struct Fp2 {
  Fp c0, c1;
};

static inline Fp2 fp2_add(const Fp2 &a, const Fp2 &b) {
  return {fp_add(a.c0, b.c0), fp_add(a.c1, b.c1)};
}
static inline Fp2 fp2_sub(const Fp2 &a, const Fp2 &b) {
  return {fp_sub(a.c0, b.c0), fp_sub(a.c1, b.c1)};
}
static inline Fp2 fp2_neg(const Fp2 &a) { return {fp_neg(a.c0), fp_neg(a.c1)}; }
static inline Fp2 fp2_conj(const Fp2 &a) { return {a.c0, fp_neg(a.c1)}; }

static inline Fp2 fp2_mul(const Fp2 &a, const Fp2 &b) {
  Fp t0 = fp_mul(a.c0, b.c0);
  Fp t1 = fp_mul(a.c1, b.c1);
  Fp t2 = fp_mul(fp_add(a.c0, a.c1), fp_add(b.c0, b.c1));
  return {fp_sub(t0, t1), fp_sub(fp_sub(t2, t0), t1)};
}

static inline Fp2 fp2_sq(const Fp2 &a) {
  return {fp_mul(fp_add(a.c0, a.c1), fp_sub(a.c0, a.c1)),
          fp_dbl(fp_mul(a.c0, a.c1))};
}

static inline Fp2 fp2_mul_fp(const Fp2 &a, const Fp &s) {
  return {fp_mul(a.c0, s), fp_mul(a.c1, s)};
}

static inline Fp2 fp2_mul_small(const Fp2 &a, u64 k) {
  return {fp_mul_small(a.c0, k), fp_mul_small(a.c1, k)};
}

static inline Fp2 fp2_mul_xi(const Fp2 &a) {  // * (u+1)
  return {fp_sub(a.c0, a.c1), fp_add(a.c0, a.c1)};
}

static inline Fp2 fp2_inv(const Fp2 &a) {
  Fp norm = fp_add(fp_sq(a.c0), fp_sq(a.c1));
  Fp ni = fp_inv(norm);
  return {fp_mul(a.c0, ni), fp_neg(fp_mul(a.c1, ni))};
}

static inline bool fp2_is_zero(const Fp2 &a) {
  return fp_is_zero_raw(a.c0) && fp_is_zero_raw(a.c1);
}
static inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
  return fp_eq_raw(a.c0, b.c0) && fp_eq_raw(a.c1, b.c1);
}

static const Fp2 FP2_ZERO = {FP_ZERO, FP_ZERO};
static Fp2 FP2_ONE;  // set in init

static Fp2 fp2_pow(const Fp2 &a, const u64 *e, int nlimbs) {
  Fp2 r = FP2_ONE;
  bool started = false;
  for (int i = nlimbs - 1; i >= 0; i--)
    for (int bit = 63; bit >= 0; bit--) {
      if (started) r = fp2_sq(r);
      if ((e[i] >> bit) & 1) {
        if (!started) {
          r = a;
          started = true;
        } else {
          r = fp2_mul(r, a);
        }
      }
    }
  return r;
}

struct Fp6 {
  Fp2 c0, c1, c2;
};

static inline Fp6 fp6_add(const Fp6 &a, const Fp6 &b) {
  return {fp2_add(a.c0, b.c0), fp2_add(a.c1, b.c1), fp2_add(a.c2, b.c2)};
}
static inline Fp6 fp6_sub(const Fp6 &a, const Fp6 &b) {
  return {fp2_sub(a.c0, b.c0), fp2_sub(a.c1, b.c1), fp2_sub(a.c2, b.c2)};
}
static inline Fp6 fp6_neg(const Fp6 &a) {
  return {fp2_neg(a.c0), fp2_neg(a.c1), fp2_neg(a.c2)};
}

static inline Fp6 fp6_mul(const Fp6 &a, const Fp6 &b) {
  Fp2 t0 = fp2_mul(a.c0, b.c0);
  Fp2 t1 = fp2_mul(a.c1, b.c1);
  Fp2 t2 = fp2_mul(a.c2, b.c2);
  Fp2 c0 = fp2_add(
      t0, fp2_mul_xi(fp2_sub(
              fp2_sub(fp2_mul(fp2_add(a.c1, a.c2), fp2_add(b.c1, b.c2)), t1),
              t2)));
  Fp2 c1 = fp2_add(
      fp2_sub(fp2_sub(fp2_mul(fp2_add(a.c0, a.c1), fp2_add(b.c0, b.c1)), t0),
              t1),
      fp2_mul_xi(t2));
  Fp2 c2 = fp2_add(
      fp2_sub(fp2_sub(fp2_mul(fp2_add(a.c0, a.c2), fp2_add(b.c0, b.c2)), t0),
              t2),
      t1);
  return {c0, c1, c2};
}

static inline Fp6 fp6_mul_by_01(const Fp6 &a, const Fp2 &s0, const Fp2 &s1) {
  return {fp2_add(fp2_mul(a.c0, s0), fp2_mul_xi(fp2_mul(a.c2, s1))),
          fp2_add(fp2_mul(a.c1, s0), fp2_mul(a.c0, s1)),
          fp2_add(fp2_mul(a.c2, s0), fp2_mul(a.c1, s1))};
}

static inline Fp6 fp6_mul_by_1(const Fp6 &a, const Fp2 &s1) {
  return {fp2_mul_xi(fp2_mul(a.c2, s1)), fp2_mul(a.c0, s1), fp2_mul(a.c1, s1)};
}

static inline Fp6 fp6_mul_by_v(const Fp6 &a) {
  return {fp2_mul_xi(a.c2), a.c0, a.c1};
}

static inline Fp6 fp6_inv(const Fp6 &a) {
  Fp2 c0 = fp2_sub(fp2_sq(a.c0), fp2_mul_xi(fp2_mul(a.c1, a.c2)));
  Fp2 c1 = fp2_sub(fp2_mul_xi(fp2_sq(a.c2)), fp2_mul(a.c0, a.c1));
  Fp2 c2 = fp2_sub(fp2_sq(a.c1), fp2_mul(a.c0, a.c2));
  Fp2 t = fp2_add(fp2_mul_xi(fp2_add(fp2_mul(a.c2, c1), fp2_mul(a.c1, c2))),
                  fp2_mul(a.c0, c0));
  Fp2 ti = fp2_inv(t);
  return {fp2_mul(c0, ti), fp2_mul(c1, ti), fp2_mul(c2, ti)};
}

static const Fp6 FP6_ZERO = {FP2_ZERO, FP2_ZERO, FP2_ZERO};
static Fp6 FP6_ONE;

struct Fp12 {
  Fp6 c0, c1;
};

static Fp12 FP12_ONE;

static inline Fp12 fp12_mul(const Fp12 &a, const Fp12 &b) {
  Fp6 t0 = fp6_mul(a.c0, b.c0);
  Fp6 t1 = fp6_mul(a.c1, b.c1);
  Fp6 c0 = fp6_add(t0, fp6_mul_by_v(t1));
  Fp6 c1 =
      fp6_sub(fp6_sub(fp6_mul(fp6_add(a.c0, a.c1), fp6_add(b.c0, b.c1)), t0),
              t1);
  return {c0, c1};
}

static inline Fp12 fp12_sq(const Fp12 &a) {
  Fp6 t = fp6_mul(a.c0, a.c1);
  Fp6 c0 = fp6_sub(
      fp6_sub(fp6_mul(fp6_add(a.c0, a.c1), fp6_add(a.c0, fp6_mul_by_v(a.c1))),
              t),
      fp6_mul_by_v(t));
  Fp6 c1 = fp6_add(t, t);
  return {c0, c1};
}

static inline Fp12 fp12_conj(const Fp12 &a) { return {a.c0, fp6_neg(a.c1)}; }

static inline Fp12 fp12_inv(const Fp12 &a) {
  Fp6 t = fp6_sub(fp6_mul(a.c0, a.c0), fp6_mul_by_v(fp6_mul(a.c1, a.c1)));
  Fp6 ti = fp6_inv(t);
  return {fp6_mul(a.c0, ti), fp6_neg(fp6_mul(a.c1, ti))};
}

// f * (lA + lB w^2 + lC w^3): the Miller-loop sparse product
// (spec ops/pairing.py line_to_fp12 + tower mul_line)
static inline Fp12 fp12_mul_line(const Fp12 &f, const Fp2 &lA, const Fp2 &lB,
                                 const Fp2 &lC) {
  Fp6 t0 = fp6_mul_by_01(f.c0, lA, lB);
  Fp6 t1 = fp6_mul_by_1(f.c1, lC);
  Fp6 c0 = fp6_add(t0, fp6_mul_by_v(t1));
  Fp6 mixed = fp6_mul_by_01(fp6_add(f.c0, f.c1), lA, fp2_add(lB, lC));
  Fp6 c1 = fp6_sub(fp6_sub(mixed, t0), t1);
  return {c0, c1};
}

// Frobenius coefficients (computed at init: gamma1[i] = xi^{i(p-1)/6},
// gamma2[i] = gamma1[i] * conj(gamma1[i]), mirroring the spec's
// ops/fields.py _GAMMA1/_GAMMA2)
static Fp2 G1C[6];
static Fp2 G2C[6];

static inline Fp12 fp12_frobenius(const Fp12 &a) {
  Fp12 r;
  r.c0.c0 = fp2_conj(a.c0.c0);
  r.c0.c1 = fp2_mul(fp2_conj(a.c0.c1), G1C[2]);
  r.c0.c2 = fp2_mul(fp2_conj(a.c0.c2), G1C[4]);
  r.c1.c0 = fp2_mul(fp2_conj(a.c1.c0), G1C[1]);
  r.c1.c1 = fp2_mul(fp2_conj(a.c1.c1), G1C[3]);
  r.c1.c2 = fp2_mul(fp2_conj(a.c1.c2), G1C[5]);
  return r;
}

static inline Fp12 fp12_frobenius2(const Fp12 &a) {
  Fp12 r;
  r.c0.c0 = a.c0.c0;
  r.c0.c1 = fp2_mul(a.c0.c1, G2C[2]);
  r.c0.c2 = fp2_mul(a.c0.c2, G2C[4]);
  r.c1.c0 = fp2_mul(a.c1.c0, G2C[1]);
  r.c1.c1 = fp2_mul(a.c1.c1, G2C[3]);
  r.c1.c2 = fp2_mul(a.c1.c2, G2C[5]);
  return r;
}

static inline bool fp2_is_one(const Fp2 &a) {
  return fp_eq_raw(a.c0, FP_ONE) && fp_is_zero_raw(a.c1);
}

static inline bool fp12_eq_one(const Fp12 &a) {
  return fp2_is_one(a.c0.c0) && fp2_is_zero(a.c0.c1) && fp2_is_zero(a.c0.c2) &&
         fp2_is_zero(a.c1.c0) && fp2_is_zero(a.c1.c1) && fp2_is_zero(a.c1.c2);
}

// ---------------------------------------------------------------------------
// Curve points (Jacobian), generic over the coordinate field
// ---------------------------------------------------------------------------

template <typename F>
struct FieldOps;  // add/sub/mul/sq/neg/dbl/small/inv/zero/one/is_zero/eq

template <>
struct FieldOps<Fp> {
  static Fp add(const Fp &a, const Fp &b) { return fp_add(a, b); }
  static Fp sub(const Fp &a, const Fp &b) { return fp_sub(a, b); }
  static Fp mul(const Fp &a, const Fp &b) { return fp_mul(a, b); }
  static Fp sq(const Fp &a) { return fp_sq(a); }
  static Fp neg(const Fp &a) { return fp_neg(a); }
  static Fp small(const Fp &a, u64 k) { return fp_mul_small(a, k); }
  static Fp inv(const Fp &a) { return fp_inv(a); }
  static Fp zero() { return FP_ZERO; }
  static Fp one() { return FP_ONE; }
  static bool is_zero(const Fp &a) { return fp_is_zero_raw(a); }
  static bool eq(const Fp &a, const Fp &b) { return fp_eq_raw(a, b); }
};

template <>
struct FieldOps<Fp2> {
  static Fp2 add(const Fp2 &a, const Fp2 &b) { return fp2_add(a, b); }
  static Fp2 sub(const Fp2 &a, const Fp2 &b) { return fp2_sub(a, b); }
  static Fp2 mul(const Fp2 &a, const Fp2 &b) { return fp2_mul(a, b); }
  static Fp2 sq(const Fp2 &a) { return fp2_sq(a); }
  static Fp2 neg(const Fp2 &a) { return fp2_neg(a); }
  static Fp2 small(const Fp2 &a, u64 k) { return fp2_mul_small(a, k); }
  static Fp2 inv(const Fp2 &a) { return fp2_inv(a); }
  static Fp2 zero() { return FP2_ZERO; }
  static Fp2 one() { return FP2_ONE; }
  static bool is_zero(const Fp2 &a) { return fp2_is_zero(a); }
  static bool eq(const Fp2 &a, const Fp2 &b) { return fp2_eq(a, b); }
};

template <typename F>
struct Jac {
  F X, Y, Z;
};

template <typename F>
static inline bool jac_is_inf(const Jac<F> &p) {
  return FieldOps<F>::is_zero(p.Z);
}

template <typename F>
static inline Jac<F> jac_inf() {
  return {FieldOps<F>::one(), FieldOps<F>::one(), FieldOps<F>::zero()};
}

// Same formulas as the spec (ops/curve.py:95-113)
template <typename F>
static Jac<F> jac_double(const Jac<F> &p) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z) || O::is_zero(p.Y)) return jac_inf<F>();
  F A = O::sq(p.X);
  F B = O::sq(p.Y);
  F C = O::sq(B);
  F D = O::sub(O::sub(O::sq(O::add(p.X, B)), A), C);
  D = O::add(D, D);
  F E = O::small(A, 3);
  F Fv = O::sq(E);
  F X3 = O::sub(Fv, O::add(D, D));
  F Y3 = O::sub(O::mul(E, O::sub(D, X3)), O::small(C, 8));
  F Z3 = O::mul(O::add(p.Y, p.Y), p.Z);
  return {X3, Y3, Z3};
}

// Same formulas as the spec (ops/curve.py:115-143)
template <typename F>
static Jac<F> jac_add(const Jac<F> &p, const Jac<F> &q) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z)) return q;
  if (O::is_zero(q.Z)) return p;
  F Z1Z1 = O::sq(p.Z);
  F Z2Z2 = O::sq(q.Z);
  F U1 = O::mul(p.X, Z2Z2);
  F U2 = O::mul(q.X, Z1Z1);
  F S1 = O::mul(p.Y, O::mul(q.Z, Z2Z2));
  F S2 = O::mul(q.Y, O::mul(p.Z, Z1Z1));
  F H = O::sub(U2, U1);
  F rr = O::sub(S2, S1);
  if (O::is_zero(H)) {
    if (O::is_zero(rr)) return jac_double(p);
    return jac_inf<F>();
  }
  rr = O::add(rr, rr);
  F I = O::sq(O::add(H, H));
  F J = O::mul(H, I);
  F V = O::mul(U1, I);
  F X3 = O::sub(O::sub(O::sq(rr), J), O::add(V, V));
  F S1J = O::mul(S1, J);
  F Y3 = O::sub(O::mul(rr, O::sub(V, X3)), O::add(S1J, S1J));
  F Z3 = O::mul(O::mul(p.Z, q.Z), H);
  Z3 = O::add(Z3, Z3);
  return {X3, Y3, Z3};
}

// Mixed addition q affine (Z=1) — saves ~4 muls in the MSM inner loop
template <typename F>
static Jac<F> jac_add_affine(const Jac<F> &p, const F &qx, const F &qy,
                             bool q_inf) {
  using O = FieldOps<F>;
  if (q_inf) return p;
  if (O::is_zero(p.Z)) return {qx, qy, O::one()};
  F Z1Z1 = O::sq(p.Z);
  F U2 = O::mul(qx, Z1Z1);
  F S2 = O::mul(qy, O::mul(p.Z, Z1Z1));
  F H = O::sub(U2, p.X);
  F rr = O::sub(S2, p.Y);
  if (O::is_zero(H)) {
    if (O::is_zero(rr)) return jac_double(p);
    return jac_inf<F>();
  }
  rr = O::add(rr, rr);
  F I = O::sq(O::add(H, H));
  F J = O::mul(H, I);
  F V = O::mul(p.X, I);
  F X3 = O::sub(O::sub(O::sq(rr), J), O::add(V, V));
  F S1J = O::mul(p.Y, J);
  S1J = O::add(S1J, S1J);
  F Y3 = O::sub(O::mul(rr, O::sub(V, X3)), S1J);
  F Z3 = O::mul(p.Z, H);
  Z3 = O::add(Z3, Z3);
  return {X3, Y3, Z3};
}

template <typename F>
static void jac_to_affine(const Jac<F> &p, F &x, F &y, bool &inf) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z)) {
    inf = true;
    x = O::zero();
    y = O::zero();
    return;
  }
  inf = false;
  F zi = O::inv(p.Z);
  F zi2 = O::sq(zi);
  x = O::mul(p.X, zi2);
  y = O::mul(p.Y, O::mul(zi2, zi));
}

// ---------------------------------------------------------------------------
// Shared-base windowed MSM (matches the TPU kernel's schedule: 4-bit
// windows msb-first over 256-bit scalars, per-base 16-entry tables)
// ---------------------------------------------------------------------------

struct Scalar {
  u64 v[4];
};  // 256-bit LE canonical

static inline unsigned scalar_window(const Scalar &s, int w) {
  // w = window index from msb: bits [252-4w .. 255-4w]
  int lo = 252 - 4 * w;
  return (unsigned)((s.v[lo / 64] >> (lo % 64)) & 0xf);
}

template <typename F>
static void msm_tables(const F *bx, const F *by, const bool *binf, int k,
                       std::vector<Jac<F>> &tables) {
  tables.assign((size_t)k * 16, jac_inf<F>());
  for (int j = 0; j < k; j++) {
    Jac<F> *row = &tables[(size_t)j * 16];
    row[0] = jac_inf<F>();
    if (binf[j]) {
      for (int d = 1; d < 16; d++) row[d] = jac_inf<F>();
      continue;
    }
    Jac<F> base = {bx[j], by[j], FieldOps<F>::one()};
    row[1] = base;
    for (int d = 2; d < 16; d++) row[d] = jac_add(row[d - 1], base);
  }
}

// One batch row: acc = sum_j s[j] * base[j], var-time (public data — the
// verify-side split the reference also makes, signature.rs:465 vs :513)
template <typename F>
static Jac<F> msm_row(const std::vector<Jac<F>> &tables, const Scalar *s,
                      int k) {
  Jac<F> acc = jac_inf<F>();
  for (int w = 0; w < 64; w++) {
    if (w) {
      acc = jac_double(acc);
      acc = jac_double(acc);
      acc = jac_double(acc);
      acc = jac_double(acc);
    }
    for (int j = 0; j < k; j++) {
      unsigned d = scalar_window(s[j], w);
      if (d) acc = jac_add(acc, tables[(size_t)j * 16 + d]);
    }
  }
  return acc;
}

// Pippenger bucket MSM, var-time, for ONE large MSM over distinct points
// (the reference's multi_scalar_mul_var_time surface, signature.rs:513,521:
// Verkey.aggregate at large thresholds and any future big-MSM workload).
// Complexity ~ nwin * (n adds + 2^c bucket-combine adds) vs the windowed
// row schedule's 64*(4 dbl + n adds); wins once n is large enough that the
// bucket combine amortizes (crossover measured in BASELINE.md).

static inline unsigned scalar_bits(const Scalar &s, int lo, int c) {
  unsigned v = 0;
  for (int b = 0; b < c; b++) {
    int idx = lo + b;
    if (idx >= 256) break;
    v |= ((unsigned)((s.v[idx / 64] >> (idx % 64)) & 1)) << b;
  }
  return v;
}

template <typename F>
static Jac<F> msm_pippenger(const F *xs, const F *ys, const bool *inf,
                            const Scalar *s, int n) {
  int c = n < 128 ? 4 : (n < 1024 ? 6 : (n < 8192 ? 8 : 12));
  int nwin = (255 + c) / c;
  Jac<F> result = jac_inf<F>();
  std::vector<Jac<F>> buckets((size_t)1 << c);
  for (int w = nwin - 1; w >= 0; w--) {
    if (w != nwin - 1)
      for (int d = 0; d < c; d++) result = jac_double(result);
    std::fill(buckets.begin(), buckets.end(), jac_inf<F>());
    for (int i = 0; i < n; i++) {
      if (inf[i]) continue;
      unsigned dg = scalar_bits(s[i], w * c, c);
      if (dg) buckets[dg] = jac_add_affine(buckets[dg], xs[i], ys[i], false);
    }
    // running-sum combine: sum_b b * bucket[b] in 2*(2^c - 1) adds
    Jac<F> run = jac_inf<F>(), sum = jac_inf<F>();
    for (int b = (1 << c) - 1; b >= 1; b--) {
      run = jac_add(run, buckets[b]);
      sum = jac_add(sum, run);
    }
    result = jac_add(result, sum);
  }
  return result;
}

// ---------------------------------------------------------------------------
// Complete projective arithmetic (Renes-Costello-Batina 2015, a = 0) — the
// SAME branch-free formulas the TPU kernels use (tpu/curve.py jadd/jdouble):
// valid for EVERY input pair including the identity (0 : 1 : 0), so the
// const-time MSM below has no secret-dependent branch anywhere.
// b3 = 3b: 12 for G1 (b = 4), 12*(u+1) for the twist (b' = 4(u+1)).
// ---------------------------------------------------------------------------

template <typename F>
struct Proj {
  F X, Y, Z;
};

static inline Fp b3_of(const Fp &t) { return fp_mul_small(t, 12); }
static inline Fp2 b3_of(const Fp2 &t) {
  return fp2_mul_small(fp2_mul_xi(t), 12);
}

template <typename F>
static inline Proj<F> proj_inf() {
  return {FieldOps<F>::zero(), FieldOps<F>::one(), FieldOps<F>::zero()};
}

// RCB 2015 Alg. 7 (a = 0): complete projective addition, 12 muls, no
// branches (mirrors tpu/curve.py jadd).
template <typename F>
static Proj<F> proj_add_complete(const Proj<F> &p, const Proj<F> &q) {
  using O = FieldOps<F>;
  F t0 = O::mul(p.X, q.X);
  F t1 = O::mul(p.Y, q.Y);
  F t2 = O::mul(p.Z, q.Z);
  F m3 = O::mul(O::add(p.X, p.Y), O::add(q.X, q.Y));
  F m4 = O::mul(O::add(p.Y, p.Z), O::add(q.Y, q.Z));
  F m5 = O::mul(O::add(p.X, p.Z), O::add(q.X, q.Z));
  F t3 = O::sub(O::sub(m3, t0), t1);  // X1Y2 + X2Y1
  F t4 = O::sub(O::sub(m4, t1), t2);  // Y1Z2 + Y2Z1
  F t5 = O::sub(O::sub(m5, t0), t2);  // X1Z2 + X2Z1
  F b3t2 = b3_of(t2);
  F y3 = b3_of(t5);
  F t0_3 = O::add(O::add(t0, t0), t0);  // 3 X1X2
  F z3s = O::add(t1, b3t2);
  F t1m = O::sub(t1, b3t2);
  F x3a = O::mul(t4, y3);
  F t2c = O::mul(t3, t1m);
  F y3b = O::mul(y3, t0_3);
  F t1d = O::mul(t1m, z3s);
  F t0e = O::mul(t0_3, t3);
  F z3f = O::mul(z3s, t4);
  return {O::sub(t2c, x3a), O::add(t1d, y3b), O::add(z3f, t0e)};
}

// RCB 2015 Alg. 9 (a = 0): complete projective doubling, 9 muls, no
// branches (mirrors tpu/curve.py jdouble).
template <typename F>
static Proj<F> proj_double_complete(const Proj<F> &p) {
  using O = FieldOps<F>;
  F a_ = O::mul(p.Y, p.Y);
  F b_ = O::mul(p.Y, p.Z);
  F c_ = O::mul(p.Z, p.Z);
  F xy = O::mul(p.X, p.Y);
  F cb = b3_of(c_);
  F e8 = O::small(a_, 8);
  F y3s = O::add(a_, cb);
  F t0m = O::sub(a_, O::small(cb, 3));
  F x3p = O::mul(cb, e8);
  F z3 = O::mul(b_, e8);
  F y2m = O::mul(t0m, y3s);
  F x3m = O::mul(t0m, xy);
  return {O::add(x3m, x3m), O::add(x3p, y2m), z3};
}

template <typename F>
static void proj_to_affine(const Proj<F> &p, F &x, F &y, bool &inf) {
  using O = FieldOps<F>;
  if (O::is_zero(p.Z)) {
    inf = true;
    x = O::zero();
    y = O::zero();
    return;
  }
  inf = false;
  F zi = O::inv(p.Z);
  x = O::mul(p.X, zi);
  y = O::mul(p.Y, zi);
}

// Fixed-window masked-lookup MSM for secret scalars (issuance side:
// const-time MSM call sites signature.rs:157,424-428). Every table entry
// is read, every add/double executed through the COMPLETE formulas above —
// no secret-dependent branch or memory access anywhere in the schedule
// (the former Jacobian-add edge-case branches are gone; VERDICT r2 item 7).
// Tables are public (wire-data bases), so their var-time build is fine.
template <typename F>
static Proj<F> msm_row_ct(const std::vector<Proj<F>> &tables, const Scalar *s,
                          int k) {
  Proj<F> acc = proj_inf<F>();
  for (int w = 0; w < 64; w++) {
    if (w) {
      acc = proj_double_complete(acc);
      acc = proj_double_complete(acc);
      acc = proj_double_complete(acc);
      acc = proj_double_complete(acc);
    }
    for (int j = 0; j < k; j++) {
      unsigned d = scalar_window(s[j], w);
      // masked gather of tables[j][d]
      Proj<F> e = proj_inf<F>();
      const u64 *src0 = (const u64 *)&tables[(size_t)j * 16];
      u64 *dst = (u64 *)&e;
      size_t words = sizeof(Proj<F>) / 8;
      for (unsigned t = 0; t < 16; t++) {
        u64 mask = (u64)0 - (u64)(t == d);
        const u64 *src = src0 + (size_t)t * words;
        for (size_t q = 0; q < words; q++)
          dst[q] = (dst[q] & ~mask) | (src[q] & mask);
      }
      acc = proj_add_complete(acc, e);
    }
  }
  return acc;
}

// Projective copies of the (public) per-base multiples for the ct schedule.
template <typename F>
static void msm_tables_proj(const std::vector<Jac<F>> &jtables, int k,
                            std::vector<Proj<F>> &out) {
  out.assign((size_t)k * 16, proj_inf<F>());
  for (size_t i = 0; i < (size_t)k * 16; i++) {
    F x, y;
    bool inf;
    jac_to_affine(jtables[i], x, y, inf);
    if (!inf) out[i] = {x, y, FieldOps<F>::one()};
  }
}

// ---------------------------------------------------------------------------
// Pairing: projective Miller loop + final exponentiation
// (structure mirrors the spec ops/pairing.py miller_loop_projective /
// final_exp_chain and the TPU kernel tpu/pairing.py — same line coeffs,
// same x-power chain)
// ---------------------------------------------------------------------------

static const u64 BLS_X_ABS = 0xd201000000010000ULL;  // |x|, x < 0

struct ProjT {
  Fp2 X, Y, Z;
};

static inline void proj_double_step(ProjT &T, Fp2 &lA, Fp2 &lB, Fp2 &lC) {
  Fp2 A = fp2_sq(T.X);
  Fp2 B = fp2_sq(T.Y);
  Fp2 C = fp2_sq(T.Z);
  Fp2 D = fp2_mul(fp2_mul(T.X, B), T.Z);
  Fp2 Fv = fp2_sub(fp2_mul_small(fp2_sq(A), 9), fp2_mul_small(D, 8));
  Fp2 YZ = fp2_mul(T.Y, T.Z);
  Fp2 X3 = fp2_mul(fp2_mul_small(YZ, 2), Fv);
  Fp2 Y3 = fp2_sub(
      fp2_mul(fp2_mul_small(A, 3), fp2_sub(fp2_mul_small(D, 4), Fv)),
      fp2_mul_small(fp2_mul(fp2_sq(B), C), 8));
  Fp2 t = fp2_mul_small(YZ, 2);
  Fp2 Z3 = fp2_mul(fp2_sq(t), t);
  lA = fp2_sub(fp2_mul(T.X, A), fp2_mul_small(fp2_mul_xi(fp2_mul(T.Z, C)), 8));
  lB = fp2_neg(fp2_mul_small(fp2_mul(A, T.Z), 3));
  lC = fp2_mul_small(fp2_mul(T.Y, C), 2);
  T = {X3, Y3, Z3};
}

static inline void proj_add_step(ProjT &T, const Fp2 &qx, const Fp2 &qy,
                                 Fp2 &lA, Fp2 &lB, Fp2 &lC) {
  Fp2 theta = fp2_sub(T.Y, fp2_mul(qy, T.Z));
  Fp2 lam = fp2_sub(T.X, fp2_mul(qx, T.Z));
  Fp2 lam2 = fp2_sq(lam);
  Fp2 lam3 = fp2_mul(lam2, lam);
  Fp2 H = fp2_sub(fp2_mul(fp2_sq(theta), T.Z),
                  fp2_mul(lam2, fp2_add(T.X, fp2_mul(qx, T.Z))));
  Fp2 X3 = fp2_mul(lam, H);
  Fp2 Y3 = fp2_sub(fp2_mul(theta, fp2_sub(fp2_mul(lam2, T.X), H)),
                   fp2_mul(lam3, T.Y));
  Fp2 Z3 = fp2_mul(lam3, T.Z);
  lA = fp2_sub(fp2_mul(theta, qx), fp2_mul(lam, qy));
  lB = fp2_neg(theta);
  lC = lam;
  T = {X3, Y3, Z3};
}

// True multi-Miller loop: all pairs interleaved inside ONE loop so the
// per-iteration fp12_sq is shared across pairs (squaring each pair's
// factor separately and multiplying would lose that sharing).

static Fp12 multi_miller(const Fp *pxs, const Fp *pys, const Fp2 *qxs,
                         const Fp2 *qys, const bool *skip, int n) {
  std::vector<ProjT> T(n);
  for (int i = 0; i < n; i++)
    if (!skip[i]) T[i] = {qxs[i], qys[i], FP2_ONE};
  int top = 63;
  while (!((BLS_X_ABS >> top) & 1)) top--;
  Fp12 f = FP12_ONE;
  Fp2 lA, lB, lC;
  for (int i = top - 1; i >= 0; i--) {
    f = fp12_sq(f);
    for (int j = 0; j < n; j++) {
      if (skip[j]) continue;
      proj_double_step(T[j], lA, lB, lC);
      f = fp12_mul_line(f, lA, fp2_mul_fp(lB, pxs[j]), fp2_mul_fp(lC, pys[j]));
    }
    if ((BLS_X_ABS >> i) & 1) {
      for (int j = 0; j < n; j++) {
        if (skip[j]) continue;
        proj_add_step(T[j], qxs[j], qys[j], lA, lB, lC);
        f = fp12_mul_line(f, lA, fp2_mul_fp(lB, pxs[j]),
                          fp2_mul_fp(lC, pys[j]));
      }
    }
  }
  return fp12_conj(f);  // x < 0
}

static Fp12 fp12_pow_x_abs(const Fp12 &m) {
  int top = 63;
  while (!((BLS_X_ABS >> top) & 1)) top--;
  Fp12 acc = m;
  for (int i = top - 1; i >= 0; i--) {
    acc = fp12_sq(acc);
    if ((BLS_X_ABS >> i) & 1) acc = fp12_mul(acc, m);
  }
  return acc;
}

static inline Fp12 fp12_pow_x_neg(const Fp12 &m) {
  return fp12_conj(fp12_pow_x_abs(m));
}

// Identical chain to the spec's final_exp_chain (ops/pairing.py:269-289)
static Fp12 final_exp(const Fp12 &f) {
  Fp12 m = fp12_mul(fp12_conj(f), fp12_inv(f));
  m = fp12_mul(fp12_frobenius2(m), m);
  Fp12 t0 = fp12_mul(fp12_pow_x_neg(m), fp12_conj(m));
  Fp12 t1 = fp12_mul(fp12_pow_x_neg(t0), fp12_conj(t0));
  Fp12 t2 = fp12_mul(fp12_pow_x_neg(t1), fp12_frobenius(t1));
  Fp12 t3 = fp12_mul(fp12_mul(fp12_pow_x_neg(fp12_pow_x_neg(t2)),
                              fp12_frobenius2(t2)),
                     fp12_conj(t2));
  return fp12_mul(t3, fp12_mul(fp12_sq(m), m));
}

// ---------------------------------------------------------------------------
// Fr: scalar field, 4x64 Montgomery (R_mont = 2^256) — the native `sss`
// arithmetic (replaces the reference's external secret_sharing crate,
// Cargo.toml:14: Polynomial/Lagrange/Shamir surfaces at keygen.rs:58,248
// and signature.rs:460,502). Protocol-layer math, var-time (ids/shares
// are not long-term secrets on the aggregation path the reference also
// runs var-time, signature.rs:513,521).
// ---------------------------------------------------------------------------

struct Fr {
  u64 v[4];
};

// r (the BLS12-381 scalar-field modulus), LE limbs
static const u64 RL[4] = {0xffffffff00000001ULL, 0x53bda402fffe5bfeULL,
                          0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL};
// -r^{-1} mod 2^64; 2^512 mod r (Montgomery RR)
static const u64 R_N0 = 0xfffffffeffffffffULL;
static const u64 R_RR[4] = {0xc999e990f3f29c6dULL, 0x2b6cedcb87925c23ULL,
                            0x05d314967254398fULL, 0x0748d9d99f59ff11ULL};
static const u64 R_M2[4] = {0xfffffffeffffffffULL, 0x53bda402fffe5bfeULL,
                            0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL};

static const Fr FR_ZERO = {{0, 0, 0, 0}};
static Fr FR_ONE;  // mont(1), set in fr_init

static inline void fr_cond_sub_r(Fr &a, u64 force) {
  u64 t[4];
  u128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 d = (u128)a.v[i] - RL[i] - borrow;
    t[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  u64 mask = (u64)0 - (force | (u64)(1 - (u64)borrow));
  for (int i = 0; i < 4; i++) a.v[i] = (a.v[i] & ~mask) | (t[i] & mask);
}

static inline Fr fr_add(const Fr &a, const Fr &b) {
  Fr r;
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 s = (u128)a.v[i] + b.v[i] + carry;
    r.v[i] = (u64)s;
    carry = s >> 64;
  }
  fr_cond_sub_r(r, (u64)carry);
  return r;
}

static inline Fr fr_sub(const Fr &a, const Fr &b) {
  Fr r;
  u128 borrow = 0;
  for (int i = 0; i < 4; i++) {
    u128 d = (u128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (u64)d;
    borrow = (d >> 64) & 1;
  }
  u64 mask = (u64)0 - (u64)borrow;
  u128 carry = 0;
  for (int i = 0; i < 4; i++) {
    u128 s = (u128)r.v[i] + (RL[i] & mask) + carry;
    r.v[i] = (u64)s;
    carry = s >> 64;
  }
  return r;
}

static inline Fr fr_mul(const Fr &a, const Fr &b) {  // CIOS Montgomery
  u64 t[6] = {0};
  for (int i = 0; i < 4; i++) {
    u128 carry = 0;
    for (int j = 0; j < 4; j++) {
      u128 s = (u128)t[j] + (u128)a.v[i] * b.v[j] + carry;
      t[j] = (u64)s;
      carry = s >> 64;
    }
    u128 s = (u128)t[4] + carry;
    t[4] = (u64)s;
    t[5] = (u64)(s >> 64);
    u64 m = t[0] * R_N0;
    carry = ((u128)t[0] + (u128)m * RL[0]) >> 64;
    for (int j = 1; j < 4; j++) {
      u128 s2 = (u128)t[j] + (u128)m * RL[j] + carry;
      t[j - 1] = (u64)s2;
      carry = s2 >> 64;
    }
    s = (u128)t[4] + carry;
    t[3] = (u64)s;
    t[4] = t[5] + (u64)(s >> 64);
    t[5] = 0;
  }
  Fr r;
  memcpy(r.v, t, 32);
  fr_cond_sub_r(r, (u64)(t[4] != 0));
  return r;
}

static Fr fr_pow(const Fr &a, const u64 *e, int nl) {
  Fr r = a;
  bool started = false;
  for (int i = nl - 1; i >= 0; i--)
    for (int bit = 63; bit >= 0; bit--) {
      if (started) {
        r = fr_mul(r, r);
        if ((e[i] >> bit) & 1) r = fr_mul(r, a);
      } else if ((e[i] >> bit) & 1) {
        started = true;
      }
    }
  return r;
}

static inline Fr fr_inv(const Fr &a) { return fr_pow(a, R_M2, 4); }

static void fr_init() {
  // call_once for the same reason as svdw_init: ctypes releases the GIL,
  // and fr_init is not forced at load time by cc_selftest.
  static std::once_flag once;
  std::call_once(once, [] {
    Fr raw1 = {{1, 0, 0, 0}};
    Fr rr;
    memcpy(rr.v, R_RR, 32);
    FR_ONE = fr_mul(raw1, rr);
  });
}

static Fr fr_from_le(const uint8_t *b) {  // canonical LE -> Montgomery
  fr_init();
  Fr a;
  for (int i = 0; i < 4; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w |= (u64)b[i * 8 + j] << (8 * j);
    a.v[i] = w;
  }
  Fr rr;
  memcpy(rr.v, R_RR, 32);
  return fr_mul(a, rr);
}

static void fr_to_le(const Fr &a, uint8_t *b) {
  Fr one = {{1, 0, 0, 0}};
  Fr c = fr_mul(a, one);
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++) b[i * 8 + j] = (uint8_t)(c.v[i] >> (8 * j));
}

static Fr fr_from_u64(u64 x) {
  fr_init();
  Fr a = {{x, 0, 0, 0}};
  Fr rr;
  memcpy(rr.v, R_RR, 32);
  return fr_mul(a, rr);
}

// ---------------------------------------------------------------------------
// Hashing to fields and groups — native implementation of the framework's
// CTH-v2 spec (coconut_tpu/ops/hashing.py): expand_message_xmd (SHA-256,
// RFC 9380 §5.3.1 construction), hash_to_fr/fp, and the Shallue-van de
// Woestijne map with import-time-derived constants. Replaces the last
// amcl_wrapper `from_msg_hash` surface the C++ core was missing (reference
// call sites signature.rs:23-29,205,598). Outputs are bit-identical to the
// Python spec (tests/vectors/hashing.json, checked through this ABI).
// ---------------------------------------------------------------------------

// SHA-256 (FIPS 180-4), single-shot.
namespace sha256 {
static const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void digest(const uint8_t *data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  // padded message: len + 1 + pad + 8 length bytes, multiple of 64
  size_t total = ((len + 8) / 64 + 1) * 64;
  std::vector<uint8_t> buf(total, 0);
  memcpy(buf.data(), data, len);
  buf[len] = 0x80;
  uint64_t bits = (uint64_t)len * 8;
  for (int i = 0; i < 8; i++) buf[total - 1 - i] = (uint8_t)(bits >> (8 * i));
  for (size_t off = 0; off < total; off += 64) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)buf[off + 4 * i] << 24) |
             ((uint32_t)buf[off + 4 * i + 1] << 16) |
             ((uint32_t)buf[off + 4 * i + 2] << 8) | buf[off + 4 * i + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (uint8_t)(h[i] >> 24);
    out[4 * i + 1] = (uint8_t)(h[i] >> 16);
    out[4 * i + 2] = (uint8_t)(h[i] >> 8);
    out[4 * i + 3] = (uint8_t)h[i];
  }
}
}  // namespace sha256

// RFC 9380 §5.3.1 expand_message_xmd with SHA-256 (spec expand_message_xmd).
static bool expand_xmd(const uint8_t *msg, size_t mlen, const uint8_t *dst,
                       size_t dlen, size_t out_len, uint8_t *out) {
  const size_t B_IN = 32, R_IN = 64;
  if (dlen > 255) return false;
  size_t ell = (out_len + B_IN - 1) / B_IN;
  if (ell > 255) return false;
  std::vector<uint8_t> buf;
  buf.assign(R_IN, 0);  // z_pad
  buf.insert(buf.end(), msg, msg + mlen);
  buf.push_back((uint8_t)(out_len >> 8));
  buf.push_back((uint8_t)out_len);
  buf.push_back(0);
  buf.insert(buf.end(), dst, dst + dlen);
  buf.push_back((uint8_t)dlen);
  uint8_t b0[32];
  sha256::digest(buf.data(), buf.size(), b0);
  std::vector<uint8_t> blk(32 + 1 + dlen + 1);
  memcpy(blk.data(), b0, 32);
  blk[32] = 1;
  memcpy(blk.data() + 33, dst, dlen);
  blk[33 + dlen] = (uint8_t)dlen;
  uint8_t bi[32];
  sha256::digest(blk.data(), blk.size(), bi);
  size_t got = 0;
  for (size_t i = 1;; i++) {
    size_t take = out_len - got < 32 ? out_len - got : 32;
    memcpy(out + got, bi, take);
    got += take;
    if (got == out_len) return true;
    for (int j = 0; j < 32; j++) blk[j] = b0[j] ^ bi[j];
    blk[32] = (uint8_t)(i + 1);
    sha256::digest(blk.data(), blk.size(), bi);
  }
}

// Big-endian byte string mod an nl-limb modulus (var-time; hashing is
// public data). Horner over bytes with 8 shifted conditional subtractions.
static void bytes_mod(const uint8_t *be, size_t len, const u64 *mod, int nl,
                      u64 *out) {
  std::vector<u64> acc(nl + 1, 0);
  for (size_t i = 0; i < len; i++) {
    // acc = acc * 256 + be[i]
    u64 carry = be[i];
    for (int j = 0; j < nl + 1; j++) {
      u64 nv = (acc[j] << 8) | carry;
      carry = acc[j] >> 56;
      acc[j] = nv;
    }
    // reduce: acc < 256 * mod, subtract mod << s for s = 8..0
    for (int s = 8; s >= 0; s--) {
      // cmp acc ? mod << s (bit shift within the nl+1-limb window)
      std::vector<u64> ms(nl + 1, 0);
      for (int j = 0; j < nl; j++) {
        ms[j] += (s < 64) ? (mod[j] << s) : 0;
        if (s) ms[j + 1] |= mod[j] >> (64 - s);
      }
      // compare
      int cmp = 0;
      for (int j = nl; j >= 0; j--) {
        if (acc[j] != ms[j]) {
          cmp = acc[j] > ms[j] ? 1 : -1;
          break;
        }
      }
      if (cmp >= 0) {
        u128 borrow = 0;
        for (int j = 0; j < nl + 1; j++) {
          u128 d = (u128)acc[j] - ms[j] - borrow;
          acc[j] = (u64)d;
          borrow = (d >> 64) & 1;
        }
      }
    }
  }
  for (int j = 0; j < nl; j++) out[j] = acc[j];
}

static const u64 G1_COF[2] = {0x8c00aaab0000aaabULL, 0x396c8c005555e156ULL};
static const u64 G2_COF[8] = {
    0xcf1c38e31c7238e5ULL, 0x1616ec6e786f0c70ULL, 0x21537e293a6691aeULL,
    0xa628f1cb4d9e82efULL, 0xa68a205b2e5a7ddfULL, 0xcd91de4547085abaULL,
    0x091d50792876a202ULL, 0x05d543a95414e7f1ULL};
// sqrt/legendre exponents ((p+1)/4, (p-3)/4, (p-1)/2)
static const u64 EXP_P14[6] = {0xee7fbfffffffeaabULL, 0x07aaffffac54ffffULL,
                               0xd9cc34a83dac3d89ULL, 0xd91dd2e13ce144afULL,
                               0x92c6e9ed90d2eb35ULL, 0x0680447a8e5ff9a6ULL};
static const u64 EXP_P34[6] = {0xee7fbfffffffeaaaULL, 0x07aaffffac54ffffULL,
                               0xd9cc34a83dac3d89ULL, 0xd91dd2e13ce144afULL,
                               0x92c6e9ed90d2eb35ULL, 0x0680447a8e5ff9a6ULL};
static const u64 EXP_P12[6] = {0xdcff7fffffffd555ULL, 0x0f55ffff58a9ffffULL,
                               0xb39869507b587b12ULL, 0xb23ba5c279c2895fULL,
                               0x258dd3db21a5d66bULL, 0x0d0088f51cbff34dULL};

// canonical (out-of-Montgomery) raw limbs — for sgn0 and codecs
static inline Fp fp_canonical(const Fp &a) {
  Fp one = {{1, 0, 0, 0, 0, 0}};
  return fp_mul(a, one);
}

static inline int fp_sgn0(const Fp &a) {
  return (int)(fp_canonical(a).v[0] & 1);
}

static inline int fp2_sgn0(const Fp2 &a) {
  Fp c0 = fp_canonical(a.c0);
  int s0 = (int)(c0.v[0] & 1);
  bool z0 = fp_is_zero_raw(c0);
  int s1 = fp_sgn0(a.c1);
  return s0 | ((int)z0 & s1);
}

// sqrt in Fp (p = 3 mod 4): a^((p+1)/4), verified (spec fp_sqrt)
static bool fp_sqrt_(const Fp &a, Fp &out) {
  Fp s = fp_pow(a, EXP_P14, 6);
  if (!fp_eq_raw(fp_sq(s), a)) return false;
  out = s;
  return true;
}

// sqrt in Fp2, complex method (spec fp2_sqrt; same branch structure)
static bool fp2_sqrt_(const Fp2 &a, Fp2 &out) {
  if (fp2_is_zero(a)) {
    out = FP2_ZERO;
    return true;
  }
  Fp2 a1 = fp2_pow(a, EXP_P34, 6);
  Fp2 x0 = fp2_mul(a1, a);
  Fp2 alpha = fp2_mul(a1, x0);
  Fp2 neg_one = {fp_neg(FP_ONE), FP_ZERO};
  Fp2 x;
  if (fp2_eq(alpha, neg_one)) {
    Fp2 u = {FP_ZERO, FP_ONE};
    x = fp2_mul(u, x0);
  } else {
    Fp2 b = fp2_pow(fp2_add(FP2_ONE, alpha), EXP_P12, 6);
    x = fp2_mul(b, x0);
  }
  if (!fp2_eq(fp2_sq(x), a)) return false;
  out = x;
  return true;
}

// Field adapter for the generic SvdW map (mirrors spec _FpAdapter/_Fp2Adapter)
struct SvdWFp {
  using T = Fp;
  static Fp embed(long k) {
    Fp r = fp_mul_small(FP_ONE, (u64)(k < 0 ? -k : k));
    return k < 0 ? fp_neg(r) : r;
  }
  static Fp b() { return embed(4); }
  static Fp add(const Fp &a, const Fp &b_) { return fp_add(a, b_); }
  static Fp sub(const Fp &a, const Fp &b_) { return fp_sub(a, b_); }
  static Fp mul(const Fp &a, const Fp &b_) { return fp_mul(a, b_); }
  static Fp sq(const Fp &a) { return fp_sq(a); }
  static Fp neg(const Fp &a) { return fp_neg(a); }
  static Fp inv0(const Fp &a) {
    return fp_is_zero_raw(a) ? FP_ZERO : fp_inv(a);
  }
  static bool sqrt(const Fp &a, Fp &o) { return fp_sqrt_(a, o); }
  static int sgn0(const Fp &a) { return fp_sgn0(a); }
  static bool is_zero(const Fp &a) { return fp_is_zero_raw(a); }
};

struct SvdWFp2 {
  using T = Fp2;
  static Fp2 embed(long k) { return {SvdWFp::embed(k), FP_ZERO}; }
  static Fp2 b() { return {SvdWFp::embed(4), SvdWFp::embed(4)}; }
  static Fp2 add(const Fp2 &a, const Fp2 &b_) { return fp2_add(a, b_); }
  static Fp2 sub(const Fp2 &a, const Fp2 &b_) { return fp2_sub(a, b_); }
  static Fp2 mul(const Fp2 &a, const Fp2 &b_) { return fp2_mul(a, b_); }
  static Fp2 sq(const Fp2 &a) { return fp2_sq(a); }
  static Fp2 neg(const Fp2 &a) { return fp2_neg(a); }
  static Fp2 inv0(const Fp2 &a) {
    return fp2_is_zero(a) ? FP2_ZERO : fp2_inv(a);
  }
  static bool sqrt(const Fp2 &a, Fp2 &o) { return fp2_sqrt_(a, o); }
  static int sgn0(const Fp2 &a) { return fp2_sgn0(a); }
  static bool is_zero(const Fp2 &a) { return fp2_is_zero(a); }
};

template <typename A>
struct SvdWConsts {
  typename A::T Z, c1, c2, c3, c4;
};

// Derive the SvdW constants exactly as the spec does (hashing.py
// _svdw_constants): first Z in (1, -1, 2, -2, ...) passing the RFC 9380
// §6.6.1 criteria; c3 sign-normalized to sgn0 == 0.
template <typename A>
static SvdWConsts<A> svdw_derive() {
  using T = typename A::T;
  auto g = [](const T &x) { return A::add(A::mul(A::sq(x), x), A::b()); };
  auto is_sq = [](const T &a) {
    T tmp;
    return A::sqrt(a, tmp);
  };
  T half = A::inv0(A::embed(2));
  for (long k = 1; k <= 64; k++) {
    for (int sign = 0; sign < 2; sign++) {
      T Z = A::embed(sign ? -k : k);
      T gZ = g(Z);
      if (A::is_zero(gZ)) continue;
      T h = A::mul(A::embed(3), A::sq(Z));
      if (A::is_zero(h)) continue;
      T t = A::neg(A::mul(h, A::inv0(A::mul(A::embed(4), gZ))));
      if (A::is_zero(t) || !is_sq(t)) continue;
      if (!(is_sq(gZ) || is_sq(g(A::mul(A::neg(Z), half))))) continue;
      SvdWConsts<A> c;
      c.Z = Z;
      c.c1 = gZ;
      c.c2 = A::mul(A::neg(Z), half);
      A::sqrt(A::neg(A::mul(gZ, h)), c.c3);
      if (A::sgn0(c.c3) == 1) c.c3 = A::neg(c.c3);
      c.c4 = A::mul(A::neg(A::mul(A::embed(4), gZ)), A::inv0(h));
      return c;
    }
  }
  // unreachable for BLS12-381 (the spec asserts the same)
  SvdWConsts<A> c{};
  return c;
}

static SvdWConsts<SvdWFp> SVDW_FP;
static SvdWConsts<SvdWFp2> SVDW_FP2;
static std::once_flag svdw_once;

static void svdw_init() {
  // call_once: ctypes releases the GIL during hash calls and the derive
  // runs long enough for real thread overlap — a plain ready-flag would be
  // a data race (flag store visible before the constant stores).
  std::call_once(svdw_once, [] {
    SVDW_FP = svdw_derive<SvdWFp>();
    SVDW_FP2 = svdw_derive<SvdWFp2>();
  });
}

// RFC 9380 §6.6.1 straight-line SvdW map (spec _map_to_curve_svdw)
template <typename A>
static void map_svdw(const SvdWConsts<A> &C, const typename A::T &u,
                     typename A::T &ox, typename A::T &oy) {
  using T = typename A::T;
  T one = A::embed(1);
  T tv1 = A::mul(A::sq(u), C.c1);
  T tv2 = A::add(one, tv1);
  tv1 = A::sub(one, tv1);
  T tv3 = A::inv0(A::mul(tv1, tv2));
  T tv4 = A::mul(A::mul(A::mul(u, tv1), tv3), C.c3);
  T x1 = A::sub(C.c2, tv4);
  T x2 = A::add(C.c2, tv4);
  T x3 = A::add(A::mul(A::sq(A::mul(A::sq(tv2), tv3)), C.c4), C.Z);
  auto g = [](const T &x) { return A::add(A::mul(A::sq(x), x), A::b()); };
  T x, y;
  if (A::sqrt(g(x1), y)) {
    x = x1;
  } else if (A::sqrt(g(x2), y)) {
    x = x2;
  } else {
    x = x3;
    A::sqrt(g(x3), y);
  }
  if (A::sgn0(y) != A::sgn0(u)) y = A::neg(y);
  ox = x;
  oy = y;
}

// var-time scalar mult by a multi-limb scalar (cofactor clearing)
template <typename F>
static Jac<F> jac_mul_limbs(const Jac<F> &p, const u64 *e, int nl) {
  Jac<F> acc = jac_inf<F>();
  bool started = false;
  for (int i = nl - 1; i >= 0; i--)
    for (int bit = 63; bit >= 0; bit--) {
      if (started) acc = jac_double(acc);
      if ((e[i] >> bit) & 1) {
        if (!started) {
          acc = p;
          started = true;
        } else {
          acc = jac_add(acc, p);
        }
      }
    }
  return acc;
}

// ---------------------------------------------------------------------------
// Codec helpers for the C ABI
// ---------------------------------------------------------------------------

static bool g1_load(const uint8_t *b, Fp &x, Fp &y) {  // returns inf flag
  bool allz = true;
  for (int i = 0; i < 96; i++)
    if (b[i]) {
      allz = false;
      break;
    }
  if (allz) {
    x = FP_ZERO;
    y = FP_ZERO;
    return true;
  }
  x = fp_from_le(b);
  y = fp_from_le(b + 48);
  return false;
}

static void g1_store(uint8_t *b, const Fp &x, const Fp &y, bool inf) {
  if (inf) {
    memset(b, 0, 96);
    return;
  }
  fp_to_le(x, b);
  fp_to_le(y, b + 48);
}

static bool g2_load(const uint8_t *b, Fp2 &x, Fp2 &y) {
  bool allz = true;
  for (int i = 0; i < 192; i++)
    if (b[i]) {
      allz = false;
      break;
    }
  if (allz) {
    x = FP2_ZERO;
    y = FP2_ZERO;
    return true;
  }
  x.c0 = fp_from_le(b);
  x.c1 = fp_from_le(b + 48);
  y.c0 = fp_from_le(b + 96);
  y.c1 = fp_from_le(b + 144);
  return false;
}

static void g2_store(uint8_t *b, const Fp2 &x, const Fp2 &y, bool inf) {
  if (inf) {
    memset(b, 0, 192);
    return;
  }
  fp_to_le(x.c0, b);
  fp_to_le(x.c1, b + 48);
  fp_to_le(y.c0, b + 96);
  fp_to_le(y.c1, b + 144);
}

static Scalar scalar_load(const uint8_t *b) {
  Scalar s;
  for (int i = 0; i < 4; i++) {
    u64 w = 0;
    for (int j = 0; j < 8; j++) w |= (u64)b[i * 8 + j] << (8 * j);
    s.v[i] = w;
  }
  return s;
}

// ---------------------------------------------------------------------------
// Init
// ---------------------------------------------------------------------------

static void ccbls_init() {
  static bool done = false;
  if (done) return;
  done = true;
  // FP_ONE = R mod p = mont(1): compute from RR via mont-mul with 1
  Fp raw1 = {{1, 0, 0, 0, 0, 0}};
  Fp rr;
  memcpy(rr.v, RR, 48);
  FP_ONE = fp_mul(raw1, rr);
  FP2_ONE = {FP_ONE, FP_ZERO};
  FP6_ONE = {FP2_ONE, FP2_ZERO, FP2_ZERO};
  FP12_ONE = {FP6_ONE, FP6_ZERO};

  // (p-1)/6 as limbs for the gamma pows
  u64 e[6];
  memcpy(e, PL, 48);
  e[0] -= 1;  // p-1 (p odd)
  // divide by 6
  u128 rem = 0;
  u64 q6[6];
  for (int i = 5; i >= 0; i--) {
    u128 cur = (rem << 64) | e[i];
    q6[i] = (u64)(cur / 6);
    rem = cur % 6;
  }
  Fp2 xi = {FP_ONE, FP_ONE};
  G1C[0] = FP2_ONE;
  G1C[1] = fp2_pow(xi, q6, 6);
  for (int i = 2; i < 6; i++) G1C[i] = fp2_mul(G1C[i - 1], G1C[1]);
  for (int i = 0; i < 6; i++) G2C[i] = fp2_mul(G1C[i], fp2_conj(G1C[i]));
}

// Var-time 4-bit-window single G1 scalar mult (public data). Shared by
// cc_g1_mul and the Pedersen commitment-side checks.
static Jac<Fp> g1_smul(const Fp &x, const Fp &y, const Scalar &s) {
  Jac<Fp> acc = jac_inf<Fp>();
  for (int w = 0; w < 64; w++) {
    if (w)
      for (int d = 0; d < 4; d++) acc = jac_double(acc);
    unsigned dg = scalar_window(s, w);
    if (dg) {
      Jac<Fp> t = jac_inf<Fp>();
      for (unsigned b = 0; b < dg; b++) t = jac_add_affine(t, x, y, false);
      acc = jac_add(acc, t);
    }
  }
  return acc;
}

// rows x (g^{s_i} h^{t_i}) through the masked-lookup CONST-TIME schedule —
// the exponents are secrets (Pedersen VSS coefficients and shares; the
// reference's const-time discipline at its MSM call sites,
// signature.rs:157,424-428, applies to the keygen side too).
static void pedersen_ct_rows(const uint8_t *g96, const uint8_t *h96,
                             const uint8_t *srows, const uint8_t *trows,
                             int rows, uint8_t *out96) {
  Fp bx[2], by[2];
  bool binf[2];
  binf[0] = g1_load(g96, bx[0], by[0]);
  binf[1] = g1_load(h96, bx[1], by[1]);
  std::vector<Jac<Fp>> tables;
  msm_tables<Fp>(bx, by, binf, 2, tables);
  std::vector<Proj<Fp>> ptables;
  msm_tables_proj(tables, 2, ptables);
  for (int i = 0; i < rows; i++) {
    Scalar s2[2] = {scalar_load(srows + (size_t)i * 32),
                    scalar_load(trows + (size_t)i * 32)};
    Proj<Fp> acc = msm_row_ct<Fp>(ptables, s2, 2);
    Fp x, y;
    bool inf;
    proj_to_affine(acc, x, y, inf);
    g1_store(out96 + (size_t)i * 96, x, y, inf);
  }
}

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Shared-base batched MSM in G1. bases: k*96B affine; scalars: B*k*32B;
// out: B*96B affine. ct != 0 selects the masked-lookup schedule.
void cc_msm_g1(const uint8_t *bases, const uint8_t *scalars, int k, int B,
               uint8_t *out, int ct) {
  ccbls_init();
  std::vector<Fp> bx(k), by(k);
  std::vector<bool> binfv(k);
  std::vector<char> binf(k);
  for (int j = 0; j < k; j++) {
    binf[j] = g1_load(bases + (size_t)j * 96, bx[j], by[j]);
  }
  std::vector<Jac<Fp>> tables;
  msm_tables<Fp>(bx.data(), by.data(), (const bool *)binf.data(), k, tables);
  std::vector<Proj<Fp>> ptables;
  if (ct) msm_tables_proj(tables, k, ptables);
  std::vector<Scalar> srow(k);
  for (int i = 0; i < B; i++) {
    for (int j = 0; j < k; j++)
      srow[j] = scalar_load(scalars + ((size_t)i * k + j) * 32);
    Fp x, y;
    bool inf;
    if (ct) {
      Proj<Fp> acc = msm_row_ct<Fp>(ptables, srow.data(), k);
      proj_to_affine(acc, x, y, inf);
    } else {
      Jac<Fp> acc = msm_row<Fp>(tables, srow.data(), k);
      jac_to_affine(acc, x, y, inf);
    }
    g1_store(out + (size_t)i * 96, x, y, inf);
  }
}

void cc_msm_g2(const uint8_t *bases, const uint8_t *scalars, int k, int B,
               uint8_t *out, int ct) {
  ccbls_init();
  std::vector<Fp2> bx(k), by(k);
  std::vector<char> binf(k);
  for (int j = 0; j < k; j++) {
    binf[j] = g2_load(bases + (size_t)j * 192, bx[j], by[j]);
  }
  std::vector<Jac<Fp2>> tables;
  msm_tables<Fp2>(bx.data(), by.data(), (const bool *)binf.data(), k, tables);
  std::vector<Proj<Fp2>> ptables;
  if (ct) msm_tables_proj(tables, k, ptables);
  std::vector<Scalar> srow(k);
  for (int i = 0; i < B; i++) {
    for (int j = 0; j < k; j++)
      srow[j] = scalar_load(scalars + ((size_t)i * k + j) * 32);
    Fp2 x, y;
    bool inf;
    if (ct) {
      Proj<Fp2> acc = msm_row_ct<Fp2>(ptables, srow.data(), k);
      proj_to_affine(acc, x, y, inf);
    } else {
      Jac<Fp2> acc = msm_row<Fp2>(tables, srow.data(), k);
      jac_to_affine(acc, x, y, inf);
    }
    g2_store(out + (size_t)i * 192, x, y, inf);
  }
}

// Batched pairing-product check: for each row i of n pairs,
// out[i] = (prod_j e(P_ij, Q_ij) == 1). Pairs with either side infinite
// contribute the factor 1 (the spec's None convention).
void cc_pairing_product_is_one(const uint8_t *ps, const uint8_t *qs, int n,
                               int B, uint8_t *out) {
  ccbls_init();
  std::vector<Fp> pxs(n), pys(n);
  std::vector<Fp2> qxs(n), qys(n);
  std::vector<char> skip(n);
  for (int i = 0; i < B; i++) {
    for (int j = 0; j < n; j++) {
      bool pinf = g1_load(ps + ((size_t)i * n + j) * 96, pxs[j], pys[j]);
      bool qinf = g2_load(qs + ((size_t)i * n + j) * 192, qxs[j], qys[j]);
      skip[j] = pinf || qinf;
    }
    Fp12 f = multi_miller(pxs.data(), pys.data(), qxs.data(), qys.data(),
                          (const bool *)skip.data(), n);
    out[i] = fp12_eq_one(final_exp(f)) ? 1 : 0;
  }
}

// Single scalar mults (protocol-layer helpers): B points x B scalars.
void cc_g1_mul(const uint8_t *pts, const uint8_t *scalars, int B,
               uint8_t *out) {
  ccbls_init();
  for (int i = 0; i < B; i++) {
    Fp x, y;
    bool inf = g1_load(pts + (size_t)i * 96, x, y);
    Scalar s = scalar_load(scalars + (size_t)i * 32);
    if (inf) {
      g1_store(out + (size_t)i * 96, FP_ZERO, FP_ZERO, true);
      continue;
    }
    Jac<Fp> acc = g1_smul(x, y, s);
    Fp ox, oy;
    bool oinf;
    jac_to_affine(acc, ox, oy, oinf);
    g1_store(out + (size_t)i * 96, ox, oy, oinf);
  }
}

// --- native sss: Lagrange / Shamir over Fr (secret_sharing crate surface,
// keygen.rs:58,248; signature.rs:460,502) --------------------------------

// l_{my_id}(0) over the (1-based, gap-tolerant) interpolation set `ids`:
// prod_{j != i} x_j / (x_j - x_i) mod r. out32 = canonical LE. Returns 0
// on success, nonzero if my_id is missing from ids or any id is 0.
int cc_fr_lagrange_basis_at_0(const uint32_t *ids, int n, uint32_t my_id,
                              uint8_t *out32) {
  fr_init();
  bool found = false;
  for (int j = 0; j < n; j++) {
    if (ids[j] == 0) return 2;
    if (ids[j] == my_id) found = true;
  }
  if (!found) return 1;
  Fr num = FR_ONE, den = FR_ONE;
  Fr mid = fr_from_u64(my_id);
  for (int j = 0; j < n; j++) {
    if (ids[j] == my_id) continue;
    Fr xj = fr_from_u64(ids[j]);
    num = fr_mul(num, xj);
    den = fr_mul(den, fr_sub(xj, mid));
  }
  fr_to_le(fr_mul(num, fr_inv(den)), out32);
  return 0;
}

// Horner evaluation of a k-coefficient polynomial (a0 first, 32B LE each)
// at integer x — the Shamir share map (keygen.rs:58).
void cc_fr_poly_eval(const uint8_t *coeffs, int k, uint32_t x,
                     uint8_t *out32) {
  fr_init();
  Fr acc = FR_ZERO;
  Fr xf = fr_from_u64(x);
  for (int i = k - 1; i >= 0; i--) {
    acc = fr_add(fr_mul(acc, xf), fr_from_le(coeffs + (size_t)i * 32));
  }
  fr_to_le(acc, out32);
}

// Lagrange-interpolate the secret at 0 from t (id, share) pairs
// (keygen.rs:248): out = sum_i l_i(0) * s_i. Returns 0 on success.
int cc_fr_reconstruct(const uint32_t *ids, const uint8_t *shares, int t,
                      uint8_t *out32) {
  fr_init();
  Fr acc = FR_ZERO;
  for (int i = 0; i < t; i++) {
    uint8_t lb[32];
    int rc = cc_fr_lagrange_basis_at_0(ids, t, ids[i], lb);
    if (rc) return rc;
    acc = fr_add(acc,
                 fr_mul(fr_from_le(lb), fr_from_le(shares + (size_t)i * 32)));
  }
  fr_to_le(acc, out32);
  return 0;
}

// ONE Pippenger bucket MSM over n distinct G1 points (var-time, public
// data — reference multi_scalar_mul_var_time, signature.rs:513,521).
// pts: n*96B affine; scalars: n*32B; out: 96B affine.
void cc_msm_pippenger_g1(const uint8_t *pts, const uint8_t *scalars, int n,
                         uint8_t *out) {
  ccbls_init();
  std::vector<Fp> xs(n), ys(n);
  std::vector<char> inf(n);
  std::vector<Scalar> s(n);
  for (int i = 0; i < n; i++) {
    inf[i] = g1_load(pts + (size_t)i * 96, xs[i], ys[i]);
    s[i] = scalar_load(scalars + (size_t)i * 32);
  }
  Jac<Fp> acc = msm_pippenger<Fp>(xs.data(), ys.data(),
                                  (const bool *)inf.data(), s.data(), n);
  Fp x, y;
  bool oinf;
  jac_to_affine(acc, x, y, oinf);
  g1_store(out, x, y, oinf);
}

void cc_msm_pippenger_g2(const uint8_t *pts, const uint8_t *scalars, int n,
                         uint8_t *out) {
  ccbls_init();
  std::vector<Fp2> xs(n), ys(n);
  std::vector<char> inf(n);
  std::vector<Scalar> s(n);
  for (int i = 0; i < n; i++) {
    inf[i] = g2_load(pts + (size_t)i * 192, xs[i], ys[i]);
    s[i] = scalar_load(scalars + (size_t)i * 32);
  }
  Jac<Fp2> acc = msm_pippenger<Fp2>(xs.data(), ys.data(),
                                    (const bool *)inf.data(), s.data(), n);
  Fp2 x, y;
  bool oinf;
  jac_to_affine(acc, x, y, oinf);
  g2_store(out, x, y, oinf);
}

// hash_to_fr (spec hash_to_fr): 64 xmd bytes reduced mod r -> 32B LE out.
// Returns 0 on success, nonzero on bad DST length.
int cc_hash_to_fr(const uint8_t *msg, int mlen, const uint8_t *dst, int dlen,
                  uint8_t *out32) {
  ccbls_init();
  uint8_t u[64];
  if (!expand_xmd(msg, (size_t)mlen, dst, (size_t)dlen, 64, u)) return 1;
  u64 limbs[4];
  bytes_mod(u, 64, RL, 4, limbs);
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++)
      out32[i * 8 + j] = (uint8_t)(limbs[i] >> (8 * j));
  return 0;
}

// hash_to_g1 (spec hash_to_g1): clear_cofactor(svdw(u0) + svdw(u1)),
// out = 96B affine (all-zero = identity, probability ~2^-255). Returns 0
// on success.
int cc_hash_to_g1(const uint8_t *msg, int mlen, const uint8_t *dst, int dlen,
                  uint8_t *out96) {
  ccbls_init();
  svdw_init();
  uint8_t u[128];
  if (!expand_xmd(msg, (size_t)mlen, dst, (size_t)dlen, 128, u)) return 1;
  Fp pts[2][2];
  for (int h = 0; h < 2; h++) {
    u64 limbs[6];
    bytes_mod(u + 64 * h, 64, PL, 6, limbs);
    uint8_t le[48];
    for (int i = 0; i < 6; i++)
      for (int j = 0; j < 8; j++)
        le[i * 8 + j] = (uint8_t)(limbs[i] >> (8 * j));
    Fp uf = fp_from_le(le);
    map_svdw<SvdWFp>(SVDW_FP, uf, pts[h][0], pts[h][1]);
  }
  Jac<Fp> q = jac_add<Fp>({pts[0][0], pts[0][1], FP_ONE},
                          {pts[1][0], pts[1][1], FP_ONE});
  Jac<Fp> r = jac_mul_limbs(q, G1_COF, 2);
  Fp x, y;
  bool inf;
  jac_to_affine(r, x, y, inf);
  g1_store(out96, x, y, inf);
  return 0;
}

// Batched hash_to_g1: n messages concatenated in `msgs` (lens[i] bytes
// each, walked in order) hashed under one shared DST into out = n * 96B
// affine points. One FFI round trip instead of n — the prepare phase
// hashes 1,024 commitments per batch and the per-call ctypes overhead
// was a visible slice of its host wall (PROFILE_r05). Returns 0 on
// success, i + 1 if message i failed (out contents before i are valid).
int cc_hash_to_g1_batch(const uint8_t *msgs, const int *lens, int n,
                        const uint8_t *dst, int dlen, uint8_t *out) {
  const uint8_t *p = msgs;
  for (int i = 0; i < n; i++) {
    int rc = cc_hash_to_g1(p, lens[i], dst, dlen, out + (size_t)i * 96);
    if (rc) return i + 1;
    p += lens[i];
  }
  return 0;
}

// hash_to_g2 (spec hash_to_g2): out = 192B affine twist point.
int cc_hash_to_g2(const uint8_t *msg, int mlen, const uint8_t *dst, int dlen,
                  uint8_t *out192) {
  ccbls_init();
  svdw_init();
  uint8_t u[256];
  if (!expand_xmd(msg, (size_t)mlen, dst, (size_t)dlen, 256, u)) return 1;
  Fp2 pts[2][2];
  for (int h = 0; h < 2; h++) {
    Fp comp[2];
    for (int c = 0; c < 2; c++) {
      u64 limbs[6];
      bytes_mod(u + 128 * h + 64 * c, 64, PL, 6, limbs);
      uint8_t le[48];
      for (int i = 0; i < 6; i++)
        for (int j = 0; j < 8; j++)
          le[i * 8 + j] = (uint8_t)(limbs[i] >> (8 * j));
      comp[c] = fp_from_le(le);
    }
    Fp2 uf = {comp[0], comp[1]};
    map_svdw<SvdWFp2>(SVDW_FP2, uf, pts[h][0], pts[h][1]);
  }
  Jac<Fp2> q = jac_add<Fp2>({pts[0][0], pts[0][1], FP2_ONE},
                            {pts[1][0], pts[1][1], FP2_ONE});
  Jac<Fp2> r = jac_mul_limbs(q, G2_COF, 8);
  Fp2 x, y;
  bool inf;
  jac_to_affine(r, x, y, inf);
  g2_store(out192, x, y, inf);
  return 0;
}

// --- native Pedersen VSS / DVSS (completes the secret_sharing rebuild
// target, SURVEY.md §2.2; reference surface keygen.rs:74-205) ---------------

// Uniform random Fr (canonical LE) from OS entropy: 64 bytes of getrandom
// reduced mod r (bias 2^-256) — the native face of the reference's
// FieldElement::random (rand crate, Cargo.toml:10). Returns 0 on success.
int cc_fr_random(uint8_t *out32) {
  uint8_t buf[64];
  size_t got = 0;
  while (got < sizeof buf) {
    ssize_t r = getrandom(buf + got, sizeof buf - got, 0);
    if (r <= 0) return 1;
    got += (size_t)r;
  }
  fr_init();
  u64 limbs[4];
  bytes_mod(buf, sizeof buf, RL, 4, limbs);
  for (int i = 0; i < 4; i++)
    for (int j = 0; j < 8; j++)
      out32[i * 8 + j] = (uint8_t)(limbs[i] >> (8 * j));
  return 0;
}

// Pedersen deal from caller-supplied polynomial coefficients (a0 first,
// 32B LE each): commitments comm[j] = g^{f_j} h^{g_j} (const-time — the
// coefficients are secret), shares s_i = F(i), t_i = G(i) for i = 1..n.
// Split from the RNG so differential tests vs the Python spec (sss.py)
// can drive both paths from one coefficient set. Mirrors
// PedersenVSS::deal (keygen.rs:93-94).
void cc_pedersen_deal_from_coeffs(int t, int n, const uint8_t *g96,
                                  const uint8_t *h96, const uint8_t *fc,
                                  const uint8_t *gc, uint8_t *out_comms,
                                  uint8_t *out_s, uint8_t *out_t) {
  ccbls_init();
  pedersen_ct_rows(g96, h96, fc, gc, t, out_comms);
  for (int i = 1; i <= n; i++) {
    cc_fr_poly_eval(fc, t, (uint32_t)i, out_s + (size_t)(i - 1) * 32);
    cc_fr_poly_eval(gc, t, (uint32_t)i, out_t + (size_t)(i - 1) * 32);
  }
}

// Full native deal: fresh random coefficients + the above. Returns 0 on
// success (nonzero: entropy failure).
int cc_pedersen_deal(int t, int n, const uint8_t *g96, const uint8_t *h96,
                     uint8_t *out_fc, uint8_t *out_gc, uint8_t *out_comms,
                     uint8_t *out_s, uint8_t *out_t) {
  for (int j = 0; j < t; j++) {
    if (cc_fr_random(out_fc + (size_t)j * 32)) return 1;
    if (cc_fr_random(out_gc + (size_t)j * 32)) return 1;
  }
  cc_pedersen_deal_from_coeffs(t, n, g96, h96, out_fc, out_gc, out_comms,
                               out_s, out_t);
  return 0;
}

// verify_share (keygen.rs:334-351): g^s h^t == prod_j comm[j]^{id^j}.
// The share side runs const-time (it is the holder's secret); the
// commitment side is public -> var-time. Returns 1 valid, 0 invalid.
int cc_pedersen_verify_share(int t, uint32_t share_id, const uint8_t *s32,
                             const uint8_t *t32, const uint8_t *comms,
                             const uint8_t *g96, const uint8_t *h96) {
  ccbls_init();
  uint8_t lhs[96];
  pedersen_ct_rows(g96, h96, s32, t32, 1, lhs);
  fr_init();
  Fr e = FR_ONE, idf = fr_from_u64(share_id);
  Jac<Fp> acc = jac_inf<Fp>();
  for (int j = 0; j < t; j++) {
    Fp cx, cy;
    bool cinf = g1_load(comms + (size_t)j * 96, cx, cy);
    if (!cinf) {
      uint8_t eb[32];
      fr_to_le(e, eb);
      acc = jac_add(acc, g1_smul(cx, cy, scalar_load(eb)));
    }
    e = fr_mul(e, idf);
  }
  Fp rx, ry;
  bool rinf;
  jac_to_affine(acc, rx, ry, rinf);
  uint8_t rhs[96];
  g1_store(rhs, rx, ry, rinf);
  return memcmp(lhs, rhs, 96) == 0 ? 1 : 0;
}

// --- DVSS participant state machine (keygen.rs:124-205): deal own secret,
// receive + verify pairwise shares, additively combine. Opaque handle ABI;
// the protocol driver (who sends what to whom) stays host-side, exactly as
// the reference keeps it in share_secret_for_testing (keygen.rs:126-165).

struct CcDvss {
  uint32_t id;
  int t, n;
  uint8_t g[96], h[96];
  std::vector<uint8_t> fc, gc;              // own poly coeffs (secret)
  std::vector<uint8_t> comms;               // own coefficient commitments
  std::vector<uint8_t> s_shares, t_shares;  // dealt shares for ids 1..n
  std::vector<char> have;                   // indexed by from_id
  std::vector<uint8_t> recv_s, recv_t;      // indexed by from_id
  std::vector<uint8_t> recv_comms;          // from_id-indexed, t*96 each
  int received;
};

CcDvss *cc_dvss_new(uint32_t id, int t, int n, const uint8_t *g96,
                    const uint8_t *h96) {
  if (t <= 0 || n < t || id < 1 || (int)id > n) return nullptr;
  CcDvss *p = new CcDvss();
  p->id = id;
  p->t = t;
  p->n = n;
  memcpy(p->g, g96, 96);
  memcpy(p->h, h96, 96);
  p->fc.resize((size_t)t * 32);
  p->gc.resize((size_t)t * 32);
  p->comms.resize((size_t)t * 96);
  p->s_shares.resize((size_t)n * 32);
  p->t_shares.resize((size_t)n * 32);
  p->have.assign(n + 1, 0);
  p->recv_s.assign((size_t)(n + 1) * 32, 0);
  p->recv_t.assign((size_t)(n + 1) * 32, 0);
  p->recv_comms.assign((size_t)(n + 1) * t * 96, 0);
  p->received = 0;
  if (cc_pedersen_deal(t, n, g96, h96, p->fc.data(), p->gc.data(),
                       p->comms.data(), p->s_shares.data(),
                       p->t_shares.data())) {
    delete p;
    return nullptr;
  }
  return p;
}

// Own deal outputs (what gets broadcast / sent pairwise): commitments
// (t*96) and the (s, t) share addressed to each participant id (n*32 each).
void cc_dvss_deal(const CcDvss *p, uint8_t *out_comms, uint8_t *out_s,
                  uint8_t *out_t) {
  memcpy(out_comms, p->comms.data(), p->comms.size());
  memcpy(out_s, p->s_shares.data(), p->s_shares.size());
  memcpy(out_t, p->t_shares.data(), p->t_shares.size());
}

// Receive + verify from_id's share addressed to us. 0 = ok; 1 = own id;
// 2 = out of range; 3 = duplicate; 4 = share fails verification.
int cc_dvss_receive(CcDvss *p, uint32_t from_id, const uint8_t *comms,
                    const uint8_t *s32, const uint8_t *t32) {
  if (from_id == p->id) return 1;
  if (from_id < 1 || (int)from_id > p->n) return 2;
  if (p->have[from_id]) return 3;
  if (cc_pedersen_verify_share(p->t, p->id, s32, t32, comms, p->g, p->h) != 1)
    return 4;
  memcpy(p->recv_s.data() + (size_t)from_id * 32, s32, 32);
  memcpy(p->recv_t.data() + (size_t)from_id * 32, t32, 32);
  memcpy(p->recv_comms.data() + (size_t)from_id * p->t * 96, comms,
         (size_t)p->t * 96);
  p->have[from_id] = 1;
  p->received++;
  return 0;
}

// Finalize: own + received shares summed into this participant's share of
// the distributed secret (keygen.rs:161-163); coefficient commitments
// combined point-wise for later share checks. 0 = ok; 1 = missing shares.
int cc_dvss_finalize(CcDvss *p, uint8_t *out_s32, uint8_t *out_t32,
                     uint8_t *out_final_comms) {
  if (p->received != p->n - 1) return 1;
  fr_init();
  Fr sa = fr_from_le(p->s_shares.data() + (size_t)(p->id - 1) * 32);
  Fr ta = fr_from_le(p->t_shares.data() + (size_t)(p->id - 1) * 32);
  for (int f = 1; f <= p->n; f++) {
    if (!p->have[f]) continue;
    sa = fr_add(sa, fr_from_le(p->recv_s.data() + (size_t)f * 32));
    ta = fr_add(ta, fr_from_le(p->recv_t.data() + (size_t)f * 32));
  }
  fr_to_le(sa, out_s32);
  fr_to_le(ta, out_t32);
  for (int j = 0; j < p->t; j++) {
    Fp x, y;
    bool inf = g1_load(p->comms.data() + (size_t)j * 96, x, y);
    Jac<Fp> acc = inf ? jac_inf<Fp>() : Jac<Fp>{x, y, FP_ONE};
    for (int f = 1; f <= p->n; f++) {
      if (!p->have[f]) continue;
      Fp cx, cy;
      bool cinf =
          g1_load(p->recv_comms.data() + ((size_t)f * p->t + j) * 96, cx, cy);
      acc = jac_add_affine(acc, cx, cy, cinf);
    }
    Fp ox, oy;
    bool oinf;
    jac_to_affine(acc, ox, oy, oinf);
    g1_store(out_final_comms + (size_t)j * 96, ox, oy, oinf);
  }
  return 0;
}

void cc_dvss_free(CcDvss *p) { delete p; }

int cc_selftest() {
  ccbls_init();
  // 1 in, 1 out through the Montgomery codec
  uint8_t buf[48] = {0};
  buf[0] = 5;
  Fp a = fp_from_le(buf);
  Fp b = fp_mul(a, fp_inv(a));
  if (!fp_eq_raw(b, FP_ONE)) return 1;
  // frobenius consistency: frob applied 12x is identity on a random-ish elt
  Fp12 x = FP12_ONE;
  x.c1.c1 = {a, fp_add(a, FP_ONE)};
  x.c0.c2 = {fp_sq(a), a};
  Fp12 y = x;
  for (int i = 0; i < 12; i++) y = fp12_frobenius(y);
  const u64 *xa = (const u64 *)&x, *ya = (const u64 *)&y;
  for (size_t i = 0; i < sizeof(Fp12) / 8; i++)
    if (xa[i] != ya[i]) return 2;
  // frob2 == frob applied twice
  Fp12 f2a = fp12_frobenius2(x);
  Fp12 f2b = fp12_frobenius(fp12_frobenius(x));
  const u64 *pa = (const u64 *)&f2a, *pb = (const u64 *)&f2b;
  for (size_t i = 0; i < sizeof(Fp12) / 8; i++)
    if (pa[i] != pb[i]) return 3;
  return 0;
}

}  // extern "C"

#ifdef CCBLS_SELFTEST_MAIN
// Standalone sanitizer-run entry (make -C native selftest_asan; ci.sh):
// exercises the arithmetic selftest plus the allocation-heavy ABI paths
// (MSM tables, multi-Miller, hashing) under ASan+UBSan.
#include <cstdio>

int main() {
  int rc = cc_selftest();
  if (rc) {
    fprintf(stderr, "cc_selftest failed: %d\n", rc);
    return rc;
  }
  // hash-to-group + MSM + pairing round trip on derived points
  uint8_t g1b[96], g2b[192], frb[32];
  const uint8_t dst1[] = "COCONUT-TPU-V2-G1";
  const uint8_t dst2[] = "COCONUT-TPU-V2-G2";
  const uint8_t dstr[] = "COCONUT-TPU-V2-FR";
  const uint8_t msg[] = "ci-selftest";
  if (cc_hash_to_g1(msg, 11, dst1, 17, g1b)) return 10;
  if (cc_hash_to_g2(msg, 11, dst2, 17, g2b)) return 11;
  if (cc_hash_to_fr(msg, 11, dstr, 17, frb)) return 12;
  // e(a P, Q) * e(-P, a Q) == 1  via cc_msm + cc_pairing_product_is_one
  uint8_t scal[64] = {0};
  memcpy(scal, frb, 32);        // a
  memcpy(scal + 32, frb, 32);   // a (same scalar for the G2 side)
  uint8_t ap[96], aq[192];
  cc_msm_g1(g1b, scal, 1, 1, ap, 0);
  cc_msm_g2(g2b, scal + 32, 1, 1, aq, 0);
  // -P: negate y of the affine G1 point = p - y
  uint8_t negp[96];
  memcpy(negp, g1b, 96);
  {
    // y' = p - y (big-int subtract on 48B LE)
    static const uint8_t ple[48] = {
        0xab, 0xaa, 0xff, 0xff, 0xff, 0xff, 0xfe, 0xb9, 0xff, 0xff, 0x53,
        0xb1, 0xfe, 0xff, 0xab, 0x1e, 0x24, 0xf6, 0xb0, 0xf6, 0xa0, 0xd2,
        0x30, 0x67, 0xbf, 0x12, 0x85, 0xf3, 0x84, 0x4b, 0x77, 0x64, 0xd7,
        0xac, 0x4b, 0x43, 0xb6, 0xa7, 0x1b, 0x4b, 0x9a, 0xe6, 0x7f, 0x39,
        0xea, 0x11, 0x01, 0x1a};
    int borrow = 0;
    for (int i = 0; i < 48; i++) {
      int d = (int)ple[i] - (int)negp[48 + i] - borrow;
      borrow = d < 0;
      negp[48 + i] = (uint8_t)(d + (borrow << 8));
    }
  }
  uint8_t ps[192], qs[384], ok = 0;
  memcpy(ps, ap, 96);
  memcpy(ps + 96, negp, 96);
  memcpy(qs, g2b, 192);
  memcpy(qs + 192, aq, 192);
  cc_pairing_product_is_one(ps, qs, 2, 1, &ok);
  if (!ok) {
    fprintf(stderr, "pairing bilinearity check failed\n");
    return 13;
  }
  printf("ccbls sanitizer selftest: ok\n");
  return 0;
}
#endif
