#!/bin/sh -e
# One-command CI (VERDICT r2 item 9; the reference's analogue is
# .travis.yml:7-9, which runs `cargo test --release` under both
# group-assignment features).
#
#   ./ci.sh           default suite + sanitizer selftest
#   CI_HEAVY=1 ./ci.sh   also runs the multi-minute fused-kernel tests
#
# Group assignments: both SignatureG1 and SignatureG2 are exercised
# IN-SUITE (tests/test_protocol.py parametrizes the full lifecycle over
# SIGNATURES_IN_G1 and SIGNATURES_IN_G2), so one pytest run covers what the
# reference needed two feature builds for.
cd "$(dirname "$0")"

echo "== native: release build + sanitizer selftest =="
make -C native libccbls.so
make -C native selftest_asan
./native/selftest_asan

echo "== test suite (both group assignments in-suite) =="
python -m pytest tests/ -q
if [ "${CI_HEAVY:-0}" = "1" ]; then
  # Heavy lane in its OWN process: the at-scale B=1024 programs
  # accumulate ~25 GB of compiled XLA CPU state, and one combined
  # heavy+default+mesh process was observed segfaulting inside a later
  # sharded pjit execution (2026-08-01) while every lane passes in
  # isolation — bound the per-process executable cache by splitting.
  # Marker-based selection: file-agnostic, and the second process runs
  # ONLY the heavy tests.
  echo "== heavy lane (separate process) =="
  COCONUT_TEST_HEAVY=1 python -m pytest tests/ -m heavy -q
fi

echo "== driver probes =="
# Compile (not just import) the flagship entry program and check its
# bits, exactly as the driver's compile-check does. Budget ~5.5 min on
# this host: the cost is dominated by Python tracing + host comb-table
# build (the persistent cache only removes the XLA compile), so treat
# this as the entry probe's expected wall time, not a cache miss.
python -c "
import __graft_entry__ as ge
fn, a = ge.entry()
import jax
assert bool(jax.jit(fn)(*a).all())
"
# Run the multi-chip dryrun exactly as the driver does (8-device virtual CPU
# mesh). tests/test_shard.py compiled these exact programs above, so this is
# warm-seconds from the persistent cache — and it keeps the cache seeded so
# the driver's MULTICHIP probe never pays a cold compile (VERDICT r3 item 1).
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
  JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as ge; ge.dryrun_multichip(8)"
echo "ci: ok"
