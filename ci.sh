#!/bin/sh -e
# One-command CI (VERDICT r2 item 9; the reference's analogue is
# .travis.yml:7-9, which runs `cargo test --release` under both
# group-assignment features).
#
#   ./ci.sh           default suite + sanitizer selftest
#   CI_HEAVY=1 ./ci.sh   also runs the multi-minute fused-kernel tests
#
# Group assignments: both SignatureG1 and SignatureG2 are exercised
# IN-SUITE (tests/test_protocol.py parametrizes the full lifecycle over
# SIGNATURES_IN_G1 and SIGNATURES_IN_G2), so one pytest run covers what the
# reference needed two feature builds for.
cd "$(dirname "$0")"

echo "== native: release build + sanitizer selftest =="
make -C native libccbls.so
make -C native selftest_asan
./native/selftest_asan

echo "== test suite (both group assignments in-suite) =="
python -m pytest tests/ -q

echo "== analysis lane (invariant lint suite + CI gate) =="
# the marker suite: each checker fires on its seeded-bad fixture, the
# runtime lock-order tracker catches a real ABBA interleaving, the
# dead-letter schema validator rejects malformed records
python -m pytest tests/test_analysis.py -m analysis -q
# the gate: lock-order / wire-contract / const-time / durability /
# metrics-doc over the tree; any finding not covered by an inline
# ``# lint: allow(...)`` pragma or analysis_baseline.json fails CI
python -m coconut_tpu.analysis --fail-on-new

echo "== fault-supervision lane (retry/fallback/bisection/checkpoints) =="
python -m pytest tests/test_faults.py -m faults -q
# dead-letter JSONL schema probe: run a tiny grouped stream with one forged
# credential and grep the bisection output for the documented keys
DLQ=$(mktemp -d)/dead.jsonl
DLQ_PATH="$DLQ" python - <<'EOF'
import os
from types import SimpleNamespace
from coconut_tpu.stream import verify_stream

def cred(ok=True):
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)

def source(i):
    sigs = [cred(ok=not (i == 1 and j == 2)) for j in range(4)]
    return sigs, [[0]] * 4

class Grouped:
    def batch_verify_grouped(self, sigs, msgs, vk, params):
        return all(s.ok for s in sigs)

verify_stream(source, 3, None, None, Grouped(), mode="grouped",
              dead_letter_path=os.environ["DLQ_PATH"])
EOF
# structured schema-v4 validation (replaces the old grep chain, which
# passed on wrong types and torn lines): every line must parse, carry
# exactly the v4 key set with the right types/null-ability, and the
# bisected culprit must be batch 1 / credential 2
python -m coconut_tpu.analysis.schema "$DLQ" \
  --expect batch=1 --expect credential=2

echo "== serve lane (dynamic batching / admission control / loadgen) =="
# "not slow": the mesh-serve integration test already ran in the full
# suite above — re-tracing its multi-minute mesh program in this second
# process would double the lane's cost for no coverage
python -m pytest tests/test_serve.py -m "serve and not slow" -q
# 2-second loadgen smoke against the REAL service on the CPU (python)
# backend: closed loop at saturation, then assert the SLO report is sane —
# every accepted future resolved, batches actually coalesced, and the
# latency percentiles present. bench_serve itself asserts the invariants
# loudly; the JSON probe re-checks them from the artifact a human reads.
SERVE_JSON=$(mktemp -d)/serve.json
BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 BENCH_CHAOS=0 \
  BENCH_SERVE_SECONDS=2 BENCH_SERVE_MAX_BATCH=4 JAX_PLATFORMS=cpu \
  python bench.py --serve > "$SERVE_JSON"
SERVE_JSON_PATH="$SERVE_JSON" python - <<'EOF'
import json, os
with open(os.environ["SERVE_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["serve"]
assert report["dropped_futures"] == 0, report
assert report["verdict_mismatches"] == 0, report
assert report["mean_batch_occupancy"] > 0.5, report
assert report["latency_s"]["p99"] is not None, report
assert report["completed"] > 0 and report["errors"] == 0, report
print("serve smoke: ok (goodput %.1f/s, occupancy %.2f, p99 %.0f ms)" % (
    report["goodput_per_s"], report["mean_batch_occupancy"],
    report["latency_s"]["p99"] * 1000.0))
EOF

# mesh-serve smoke (ISSUE 8): the same short real-service loadgen, now
# through the per-device dispatcher pool on the 8-device virtual CPU mesh,
# swept over pool sizes (BENCH_SERVE_DEVICES -> "serve"."scaling" in the
# BENCH JSON). The probe asserts from the artifact that scaling actually
# engaged: MORE THAN ONE device saw dispatches at the widest point, zero
# dropped futures at every point. (The jax mesh-sharded serve path itself
# is covered in-suite by tests/test_serve.py::test_mesh_serve_integration*
# on the same virtual mesh.)
MESH_SERVE_JSON=$(mktemp -d)/mesh_serve.json
BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 BENCH_CHAOS=0 \
  BENCH_SERVE_SECONDS=1 BENCH_SERVE_MAX_BATCH=4 BENCH_TRACE_OVERHEAD=0 \
  BENCH_SERVE_DEVICES="1,8" BENCH_SERVE_SWEEP_SECONDS=0.5 \
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python bench.py --serve > "$MESH_SERVE_JSON"
MESH_SERVE_JSON_PATH="$MESH_SERVE_JSON" python - <<'EOF'
import json, os
with open(os.environ["MESH_SERVE_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
scaling = json.loads(line)["serve"]["scaling"]
points = {p["devices"]: p for p in scaling["points"]}
assert set(points) == {1, 8}, sorted(points)
for n, p in sorted(points.items()):
    assert p["goodput_per_s"] > 0, p
    assert p["dropped_futures"] == 0, p
    assert p["devices_with_dispatches"] >= 1, p
wide = points[8]
assert wide["devices_with_dispatches"] > 1, wide
assert all(v > 0 for v in wide["per_device_dispatches"].values()), wide
print("mesh-serve smoke: ok (%d devices dispatched at n=8, "
      "efficiency %.2f)" % (wide["devices_with_dispatches"],
                            wide["scaling_efficiency"]))
EOF

echo "== chaos lane (self-healing pool: crash containment / watchdog / brownout) =="
# the marker suite: breaker/watchdog/brownout units (tests/test_health.py),
# fake-clock crash/hang/quarantine/probation integration (test_serve.py),
# injection + rotation + crash-atomic checkpoint satellites (test_faults.py).
# COCONUT_LOCK_CHECK=1 runs the whole lane under the runtime lock-order
# tracker (analysis/lockcheck.py): any acquisition-order inversion
# recorded during a test fails that test
COCONUT_LOCK_CHECK=1 python -m pytest tests/ -m chaos -q
# end-to-end acceptance smoke (ISSUE 9): a real 8-executor stub-device
# service takes one injected executor crash AND one hung dispatch mid-run;
# the probe asserts every submitted future settled, the culprits were
# quarantined (crash + watchdog paths both fired), and goodput recovered
# to >= half the pre-fault level after the probation ladder re-admits
JAX_PLATFORMS=cpu python probes/probe_chaos.py
# chaos-recovery bench datapoint: goodput before/during/after a scheduled
# crash+hang pair, from the same JSON artifact a human reads
CHAOS_JSON=$(mktemp -d)/chaos.json
BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 BENCH_TRACE_OVERHEAD=0 \
  BENCH_SERVE_SECONDS=0.5 BENCH_SERVE_MAX_BATCH=4 BENCH_CHAOS_SECONDS=0.5 \
  JAX_PLATFORMS=cpu python bench.py --serve > "$CHAOS_JSON"
CHAOS_JSON_PATH="$CHAOS_JSON" python - <<'PYEOF'
import json, os
with open(os.environ["CHAOS_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
cr = json.loads(line)["serve"]["chaos_recovery"]
assert cr["counters"]["serve_executor_crashes"] >= 1, cr
assert cr["counters"]["serve_quarantined"] >= 1, cr
assert all(v == 0 for v in cr["errors"].values()), cr
assert cr["recovery_ratio"] is not None and cr["recovery_ratio"] >= 0.5, cr
print("chaos bench smoke: ok (recovery ratio %.2f, %d quarantined, "
      "%d watchdog timeouts)" % (cr["recovery_ratio"],
                                 cr["counters"]["serve_quarantined"],
                                 cr["counters"]["serve_watchdog_timeouts"]))
PYEOF

echo "== issue lane (threshold issuance: quorum fan-out / hedging / attribution) =="
# the marker suite: fake-clock quorum/hedge/attribution mechanics plus the
# real-crypto first-t-bit-identical and crash+hang acceptance tests
python -m pytest tests/ -m issue -q
# end-to-end acceptance smoke (ISSUE 10): a real 5-authority t=3 pool
# takes one injected authority crash AND one hung sign on its first
# fan-out; the probe asserts every order minted, every minted credential
# verifies under the Lagrange-aggregated verkey, and the crashed
# authority was quarantined while the pool kept minting
JAX_PLATFORMS=cpu python probes/probe_issue.py
# issuance bench smoke: pure-issuance loadgen against the real service on
# the CPU backend, asserted from the JSON artifact a human reads
ISSUE_JSON=$(mktemp -d)/issue.json
BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 \
  BENCH_ISSUE_SECONDS=1.5 BENCH_ISSUE_MAX_BATCH=4 JAX_PLATFORMS=cpu \
  python bench.py --issue > "$ISSUE_JSON"
ISSUE_JSON_PATH="$ISSUE_JSON" python - <<'EOF'
import json, os
with open(os.environ["ISSUE_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["issue"]
assert report["dropped_futures"] == 0, report
assert report["mint_mismatches"] == 0, report
assert report["errors"] == 0, report
assert report["minted"] > 0, report
assert report["quorum_unreachable"] == 0, report
assert report["quorum_wait_s"]["p95"] is not None, report
print("issue smoke: ok (%.1f credentials/s, quorum-wait p95 %.0f ms, "
      "hedge rate %s)" % (report["credentials_per_sec"],
                          report["quorum_wait_s"]["p95"] * 1000.0,
                          report["hedge_rate"]))
EOF

echo "== engine lane (unified fabric: five programs / one pool / session pipeline) =="
# the marker suite: typed retriable-error hierarchy, online/offline show
# parity through engine lanes (padding + ragged tails), mixed-program
# full-session pipeline, jit-shape-cache stability
python -m pytest tests/ -m engine -q
# end-to-end acceptance smoke (ISSUE 12): a real ProtocolEngine runs all
# FIVE phases over one 2-executor pool + 3-authority t=2 mint pool, takes
# one injected executor crash mid-workload; the probe asserts every
# future settled, the full sessions round-trip (mint -> verify -> show),
# the crash was contained+redistributed, and the per-program jit-shape
# counters stayed flat after warmup (no cross-program recompiles)
JAX_PLATFORMS=cpu python probes/probe_engine.py
# full-session bench smoke: closed-loop sessions (prepare -> mint ->
# show_prove -> show_verify) against the real engine on the CPU backend,
# asserted from the JSON artifact a human reads
SESSION_JSON=$(mktemp -d)/session.json
BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 BENCH_CHAOS=0 \
  BENCH_SESSION_SECONDS=1.5 BENCH_SESSION_MAX_BATCH=4 JAX_PLATFORMS=cpu \
  python bench.py --session > "$SESSION_JSON"
SESSION_JSON_PATH="$SESSION_JSON" python - <<'EOF'
import json, os
with open(os.environ["SESSION_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["session"]
assert report["sessions_completed"] > 0, report
assert report["errors"] == 0, report
assert report["failed_shows"] == 0, report
assert report["jit_shapes_stable"], report
assert report["session_latency_s"]["p95"] is not None, report
print("session smoke: ok (%.1f sessions/s, p95 %.0f ms, jit shapes "
      "stable across %d programs)" % (
          report["sessions_per_s"],
          report["session_latency_s"]["p95"] * 1000.0,
          len(report["per_program"])))
EOF

echo "== gateway lane (wire-format RPC ingress / tenant admission / replica router) =="
# the marker suite: byte-exact wire golden vectors, strict-decode
# rejection, typed error envelopes round-tripped, fake-clock token
# buckets and gossip, consistent-hash affinity, loopback-fleet chaos
python -m pytest tests/ -m gateway -q
# end-to-end acceptance smoke (ISSUE 13): a REAL 3-replica fleet over
# loopback TCP sockets behind the router + gossip thread. The probe
# kills one replica mid-run and asserts: every in-flight future settles
# via retry on the survivors (zero dangling), the router demotes the
# dead replica, the over-quota tenant alone is refused, and the replica
# REJOINS via a fresh beacon after its serve loop restarts.
JAX_PLATFORMS=cpu python probes/probe_gateway.py
# RPC-tax bench smoke: the same warm CredentialService direct vs through
# the wire (real socket), asserted from the JSON artifact a human reads —
# the ISSUE 13 floor is RPC goodput >= 80% of direct
GATEWAY_JSON=$(mktemp -d)/gateway.json
BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 BENCH_CHAOS=0 \
  BENCH_GATEWAY_SECONDS=2 BENCH_GATEWAY_MAX_BATCH=4 JAX_PLATFORMS=cpu \
  python bench.py --gateway > "$GATEWAY_JSON"
GATEWAY_JSON_PATH="$GATEWAY_JSON" python - <<'EOF'
import json, os
with open(os.environ["GATEWAY_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["gateway"]
assert report["goodput_ratio"] >= report["min_ratio"], report
for side in ("direct", "rpc"):
    assert report[side]["completed"] > 0, report
    assert report[side]["errors"] == 0, report
    assert report[side]["dropped_futures"] == 0, report
    assert report[side]["verdict_mismatches"] == 0, report
assert report["rpc"]["rpc_overhead_s"] is not None, report
print("gateway smoke: ok (rpc/direct goodput ratio %.2f, "
      "rpc overhead %.1f ms/req)" % (
          report["goodput_ratio"],
          report["rpc"]["rpc_overhead_s"] * 1000.0))
EOF

echo "== lifecycle lane (warm restarts / readiness gating / drain-and-handoff) =="
# the marker suite: shape-manifest canonicalization + corruption handling,
# WARMING->UP->DRAINING->CLOSED state machine on a fake clock, graceful
# drain refusals resubmitted on ring successors, elastic park/unpark
# hysteresis, and the deterministic loopback rolling-restart drill
python -m pytest tests/ -m lifecycle -q
# end-to-end acceptance smoke (ISSUE 14): a REAL 3-replica TCP fleet under
# continuous loadgen traffic has every replica restarted in sequence —
# graceful drain persists the shape manifest, the successor boots WARMING,
# replays it, and rejoins. The probe asserts zero dangling futures, zero
# non-retryable client errors, the gateway_placed_warming/draining audit
# counters at ZERO, and bounded restart-to-first-SLO per restart.
JAX_PLATFORMS=cpu python probes/probe_lifecycle.py
# warm-restart bench smoke: simulated compile walls behind the manifest +
# persistent-cache replay; asserted from the JSON artifact a human reads —
# the ISSUE 14 floor is warm restart-to-first-SLO at a small fraction of
# the cold compile_plus_run floor (both numbers embedded in the artifact).
# BENCH_LIFECYCLE=0 skips the lane (e.g. on boxes where the simulated
# compile sleeps make the wall too noisy to assert on).
if [ "${BENCH_LIFECYCLE:-1}" = "1" ]; then
  LIFECYCLE_JSON=$(mktemp -d)/lifecycle.json
  BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=8 JAX_PLATFORMS=cpu \
    python bench.py --lifecycle > "$LIFECYCLE_JSON"
  LIFECYCLE_JSON_PATH="$LIFECYCLE_JSON" python - <<'EOF'
import json, os
with open(os.environ["LIFECYCLE_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["lifecycle"]
assert report["manifest_shapes"] == report["shapes"], report
assert report["cold_restart_to_first_slo_s"] >= report[
    "compile_plus_run_floor_s"], report
assert report["warm_restart_to_first_slo_s"] <= (
    report["max_fraction"] * report["cold_restart_to_first_slo_s"]), report
assert report["warm_over_cold"] <= report["max_fraction"], report
print("lifecycle bench smoke: ok (warm restart %.0f ms vs cold floor "
      "%.0f ms, warm/cold %.3f)" % (
          report["warm_restart_to_first_slo_s"] * 1000.0,
          report["compile_plus_run_floor_s"] * 1000.0,
          report["warm_over_cold"]))
EOF
else
  echo "lifecycle bench smoke: skipped (BENCH_LIFECYCLE=0)"
fi

echo "== keylife lane (online DKG / proactive refresh / epoch rollover) =="
# the marker suite: typed share-rejection paths, DKG complaint attribution
# + typed abort, no-master-secret enforcement, refresh same-verkey/all-
# shares-change, epoch registry window/pin mechanics, epoch-keyed wire +
# static-cache coexistence, and the deterministic rollover chaos drill
python -m pytest tests/ -m keylife -q
# end-to-end acceptance smoke (ISSUE 15): a REAL 5-authority fleet born
# from an online DKG (corrupt dealer named + excluded) serves full
# sessions over a TCP socket while the lifecycle takes one proactive
# refresh AND one 3-of-5 -> 2-of-5 reshare mid-traffic. The probe asserts
# zero dangling futures, zero terminal errors, every pre-rollover
# credential verifying post-rollover under its mint epoch, and the beacon
# epoch window advertising each transition.
JAX_PLATFORMS=cpu python probes/probe_epoch.py
# rollover bench smoke: goodput before/during/after a live reshare,
# asserted from the JSON artifact a human reads — the ISSUE 15 floor is a
# NON-ZERO during phase (the rollover never blacks out serving).
# BENCH_KEYLIFE=0 skips the lane.
if [ "${BENCH_KEYLIFE:-1}" = "1" ]; then
  KEYLIFE_JSON=$(mktemp -d)/keylife.json
  BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=16 BENCH_CHAOS=0 \
    BENCH_KEYLIFE_SECONDS=1.5 BENCH_KEYLIFE_MAX_BATCH=4 JAX_PLATFORMS=cpu \
    python bench.py --keylife > "$KEYLIFE_JSON"
  KEYLIFE_JSON_PATH="$KEYLIFE_JSON" python - <<'EOF'
import json, os
with open(os.environ["KEYLIFE_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["keylife"]
assert report["goodput_per_s"]["during"] > 0, report
assert report["goodput_per_s"]["before"] > 0, report
assert report["goodput_per_s"]["after"] > 0, report
assert report["degradation_ratio"] is not None, report
assert report["refreshes"] == 1 and report["reshares"] == 1, report
print("keylife bench smoke: ok (goodput %.1f -> %.1f -> %.1f /s through "
      "refresh+reshare, degradation %.2f)" % (
          report["goodput_per_s"]["before"],
          report["goodput_per_s"]["during"],
          report["goodput_per_s"]["after"],
          report["degradation_ratio"]))
EOF
else
  echo "keylife bench smoke: skipped (BENCH_KEYLIFE=0)"
fi

echo "== batchverify lane (RLC combined pairing check / bisection fallback) =="
# the marker suite: deterministic combiner derivation (same transcript ->
# same exponents, cross-process), transcript domain separation (verkey /
# epoch / lane content), batched-vs-exact bit-identical verdicts, forged-
# lane attribution through the bisection ladder, the adversarial 100-draw
# soundness sweeps (B in {16,256}) and the cancellation-pair attack, plus
# the serve/engine "batched" program modes (pow2 jit-shape bucketing,
# COCONUT_BATCH_VERIFY default, keychain refusal)
python -m pytest tests/ -m batchverify -q
# end-to-end acceptance smoke (ISSUE 16): a REAL CredentialService in
# mode="batched" folds a 64-lane batch (one forged sigma_2) into ONE
# combined pairing check, bisects the failure down to the culprit lane,
# dead-letters it with program + lane index, and settles every survivor
# True — then proves the steady state: an all-valid batch is ONE combined
# check and ONE final exponentiation.
JAX_PLATFORMS=cpu python probes/probe_batchverify.py
# bench smoke: batched-vs-exact device time for verify AND show-verify,
# asserted from the JSON artifact a human reads — the ISSUE 16 floor is
# <= 2 final exponentiations per combined batch and a reported crossover.
# BENCH_BATCHVERIFY=0 skips the lane.
if [ "${BENCH_BATCHVERIFY:-1}" = "1" ]; then
  BATCHV_JSON=$(mktemp -d)/batchverify.json
  BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=8 BENCH_CHAOS=0 \
    BENCH_BATCHVERIFY_SIZES=4,8 BENCH_BATCHVERIFY_REPS=1 JAX_PLATFORMS=cpu \
    python bench.py --batchverify > "$BATCHV_JSON"
  BATCHV_JSON_PATH="$BATCHV_JSON" python - <<'EOF'
import json, os
with open(os.environ["BATCHV_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["batchverify"]
assert report["points"], report
for p in report["points"]:
    assert p["verify_batched_final_exps"] <= 2, p
    assert p["show_batched_final_exps"] <= 2, p
assert report["batched_fallbacks"] == 0, report
assert "crossover_b" in report, report
print("batchverify bench smoke: ok (verify %.2fx, show %.2fx at B=%d, "
      "crossover_b=%s)" % (
          report["verify_speedup_at_max_b"],
          report["show_speedup_at_max_b"],
          report["points"][-1]["b"],
          report["crossover_b"]))
EOF
else
  echo "batchverify bench smoke: skipped (BENCH_BATCHVERIFY=0)"
fi

echo "== state lane (durable WAL / replicated nullifiers / kill-the-witness) =="
# the marker suite: WAL framing + torn-tail truncation (counted exactly
# once), the five-point crash enumeration (pre-append / mid-record /
# post-append-pre-fsync / mid-snapshot / mid-compaction -> prefix-
# consistent replay), snapshot+replay StateStore with LWW anti-entropy,
# nullifier derivation / device-vs-host probe parity / check-and-set
# commit, the typed DoubleSpendError through engine + wire, and the
# deterministic loopback kill-the-witness drill
python -m pytest tests/test_state.py -m state -q
# end-to-end acceptance smoke (ISSUE 17): a REAL 3-replica TCP fleet
# with per-replica WALs and beacon-driven anti-entropy — witness a show,
# SIGKILL-equivalent the witnessing replica, prove both survivors AND
# the WAL-replaying restarted witness still reject the replayed
# nullifier while a fresh re-randomized show stays accepted.
JAX_PLATFORMS=cpu python probes/probe_nullifier.py
# bench smoke: show-verify goodput bare vs WAL-backed nullifier set,
# asserted from the JSON artifact — the ISSUE 17 floor is >= 0.85x
# goodput with the group-commit-per-batch fsync policy visible as
# wal_fsyncs well under wal_appends. BENCH_STATE=0 skips the lane.
if [ "${BENCH_STATE:-1}" = "1" ]; then
  STATE_JSON=$(mktemp -d)/state.json
  BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=8 BENCH_CHAOS=0 \
    BENCH_STATE_SHOWS=32 JAX_PLATFORMS=cpu \
    python bench.py --state > "$STATE_JSON"
  STATE_JSON_PATH="$STATE_JSON" python - <<'EOF'
import json, os
with open(os.environ["STATE_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["state"]
assert report["fsync_policy"] == "group_commit_per_batch", report
assert report["goodput_ratio"] >= report["min_ratio"], report
assert report["wal_fsyncs"] < report["wal_appends"], report
assert report["nullifier_commits"] == report["shows"], report
print("state bench smoke: ok (ratio %.2fx, %d commits in %d fsyncs)"
      % (report["goodput_ratio"], report["nullifier_commits"],
         report["wal_fsyncs"]))
EOF
else
  echo "state bench smoke: skipped (BENCH_STATE=0)"
fi

echo "== hashmsm lane (device hash-to-G1 / bucketed Pippenger MSM) =="
# the marker suite: SvdW map parity vs the spec and the native oracle
# (random messages, empty message, the 255-byte DST boundary, u-values
# driving each of the three x-candidates, the (u, p-u) identity-sum
# edge), bucketed-vs-Horner bit parity across window sizes / ragged B /
# zero scalars / GLV on/off, knob parsing, dispatch-counter routing,
# and the epoch-retirement nullifier compaction satellite
python -m pytest tests/ -m hashmsm -q
# end-to-end acceptance smokes: prepare with the device hash FORCED on
# (the probe asserts device_hash_batches moved and zero fallbacks), and
# the bucketed-vs-Horner micro-probe with every lane checked against
# the Python spec (small shapes — this is the CPU parity gate, the
# timing story lives on the real chip)
COCONUT_DEVICE_HASH=1 PROBE_PREPARE_B=8 JAX_PLATFORMS=cpu \
  python probes/probe_prepare.py
PROBE_MSM_WINDOWS=3 JAX_PLATFORMS=cpu python probes/probe_pippenger.py 4 6
# calibration mode (ISSUE 19 satellite): measured-vs-model crossover
# sweep on tiny shapes — prints per-shape verdicts and a
# COCONUT_MSM_WINDOW recommendation; exits nonzero on parity failure
PROBE_MSM_WINDOWS=3 PROBE_CALIB_B=2 PROBE_CALIB_KS=4,6 \
  JAX_PLATFORMS=cpu python probes/probe_pippenger.py --calibrate
# bench smoke: old-vs-new path goodput for the hash and MSM stages,
# parity + path selection asserted from the artifact's counters. On
# this CPU mesh there is NO timing floor (ISSUE 18 acceptance split:
# the "new path faster" assert binds on the device backend only).
# BENCH_HASHMSM=0 skips the lane.
if [ "${BENCH_HASHMSM:-1}" = "1" ]; then
  HASHMSM_JSON=$(mktemp -d)/hashmsm.json
  BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=8 BENCH_CHAOS=0 \
    BENCH_HASHMSM_B=8 BENCH_HASHMSM_K=4 BENCH_HASHMSM_REPS=1 \
    JAX_PLATFORMS=cpu python bench.py --hashmsm > "$HASHMSM_JSON"
  HASHMSM_JSON_PATH="$HASHMSM_JSON" python - <<'EOF'
import json, os
with open(os.environ["HASHMSM_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
report = json.loads(line)["hashmsm"]
assert report["parity_ok"], report
assert report["device_hash_fallbacks"] == 0, report
assert report["device_hash_batches"] > 0, report
assert report["msm_bucketed_dispatches"] > 0, report
assert report["msm_horner_dispatches"] > 0, report
assert report["msm_bucket_window"] == report["window"], report
print("hashmsm bench smoke: ok (hash %s -> device x%s, msm horner -> "
      "bucketed w=%d x%s, floor_enforced=%s)" % (
          report["hash_old_path"], report["hash_speedup"],
          report["window"], report["msm_speedup"],
          report["timing_floor_enforced"]))
EOF
else
  echo "hashmsm bench smoke: skipped (BENCH_HASHMSM=0)"
fi

echo "== scenarios lane (application workflows / population traffic model) =="
# the marker suite: workflow state-machine runtime on a fake clock
# (retry taxonomy, deadlines, parked-retry resubmission, drain-cancel
# leaves no dangling frames), bit-stable seeded arrival streams
# (golden hash), Zipf tenanting + lazy population, report attribution,
# and the petition/e-cash/access flows end-to-end over loopback RPC
# with typed double-spend rejections
python -m pytest tests/ -m scenarios -q
# end-to-end acceptance smoke: a REAL 3-replica TCP fleet (per-replica
# WALs, anti-entropy, gossip-fed router) absorbing a mixed honest
# population through a flash crowd — zero failed, zero cancelled, zero
# rejections, availability timeline spanning the run
JAX_PLATFORMS=cpu python probes/probe_scenarios.py
# bench smoke: sustained mixed run on the local engine with the
# elastic controller in the loop and adversarial fractions ON — the
# artifact must show goodput tracking the diurnal curve, the pool
# resizing, p99 inside the SLO through the flash crowd, and every
# deliberate re-sign/double-spend as a typed rejection (asserted
# inside the lane itself). BENCH_SCENARIOS=0 skips the lane.
if [ "${BENCH_SCENARIOS:-1}" = "1" ]; then
  SCN_JSON=$(mktemp -d)/scenarios.json
  BENCH_OFFLINE=0 BENCH_BACKEND=python BENCH_BATCH=8 BENCH_CHAOS=0 \
    BENCH_SCENARIOS_S=40 JAX_PLATFORMS=cpu \
    python bench.py --scenarios > "$SCN_JSON"
  SCN_JSON_PATH="$SCN_JSON" python - <<'EOF'
import json, os
with open(os.environ["SCN_JSON_PATH"]) as f:
    line = f.read().strip().splitlines()[-1]
top = json.loads(line)
scn = top["scenarios"]
totals = scn["report"]["totals"]
assert totals["failed"] == 0 and totals["cancelled"] == 0, totals
assert totals["completed"] > 0 and totals["rejected_expected"] > 0, totals
print("scenarios bench smoke: ok (%.2f workflows/s, %d completed, "
      "%d typed rejections, peak %.2f/s vs trough %.2f/s)"
      % (top["value"], totals["completed"], totals["rejected_expected"],
         scn["goodput_peak_half_per_s"], scn["goodput_trough_per_s"]))
EOF
else
  echo "scenarios bench smoke: skipped (BENCH_SCENARIOS=0)"
fi

echo "== obs lane (request-scoped tracing / Perfetto export / flight recorder) =="
python -m pytest tests/test_obs.py -m obs -q
# end-to-end acceptance smoke on the REAL service (CPU, stub backend):
# one injected dispatch fault + one forged credential, tracing enabled.
# The forged request's span tree must show admission -> coalesce ->
# dispatch -> retry -> bisection -> dead-letter, its trace_id must appear
# in the dead-letter JSONL line AND the flight record, and the Chrome
# trace export must pass probe_trace's structural validation.
OBS_DIR=$(mktemp -d)
OBS_DLQ="$OBS_DIR/dead.jsonl" OBS_TRACE="$OBS_DIR/trace.json" python - <<'EOF'
import os
from types import SimpleNamespace
from coconut_tpu.faults import DeadLetterLog, FaultyBackend
from coconut_tpu.obs import export, flight
from coconut_tpu.obs import trace as otrace
from coconut_tpu.retry import RetryPolicy
from coconut_tpu.serve.service import CredentialService

def cred(ok=True):
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)

class Grouped:
    def batch_verify_grouped(self, sigs, msgs, vk, params):
        return all(s.sigma_1 is not None and s.ok for s in sigs)

otrace.enable()
dlq = os.environ["OBS_DLQ"]
svc = CredentialService(
    FaultyBackend(Grouped(), raise_on={0}), None, None, mode="grouped",
    max_batch=4, retry_policy=RetryPolicy(max_attempts=2, base_delay=0.0),
    dead_letter_path=dlq)
with svc:
    futs = [svc.submit(cred(ok=(i != 2)), [0], max_wait_ms=100.0)
            for i in range(4)]
    verdicts = [f.result(30.0) for f in futs]
assert verdicts == [True, True, False, True], verdicts
(rec,) = DeadLetterLog.read(dlq)
assert rec["schema"] == 4 and rec["trace_id"] == futs[2].trace_id, rec
assert rec["program"] == "verify", rec
tree = otrace.get_tracer().spans_for(futs[2].trace_id)
names = {s.name for s in tree}
assert names >= {"request", "queue_wait", "batch", "coalesce", "dispatch",
                 "device", "bisect", "demux"}, names
events = {e["name"] for s in tree for e in s.events}
assert {"retry", "attempt_failed", "split", "dead_letter"} <= events, events
(fl,) = flight.read(dlq)
assert fl["trace_id"] == futs[2].trace_id and fl["reason"] == "dead_letter"
n = export.export_chrome(os.environ["OBS_TRACE"])
assert n > 0
print("obs smoke: ok (%d trace events, culprit trace %s)"
      % (n, rec["trace_id"]))
EOF
JAX_PLATFORMS=cpu python probes/probe_trace.py "$OBS_DIR/trace.json"
test -f "$OBS_DIR/dead.jsonl.flight.jsonl"

echo "== encode-pipeline lane (prefetch worker / static cache / raw wire) =="
# lean by construction: only host-side / small-jit tests carry the
# `pipeline` marker (the kernel-materializing encode tests ride the
# default suite above, the sharded pad regression the heavy lane) — so
# this lane stays minutes, not the multi-minute-per-shape trace cost
python -m pytest tests/ -m pipeline -q
# per-stage encode micro-probe (bytes-framing vs digits vs tables): the
# profiling-round artifact for where the host encode wall actually is.
# Host-encode stages are platform-independent — pin CPU so the probe
# never pays a tunneled comb build in the default lane.
JAX_PLATFORMS=cpu python probes/probe_encode.py
if [ "${CI_HEAVY:-0}" = "1" ]; then
  # Heavy lane in its OWN process: the at-scale B=1024 programs
  # accumulate ~25 GB of compiled XLA CPU state, and one combined
  # heavy+default+mesh process was observed segfaulting inside a later
  # sharded pjit execution (2026-08-01) while every lane passes in
  # isolation — bound the per-process executable cache by splitting.
  # Marker-based selection: file-agnostic, and the second process runs
  # ONLY the heavy tests.
  echo "== heavy lane (separate process) =="
  COCONUT_TEST_HEAVY=1 python -m pytest tests/ -m heavy -q
fi

echo "== driver probes =="
# Compile (not just import) the flagship entry program and check its
# bits, exactly as the driver's compile-check does. Budget ~5.5 min on
# this host: the cost is dominated by Python tracing + host comb-table
# build (the persistent cache only removes the XLA compile), so treat
# this as the entry probe's expected wall time, not a cache miss.
python -c "
import __graft_entry__ as ge
fn, a = ge.entry()
import jax
assert bool(jax.jit(fn)(*a).all())
"
# Run the multi-chip dryrun exactly as the driver does (8-device virtual CPU
# mesh). tests/test_shard.py compiled these exact programs above, so this is
# warm-seconds from the persistent cache — and it keeps the cache seeded so
# the driver's MULTICHIP probe never pays a cold compile (VERDICT r3 item 1).
XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
  JAX_PLATFORMS=cpu \
  python -c "import __graft_entry__ as ge; ge.dryrun_multichip(8)"
echo "ci: ok"
