"""Driver benchmark — prints ONE JSON line with the north-star metric.

Metric (BASELINE.json): aggregated-credential verifies/sec, batch=1k,
6 attrs, 3-of-5 threshold. The work measured per credential is exactly the
reference's `Signature::verify` (signature.rs:472-478): one
(msg_count+1)-term OtherGroup MSM + one 2-pairing product check, run through
the fused JAX/TPU backend (coconut_tpu/tpu/backend.py).

`vs_baseline` is measured/target against the BASELINE.json north star of
10,000 verifies/sec (the reference itself publishes no numbers —
reference README.md:174-177).

Phase timers (VERDICT round-1 item 9): host encode, device kernel, readback.
Env knobs: BENCH_BATCH (default 1024), BENCH_REPS (default 3),
BENCH_BACKEND (jax|python, default jax).
"""

import json
import os
import sys
import time

NORTH_STAR = 10_000.0  # verifies/sec, BASELINE.json north_star


def main():
    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    backend_name = os.environ.get("BENCH_BACKEND", "jax")

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import __graft_entry__ as ge

    t0 = time.time()
    params, _, vk, sigs, msgs_list = ge._fixture(batch=batch)
    t_fixture = time.time() - t0

    extras = {
        "batch": batch,
        "backend": backend_name,
        "msg_count": ge.MSG_COUNT,
        "fixture_s": round(t_fixture, 3),
    }

    from coconut_tpu import metrics

    if backend_name == "python":
        from coconut_tpu.ps import ps_verify

        with metrics.timer("kernel"):
            bits = [
                ps_verify(s, m, vk, params) for s, m in zip(sigs, msgs_list)
            ]
        metrics.count("verifies", batch)
        dt = metrics.snapshot()["timers_s"]["kernel"]
        assert all(bits)
        value = batch / dt
        extras["kernel_s"] = round(dt, 3)
    else:
        import jax

        # persistent compile cache: the fused program takes minutes to build
        # over the tunnel; cache it across bench invocations
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
        import numpy as np

        from coconut_tpu.tpu.backend import JaxBackend, _fused_verify_kernel

        extras["device"] = str(jax.devices()[0])
        be = JaxBackend()

        # phase timers via the metrics module (SURVEY §5 observability):
        # one timing system, snapshotted into the JSON below
        with metrics.timer("encode"):
            operands = be.encode_verify_batch(sigs, msgs_list, vk, params)
        t_encode = metrics.snapshot()["timers_s"]["encode"]

        sig_is_g1 = params.ctx.name == "G1"
        with metrics.timer("compile_plus_run"):
            bits = _fused_verify_kernel(sig_is_g1, *operands)
            bits.block_until_ready()
        t_compile = metrics.snapshot()["timers_s"]["compile_plus_run"]

        times = []
        for _ in range(reps):
            t0 = time.time()
            with metrics.timer("kernel"):
                bits = _fused_verify_kernel(sig_is_g1, *operands)
                bits.block_until_ready()
            times.append(time.time() - t0)
            metrics.count("verifies", batch)
            metrics.count("batches")
        t_kernel = min(times)

        with metrics.timer("readback"):
            host_bits = np.asarray(bits)
        t_read = metrics.snapshot()["timers_s"]["readback"]
        assert bool(host_bits.all()), "verification bits wrong"

        value = batch / t_kernel
        extras.update(
            {
                "host_encode_s": round(t_encode, 3),
                "compile_plus_run_s": round(t_compile, 3),
                "kernel_s": round(t_kernel, 4),
                "readback_s": round(t_read, 5),
            }
        )

        if os.environ.get("BENCH_COMBINED", "0") == "1":
            # combined (small-exponents) batch verify: one bool per batch
            t0 = time.time()
            ok = be.batch_verify_combined(sigs, msgs_list, vk, params)
            t_comb_compile = time.time() - t0
            t0 = time.time()
            ok = be.batch_verify_combined(sigs, msgs_list, vk, params)
            t_comb = time.time() - t0
            assert ok is True
            extras.update(
                {
                    "combined_compile_plus_run_s": round(t_comb_compile, 3),
                    "combined_s": round(t_comb, 4),
                    "combined_verifies_per_sec": round(batch / t_comb, 2),
                }
            )

        if os.environ.get("BENCH_GROUPED", "1") == "1":
            # attribute-grouped combined verify: q+2 pairings total
            t0 = time.time()
            ok = be.batch_verify_grouped(sigs, msgs_list, vk, params)
            t_grp_compile = time.time() - t0
            t0 = time.time()
            ok = be.batch_verify_grouped(sigs, msgs_list, vk, params)
            t_grp = time.time() - t0
            assert ok is True
            extras.update(
                {
                    "grouped_compile_plus_run_s": round(t_grp_compile, 3),
                    "grouped_s": round(t_grp, 4),
                    "grouped_verifies_per_sec": round(batch / t_grp, 2),
                }
            )

    extras["metrics"] = metrics.snapshot()
    print(
        json.dumps(
            {
                "metric": "aggregated_credential_verifies_per_sec",
                "value": round(value, 2),
                "unit": "verifies/sec",
                "vs_baseline": round(value / NORTH_STAR, 4),
                **extras,
            }
        )
    )


if __name__ == "__main__":
    main()
