"""Driver benchmark — prints ONE JSON line with the north-star metric.

Metric (BASELINE.json): aggregated-credential verifies/sec, batch=1k,
6 attrs, 3-of-5 threshold. The work per credential is the reference's
`Signature::verify` (signature.rs:472-478): one (msg_count+1)-term
OtherGroup MSM + one 2-pairing product check.

The headline `value` is the attribute-grouped combined batch verification
(coconut_tpu/tpu/backend.py `fused_verify_grouped`): the standard
small-exponents batch-verify equation regrouped per verkey component, so a
1024-credential batch costs q+2 pairings TOTAL plus q+2 shared-point MSMs.
Semantics: ONE accept/reject boolean for the whole batch (soundness error
2^-128 per forged credential); per-credential bits come from the fused
per-credential kernel, reported as `percred_verifies_per_sec` (a failing
batch bisects to it). Both paths are differentially tested against the
pure-Python spec (tests/test_backends.py).

Also measured (BASELINE.md configs):
  config 3: batched PoKOfSignature verify (2 hidden / 4 revealed)  [default]
  config 4: threshold issuance, batched blind-sign MSMs            [default]
  config 5: short streamed run through verify_stream               [BENCH_STREAM=1]
  serve lane: loadgen against the online CredentialService         [--serve]
  issue lane: loadgen against the online IssuanceService           [--issue]
  session lane: full-session loadgen against the ProtocolEngine    [--session]
  gateway lane: RPC-vs-direct goodput through the fleet gateway    [--gateway]
  batchverify lane: RLC-combined vs exact verify/show-verify       [--batchverify]
    (ISSUE 16 — B in BENCH_BATCHVERIFY_SIZES, crossover point,
    <= 2 final exps per combined batch; BENCH_BATCHVERIFY=0 skips)
  state lane: show-verify goodput bare vs WAL-backed nullifiers    [--state]
    (ISSUE 17 — group-commit fsync per batch, ratio >=
    BENCH_STATE_MIN_RATIO (0.85); BENCH_STATE=0 skips)
  hashmsm lane: host-vs-device hash-to-G1 + Horner-vs-bucketed MSM [--hashmsm]
    (ISSUE 18 — bit parity + path selection asserted from counters
    everywhere, "new path faster" floor on the real chip only;
    BENCH_HASHMSM=0 skips)

Phase timers (VERDICT round-1 item 9): host encode, device kernel, readback.
Env knobs: BENCH_BATCH (default 1024), BENCH_REPS (default 5),
BENCH_BACKEND (jax|python), BENCH_PERCRED/BENCH_SHOW/BENCH_ISSUE (default 1),
BENCH_STREAM (default 1 — config 5 is driver-captured), BENCH_STREAM_BATCHES
(default 8), BENCH_ISSUE_N (default 1024), BENCH_COMBINED (default 0),
BENCH_MULTIVK (default 0 — 8-verkey rotation datapoint), BENCH_PROFILE
(default 0 — one traced rep of the headline to BENCH_PROFILE_DIR).

Serve lane (`python bench.py --serve`): closed-loop loadgen at saturation
against coconut_tpu/serve (dynamic batching, admission control), embedding
p50/p95/p99 request latency, goodput, mean batch occupancy, and rejection
counts in the same JSON line under "serve". Knobs: BENCH_SERVE_SECONDS
(default 2), BENCH_SERVE_MAX_BATCH (default 4), BENCH_SERVE_CONCURRENCY
(default 2*max_batch), BENCH_SERVE_MODE (per_credential|grouped),
BENCH_SERVE_FORGED (default 1 — forged credentials in the pool),
BENCH_OFFLINE=0 skips the offline lanes so `--serve` can run standalone
(the CPU smoke in ci.sh does exactly that). BENCH_SERVE_DEVICES="1,2,4,8"
additionally runs the dispatcher-pool device-count sweep — per pool size:
goodput, p99 latency, occupancy, per-device dispatch counts, and scaling
efficiency goodput_n/(n*goodput_1) — embedded under "serve"."scaling"
(BENCH_SERVE_SWEEP_SECONDS trims the per-point duration; on the jax
backend each executor pins to a real device, elsewhere executors are
unpinned workers).

Issue lane (`python bench.py --issue`): pure-issuance closed-loop loadgen
(issue_fraction=1.0) against a real BENCH_ISSUE_AUTHORITIES-of-
BENCH_ISSUE_THRESHOLD (default 5, t=3) IssuanceService — quorum fan-out,
first-t-of-n aggregation, verify-before-release on the hot path —
embedding credentials/sec, quorum-wait p50/p95/p99, hedge rate, and mint
outcome counts under "issue". Knobs: BENCH_ISSUE_SECONDS (default 2),
BENCH_ISSUE_MAX_BATCH (default 4), BENCH_ISSUE_CONCURRENCY (default
2*max_batch); BENCH_ISSUE=0 skips (the same gate as the offline config-4
blind-sign lane); composes with --serve and BENCH_OFFLINE=0.

Session lane (`python bench.py --session`): closed-loop FULL protocol
sessions (prepare -> mint -> show_prove -> show_verify, one credential
each) against an engine.ProtocolEngine running all five phases on one
executor pool — embedding sessions/sec, end-to-end session p50/p95/p99,
the per-phase latency breakdown, and the per-program jit-shape counters
(flat after warmup = no cross-program recompiles) under "session".
Knobs: BENCH_SESSION_SECONDS (default 2), BENCH_SESSION_MAX_BATCH
(default 4), BENCH_SESSION_CONCURRENCY (default 2*max_batch),
BENCH_SESSION_AUTHORITIES/BENCH_SESSION_THRESHOLD (default 3, t=2);
BENCH_SESSION=0 skips; composes with the other lanes and
BENCH_OFFLINE=0.

Gateway lane (`python bench.py --gateway`, ISSUE 13): the SAME warm
CredentialService measured twice back-to-back under the closed-loop
verify loadgen — direct submit calls, then through a net.Replica over a
real loopback TCP socket (CTS-RPC/1 frames both ways via
GatewayClient) — embedding both reports, the goodput ratio, and the
measured per-request rpc_overhead_s under "gateway". Asserts RPC
goodput >= BENCH_GATEWAY_MIN_RATIO (default 0.8) of direct. Knobs:
BENCH_GATEWAY_SECONDS (default 2), BENCH_GATEWAY_MAX_BATCH (default 4),
BENCH_GATEWAY_CONCURRENCY (default 2*max_batch); BENCH_GATEWAY=0 skips;
composes with the other lanes and BENCH_OFFLINE=0.

Lifecycle lane (`python bench.py --lifecycle`, ISSUE 14): the
warm-restart headline. A predecessor "process" (a simulated-compile
engine whose per-shape compile wall models BENCH_r05's 130-500 s
`*_compile_plus_run_s` floor at sub-second scale) serves a shape set,
drains through a real LifecycleController (shape manifest saved), then
two successors race to their first SLO-compliant response: COLD (no
manifest, no persistent compilation cache — every shape pays the full
wall) vs WARM (manifest replayed through warm_shapes + cache hits).
Embeds both restart numbers AND the measured compile_plus_run floor
under "lifecycle"; asserts warm <= BENCH_LIFECYCLE_MAX_FRACTION
(default 0.5) of cold. Knobs: BENCH_LIFECYCLE_COMPILE_S (default 0.3,
the per-shape simulated wall), BENCH_LIFECYCLE_SHAPES (default 3);
BENCH_LIFECYCLE=0 skips; composes with the other lanes and
BENCH_OFFLINE=0.

Key-lifecycle lane (`python bench.py --keylife`, ISSUE 15): goodput
before / during / after a live t/n reshare on a 5-authority engine born
from an online DKG — one proactive refresh plus one 3-of-5 -> 2-of-5
reshare land mid-traffic on a side thread while the closed-loop verify
loadgen keeps driving pre-rollover credentials. Embeds the three goodput
numbers, the during/before degradation ratio, and the after/before
rollover ratio under "keylife"; asserts the during phase stayed non-zero
(zero-downtime rollover) and zero dropped futures. Knobs:
BENCH_KEYLIFE_SECONDS (default 2), BENCH_KEYLIFE_MAX_BATCH (default 4),
BENCH_KEYLIFE_CONCURRENCY (default 2*max_batch); BENCH_KEYLIFE=0 skips;
composes with the other lanes and BENCH_OFFLINE=0.

Chaos-recovery sub-report (ISSUE 9, on by default with --serve;
BENCH_CHAOS=0 skips): a three-phase loadgen pass — clean, then one
injected executor crash + one hung dispatch, then post-fault — against a
BENCH_CHAOS_DEVICES-wide pool (default 4) with a fast watchdog and
probation ladder, embedded under "serve"."chaos_recovery": goodput
before/during/after, the recovery ratio, and the quarantine/watchdog/
redistribution counters. BENCH_CHAOS_SECONDS sets the per-phase duration
(default 0.8).
"""

import json
import os
import sys
import time

NORTH_STAR = 10_000.0  # verifies/sec, BASELINE.json north_star


def _timeit(fn, reps):
    """(best seconds, result) over reps calls."""
    best, out = None, None
    for _ in range(reps):
        t0 = time.time()
        out = fn()
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return best, out


def bench_python(batch, ge, params, vk, sigs, msgs_list, extras):
    from coconut_tpu import metrics
    from coconut_tpu.ps import ps_verify

    with metrics.timer("kernel"):
        bits = [ps_verify(s, m, vk, params) for s, m in zip(sigs, msgs_list)]
    metrics.count("verifies", batch)
    dt = metrics.snapshot()["timers_s"]["kernel"]
    assert all(bits)
    extras["kernel_s"] = round(dt, 3)
    return batch / dt


def bench_serve(ge, params, vk, sigs, msgs_list, extras, backend_name):
    """Online-serving lane: closed-loop loadgen at saturation against the
    dynamic-batching CredentialService; embeds the SLO report (p50/p95/p99
    latency, goodput, mean batch occupancy, rejection counts) under
    extras["serve"], plus a tracing-overhead probe (goodput with
    COCONUT_TRACE off vs on, BENCH_TRACE_OVERHEAD=0 to skip) under
    extras["serve"]["trace_overhead"]. Returns the goodput
    (requests/sec)."""
    from coconut_tpu.serve import CredentialService, run_loadgen
    from coconut_tpu.signature import Signature

    seconds = float(os.environ.get("BENCH_SERVE_SECONDS", "2"))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "4"))
    # 2x max_batch closed-loop clients saturate the coalescer: there is
    # always a full batch's worth of backlog, so occupancy reads the
    # batching ceiling rather than arrival luck
    concurrency = int(
        os.environ.get("BENCH_SERVE_CONCURRENCY", str(2 * max_batch))
    )
    mode = os.environ.get("BENCH_SERVE_MODE", "per_credential")
    max_wait_ms = float(os.environ.get("BENCH_SERVE_MAX_WAIT_MS", "20"))

    pool = [(s, m, True) for s, m in zip(sigs, msgs_list)]
    if os.environ.get("BENCH_SERVE_FORGED", "1") == "1":
        # forged credentials in the mix exercise the demux under load (and,
        # in grouped mode, the bisection ladder); the loadgen checks each
        # verdict against its expectation, so a demux bug surfaces as
        # verdict_mismatches, not as silent throughput
        for s, m in list(zip(sigs, msgs_list))[: max(1, len(sigs) // 8)]:
            forged = Signature(s.sigma_1, params.ctx.sig.mul(s.sigma_2, 2))
            pool.append((forged, m, False))

    svc = CredentialService(
        backend_name,
        vk,
        params,
        mode=mode,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
    )
    with svc:
        # warm the backend at the serving shape OUTSIDE the timed window
        # (on the jax backend the first batch pays compile time; the
        # loadgen's occupancy/latency deltas start after this settles)
        warm = [
            svc.submit(*pool[i % len(pool)][:2])
            for i in range(max_batch)
        ]
        for f in warm:
            f.result(timeout=600.0)
        report = run_loadgen(
            svc,
            pool,
            duration_s=seconds,
            arrival="closed",
            concurrency=concurrency,
        )
        trace_overhead = None
        if os.environ.get("BENCH_TRACE_OVERHEAD", "1") == "1":
            # tracing-overhead probe (ISSUE 6 acceptance: enabled-tracing
            # goodput within ~5% of disabled): two short back-to-back
            # closed-loop passes against the SAME warm service, tracing
            # off then on. Reported, not asserted — sub-second CPU lanes
            # are too noisy for a hard gate, the BENCH JSON is the audit
            # surface. BENCH_TRACE_OVERHEAD=0 skips.
            from coconut_tpu.obs import trace as otrace

            t_secs = float(os.environ.get("BENCH_TRACE_SECONDS", "1"))
            was_enabled = otrace.enabled()
            otrace.disable()
            off = run_loadgen(
                svc, pool, duration_s=t_secs,
                arrival="closed", concurrency=concurrency,
            )
            otrace.enable()
            on = run_loadgen(
                svc, pool, duration_s=t_secs,
                arrival="closed", concurrency=concurrency,
            )
            if not was_enabled:
                otrace.disable()
            off_g, on_g = off["goodput_per_s"], on["goodput_per_s"]
            trace_overhead = {
                "off_goodput_per_s": off_g,
                "on_goodput_per_s": on_g,
                "overhead_frac": (
                    round((off_g - on_g) / off_g, 4) if off_g else None
                ),
            }
    assert report["dropped_futures"] == 0, (
        "serve lane dropped futures: %r" % (report,)
    )
    assert report["verdict_mismatches"] == 0, (
        "serve lane verdict mismatch: %r" % (report,)
    )
    occ = report["mean_batch_occupancy"]
    assert occ is not None and occ > 0.5, (
        "serve lane under-coalesced at saturation "
        "(mean_batch_occupancy=%r): %r" % (occ, report)
    )
    extras["serve"] = {
        "mode": mode,
        "max_batch": max_batch,
        "max_wait_ms": max_wait_ms,
        **report,
        "trace_overhead": trace_overhead,
    }
    if os.environ.get("BENCH_SERVE_DEVICES"):
        extras["serve"]["scaling"] = _bench_serve_scaling(
            params, vk, pool, backend_name, mode, max_batch, max_wait_ms
        )
    if os.environ.get("BENCH_CHAOS", "1") == "1":
        extras["serve"]["chaos_recovery"] = _bench_chaos_recovery(
            params, vk, pool, backend_name, mode, max_batch, max_wait_ms
        )
    return report["goodput_per_s"]


def bench_issue(ge, params, vk, sigs, msgs_list, extras, backend_name):
    """Threshold-issuance lane (--issue): pure-issuance closed-loop
    loadgen (issue_fraction=1.0) against a REAL t-of-n IssuanceService —
    quorum fan-out, first-t-of-n aggregation, verify-before-release all
    on the hot path. Embeds credentials/sec, quorum-wait p50/p95/p99,
    and the hedge rate under extras["issue"]; returns the goodput
    (credentials/sec). BENCH_ISSUE=0 skips (same gate as the offline
    config-4 blind-sign lane)."""
    from coconut_tpu import metrics
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.issue import IssuanceService
    from coconut_tpu.keygen import trusted_party_SSS_keygen
    from coconut_tpu.serve import CredentialService, run_loadgen
    from coconut_tpu.signature import SignatureRequest
    from coconut_tpu.sss import rand_fr

    seconds = float(os.environ.get("BENCH_ISSUE_SECONDS", "2"))
    max_batch = int(os.environ.get("BENCH_ISSUE_MAX_BATCH", "4"))
    concurrency = int(
        os.environ.get("BENCH_ISSUE_CONCURRENCY", str(2 * max_batch))
    )
    total = int(os.environ.get("BENCH_ISSUE_AUTHORITIES", "5"))
    threshold = int(os.environ.get("BENCH_ISSUE_THRESHOLD", "3"))

    _, _, signers = trusted_party_SSS_keygen(threshold, total, params)
    ipool = []
    for _ in range(4 * max_batch):
        msgs = [rand_fr() for _ in range(ge.MSG_COUNT)]
        esk, epk = elgamal_keygen(params.ctx.sig, params.g)
        req, _ = SignatureRequest.new(msgs, 2, epk, params)
        ipool.append((req, msgs, esk))

    isvc = IssuanceService(
        signers, params, threshold, backend=backend_name,
        max_batch=max_batch,
    )
    # the mixed-workload loadgen drives a verify service too; at
    # issue_fraction=1.0 it sits idle but must exist and be started
    vsvc = CredentialService(
        backend_name, vk, params, max_batch=max_batch
    )
    with vsvc, isvc:
        # warm every authority at the serving shape OUTSIDE the timed
        # window (on the jax backend the first sign pays compile time)
        warm = [
            isvc.submit(*ipool[i % len(ipool)]) for i in range(max_batch)
        ]
        for f in warm:
            f.result(timeout=600.0)
        report = run_loadgen(
            vsvc,
            [(sigs[0], msgs_list[0], True)],
            duration_s=seconds,
            arrival="closed",
            concurrency=concurrency,
            issue_service=isvc,
            issue_pool=ipool,
            issue_fraction=1.0,
        )
    issue = report["issue"]
    assert issue["dropped_futures"] == 0, (
        "issue lane dropped futures: %r" % (issue,)
    )
    assert issue["mint_mismatches"] == 0, (
        "issue lane released a falsy mint: %r" % (issue,)
    )
    assert issue["errors"] == 0, "issue lane errors: %r" % (issue,)
    assert issue["minted"] > 0, "issue lane minted nothing: %r" % (issue,)
    qwait = (
        metrics.snapshot()
        .get("histograms", {})
        .get("issue_quorum_wait_s", {})
    )
    extras["issue"] = {
        "authorities": total,
        "threshold": threshold,
        "max_batch": max_batch,
        "concurrency": concurrency,
        **issue,
        "credentials_per_sec": issue["goodput_per_s"],
        "quorum_wait_s": {
            "p50": qwait.get("p50_s"),
            "p95": qwait.get("p95_s"),
            "p99": qwait.get("p99_s"),
        },
        "hedge_rate": (
            round(issue["hedges"] / issue["fanouts"], 4)
            if issue["fanouts"]
            else None
        ),
    }
    return issue["goodput_per_s"]


def bench_session(ge, params, extras, backend_name):
    """Full-session lane (--session): closed-loop FULL protocol sessions
    (prepare -> mint -> show_prove -> show_verify, one credential each)
    against a ProtocolEngine running all five phases on one executor
    pool. Embeds sessions/sec, end-to-end session p50/p95/p99, and the
    per-phase latency breakdown under extras["session"]; returns
    sessions/sec. Knobs: BENCH_SESSION_SECONDS (default 2),
    BENCH_SESSION_MAX_BATCH (default 4), BENCH_SESSION_CONCURRENCY
    (default 2*max_batch), BENCH_SESSION_AUTHORITIES /
    BENCH_SESSION_THRESHOLD (default 3, t=2); BENCH_SESSION=0 skips."""
    from coconut_tpu import metrics
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.engine import ProtocolEngine
    from coconut_tpu.keygen import trusted_party_SSS_keygen
    from coconut_tpu.serve import run_session_loadgen
    from coconut_tpu.sss import rand_fr

    seconds = float(os.environ.get("BENCH_SESSION_SECONDS", "2"))
    max_batch = int(os.environ.get("BENCH_SESSION_MAX_BATCH", "4"))
    concurrency = int(
        os.environ.get("BENCH_SESSION_CONCURRENCY", str(2 * max_batch))
    )
    total = int(os.environ.get("BENCH_SESSION_AUTHORITIES", "3"))
    threshold = int(os.environ.get("BENCH_SESSION_THRESHOLD", "2"))

    _, _, signers = trusted_party_SSS_keygen(threshold, total, params)
    pool = []
    for _ in range(4 * max_batch):
        msgs = [rand_fr() for _ in range(ge.MSG_COUNT)]
        esk, epk = elgamal_keygen(params.ctx.sig, params.g)
        pool.append((msgs, epk, esk))
    revealed = list(range(2, ge.MSG_COUNT))

    engine = ProtocolEngine(
        signers, params, threshold,
        count_hidden=2, revealed_msg_indices=revealed,
        backend=backend_name, max_batch=max_batch,
    )
    jit0 = {
        ns: metrics.get_count("%s_jit_shapes" % ns)
        for ns in ("serve", "prep", "prove", "showv")
    }
    with engine:
        # one full warmup session outside the timed window: every
        # program's serving shape compiles here, not in the report
        msgs, epk, esk = pool[0]
        req, _ = engine.submit_prepare(msgs, epk).result(600.0)
        cred = engine.submit_mint(req, msgs, esk).result(600.0)
        proof, chal, rev = engine.submit_show_prove(cred, msgs).result(600.0)
        assert engine.submit_show_verify(proof, rev, chal).result(600.0)
        jit_warm = {
            ns: metrics.get_count("%s_jit_shapes" % ns)
            for ns in ("serve", "prep", "prove", "showv")
        }
        report = run_session_loadgen(
            engine, pool, duration_s=seconds, concurrency=concurrency
        )
    jit_end = {
        ns: metrics.get_count("%s_jit_shapes" % ns)
        for ns in ("serve", "prep", "prove", "showv")
    }
    assert report["errors"] == 0, "session lane errors: %r" % (report,)
    assert report["failed_shows"] == 0, (
        "a minted credential failed show-verify: %r" % (report,)
    )
    assert report["sessions_completed"] > 0, (
        "session lane completed nothing: %r" % (report,)
    )
    extras["session"] = {
        "authorities": total,
        "threshold": threshold,
        "max_batch": max_batch,
        **report,
        # flat counters after warmup = heterogeneous traffic never
        # cross-program recompiled (the engine's multiplexing claim)
        "jit_shapes_after_warmup": jit_warm,
        "jit_shapes_after_run": jit_end,
        "jit_shapes_stable": jit_warm == jit_end,
        "jit_shapes_cold": jit0,
    }
    return report["sessions_per_s"]


def bench_gateway(ge, params, vk, sigs, msgs_list, extras, backend_name):
    """RPC-ingress lane (--gateway, ISSUE 13): measure the wire tax. The
    SAME warm CredentialService is driven twice back-to-back by the
    closed-loop verify loadgen — direct submit calls, then through a
    net.Replica serving CTS-RPC/1 frames on a real loopback TCP socket
    (SocketTransport + GatewayClient). Embeds both reports, the goodput
    ratio, and the measured per-request rpc_overhead_s under
    extras["gateway"]; asserts ratio >= BENCH_GATEWAY_MIN_RATIO
    (default 0.8). Returns the RPC goodput (requests/sec).
    BENCH_GATEWAY=0 skips."""
    from coconut_tpu import net
    from coconut_tpu.serve import CredentialService, run_loadgen

    seconds = float(os.environ.get("BENCH_GATEWAY_SECONDS", "2"))
    max_batch = int(os.environ.get("BENCH_GATEWAY_MAX_BATCH", "4"))
    concurrency = int(
        os.environ.get("BENCH_GATEWAY_CONCURRENCY", str(2 * max_batch))
    )
    min_ratio = float(os.environ.get("BENCH_GATEWAY_MIN_RATIO", "0.8"))

    pool = [(s, m, True) for s, m in zip(sigs, msgs_list)][: 8 * max_batch]
    codec = net.WireCodec(params)
    svc = CredentialService(
        backend_name, vk, params, max_batch=max_batch, max_wait_ms=20.0
    )
    replica = net.Replica(svc, codec, replica_id="bench-r0")
    with svc:
        # warm the backend at the serving shape outside both timed passes
        warm = [
            svc.submit(*pool[i % len(pool)][:2]) for i in range(max_batch)
        ]
        for f in warm:
            f.result(timeout=600.0)
        direct = run_loadgen(
            svc, pool, duration_s=seconds, arrival="closed",
            concurrency=concurrency,
        )
        replica.serve()
        client = net.GatewayClient(net.SocketTransport(replica.address),
                                   codec)
        try:
            rpc = run_loadgen(
                client, pool, duration_s=seconds, arrival="closed",
                concurrency=concurrency, transport="rpc",
            )
        finally:
            client.close()
            replica.close()
    for name, rep in (("direct", direct), ("rpc", rpc)):
        assert rep["completed"] > 0, (
            "gateway lane %s pass completed nothing: %r" % (name, rep)
        )
        assert rep["dropped_futures"] == 0, (
            "gateway lane %s pass dropped futures: %r" % (name, rep)
        )
        assert rep["verdict_mismatches"] == 0, (
            "gateway lane %s pass verdict mismatch: %r" % (name, rep)
        )
    ratio = (
        round(rpc["goodput_per_s"] / direct["goodput_per_s"], 4)
        if direct["goodput_per_s"]
        else None
    )
    assert ratio is not None and ratio >= min_ratio, (
        "RPC ingress costs too much: rpc/direct goodput ratio %r < %r "
        "(direct=%r rpc=%r)"
        % (ratio, min_ratio, direct["goodput_per_s"],
           rpc["goodput_per_s"])
    )
    extras["gateway"] = {
        "max_batch": max_batch,
        "concurrency": concurrency,
        "min_ratio": min_ratio,
        "goodput_ratio": ratio,
        "direct": direct,
        "rpc": rpc,
    }
    return rpc["goodput_per_s"]


def bench_state(ge, params, extras, backend_name):
    """Durable-state lane (--state, ISSUE 17): the WAL tax. The same
    show-verify traffic is driven twice through a ProtocolEngine —
    first bare, then with a StateStore-backed nullifier guard (device
    membership probe + group-commit WAL append per batch) — and the
    goodput ratio must stay >= BENCH_STATE_MIN_RATIO (default 0.85).
    Every show is a FRESH re-randomization of one credential, so every
    lane commits a new nullifier: the durable pass pays the full
    journal cost, one fsync per engine batch (group commit), never one
    per lane — the artifact embeds wal_appends vs wal_fsyncs to prove
    the policy. Knobs: BENCH_STATE_SHOWS (default 64),
    BENCH_STATE_MAX_BATCH (default 4); BENCH_STATE=0 skips."""
    import tempfile

    from coconut_tpu import metrics
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.engine import ProtocolEngine
    from coconut_tpu.keygen import trusted_party_SSS_keygen
    from coconut_tpu.sss import rand_fr
    from coconut_tpu.state import StateStore

    n_shows = int(os.environ.get("BENCH_STATE_SHOWS", "64"))
    max_batch = int(os.environ.get("BENCH_STATE_MAX_BATCH", "4"))
    min_ratio = float(os.environ.get("BENCH_STATE_MIN_RATIO", "0.85"))

    _, _, signers = trusted_party_SSS_keygen(2, 3, params)
    revealed = list(range(2, ge.MSG_COUNT))
    msgs = [rand_fr() for _ in range(ge.MSG_COUNT)]
    esk, epk = elgamal_keygen(params.ctx.sig, params.g)

    def _run_pass(store):
        """One timed show-verify pass; returns (goodput, commits)."""
        engine = ProtocolEngine(
            signers, params, 2,
            count_hidden=2, revealed_msg_indices=revealed,
            backend=backend_name, max_batch=max_batch,
            state_store=store,
        )
        with engine:
            req, _ = engine.submit_prepare(msgs, epk).result(600.0)
            cred = engine.submit_mint(req, msgs, esk).result(600.0)
            # each lane shows a FRESH re-randomization: distinct
            # nullifiers, so the durable pass commits on every lane
            # (+1 warm show outside the timed window)
            shows = [
                engine.submit_show_prove(cred, msgs).result(600.0)
                for _ in range(n_shows + 1)
            ]
            proof, chal, rev = shows[0]
            assert engine.submit_show_verify(proof, rev, chal).result(600.0)
            c0 = metrics.get_count("nullifier_commits")
            t0 = time.time()
            futs = [
                engine.submit_show_verify(p, r, c)
                for p, c, r in shows[1:]
            ]
            ok = sum(1 for f in futs if f.result(600.0) is True)
            dt = time.time() - t0
            assert ok == n_shows, (
                "state lane: %d of %d fresh shows verified" % (ok, n_shows)
            )
        return n_shows / dt, metrics.get_count("nullifier_commits") - c0

    goodput_bare, _ = _run_pass(None)
    wal_appends0 = metrics.get_count("wal_appends")
    wal_fsyncs0 = metrics.get_count("wal_fsyncs")
    root = tempfile.mkdtemp(prefix="bench-state-")
    try:
        store = StateStore(root, replica_id="bench-r0")
        goodput_store, commits = _run_pass(store)
        store.close()
    finally:
        import shutil

        shutil.rmtree(root, ignore_errors=True)
    wal_appends = metrics.get_count("wal_appends") - wal_appends0
    wal_fsyncs = metrics.get_count("wal_fsyncs") - wal_fsyncs0

    assert commits == n_shows, (
        "durable pass committed %d nullifiers for %d timed shows"
        % (commits, n_shows)
    )
    # THE fsync policy: group commit per engine batch, never per lane —
    # with max_batch-wide batches the sync count stays well under the
    # lane count (each batch is one append_many = one fsync)
    assert wal_fsyncs <= (n_shows + 1 + max_batch - 1) // max_batch + n_shows // 2, (
        "fsync count %d looks per-lane, not per-batch (%d lanes, "
        "max_batch=%d)" % (wal_fsyncs, n_shows + 1, max_batch)
    )
    assert wal_fsyncs < wal_appends or n_shows < max_batch, (
        "group commit never amortized: %d fsyncs for %d appends"
        % (wal_fsyncs, wal_appends)
    )
    ratio = (
        round(goodput_store / goodput_bare, 4) if goodput_bare else None
    )
    assert ratio is not None and ratio >= min_ratio, (
        "durable nullifier set costs too much: with-store/bare goodput "
        "ratio %r < %r (bare=%r store=%r)"
        % (ratio, min_ratio, goodput_bare, goodput_store)
    )
    extras["state"] = {
        "fsync_policy": "group_commit_per_batch",
        "shows": n_shows,
        "max_batch": max_batch,
        "min_ratio": min_ratio,
        "goodput_bare_per_s": round(goodput_bare, 2),
        "goodput_store_per_s": round(goodput_store, 2),
        "goodput_ratio": ratio,
        "nullifier_commits": commits,
        "wal_appends": wal_appends,
        "wal_fsyncs": wal_fsyncs,
    }
    return ratio


def bench_hashmsm(ge, params, extras, backend_name):
    """Hash/MSM lane (--hashmsm, ISSUE 18): the last two PROFILE_r05
    walls, old vs new path, BOTH asserted bit-identical. (1) prepare's
    hash stage: the host path (native cc_hash_to_g1_batch if built,
    else the Python spec) against the device SvdW kernel, messages/s.
    (2) show-prove's sigma MSM stage: the signed-Horner distinct MSM
    against the bucketed Pippenger schedule at a forced window, rows/s.
    Parity is asserted from the outputs AND from counters (the device
    batches/fallbacks and bucketed/horner dispatch counts embedded in
    the artifact). The "new path faster" floor is enforced only on the
    real chip — on the CPU CI mesh the lane proves parity + path
    selection, per the ISSUE 18 acceptance split. Knobs:
    BENCH_HASHMSM_B (default 64), BENCH_HASHMSM_K (default 32),
    BENCH_HASHMSM_WINDOW (default 5), BENCH_HASHMSM_REPS (default 3);
    BENCH_HASHMSM=0 skips."""
    import random as _random

    import jax

    from coconut_tpu import metrics, native
    from coconut_tpu.ops.curve import G1_GEN, g1
    from coconut_tpu.ops.fields import R as _FR
    from coconut_tpu.tpu import backend as tb

    B = int(os.environ.get("BENCH_HASHMSM_B", "64"))
    k = int(os.environ.get("BENCH_HASHMSM_K", "32"))
    window = int(os.environ.get("BENCH_HASHMSM_WINDOW", "5"))
    reps = int(os.environ.get("BENCH_HASHMSM_REPS", "3"))
    on_tpu = jax.default_backend() == "tpu"

    be = tb.JaxBackend()
    rng = _random.Random(0x18)

    def best_of(fn):
        best = None
        for _ in range(reps):
            t0 = time.time()
            out = fn()
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        return out, best

    # -- prepare hash stage: host path vs device SvdW kernel ------------
    datas = [b"bench-hashmsm-%d" % i for i in range(B)]
    if native.available():
        old_name = "native"
        old_pts, t_old = best_of(
            lambda: list(native.hash_to_g1_batch(datas))
        )
    else:
        old_name = "spec"
        old_pts, t_old = best_of(
            lambda: [params.ctx.hash_to_sig(d) for d in datas]
        )
    be.hash_to_g1_batch(datas)  # warm/compile outside the clock
    hb0 = metrics.get_count("device_hash_batches")
    hf0 = metrics.get_count("device_hash_fallbacks")
    new_pts, t_new = best_of(lambda: be.hash_to_g1_batch(datas))
    hash_batches = metrics.get_count("device_hash_batches") - hb0
    hash_fallbacks = metrics.get_count("device_hash_fallbacks") - hf0
    assert new_pts == old_pts, "device hash diverges from %s" % old_name
    assert hash_batches == reps and hash_fallbacks == 0, (
        "device path not taken: batches=%d fallbacks=%d"
        % (hash_batches, hash_fallbacks)
    )

    # -- show-prove MSM stage: Horner vs bucketed Pippenger -------------
    pts = [
        [g1.mul(G1_GEN, rng.randrange(1, _FR)) for _ in range(k)]
        for _ in range(B)
    ]
    scal = [[rng.randrange(_FR) for _ in range(k)] for _ in range(B)]
    scal[0][0] = 0
    mode0 = tb._BUCKET_MODE
    try:
        tb._BUCKET_MODE = "off"
        be.msm_g1_distinct(pts, scal)  # warm
        h0 = metrics.get_count("msm_horner_dispatches")
        msm_old, t_msm_old = best_of(
            lambda: be.msm_g1_distinct(pts, scal)
        )
        horner_disp = metrics.get_count("msm_horner_dispatches") - h0
        tb._BUCKET_MODE = window
        be.msm_g1_distinct(pts, scal)  # warm
        b0 = metrics.get_count("msm_bucketed_dispatches")
        msm_new, t_msm_new = best_of(
            lambda: be.msm_g1_distinct(pts, scal)
        )
        bucket_disp = metrics.get_count("msm_bucketed_dispatches") - b0
    finally:
        tb._BUCKET_MODE = mode0
    assert msm_new == msm_old, "bucketed MSM diverges from Horner"
    assert horner_disp == reps and bucket_disp == reps, (
        "MSM path selection wrong: horner=%d bucketed=%d"
        % (horner_disp, bucket_disp)
    )

    hash_speedup = round(t_old / t_new, 4) if t_new else None
    msm_speedup = (
        round(t_msm_old / t_msm_new, 4) if t_msm_new else None
    )

    # -- measured vs model crossover (PR 19, --calibrate companion) -----
    # The cost model picks the schedule; this records whether the LIVE
    # measurement at the benchmark shape agrees, plus where the model
    # puts the crossover (pure arithmetic — probes/probe_pippenger.py
    # --calibrate is the multi-shape measured sweep).
    glv_k = 2 * k if tb._GLV_ENABLED else k
    nbits = 128 if tb._GLV_ENABLED else 255
    model_bucket = tb._bucket_cost(glv_k, nbits, window)
    model_horner = tb._horner_cost(glv_k, nbits)
    model_cross_k = next(
        (
            kk
            for kk in range(1, 4097)
            if min(
                tb._bucket_cost(kk, nbits, w) for w in range(2, 9)
            )
            < tb._horner_cost(kk, nbits)
        ),
        None,
    )
    measured_winner = (
        "bucket" if msm_speedup and msm_speedup > 1.0 else "horner"
    )
    model_winner = "bucket" if model_bucket < model_horner else "horner"
    if on_tpu:
        # the acceptance floor only binds on the device backend
        assert hash_speedup and hash_speedup > 1.0, (
            "device hash slower than %s at B=%d: x%r"
            % (old_name, B, hash_speedup)
        )
        assert msm_speedup and msm_speedup > 1.0, (
            "bucketed MSM slower than Horner at B=%d k=%d: x%r"
            % (B, k, msm_speedup)
        )
    extras["hashmsm"] = {
        "b": B,
        "k": k,
        "window": window,
        "hash_old_path": old_name,
        "hash_old_per_s": round(B / t_old, 2) if t_old else None,
        "hash_new_per_s": round(B / t_new, 2) if t_new else None,
        "hash_speedup": hash_speedup,
        "msm_old_per_s": round(B / t_msm_old, 2) if t_msm_old else None,
        "msm_new_per_s": round(B / t_msm_new, 2) if t_msm_new else None,
        "msm_speedup": msm_speedup,
        "device_hash_batches": hash_batches,
        "device_hash_fallbacks": hash_fallbacks,
        "msm_horner_dispatches": horner_disp,
        "msm_bucketed_dispatches": bucket_disp,
        "msm_bucket_window": metrics.get_gauge("msm_bucket_window"),
        "calibration": {
            "effective_k": glv_k,
            "model_bucket_cost": round(model_bucket, 1),
            "model_horner_cost": round(model_horner, 1),
            "model_winner": model_winner,
            "measured_winner": measured_winner,
            "model_measured_agree": model_winner == measured_winner,
            "model_crossover_k": model_cross_k,
        },
        "parity_ok": True,
        "timing_floor_enforced": on_tpu,
    }
    return hash_speedup or 0.0


def bench_scenarios(ge, params, extras, backend_name):
    """Application-scenario lane (--scenarios, PR 19): a sustained
    mixed petition/e-cash/access population run against a local
    ProtocolEngine with an ElasticController in the loop, arrivals on
    a compressed diurnal "day" with one flash crowd. The artifact
    embeds the full availability timeline; the lane asserts the ISSUE
    19 acceptance bar: goodput tracks the diurnal curve (peak-half
    completions beat the trough half), the elastic pool size responds
    (at least one park or unpark), p99 stays inside the SLO through
    the flash crowd, every deliberate double-spend/re-sign is a typed
    terminal rejection, and there are zero dangling futures and zero
    unattributed errors. Knobs: BENCH_SCENARIOS_S (day length, default
    48), BENCH_SCENARIOS_BASE/_PEAK (arrival rates, default 0.25/1.0),
    BENCH_SCENARIOS_SLO_S (default 10); BENCH_SCENARIOS=0 skips."""
    import tempfile

    from coconut_tpu import metrics
    from coconut_tpu.engine import ProtocolEngine
    from coconut_tpu.engine.lifecycle import (
        ElasticController,
        ElasticPolicy,
    )
    from coconut_tpu.keygen import trusted_party_SSS_keygen
    from coconut_tpu.scenarios import (
        AccessScenario,
        DiurnalCurve,
        EcashScenario,
        FlashCrowd,
        PetitionScenario,
        Population,
        PopulationDriver,
        RateSchedule,
        ScenarioReport,
    )
    from coconut_tpu.state import StateStore

    duration = float(os.environ.get("BENCH_SCENARIOS_S", "48"))
    base_rate = float(os.environ.get("BENCH_SCENARIOS_BASE", "0.25"))
    peak_rate = float(os.environ.get("BENCH_SCENARIOS_PEAK", "1.0"))
    slo_s = float(os.environ.get("BENCH_SCENARIOS_SLO_S", "10"))

    metrics.reset()
    _, _, signers = trusted_party_SSS_keygen(2, 3, params)
    revealed = list(range(2, ge.MSG_COUNT))
    root = tempfile.mkdtemp(prefix="bench-scenarios-")
    store = StateStore(root, replica_id="bench-scn")
    engine = ProtocolEngine(
        signers, params, 2,
        count_hidden=2, revealed_msg_indices=revealed,
        backend=backend_name, devices=2, max_batch=8,
        max_wait_ms=5.0, state_store=store,
    )
    # phase the diurnal curve so the run STARTS at the trough, peaks
    # mid-day, and returns to the trough — the elastic controller
    # should shrink at the edges and grow through the middle
    curve = DiurnalCurve(base_rate, peak_rate, duration)
    crowd = FlashCrowd(
        at_s=duration * 0.5, duration_s=duration * 0.12,
        multiplier=2.0, ramp_s=duration * 0.05,
    )
    report = ScenarioReport(slo_s=slo_s, flash_window=crowd.window())
    try:
        with engine:
            # one full warmup session outside the run: every program's
            # serving shape compiles here, not inside the SLO window
            from coconut_tpu.elgamal import elgamal_keygen
            from coconut_tpu.sss import rand_fr

            w_msgs = [rand_fr() for _ in range(ge.MSG_COUNT)]
            w_esk, w_epk = elgamal_keygen(params.ctx.sig, params.g)
            req, _ = engine.submit_prepare(w_msgs, w_epk).result(600.0)
            cred = engine.submit_mint(req, w_msgs, w_esk).result(600.0)
            proof, chal, rev = engine.submit_show_prove(
                cred, w_msgs
            ).result(600.0)
            assert engine.submit_show_verify(proof, rev, chal).result(600.0)

            elastic = ElasticController(
                engine,
                policy=ElasticPolicy(
                    min_executors=1, grow_after=2, shrink_after=3
                ),
            )
            mix = [
                (2.0, PetitionScenario(
                    engine, params, campaigns=4, resign_p=0.15,
                )),
                (2.0, EcashScenario(
                    engine, params, double_spend_p=0.15,
                )),
                (1.0, AccessScenario(
                    engine, params, session_range=(2, 3),
                )),
            ]
            driver = PopulationDriver(
                Population(128, n_tenants=8, seed=0x19),
                mix,
                RateSchedule(curve, [crowd]),
                duration,
                max_in_flight=64,
                seed=0x19,
                report=report,
                engine=engine,
                elastic=elastic,
                drain_timeout_s=120.0,
            )
            out = driver.run()
    finally:
        store.close()
        import shutil

        shutil.rmtree(root, ignore_errors=True)

    totals = out["totals"]
    # zero unattributed errors, zero dangling futures
    assert totals["failed"] == 0, (
        "unattributed scenario errors: %r" % (out["error_codes"],)
    )
    assert totals["cancelled"] == 0, "dangling futures after drain"
    assert totals["completed"] > 0, "no workflow completed"
    # every deliberate double-spend / re-sign is a TYPED rejection
    rejections = out["rejections"]
    rejected_n = sum(sum(r.values()) for r in rejections.values())
    labels = set()
    for per in rejections.values():
        labels.update(per)
    assert rejected_n > 0, (
        "adversarial fractions produced no rejection — detector dead?"
    )
    assert labels == {"double_spend"}, (
        "rejections carry unexpected labels: %r" % (rejections,)
    )
    # goodput tracks the diurnal curve: completions per second through
    # the mid-day peak beat the OPENING trough quarter (the closing
    # quarter is not comparable — the drain flushes mid-day backlog
    # into it, so completions bunch there regardless of arrival rate)
    good = out["availability"]["per_second_goodput"]
    day = good[: int(duration)]
    q = len(day) // 4
    mid = day[q : len(day) - q]
    opening = day[:q]
    mid_rate = sum(mid) / max(1, len(mid))
    trough_rate = sum(opening) / max(1, len(opening))
    assert mid_rate > trough_rate, (
        "goodput does not track the diurnal curve: peak-half %.2f/s "
        "vs opening trough %.2f/s" % (mid_rate, trough_rate)
    )
    # the elastic pool responded to the swing
    elastic_out = out["elastic"]
    pool_moved = (
        (elastic_out["grown"] or 0) + (elastic_out["shrunk"] or 0) > 0
    )
    assert pool_moved, (
        "elastic pool never changed size: %r" % (elastic_out,)
    )
    # p99 stays in SLO through the flash crowd (when the window saw
    # any completions at all)
    flash_p99 = out["slo"]["flash_p99_s"]
    if out["slo"]["flash_completed"]:
        assert flash_p99 is not None and flash_p99 <= slo_s, (
            "flash-crowd p99 %.2fs blew the %.1fs SLO" % (flash_p99, slo_s)
        )

    extras["scenarios"] = {
        "duration_s": duration,
        "base_rate": base_rate,
        "peak_rate": peak_rate,
        "slo_s": slo_s,
        "flash_window": crowd.window(),
        "goodput_peak_half_per_s": round(mid_rate, 3),
        "goodput_trough_per_s": round(trough_rate, 3),
        "report": out,
    }
    return out["goodput_per_s"] or 0.0


def bench_lifecycle(extras):
    """Warm-restart lane (--lifecycle, ISSUE 14): restart-to-first-SLO-
    compliant-response, cold vs warm. The compile wall is SIMULATED
    (BENCH_r05's 130-500 s per-shape `*_compile_plus_run_s` floor scaled
    to BENCH_LIFECYCLE_COMPILE_S seconds) so the lane runs in CI
    seconds, but the lifecycle machinery is REAL: a LifecycleController
    drains the predecessor (manifest saved), the warm successor replays
    that manifest through engine.warm_shapes with persistent-cache hits,
    and readiness gates on the replay. Embeds the cold floor, both
    restart numbers, and their ratio under extras["lifecycle"]; asserts
    warm <= BENCH_LIFECYCLE_MAX_FRACTION * cold and that the warm
    successor never pays a full compile wall. Returns the speedup
    (cold / warm). BENCH_LIFECYCLE=0 skips."""
    import tempfile

    from coconut_tpu.engine.lifecycle import (
        LifecycleController,
        ShapeManifest,
    )

    compile_s = float(os.environ.get("BENCH_LIFECYCLE_COMPILE_S", "0.3"))
    n_shapes = int(os.environ.get("BENCH_LIFECYCLE_SHAPES", "3"))
    max_fraction = float(
        os.environ.get("BENCH_LIFECYCLE_MAX_FRACTION", "0.5")
    )
    #: cache-deserialize cost as a fraction of a full compile — JAX's
    #: persistent cache loads in seconds what XLA builds in minutes
    CACHE_HIT_FRACTION, RUN_S = 0.05, 0.002
    persistent_cache = {}  # the simulated jax_compilation_cache_dir

    class SimCompileEngine:
        """Every NEW shape pays the compile wall; a persistent-cache hit
        pays the deserialize fraction. warm_shapes is the manifest-replay
        seam, exactly like ExecutionEngine's."""

        def __init__(self, name, cache=None):
            self.name = name
            self.cache = cache  # None = no persistent cache wired
            self._compiled = set()
            self._shapes = set()
            self.full_walls = 0

        def shape_keys(self):
            return set(self._shapes)

        def _ensure(self, shape):
            if shape in self._compiled:
                return
            if self.cache is not None and shape in self.cache:
                time.sleep(compile_s * CACHE_HIT_FRACTION)
            else:
                time.sleep(compile_s)
                self.full_walls += 1
                if self.cache is not None:
                    self.cache[shape] = True
            self._compiled.add(shape)

        def warm_shapes(self, shapes):
            warmed = 0
            for prog, placement, shape in shapes:
                self._ensure(shape)
                self._shapes.add((prog, placement, shape))
                warmed += 1
            return warmed, 0

        def serve_one(self, shape):
            self._ensure(shape)
            time.sleep(RUN_S)
            self._shapes.add(("verify", "single", shape))

        def drain(self, timeout=None):
            return True

    shapes = [(2 ** i,) for i in range(n_shapes)]
    manifest_path = os.path.join(
        tempfile.mkdtemp(prefix="coconut-bench-lifecycle-"), "shapes.json"
    )

    def restart(name, cache, path):
        """One successor boot: controller boot (manifest replay when
        `path` names one) then first response at EVERY serving shape.
        Returns seconds from restart start to the last first-response —
        the restart-to-first-SLO-compliant-response number."""
        eng = SimCompileEngine(name, cache=cache)
        lc = LifecycleController(eng, manifest_path=path)
        t0 = time.monotonic()
        assert lc.boot() is not None and lc.ready()
        for s in shapes:
            eng.serve_one(s)
        return time.monotonic() - t0, eng

    # predecessor: pays the true cold floor, then drains + saves
    pred = SimCompileEngine("pred", cache=persistent_cache)
    pred_lc = LifecycleController(pred, manifest_path=manifest_path)
    pred_lc.boot()
    t0 = time.monotonic()
    for s in shapes:
        pred.serve_one(s)
    floor_s = time.monotonic() - t0
    assert pred_lc.begin_drain(timeout=30.0)
    manifest_shapes = len(ShapeManifest.load(manifest_path))
    assert manifest_shapes == n_shapes, (
        "predecessor manifest lost shapes: %d of %d"
        % (manifest_shapes, n_shapes)
    )

    # cold: no manifest, no cache — the pre-PR-14 restart experience
    cold_s, cold_eng = restart("cold", None, None)
    # warm: manifest replay + persistent-cache hits, readiness gated
    warm_s, warm_eng = restart("warm", persistent_cache, manifest_path)

    assert cold_eng.full_walls == n_shapes
    assert warm_eng.full_walls == 0, (
        "warm successor paid %d full compile walls" % warm_eng.full_walls
    )
    assert warm_s <= max_fraction * cold_s, (
        "warm restart is not cheap enough: %.3fs vs %.3fs cold "
        "(fraction %.2f > %.2f)"
        % (warm_s, cold_s, warm_s / cold_s, max_fraction)
    )
    extras["lifecycle"] = {
        "shapes": n_shapes,
        "simulated_compile_s": compile_s,
        "compile_plus_run_floor_s": round(floor_s, 4),
        "cold_restart_to_first_slo_s": round(cold_s, 4),
        "warm_restart_to_first_slo_s": round(warm_s, 4),
        "warm_over_cold": round(warm_s / cold_s, 4),
        "max_fraction": max_fraction,
        "manifest_shapes": manifest_shapes,
    }
    return cold_s / warm_s


def bench_keylife(ge, params, extras, backend_name):
    """Key-lifecycle lane (--keylife, ISSUE 15): goodput before / during /
    after a live t/n reshare. A 5-authority engine born from an ONLINE
    DKG serves closed-loop verify traffic; mid-run the lifecycle takes
    one proactive refresh AND one 3-of-5 -> 2-of-5 reshare on a side
    thread while the loadgen keeps driving pre-rollover credentials.
    Embeds the three goodput numbers, the during/before degradation
    ratio, and the after/before rollover ratio under extras["keylife"];
    asserts the during phase stayed NON-ZERO (rollover never blacked out
    serving) and that zero futures dropped across all three phases.
    Returns the after-rollover goodput. Knobs: BENCH_KEYLIFE_SECONDS
    (default 2), BENCH_KEYLIFE_MAX_BATCH (default 4),
    BENCH_KEYLIFE_CONCURRENCY (default 2*max_batch);
    BENCH_KEYLIFE=0 skips."""
    import threading

    from coconut_tpu import metrics
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.engine import ProtocolEngine
    from coconut_tpu.keylife import KeyLifecycleManager
    from coconut_tpu.serve import run_loadgen
    from coconut_tpu.sss import rand_fr

    seconds = float(os.environ.get("BENCH_KEYLIFE_SECONDS", "2"))
    max_batch = int(os.environ.get("BENCH_KEYLIFE_MAX_BATCH", "4"))
    concurrency = int(
        os.environ.get("BENCH_KEYLIFE_CONCURRENCY", str(2 * max_batch))
    )
    threshold, total = 3, 5

    mgr = KeyLifecycleManager(params, label=b"bench-keylife", window=3)
    ks1 = mgr.bootstrap(threshold, total)
    revealed = list(range(2, ge.MSG_COUNT))
    engine = ProtocolEngine(
        list(ks1.signers), params, threshold,
        count_hidden=2, revealed_msg_indices=revealed,
        vk=ks1.vk, backend=backend_name, max_batch=max_batch,
        keychain=mgr.registry,
    )
    mgr.attach(engine)

    class _VerifyFacade:
        """run_loadgen's verify surface (.submit) over the engine."""

        @staticmethod
        def submit(sig, messages, lane="interactive"):
            return engine.submit_verify(sig, messages, lane=lane)

    facade = _VerifyFacade()
    with engine:
        # pre-rollover credential pool, minted under epoch 1 — the
        # traffic the reshare must keep serving
        pool = []
        for _ in range(4 * max_batch):
            msgs = [rand_fr() for _ in range(ge.MSG_COUNT)]
            esk, epk = elgamal_keygen(params.ctx.sig, params.g)
            req, _ = engine.submit_prepare(msgs, epk).result(600.0)
            cred = engine.submit_mint(req, msgs, esk).result(600.0)
            pool.append((cred, msgs, True))
        assert all(c.epoch == 1 for c, _m, _e in pool)
        warm = [
            facade.submit(*pool[i % len(pool)][:2])
            for i in range(max_batch)
        ]
        for f in warm:
            f.result(timeout=600.0)

        def phase(duration):
            return run_loadgen(
                facade, pool, duration_s=duration,
                arrival="closed", concurrency=concurrency,
            )

        before = phase(seconds)
        rollover_err = []

        def rollover():
            try:
                ks1r = mgr.refresh()
                assert ks1r.vk.to_bytes(params.ctx) == ks1.vk.to_bytes(
                    params.ctx
                )
                mgr.reshare(threshold=2, total=total)
            except Exception as e:  # pragma: no cover - surfaced below
                rollover_err.append(e)

        t = threading.Thread(target=rollover, daemon=True)
        t.start()
        during = phase(max(seconds, 1.0))
        t.join(120.0)
        assert not t.is_alive(), "rollover thread hung under traffic"
        assert not rollover_err, "rollover failed: %r" % (rollover_err,)
        after = phase(seconds)
    for name, rep in (
        ("before", before), ("during", during), ("after", after)
    ):
        assert rep["dropped_futures"] == 0, (
            "keylife lane %s phase dropped futures: %r" % (name, rep)
        )
        assert rep["verdict_mismatches"] == 0, (
            "keylife lane %s phase verdict mismatch: %r" % (name, rep)
        )
    assert during["goodput_per_s"] > 0, (
        "reshare blacked out serving: %r" % (during,)
    )
    degradation = (
        round(during["goodput_per_s"] / before["goodput_per_s"], 4)
        if before["goodput_per_s"]
        else None
    )
    extras["keylife"] = {
        "authorities": total,
        "threshold_before": threshold,
        "threshold_after": 2,
        "max_batch": max_batch,
        "concurrency": concurrency,
        "seconds_per_phase": seconds,
        "goodput_per_s": {
            "before": before["goodput_per_s"],
            "during": during["goodput_per_s"],
            "after": after["goodput_per_s"],
        },
        "degradation_ratio": degradation,
        "rollover_ratio": (
            round(after["goodput_per_s"] / before["goodput_per_s"], 4)
            if before["goodput_per_s"]
            else None
        ),
        "refreshes": metrics.get_count("keylife_refreshes"),
        "reshares": metrics.get_count("keylife_reshares"),
    }
    return after["goodput_per_s"]


def bench_batchverify(ge, params, vk, sigs, msgs_list, extras,
                      backend_name):
    """Batched-pairing-verification lane (--batchverify, ISSUE 16):
    device time of the RLC-combined check (ONE multi-Miller product +
    ONE shared final exponentiation per batch) vs the exact per-lane
    path, for plain verify AND show-verify, at each batch width in
    BENCH_BATCHVERIFY_SIZES (default 64,256,1024 — widths above the
    fixture batch recycle fixture credentials). Embeds per-width
    timings, speedups, the smallest width where batched wins
    ("crossover_b"), and the soundness parameter under
    extras["batchverify"]; asserts every combined batch cost <= 2 final
    exponentiations (the "verify_final_exps" counter delta) while the
    exact path cost B, and that all-valid verdict vectors are
    bit-identical across modes. Knobs: BENCH_BATCHVERIFY_REPS
    (default 3); BENCH_BATCHVERIFY=0 skips. Returns the verify speedup
    at the widest batch."""
    from coconut_tpu import metrics, pok_sig, ps
    from coconut_tpu.backend import get_backend
    from coconut_tpu.batchverify import batch_lambda

    reps = int(os.environ.get("BENCH_BATCHVERIFY_REPS", "3"))
    sizes = sorted(
        int(x)
        for x in os.environ.get(
            "BENCH_BATCHVERIFY_SIZES", "64,256,1024"
        ).split(",")
        if x.strip()
    )
    backend = get_backend(backend_name)
    revealed = list(range(2, ge.MSG_COUNT))

    max_b = max(sizes)
    vsigs = [sigs[i % len(sigs)] for i in range(max_b)]
    vmsgs = [msgs_list[i % len(msgs_list)] for i in range(max_b)]
    proofs, challenges, revealed_list = pok_sig.batch_show(
        vsigs, vk, params, vmsgs, revealed, backend=backend
    )

    def fexp_delta(fn):
        base = metrics.get_count("verify_final_exps")
        out = fn()
        return metrics.get_count("verify_final_exps") - base, out

    points = []
    for B in sizes:
        def v_exact():
            return backend.batch_verify(
                vsigs[:B], vmsgs[:B], vk, params
            )

        def v_batched():
            return ps.batch_verify(
                vsigs[:B], vmsgs[:B], vk, params,
                backend=backend, mode="batched",
            )

        def s_exact():
            return ps.batch_show_verify(
                proofs[:B], vk, params, revealed_list[:B],
                challenges=challenges[:B], backend=backend,
                mode="exact",
            )

        def s_batched():
            return ps.batch_show_verify(
                proofs[:B], vk, params, revealed_list[:B],
                challenges=challenges[:B], backend=backend,
                mode="batched",
            )

        # warmup (jit compile), then pin the final-exp economics on one
        # counted call each: exact pays B, combined pays <= 2
        exact_fexp, exact_bits = fexp_delta(v_exact)
        combined_fexp, batched_bits = fexp_delta(v_batched)
        assert list(exact_bits) == list(batched_bits), (
            "verdict vectors diverged at B=%d" % B
        )
        assert all(batched_bits), "fixture batch must be all-valid"
        assert combined_fexp <= 2, (
            "combined batch cost %d final exps at B=%d (want <= 2)"
            % (combined_fexp, B)
        )
        show_fexp, show_batched_bits = fexp_delta(s_batched)
        assert show_fexp <= 2, (
            "combined show batch cost %d final exps at B=%d (want <= 2)"
            % (show_fexp, B)
        )
        assert list(show_batched_bits) == list(s_exact()), (
            "show verdict vectors diverged at B=%d" % B
        )

        t_vexact, _ = _timeit(v_exact, reps)
        t_vbatched, _ = _timeit(v_batched, reps)
        t_sexact, _ = _timeit(s_exact, reps)
        t_sbatched, _ = _timeit(s_batched, reps)
        points.append({
            "b": B,
            "verify_exact_s": round(t_vexact, 4),
            "verify_batched_s": round(t_vbatched, 4),
            "verify_speedup": round(t_vexact / t_vbatched, 3),
            "verify_exact_final_exps": exact_fexp,
            "verify_batched_final_exps": combined_fexp,
            "show_exact_s": round(t_sexact, 4),
            "show_batched_s": round(t_sbatched, 4),
            "show_speedup": round(t_sexact / t_sbatched, 3),
            "show_batched_final_exps": show_fexp,
        })

    crossover = next(
        (p["b"] for p in points if p["verify_speedup"] > 1.0), None
    )
    top = points[-1]
    extras["batchverify"] = {
        "lambda": batch_lambda(),
        "sizes": sizes,
        "points": points,
        "crossover_b": crossover,
        "verify_speedup_at_max_b": top["verify_speedup"],
        "show_speedup_at_max_b": top["show_speedup"],
        "batched_checks": metrics.get_count("verify_batched_checks"),
        "batched_fallbacks": metrics.get_count("verify_batched_fallbacks"),
    }
    return top["verify_speedup"]


def _bench_chaos_recovery(params, vk, pool, backend_name, mode, max_batch,
                          max_wait_ms):
    """Self-healing recovery datapoint (ISSUE 9): goodput before / during /
    after a scheduled mid-run fault pair (one executor-loop crash + one
    hung dispatch) against a pool with a fast watchdog and probation
    ladder. The number that matters is recovery_ratio = after/before: a
    pool that quarantines the culprits and re-admits them after a probe
    holds it near 1.0; a pool that bleeds capacity does not.
    BENCH_CHAOS=0 skips, BENCH_CHAOS_DEVICES / BENCH_CHAOS_SECONDS size
    the experiment."""
    from coconut_tpu import metrics
    from coconut_tpu.backend import get_backend
    from coconut_tpu.faults import ChaosSchedule
    from coconut_tpu.serve import CredentialService, run_loadgen
    from coconut_tpu.serve.health import HealthPolicy, Watchdog

    n_devices = int(os.environ.get("BENCH_CHAOS_DEVICES", "4"))
    seconds = float(os.environ.get("BENCH_CHAOS_SECONDS", "0.8"))
    concurrency = 2 * max_batch
    sched = ChaosSchedule()  # indices scheduled mid-run, below
    fb = sched.wrap(get_backend(backend_name))
    counters0 = {
        name: metrics.get_count(name)
        for name in (
            "serve_executor_crashes",
            "serve_watchdog_timeouts",
            "serve_quarantined",
            "serve_recovered",
            "serve_redistributed_batches",
        )
    }
    svc = CredentialService(
        fb,
        vk,
        params,
        mode=mode,
        max_batch=max_batch,
        max_wait_ms=max_wait_ms,
        max_depth=max(1024, 4 * max_batch * n_devices),
        devices=n_devices,
        watchdog=Watchdog(
            k=4.0, min_timeout_s=0.2, initial_timeout_s=120.0,
            max_timeout_s=120.0,
        ),
        watchdog_interval_s=0.05,
        health_policy=HealthPolicy(probe_after_s=0.3, probe_successes=1),
    )
    with svc:
        warm = [
            svc.submit(*pool[i % len(pool)][:2])
            for i in range(max_batch * n_devices)
        ]
        for f in warm:
            f.result(timeout=600.0)

        def phase(duration):
            return run_loadgen(
                svc, pool, duration_s=duration,
                arrival="closed", concurrency=concurrency,
            )

        before = phase(seconds)
        # schedule the faults at near-future dispatch indices (mirrored
        # onto the schedule object so describe() reports what actually ran)
        fb.crash_on = sched.crash_on = frozenset({fb.dispatches + 2})
        fb.hang_on = sched.hang_on = frozenset({fb.dispatches + 4})
        during = phase(max(seconds, 1.0))
        sched.release_hangs()
        time.sleep(0.4)  # one probation cooldown's room
        after = phase(seconds)
    for rep in (before, during, after):
        assert rep["dropped_futures"] == 0, (
            "chaos recovery dropped futures: %r" % (rep,)
        )
    ratio = (
        round(after["goodput_per_s"] / before["goodput_per_s"], 4)
        if before["goodput_per_s"]
        else None
    )
    return {
        "devices": n_devices,
        "seconds_per_phase": seconds,
        "schedule": sched.describe(),
        "goodput_per_s": {
            "before": before["goodput_per_s"],
            "during": during["goodput_per_s"],
            "after": after["goodput_per_s"],
        },
        "errors": {
            "before": before["errors"],
            "during": during["errors"],
            "after": after["errors"],
        },
        "recovery_ratio": ratio,
        "counters": {
            name: metrics.get_count(name) - start
            for name, start in sorted(counters0.items())
        },
    }


def _bench_serve_scaling(params, vk, pool, backend_name, mode, max_batch,
                         max_wait_ms):
    """BENCH_SERVE_DEVICES="1,2,4,8" device-count sweep (ISSUE 8 headline):
    one saturating closed-loop loadgen pass per dispatcher-pool size,
    reporting goodput, p99 latency, batch occupancy, per-device dispatch
    counts, and scaling efficiency (goodput_n / (n * goodput_1)). On the
    jax backend each executor pins to a real jax device (so 8 means the
    8-device mesh's chips); other backends get n unpinned worker
    executors. Each point drives 2*max_batch clients PER device so every
    pool size runs at ITS saturation, not the smallest pool's."""
    from coconut_tpu.serve import CredentialService, run_loadgen

    counts = [
        int(tok)
        for tok in os.environ["BENCH_SERVE_DEVICES"].replace(",", " ").split()
    ]
    seconds = float(
        os.environ.get(
            "BENCH_SERVE_SWEEP_SECONDS",
            os.environ.get("BENCH_SERVE_SECONDS", "2"),
        )
    )
    points = []
    base_goodput = None
    for n in counts:
        devices = n
        if backend_name == "jax":
            import jax

            devs = jax.devices()
            if len(devs) >= n:
                devices = list(devs[:n])
        svc = CredentialService(
            backend_name,
            vk,
            params,
            mode=mode,
            max_batch=max_batch,
            max_wait_ms=max_wait_ms,
            max_depth=max(1024, 4 * max_batch * n),
            devices=devices,
        )
        with svc:
            warm = [
                svc.submit(*pool[i % len(pool)][:2])
                for i in range(max_batch * n)
            ]
            for f in warm:
                f.result(timeout=600.0)
            report = run_loadgen(
                svc,
                pool,
                duration_s=seconds,
                arrival="closed",
                concurrency=2 * max_batch * n,
            )
        assert report["dropped_futures"] == 0, (
            "serve scaling sweep (devices=%d) dropped futures: %r"
            % (n, report)
        )
        goodput = report["goodput_per_s"]
        if base_goodput is None:
            base_goodput = goodput
        devices_seen = report["devices"] or {}
        points.append({
            "devices": n,
            "goodput_per_s": goodput,
            "dropped_futures": report["dropped_futures"],
            "p99_latency_s": report["latency_s"]["p99"],
            "mean_batch_occupancy": report["mean_batch_occupancy"],
            "devices_with_dispatches": len(devices_seen),
            "per_device_dispatches": {
                label: d.get("dispatches", 0)
                for label, d in sorted(devices_seen.items())
            },
            "scaling_efficiency": (
                round(goodput / (n * base_goodput), 4)
                if base_goodput
                else None
            ),
        })
    return {"seconds_per_point": seconds, "points": points}


def main():
    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    # best-of-5: the tunneled chip shows 30-60% run-to-run variance under
    # contention (measured 0.40-0.65 s for the identical compiled grouped
    # program); more reps make the best-of timing robust to that noise
    reps = int(os.environ.get("BENCH_REPS", "5"))
    backend_name = os.environ.get("BENCH_BACKEND", "jax")
    serve_flag = "--serve" in sys.argv[1:]
    # the online issuance lane shares the offline config-4 gate: if the
    # operator turned blind-sign benching off, the CLI flag stays off too
    issue_flag = (
        "--issue" in sys.argv[1:]
        and os.environ.get("BENCH_ISSUE", "1") == "1"
    )
    session_flag = (
        "--session" in sys.argv[1:]
        and os.environ.get("BENCH_SESSION", "1") == "1"
    )
    gateway_flag = (
        "--gateway" in sys.argv[1:]
        and os.environ.get("BENCH_GATEWAY", "1") == "1"
    )
    lifecycle_flag = (
        "--lifecycle" in sys.argv[1:]
        and os.environ.get("BENCH_LIFECYCLE", "1") == "1"
    )
    keylife_flag = (
        "--keylife" in sys.argv[1:]
        and os.environ.get("BENCH_KEYLIFE", "1") == "1"
    )
    batchverify_flag = (
        "--batchverify" in sys.argv[1:]
        and os.environ.get("BENCH_BATCHVERIFY", "1") == "1"
    )
    state_flag = (
        "--state" in sys.argv[1:]
        and os.environ.get("BENCH_STATE", "1") == "1"
    )
    hashmsm_flag = (
        "--hashmsm" in sys.argv[1:]
        and os.environ.get("BENCH_HASHMSM", "1") == "1"
    )
    scenarios_flag = (
        "--scenarios" in sys.argv[1:]
        and os.environ.get("BENCH_SCENARIOS", "1") == "1"
    )
    # BENCH_OFFLINE=0 (only meaningful with --serve/--issue) skips the
    # offline lanes so the CI online smokes don't pay for them
    offline = os.environ.get("BENCH_OFFLINE", "1") == "1" or not (
        serve_flag
        or issue_flag
        or session_flag
        or gateway_flag
        or lifecycle_flag
        or keylife_flag
        or batchverify_flag
        or state_flag
        or hashmsm_flag
        or scenarios_flag
    )

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import __graft_entry__ as ge

    t0 = time.time()
    params, sk, vk, sigs, msgs_list = ge._fixture(batch=batch)
    t_fixture = time.time() - t0

    extras = {
        "batch": batch,
        "backend": backend_name,
        "msg_count": ge.MSG_COUNT,
        "fixture_s": round(t_fixture, 3),
    }

    from coconut_tpu import metrics

    if offline:
        if backend_name == "python":
            value = bench_python(
                batch, ge, params, vk, sigs, msgs_list, extras
            )
        else:
            value = bench_jax(
                batch, reps, ge, params, sk, vk, sigs, msgs_list, extras
            )
        metric, unit = "aggregated_credential_verifies_per_sec", "verifies/sec"
    else:
        value = None

    if serve_flag:
        goodput = bench_serve(
            ge, params, vk, sigs, msgs_list, extras, backend_name
        )
        if value is None:
            value = goodput
            metric, unit = "serve_goodput_per_sec", "requests/sec"

    if issue_flag:
        minted_per_s = bench_issue(
            ge, params, vk, sigs, msgs_list, extras, backend_name
        )
        if value is None:
            value = minted_per_s
            metric, unit = "issue_credentials_per_sec", "credentials/sec"

    if session_flag:
        sessions_per_s = bench_session(ge, params, extras, backend_name)
        if value is None:
            value = sessions_per_s
            metric, unit = "session_sessions_per_sec", "sessions/sec"

    if gateway_flag:
        rpc_goodput = bench_gateway(
            ge, params, vk, sigs, msgs_list, extras, backend_name
        )
        if value is None:
            value = rpc_goodput
            metric, unit = "gateway_rpc_goodput_per_sec", "requests/sec"

    if lifecycle_flag:
        speedup = bench_lifecycle(extras)
        if value is None:
            value = speedup
            metric, unit = "lifecycle_warm_restart_speedup", "x"

    if keylife_flag:
        keylife_goodput = bench_keylife(ge, params, extras, backend_name)
        if value is None:
            value = keylife_goodput
            metric, unit = "keylife_rollover_goodput_per_sec", "requests/sec"

    if batchverify_flag:
        bv_speedup = bench_batchverify(
            ge, params, vk, sigs, msgs_list, extras, backend_name
        )
        if value is None:
            value = bv_speedup
            metric, unit = "batchverify_speedup_at_max_batch", "x"

    if state_flag:
        state_ratio = bench_state(ge, params, extras, backend_name)
        if value is None:
            value = state_ratio
            metric, unit = "state_goodput_ratio", "x"

    if hashmsm_flag:
        hash_speedup = bench_hashmsm(ge, params, extras, backend_name)
        if value is None:
            value = hash_speedup
            metric, unit = "hashmsm_device_hash_speedup", "x"

    if scenarios_flag:
        scn_goodput = bench_scenarios(ge, params, extras, backend_name)
        if value is None:
            value = scn_goodput
            metric, unit = "scenario_goodput_per_sec", "workflows/sec"

    extras["metrics"] = metrics.snapshot()
    # static-operand cache effectiveness, surfaced at top level so a
    # profiling round can grep them without digging into the snapshot
    extras["encode_cache_hits"] = metrics.get_count("encode_cache_hits")
    extras["encode_cache_misses"] = metrics.get_count("encode_cache_misses")
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 2),
                "unit": unit,
                "vs_baseline": round(value / NORTH_STAR, 4),
                **extras,
            }
        )
    )


def bench_jax(batch, reps, ge, params, sk, vk, sigs, msgs_list, extras):
    import jax

    # persistent compile cache: the fused programs take minutes to build
    # over the tunnel; cache them across bench invocations (one shared
    # definition — see coconut_tpu/tpu/__init__.py)
    import coconut_tpu.tpu

    coconut_tpu.tpu.enable_compile_cache()
    import numpy as np

    from coconut_tpu import metrics
    from coconut_tpu.tpu.backend import JaxBackend, _fused_verify_kernel

    extras["device"] = str(jax.devices()[0])
    be = JaxBackend()

    # Pallas Montgomery-mul spot check ON THE CHIP: 256 random products
    # must decode to (x*y) mod p exactly. The fused kernels would catch a
    # mul regression only as wrong verify bits; this names the culprit.
    from coconut_tpu.ops.fields import P as _P
    from coconut_tpu.tpu import limbs as _limbs
    from coconut_tpu.tpu import pallas_fp as _pfp

    if _pfp.enabled():
        import random as _random

        _rng = _random.Random(0xF00D)
        _xs = [_rng.randrange(_P) for _ in range(256)]
        _ys = [_rng.randrange(_P) for _ in range(256)]
        _out = _limbs.fp_decode_batch(
            np.asarray(
                jax.jit(_pfp.mul)(
                    jax.numpy.asarray(_limbs.fp_encode_batch(_xs)),
                    jax.numpy.asarray(_limbs.fp_encode_batch(_ys)),
                )
            )
        )
        assert _out == [x * y % _P for x, y in zip(_xs, _ys)], (
            "pallas fp.mul product mismatch"
        )
        extras["pallas_mul_exact"] = True

    # --- headline: attribute-grouped combined batch verify -----------------
    t0 = time.time()
    ok = be.batch_verify_grouped(sigs, msgs_list, vk, params)
    extras["grouped_compile_plus_run_s"] = round(time.time() - t0, 3)
    assert ok is True, "grouped verification wrong"
    t_grp, ok = _timeit(
        lambda: be.batch_verify_grouped(sigs, msgs_list, vk, params), reps
    )
    assert ok is True
    if os.environ.get("BENCH_PROFILE", "0") == "1":
        # device-side observability (VERDICT r3 item 9): one profiled rep
        # of the headline; the trace (viewable in xprof/tensorboard) breaks
        # kernel time down by the jax.named_scope annotations in
        # tpu/backend.py (comb_msm / grouped_* / miller / final_exp)
        trace_dir = os.environ.get("BENCH_PROFILE_DIR", "/tmp/coconut_trace")
        with jax.profiler.trace(trace_dir):
            be.batch_verify_grouped(sigs, msgs_list, vk, params)
        extras["profile_trace_dir"] = trace_dir
    value = batch / t_grp
    extras["grouped_s"] = round(t_grp, 4)
    metrics.count("verifies", batch * reps)  # headline (grouped) path only

    # steady-state (cache-hot, post-warmup) per-batch host encode for the
    # grouped path: what a stream actually pays per batch once the
    # static-operand cache holds the verkey tables — the ISSUE-3 axis
    # (BENCH_r05 measured 32.5 s COLD for the percred fixture encode; the
    # hot number is the Amdahl term that bounds multi-chip scaling)
    t_genc, _ = _timeit(
        lambda: be.encode_grouped_batch(sigs, msgs_list, vk, params), reps
    )
    extras["grouped_host_encode_hot_s"] = round(t_genc, 4)

    # soundness spot-check ON THE CHIP: one tampered credential must flip
    # the whole-batch boolean (same shapes -> no recompile)
    from coconut_tpu.signature import Signature as _Sig

    forged = list(sigs)
    forged[batch // 2] = _Sig(
        sigs[batch // 2].sigma_1,
        params.ctx.sig.mul(sigs[batch // 2].sigma_2, 2),
    )
    rejected = be.batch_verify_grouped(forged, msgs_list, vk, params) is False
    assert rejected, "grouped verify accepted a forged credential"
    extras["grouped_rejects_forgery"] = rejected

    # --- per-credential fused kernel (bit-per-credential path) -------------
    if os.environ.get("BENCH_PERCRED", "1") == "1":
        with metrics.timer("encode"):
            operands = be.encode_verify_batch(sigs, msgs_list, vk, params)
        extras["host_encode_s"] = round(
            metrics.snapshot()["timers_s"]["encode"], 3
        )
        # steady-state comparator: same encode with the static-operand
        # cache hot (comb tables + g_tilde cached; only signature points
        # and scalar digits are re-encoded)
        t_henc, _ = _timeit(
            lambda: be.encode_verify_batch(sigs, msgs_list, vk, params), reps
        )
        extras["host_encode_hot_s"] = round(t_henc, 4)
        sig_is_g1 = params.ctx.name == "G1"
        with metrics.timer("compile_plus_run"):
            bits = _fused_verify_kernel(sig_is_g1, *operands)
            bits.block_until_ready()
        extras["percred_compile_plus_run_s"] = round(
            metrics.snapshot()["timers_s"]["compile_plus_run"], 3
        )

        def run():
            # time through the host transfer: block_until_ready has been
            # observed returning early over the axon tunnel, which would
            # credit the kernel time to "readback" instead. The [B] bool
            # transfer itself is sub-millisecond.
            with metrics.timer("kernel"):
                out = _fused_verify_kernel(sig_is_g1, *operands)
                return np.asarray(out)

        t_kernel, host_bits = _timeit(run, reps)
        assert bool(host_bits.all()), "verification bits wrong"
        extras["percred_kernel_s"] = round(t_kernel, 4)
        extras["percred_verifies_per_sec"] = round(batch / t_kernel, 2)

        # at-scale rejection ON THE CHIP for the per-credential path too
        # (VERDICT r3 item 8): the axon miscompiles seen in rounds 2-3 were
        # shape-dependent (B>=256, B=1024) — assert the full-width program
        # flips exactly the forged lane (same shapes -> no recompile)
        f_operands = be.encode_verify_batch(forged, msgs_list, vk, params)
        f_bits = np.asarray(_fused_verify_kernel(sig_is_g1, *f_operands))
        assert not f_bits[batch // 2] and bool(
            f_bits.sum() == batch - 1
        ), "per-credential kernel mis-flagged the forged lane"
        extras["percred_rejects_forgery"] = True

    if os.environ.get("BENCH_MULTIVK", "0") == "1":
        # multi-issuer verifier (VERDICT r4 weak #5): 8 verkeys round-robin
        # through the per-credential program. The per-verkey comb tables
        # must amortize behind the LRU cache — the datapoint is the
        # steady-state rate across verkey switches vs the single-verkey
        # rate above (a wholesale-clearing cache would rebuild tables,
        # host multiples + device doublings, on every switch).
        import random as _rnd

        _r = _rnd.Random(0x8151)
        nvk = 8
        vks = []
        for _ in range(nvk):
            # one issuer per fixture (own params/verkey/credentials);
            # identical shapes, so the compiled program is shared and the
            # only per-issuer cost is the comb-table build the LRU cache
            # amortizes
            p2, _, vk2, sigs2, ml2 = ge._fixture(
                batch=batch, seed=_r.randrange(1 << 30)
            )
            vks.append((p2, vk2, sigs2, ml2))
        sig_is_g1 = vks[0][0].ctx.name == "G1"
        # warm: one pass builds all 8 verkeys' comb tables
        for p2, vk2, sigs2, ml2 in vks:
            ops2 = be.encode_verify_batch(sigs2, ml2, vk2, p2)
            np.asarray(_fused_verify_kernel(sig_is_g1, *ops2))
        rounds = 2

        def timed_pass(issuers):
            t0 = time.time()
            for p2, vk2, sigs2, ml2 in issuers:
                ops2 = be.encode_verify_batch(sigs2, ml2, vk2, p2)
                bits2 = np.asarray(_fused_verify_kernel(sig_is_g1, *ops2))
                assert bool(bits2.all())
            return time.time() - t0

        dt = sum(timed_pass(vks) for _ in range(rounds))
        extras["multivk_verifies_per_sec"] = round(
            rounds * nvk * batch / dt, 2
        )
        # SAME-basis single-issuer comparator (encode included in the
        # timed region, unlike percred_verifies_per_sec which times a
        # pre-encoded kernel call): isolates what verkey ROTATION costs
        dt1 = sum(timed_pass(vks[:1]) for _ in range(rounds * nvk))
        extras["multivk_single_issuer_per_sec"] = round(
            rounds * nvk * batch / dt1, 2
        )
        extras["multivk_n"] = nvk

    if os.environ.get("BENCH_COMBINED", "0") == "1":
        # combined (small-exponents) batch verify: one bool per batch,
        # B+1 Miller pairs (superseded by grouped; kept for comparison)
        t0 = time.time()
        ok = be.batch_verify_combined(sigs, msgs_list, vk, params)
        extras["combined_compile_plus_run_s"] = round(time.time() - t0, 3)
        t_comb, ok = _timeit(
            lambda: be.batch_verify_combined(sigs, msgs_list, vk, params),
            reps,
        )
        assert ok is True
        extras["combined_s"] = round(t_comb, 4)
        extras["combined_verifies_per_sec"] = round(batch / t_comb, 2)

    # --- config 3: batched selective-disclosure prove + verify -------------
    if os.environ.get("BENCH_SHOW", "1") == "1":
        from coconut_tpu.pok_sig import batch_show

        t0 = time.time()
        proofs, chals, rmls = batch_show(
            sigs, vk, params, msgs_list, {2, 3, 4, 5}, backend=be
        )
        extras["show_prove_compile_plus_run_s"] = round(time.time() - t0, 3)
        t_prove, _ = _timeit(
            lambda: batch_show(
                sigs, vk, params, msgs_list, {2, 3, 4, 5}, backend=be
            ),
            reps,
        )
        extras["show_prove_per_sec"] = round(batch / t_prove, 2)
        extras["show_prove_s"] = round(t_prove, 4)
        t0 = time.time()
        bits = be.batch_show_verify(proofs, vk, params, rmls, chals)
        extras["show_compile_plus_run_s"] = round(time.time() - t0, 3)
        assert all(bits), "show-verify bits wrong"
        t_show, bits = _timeit(
            lambda: be.batch_show_verify(proofs, vk, params, rmls, chals),
            reps,
        )
        extras["show_verifies_per_sec"] = round(batch / t_show, 2)
        extras["show_s"] = round(t_show, 4)

        # the SECURE non-interactive path (VERDICT r3 item 5): recompute the
        # Fiat-Shamir challenge from each proof transcript inside the timed
        # region (ps.batch_show_verify challenges=None), so config 3 reports
        # what a real verifier pays, not the interactive-style cost above
        from coconut_tpu.ps import batch_show_verify as ps_batch_show_verify

        fs_bits = ps_batch_show_verify(
            proofs, vk, params, rmls, challenges=None, backend=be
        )
        assert all(fs_bits), "FS show-verify bits wrong"
        t_fs, _ = _timeit(
            lambda: ps_batch_show_verify(
                proofs, vk, params, rmls, challenges=None, backend=be
            ),
            reps,
        )
        extras["show_verify_fs_per_sec"] = round(batch / t_fs, 2)
        extras["show_fs_s"] = round(t_fs, 4)

    # --- config 4: threshold issuance (batched blind-sign MSMs) ------------
    if os.environ.get("BENCH_ISSUE", "1") == "1":
        from coconut_tpu.elgamal import elgamal_keygen
        from coconut_tpu.signature import (
            batch_blind_sign,
            batch_prepare_blind_sign,
        )

        # full-batch issuance: the small-distinct-MSM programs underfill
        # the VPU below ~1k lanes (256 -> 1024 lanes measured 157 -> 393
        # prepare/s, 658 -> 1262 blind-sign/s), so the honest batch shape
        # is the same 1024 the verify configs use
        n_req = min(batch, int(os.environ.get("BENCH_ISSUE_N", "1024")))
        # fixture (keygen) and first-call compile timed SEPARATELY so the
        # artifact shows which part of issuance is slow (VERDICT r3 weak 8)
        t0 = time.time()
        elg_sk, elg_pk = elgamal_keygen(params.ctx.sig, params.g)
        extras["issue_keygen_s"] = round(time.time() - t0, 3)
        t0 = time.time()
        out = batch_prepare_blind_sign(
            msgs_list[:n_req], 2, elg_pk, params, backend=be
        )
        reqs = [r for r, _ in out]
        extras["issue_prepare_compile_plus_run_s"] = round(time.time() - t0, 3)
        t_prep, _ = _timeit(
            lambda: batch_prepare_blind_sign(
                msgs_list[:n_req], 2, elg_pk, params, backend=be
            ),
            reps,
        )
        extras["issue_prepare_per_sec"] = round(n_req / t_prep, 2)
        t0 = time.time()
        blinded = batch_blind_sign(reqs, sk, params, backend=be)
        extras["issue_compile_plus_run_s"] = round(time.time() - t0, 3)
        from coconut_tpu.signature import BlindSignature

        want = BlindSignature.new(reqs[0], sk, params)
        assert (blinded[0].h, blinded[0].blinded) == (want.h, want.blinded), (
            "issuance output wrong"
        )
        t_issue, blinded = _timeit(
            lambda: batch_blind_sign(reqs, sk, params, backend=be), reps
        )
        extras["issue_per_sec"] = round(n_req / t_issue, 2)
        extras["issue_n"] = n_req
        extras["issue_s"] = round(t_issue, 4)

    # --- config 5: short streamed run (checkpointed, pipelined) ------------
    if os.environ.get("BENCH_STREAM", "1") == "1":
        import tempfile

        from coconut_tpu.stream import verify_stream

        n_batches = int(os.environ.get("BENCH_STREAM_BATCHES", "8"))
        with tempfile.TemporaryDirectory() as tmpdir:

            def stream(mode, name):
                wait0 = metrics.snapshot()["timers_s"].get("prefetch_wait", 0)
                t0 = time.time()
                state = verify_stream(
                    lambda i: (sigs, msgs_list),
                    n_batches,
                    vk,
                    params,
                    be,
                    state_path=os.path.join(tmpdir, name),
                    mode=mode,
                )
                dt = time.time() - t0
                # pipeline occupancy: fraction of the stream wall the main
                # thread was NOT starved waiting on the background encode
                # worker (1.0 = the prefetcher kept the device fed)
                wait = (
                    metrics.snapshot()["timers_s"].get("prefetch_wait", 0)
                    - wait0
                )
                occ = 1.0 - wait / dt if dt > 0 else None
                return state, dt, occ

            # grouped: ONE bool per batch — honest batch accounting
            state, dt, occ = stream("grouped", "grouped.json")
            assert state.batches_ok == n_batches and state.batches_failed == 0
            assert state.verified == n_batches * batch
            extras["stream_creds_per_sec"] = round(n_batches * batch / dt, 2)
            extras["stream_batches"] = n_batches
            extras["stream_mode"] = "grouped"
            if occ is not None:
                extras["stream_pipeline_occupancy"] = round(occ, 4)

            if os.environ.get("BENCH_PERCRED", "1") == "1":
                # sustained PER-CREDENTIAL rate (one bit per credential,
                # the reference's Signature::verify verdict semantics):
                # the same pipelined stream with the fused per-credential
                # program, which the percred section above already
                # compiled (same shapes) — this costs only run time.
                state, dt, occ = stream("per_credential", "percred.json")
                assert (
                    state.verified == n_batches * batch and state.failed == 0
                )
                extras["percred_stream_per_sec"] = round(
                    n_batches * batch / dt, 2
                )
                if occ is not None:
                    extras["percred_stream_occupancy"] = round(occ, 4)

    return value


if __name__ == "__main__":
    main()
