"""RLC combined-pairing batch verification (PR 16).

Covers the soundness-critical plumbing around the combined check:
deterministic combiner derivation (replayable across processes,
domain-separated by check flavor / verkey / PR-15 epoch), the batched
ps-layer mode (one combined pairing product, bisection-on-rejection with
exact attribution, verdict vectors bit-identical to the exact path), the
serve-layer "batched" mode's demux invariant, and the
COCONUT_BATCH_VERIFY / COCONUT_BATCH_LAMBDA knobs. The adversarial
soundness suite (forged/cancellation lanes over many seeded draws) lives
in test_adversarial.py; the device-kernel pad-lane contract in
test_ops.py."""

import random
import subprocess
import sys
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics, ps
from coconut_tpu.backend import get_backend
from coconut_tpu.batchverify import (
    DEFAULT_LAMBDA,
    MAX_LAMBDA,
    MIN_LAMBDA,
    batch_lambda,
    derive_combiners,
    env_batched_default,
    show_transcript,
    verify_transcript,
)
from coconut_tpu.errors import PSError
from coconut_tpu.faults import DeadLetterLog
from coconut_tpu.ops.fields import R
from coconut_tpu.params import Params
from coconut_tpu.pok_sig import batch_show_verify, show
from coconut_tpu.serve.service import CredentialService
from coconut_tpu.signature import Signature, Sigkey, Verkey

pytestmark = pytest.mark.batchverify

rng = random.Random(0xB16C)

Q = 3
B = 8


@pytest.fixture(scope="module")
def params():
    return Params.new(Q, b"batchverify-test")


@pytest.fixture(scope="module")
def keypair(params):
    sk = Sigkey(
        rng.randrange(1, R), [rng.randrange(1, R) for _ in range(Q)]
    )
    ops = params.ctx.other
    vk = Verkey(
        ops.mul(params.g_tilde, sk.x),
        [ops.mul(params.g_tilde, y) for y in sk.y],
    )
    return sk, vk


def _direct_sign(sk, msgs, params):
    ops = params.ctx.sig
    t = rng.randrange(1, R)
    s1 = ops.mul(params.g, t)
    expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
    return Signature(s1, ops.mul(s1, expo))


@pytest.fixture(scope="module")
def valid_batch(params, keypair):
    sk, _ = keypair
    msgs_list = [
        [rng.randrange(R) for _ in range(Q)] for _ in range(B)
    ]
    sigs = [_direct_sign(sk, m, params) for m in msgs_list]
    return sigs, msgs_list


@pytest.fixture(scope="module")
def pybe():
    return get_backend("python")


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# --- deterministic combiner derivation --------------------------------------


class TestCombinerDerivation:
    def test_same_transcript_same_exponents(self):
        t = b"\x01" * 32
        a = derive_combiners(t, 16)
        b = derive_combiners(t, 16)
        assert a == b
        assert all(1 <= r < (1 << DEFAULT_LAMBDA) for r in a)
        # prefixes agree: lane i's exponent is a pure function of
        # (seed, i), independent of the batch width
        assert derive_combiners(t, 4) == a[:4]

    def test_cross_process_determinism(self):
        t = b"\x5a" * 32
        here = derive_combiners(t, 6)
        code = (
            "from coconut_tpu.batchverify import derive_combiners;"
            "print(derive_combiners(bytes([0x5a])*32, 6))"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        assert out == str(here)

    def test_different_transcripts_different_exponents(self):
        assert derive_combiners(b"a" * 32, 4) != derive_combiners(
            b"b" * 32, 4
        )

    def test_lambda_narrows_range(self):
        rs = derive_combiners(b"t" * 32, 64, lam=MIN_LAMBDA)
        assert all(1 <= r < (1 << MIN_LAMBDA) for r in rs)
        # and the draw itself is domain-separated by lambda
        assert rs != derive_combiners(b"t" * 32, 64, lam=MAX_LAMBDA)

    def test_lambda_env_knob(self, monkeypatch):
        monkeypatch.setenv("COCONUT_BATCH_LAMBDA", "64")
        assert batch_lambda() == 64
        monkeypatch.delenv("COCONUT_BATCH_LAMBDA")
        assert batch_lambda() == DEFAULT_LAMBDA

    @pytest.mark.parametrize("bad", ["32", "63", "129", "0"])
    def test_lambda_out_of_range_refused(self, monkeypatch, bad):
        monkeypatch.setenv("COCONUT_BATCH_LAMBDA", bad)
        with pytest.raises(ValueError):
            batch_lambda()

    def test_env_batched_default(self, monkeypatch):
        for raw, want in [
            ("1", True), ("batched", True), ("TRUE", True),
            ("0", False), ("", False), ("exact", False),
        ]:
            monkeypatch.setenv("COCONUT_BATCH_VERIFY", raw)
            assert env_batched_default() is want
        monkeypatch.delenv("COCONUT_BATCH_VERIFY")
        assert env_batched_default() is False


class TestTranscriptSeparation:
    def test_verkey_separation(self, params, keypair, valid_batch):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        ops = params.ctx.other
        vk2 = Verkey(
            ops.mul(params.g_tilde, 7),
            [ops.mul(params.g_tilde, 7 + i) for i in range(Q)],
        )
        t1 = verify_transcript(sigs, msgs_list, vk, params)
        t2 = verify_transcript(sigs, msgs_list, vk2, params)
        assert t1 != t2
        assert derive_combiners(t1, B) != derive_combiners(t2, B)

    def test_epoch_separation(self, params, keypair, valid_batch):
        # PR 15: proactive refresh preserves the verkey bytes, so the
        # epoch id must separate draws on its own
        _, vk = keypair
        sigs, msgs_list = valid_batch
        ts = [
            verify_transcript(sigs, msgs_list, vk, params, epoch=e)
            for e in (None, 0, 1)
        ]
        assert len(set(ts)) == 3

    def test_lane_content_bound(self, params, keypair, valid_batch):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        t1 = verify_transcript(sigs, msgs_list, vk, params)
        tampered = [list(m) for m in msgs_list]
        tampered[3][0] = (tampered[3][0] + 1) % R
        assert t1 != verify_transcript(sigs, tampered, vk, params)

    def test_show_domain_separated_from_verify(self, params, keypair,
                                               valid_batch):
        # even with identical absorbed bytes downstream, the leading
        # domain tag splits the two check flavors
        _, vk = keypair
        sigs, msgs_list = valid_batch
        proofs, challenges, revealed = [], [], []
        for s, m in zip(sigs[:2], msgs_list[:2]):
            p, c, rv = show(s, vk, params, m, [0])
            proofs.append(p)
            challenges.append(c)
            revealed.append(rv)
        tv = verify_transcript(sigs[:2], msgs_list[:2], vk, params)
        tsu = show_transcript(proofs, vk, params, revealed, challenges)
        assert tv != tsu


# --- the ps-layer batched mode ----------------------------------------------


class TestBatchedVerify:
    def test_all_valid_bit_identical_to_exact(self, params, keypair,
                                              valid_batch, pybe):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        exact = ps.batch_verify(
            sigs, msgs_list, vk, params, backend=pybe, mode="exact"
        )
        batched = ps.batch_verify(
            sigs, msgs_list, vk, params, backend=pybe, mode="batched"
        )
        assert batched == exact == [True] * B
        # an accepted batch costs exactly one combined check, no ladder
        assert metrics.get_count("verify_batched_fallbacks") == 0
        assert metrics.get_count("verify_bisection_depth") == 0

    def test_forged_lanes_attributed(self, params, keypair, valid_batch,
                                     pybe):
        sk, vk = keypair
        sigs, msgs_list = valid_batch
        bad = list(sigs)
        bad[3] = Signature(
            bad[3].sigma_1, params.ctx.sig.mul(bad[3].sigma_2, 2)
        )
        wrong = [list(m) for m in msgs_list]
        wrong[5][0] = (wrong[5][0] + 1) % R
        bits = ps.batch_verify(
            bad, wrong, vk, params, backend=pybe, mode="batched"
        )
        expect = [i not in (3, 5) for i in range(B)]
        assert bits == expect
        assert bits == ps.batch_verify(
            bad, wrong, vk, params, backend=pybe, mode="exact"
        )
        assert metrics.get_count("verify_batched_fallbacks") == 1
        assert metrics.get_count("verify_bisection_depth") >= 1

    def test_single_lane_equivalence(self, params, keypair, valid_batch,
                                     pybe):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        assert ps.batch_verify(
            sigs[:1], msgs_list[:1], vk, params, backend=pybe,
            mode="batched",
        ) == [True]
        forged = [Signature(
            sigs[0].sigma_1, params.ctx.sig.mul(sigs[0].sigma_2, 3)
        )]
        assert ps.batch_verify(
            forged, msgs_list[:1], vk, params, backend=pybe,
            mode="batched",
        ) == [False]

    def test_identity_sigma_lane(self, params, keypair, valid_batch,
                                 pybe):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        mixed = list(sigs)
        mixed[2] = Signature(None, None)
        bits = ps.batch_verify(
            mixed, msgs_list, vk, params, backend=pybe, mode="batched"
        )
        assert bits == [i != 2 for i in range(B)]

    def test_empty_batch(self, params, keypair, pybe):
        _, vk = keypair
        assert ps.batch_verify(
            [], [], vk, params, backend=pybe, mode="batched"
        ) == []

    def test_mode_validation(self, params, keypair, valid_batch, pybe):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        with pytest.raises(PSError):
            ps.batch_verify(
                sigs, msgs_list, vk, params, backend=pybe, mode="bogus"
            )
        with pytest.raises(PSError):
            ps.batch_verify(sigs, msgs_list, vk, params, mode="batched")


class TestBatchedShowVerify:
    @pytest.fixture(scope="class")
    def shows(self, params, keypair, valid_batch):
        _, vk = keypair
        sigs, msgs_list = valid_batch
        proofs, challenges, revealed = [], [], []
        for s, m in zip(sigs, msgs_list):
            p, c, rv = show(s, vk, params, m, [0])
            proofs.append(p)
            challenges.append(c)
            revealed.append(rv)
        return proofs, challenges, revealed

    def test_all_valid_bit_identical_to_exact(self, params, keypair,
                                              shows, pybe):
        _, vk = keypair
        proofs, challenges, revealed = shows
        exact = batch_show_verify(
            proofs, vk, params, revealed, challenges=challenges,
            backend=pybe, mode="exact",
        )
        batched = batch_show_verify(
            proofs, vk, params, revealed, challenges=challenges,
            backend=pybe, mode="batched",
        )
        assert batched == exact == [True] * B

    def test_tampered_lane_attributed(self, params, keypair, shows,
                                      pybe):
        _, vk = keypair
        proofs, challenges, revealed = shows
        rv = [dict(r) for r in revealed]
        rv[4][0] = (rv[4][0] + 1) % R
        bits = batch_show_verify(
            proofs, vk, params, rv, challenges=challenges,
            backend=pybe, mode="batched",
        )
        assert bits == [i != 4 for i in range(B)]
        assert metrics.get_count("verify_batched_fallbacks") == 1

    def test_dead_lane_fails_alone(self, params, keypair, shows, pybe):
        # identity sigma': the lane is excluded from the fold and fails
        # via its own schnorr/dead bit — the rest of the batch passes
        # the combined pairing check without a bisection ladder
        from coconut_tpu.ps import PoKOfSignatureProof

        _, vk = keypair
        proofs, challenges, revealed = shows
        dead = list(proofs)
        p0 = proofs[1]
        dead[1] = PoKOfSignatureProof(
            None, None, p0.J, p0.proof_vc, p0.revealed_msg_indices
        )
        bits = batch_show_verify(
            dead, vk, params, revealed, challenges=challenges,
            backend=pybe, mode="batched",
        )
        assert bits == [i != 1 for i in range(B)]
        assert metrics.get_count("verify_batched_fallbacks") == 0

    def test_mode_validation(self, params, keypair, shows):
        _, vk = keypair
        proofs, challenges, revealed = shows
        with pytest.raises(PSError):
            batch_show_verify(
                proofs, vk, params, revealed, challenges=challenges,
                mode="batched",
            )


# --- the serve-layer "batched" mode -----------------------------------------


def _cred(ok=True):
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)


def _lane_bit(s):
    return s.sigma_1 is not None and bool(getattr(s, "ok", False))


class StubCombined:
    """Stub backend exposing ONLY the combined (RLC) seam plus the
    per-credential reference path the bisector's leaf probes ride."""

    def __init__(self):
        self.combined_calls = 0

    def batch_verify_combined(self, sigs, msgs, vk, params, rs=None,
                              epoch=None):
        self.combined_calls += 1
        return all(_lane_bit(s) for s in sigs)


def _service(backend, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 2.0)
    return CredentialService(backend, None, None, **kw)


class TestServeBatchedMode:
    def test_demux_invariant_one_forged_one_dead_letter(self, tmp_path):
        dlq = str(tmp_path / "batched_dead.jsonl")
        be = StubCombined()
        svc = _service(be, mode="batched", dead_letter_path=dlq).start()
        futs = [svc.submit(_cred(ok=(i != 2)), [i]) for i in range(4)]
        assert svc.drain(timeout=10.0)
        assert [f.result(0) for f in futs] == [True, True, False, True]
        records = DeadLetterLog.read(dlq)
        assert len(records) == 1
        assert records[0]["batch"] == 0 and records[0]["credential"] == 2
        assert records[0]["program"] == "verify"
        assert metrics.get_count("dead_letters") == 1
        assert be.combined_calls >= 2  # the batch + bisection probes

    def test_all_valid_single_combined_check(self, tmp_path):
        dlq = str(tmp_path / "batched_clean.jsonl")
        be = StubCombined()
        with _service(be, mode="batched", dead_letter_path=dlq) as svc:
            futs = [svc.submit(_cred(), [i]) for i in range(4)]
        assert all(f.result(5.0) for f in futs)
        assert DeadLetterLog.read(dlq) == []
        assert be.combined_calls == 1

    def test_jit_shape_key_pow2_bucketed(self):
        be = StubCombined()
        svc = _service(be, mode="batched", pad_partial=False).start()
        try:
            futs = [svc.submit(_cred(), [i]) for i in range(4)]
            assert all(f.result(5.0) for f in futs)
            futs = [svc.submit(_cred(), [i]) for i in range(3)]
            assert all(f.result(5.0) for f in futs)
            # 3 and 4 lanes share the pow2-4 bucket: ONE jit shape
            assert metrics.get_count("serve_jit_shapes") == 1
        finally:
            svc.shutdown()

    def test_env_default_mode(self, monkeypatch):
        monkeypatch.setenv("COCONUT_BATCH_VERIFY", "1")
        with _service(StubCombined()) as svc:
            assert svc.mode == "batched"
        monkeypatch.delenv("COCONUT_BATCH_VERIFY")
        stub = StubCombined()
        stub.batch_verify = lambda s, m, vk, p: [_lane_bit(x) for x in s]
        with _service(stub) as svc:
            assert svc.mode == "per_credential"

    def test_keychain_refused(self):
        from coconut_tpu.serve.service import VerifyProgram

        with pytest.raises(ValueError):
            VerifyProgram(
                StubCombined(), None, None, "batched", 4, 2.0, 16,
                False, None, None, None, keychain=object(),
            )

    def test_unknown_mode_refused(self):
        with pytest.raises(ValueError):
            _service(StubCombined(), mode="combined")
