"""Fault-supervision suite (ISSUE 2): retry/backoff, backend fallback,
grouped-failure bisection, dead-lettering, and checkpoint hardening, all
driven by the deterministic `coconut_tpu.faults.FaultyBackend` injector.

Economics: the tier-1 budget is tight, so nearly everything here runs on
stub backends (SimpleNamespace credentials carrying their own verdict);
real BLS crypto appears only in the handful of acceptance tests that the
ISSUE pins to real verification. All retry policies use base_delay=0 or an
injected no-op sleep — the suite never sleeps."""

import json
import random
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics
from coconut_tpu.errors import (
    CheckpointCorruptError,
    CheckpointMismatchError,
    TransientBackendError,
)
from coconut_tpu.faults import DeadLetterLog, FaultyBackend
from coconut_tpu.retry import RetryPolicy, call_with_retry, note_attempt
from coconut_tpu.stream import (
    STATE_SCHEMA_VERSION,
    StreamState,
    run_fingerprint,
    verify_stream,
)

pytestmark = pytest.mark.faults


# --- stub world: credentials that carry their own verdict ------------------


def _cred(ok=True):
    # sigma fields non-None so the drivers' identity-signature guards pass
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)


def _stub_source(n_batches, per_batch=3, forged=()):
    """source(i) -> (sigs, msgs) of stub credentials; forged is a set of
    (batch, index-in-batch) pairs whose credential verdicts are False."""
    forged = set(forged)

    def source(i):
        sigs = [_cred(ok=(i, j) not in forged) for j in range(per_batch)]
        return sigs, [[0, 0] for _ in range(per_batch)]

    return source


class StubPerCred:
    def batch_verify(self, sigs, msgs, vk, params):
        return [bool(s.ok) for s in sigs]


class StubGrouped:
    def batch_verify_grouped(self, sigs, msgs, vk, params):
        return all(s.ok for s in sigs)


class StubAsync:
    def batch_verify_async(self, sigs, msgs, vk, params):
        bits = [bool(s.ok) for s in sigs]
        return lambda: bits


def _policy(**kw):
    kw.setdefault("max_attempts", 3)
    kw.setdefault("base_delay", 0.0)
    return RetryPolicy(**kw)


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# --- RetryPolicy / call_with_retry unit behavior ---------------------------


def test_backoff_deterministic_bounded_and_desynced():
    p = RetryPolicy(base_delay=0.1, max_delay=0.35, jitter=0.5)
    for attempt in (1, 2, 3, 4):
        for key in (0, 1, 7):
            d = p.backoff(attempt, key=key)
            raw = min(0.35, 0.1 * 2.0 ** (attempt - 1))
            assert 0.5 * raw <= d <= raw
            assert d == p.backoff(attempt, key=key)  # pure
    # distinct batches desynchronize their re-dispatch times
    assert p.backoff(1, key=0) != p.backoff(1, key=1)


def test_policy_validates_configuration():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)
    with pytest.raises(ValueError):
        RetryPolicy(base_delay=-1)


def test_call_with_retry_recovers_and_counts():
    boom = [2]

    def fn():
        if boom[0]:
            boom[0] -= 1
            raise TransientBackendError("flaky")
        return 42

    attempts = []
    slept = []
    p = _policy(sleep=slept.append)
    assert call_with_retry(fn, p, key=5, attempts=attempts) == 42
    assert metrics.get_count("retries") == 2
    assert len(slept) == 2
    assert [a["attempt"] for a in attempts] == [1, 2]
    assert attempts[0]["error"] == "TransientBackendError"
    assert "flaky" in attempts[0]["detail"]


def test_call_with_retry_exhaustion_reraises_without_fallback():
    def fn():
        raise TransientBackendError("always")

    with pytest.raises(TransientBackendError):
        call_with_retry(fn, _policy())
    assert metrics.get_count("retries") == 2  # attempts 2 and 3
    assert metrics.get_count("fallbacks") == 0


def test_call_with_retry_exhaustion_runs_fallback():
    def fn():
        raise TransientBackendError("always")

    assert call_with_retry(fn, _policy(), fallback=lambda: "degraded") == (
        "degraded"
    )
    assert metrics.get_count("fallbacks") == 1


def test_permanent_error_is_not_retried():
    calls = []

    def fn():
        calls.append(1)
        raise RuntimeError("permanent")

    with pytest.raises(RuntimeError):
        call_with_retry(fn, _policy())
    assert len(calls) == 1
    assert metrics.get_count("retries") == 0


def test_preconsumed_attempts_raise_synthetic_transient():
    attempts = []
    for _ in range(3):
        note_attempt(attempts, TransientBackendError("eager"))
    with pytest.raises(TransientBackendError, match="retries exhausted"):
        call_with_retry(lambda: 1, _policy(), attempts=attempts)


# --- FaultyBackend injector ------------------------------------------------


def test_faulty_backend_is_capability_transparent():
    faulty = FaultyBackend(StubPerCred())
    assert hasattr(faulty, "batch_verify")
    assert not hasattr(faulty, "batch_verify_grouped")
    assert not hasattr(faulty, "batch_verify_async")


def test_faulty_backend_raise_every_schedule():
    faulty = FaultyBackend(StubPerCred(), raise_every=3)
    seen = []
    for i in range(6):
        try:
            faulty.batch_verify([_cred()], [[0]], None, None)
            seen.append("ok")
        except TransientBackendError:
            seen.append("boom")
    assert seen == ["ok", "ok", "boom", "ok", "ok", "boom"]
    assert faulty.dispatches == 6


def test_faulty_backend_flips_verdicts():
    faulty = FaultyBackend(StubGrouped(), flip_on={0})
    sigs = [_cred(), _cred()]
    assert faulty.batch_verify_grouped(sigs, [[0], [0]], None, None) is False
    assert faulty.batch_verify_grouped(sigs, [[0], [0]], None, None) is True


def test_faulty_backend_corrupts_async_finalizer():
    faulty = FaultyBackend(StubAsync(), corrupt_finalizer_on={0})
    fin = faulty.batch_verify_async([_cred()], [[0]], None, None)
    with pytest.raises(TransientBackendError, match="finalizer fault"):
        fin()
    fin2 = faulty.batch_verify_async([_cred()], [[0]], None, None)
    assert fin2() == [True]


# --- supervised verify_stream: retry + fallback ----------------------------


def test_stream_retries_through_transient_faults_stub():
    """Every 3rd dispatch raises; the retry ladder absorbs each fault and
    the 20-batch stream completes with exact tallies."""
    faulty = FaultyBackend(StubPerCred(), raise_every=3)
    state = verify_stream(
        _stub_source(20, forged={(4, 1)}),
        20,
        None,
        None,
        faulty,
        mode="per_credential",
        retry_policy=_policy(),
    )
    assert state.next_batch == 20
    assert state.verified + state.failed == 60
    assert state.failed == 1
    assert metrics.get_count("retries") > 0
    assert metrics.get_count("fallbacks") == 0


def test_stream_exhaustion_falls_back_per_batch():
    """A backend that ALWAYS raises: every batch exhausts its attempts and
    re-dispatches on the fallback; the stream still completes exactly."""

    class AlwaysDown:
        def batch_verify(self, sigs, msgs, vk, params):
            raise TransientBackendError("device gone")

    state = verify_stream(
        _stub_source(5),
        5,
        None,
        None,
        AlwaysDown(),
        mode="per_credential",
        retry_policy=_policy(max_attempts=2),
        fallback_backend=StubPerCred(),
    )
    assert state.verified == 15 and state.failed == 0
    assert metrics.get_count("fallbacks") == 5
    assert metrics.get_count("retries") == 5  # one re-attempt per batch


def test_stream_no_fallback_propagates_and_checkpoint_resumes(tmp_path):
    """Without a fallback, exhaustion propagates; the checkpoint preserves
    the completed prefix, and a rerun against a healed backend finishes
    with exact totals."""
    path = str(tmp_path / "state.json")
    source = _stub_source(4)

    class DiesOnBatch2:
        def __init__(self):
            self.calls = 0

        def batch_verify(self, sigs, msgs, vk, params):
            if self.calls == 2:
                raise TransientBackendError("stuck")
            self.calls += 1
            return [bool(s.ok) for s in sigs]

    with pytest.raises(TransientBackendError):
        verify_stream(
            source, 4, None, None, DiesOnBatch2(),
            state_path=path, retry_policy=_policy(max_attempts=1),
        )
    st = StreamState(path)
    assert st.next_batch == 2 and st.verified == 6
    state = verify_stream(
        source, 4, None, None, StubPerCred(), state_path=path
    )
    assert state.next_batch == 4 and state.verified == 12


def test_stream_retries_corrupted_async_finalizer():
    """A readback (finalizer) fault re-runs the full dispatch+readback
    cycle — the pipelined seam, not just the sync one."""
    faulty = FaultyBackend(StubAsync(), corrupt_finalizer_on={1})
    state = verify_stream(
        _stub_source(4),
        4,
        None,
        None,
        faulty,
        retry_policy=_policy(),
        pipeline_depth=2,
    )
    assert state.verified == 12 and state.failed == 0
    assert metrics.get_count("retries") == 1


def test_stream_default_policy_keeps_old_error_behavior():
    """No retry_policy and no fallback: a dispatch error propagates
    exactly as before the supervision layer existed."""

    class Dies:
        def batch_verify(self, sigs, msgs, vk, params):
            raise TransientBackendError("boom")

    with pytest.raises(TransientBackendError):
        verify_stream(_stub_source(2), 2, None, None, Dies())
    assert metrics.get_count("retries") == 0


def test_stream_flipped_verdict_is_not_a_crash():
    """A miscompute (flipped verdict) is NOT an exception: supervision
    does not mask it, the tallies record it."""
    faulty = FaultyBackend(StubPerCred(), flip_on={2})
    state = verify_stream(
        _stub_source(4), 4, None, None, faulty, retry_policy=_policy()
    )
    assert state.failed == 3  # batch 2's three verdicts negated
    assert state.verified == 9


# --- grouped-failure bisection + dead-letter -------------------------------


def test_grouped_bisection_isolates_single_culprit(tmp_path):
    dlq = str(tmp_path / "dead.jsonl")
    state = verify_stream(
        _stub_source(4, per_batch=8, forged={(2, 5)}),
        4,
        None,
        None,
        StubGrouped(),
        mode="grouped",
        dead_letter_path=dlq,
    )
    assert state.batches_ok == 3 and state.batches_failed == 1
    # granular accounting: only the culprit fails, not the whole batch
    assert state.failed == 1 and state.verified == 31
    assert metrics.get_count("bisections") > 0
    assert metrics.get_count("dead_letters") == 1
    (rec,) = DeadLetterLog.read(dlq)
    assert rec["batch"] == 2 and rec["credential"] == 5
    assert "bisection" in rec["reason"]
    assert rec["attempts"] == []


def test_grouped_bisection_multiple_culprits(tmp_path):
    dlq = str(tmp_path / "dead.jsonl")
    forged = {(1, 0), (1, 3), (1, 7)}
    state = verify_stream(
        _stub_source(2, per_batch=8, forged=forged),
        2,
        None,
        None,
        StubGrouped(),
        mode="grouped",
        dead_letter_path=dlq,
    )
    assert state.failed == 3 and state.verified == 13
    recs = DeadLetterLog.read(dlq)
    assert sorted(r["credential"] for r in recs) == [0, 3, 7]
    assert all(r["batch"] == 1 for r in recs)


def test_grouped_without_dead_letter_keeps_wholesale_accounting():
    """No dead_letter_path -> bisection stays off by default and a
    rejected batch counts wholesale, exactly the pre-existing grouped
    semantics."""
    state = verify_stream(
        _stub_source(3, forged={(1, 2)}),
        3,
        None,
        None,
        StubGrouped(),
        mode="grouped",
    )
    assert state.batches_failed == 1
    assert state.failed == 3 and state.verified == 6
    assert metrics.get_count("bisections") == 0


def test_bisect_failures_forced_on_without_dead_letter(tmp_path):
    """bisect_failures=True without a dead-letter path: granular
    accounting, no file written."""
    state = verify_stream(
        _stub_source(3, per_batch=4, forged={(0, 1)}),
        3,
        None,
        None,
        StubGrouped(),
        mode="grouped",
        bisect_failures=True,
    )
    assert state.failed == 1 and state.verified == 11
    assert metrics.get_count("dead_letters") == 0


def test_bisection_probes_ride_the_retry_ladder(tmp_path):
    """Bisection probes hitting injected transient faults are retried with
    the same policy as regular dispatches."""
    dlq = str(tmp_path / "dead.jsonl")
    faulty = FaultyBackend(StubGrouped(), raise_every=4)
    state = verify_stream(
        _stub_source(3, per_batch=8, forged={(1, 6)}),
        3,
        None,
        None,
        faulty,
        mode="grouped",
        retry_policy=_policy(),
        dead_letter_path=dlq,
    )
    assert state.failed == 1 and state.verified == 23
    (rec,) = DeadLetterLog.read(dlq)
    assert rec["batch"] == 1 and rec["credential"] == 6
    assert metrics.get_count("retries") > 0


def test_dead_letter_log_roundtrip(tmp_path):
    path = str(tmp_path / "d.jsonl")
    log = DeadLetterLog(path)
    log.append(batch=3, credential=1, reason="r", attempts=[{"attempt": 1}])
    log.append(batch=4, credential=0, reason="s")
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 2
    first = json.loads(lines[0])
    assert first == {
        "attempts": [{"attempt": 1}],
        "batch": 3,
        "credential": 1,
        "reason": "r",
        "schema": 4,
        "trace_id": None,
        "span_id": None,
        "program": None,
        "nullifier": None,
    }
    assert DeadLetterLog.read(path)[1]["batch"] == 4
    assert DeadLetterLog.read(str(tmp_path / "missing.jsonl")) == []
    # pre-v4 lines (no schema/trace/program/nullifier fields) normalize
    # on read: the reader never needs per-version key checks
    with open(path, "a") as f:
        f.write(json.dumps({"batch": 9, "credential": 0, "reason": "old"}) + "\n")
    old = DeadLetterLog.read(path)[2]
    assert old["schema"] == 1
    assert old["trace_id"] is None and old["span_id"] is None
    assert old["program"] is None
    assert old["nullifier"] is None


# --- checkpoint hardening --------------------------------------------------


def _run_then_state(tmp_path, n=3):
    path = str(tmp_path / "state.json")
    verify_stream(
        _stub_source(n), n, None, None, StubPerCred(), state_path=path
    )
    return path


def test_state_file_carries_schema_crc_fingerprint(tmp_path):
    path = _run_then_state(tmp_path)
    doc = json.load(open(path))
    assert doc["schema"] == STATE_SCHEMA_VERSION
    assert isinstance(doc["crc32"], int)
    assert doc["payload"]["fingerprint"] == run_fingerprint(
        "per_credential", None, None
    )
    assert doc["payload"]["next_batch"] == 3


def test_truncated_checkpoint_quarantined_and_rerun_completes(tmp_path):
    path = _run_then_state(tmp_path, n=3)
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[: len(raw) // 2])  # truncate mid-JSON
    state = verify_stream(
        _stub_source(3), 3, None, None, StubPerCred(), state_path=path
    )
    # rerun started clean and re-verified everything, exactly
    assert state.next_batch == 3 and state.verified == 9
    assert state.quarantined and state.quarantined.endswith(".corrupt")
    assert open(state.quarantined, "rb").read() == raw[: len(raw) // 2]
    assert metrics.get_count("checkpoint_quarantined") == 1
    # the fresh checkpoint written by the rerun is valid again
    assert StreamState(path).next_batch == 3


def test_wrong_schema_version_quarantined(tmp_path):
    path = _run_then_state(tmp_path)
    doc = json.load(open(path))
    doc["schema"] = 99
    json.dump(doc, open(path, "w"))
    st = StreamState(path)
    assert st.next_batch == 0 and st.quarantined
    assert metrics.get_count("checkpoint_quarantined") == 1


def test_crc_tamper_quarantined(tmp_path):
    path = _run_then_state(tmp_path)
    doc = json.load(open(path))
    doc["payload"]["verified"] += 1  # bit-flip the tallies
    json.dump(doc, open(path, "w"))
    st = StreamState(path)
    assert st.next_batch == 0 and st.quarantined
    assert metrics.get_count("checkpoint_quarantined") == 1


def test_quarantine_never_overwrites_earlier_quarantine(tmp_path):
    path = str(tmp_path / "s.json")
    for expect in (".corrupt", ".corrupt-1"):
        with open(path, "w") as f:
            f.write("not json")
        st = StreamState(path)
        assert st.quarantined.endswith(expect)


def test_fingerprint_mismatch_fails_loudly(tmp_path):
    path = _run_then_state(tmp_path)  # per_credential run
    with pytest.raises(CheckpointMismatchError) as ei:
        verify_stream(
            _stub_source(3), 3, None, None, StubGrouped(),
            state_path=path, mode="grouped",
        )
    assert ei.value.stored == run_fingerprint("per_credential", None, None)
    assert ei.value.expected == run_fingerprint("grouped", None, None)
    # the file is intact — mismatch must not quarantine or clobber
    assert StreamState(path).next_batch == 3


def test_stored_fingerprint_none_is_accepted(tmp_path):
    """A checkpoint written without a fingerprint (direct StreamState use,
    e.g. pre-supervision callers) resumes fine under a fingerprinted
    run."""
    path = str(tmp_path / "s.json")
    st = StreamState(path)
    st.next_batch = 1
    st.verified = 3
    st.save()
    state = verify_stream(
        _stub_source(3), 3, None, None, StubPerCred(), state_path=path
    )
    assert state.next_batch == 3 and state.verified == 9


def test_legacy_v1_checkpoint_quarantined_not_crashed(tmp_path):
    """A pre-hardening (schema-less flat JSON) state file is treated as an
    unknown schema: quarantined, stream restarts from zero."""
    path = str(tmp_path / "s.json")
    json.dump({"next_batch": 2, "verified": 6, "failed": 0}, open(path, "w"))
    st = StreamState(path)
    assert st.next_batch == 0 and st.quarantined


def test_mid_on_batch_crash_replays_batch_at_least_once(tmp_path):
    """on_batch runs BEFORE the checkpoint write: a crash inside it means
    the batch replays on resume (at-least-once) and tallies stay exact."""
    path = str(tmp_path / "s.json")
    delivered = []
    crashed = []

    def exploding_on_batch(i, bits):
        if i == 1 and not crashed:
            crashed.append(True)
            raise RuntimeError("killed mid-delivery")
        delivered.append(i)

    with pytest.raises(RuntimeError, match="mid-delivery"):
        verify_stream(
            _stub_source(3), 3, None, None, StubPerCred(),
            state_path=path, on_batch=exploding_on_batch,
        )
    assert StreamState(path).next_batch == 1  # batch 1 not checkpointed
    state = verify_stream(
        _stub_source(3), 3, None, None, StubPerCred(),
        state_path=path, on_batch=exploding_on_batch,
    )
    assert delivered == [0, 1, 2]  # batch 1 replayed, none lost
    assert state.verified == 9 and state.next_batch == 3


def test_checkpoint_corrupt_error_is_typed():
    with pytest.raises(CheckpointCorruptError):
        StreamState._load_checked("/nonexistent/state.json")


# --- acceptance: real crypto under injected faults -------------------------


def _real_setup():
    from coconut_tpu.ops.curve import G1_GEN, G2_GEN
    from coconut_tpu.ops.fields import R
    from coconut_tpu.params import Params, SIGNATURES_IN_G1
    from coconut_tpu.signature import Sigkey, Verkey

    rng = random.Random(0xFA171)
    ctx = SIGNATURES_IN_G1
    g = ctx.sig.mul(G1_GEN, rng.randrange(1, R))
    g_tilde = ctx.other.mul(G2_GEN, rng.randrange(1, R))
    h = [ctx.sig.mul(G1_GEN, rng.randrange(1, R)) for _ in range(2)]
    params = Params(g, g_tilde, h, ctx)
    sk = Sigkey(rng.randrange(1, R), [rng.randrange(1, R) for _ in range(2)])
    vk = Verkey(
        ctx.other.mul(g_tilde, sk.x),
        [ctx.other.mul(g_tilde, y) for y in sk.y],
    )
    return rng, params, sk, vk


def _real_source(rng, params, sk, per_batch, corrupt_at=None):
    from coconut_tpu.ops.fields import R
    from coconut_tpu.signature import Signature

    def source(i):
        sigs, msgs_list = [], []
        for j in range(per_batch):
            msgs = [rng.randrange(R) for _ in range(2)]
            t = rng.randrange(1, R)
            s1 = params.ctx.sig.mul(params.g, t)
            expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
            s2 = params.ctx.sig.mul(s1, expo)
            if corrupt_at == (i, j):
                s2 = params.ctx.sig.mul(s2, 2)
            sigs.append(Signature(s1, s2))
            msgs_list.append(msgs)
        return sigs, msgs_list

    return source


def test_acceptance_real_stream_survives_every_3rd_dispatch_fault():
    """ISSUE acceptance: injected transient fault on every 3rd dispatch,
    20-batch real-crypto stream completes with exact tallies and nonzero
    retries in metrics.snapshot()."""
    from coconut_tpu.backend import PythonBackend

    rng, params, sk, vk = _real_setup()
    source = _real_source(rng, params, sk, per_batch=2, corrupt_at=(7, 1))
    faulty = FaultyBackend(PythonBackend(), raise_every=3)
    state = verify_stream(
        source, 20, vk, params, faulty, retry_policy=_policy()
    )
    assert state.next_batch == 20
    assert state.verified + state.failed == 40
    assert state.failed == 1
    snap = metrics.snapshot()["counters"]
    assert snap["retries"] > 0
    assert snap.get("fallbacks", 0) == 0


def test_acceptance_real_grouped_bisection_dead_letters_forgery(tmp_path):
    """ISSUE acceptance: a grouped batch with exactly one forged
    credential yields a dead-letter entry naming that credential's index
    via bisection, under real PS verification."""
    from coconut_tpu.ps import ps_verify

    rng, params, sk, vk = _real_setup()
    source = _real_source(rng, params, sk, per_batch=4, corrupt_at=(1, 2))

    class GroupedPy:
        def batch_verify_grouped(self, s, m, v, p):
            return all(ps_verify(si, mi, v, p) for si, mi in zip(s, m))

    dlq = str(tmp_path / "dead.jsonl")
    state = verify_stream(
        source, 3, vk, params, GroupedPy(),
        mode="grouped", dead_letter_path=dlq,
    )
    assert state.batches_failed == 1 and state.failed == 1
    assert state.verified == 11
    (rec,) = DeadLetterLog.read(dlq)
    assert rec["batch"] == 1 and rec["credential"] == 2
    assert metrics.get_count("bisections") > 0


def test_acceptance_fallback_backend_by_name():
    """fallback_backend='python' resolves through the registry; an
    always-down primary degrades onto real reference verification."""

    class AlwaysDown:
        def batch_verify(self, sigs, msgs, vk, params):
            raise TransientBackendError("down")

    rng, params, sk, vk = _real_setup()
    source = _real_source(rng, params, sk, per_batch=2)
    state = verify_stream(
        source, 2, vk, params, AlwaysDown(),
        retry_policy=_policy(max_attempts=2),
        fallback_backend="python",
    )
    assert state.verified == 4 and state.failed == 0
    assert metrics.get_count("fallbacks") == 2


# --- satellite: mesh axis validation + final-batch padding -----------------


def test_require_axes_clear_error():
    from coconut_tpu.tpu import shard

    mesh = SimpleNamespace(shape={"data": 8})
    with pytest.raises(ValueError, match="missing axis"):
        shard.require_axes(mesh, "dp", "tp")
    shard.require_axes(mesh, "data")  # present axis passes


def test_stream_mesh_missing_axis_is_clear_valueerror():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    from coconut_tpu.tpu import shard

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("data",))

    class MeshStub:
        # attribute presence is all _dispatchers probes before axes
        encode_verify_batch = staticmethod(lambda *a, **k: ())
        encode_grouped_batch = staticmethod(lambda *a, **k: ())

    with pytest.raises(ValueError, match="missing axis"):
        verify_stream(
            _stub_source(1), 1, None, None, MeshStub(), mesh=mesh
        )
    with pytest.raises(ValueError, match="missing axis"):
        verify_stream(
            _stub_source(1), 1, None, None, MeshStub(),
            mesh=mesh, mode="grouped",
        )


def test_sharded_percred_pads_final_batch(monkeypatch):
    """batch_verify_sharded_async pads a non-divisible final batch by
    repeating the last credential and slices the bits back to len(sigs)."""
    import numpy as np

    from coconut_tpu.tpu import shard

    mesh = SimpleNamespace(shape={"dp": 4, "tp": 1})
    seen = {}

    class EncBackend:
        def encode_verify_batch(self, sigs, msgs, vk, params, **kw):
            seen["n"] = len(sigs)
            seen["last_two_same"] = sigs[-1] is sigs[-2]
            return (len(sigs),)

    def fake_make(mesh_, g1, ba, ma):
        return lambda n: np.ones(n, dtype=bool)

    monkeypatch.setattr(shard, "make_sharded_verify", fake_make)
    vk = SimpleNamespace(Y_tilde=[1, 2])
    params = SimpleNamespace(ctx=SimpleNamespace(name="G1"))
    sigs = [_cred() for _ in range(6)]
    fin = shard.batch_verify_sharded_async(
        EncBackend(), sigs, [[0]] * 6, vk, params, mesh
    )
    assert seen["n"] == 8  # padded 6 -> 8 (next multiple of dp=4)
    assert seen["last_two_same"]  # pad repeats the final credential
    assert fin() == [True] * 6  # sliced back to the true length
    # empty batch short-circuits without touching the mesh
    assert shard.batch_verify_sharded_async(
        EncBackend(), [], [], vk, params, mesh
    )() == []


# --- satellite: COCONUT_PALLAS_KARATSUBA parse -----------------------------


def test_parse_karatsuba_matrix():
    from coconut_tpu.tpu.pallas_fp import _parse_karatsuba

    for raw in (None, "", "  ", "banana", "-1", "1.5"):
        assert _parse_karatsuba(raw) == 2
    assert _parse_karatsuba("0") == 0
    assert _parse_karatsuba("1") == 1
    assert _parse_karatsuba(" 2 ") == 2
    with pytest.raises(ValueError, match="at most two levels"):
        _parse_karatsuba("3")


# --- satellite: COCONUT_DEBUG_PACK host-side assert ------------------------


def test_pack_debug_records_and_asserts_at_decode():
    import numpy as np

    from coconut_tpu.tpu import limbs

    del limbs.PACK_DEBUG_VIOLATIONS[:]
    limbs.pack_debug_record(np.float32(100.0))  # within bound: ignored
    limbs.pack_debug_check()  # no violation, no raise
    limbs.pack_debug_record(np.float32(500.0))
    with pytest.raises(AssertionError, match="pack bound 396"):
        limbs.fp_decode_batch(
            np.zeros((1, limbs.NLIMBS), dtype=np.float32)
        )
    # the check drained the buffer: decoding works again
    assert limbs.fp_decode_batch(
        np.zeros((1, limbs.NLIMBS), dtype=np.float32)
    ) == [0]


def test_pack_debug_callback_records_under_jit(monkeypatch):
    """The COCONUT_DEBUG_PACK=1 branch of _pack_pt records the limb max
    through jax.debug.callback without raising inside the jitted program;
    an in-bound pack leaves the buffer empty."""
    import jax
    import jax.numpy as jnp

    from coconut_tpu.tpu import backend as bk
    from coconut_tpu.tpu import limbs

    monkeypatch.setenv("COCONUT_DEBUG_PACK", "1")
    del limbs.PACK_DEBUG_VIOLATIONS[:]

    @jax.jit
    def prog(x, y):
        return bk._pack_pt(x, y)

    x = jnp.zeros((1, limbs.NLIMBS), dtype=jnp.float32)
    jax.block_until_ready(prog(x, x))
    limbs.pack_debug_check()  # in-bound: nothing recorded

    y = jnp.full((1, limbs.NLIMBS), 500.0, dtype=jnp.float32)
    jax.block_until_ready(prog(x, y))
    jax.effects_barrier()
    with pytest.raises(AssertionError, match="pack bound 396"):
        limbs.pack_debug_check()


# --- chaos injection: crash / hang / schedules (ISSUE 9) -------------------


@pytest.mark.chaos
def test_injected_crash_escapes_exception_handlers_deterministically():
    """InjectedCrash deliberately subclasses BaseException: per-batch
    `except Exception` containment must NOT catch it — that is what makes
    it reach the executor loop's crash handler in serve tests."""
    from coconut_tpu.faults import InjectedCrash

    assert issubclass(InjectedCrash, BaseException)
    assert not issubclass(InjectedCrash, Exception)
    faulty = FaultyBackend(StubPerCred(), crash_on={1})
    assert faulty.batch_verify([_cred()], [[0]], None, None) == [True]
    with pytest.raises(InjectedCrash, match="injected executor crash #1"):
        faulty.batch_verify([_cred()], [[0]], None, None)
    assert faulty.batch_verify([_cred()], [[0]], None, None) == [True]
    assert faulty.crashes == 1 and faulty.dispatches == 3


@pytest.mark.chaos
def test_hang_injection_releases_without_real_sleeps():
    """A pre-released hang returns immediately (the deterministic-test
    mode); hang_entered is the sync point a watchdog test coordinates
    on."""
    faulty = FaultyBackend(StubPerCred(), hang_on={0})
    faulty.hang_release.set()  # pre-release: the wait falls through
    assert faulty.batch_verify([_cred()], [[0]], None, None) == [True]
    assert faulty.hangs == 1 and faulty.hang_entered.is_set()
    # async seam: the hang sits INSIDE the finalizer (a hung readback)
    faulty2 = FaultyBackend(StubAsync(), hang_on={0})
    faulty2.hang_release.set()
    fin = faulty2.batch_verify_async([_cred()], [[0]], None, None)
    assert faulty2.hangs == 0  # dispatch returned; the hang is in fin
    assert fin() == [True]
    assert faulty2.hangs == 1


@pytest.mark.chaos
def test_chaos_schedule_is_deterministic_and_replayable():
    """The same ChaosSchedule wrapped twice over the same inner backend
    yields the SAME outcome sequence — chaos experiments replay exactly."""
    from coconut_tpu.faults import ChaosSchedule, InjectedCrash

    sched = ChaosSchedule(fault_on={0}, flip_on={1}, crash_on={2})

    def outcomes():
        fb = sched.wrap(StubPerCred())
        out = []
        for _ in range(4):
            try:
                out.append(fb.batch_verify([_cred()], [[0]], None, None))
            except TransientBackendError:
                out.append("fault")
            except InjectedCrash:
                out.append("crash")
        return out

    first, second = outcomes(), outcomes()
    assert first == ["fault", [False], "crash", [True]]
    assert second == first
    assert len(sched.backends) == 2
    assert sched.describe() == {
        "crash_on": [2],
        "hang_on": [],
        "fault_on": [0],
        "flip_on": [1],
        "delay_on": [],
        "delay_s": 0.0,
        "fail_sign_on": [],
        "crash_sign_on": [],
        "hang_sign_on": [],
        "corrupt_partial_on": [],
    }


@pytest.mark.chaos
def test_chaos_schedule_release_hangs_frees_every_wrapped_backend():
    from coconut_tpu.faults import ChaosSchedule

    sched = ChaosSchedule(hang_on={0})
    backends = [sched.wrap(StubPerCred()) for _ in range(3)]
    sched.release_hangs()
    for fb in backends:
        assert fb.hang_release.is_set()
        assert fb.batch_verify([_cred()], [[0]], None, None) == [True]


# --- dead-letter / flight JSONL rotation (ISSUE 9 satellite) ----------------


@pytest.mark.chaos
def test_dead_letter_rotates_on_record_count(tmp_path):
    path = str(tmp_path / "dead.jsonl")
    log = DeadLetterLog(path, max_records=2, keep=2)
    for i in range(5):
        log.append(batch=i, credential=0, reason="r%d" % i)
    # newest-first rotation chain: live file r4; .1 = r2,r3; .2 = r0,r1
    assert [r["batch"] for r in DeadLetterLog.read(path)] == [4]
    assert [r["batch"] for r in DeadLetterLog.read(path + ".1")] == [2, 3]
    assert [r["batch"] for r in DeadLetterLog.read(path + ".2")] == [0, 1]
    assert metrics.get_count("rotations") == 2


@pytest.mark.chaos
def test_dead_letter_rotates_on_size_and_drops_past_keep(tmp_path):
    path = str(tmp_path / "dead.jsonl")
    log = DeadLetterLog(path, max_bytes=1, keep=2)  # every append rotates
    for i in range(4):
        log.append(batch=i, credential=0, reason="big")
    assert [r["batch"] for r in DeadLetterLog.read(path)] == [3]
    assert [r["batch"] for r in DeadLetterLog.read(path + ".1")] == [2]
    assert [r["batch"] for r in DeadLetterLog.read(path + ".2")] == [1]
    import os

    assert not os.path.exists(path + ".3")  # keep=2: oldest dropped


@pytest.mark.chaos
def test_rotate_if_needed_unit(tmp_path):
    from coconut_tpu.obs.flight import rotate_if_needed

    path = str(tmp_path / "x.jsonl")
    assert rotate_if_needed(path, max_bytes=1) is False  # no file yet
    with open(path, "w") as f:
        f.write("line\n")
    assert rotate_if_needed(path, max_bytes=10**6) is False  # under cap
    assert rotate_if_needed(path, max_records=1, record_count=1) is True
    assert open(path + ".1").read() == "line\n"
    import os

    assert not os.path.exists(path)
    assert metrics.get_count("rotations") == 1


# --- crash-atomic checkpoint writes (ISSUE 9 satellite) ---------------------


@pytest.mark.chaos
def test_stale_torn_tmp_never_quarantines_the_checkpoint(tmp_path):
    """A kill mid-save leaves at most a torn `<path>.tmp`; the restart
    must load the intact checkpoint (or start clean) with ZERO
    `.corrupt*` quarantines — the torn bytes never reach `path`."""
    import os

    path = _run_then_state(tmp_path, n=3)
    doc_before = open(path).read()
    with open(path + ".tmp", "w") as f:
        f.write('{"schema": 2, "crc32": 123, "payl')  # torn mid-write
    st = StreamState(path)
    assert st.next_batch == 3 and st.quarantined is None
    assert metrics.get_count("checkpoint_quarantined") == 0
    assert open(path).read() == doc_before
    # the next save truncates the stale tmp and lands atomically
    st.save()
    assert not os.path.exists(path + ".tmp")
    assert not [p for p in os.listdir(tmp_path) if ".corrupt" in p]
    assert StreamState(path).next_batch == 3


@pytest.mark.chaos
def test_save_failure_mid_replace_leaves_old_checkpoint_intact(
    tmp_path, monkeypatch
):
    """If the atomic rename itself dies, `path` still holds the previous
    COMPLETE document — a torn new document can never land there."""
    import coconut_tpu.stream as stream_mod

    path = _run_then_state(tmp_path, n=3)
    before = open(path).read()
    st = StreamState(path)
    st.verified += 100

    def boom(src, dst):
        raise OSError("disk pulled mid-rename")

    monkeypatch.setattr(stream_mod.os, "replace", boom)
    with pytest.raises(OSError):
        st.save()
    monkeypatch.undo()
    assert open(path).read() == before
    reloaded = StreamState(path)
    assert reloaded.quarantined is None and reloaded.next_batch == 3


@pytest.mark.chaos
def test_save_fsyncs_before_the_rename(tmp_path, monkeypatch):
    """Ordering matters: the tmp file's bytes must be durable BEFORE the
    rename makes them the checkpoint (else a power cut can leave a
    complete-looking but empty file)."""
    import coconut_tpu.stream as stream_mod

    calls = []
    real_fsync, real_replace = stream_mod.os.fsync, stream_mod.os.replace
    monkeypatch.setattr(
        stream_mod.os,
        "fsync",
        lambda fd: (calls.append("fsync"), real_fsync(fd))[1],
    )
    monkeypatch.setattr(
        stream_mod.os,
        "replace",
        lambda s, d: (calls.append("replace"), real_replace(s, d))[1],
    )
    path = str(tmp_path / "state.json")
    st = StreamState(path)
    st.next_batch = 1
    st.save()
    assert calls.index("fsync") < calls.index("replace")
