"""Device hash-to-curve + bucketed MSM suite (PR 18).

Two kernels close the last two PROFILE_r05 walls, and both are pure
re-schedules of already-proven math — so every test here is a BIT
parity test against an independent oracle, never a statistical one:

  - device `hash_to_g1` (SvdW straight-line map + cofactor clear as
    one jitted program) vs the Python spec in ops/hashing.py and,
    when built, the native `cc_hash_to_g1_batch` FFI core from PR 3;
  - the bucketed Pippenger MSM schedule vs the existing signed-Horner
    distinct-base kernels, across window sizes, ragged batch sizes,
    zero scalars, and GLV on/off.

Adversarial hash vectors: empty message, the 255-byte DST boundary
(expand_message_xmd's long-DST hashing kicks in above 255), u-values
driving each of the three SvdW x-candidates, and the identity-sum
edge via the map's oddness (map(p-u) = -map(u), so u1 = p - u0 sums
to infinity and must raise, exactly like the spec)."""

import random

import pytest

from coconut_tpu.ops import hashing as spec_hashing
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import P, R, fp_sqrt

pytestmark = pytest.mark.hashmsm


@pytest.fixture(scope="module")
def jax_backend():
    from coconut_tpu.backend import get_backend

    return get_backend("jax")


@pytest.fixture()
def device_hash_on(monkeypatch):
    import coconut_tpu.tpu.backend as tb

    monkeypatch.setattr(tb, "_DEVICE_HASH", True)


def _force_window(monkeypatch, w):
    """Pin the bucket-schedule knob: an int forces that window for
    every distinct-base MSM, 'off' forces the legacy Horner path."""
    import coconut_tpu.tpu.backend as tb

    monkeypatch.setattr(tb, "_BUCKET_MODE", w)


# ---------------------------------------------------------------------------
# device hash-to-G1 parity
# ---------------------------------------------------------------------------


class TestDeviceHashParity:
    def test_random_messages_vs_spec(self, jax_backend, device_hash_on):
        rng = random.Random(0xC0C0)
        msgs = [
            bytes(rng.randrange(256) for _ in range(rng.randrange(0, 96)))
            for _ in range(17)
        ]
        got = jax_backend.hash_to_g1_batch(msgs)
        for m, p in zip(msgs, got):
            assert p == spec_hashing.hash_to_g1(m)

    def test_native_oracle(self, jax_backend, device_hash_on):
        from coconut_tpu import native

        if not native.available():
            pytest.skip("native core not built")
        msgs = [b"oracle-%d" % i for i in range(9)]
        assert jax_backend.hash_to_g1_batch(msgs) == list(
            native.hash_to_g1_batch(msgs)
        )

    def test_empty_message_and_empty_batch(
        self, jax_backend, device_hash_on
    ):
        assert jax_backend.hash_to_g1_batch([]) == []
        (p,) = jax_backend.hash_to_g1_batch([b""])
        assert p == spec_hashing.hash_to_g1(b"")

    def test_dst_boundary_255(self, jax_backend, device_hash_on):
        # expand_message_xmd switches to the hashed-DST form above 255
        # bytes; 255 is the last direct-encoding length
        for dst in (bytes(range(255)), b"\xff" * 255, b"x"):
            msgs = [b"", b"dst-edge", b"A" * 130]
            got = jax_backend.hash_to_g1_batch(msgs, dst=dst)
            for m, p in zip(msgs, got):
                assert p == spec_hashing.hash_to_g1(m, dst=dst)

    def test_counters_and_path_selection(
        self, jax_backend, device_hash_on
    ):
        from coconut_tpu import metrics

        b0 = metrics.get_count("device_hash_batches")
        p0 = metrics.get_count("device_hash_points")
        jax_backend.hash_to_g1_batch([b"a", b"b", b"c"])
        assert metrics.get_count("device_hash_batches") == b0 + 1
        assert metrics.get_count("device_hash_points") == p0 + 3


def _u_for_candidate(which):
    """Search out a field element whose SvdW map accepts exactly
    x-candidate `which` (1-based), replaying the spec's own
    straight-line candidates and square tests."""
    F = spec_hashing._FpAdapter
    Z, c1, c2, c3, c4 = spec_hashing._SVDW_FP
    one = F.embed(1)
    rng = random.Random(0x5D + which)

    def g(x):
        return F.add(F.mul(F.sq(x), x), F.embed(F.B))

    while True:
        u = rng.randrange(1, P)
        tv1 = F.mul(F.sq(u), c1)
        tv2 = F.add(one, tv1)
        tv1 = F.sub(one, tv1)
        tv3 = F.inv0(F.mul(tv1, tv2))
        tv4 = F.mul(F.mul(F.mul(u, tv1), tv3), c3)
        x1 = F.sub(c2, tv4)
        x2 = F.add(c2, tv4)
        x3 = F.add(F.mul(F.sq(F.mul(F.sq(tv2), tv3)), c4), Z)
        sq = [fp_sqrt(g(x)) is not None for x in (x1, x2, x3)]
        if which == 1 and sq[0]:
            return u
        if which == 2 and not sq[0] and sq[1]:
            return u
        if which == 3 and not sq[0] and not sq[1]:
            # the SvdW construction guarantees x3 works here
            assert sq[2]
            return u


class TestSvdwCandidates:
    """Drive the device map through each of the three x-candidate
    accept branches and the identity edge, below the message layer."""

    @pytest.fixture(scope="class")
    def kernel(self):
        import jax.numpy as jnp

        from coconut_tpu.tpu import backend as tb
        from coconut_tpu.tpu.limbs import fp_encode_raw_batch

        def run(u_pairs):
            import numpy as np

            flat = [u for pair in u_pairs for u in pair]
            dig = fp_encode_raw_batch(flat).reshape(len(u_pairs), 2, -1)
            par = np.array(
                [u & 1 for u in flat], dtype=bool
            ).reshape(len(u_pairs), 2)
            handle = tb._hash_to_g1_kernel(
                jnp.asarray(dig), jnp.asarray(par)
            )
            return tb.JaxBackend.hash_to_g1_wait(handle)

        return run

    def _spec_point(self, u0, u1):
        F = spec_hashing._FpAdapter
        consts = spec_hashing._SVDW_FP
        q0 = spec_hashing._map_to_curve_svdw(F, consts, u0)
        q1 = spec_hashing._map_to_curve_svdw(F, consts, u1)
        from coconut_tpu.ops.curve import G1_COFACTOR

        return g1.mul(g1.add(q0, q1), G1_COFACTOR)

    @pytest.mark.parametrize("cand", [1, 2, 3])
    def test_each_candidate(self, kernel, cand):
        u = _u_for_candidate(cand)
        v = _u_for_candidate((cand % 3) + 1)
        got = kernel([(u, v)])
        assert got[0] == self._spec_point(u, v)

    def test_identity_sum_raises(self, kernel):
        # for a candidate-3 u (both gx1, gx2 non-square) the map is odd
        # in u — negating u keeps x3 (it depends only on u^2) and flips
        # the y sign — so the pair (u, p-u) sums to the identity, which
        # must be refused exactly like the spec's ~2^-255 edge
        u = _u_for_candidate(3)
        with pytest.raises(ValueError):
            kernel([(u, P - u)])


# ---------------------------------------------------------------------------
# bucketed Pippenger MSM parity
# ---------------------------------------------------------------------------


def _rand_rows(grp, gen, B, k, rng, zero_lane=False):
    pts = [
        [grp.mul(gen, rng.randrange(1, R)) for _ in range(k)]
        for _ in range(B)
    ]
    scs = [[rng.randrange(R) for _ in range(k)] for _ in range(B)]
    if zero_lane:
        scs[0][0] = 0
    return pts, scs


class TestBucketedMsmParity:
    # the full window sweep / ragged-shape / GLV-off / G2 lanes each
    # compile a fresh XLA program per (B, k, window) shape — minutes on
    # the CPU mesh, so they ride the hashmsm CI lane (-m hashmsm) and
    # stay out of the bounded tier-1 run; all_zero + dispatch_counters
    # below keep a fast bucketed-path representative in tier-1
    @pytest.mark.slow
    @pytest.mark.parametrize("window", [2, 3, 5, 8])
    def test_g1_windows_vs_horner(
        self, jax_backend, monkeypatch, window
    ):
        rng = random.Random(900 + window)
        pts, scs = _rand_rows(g1, G1_GEN, 3, 6, rng, zero_lane=True)
        _force_window(monkeypatch, "off")
        ref = jax_backend.msm_g1_distinct(pts, scs)
        _force_window(monkeypatch, window)
        assert jax_backend.msm_g1_distinct(pts, scs) == ref
        assert ref == [grp_msm(g1, p, s) for p, s in zip(pts, scs)]

    @pytest.mark.slow
    @pytest.mark.parametrize("B,k", [(1, 4), (3, 1), (5, 7)])
    def test_g1_ragged_shapes(self, jax_backend, monkeypatch, B, k):
        rng = random.Random(1000 + 10 * B + k)
        pts, scs = _rand_rows(g1, G1_GEN, B, k, rng)
        _force_window(monkeypatch, 4)
        got = jax_backend.msm_g1_distinct(pts, scs)
        assert got == [grp_msm(g1, p, s) for p, s in zip(pts, scs)]

    @pytest.mark.slow
    def test_g1_glv_off(self, jax_backend, monkeypatch):
        import coconut_tpu.tpu.backend as tb

        rng = random.Random(77)
        pts, scs = _rand_rows(g1, G1_GEN, 2, 5, rng, zero_lane=True)
        monkeypatch.setattr(tb, "_GLV_ENABLED", False)
        _force_window(monkeypatch, 5)
        got = jax_backend.msm_g1_distinct(pts, scs)
        assert got == [grp_msm(g1, p, s) for p, s in zip(pts, scs)]

    @pytest.mark.slow
    def test_g2(self, jax_backend, monkeypatch):
        rng = random.Random(78)
        pts, scs = _rand_rows(g2, G2_GEN, 2, 3, rng, zero_lane=True)
        _force_window(monkeypatch, "off")
        ref = jax_backend.msm_g2_distinct(pts, scs)
        _force_window(monkeypatch, 3)
        assert jax_backend.msm_g2_distinct(pts, scs) == ref
        assert ref == [grp_msm(g2, p, s) for p, s in zip(pts, scs)]

    def test_all_zero_scalars(self, jax_backend, monkeypatch):
        pts = [[G1_GEN, g1.double(G1_GEN)]]
        scs = [[0, 0]]
        _force_window(monkeypatch, 3)
        assert jax_backend.msm_g1_distinct(pts, scs) == [None]

    def test_dispatch_counters(self, jax_backend, monkeypatch):
        from coconut_tpu import metrics

        rng = random.Random(79)
        pts, scs = _rand_rows(g1, G1_GEN, 1, 3, rng)
        _force_window(monkeypatch, 5)
        b0 = metrics.get_count("msm_bucketed_dispatches")
        jax_backend.msm_g1_distinct(pts, scs)
        assert metrics.get_count("msm_bucketed_dispatches") == b0 + 1
        assert metrics.get_gauge("msm_bucket_window") == 5
        _force_window(monkeypatch, "off")
        h0 = metrics.get_count("msm_horner_dispatches")
        jax_backend.msm_g1_distinct(pts, scs)
        assert metrics.get_count("msm_horner_dispatches") == h0 + 1


def grp_msm(grp, pts, scs):
    return grp.msm(pts, scs)


class TestWindowSelection:
    """The lazy knob: COCONUT_MSM_WINDOW forces, 'auto' consults the
    cost model, CPU defaults to the legacy Horner schedule."""

    def test_forced_window_parses(self, monkeypatch):
        import coconut_tpu.tpu.backend as tb

        monkeypatch.setattr(tb, "_BUCKET_MODE", None)
        monkeypatch.setenv("COCONUT_MSM_WINDOW", "6")
        assert tb._bucket_window(100, 255) == 6
        monkeypatch.setattr(tb, "_BUCKET_MODE", None)
        monkeypatch.setenv("COCONUT_MSM_WINDOW", "0")
        assert tb._bucket_window(100, 255) is None

    def test_bad_window_rejected(self, monkeypatch):
        import coconut_tpu.tpu.backend as tb

        monkeypatch.setattr(tb, "_BUCKET_MODE", None)
        monkeypatch.setenv("COCONUT_MSM_WINDOW", "17")
        with pytest.raises(ValueError):
            tb._bucket_window(100, 255)
        monkeypatch.setattr(tb, "_BUCKET_MODE", None)

    def test_auto_prefers_buckets_only_at_scale(self, monkeypatch):
        import coconut_tpu.tpu.backend as tb

        monkeypatch.setattr(tb, "_BUCKET_MODE", "auto")
        # the show prover's post-GLV sigma pair is k=4: Horner wins
        assert tb._bucket_window(4, 128) is None
        # at prepare/batch-verify scale the bucket schedule wins
        assert tb._bucket_window(512, 255) is not None


# ---------------------------------------------------------------------------
# epoch retirement drops the nullifier keyspace (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.state
class TestRetirementCompaction:
    def test_retired_epoch_refused_before_probe(self, tmp_path):
        import collections

        from coconut_tpu import metrics
        from coconut_tpu.errors import EpochRetiredError
        from coconut_tpu.keylife.epoch import EpochRegistry
        from coconut_tpu.state.nullifier import (
            NullifierGuard,
            keyspace_of,
        )
        from coconut_tpu.state.store import StateStore

        store = StateStore(str(tmp_path))
        guard = NullifierGuard(store, use_device=False)
        reg = EpochRegistry(window=1, store=store)
        reg.add_retire_hook(guard.retire_epoch)

        probes = []
        real_probe = guard.probe

        def spying_probe(*a, **kw):
            probes.append(a)
            return real_probe(*a, **kw)

        guard.probe = spying_probe

        KS = collections.namedtuple("KS", "epoch gen key vk")
        reg.register(KS(1, 0, "k1", "vk1"))
        reg.activate(1)
        digest = "ab" * 32
        assert guard.commit([digest], epochs=[1]) == [True]
        assert store.seen(keyspace_of(1), digest)

        n0 = metrics.get_count("state_nullifiers_compacted")
        reg.register(KS(2, 0, "k2", "vk2"))
        reg.activate(2)  # window=1: epoch 1 retires NOW

        # the keyspace is gone wholesale and the counter moved
        assert keyspace_of(1) not in store.keyspaces()
        assert not store.seen(keyspace_of(1), digest)
        assert (
            metrics.get_count("state_nullifiers_compacted") == n0 + 1
        )

        # a retired-epoch show is refused at resolve time — BEFORE any
        # membership probe could touch the (now absent) keyspace
        probes.clear()
        with pytest.raises(EpochRetiredError):
            reg.resolve(1)
        assert probes == []

        # the WAL was compacted underneath: a fresh store over the same
        # root must not resurrect the dropped keyspace
        store.close()
        store2 = StateStore(str(tmp_path))
        assert keyspace_of(1) not in store2.keyspaces()
        assert store2.seen("epoch", "1")  # journal survives
        store2.close()
