"""Durable state plane suite (PR 17).

Covers the tentpole end to end:

  - WAL framing: CRC frames, group-commit fsync accounting, torn-tail
    truncation (exactly once, counted), bounded segment rotation;
  - StateStore: WAL-append-before-apply, snapshot+replay convergence,
    LWW conflict resolution, idempotent remote application, corrupt-
    snapshot quarantine;
  - CRASH-POINT ENUMERATION: a store killed at every injected fault
    point (pre-append, mid-record, post-append-pre-fsync, mid-snapshot,
    mid-compaction) reopens to a PREFIX of the acknowledged state —
    no acknowledged record lost, no phantom or duplicated records;
  - anti-entropy replication: beacon marks -> gap pull -> convergence,
    replication-gap chaos healing, transitive spread (a fact outlives
    its witness);
  - the nullifier subsystem: deterministic transcript digests, device
    probe == host probe, commit check-and-set (intra-batch duplicates
    included), typed DoubleSpendError through the engine and over the
    wire, dead-letter schema v4 with the nullifier attached;
  - the DETERMINISTIC KILL-THE-WITNESS DRILL over LoopbackTransport
    (the real-TCP twin lives in probes/probe_nullifier.py).

Everything runs on the python backend with 3-message params; no real
sleeps except bounded engine-batch waits.
"""

import json
import os
import threading
from types import SimpleNamespace

import numpy as np
import pytest

from coconut_tpu import metrics
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import DoubleSpendError
from coconut_tpu.faults import (
    DeadLetterLog,
    ReplicationChaos,
    SimulatedCrash,
    WalChaos,
)
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.keylife.epoch import EpochRegistry
from coconut_tpu.net import gossip, rpc, wire
from coconut_tpu.net.tenant import TenantTable
from coconut_tpu.params import Params
from coconut_tpu.sss import rand_fr
from coconut_tpu.state import (
    NullifierGuard,
    StateReplicator,
    StateStore,
    WriteAheadLog,
    build_table,
    digests_to_limbs,
    frame_record,
    membership_probe,
    nullifier_of,
    scan_frames,
)

pytestmark = pytest.mark.state

MSGS = 3
HIDDEN = 1
REVEALED = [1, 2]
THRESHOLD, TOTAL = 2, 3


@pytest.fixture(scope="module")
def world():
    params = Params.new(MSGS, b"test-state")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    return SimpleNamespace(
        params=params,
        signers=signers,
        backend=get_backend("python"),
        codec=wire.WireCodec(params),
    )


def _engine(world, store=None, dlq=None):
    return ProtocolEngine(
        world.signers,
        world.params,
        THRESHOLD,
        count_hidden=HIDDEN,
        revealed_msg_indices=REVEALED,
        backend=world.backend,
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
        state_store=store,
        dead_letter_path=dlq,
    ).start()


def _session(world, eng):
    """prepare -> mint -> show_prove; returns (proof, challenge,
    revealed) ready for show_verify."""
    msgs = [rand_fr() for _ in range(MSGS)]
    esk, epk = elgamal_keygen(world.params.ctx.sig, world.params.g)
    sig_req, _ = eng.submit_prepare(msgs, epk).result(120.0)
    cred = eng.submit_mint(sig_req, msgs, esk).result(120.0)
    return eng.submit_show_prove(cred, msgs).result(120.0), cred, msgs


# --- WAL framing and recovery -----------------------------------------------


def test_frame_roundtrip_and_torn_tail_scan():
    frames = b"".join(frame_record(b"rec%d" % i) for i in range(5))
    payloads, valid = scan_frames(frames)
    assert payloads == [b"rec%d" % i for i in range(5)]
    assert valid == len(frames)
    # torn mid-record: prefix survives, tail is invalid
    torn = frames + frame_record(b"tail")[:7]
    payloads, valid = scan_frames(torn)
    assert payloads == [b"rec%d" % i for i in range(5)]
    assert valid == len(frames)
    # corrupt CRC stops the scan at the bad frame
    corrupt = bytearray(frames)
    corrupt[-2] ^= 0xFF
    payloads, _ = scan_frames(bytes(corrupt))
    assert payloads == [b"rec%d" % i for i in range(4)]


def test_wal_append_replay_and_group_commit_fsyncs(tmp_path):
    metrics.reset()
    w = WriteAheadLog(str(tmp_path / "wal.log"))
    w.append(b"one")
    w.append_many([b"two", b"three", b"four"])
    assert metrics.get_count("wal_appends") == 4
    # THE fsync policy: one per append call, not one per record
    assert metrics.get_count("wal_fsyncs") == 2
    w.close()
    w2 = WriteAheadLog(str(tmp_path / "wal.log"))
    assert w2.replay() == [b"one", b"two", b"three", b"four"]
    assert metrics.get_count("wal_replayed_records") == 4
    assert metrics.get_count("wal_torn_tails") == 0
    w2.close()


def test_wal_torn_tail_truncated_exactly_once(tmp_path):
    metrics.reset()
    path = str(tmp_path / "wal.log")
    w = WriteAheadLog(path)
    w.append_many([b"a", b"b"])
    w.close()
    with open(path, "ab") as f:
        f.write(frame_record(b"torn-record")[:9])
    size_torn = os.path.getsize(path)
    w2 = WriteAheadLog(path)
    assert metrics.get_count("wal_torn_tails") == 1
    assert os.path.getsize(path) < size_torn
    assert w2.replay() == [b"a", b"b"]
    w2.close()
    # reopening the CLEAN file must not count another truncation
    w3 = WriteAheadLog(path)
    assert metrics.get_count("wal_torn_tails") == 1
    assert w3.replay() == [b"a", b"b"]
    w3.close()


def test_wal_torn_write_injection(tmp_path):
    metrics.reset()
    path = str(tmp_path / "wal.log")
    chaos = WalChaos(torn_on={2})
    w = WriteAheadLog(path, chaos=chaos)
    w.append_many([b"a", b"b"])
    with pytest.raises(SimulatedCrash):
        w.append(b"c")  # append index 2: half the frame lands
    assert chaos.torn_writes == 1
    w.close()
    w2 = WriteAheadLog(path)
    # the torn half-frame is truncated (counted), acknowledged
    # records survive
    assert metrics.get_count("wal_torn_tails") == 1
    assert w2.replay() == [b"a", b"b"]
    w2.close()


def test_wal_fsync_failure_injection(tmp_path):
    chaos = WalChaos(fsync_fail_on={0})
    w = WriteAheadLog(str(tmp_path / "wal.log"), chaos=chaos)
    with pytest.raises(OSError):
        w.append(b"a")
    # the record may be in the page cache but was never acknowledged;
    # the NEXT fsync succeeds and covers it
    w.append(b"b")
    w.close()
    w2 = WriteAheadLog(str(tmp_path / "wal.log"))
    assert w2.replay() == [b"a", b"b"]
    w2.close()


def test_wal_segment_rotation_bounded(tmp_path):
    metrics.reset()
    path = str(tmp_path / "wal.log")
    w = WriteAheadLog(path, segment_bytes=64, keep=2)
    for i in range(20):
        w.append(b"record-%04d" % i)
    assert metrics.get_count("wal_segments_rotated") > 0
    # the chain is bounded: active + at most `keep` rotated segments
    segs = [p for p in (path, path + ".1", path + ".2", path + ".3")
            if os.path.exists(p)]
    assert path + ".3" not in segs
    # replay returns the SUFFIX the bounded chain retains, oldest
    # first, ending at the newest record
    replayed = w.replay()
    assert replayed[-1] == b"record-0019"
    assert replayed == sorted(replayed)
    w.close()


# --- StateStore -------------------------------------------------------------


def test_store_put_get_replay_and_compaction(tmp_path):
    root = str(tmp_path / "s")
    s = StateStore(root, replica_id="rA")
    s.put("ks", "k1", {"x": 1})
    s.put("ks", "k2", [1, 2, 3])
    s.delete("ks", "k1")
    assert s.get("ks", "k1") is None
    assert not s.seen("ks", "k1")
    assert s.get("ks", "k2") == [1, 2, 3]
    s.close()
    # replay rebuilds the image, including the tombstone
    s2 = StateStore(root, replica_id="rA")
    assert s2.get("ks", "k1") is None
    assert s2.get("ks", "k2") == [1, 2, 3]
    assert s2.marks() == (("ks", "rA", 3),)
    s2.compact()
    assert s2.wal.size_bytes() == 0
    s2.put("ks", "k3", "post-compact")
    s2.close()
    # snapshot + post-compact WAL tail converge
    s3 = StateStore(root, replica_id="rA")
    assert s3.get("ks", "k2") == [1, 2, 3]
    assert s3.get("ks", "k3") == "post-compact"
    assert s3.marks() == (("ks", "rA", 4),)
    s3.close()


def test_store_corrupt_snapshot_quarantined(tmp_path):
    metrics.reset()
    root = str(tmp_path / "s")
    s = StateStore(root, replica_id="rA")
    s.put("ks", "k", 1)
    s.compact()
    s.put("ks", "k2", 2)  # lives only in the WAL tail
    s.close()
    with open(s.snap_path, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff")
    s2 = StateStore(root, replica_id="rA")
    assert metrics.get_count("state_snapshot_corrupt") == 1
    assert os.path.exists(s.snap_path + ".corrupt")
    # the snapshot is gone, but the post-compaction WAL tail replays:
    # the store degrades, never trusts corrupt bytes
    assert s2.get("ks", "k2") == 2
    assert s2.get("ks", "k") is None
    s2.close()


def test_store_lww_by_epoch_then_seq(tmp_path):
    s = StateStore(str(tmp_path / "s"), replica_id="rA")
    # remote record with a HIGHER epoch beats a later local lower-epoch
    s.apply_remote(
        [{"ks": "ks", "k": "k", "v": "high", "o": "rB", "s": 1,
          "e": 5, "t": 0}]
    )
    s.put("ks", "k", "low", epoch=1)
    assert s.get("ks", "k") == "high"
    # same epoch: higher apply index wins
    s.apply_remote(
        [{"ks": "ks", "k": "k", "v": "newer", "o": "rB", "s": 2,
          "e": 5, "t": 0}]
    )
    assert s.get("ks", "k") == "newer"
    s.close()


def test_store_records_after_serves_replicated_facts(tmp_path):
    """A replica serves records it merely replicated — the transitive
    spread that lets facts outlive their witness."""
    a = StateStore(str(tmp_path / "a"), replica_id="rA")
    b = StateStore(str(tmp_path / "b"), replica_id="rB")
    a.put("ks", "k", "fact")
    assert b.apply_remote(a.records_after("ks", "rA", 0)) == 1
    # B now serves rA's records from its own log
    page = b.records_after("ks", "rA", 0)
    assert len(page) == 1 and page[0]["o"] == "rA"
    c = StateStore(str(tmp_path / "c"), replica_id="rC")
    assert c.apply_remote(page) == 1
    assert c.seen("ks", "k")
    a.close(), b.close(), c.close()


# --- crash-point enumeration (satellite) ------------------------------------

CRASH_POINTS = (
    "wal.pre_append",
    "wal.mid_record",  # via torn-write injection
    "wal.post_append",  # post-append, pre-fsync
    "store.mid_snapshot",
    "store.mid_compact",
)


def _drive_until_crash(root, chaos):
    """Apply a deterministic workload to a fresh store under `chaos`;
    returns the keys ACKNOWLEDGED (call returned) before the kill."""
    acked = []
    store = None
    try:
        store = StateStore(root, replica_id="rA", chaos=chaos)
        for i in range(6):
            if i == 3:
                store.compact()
            store.put("ks", "k%d" % i, i)
            acked.append("k%d" % i)
    except (SimulatedCrash, OSError):
        pass  # the "process" dies here; the object is abandoned
    finally:
        if store is not None:
            try:
                store.wal.close()
            except Exception:
                pass
    return acked


@pytest.mark.parametrize("point", CRASH_POINTS)
def test_crash_point_enumeration_replay_converges(tmp_path, point):
    """Kill the store at every injected fault point: reopening must
    yield a PREFIX of the acknowledged writes — every acknowledged
    record present, zero phantom keys, zero duplicated records."""
    metrics.reset()
    root = str(tmp_path / point.replace(".", "_"))
    if point == "wal.mid_record":
        chaos = WalChaos(torn_on={4})
    else:
        chaos = WalChaos(crash_at={point})
    acked = _drive_until_crash(root, chaos)
    assert chaos.crashes + chaos.torn_writes == 1

    recovered = StateStore(root, replica_id="rA")
    got = sorted(recovered.keys("ks"))
    want_all = ["k%d" % i for i in range(6)]
    # prefix consistency: acknowledged writes all present...
    for k in acked:
        assert k in got, "acknowledged %s lost at %s" % (k, point)
    # ...and nothing invented beyond the workload's keyspace
    assert set(got) <= set(want_all), "phantom records at %s" % point
    # no duplicated records: per-origin log seqs strictly increase
    log = recovered.records_after("ks", "rA", 0, limit=1000)
    seqs = [r["s"] for r in log]
    assert seqs == sorted(set(seqs)), "duplicated seqs at %s" % point
    # the recovered store accepts new writes and survives a clean cycle
    recovered.put("ks", "post", "recovery")
    recovered.compact()
    recovered.close()
    final = StateStore(root, replica_id="rA")
    assert final.get("ks", "post") == "recovery"
    final.close()


def test_mid_record_crash_truncates_torn_tail_once(tmp_path):
    metrics.reset()
    root = str(tmp_path / "torn")
    chaos = WalChaos(torn_on={2})
    _drive_until_crash(root, chaos)
    StateStore(root, replica_id="rA").close()
    assert metrics.get_count("wal_torn_tails") == 1
    StateStore(root, replica_id="rA").close()
    assert metrics.get_count("wal_torn_tails") == 1


# --- nullifier derivation + device probe ------------------------------------


def test_nullifier_deterministic_and_fresh(world):
    eng = _engine(world)
    try:
        (proof, chal, rev), cred, msgs = _session(world, eng)
        d1 = nullifier_of(proof, chal, None, world.params)
        d2 = nullifier_of(proof, chal, None, world.params)
        assert d1 == d2 and len(d1) == 64
        # epoch scoping changes the digest (one show per epoch)
        assert nullifier_of(proof, chal, 3, world.params) != d1
        # a FRESH show of the same credential re-randomizes: new digest
        proof2, chal2, _ = eng.submit_show_prove(cred, msgs).result(60.0)
        assert nullifier_of(proof2, chal2, None, world.params) != d1
    finally:
        assert eng.drain(timeout=60.0)


def test_membership_probe_device_matches_host():
    import hashlib

    spent = [hashlib.sha256(b"s%d" % i).hexdigest() for i in range(37)]
    queries = spent[::3] + [
        hashlib.sha256(b"q%d" % i).hexdigest() for i in range(11)
    ]
    table, n_real = build_table(spent)
    assert table.shape == (64, 8)  # padded to a power of two
    q = digests_to_limbs(queries)
    host = membership_probe(table, n_real, q, xp=np)
    import jax.numpy as jnp

    dev = membership_probe(table, n_real, q, xp=jnp)
    want = np.array([d in set(spent) for d in queries])
    assert np.array_equal(host, want)
    assert np.array_equal(dev, want)


def test_guard_commit_check_and_set(tmp_path):
    import hashlib

    metrics.reset()
    store = StateStore(str(tmp_path / "s"), replica_id="rA")
    g = NullifierGuard(store, use_device=False)
    d = [hashlib.sha256(b"n%d" % i).hexdigest() for i in range(3)]
    # intra-batch duplicate: exactly one of the pair lands
    ok = g.commit([d[0], d[1], d[0]], epochs=[1, 1, 1])
    assert ok == [True, True, False]
    # replay in a later batch is rejected; a new digest still lands
    ok2 = g.commit([d[0], d[2]], epochs=[1, 1])
    assert ok2 == [False, True]
    # accept=False lanes are never committed
    assert g.commit([d[2]], epochs=[2], accept=[False]) == [False]
    assert not g.seen(d[2], epoch=2)
    assert metrics.get_count("nullifier_commits") == 3
    assert metrics.get_count("nullifier_double_spends") == 2
    assert metrics.get_count("wal_fsyncs") == 2  # one per commit batch
    store.close()


# --- engine integration -----------------------------------------------------


def test_engine_double_spend_typed_and_dead_lettered(world, tmp_path):
    metrics.reset()
    store = StateStore(str(tmp_path / "s"), replica_id="rA")
    dlq = str(tmp_path / "dead.jsonl")
    eng = _engine(world, store=store, dlq=dlq)
    try:
        (proof, chal, rev), _, _ = _session(world, eng)
        assert eng.submit_show_verify(proof, rev, chal).result(60.0) is True
        with pytest.raises(DoubleSpendError) as ei:
            eng.submit_show_verify(proof, rev, chal).result(60.0)
        assert ei.value.code == "double_spend"
        digest = nullifier_of(proof, chal, None, world.params)
        assert ei.value.nullifier == digest
    finally:
        assert eng.drain(timeout=60.0)
    assert metrics.get_count("nullifier_commits") == 1
    assert metrics.get_count("nullifier_double_spends") >= 1
    # dead-letter schema v4 carries the spent nullifier
    recs = [r for r in DeadLetterLog.read(dlq)
            if r["reason"] == "double_spend"]
    assert recs and recs[0]["schema"] == 4
    assert recs[0]["nullifier"] == digest
    assert recs[0]["program"] == "show_verify"
    # the dead-letter index rode the store
    assert store.keys("deadletter")
    store.close()


def test_engine_wal_replay_survives_restart(world, tmp_path):
    root = str(tmp_path / "s")
    store = StateStore(root, replica_id="rA")
    eng = _engine(world, store=store)
    try:
        (proof, chal, rev), _, _ = _session(world, eng)
        assert eng.submit_show_verify(proof, rev, chal).result(60.0) is True
    finally:
        assert eng.drain(timeout=60.0)
    store.close()
    # "restart": a fresh store over the same directory replays the WAL
    store2 = StateStore(root, replica_id="rA")
    eng2 = _engine(world, store=store2)
    try:
        with pytest.raises(DoubleSpendError):
            eng2.submit_show_verify(proof, rev, chal).result(60.0)
    finally:
        assert eng2.drain(timeout=60.0)
    store2.close()


# --- wire codecs ------------------------------------------------------------


def test_state_pull_and_chunk_roundtrip():
    enc = wire.encode_state_pull("nullifier/3", "rA", 17, 256)
    assert wire.decode_state_pull(enc) == ("nullifier/3", "rA", 17, 256)
    recs = [
        {"ks": "nullifier/3", "k": "ab" * 32, "v": 1, "o": "rA",
         "s": 18, "e": 3, "t": 0},
        {"ks": "epoch", "k": "2", "v": {"event": "retired"}, "o": "rB",
         "s": 4, "e": None, "t": 1},
    ]
    assert wire.decode_state_chunk(wire.encode_state_chunk(recs)) == recs
    assert wire.decode_state_chunk(wire.encode_state_chunk([])) == []


def test_beacon_carries_state_marks():
    b = wire.Beacon(
        "r1", "healthy", 1.0, 0, False, 1, 1, 2.5,
        state_marks=(("nullifier/0", "rA", 7), ("epoch", "r1", 2)),
    )
    d = wire.decode_beacon(wire.encode_beacon(b))
    assert d.state_marks == (("nullifier/0", "rA", 7), ("epoch", "r1", 2))
    assert d.as_dict() == b.as_dict()


# --- replication ------------------------------------------------------------


class _DirectPuller:
    """Duck-typed client pulling straight from a peer store."""

    def __init__(self, store):
        self.store = store

    def pull_state(self, ks, origin, after_seq, limit):
        return self.store.records_after(ks, origin, after_seq, limit)


class _StaticDirectory:
    def __init__(self, stores):
        self.stores = stores

    def state_marks(self, rid):
        return self.stores[rid].marks()


def test_replicator_heals_gaps_and_chaos(tmp_path):
    metrics.reset()
    a = StateStore(str(tmp_path / "a"), replica_id="rA")
    b = StateStore(str(tmp_path / "b"), replica_id="rB")
    directory = _StaticDirectory({"rA": a, "rB": b})
    chaos = ReplicationChaos(drop_pairs={("rA", None)})
    rep = StateReplicator(
        b, directory, {"rA": _DirectPuller(a)}, chaos=chaos
    )
    a.put("ks", "k", "v")
    assert rep.step() == 0  # partitioned: the pull is swallowed
    assert chaos.dropped == 1
    chaos.heal()
    assert rep.step() == 1  # convergence after heal
    assert b.seen("ks", "k")
    assert rep.step() == 0  # idempotent once converged
    assert metrics.get_count("state_antientropy_pulls") >= 1
    a.close(), b.close()


# --- the kill-the-witness drill (deterministic loopback twin) ---------------


def test_kill_the_witness_loopback(world, tmp_path):
    """Replica A witnesses a show; A is killed WITHOUT a drain; the
    same nullifier replayed against the survivors is rejected with the
    typed wire error; A restarts, replays its WAL, and rejects it too.
    Fully deterministic: loopback transports, manual replication steps."""
    metrics.reset()
    rids = ("rA", "rB", "rC")
    stores, engines, replicas, clients = {}, {}, {}, {}
    try:
        for rid in rids:
            stores[rid] = StateStore(
                str(tmp_path / rid), replica_id=rid
            )
            engines[rid] = _engine(world, store=stores[rid])
            replicas[rid] = rpc.Replica(
                engines[rid], world.codec, replica_id=rid
            )
            clients[rid] = rpc.GatewayClient(
                rpc.LoopbackTransport(replicas[rid]), world.codec
            )
        (proof, chal, rev), _, _ = _session(world, engines["rA"])

        # 1. replica A witnesses (and durably records) the show
        assert (
            clients["rA"]
            .submit_show_verify(proof, rev, chal)
            .result(60.0)
            is True
        )
        digest = nullifier_of(proof, chal, None, world.params)

        # 2. anti-entropy replicates the fact to the survivors, driven
        # by the marks A's beacon advertises
        directory = gossip.HealthDirectory()
        directory.observe(clients["rA"].poll_beacon(), now=0.0)
        assert ("nullifier/0", "rA", 1) in directory.state_marks("rA")
        for rid in ("rB", "rC"):
            n = StateReplicator(
                stores[rid], directory, {"rA": clients["rA"]}
            ).step()
            assert n >= 1
            assert stores[rid].seen("nullifier/0", digest)

        # 3. KILL the witness — no drain, in-memory state gone
        clients["rA"].transport.kill()
        replicas["rA"].close()

        # 4. the survivors still reject the replayed show, typed
        for rid in ("rB", "rC"):
            with pytest.raises(DoubleSpendError) as ei:
                clients[rid].submit_show_verify(
                    proof, rev, chal
                ).result(60.0)
            assert ei.value.code == "double_spend"
            assert ei.value.nullifier == digest

        # 5. A restarts: a fresh store over the same directory replays
        # the WAL — the witness itself also still rejects
        assert engines["rA"].drain(timeout=60.0)
        engines.pop("rA")
        stores["rA"].close()
        stores["rA"] = StateStore(str(tmp_path / "rA"), replica_id="rA")
        engines["rA"] = _engine(world, store=stores["rA"])
        replicas["rA"] = rpc.Replica(
            engines["rA"], world.codec, replica_id="rA"
        )
        clients["rA"] = rpc.GatewayClient(
            rpc.LoopbackTransport(replicas["rA"]), world.codec
        )
        with pytest.raises(DoubleSpendError):
            clients["rA"].submit_show_verify(
                proof, rev, chal
            ).result(60.0)
    finally:
        for rep in replicas.values():
            rep.close()
        for eng in engines.values():
            assert eng.drain(timeout=60.0)
        for st in stores.values():
            st.close()


# --- store adoption by existing subsystems ----------------------------------


def test_epoch_registry_journals_and_restores(tmp_path):
    from coconut_tpu.errors import EpochRetiredError, GeneralError
    from coconut_tpu.keylife.epoch import KeySet

    def _ks(epoch):
        return KeySet(epoch, 0, THRESHOLD, [], vk=None)

    root = str(tmp_path / "s")
    store = StateStore(root, replica_id="rA")
    reg = EpochRegistry(window=1, store=store)
    reg.register(_ks(1))
    reg.activate(1)
    reg.register(_ks(2))
    reg.activate(2)  # window=1: epoch 1 retires
    assert store.get("epoch", "1") == {"event": "retired"}
    assert store.get("epoch", "2") == {"event": "active"}
    store.close()
    # restart: the journal survives — retired stays retired, epoch ids
    # stay monotonic, even before keysets are re-installed
    store2 = StateStore(root, replica_id="rA")
    reg2 = EpochRegistry(window=1, store=store2)
    assert reg2.next_epoch() == 3
    with pytest.raises(EpochRetiredError):
        reg2.resolve(1)
    with pytest.raises(GeneralError):
        reg2.register(_ks(1))  # epoch 1 already used
    store2.close()


def test_tenant_quota_survives_restart(tmp_path):
    root = str(tmp_path / "s")
    store = StateStore(root, replica_id="rA")
    table = TenantTable(store=store)
    table.provision("acme", "key-acme", quota=3)
    for _ in range(2):
        table.admit("key-acme")
    store.close()
    # restart: the used counter is restored, not reset to zero
    store2 = StateStore(root, replica_id="rA")
    table2 = TenantTable(store=store2)
    t = table2.provision("acme", "key-acme", quota=3)
    assert t.used == 2
    table2.admit("key-acme")
    from coconut_tpu.errors import TenantQuotaError

    with pytest.raises(TenantQuotaError):
        table2.admit("key-acme")
    store2.close()


def test_dead_letter_store_index(tmp_path):
    store = StateStore(str(tmp_path / "s"), replica_id="rA")
    log = DeadLetterLog(str(tmp_path / "d.jsonl"), store=store)
    log.append(batch=1, credential=2, reason="r", nullifier="ab" * 32)
    (key,) = store.keys("deadletter")
    rec = store.get("deadletter", key)
    assert rec["nullifier"] == "ab" * 32 and rec["schema"] == 4
    store.close()


# --- concurrency ------------------------------------------------------------


def test_concurrent_commits_no_double_accept(tmp_path):
    """Two guards over one store racing the same digest: exactly one
    commit wins — the check-and-set is atomic under the store lock."""
    import hashlib

    store = StateStore(str(tmp_path / "s"), replica_id="rA")
    g = NullifierGuard(store, use_device=False)
    digest = hashlib.sha256(b"raced").hexdigest()
    results = []
    barrier = threading.Barrier(4)

    def worker():
        barrier.wait()
        results.append(g.commit([digest], epochs=[1])[0])

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 1
    store.close()
