"""Zero-downtime lifecycle suite (ISSUE 14, marker `lifecycle`).

Covers the PR-14 contract surface:

  - SHAPE MANIFEST: dedup/canonicalization, atomic save, load round-
    trips, and the corruption-never-blocks-boot guarantee;
  - READINESS GATING: LifecycleController promotes WARMING -> UP only
    AFTER the manifest replay finished, boot is idempotent, and a
    drained controller refuses to un-drain;
  - GRACEFUL DRAIN: one deadline shared between the engine drain and
    the manifest save, CLOSED reported at the end, a successor process
    warm-boots from the saved manifest;
  - REPLICA INTEGRATION: beacons report "warming"/"draining" from the
    controller, a draining replica refuses program requests with a
    RETRYABLE ServiceClosedError (and the refusal survives the wire);
  - ROUTER HANDOFF: a draining primary's refusal fails over to a ring
    successor, marks DRAINING (not DOWN) in the directory, and the
    placement audit counters never show a WARMING/DRAINING placement;
  - ELASTIC SIZING: consecutive-sample hysteresis never flaps on a
    single sample, the controller parks/unparks through the engine,
    and a REAL engine's parked executor receives no work while pool
    capacity stays 1.0 (parking is not degradation);
  - ROLLING-RESTART DRILL: a deterministic 3-replica loopback fleet is
    restarted in sequence under mixed traffic — every future settles,
    zero non-retryable client errors, and the router provably never
    places a new session on a WARMING or DRAINING replica.

Everything except the two real-engine tests runs on stub engines and
fake clocks with zero real sleeps."""

import json
import threading
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.engine import lifecycle as lc_mod
from coconut_tpu.engine.lifecycle import (
    ElasticController,
    ElasticPolicy,
    LifecycleController,
    ShapeManifest,
)
from coconut_tpu.errors import (
    ServiceClosedError,
    ServiceRetryableError,
    TransientBackendError,
)
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.net import gossip, rpc, wire
from coconut_tpu.net.router import ReplicaRouter
from coconut_tpu.params import Params
from coconut_tpu.retry import RetryPolicy
from coconut_tpu.serve.queue import ServeFuture
from coconut_tpu.signature import Signature
from coconut_tpu.sss import rand_fr

pytestmark = pytest.mark.lifecycle

MSGS = 3
HIDDEN = 1
REVEALED = [1, 2]
THRESHOLD, TOTAL = 2, 3


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def world():
    params = Params.new(MSGS, b"test-lifecycle")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    return SimpleNamespace(
        params=params,
        signers=signers,
        backend=get_backend("python"),
        codec=wire.WireCodec(params),
    )


class StubLifecycleEngine:
    """Everything LifecycleController + Replica touch, inline-resolved:
    verify futures settle immediately, warm_shapes records its input,
    drain records its deadline."""

    def __init__(self, shapes=(), name="stub"):
        self.name = name
        self._shapes = set(shapes)
        self.warm_calls = []
        self.drain_timeouts = []
        self.calls = 0
        self.depth_value = 0
        self.verdict = True

    def depth(self):
        return self.depth_value

    def shape_keys(self):
        return set(self._shapes)

    def warm_shapes(self, shapes):
        self.warm_calls.append(list(shapes))
        warmed = 0
        for s in shapes:
            self._shapes.add(tuple(s))
            warmed += 1
        return warmed, 0

    def drain(self, timeout=None):
        self.drain_timeouts.append(timeout)
        return True

    def submit_verify(self, sig, messages, lane="interactive",
                      max_wait_ms=None):
        self.calls += 1
        self._shapes.add(("verify", "single", (len(messages),)))
        fut = ServeFuture()
        fut.set_result(self.verdict)
        return fut


# --- tentpole: shape manifest ------------------------------------------------


def test_manifest_dedup_and_canonicalization():
    """Lists and tuples that JSON-round-trip equal ARE equal: one
    manifest entry, tuples inside after canonicalization."""
    m = ShapeManifest(
        shapes=[
            ("verify", "single", (8,)),
            ["verify", "single", [8]],  # same shape, JSON spelling
            ("mint", "single", (4, 2)),
            ("bad-entry",),  # malformed: silently dropped
        ],
        engine_name="eng-a",
    )
    assert len(m) == 2
    assert ("verify", "single", (8,)) in m.shapes
    assert ("mint", "single", (4, 2)) in m.shapes


def test_manifest_save_load_roundtrip(tmp_path):
    path = tmp_path / "shapes.json"
    m = ShapeManifest(
        shapes=[("verify", "single", (8,)), ("prepare", "sharded", (16, 3))],
        engine_name="eng-rt",
    )
    m.save(path)
    # atomic write: no tmp litter next to the artifact
    assert [p.name for p in tmp_path.iterdir()] == ["shapes.json"]
    loaded = ShapeManifest.load(path)
    assert loaded.engine_name == "eng-rt"
    assert loaded.shapes == m.shapes
    # the documented schema-1 artifact layout is a promise
    doc = json.loads(path.read_text())
    assert doc["schema"] == 1
    assert {"program": "verify", "placement": "single", "shape": [8]} in (
        doc["shapes"]
    )


def test_manifest_corruption_never_blocks_boot(tmp_path):
    metrics.reset()
    # missing file: empty manifest, no corruption counted
    assert len(ShapeManifest.load(tmp_path / "absent.json")) == 0
    assert metrics.get_count("lifecycle_manifest_corrupt") == 0
    # garbage bytes
    garbage = tmp_path / "garbage.json"
    garbage.write_bytes(b"\x00not json at all")
    assert len(ShapeManifest.load(garbage)) == 0
    assert metrics.get_count("lifecycle_manifest_corrupt") == 1
    # wrong schema
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"schema": 99, "shapes": []}))
    assert len(ShapeManifest.load(stale)) == 0
    assert metrics.get_count("lifecycle_manifest_corrupt") == 2
    # a corrupt manifest on disk does not poison the next save
    ShapeManifest(
        shapes=[("verify", "single", (2,))], engine_name="x"
    ).save(garbage)
    assert ShapeManifest.load(garbage).shapes == [("verify", "single", (2,))]


# --- tentpole: readiness gating ----------------------------------------------


def test_boot_promotes_to_up_only_after_replay(tmp_path):
    metrics.reset()
    path = tmp_path / "m.json"
    ShapeManifest(
        shapes=[("verify", "single", (4,)), ("mint", "single", (2,))],
        engine_name="pred",
    ).save(path)
    clock = FakeClock()
    seen_state = []

    class GatingEngine(StubLifecycleEngine):
        def warm_shapes(self, shapes):
            # the boot gate's whole point: still WARMING mid-replay
            seen_state.append(lc.state)
            clock.advance(1.5)
            return super().warm_shapes(shapes)

    eng = GatingEngine()
    lc = LifecycleController(eng, manifest_path=path, clock=clock)
    assert lc.state == lc_mod.WARMING
    assert not lc.ready()
    assert metrics.get_gauge("lifecycle_state") == 0

    assert lc.boot() == (2, 0)
    assert seen_state == [lc_mod.WARMING]
    assert lc.state == lc_mod.UP and lc.ready()
    assert metrics.get_gauge("lifecycle_state") == 1
    assert metrics.get_gauge("lifecycle_manifest_shapes") == 2
    assert metrics.get_gauge("lifecycle_warmup_s") == pytest.approx(1.5)
    assert metrics.get_count("lifecycle_warmed_shapes") == 2
    # the replayed triples are exactly the manifest's, tuples restored
    assert sorted(eng.warm_calls[0], key=repr) == [
        ("mint", "single", (2,)),
        ("verify", "single", (4,)),
    ]
    # idempotent while UP; refuses after drain (a process never un-drains)
    assert lc.boot() == (2, 0)
    lc.begin_drain(timeout=1.0)
    assert lc.boot() is None
    assert lc.state == lc_mod.CLOSED


def test_missing_manifest_boots_cold_but_up(tmp_path):
    metrics.reset()
    eng = StubLifecycleEngine()
    lc = LifecycleController(eng, manifest_path=tmp_path / "never.json")
    assert lc.boot() == (0, 0)
    assert lc.ready()
    assert metrics.get_gauge("lifecycle_manifest_shapes") == 0


# --- tentpole: graceful drain ------------------------------------------------


def test_drain_shares_one_deadline_and_saves_manifest(tmp_path):
    metrics.reset()
    path = tmp_path / "m.json"
    eng = StubLifecycleEngine(shapes=[("verify", "single", (8,))])
    lc = LifecycleController(eng, manifest_path=path)
    lc.boot()

    assert lc.begin_drain(timeout=5.0) is True
    assert lc.state == lc_mod.CLOSED
    assert metrics.get_gauge("lifecycle_state") == 3
    # the engine's join budget is the REMAINDER of the shared deadline,
    # never a fresh 5 s allowance (and never None)
    assert len(eng.drain_timeouts) == 1
    assert eng.drain_timeouts[0] is not None
    assert 0.0 < eng.drain_timeouts[0] <= 5.0
    # manifest persisted for the successor
    assert ShapeManifest.load(path).shapes == [("verify", "single", (8,))]
    # idempotent: no second engine drain
    assert lc.begin_drain(timeout=5.0) is True
    assert len(eng.drain_timeouts) == 1


def test_successor_warm_boots_from_predecessor_manifest(tmp_path):
    """The restart contract end to end: drain writes, successor reads,
    and the successor's replay receives exactly the predecessor's
    dispatched shape set."""
    path = tmp_path / "hand.json"
    old = StubLifecycleEngine(name="old")
    old_lc = LifecycleController(old, manifest_path=path)
    old_lc.boot()
    old.submit_verify(Signature(None, None), [1, 2, 3]).result(1.0)
    old.submit_verify(Signature(None, None), [1]).result(1.0)
    assert old_lc.begin_drain(timeout=2.0)

    new = StubLifecycleEngine(name="new")
    new_lc = LifecycleController(new, manifest_path=path)
    warmed, skipped = new_lc.boot()
    assert (warmed, skipped) == (2, 0)
    assert sorted(new.warm_calls[0], key=repr) == [
        ("verify", "single", (1,)),
        ("verify", "single", (3,)),
    ]
    assert new_lc.ready()


def test_manifest_save_failure_never_fails_drain(tmp_path):
    metrics.reset()

    class UnsaveableEngine(StubLifecycleEngine):
        def shape_keys(self):
            raise RuntimeError("snapshot exploded")

    lc = LifecycleController(
        UnsaveableEngine(), manifest_path=tmp_path / "m.json"
    )
    lc.boot()
    assert lc.begin_drain(timeout=1.0) is True
    assert lc.state == lc_mod.CLOSED
    assert metrics.get_count("lifecycle_manifest_save_errors") == 1


# --- satellite: replica integration (beacon + retryable refusal) -------------


def test_beacon_reports_lifecycle_states(world):
    drain_sig = Signature(world.params.g, world.params.g)
    eng = StubLifecycleEngine()
    lc = LifecycleController(eng)
    rep = rpc.Replica(eng, world.codec, replica_id="rw", lifecycle=lc)
    assert rep.beacon().state == "warming"
    lc.boot()
    assert rep.beacon().state == "healthy"
    # drain via the REPLICA: refusals + beacon flip before the close
    states_mid_drain = []

    class DrainWatchingEngine(StubLifecycleEngine):
        def drain(self, timeout=None):
            # mid-drain: the beacon must already say "draining" and the
            # program path must already refuse with a RETRYABLE error
            states_mid_drain.append(rep2.beacon().state)
            try:
                client.submit_verify(drain_sig, [1]).result(5.0)
                states_mid_drain.append("admitted")
            except ServiceClosedError:
                states_mid_drain.append("refused-retryable")
            return super().drain(timeout=timeout)

    eng2 = DrainWatchingEngine()
    lc2 = LifecycleController(eng2)
    rep2 = rpc.Replica(eng2, world.codec, replica_id="rd", lifecycle=lc2)
    client = rpc.GatewayClient(
        rpc.LoopbackTransport(rep2), world.codec, api_key="k"
    )
    lc2.boot()
    assert rep2.beacon().state == "healthy"
    assert rep2.begin_drain(timeout=5.0) is True
    assert states_mid_drain == ["draining", "refused-retryable"]
    # after the drain the listener is closed: a dead replica, not a liar
    assert rep2.beacon().state == "down"


def test_service_closed_error_retryable_over_wire():
    """Satellite 1: ServiceClosedError is a ServiceRetryableError and
    the wire envelope round-trips it with retryable=True — the router
    on the far side may fail it over."""
    exc = ServiceClosedError("replica 'r0' is draining: resubmit elsewhere")
    assert isinstance(exc, ServiceRetryableError)
    assert exc.retry_after_s == 0.0  # retry elsewhere IMMEDIATELY
    payload = wire.encode_error(exc, program="verify")
    back = wire.decode_error(payload)
    assert type(back) is ServiceClosedError
    assert isinstance(back, ServiceRetryableError)
    assert back.retry_after_s == 0.0


# --- satellite: router drain handoff -----------------------------------------


def _beacon(rid, state="healthy", depth=0):
    return wire.Beacon(rid, state, 1.0, depth, False, 1, 1, 0.0)


def _sig(world):
    # a wire-encodable signature; the stub engines never inspect it
    return Signature(world.params.g, world.params.g)


class GatedDrainEngine(StubLifecycleEngine):
    """Drain blocks on an event: holds the replica in the DRAINING
    window (_draining set, listener still open) so tests can submit
    traffic mid-drain — the window where refusals are the RETRYABLE
    ServiceClosedError. After close() the refusal is a torn connection
    (TransientBackendError), the crash path, by design."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.drain_started = threading.Event()
        self.drain_gate = threading.Event()

    def drain(self, timeout=None):
        self.drain_started.set()
        assert self.drain_gate.wait(10.0), "drain gate never released"
        return super().drain(timeout=timeout)


def _lifecycle_fleet(world, n=3):
    """n stub replicas (each with a LifecycleController) behind loopback
    transports + a router; returns (router, parts) where parts[rid] is a
    mutable SimpleNamespace(engine, lc, replica, transport)."""
    parts, clients = {}, {}
    for i in range(n):
        rid = "r%d" % i
        eng = GatedDrainEngine(name=rid)
        lc = LifecycleController(eng)
        rep = rpc.Replica(eng, world.codec, replica_id=rid, lifecycle=lc)
        t = rpc.LoopbackTransport(rep)
        parts[rid] = SimpleNamespace(
            engine=eng, lc=lc, replica=rep, transport=t
        )
        clients[rid] = rpc.GatewayClient(t, world.codec, api_key="key-a")
    router = ReplicaRouter(
        clients,
        retry_policy=RetryPolicy(
            max_attempts=n + 1,
            base_delay=0.0,
            jitter=0.0,
            retryable=(TransientBackendError, ServiceClosedError),
            sleep=lambda s: None,
        ),
    )
    return router, parts


def test_drain_handoff_settles_on_successor(world):
    metrics.reset()
    router, parts = _lifecycle_fleet(world)
    for p in parts.values():
        p.lc.boot()
    for rid in parts:
        router.directory.observe(router.clients[rid].poll_beacon())
    assert all(s == gossip.UP for s in router.directory.states().values())

    session = "handoff"
    ring = router.candidates(session)
    primary = ring[0]
    # the primary enters its drain window; the directory does NOT know
    # yet — the refusal itself must teach it
    eng = parts[primary].engine
    drained = []
    drainer = threading.Thread(
        target=lambda: drained.append(
            parts[primary].replica.begin_drain(timeout=10.0)
        )
    )
    drainer.start()
    try:
        assert eng.drain_started.wait(5.0)
        fut = router.submit_verify(_sig(world), [1], session=session)
        assert fut.result(5.0) is True
        assert fut.replica_id != primary
        assert fut.replica_id in ring[1:]
        assert router.directory.state(primary) == gossip.DRAINING
        assert metrics.get_count("gateway_drain_handoffs") >= 1
        # graceful: DRAINING, never DOWN — no misplacements either way
        assert metrics.get_count("gateway_placed_draining") == 0
        assert metrics.get_count("gateway_placed_warming") == 0
        # once the directory knows, new sessions never even try it
        fut2 = router.submit_verify(_sig(world), [1], session=session)
        assert fut2.result(5.0) is True
        assert fut2.replica_id != primary
        assert metrics.get_count("gateway_placed_draining") == 0
    finally:
        eng.drain_gate.set()
        drainer.join(5.0)
    assert drained == [True]


# --- satellite: elastic hysteresis -------------------------------------------


def test_elastic_policy_never_flaps_on_single_sample():
    p = ElasticPolicy(
        min_executors=1, max_executors=4, grow_after=2, shrink_after=3
    )
    # one hot sample: NO resize
    assert p.observe(depth=100, busy=1.0, active=2) is None
    # a disagreeing sample resets the streak
    assert p.observe(depth=1, busy=0.5, active=2) is None
    assert p.observe(depth=100, busy=1.0, active=2) is None
    assert p.observe(depth=100, busy=1.0, active=2) == "grow"
    # after acting the streak restarts: no immediate second grow
    assert p.observe(depth=100, busy=1.0, active=3) is None
    # at the cap: grow suppressed even with a full streak
    assert p.observe(depth=100, busy=1.0, active=4) is None
    assert p.observe(depth=100, busy=1.0, active=4) is None

    # shrink needs THREE consecutive idle samples
    assert p.observe(depth=0, busy=0.0, active=4) is None
    assert p.observe(depth=0, busy=0.0, active=4) is None
    assert p.observe(depth=0, busy=0.0, active=4) == "shrink"
    # at the floor: shrink suppressed
    for _ in range(5):
        assert p.observe(depth=0, busy=0.0, active=1) is None


def test_elastic_controller_drives_park_and_unpark():
    metrics.reset()
    clock = FakeClock()

    class ElasticStubEngine:
        def __init__(self):
            self.active = 3
            self.depth_value = 0
            self._executors = ()
            self.parked = []
            self.unparked = []

        def total_depth(self):
            return self.depth_value

        def active_pool_size(self):
            return self.active

        def park_executor(self, label=None):
            self.active -= 1
            self.parked.append("dev%d" % self.active)
            return self.parked[-1]

        def unpark_executor(self, label=None):
            if not self.parked:
                return None
            self.active += 1
            self.unparked.append(self.parked.pop())
            return self.unparked[-1]

    eng = ElasticStubEngine()
    ctl = ElasticController(
        eng,
        policy=ElasticPolicy(
            min_executors=1, grow_after=2, shrink_after=3
        ),
        clock=clock,
    )
    # warm-up sample: no busy fraction to difference over yet
    assert ctl.tick() is None
    # three consecutive idle samples -> ONE park, no flapping after
    decisions = []
    for _ in range(4):
        clock.advance(1.0)
        decisions.append(ctl.tick())
    assert decisions.count("shrink") == 1
    assert eng.parked == ["dev2"]
    assert metrics.get_count("elastic_shrunk") == 1
    # pressure returns: queue floods -> unpark after the grow window
    eng.depth_value = 50
    decisions = []
    for _ in range(3):
        clock.advance(1.0)
        decisions.append(ctl.tick())
    assert decisions.count("grow") == 1
    assert eng.unparked == ["dev2"]
    assert metrics.get_count("elastic_grown") == 1
    # nothing parked + grow signal: acting is a no-op, not a crash
    for _ in range(3):
        clock.advance(1.0)
        ctl.tick()
    assert metrics.get_count("elastic_grown") == 1


def test_elastic_busy_fraction_from_device_timers():
    """sample() differences the serve_dev*_busy_s timers over the
    interval: 1.5 busy-seconds across 3 executors in 1 s -> 0.5."""
    clock = FakeClock()
    eng = SimpleNamespace(
        total_depth=lambda: 0,
        active_pool_size=lambda: 3,
        _executors=tuple(
            SimpleNamespace(busy_timer="serve_dev%d_busy_s" % i)
            for i in range(3)
        ),
    )
    ctl = ElasticController(eng, clock=clock)
    depth, busy, active = ctl.sample()
    assert busy is None  # warm-up
    # fabricate device busy time the way the executors would accrue it
    with metrics._lock:
        for i in range(3):
            metrics._timers["serve_dev%d_busy_s" % i] += 0.5
    clock.advance(1.0)
    depth, busy, active = ctl.sample()
    assert busy == pytest.approx(0.5)
    assert active == 3
    # no further accrual: next interval reads fully idle
    clock.advance(1.0)
    _, busy, _ = ctl.sample()
    assert busy == 0.0


# --- satellite: elastic park/unpark on a REAL engine -------------------------


def test_real_engine_park_is_invisible_to_health(world):
    """Parking shrinks the pool without looking like degradation: the
    capacity fraction stays 1.0 (brownout never trips), the parked
    executor gets NO dispatches, and unpark restores it to service."""
    metrics.reset()
    eng = ProtocolEngine(
        world.signers,
        world.params,
        THRESHOLD,
        count_hidden=HIDDEN,
        revealed_msg_indices=REVEALED,
        backend=world.backend,
        devices=4,
        max_batch=4,
        max_wait_ms=5.0,
    ).start()
    try:
        sig = Signature(world.params.g, world.params.g)
        msgs = [rand_fr() for _ in range(MSGS)]
        assert eng.active_pool_size() == 4
        assert eng.submit_verify(sig, msgs).result(60.0) in (True, False)

        parked = eng.park_executor()
        assert parked is not None
        assert eng.parked_executors() == {parked}
        assert eng.active_pool_size() == 3
        # intentional shrink is NOT degradation
        assert eng._capacity_fraction() == pytest.approx(1.0)
        parked_ex = next(
            ex for ex in eng._executors if ex.label == parked
        )
        assert not parked_ex.has_worker()

        before = dict(metrics.counters_with_prefix("serve_dev"))
        futs = [eng.submit_verify(sig, msgs) for _ in range(12)]
        assert all(f.result(60.0) in (True, False) for f in futs)
        after = metrics.counters_with_prefix("serve_dev")
        key = "serve_dev%s_dispatches" % parked
        assert after.get(key, 0) == before.get(key, 0), (
            "parked executor %s was dispatched to" % parked
        )

        # never parks down to zero
        while eng.park_executor() is not None:
            pass
        assert eng.active_pool_size() == 1
        assert eng.park_executor() is None

        # unpark: the PR 9 respawn path brings it straight back
        label = eng.unpark_executor()
        assert label is not None
        assert eng.active_pool_size() == 2
        revived = next(ex for ex in eng._executors if ex.label == label)
        assert revived.has_worker()
        futs = [eng.submit_verify(sig, msgs) for _ in range(8)]
        assert all(f.result(60.0) in (True, False) for f in futs)
    finally:
        assert eng.drain(timeout=60.0)


# --- tentpole: the rolling-restart drill -------------------------------------


def test_rolling_restart_drill_drops_nothing(world, tmp_path):
    """The PR's acceptance drill, deterministic over loopback: a
    3-replica fleet restarted in sequence under mixed traffic. Every
    future settles, zero non-retryable client errors, the router never
    places a session on a WARMING or DRAINING replica (audited from the
    gateway_placed_* counters), and each restart hands its shape
    manifest to its successor."""
    metrics.reset()
    router, parts = _lifecycle_fleet(world)
    manifest_paths = {
        rid: tmp_path / ("%s.json" % rid) for rid in parts
    }
    for rid, p in parts.items():
        p.lc.manifest_path = manifest_paths[rid]
        p.lc.boot()
    # pollers read THROUGH router.clients so a restarted replica's fresh
    # client is what the next sweep polls (same wiring as the probe)
    gossip_loop = gossip.GossipLoop(
        router.directory,
        {
            rid: (lambda r=rid: router.clients[r].poll_beacon(timeout=2.0))
            for rid in parts
        },
        clock=FakeClock(),
    )
    gossip_loop.step()
    assert all(
        s == gossip.UP for s in router.directory.states().values()
    )

    sig = _sig(world)
    # guaranteed coverage: four sessions ring-primaried on EACH replica,
    # so every drain window provably exercises the graceful handoff
    by_primary = {rid: [] for rid in parts}
    i = 0
    while any(len(v) < 4 for v in by_primary.values()):
        s = "sess-%d" % i
        i += 1
        owner = router.candidates(s)[0]
        if len(by_primary[owner]) < 4:
            by_primary[owner].append(s)
    sessions = [s for v in by_primary.values() for s in v]
    settled = 0

    def traffic(tag):
        nonlocal settled
        futs = [
            router.submit_verify(sig, [1, 2], session=s) for s in sessions
        ]
        for f in futs:
            assert f.result(5.0) is True, "dangling future during %s" % tag
            settled += 1

    traffic("steady-state")

    for rid in sorted(parts):
        old = parts[rid]
        # 1) drain window: refusals are retryable handoffs onto ring
        # successors while in-flight work settles, then manifest saved
        drained = []
        drainer = threading.Thread(
            target=lambda o=old: drained.append(
                o.replica.begin_drain(timeout=10.0)
            )
        )
        drainer.start()
        assert old.engine.drain_started.wait(5.0)
        traffic("drain of %s" % rid)  # refusal -> successor handoff
        old.engine.drain_gate.set()
        drainer.join(5.0)
        assert drained == [True], "drain of %s failed" % rid
        assert manifest_paths[rid].exists()
        gossip_loop.step()  # closed listener -> a miss, not a lie

        # 2) restart: fresh engine + controller, beacon says WARMING
        eng = StubLifecycleEngine(name=rid)
        lc = LifecycleController(
            eng, manifest_path=manifest_paths[rid]
        )
        rep = rpc.Replica(
            eng, world.codec, replica_id=rid, lifecycle=lc
        )
        parts[rid] = SimpleNamespace(
            engine=eng, lc=lc, replica=rep, transport=None
        )
        old_client = router.clients[rid]
        router.clients[rid] = rpc.GatewayClient(
            rpc.LoopbackTransport(rep), world.codec, api_key="key-a"
        )
        old_client.close()
        gossip_loop.step()
        assert router.directory.state(rid) == gossip.WARMING
        # traffic while WARMING: the router must route around it
        traffic("warming of %s" % rid)

        # 3) boot: manifest replayed (warm restart), THEN readmitted
        warmed, _skipped = lc.boot()
        assert warmed >= 1, "successor of %s booted cold" % rid
        assert eng.warm_calls, "manifest replay never reached the engine"
        gossip_loop.step()
        assert router.directory.state(rid) == gossip.UP
        traffic("post-boot of %s" % rid)

    # -- the drill's verdicts ------------------------------------------------
    assert settled == len(sessions) * (1 + 3 * 3)
    # the router provably never misplaced: all placements landed on
    # UP/DEGRADED replicas through three full restart cycles
    assert metrics.get_count("gateway_placed_warming") == 0
    assert metrics.get_count("gateway_placed_draining") == 0
    assert metrics.get_count("gateway_placed_up") > 0
    # every restart was observed as an orderly drain at least once
    assert metrics.get_count("gateway_drain_handoffs") >= 3
    # and the whole fleet ends UP
    assert all(
        s == gossip.UP for s in router.directory.states().values()
    )
