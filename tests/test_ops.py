"""Tests for the low-level ops layer (fields, curves, pairing, hashing,
serialization). Oracles are algebraic identities on random inputs, following
the reference's test style (SURVEY.md §4: no golden files, no mocks), plus
the negative/serialization coverage the reference lacked."""

import random

import pytest

from coconut_tpu.errors import DeserializationError
from coconut_tpu.ops import pairing as pr
from coconut_tpu.ops import serialize as ser
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import (
    BLS_X,
    FP2_ONE,
    FP12_ONE,
    P,
    R,
    fp2_inv,
    fp2_mul,
    fp2_pow,
    fp2_sq,
    fp2_sqrt,
    fp12_frobenius,
    fp12_frobenius2,
    fp12_inv,
    fp12_mul,
    fp12_pow,
    fp_inv,
    fp_sqrt,
)
from coconut_tpu.ops.hashing import (
    expand_message_xmd,
    hash_to_fr,
    hash_to_g1,
    hash_to_g2,
)

rng = random.Random(0xC0C0)


def rand_fp():
    return rng.randrange(P)


def rand_fp2():
    return (rand_fp(), rand_fp())


def rand_fp12():
    return tuple(
        tuple(tuple(rand_fp2() for _ in range(1))[0] for _ in range(3))
        for _ in range(2)
    )


def rand_fr():
    return rng.randrange(1, R)


class TestFields:
    def test_curve_parameter_identities(self):
        assert R == BLS_X**4 - BLS_X**2 + 1
        assert P == (BLS_X - 1) ** 2 // 3 * R + BLS_X

    def test_fp_inv(self):
        for _ in range(10):
            a = rng.randrange(1, P)
            assert a * fp_inv(a) % P == 1
        with pytest.raises(ZeroDivisionError):
            fp_inv(0)

    def test_fp_sqrt(self):
        for _ in range(10):
            a = rand_fp()
            s = fp_sqrt(a * a % P)
            assert s is not None and s * s % P == a * a % P
        # a non-residue: -1 is a non-residue mod p (p = 3 mod 4)
        assert fp_sqrt(P - 1) is None

    def test_fp2_mul_inv(self):
        for _ in range(10):
            a, b = rand_fp2(), rand_fp2()
            # commutativity + distributivity spot-check
            assert fp2_mul(a, b) == fp2_mul(b, a)
            assert fp2_mul(a, fp2_inv(a)) == FP2_ONE
        # (u)^2 == -1
        assert fp2_sq((0, 1)) == (P - 1, 0)

    def test_fp2_sqrt(self):
        for _ in range(10):
            a = rand_fp2()
            sq = fp2_sq(a)
            s = fp2_sqrt(sq)
            assert s is not None and fp2_sq(s) == sq

    def test_fp2_pow_matches_repeated_mul(self):
        a = rand_fp2()
        acc = FP2_ONE
        for i in range(8):
            assert fp2_pow(a, i) == acc
            acc = fp2_mul(acc, a)

    def test_fp12_mul_inv_assoc(self):
        a, b, c = rand_fp12(), rand_fp12(), rand_fp12()
        assert fp12_mul(a, fp12_mul(b, c)) == fp12_mul(fp12_mul(a, b), c)
        assert fp12_mul(a, fp12_inv(a)) == FP12_ONE

    def test_frobenius_is_pth_power(self):
        a = rand_fp12()
        assert fp12_frobenius(a) == fp12_pow(a, P)
        assert fp12_frobenius2(a) == fp12_pow(a, P * P)


class TestCurve:
    def test_generators(self):
        assert g1.is_on_curve(G1_GEN) and g1.mul(G1_GEN, R) is None
        assert g2.is_on_curve(G2_GEN) and g2.mul(G2_GEN, R) is None

    def test_group_laws_g1(self):
        a, b = rand_fr(), rand_fr()
        pa, pb = g1.mul(G1_GEN, a), g1.mul(G1_GEN, b)
        assert g1.add(pa, pb) == g1.mul(G1_GEN, (a + b) % R)
        assert g1.add(pa, None) == pa
        assert g1.add(pa, g1.neg(pa)) is None
        assert g1.double(pa) == g1.add(pa, pa)
        assert g1.is_on_curve(pa)

    def test_group_laws_g2(self):
        a, b = rand_fr(), rand_fr()
        qa, qb = g2.mul(G2_GEN, a), g2.mul(G2_GEN, b)
        assert g2.add(qa, qb) == g2.mul(G2_GEN, (a + b) % R)
        assert g2.add(qa, g2.neg(qa)) is None
        assert g2.double(qa) == g2.add(qa, qa)
        assert g2.is_on_curve(qa)

    @pytest.mark.parametrize("grp,gen", [(g1, G1_GEN), (g2, G2_GEN)])
    def test_msm_matches_naive(self, grp, gen):
        pts = [grp.mul(gen, rand_fr()) for _ in range(5)]
        ks = [rand_fr() for _ in range(5)]
        expected = None
        for pt, k in zip(pts, ks):
            expected = grp.add(expected, grp.mul(pt, k))
        assert grp.msm(pts, ks) == expected

    def test_msm_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            g1.msm([G1_GEN], [1, 2])

    def test_msm_zero_scalars(self):
        assert g1.msm([G1_GEN, g1.double(G1_GEN)], [0, 0]) is None


class TestPairing:
    def test_bilinearity(self):
        a, b = rand_fr(), rand_fr()
        e_ab = pr.pairing(g1.mul(G1_GEN, a), g2.mul(G2_GEN, b))
        e_base = pr.pairing(G1_GEN, G2_GEN)
        assert e_ab == fp12_pow(e_base, a * b % R)
        # swap sides
        assert e_ab == pr.pairing(g1.mul(G1_GEN, a * b % R), G2_GEN)

    def test_non_degenerate(self):
        assert pr.pairing(G1_GEN, G2_GEN) != FP12_ONE

    def test_identity_inputs(self):
        assert pr.pairing(None, G2_GEN) == FP12_ONE
        assert pr.pairing(G1_GEN, None) == FP12_ONE

    def test_final_exp_matches_slow(self):
        f = rand_fp12()
        assert pr.final_exp(f) == pr.final_exp_slow(f)

    def test_final_exp_chain_matches(self):
        # The x-power-chain form (TPU backend blueprint) equals the
        # Frobenius multi-exp form on arbitrary Fp12 inputs.
        for _ in range(3):
            f = rand_fp12()
            assert pr.final_exp_chain(f) == pr.final_exp(f)

    def test_projective_miller_matches_affine(self):
        # Projective (inversion-free, backend blueprint) and affine (oracle)
        # Miller loops agree after final exponentiation; the raw Miller
        # values differ by the Fp4-subfield line scalings.
        for _ in range(3):
            a, b = rand_fr(), rand_fr()
            p1, q2 = g1.mul(G1_GEN, a), g2.mul(G2_GEN, b)
            assert pr.final_exp(pr.miller_loop_projective(p1, q2)) == (
                pr.final_exp(pr.miller_loop(p1, q2))
            )

    def test_projective_miller_identity_inputs(self):
        assert pr.miller_loop_projective(None, G2_GEN) == FP12_ONE
        assert pr.miller_loop_projective(G1_GEN, None) == FP12_ONE

    def test_pairing_check_product(self):
        # e(P, bQ) * e(-bP, Q) == 1
        b = rand_fr()
        assert pr.pairing_check(
            [(G1_GEN, g2.mul(G2_GEN, b)), (g1.neg(g1.mul(G1_GEN, b)), G2_GEN)]
        )
        # and a wrong statement fails
        assert not pr.pairing_check(
            [(G1_GEN, g2.mul(G2_GEN, b)), (g1.neg(G1_GEN), G2_GEN)]
        )


class TestHashing:
    def test_expand_message_xmd_lengths(self):
        out = expand_message_xmd(b"abc", b"DST", 99)
        assert len(out) == 99
        # deterministic
        assert out == expand_message_xmd(b"abc", b"DST", 99)
        # msg and dst separation
        assert expand_message_xmd(b"abc", b"DST2", 99) != out
        assert expand_message_xmd(b"abd", b"DST", 99) != out

    def test_hash_to_fr_range_and_determinism(self):
        c = hash_to_fr(b"challenge input")
        assert 0 <= c < R
        assert c == hash_to_fr(b"challenge input")
        assert c != hash_to_fr(b"challenge inpuu")

    def test_hash_to_g1_subgroup(self):
        p = hash_to_g1(b"test : g")
        assert g1.is_on_curve(p) and g1.mul(p, R) is None
        assert hash_to_g1(b"test : g") == p
        assert hash_to_g1(b"other") != p

    def test_hash_to_g2_subgroup(self):
        q = hash_to_g2(b"test : g_tilde")
        assert g2.is_on_curve(q) and g2.mul(q, R) is None


class TestSerialize:
    def test_fr_roundtrip(self):
        a = rand_fr()
        assert ser.fr_from_bytes(ser.fr_to_bytes(a)) == a
        with pytest.raises(DeserializationError):
            ser.fr_from_bytes(R.to_bytes(32, "big"))

    def test_g1_roundtrip(self):
        p = g1.mul(G1_GEN, rand_fr())
        assert ser.g1_from_bytes(ser.g1_to_bytes(p)) == p
        assert ser.g1_from_bytes(ser.g1_to_bytes(None)) is None
        assert ser.g1_from_compressed(ser.g1_to_compressed(p)) == p
        assert ser.g1_from_compressed(ser.g1_to_compressed(None)) is None

    def test_g2_roundtrip(self):
        q = g2.mul(G2_GEN, rand_fr())
        assert ser.g2_from_bytes(ser.g2_to_bytes(q)) == q
        assert ser.g2_from_bytes(ser.g2_to_bytes(None)) is None
        assert ser.g2_from_compressed(ser.g2_to_compressed(q)) == q
        assert ser.g2_from_compressed(ser.g2_to_compressed(None)) is None

    def test_g1_rejects_off_curve(self):
        bad = ser.fp_to_bytes(5) + ser.fp_to_bytes(7)
        with pytest.raises(DeserializationError):
            ser.g1_from_bytes(bad)

    def test_g1_rejects_non_subgroup(self):
        # find a curve point not in the r-torsion (cofactor > 1)
        x = 1
        while True:
            y2 = (x * x * x + 4) % P
            y = fp_sqrt(y2)
            if y is not None:
                cand = (x, y)
                if g1.mul(cand, R) is not None:
                    break
            x += 1
        with pytest.raises(DeserializationError):
            ser.g1_from_bytes(ser.fp_to_bytes(cand[0]) + ser.fp_to_bytes(cand[1]))


class TestPadLaneSemantics:
    """The pad-lane contract the PR-16 RLC batch verifier leans on
    (tpu/pairing.multi_miller_loop docstring): a pair with valid=0 — at
    the spec level, a pair containing an identity (None) point —
    contributes EXACTLY the GT identity to the product, so pad lanes
    never change a batch's verdict no matter where they sit."""

    def _good_bad_pad(self):
        b = rand_fr()
        good = [
            (G1_GEN, g2.mul(G2_GEN, b)),
            (g1.neg(g1.mul(G1_GEN, b)), G2_GEN),
        ]
        bad = [(G1_GEN, g2.mul(G2_GEN, b)), (g1.neg(G1_GEN), G2_GEN)]
        pad = (None, G2_GEN)
        return good, bad, pad

    @pytest.fixture(scope="class")
    def jaxbe(self):
        try:
            import jax  # noqa: F401

            from coconut_tpu.tpu import backend as _jb  # noqa: F401
        except ImportError:
            pytest.skip("jax backend unavailable")
        from coconut_tpu.backend import get_backend

        return get_backend("jax")

    def test_all_pad_row_is_identity(self, jaxbe):
        # every lane valid=0: the empty product, i.e. GT identity -> True
        _, _, pad = self._good_bad_pad()
        assert pr.pairing_check([pad, pad])
        got = jaxbe.pairing_product_is_one([[pad, pad], [pad, pad]])
        assert got == [True, True]

    def test_ragged_final_batch_pad(self, jaxbe):
        # a short final row padded out with None pairs keeps its
        # unpadded verdict — both polarities
        good, bad, pad = self._good_bad_pad()
        rows = [good + [pad, pad], bad + [pad, pad]]
        assert pr.pairing_check(rows[0]) and not pr.pairing_check(rows[1])
        got = jaxbe.pairing_product_is_one(rows)
        assert got == [True, False]

    def test_interleaved_pad_lanes(self, jaxbe):
        # pad position is irrelevant: leading, interleaved, trailing
        good, bad, pad = self._good_bad_pad()
        layouts = [
            [pad] + good + [pad],
            [good[0], pad, good[1], pad],
            [pad, bad[0], pad, bad[1]],
        ]
        expect = [True, True, False]
        assert [pr.pairing_check(r) for r in layouts] == expect
        assert jaxbe.pairing_product_is_one(layouts) == expect

    def test_pad_coordinates_are_inert(self, jaxbe):
        # a valid=0 lane's PARTNER coordinates may be arbitrary curve
        # points without perturbing the product (the Miller lines are
        # masked per step, not post-hoc)
        good, _, _ = self._good_bad_pad()
        junk1 = g1.mul(G1_GEN, rand_fr())
        junk2 = g2.mul(G2_GEN, rand_fr())
        rows = [
            good + [(None, junk2), (junk1, None)],
            good + [(None, G2_GEN), (G1_GEN, None)],
        ]
        assert jaxbe.pairing_product_is_one(rows) == [True, True]
