"""Dealerless key-lifecycle suite (PR 15).

Covers the PR-15 contract surface:

  - SSS REJECT PATHS: every bad-share path raises the typed
    ShareVerificationError naming the dealer (tampered share, wrong
    recipient id, own share echoed back, duplicate dealer) and bad
    thresholds refuse up front;
  - ONLINE DKG: complaints name the corrupt dealer EXACTLY, unreachable
    quorums abort with the typed retryable DkgAbortedError, and no code
    path materializes the master secret (enforced two ways: the
    DkgResult shape is pinned, and the in-process aggregation entry
    points are booby-trapped for the whole manager surface);
  - PROACTIVE REFRESH: the verkey stays bit-identical while EVERY share
    changes; a secret-shifting dealer is complained against and
    excluded without moving the verkey;
  - EPOCH REGISTRY: monotonic ids, two-phase PENDING->ACTIVE handoff,
    window-pressure retirement (pins defer it), typed
    EpochUnknownError/EpochRetiredError carrying the live set;
  - EPOCH-KEYED STATIC-OPERAND CACHE: two epochs' verkey fingerprints
    coexist in the 32-entry LRU without evict-thrash;
  - THE ROLLOVER CHAOS DRILL: a 5-authority engine behind the RPC
    gateway performs DKG (with a corrupt dealer named + excluded),
    serves mints, takes one proactive refresh and one t/n reshare under
    in-flight traffic, and every pre-rollover credential verifies
    post-rollover under its mint epoch — zero dangling futures, zero
    engine-side terminal errors, wrong-epoch verification rejects, and
    retirement out of the window refuses typed through the envelope.
"""

from types import SimpleNamespace

import pytest

from coconut_tpu import metrics
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import (
    DkgAbortedError,
    EpochRetiredError,
    EpochUnknownError,
    GeneralError,
    ServiceRetryableError,
    ShareVerificationError,
)
from coconut_tpu.keylife import (
    ACTIVE,
    EPOCH_STATE_CODES,
    EPOCH_STATE_OF_CODE,
    DkgResult,
    EpochRegistry,
    KeyLifecycleManager,
    KeySet,
    PENDING,
    RETIRED,
    RETIRING,
    run_dkg,
    run_refresh,
)
from coconut_tpu.net import gossip, rpc, wire
from coconut_tpu.params import Params
from coconut_tpu.sss import (
    PedersenDVSSParticipant,
    PedersenVSS,
    get_shared_secret,
    rand_fr,
    reconstruct_secret,
)

pytestmark = pytest.mark.keylife

MSGS = 2
HIDDEN = 1
REVEALED = [1]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def params():
    return Params.new(MSGS, b"test-keylife")


@pytest.fixture(scope="module")
def gens():
    return PedersenVSS.gens(b"test-keylife")


def _stub_keyset(epoch, gen=0):
    """Registry-only KeySet: the registry never inspects key material."""
    return KeySet(epoch, gen, 2, [], vk=None)


# --- satellite: sss reject paths --------------------------------------------


def test_check_share_rejects_tampered_share(gens):
    g, h = gens
    _, _, comm, s_shares, t_shares = PedersenVSS.deal(2, 3, g, h)
    good = (s_shares[2], t_shares[2])
    PedersenVSS.check_share(2, 2, good, comm, g, h)  # honest passes
    with pytest.raises(ShareVerificationError) as ei:
        PedersenVSS.check_share(
            2, 2, ((good[0] + 1) % (1 << 255), good[1]), comm, g, h,
            dealer_id=7, round="dkg",
        )
    assert ei.value.dealer_id == 7
    assert ei.value.round == "dkg"
    assert ei.value.code == "share_rejected"
    assert not PedersenVSS.verify_share(
        2, 2, (good[0] + 1, good[1]), comm, g, h
    )


def test_check_share_rejects_tampered_commitment(gens):
    g, h = gens
    _, _, comm, s_shares, t_shares = PedersenVSS.deal(2, 3, g, h)
    bad = dict(comm)
    bad[1] = PedersenVSS.ops.add(bad[1], g)  # dealer lied about a coeff
    with pytest.raises(ShareVerificationError):
        PedersenVSS.check_share(
            2, 1, (s_shares[1], t_shares[1]), bad, g, h, dealer_id=1
        )


def test_check_share_rejects_wrong_recipient_id(gens):
    g, h = gens
    _, _, comm, s_shares, t_shares = PedersenVSS.deal(2, 3, g, h)
    # share dealt for id 2, presented as id 3: never verifies
    with pytest.raises(ShareVerificationError):
        PedersenVSS.check_share(
            2, 3, (s_shares[2], t_shares[2]), comm, g, h, dealer_id=1
        )


def test_deal_rejects_bad_threshold(gens):
    g, h = gens
    for t, n in ((4, 3), (0, 3)):
        with pytest.raises(GeneralError):
            PedersenVSS.deal(t, n, g, h)
        with pytest.raises(GeneralError):
            PedersenVSS.deal_zero(t, n, g, h)
        with pytest.raises(GeneralError):
            get_shared_secret(t, n)


def test_dvss_rejects_own_share_and_duplicate_dealer(gens):
    g, h = gens
    p = PedersenDVSSParticipant(1, 2, 3, g, h)
    dealer = PedersenDVSSParticipant(2, 2, 3, g, h)
    share = (dealer.s_shares[1], dealer.t_shares[1])
    with pytest.raises(ShareVerificationError) as ei:
        p.received_share(1, p.comm_coeffs, (p.s_shares[1], p.t_shares[1]),
                         2, 3, g, h)
    assert ei.value.dealer_id == 1
    p.received_share(2, dealer.comm_coeffs, share, 2, 3, g, h)
    with pytest.raises(ShareVerificationError) as ei:
        p.received_share(2, dealer.comm_coeffs, share, 2, 3, g, h)
    assert ei.value.dealer_id == 2  # the duplicate dealer is named


def test_deal_zero_shares_a_verifiable_zero(gens):
    g, h = gens
    blind0, comm, s_shares, t_shares = PedersenVSS.deal_zero(3, 5, g, h)
    # the published degree-0 blinding opens the zero commitment
    assert comm[0] == PedersenVSS.ops.mul(h, blind0)
    # the shared secret really is zero
    assert reconstruct_secret(3, s_shares) == 0
    # and each share still Pedersen-verifies
    for i in range(1, 6):
        PedersenVSS.check_share(3, i, (s_shares[i], t_shares[i]), comm, g, h)


# --- tentpole: online DKG ---------------------------------------------------


def _tamper_one(dealer, recipient, dim=0):
    def tamper(d, r, dm, share):
        if (d, r, dm) == (dealer, recipient, dim):
            return ((share[0] + 1), share[1])
        return None

    return tamper


def test_dkg_complaints_name_corrupt_dealer_exactly(params, gens):
    g, h = gens
    result = run_dkg(3, 5, params, g, h, tamper=_tamper_one(2, 4))
    assert result.complaints == {2: (4,)}  # exactly dealer 2, by rec 4
    assert result.excluded == (2,)
    assert result.qual == (1, 3, 4, 5)
    # the excluded DEALER still received key shares (it can sign later)
    assert sorted(s.id for s in result.signers) == [1, 2, 3, 4, 5]


def test_dkg_aborts_typed_when_quorum_unreachable(params, gens):
    g, h = gens
    with pytest.raises(DkgAbortedError) as ei:
        run_dkg(4, 5, params, g, h, unreachable={1, 2})
    err = ei.value
    assert isinstance(err, ServiceRetryableError)  # retriable by type
    assert err.code == "dkg_aborted"
    assert (err.needed, err.qualified) == (4, 3)
    assert err.excluded == (1, 2)


def test_dkg_result_carries_no_master_secret(params, gens):
    """The acceptance invariant: DkgResult holds per-signer shares and
    the dealer audit trail — never the reconstructed master secret."""
    g, h = gens
    result = run_dkg(2, 3, params, g, h)
    assert DkgResult._fields == (
        "signers", "qual", "excluded", "complaints", "threshold", "total",
    )
    # reconstruct the master secrets independently (test-only!) and
    # assert they appear nowhere in the round's output
    master = {reconstruct_secret(2, {s.id: s.sigkey.x for s in result.signers})}
    for j in range(MSGS):
        master.add(
            reconstruct_secret(
                2, {s.id: s.sigkey.y[j] for s in result.signers}
            )
        )
    for s in result.signers:
        assert s.sigkey.x not in master
        assert master.isdisjoint(s.sigkey.y)
    assert master.isdisjoint(result.qual)
    assert master.isdisjoint(result.excluded)
    assert master.isdisjoint({result.threshold, result.total})


def test_online_lifecycle_never_aggregates_in_process(params, monkeypatch):
    """Booby-trap every in-process master-secret aggregation entry point
    (sss.reconstruct_secret / sss.get_shared_secret / keygen's dealer and
    DVSS drivers): the whole manager surface — bootstrap, refresh,
    reshare — must complete without touching any of them. Only the test
    alias setup_signers_for_test may aggregate in-process."""
    import coconut_tpu.keygen as keygen_mod
    import coconut_tpu.sss as sss_mod

    def boom(*a, **k):
        raise AssertionError(
            "master-secret aggregation on the online DKG path"
        )

    for mod, name in (
        (sss_mod, "reconstruct_secret"),
        (sss_mod, "get_shared_secret"),
        (keygen_mod, "get_shared_secret"),
        (keygen_mod, "dvss_keygen"),
        (keygen_mod, "setup_signers_for_test"),
        (keygen_mod, "trusted_party_SSS_keygen"),
    ):
        monkeypatch.setattr(mod, name, boom)
    mgr = KeyLifecycleManager(params, label=b"keylife-noagg")
    ks1 = mgr.bootstrap(2, 3)
    ks1r = mgr.refresh()
    ks2 = mgr.reshare()
    assert (ks1.epoch, ks1r.gen, ks2.epoch) == (1, 1, 2)


# --- tentpole: proactive refresh --------------------------------------------


def _share_map(signers):
    return {s.id: (s.sigkey.x, tuple(s.sigkey.y)) for s in signers}


def test_refresh_same_verkey_all_shares_change(params):
    mgr = KeyLifecycleManager(params, label=b"keylife-refresh")
    ks1 = mgr.bootstrap(3, 5)
    before = _share_map(ks1.signers)
    ks1r = mgr.refresh()
    after = _share_map(ks1r.signers)
    ctx = params.ctx
    assert ks1r.vk.to_bytes(ctx) == ks1.vk.to_bytes(ctx)  # bit-identical
    assert (ks1r.epoch, ks1r.gen) == (ks1.epoch, ks1.gen + 1)
    for i in before:
        assert before[i][0] != after[i][0]  # every x share changed
        for y_old, y_new in zip(before[i][1], after[i][1]):
            assert y_old != y_new  # every y share changed
    # the registry now serves the new gen under the SAME epoch
    assert mgr.registry.resolve(ks1.epoch).gen == ks1.gen + 1


def test_refresh_excludes_secret_shifting_dealer(params):
    """A dealer whose refresh share fails verification is complained
    against and excluded — and the round STILL leaves the verkey
    bit-identical (the shift never lands)."""
    mgr = KeyLifecycleManager(params, label=b"keylife-refresh-bad")
    ks1 = mgr.bootstrap(3, 5)
    ks1r = mgr.refresh(tamper=_tamper_one(3, 1))
    assert mgr.last_round.complaints == {3: (1,)}
    assert 3 not in mgr.last_round.qual
    assert ks1r.vk.to_bytes(params.ctx) == ks1.vk.to_bytes(params.ctx)


def test_refresh_aborts_when_quorum_unreachable(params, gens):
    g, h = gens
    result = run_dkg(3, 4, params, g, h)
    with pytest.raises(DkgAbortedError):
        run_refresh(result.signers, 3, params, g, h, unreachable={1, 2})


# --- epoch registry ---------------------------------------------------------


def test_registry_two_phase_and_monotonic_ids():
    reg = EpochRegistry(window=3)
    assert reg.next_epoch() == 1
    ks = _stub_keyset(1)
    reg.register(ks)
    assert reg.state(1) == PENDING
    with pytest.raises(EpochUnknownError):
        reg.resolve(1)  # registered but NOT yet activated
    reg.activate(1)
    assert reg.state(1) == ACTIVE
    assert reg.resolve(1) is ks
    with pytest.raises(GeneralError, match="monotonic"):
        reg.register(_stub_keyset(1))
    with pytest.raises(GeneralError, match="not pending"):
        reg.activate(1)
    with pytest.raises(GeneralError, match="unknown"):
        reg.activate(9)


def test_registry_window_pressure_retires_oldest():
    metrics.reset()
    reg = EpochRegistry(window=2)
    for e in (1, 2, 3, 4):
        reg.register(_stub_keyset(e))
        reg.activate(e)
    assert reg.live_epochs() == [(3, RETIRING), (4, ACTIVE)]
    assert reg.state(1) == RETIRED
    assert reg.state(2) == RETIRED
    with pytest.raises(EpochRetiredError) as ei:
        reg.resolve(1)
    assert ei.value.epoch == 1
    assert ei.value.live == (3, 4)  # carried for client re-resolution
    with pytest.raises(EpochUnknownError) as ei:
        reg.resolve(99)
    assert ei.value.live == (3, 4)
    assert metrics.get_count("keylife_retirements") == 2
    assert metrics.get_count("keylife_epoch_retired") == 1
    assert metrics.get_count("keylife_epoch_unknown") == 1


def test_registry_pins_defer_retirement():
    reg = EpochRegistry(window=1)
    reg.register(_stub_keyset(1))
    reg.activate(1)
    pinned = reg.pin_active()
    reg.register(_stub_keyset(2))
    reg.activate(2)
    # over the window, but epoch 1 has an open fan-out: retirement waits
    assert reg.state(1) == RETIRING
    assert reg.resolve(1) is pinned
    assert reg.pin_count(1) == 1
    reg.unpin(pinned)
    assert reg.state(1) == RETIRED
    with pytest.raises(EpochRetiredError):
        reg.resolve(1)


def test_registry_refresh_gen_pins_coexist():
    reg = EpochRegistry(window=3)
    ks_g0 = _stub_keyset(1, gen=0)
    reg.register(ks_g0)
    reg.activate(1)
    pinned_old = reg.pin_active()
    assert pinned_old is ks_g0
    reg.install_gen(_stub_keyset(1, gen=1))
    pinned_new = reg.pin_active()
    assert pinned_new.gen == 1  # new fan-outs pin the refreshed set
    assert reg.pin_count(1) == 2  # both gens' fan-outs in flight
    with pytest.raises(GeneralError, match="gen"):
        reg.install_gen(_stub_keyset(1, gen=5))  # gens are sequential
    reg.unpin(pinned_old)
    reg.unpin(pinned_new)
    assert reg.pin_count(1) == 0


def test_epoch_state_wire_codes_pinned():
    assert EPOCH_STATE_CODES == {
        PENDING: 0, ACTIVE: 1, RETIRING: 2, RETIRED: 3,
    }
    assert EPOCH_STATE_OF_CODE == {
        0: PENDING, 1: ACTIVE, 2: RETIRING, 3: RETIRED,
    }


def test_manager_attach_replays_live_epochs(params):
    mgr = KeyLifecycleManager(params, label=b"keylife-attach")
    ks1 = mgr.bootstrap(2, 3)
    ks2 = mgr.reshare()
    installed = []
    mgr.attach(SimpleNamespace(install_keyset=installed.append))
    # late-attached services immediately learn every live epoch
    assert sorted(k.epoch for k in installed) == [ks1.epoch, ks2.epoch]


# --- satellite: epoch-keyed static-operand cache ----------------------------


def test_epoch_verkey_fingerprints_coexist_in_static_cache(params):
    """Across a rollover BOTH epochs' verkeys are in play (old creds
    verify under the retiring epoch while new mints pin the new one).
    Their static-operand entries must coexist in the 32-entry LRU —
    alternating epochs is all hits after first build, no evict-thrash."""
    from coconut_tpu.tpu import backend as tbe

    mgr = KeyLifecycleManager(params, label=b"keylife-cache")
    ks1 = mgr.bootstrap(2, 3)
    ks2 = mgr.reshare()
    assert ks1.vk.to_bytes(params.ctx) != ks2.vk.to_bytes(params.ctx)
    fp1 = tbe._static_fingerprint(ks1.vk, params)
    fp2 = tbe._static_fingerprint(ks2.vk, params)
    assert fp1 != fp2  # distinct epochs -> distinct cache keys

    saved = dict(tbe._STATIC_CACHE)
    tbe._STATIC_CACHE.clear()
    metrics.reset()
    try:
        builds = []

        def lookup(ks):
            return tbe._static_operands(
                "verify", ks.vk, params, None,
                lambda: builds.append(ks.epoch) or ("tables", ks.epoch),
            )

        assert lookup(ks1) == ("tables", ks1.epoch)
        assert lookup(ks2) == ("tables", ks2.epoch)
        assert builds == [ks1.epoch, ks2.epoch]  # one build each
        for _ in range(8):  # alternate: pure hits, no rebuilds
            assert lookup(ks1)[1] == ks1.epoch
            assert lookup(ks2)[1] == ks2.epoch
        assert builds == [ks1.epoch, ks2.epoch]
        assert metrics.get_count("encode_cache_misses") == 2
        assert metrics.get_count("encode_cache_hits") == 16
        # crowding the LRU with 30 other entries keeps both epochs
        # resident (32-entry capacity; recency protects the hot pair)
        for i in range(30):
            tbe._static_operands(
                "verify", ks1.vk, params, ("pad", i), lambda: object()
            )
            lookup(ks1)
            lookup(ks2)
        assert builds == [ks1.epoch, ks2.epoch]  # still never rebuilt
    finally:
        tbe._STATIC_CACHE.clear()
        tbe._STATIC_CACHE.update(saved)


# --- the epoch-rollover chaos drill -----------------------------------------


def test_epoch_rollover_chaos_drill(params):
    """The PR's acceptance drill, deterministic over loopback RPC: a
    5-authority engine bootstraps via DKG (corrupt dealer named and
    excluded), serves full sessions, takes one proactive refresh and one
    t/n reshare with mints in flight, and every pre-rollover credential
    verifies post-rollover under its mint epoch. Zero dangling futures,
    zero engine-side terminal errors; wrong-epoch verification rejects;
    window-pressure retirement refuses typed through the envelope."""
    metrics.reset()
    mgr = KeyLifecycleManager(params, label=b"keylife-drill", window=3)

    # 1) DKG with a corrupt dealer: named exactly, excluded, round lands
    ks1 = mgr.bootstrap(3, 5, tamper=_tamper_one(2, 4))
    assert mgr.last_round.complaints == {2: (4,)}
    assert ks1.excluded == (2,)
    eng = ProtocolEngine(
        [ks1.signer(i) for i in range(1, 6)],
        params,
        3,
        count_hidden=HIDDEN,
        revealed_msg_indices=REVEALED,
        vk=ks1.vk,
        backend="python",
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
        keychain=mgr.registry,
    ).start()
    mgr.attach(eng)
    codec = wire.WireCodec(params)
    replica = rpc.Replica(eng, codec, replica_id="r0")
    client = rpc.GatewayClient(rpc.LoopbackTransport(replica), codec)
    directory = gossip.HealthDirectory(["r0"])
    loop = gossip.GossipLoop(
        directory,
        {"r0": lambda: client.poll_beacon(timeout=5.0)},
        clock=FakeClock(),
    )
    loop.step()
    assert directory.epochs("r0") == ((1, ACTIVE),)

    settled = []

    def mint_one():
        msgs = [rand_fr() for _ in range(MSGS)]
        esk, epk = elgamal_keygen(params.ctx.sig, params.g)
        sig_req, _ = client.submit_prepare(msgs, epk).result(120.0)
        cred = client.submit_mint(sig_req, msgs, esk).result(120.0)
        settled.append(cred)
        return cred, msgs

    def full_show(cred, msgs):
        proof, chal, rev = client.submit_show_prove(cred, msgs).result(
            120.0
        )
        # explicit challenge AND the stranger-verifier re-hash path
        assert client.submit_show_verify(
            proof, rev, chal, epoch=cred.epoch
        ).result(120.0) is True
        assert client.submit_show_verify(
            proof, rev, None, epoch=cred.epoch
        ).result(120.0) is True

    pre = [mint_one() for _ in range(3)]
    assert all(c.epoch == 1 for c, _ in pre)  # stamped over the wire
    full_show(*pre[0])

    # 2) proactive refresh with mints IN FLIGHT (engine-side futures
    # genuinely straddle the round; loopback settles the RPC ones inline)
    inflight_msgs = [rand_fr() for _ in range(MSGS)]
    esk, epk = elgamal_keygen(params.ctx.sig, params.g)
    sig_req, _ = eng.submit_prepare(inflight_msgs, epk).result(120.0)
    inflight = [
        eng.submit_mint(sig_req, inflight_msgs, esk) for _ in range(4)
    ]
    before = _share_map(ks1.signers)
    ks1r = mgr.refresh()
    assert ks1r.vk.to_bytes(params.ctx) == ks1.vk.to_bytes(params.ctx)
    after = _share_map(ks1r.signers)
    assert all(before[i] != after[i] for i in before)  # all shares moved
    for f in inflight:  # straddling mints settle: no dangling futures
        cred = f.result(120.0)
        assert cred.epoch == 1
        assert eng.submit_verify(cred, inflight_msgs).result(120.0) is True
    mid = [mint_one() for _ in range(2)]
    assert all(c.epoch == 1 for c, _ in mid)  # refresh kept the epoch

    # 3) t/n reshare (3-of-5 -> 2-of-5) with mints in flight: a NEW
    # epoch activates; straddlers complete under whichever epoch their
    # fan-out pinned and verify under that stamp
    inflight = [
        eng.submit_mint(sig_req, inflight_msgs, esk) for _ in range(4)
    ]
    ks2 = mgr.reshare(threshold=2, total=5)
    assert ks2.epoch == 2
    assert ks2.vk.to_bytes(params.ctx) != ks1.vk.to_bytes(params.ctx)
    for f in inflight:
        cred = f.result(120.0)
        assert cred.epoch in (1, 2)
        assert eng.submit_verify(cred, inflight_msgs).result(120.0) is True
    loop.step()
    assert directory.epochs("r0") == ((1, RETIRING), (2, ACTIVE))

    # 4) every pre-rollover credential verifies post-rollover under its
    # mint epoch — full session, over the wire
    for cred, msgs in pre + mid:
        assert client.submit_verify(cred, msgs).result(120.0) is True
        full_show(cred, msgs)
    post = [mint_one() for _ in range(2)]
    assert all(c.epoch == 2 for c, _ in post)
    full_show(*post[0])

    # 5) wrong-epoch verification REJECTS (verdict False, not a crash):
    # an epoch-1 credential presented as epoch-2 fails under that verkey
    cred, msgs = pre[0]
    cred.epoch = 2
    assert client.submit_verify(cred, msgs).result(120.0) is False
    cred.epoch = 1

    # 6) unknown epoch refuses typed through the RPC error envelope
    cred.epoch = 42
    with pytest.raises(EpochUnknownError):
        client.submit_verify(cred, msgs).result(120.0)
    cred.epoch = 1

    # 7) window pressure: two more reshares retire epoch 1; its
    # credentials now refuse typed (EpochRetiredError) over the wire
    ks3 = mgr.reshare()
    ks4 = mgr.reshare()
    assert (ks3.epoch, ks4.epoch) == (3, 4)
    loop.step()
    assert directory.epochs("r0") == (
        (2, RETIRING), (3, RETIRING), (4, ACTIVE),
    )
    with pytest.raises(EpochRetiredError) as ei:
        client.submit_verify(cred, msgs).result(120.0)
    # structured attrs don't survive the envelope, but the live set does
    # travel in the message for client re-resolution
    assert "live epochs: [2, 3, 4]" in str(ei.value)
    # epoch-2 credentials still verify: retirement was window pressure,
    # not a blanket invalidation
    assert client.submit_verify(post[0][0], post[0][1]).result(120.0)

    # -- the drill's verdicts ------------------------------------------------
    assert len(settled) == 7  # every RPC mint settled exactly once
    for e in (2, 3, 4):
        assert mgr.registry.pin_count(e) == 0  # no leaked pins
    assert metrics.get_count("gateway_errors") == 0  # no terminal errors
    assert metrics.get_count("keylife_refreshes") == 1
    assert metrics.get_count("keylife_reshares") == 3
    assert metrics.get_count("keylife_retirements") == 1
    assert eng.drain(timeout=60.0)
