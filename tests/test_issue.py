"""Threshold-issuance suite (ISSUE 10): quorum fan-out, first-t-of-n
aggregation, straggler hedging, corrupt-partial attribution, and the
share-id validation satellites.

Economics mirror tests/test_serve.py: the quorum/hedge mechanics run on
STUB signers and a stub minter with injected clocks — resolution order is
proven by gating per-authority events and ADVANCING a fake clock, never
by sleeping in an assert (`_wait` spins on millisecond polls only for the
service's own thread handoffs). The real-crypto end-to-end tests at the
bottom run the full 5-authority t=3 pool with injected crash/hang/corrupt
faults on small parameters and verify every minted credential."""

import threading
import time
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics
from coconut_tpu.errors import (
    GeneralError,
    QuorumUnreachableError,
    TransientBackendError,
)
from coconut_tpu.faults import FaultyBackend, InjectedCrash
from coconut_tpu.issue import (
    HedgePolicy,
    HedgeScheduler,
    IssuanceService,
    QuorumTracker,
)
from coconut_tpu.issue.quorum import Fanout
from coconut_tpu.obs import trace as otrace
from coconut_tpu.serve import health as _health

pytestmark = pytest.mark.issue


# --- stub world ------------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class StubSign:
    """Stub authority backend: one opaque partial token per request,
    tagged with the share it was 'signed' under."""

    def __init__(self):
        self.calls = 0

    def batch_blind_sign(self, sig_requests, sigkey, params):
        self.calls += 1
        return [("partial", sigkey, req) for req in sig_requests]


class GatedSign(StubSign):
    """Blocks inside the sign until released — the test controls partial
    ARRIVAL ORDER, which is what first-t-wins resolves on."""

    def __init__(self):
        super().__init__()
        self.entered = threading.Event()
        self.release = threading.Event()

    def batch_blind_sign(self, sig_requests, sigkey, params):
        self.entered.set()
        assert self.release.wait(10.0), "gate never released"
        return super().batch_blind_sign(sig_requests, sigkey, params)


class FailingSign(StubSign):
    def batch_blind_sign(self, sig_requests, sigkey, params):
        raise TransientBackendError("injected sign fault")


class CrashingSign(StubSign):
    def batch_blind_sign(self, sig_requests, sigkey, params):
        raise InjectedCrash("injected authority crash")


class StubMinter:
    """Crypto-free minter: aggregation records the winning subset on the
    'credential'; `corrupt_ids` makes any subset containing them fail the
    release gate, with per-partial attribution naming exactly them."""

    def __init__(self, corrupt_ids=()):
        self.corrupt_ids = set(corrupt_ids)
        self.minted_subsets = []

    def unblind(self, blind_rows, sks):
        return blind_rows

    def aggregate(self, subset, sig_rows):
        self.minted_subsets.append(tuple(subset))
        return [
            SimpleNamespace(subset=tuple(subset), row=list(row))
            for row in sig_rows
        ]

    def verify(self, creds, messages_list, subset):
        ok = not any(i in self.corrupt_ids for i in subset)
        return [ok] * len(creds)

    def verify_partial(self, signer_id, sig, messages):
        return signer_id not in self.corrupt_ids


def _signers(n):
    return [
        SimpleNamespace(
            id=i + 1, sigkey="sk%d" % (i + 1), verkey="vk%d" % (i + 1)
        )
        for i in range(n)
    ]


def _svc(n=5, t=3, backends=None, minter=None, clk=None, **kw):
    clk = clk if clk is not None else FakeClock()
    backends = backends if backends is not None else [StubSign() for _ in range(n)]
    kw.setdefault("watchdog_interval_s", None)
    kw.setdefault(
        "watchdog",
        _health.Watchdog(
            clock=clk, k=6.0, min_timeout_s=1.0, initial_timeout_s=5.0
        ),
    )
    kw.setdefault(
        "hedge",
        HedgePolicy(k=3.0, alpha=1.0, initial_delay_s=100.0, min_delay_s=0.0),
    )
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_wait_ms", 2.0)
    svc = IssuanceService(
        _signers(n),
        None,
        t,
        backends=backends,
        minter=minter if minter is not None else StubMinter(),
        clock=clk,
        **kw,
    )
    return svc, clk, backends


def _wait(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "timed out waiting for " + msg
        time.sleep(0.001)


def _submit_batch(svc, n=2):
    """Submit n orders (n = max_batch triggers an immediate full flush)
    and return their futures."""
    return [
        svc.submit("req%d" % i, ["m%d" % i], "esk%d" % i) for i in range(n)
    ]


def _open_fanout(svc):
    _wait(lambda: svc._tracker.outstanding(), msg="fan-out to open")
    return svc._tracker.outstanding()[0]


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# --- hedge policy / scheduler (pure, fake-clock) ----------------------------


def test_hedge_policy_ema_fold_and_budget_clamp():
    p = HedgePolicy(k=3.0, alpha=0.5, initial_delay_s=9.0, min_delay_s=0.1,
                    max_delay_s=2.0)
    assert p.ema("a") is None
    assert p.budget("a") == 9.0  # no EMA yet: don't hedge around a compile
    p.observe("a", 0.2)
    assert p.ema("a") == pytest.approx(0.2)
    p.observe("a", 0.4)
    assert p.ema("a") == pytest.approx(0.3)  # 0.5*0.4 + 0.5*0.2
    assert p.budget("a") == pytest.approx(0.9)  # k * ema
    p.observe("a", 10.0)
    assert p.budget("a") == 2.0  # clamped to max_delay_s
    p.observe("b", 1e-9)
    assert p.budget("b") == pytest.approx(0.1)  # clamped to min_delay_s
    with pytest.raises(ValueError):
        HedgePolicy(k=0.0)


def test_hedge_scheduler_due_pops_once_and_cancel_drops_fanout():
    clk = FakeClock()
    sched = HedgeScheduler(clock=clk)
    f1 = SimpleNamespace(fid=1)
    f2 = SimpleNamespace(fid=2)
    sched.begin(f1, "a", 0.5, now=0.0)
    sched.begin(f1, "b", 2.0, now=0.0)
    sched.begin(f2, "a", 0.5, now=0.0)
    assert sched.outstanding() == 3
    clk.advance(1.0)
    due = sched.due()
    assert {(f.fid, label) for f, label, _ in due} == {(1, "a"), (2, "a")}
    assert due[0][2] == pytest.approx(0.5)  # overdue_s
    assert sched.due() == []  # popped exactly once
    assert sched.cancel(1) == 1  # drops f1's remaining "b" timer
    sched.end(2, "a")  # already popped: no-op
    assert sched.outstanding() == 0


# --- quorum tracker (pure) --------------------------------------------------


def _fanout(fid=0, n_requests=0):
    reqs = [
        SimpleNamespace(future=SimpleNamespace(done=lambda: False))
        for _ in range(n_requests)
    ]
    return Fanout(fid, reqs, ["sr"] * n_requests, [["m"]] * n_requests,
                  ["sk"] * n_requests, otrace.NOOP, 0.0)


def test_tracker_resolves_exactly_once_on_tth_row():
    clk = FakeClock()
    tr = QuorumTracker(3, clock=clk)
    f = _fanout(n_requests=2)
    tr.open(f)
    clk.advance(0.25)
    assert tr.record(f, 4, ["p", "p"]) is None
    assert tr.record(f, 1, ["p", "p"]) is None
    subset = tr.record(f, 5, ["p", "p"])
    assert subset == [4, 1, 5]  # arrival order, not id order
    assert f.quorum_at == 0.25
    # the quorum-wait histogram observed exactly once
    assert metrics.snapshot()["histograms"]["issue_quorum_wait_s"]["count"] == 1
    # a 4th row while minting does NOT re-resolve
    assert tr.record(f, 2, ["p", "p"]) is None
    assert f.order == [4, 1, 5, 2]


def test_tracker_discards_duplicate_and_stale_rows():
    tr = QuorumTracker(2, clock=FakeClock())
    f = _fanout(n_requests=3)
    tr.open(f)
    assert tr.record(f, 1, ["a", "b", "c"]) is None
    assert tr.record(f, 1, ["a", "b", "c"]) is None  # duplicate authority
    assert metrics.get_count("issue_partials_discarded") == 3
    tr.close_fanout(f)  # resolved: everything after is stale
    assert tr.record(f, 2, ["a", "b", "c"]) is None
    assert metrics.get_count("issue_partials_discarded") == 6
    assert tr.outstanding() == []


def test_tracker_drop_partials_and_next_subset():
    tr = QuorumTracker(2, clock=FakeClock())
    f = _fanout(n_requests=1)
    tr.open(f)
    tr.record(f, 1, ["a"])
    assert tr.record(f, 2, ["b"]) == [1, 2]
    tr.drop_partials(f, {1})  # attribution: authority 1's row is corrupt
    assert tr.next_subset(f) is None  # only one clean row: wait
    assert f.minting is False  # claim released for the next arrival
    assert tr.record(f, 3, ["c"]) == [2, 3]  # skips the dropped row


# --- service: first-t-wins, stale guard -------------------------------------


def test_first_t_wins_resolution_order_and_late_rows_discarded():
    gates = [GatedSign() for _ in range(5)]
    svc, clk, _ = _svc(backends=gates)
    with svc:
        futs = _submit_batch(svc, 2)
        f = _open_fanout(svc)
        for g in gates:
            assert g.entered.wait(5.0)  # fanned out to ALL five
        # release authorities 2, 4, 5 in that order: the quorum is the
        # FIRST three distinct rows, in arrival order
        for sid in (2, 4, 5):
            gates[sid - 1].release.set()
            _wait(lambda: sid in f.partials, msg="row %d" % sid)
        creds = [fut.result(timeout=5.0) for fut in futs]
        assert all(c.subset == (2, 4, 5) for c in creds)
        assert metrics.get_count("issue_minted") == 2
        # stragglers 1 and 3 land late: discarded by the stale guard,
        # never re-minted
        for sid in (1, 3):
            gates[sid - 1].release.set()
        _wait(
            lambda: metrics.get_count("issue_partials_discarded") == 4,
            msg="late rows discarded",
        )
        assert svc.minter.minted_subsets == [(2, 4, 5)]
    assert metrics.get_count("issue_sign_skips") == 0


def test_ready_gate_holds_batch_until_quorum_capacity():
    # with every authority quarantined there is no quorum capacity: the
    # coalesced batch must stay IN the queue, not fan out to nobody
    svc, clk, _ = _svc()
    for auth in svc._authorities:
        svc._health_of(auth.label).on_crash("made unavailable")
    with svc:
        fut = svc.submit("req", ["m"], "esk")
        clk.advance(1.0)
        svc.kick()
        time.sleep(0.05)
        assert svc.depth() == 1  # held by the ready gate
        assert not fut.done()
        # capacity returns: cooldown elapses, probation probes revive the
        # pool and the batch fans out
        clk.advance(10.0)
        svc.health_tick()
        assert fut.result(timeout=5.0).subset is not None
    assert metrics.get_count("issue_minted") == 1


# --- service: hedging -------------------------------------------------------


def test_hedge_fires_at_k_ema_cancels_on_quorum():
    gates = [GatedSign() for _ in range(6)]
    svc, clk, _ = _svc(n=6, t=3, backends=gates)
    spare = svc._authorities[5]
    # authority 6 is BUSY at fan-out time (mid-sign on one dummy fan-out,
    # two more queued): can_accept() is False, so the fan-out targets
    # only 1..5 and 6 is the hedge spare
    dummies = [_fanout(fid=-1), _fanout(fid=-2), _fanout(fid=-3)]
    spare._inbox.extend(dummies)
    # prime every authority's sign EMA: budget = k * 0.1 = 0.3s
    for auth in svc._authorities:
        svc.hedge_policy.observe(auth.label, 0.1)
    with svc:
        assert gates[5].entered.wait(5.0)  # spare stuck on the dummy
        futs = _submit_batch(svc, 2)
        f = _open_fanout(svc)
        assert set(f.targets) == {"1", "2", "3", "4", "5"}
        for sid in (1, 2):
            gates[sid - 1].release.set()
            _wait(lambda: sid in f.partials, msg="row %d" % sid)
        # authorities 3, 4, 5 straggle past k x EMA: the FIRST due hedge
        # takes the only spare; the other two find none
        clk.advance(0.5)
        svc.health_tick()
        assert metrics.get_count("issue_hedges") == 1
        assert metrics.get_count("issue_hedge_no_spare") == 2
        assert "6" in f.targets
        assert spare.queued() == 3  # two queued dummies + the hedged fan-out
        # quorum completes via straggler 3: the hedge loses the race and
        # its queued sign is CANCELED, never run
        gates[2].release.set()
        creds = [fut.result(timeout=5.0) for fut in futs]
        assert all(c.subset == (1, 2, 3) for c in creds)
        _wait(
            lambda: metrics.get_count("issue_cancelled_signs") == 1,
            msg="hedge cancel",
        )
        assert svc._hedges.outstanding() == 0
        # unblock the spare's dummies and the remaining stragglers
        for g in gates:
            g.release.set()
        _wait(
            lambda: metrics.get_count("issue_partials_discarded") == 4,
            msg="late rows discarded",
        )
    assert svc.minter.minted_subsets == [(1, 2, 3)]


# --- service: corrupt-partial attribution -----------------------------------


def test_corrupt_partial_attribution_quarantines_only_culprit():
    gates = [GatedSign() for _ in range(5)]
    minter = StubMinter(corrupt_ids={2})
    svc, clk, _ = _svc(
        backends=gates,
        minter=minter,
        health_policy=_health.HealthPolicy(suspect_after=1, quarantine_after=1),
    )
    with svc:
        futs = _submit_batch(svc, 2)
        f = _open_fanout(svc)
        for sid in (1, 2, 3):
            gates[sid - 1].release.set()
            _wait(lambda: sid in f.partials, msg="row %d" % sid)
        # first mint round used (1, 2, 3) and failed the release gate;
        # attribution names authority 2 ONLY, drops its row, quarantines
        # it, and the fan-out waits for a clean 3rd row
        _wait(
            lambda: metrics.get_count("issue_corrupt_partials") == 1,
            msg="attribution",
        )
        assert svc._health_of("2").state == _health.QUARANTINED
        assert all(
            svc._health_of(a.label).state == _health.HEALTHY
            for a in svc._authorities
            if a.label != "2"
        )
        assert not futs[0].done()  # nothing released from the bad round
        gates[3].release.set()  # authority 4's clean row completes quorum
        creds = [fut.result(timeout=5.0) for fut in futs]
        assert all(c.subset == (1, 3, 4) for c in creds)
        gates[4].release.set()
    assert minter.minted_subsets == [(1, 2, 3), (1, 3, 4)]
    assert metrics.get_count("issue_minted") == 2
    assert metrics.get_count("issue_quarantined") == 1
    # no corrupt credential was ever released
    assert all(2 not in c.subset for c in creds)


# --- service: faults, crashes, hangs ----------------------------------------


def test_sign_fault_marks_target_failed_and_quorum_survives():
    # survivors are GATED: were they free-running stubs, the quorum could
    # resolve before authority 1's sign even pops, the pop would be
    # skipped (first-t-wins), and the fault would never fire
    gates = [GatedSign() for _ in range(4)]
    backends = [FailingSign()] + gates
    svc, clk, _ = _svc(backends=backends)
    with svc:
        futs = _submit_batch(svc, 2)
        _wait(
            lambda: svc._health_of("1").state == _health.SUSPECT,
            msg="sign fault noted",
        )
        for g in gates:
            g.release.set()
        creds = [fut.result(timeout=5.0) for fut in futs]
        assert all(1 not in c.subset for c in creds)
    assert metrics.get_count("issue_minted") == 2
    assert svc._health_of("1").state == _health.SUSPECT


def test_authority_crash_is_contained_and_quorum_survives():
    # gated survivors, same reason as the sign-fault test above: the
    # crash must land before the quorum can resolve and skip it
    gates = [GatedSign() for _ in range(4)]
    backends = [CrashingSign()] + gates
    svc, clk, _ = _svc(backends=backends)
    with svc:
        futs = _submit_batch(svc, 2)
        _wait(
            lambda: metrics.get_count("issue_authority_crashes") == 1,
            msg="crash containment",
        )
        for g in gates:
            g.release.set()
        creds = [fut.result(timeout=5.0) for fut in futs]
        assert all(1 not in c.subset for c in creds)
    assert metrics.get_count("issue_minted") == 2
    assert svc._health_of("1").state == _health.QUARANTINED
    assert not svc._authorities[0].has_worker()


def test_quorum_unreachable_is_typed_and_loud():
    # three of five authorities crash: 2 live < t=3 after the fan-out's
    # failed targets are excluded, and no spare exists
    backends = [CrashingSign(), CrashingSign(), CrashingSign(),
                StubSign(), StubSign()]
    svc, clk, _ = _svc(backends=backends)
    with svc:
        futs = _submit_batch(svc, 2)
        excs = [fut.exception(timeout=5.0) for fut in futs]
    assert all(isinstance(e, QuorumUnreachableError) for e in excs)
    assert excs[0].needed == 3
    assert "retry" in str(excs[0])
    assert metrics.get_count("issue_quorum_unreachable") >= 1
    assert metrics.get_count("issue_minted") == 0


def test_watchdog_expires_hung_sign_quarantines_and_probation_revives():
    gates = [GatedSign() for _ in range(5)]
    svc, clk, _ = _svc(
        backends=gates,
        health_policy=_health.HealthPolicy(probe_after_s=5.0),
    )
    with svc:
        futs = _submit_batch(svc, 2)
        f = _open_fanout(svc)
        assert gates[0].entered.wait(5.0)
        for sid in (2, 3, 4):  # quorum resolves; authority 1 stays hung
            gates[sid - 1].release.set()
            _wait(lambda: sid in f.partials, msg="row %d" % sid)
        [fut.result(timeout=5.0) for fut in futs]
        gates[4].release.set()
        _wait(  # authority 5's late row lands (its watchdog entry ends)
            lambda: metrics.get_count("issue_partials_discarded") == 2,
            msg="authority 5 settling",
        )
        # the hung sign outlives its watchdog budget (initial 5s): the
        # stuck worker is abandoned and the authority quarantined even
        # though the fan-out already resolved without it
        clk.advance(6.0)
        svc.health_tick()
        assert metrics.get_count("issue_watchdog_timeouts") == 1
        assert svc._health_of("1").state == _health.QUARANTINED
        assert not svc._authorities[0].has_worker()
        # the abandoned worker finally returns: its row is STALE (the
        # generation moved on), discarded without touching health
        gates[0].release.set()
        _wait(
            lambda: metrics.get_count("issue_partials_discarded") == 4,
            msg="stale row discarded",
        )
        assert svc._health_of("1").state == _health.QUARANTINED
        # cooldown elapses -> probation respawns a fresh worker and the
        # pool mints with all five again
        clk.advance(10.0)
        svc.health_tick()
        assert svc._authorities[0].has_worker()
        futs2 = _submit_batch(svc, 2)
        assert all(fut.result(timeout=5.0) for fut in futs2)
    assert metrics.get_count("issue_minted") == 4


def test_drain_fails_unreachable_fanouts_no_dangling_futures():
    # t=3 of n=3 but one authority never returns: the fan-out can never
    # reach quorum — drain must fail its futures loudly, never hang them
    gates = [GatedSign() for _ in range(3)]
    svc, clk, _ = _svc(n=3, t=3, backends=gates)
    svc.start()
    futs = _submit_batch(svc, 2)
    f = _open_fanout(svc)
    for sid in (1, 2):
        gates[sid - 1].release.set()
        _wait(lambda: sid in f.partials, msg="row %d" % sid)
    assert svc.drain(timeout=0.5) is False  # the hung join times out
    for fut in futs:
        assert fut.done()
        assert isinstance(fut.exception(0), QuorumUnreachableError)
    assert metrics.get_count("issue_quorum_unreachable") >= 1
    gates[2].release.set()  # unblock the worker thread


def test_shutdown_without_drain_refuses_queued_backlog():
    # never started: the queued backlog is refused typed, not signed
    svc, clk, _ = _svc()
    fut = svc.submit("req", ["m"], "esk")
    svc.shutdown(drain=False, timeout=2.0)
    from coconut_tpu.errors import ServiceClosedError

    assert isinstance(fut.exception(0), ServiceClosedError)
    assert metrics.get_count("issue_cancelled") == 1
    with pytest.raises(ServiceClosedError):
        svc.submit("late", ["m"], "esk")


# --- signature.py satellites: share-id validation + batched aggregation -----


def _fake_partials(ids):
    sig = SimpleNamespace(sigma_1="h", sigma_2="s")
    return [(i, sig) for i in ids]


def _fake_verkeys(ids):
    vk = SimpleNamespace(X_tilde="x", Y_tilde=["y"])
    return [(i, vk) for i in ids]


def test_signature_aggregate_rejects_duplicate_ids():
    from coconut_tpu.signature import Signature

    with pytest.raises(GeneralError) as ei:
        Signature.aggregate(3, _fake_partials([1, 2, 2]))
    assert "duplicate signer ids" in str(ei.value)
    assert "[2]" in str(ei.value)  # names the offending id


def test_signature_aggregate_rejects_out_of_range_ids():
    from coconut_tpu.signature import Signature

    for bad in ([0, 1, 2], [-3, 1, 2], [1.5, 1, 2]):
        with pytest.raises(GeneralError) as ei:
            Signature.aggregate(3, _fake_partials(bad))
        assert "out-of-range signer ids" in str(ei.value)


def test_verkey_aggregate_rejects_duplicate_and_bad_ids():
    from coconut_tpu.signature import Verkey

    with pytest.raises(GeneralError) as ei:
        Verkey.aggregate(2, _fake_verkeys([4, 4]))
    assert "duplicate signer ids" in str(ei.value) and "[4]" in str(ei.value)
    with pytest.raises(GeneralError) as ei:
        Verkey.aggregate(2, _fake_verkeys([0, 3]))
    assert "out-of-range signer ids" in str(ei.value) and "[0]" in str(
        ei.value
    )


def test_batch_aggregate_validates_every_request():
    from coconut_tpu.signature import batch_aggregate

    assert batch_aggregate(3, []) == []
    with pytest.raises(GeneralError):
        batch_aggregate(3, [_fake_partials([1, 2, 3]),
                            _fake_partials([1, 1, 2])])


# --- real crypto ------------------------------------------------------------


@pytest.fixture(scope="module")
def issue_world():
    """Small real-crypto world: 2-message params, 3-of-5 SSS keygen, and
    a pool of blind-sign orders (request, messages, elgamal sk)."""
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.keygen import trusted_party_SSS_keygen
    from coconut_tpu.params import Params
    from coconut_tpu.signature import SignatureRequest
    from coconut_tpu.sss import rand_fr

    params = Params.new(2, b"test-issue")
    _, _, signers = trusted_party_SSS_keygen(3, 5, params)

    def order():
        msgs = [rand_fr(), rand_fr()]
        sk, pk = elgamal_keygen(params.ctx.sig, params.g)
        req, _ = SignatureRequest.new(msgs, 1, pk, params)
        return req, msgs, sk

    return SimpleNamespace(params=params, signers=signers, order=order)


def _agg_vk(world, ids):
    from coconut_tpu.signature import Verkey

    return Verkey.aggregate(
        3,
        [(s.id, s.verkey) for s in world.signers if s.id in ids],
        ctx=world.params.ctx,
    )


def test_batch_aggregate_bit_identical_to_sequential(issue_world):
    """The batched [B, t] Lagrange MSM must equal per-credential
    Signature.aggregate, and ANY t-subset must interpolate to the SAME
    credential (subset-independence is what makes first-t-wins sound)."""
    from coconut_tpu.signature import (
        BlindSignature,
        Signature,
        batch_aggregate,
        batch_unblind,
    )

    world = issue_world
    orders = [world.order() for _ in range(2)]
    partials = {}  # signer id -> per-order unblinded partial
    for s in world.signers:
        blind = [BlindSignature.new(req, s.sigkey, world.params)
                 for req, _, _ in orders]
        partials[s.id] = batch_unblind(
            blind, [sk for _, _, sk in orders], world.params.ctx
        )
    subsets = [(1, 2, 3), (2, 4, 5), (1, 3, 5)]
    creds_by_subset = []
    for subset in subsets:
        rows = [
            [(i, partials[i][b]) for i in subset] for b in range(len(orders))
        ]
        batched = batch_aggregate(3, rows, ctx=world.params.ctx)
        sequential = [Signature.aggregate(3, row, ctx=world.params.ctx)
                      for row in rows]
        assert batched == sequential  # bit-identical
        vk = _agg_vk(world, set(subset))
        assert all(
            c.verify(msgs, vk, world.params)
            for c, (_, msgs, _) in zip(batched, orders)
        )
        creds_by_subset.append(batched)
    # subset-independence: every t-subset interpolates the same signature
    for other in creds_by_subset[1:]:
        assert other == creds_by_subset[0]


def test_e2e_five_authorities_mint_through_crash_and_hang(issue_world):
    """The acceptance scenario: a 5-authority t=3 pool with one CRASHED
    and one HUNG authority still mints every credential, and each minted
    credential verifies under the Lagrange-aggregated verkey."""
    world = issue_world
    from coconut_tpu.backend import get_backend

    py = get_backend("python")
    backends = [
        py,
        FaultyBackend(py, crash_sign_on=(0,)),  # authority 2 crashes
        FaultyBackend(py, hang_sign_on=(0,), hang_max_s=30.0),  # 3 hangs
        py,
        py,
    ]
    svc = IssuanceService(
        world.signers,
        world.params,
        3,
        backend="python",
        backends=backends,
        max_batch=4,
        max_wait_ms=5.0,
    ).start()
    try:
        orders = [world.order() for _ in range(4)]
        futs = [svc.submit(req, msgs, sk) for req, msgs, sk in orders]
        creds = [fut.result(timeout=120.0) for fut in futs]
    finally:
        backends[2].hang_release.set()
        svc.drain(timeout=30.0)
    vk = _agg_vk(world, {1, 4, 5})
    assert all(
        c.verify(msgs, vk, world.params)
        for c, (_, msgs, _) in zip(creds, orders)
    )
    assert backends[1].crashes == 1
    assert metrics.get_count("issue_authority_crashes") == 1
    assert metrics.get_count("issue_minted") == 4
    assert svc._health_of("2").state == _health.QUARANTINED


def test_e2e_corrupt_partial_never_releases_bad_credential(issue_world):
    """Byzantine authority: one partial comes back with a flipped limb.
    The verify-before-release gate must catch it, attribution must name
    the culprit, and every released credential must still verify."""
    world = issue_world
    from coconut_tpu.backend import get_backend

    py = get_backend("python")
    gates = [GatedSign() for _ in range(2)]  # hold authorities 4, 5 back

    class GatedReal:
        """Delegate to the real signer only after release — pins the
        first-t subset to {1, 2, 3} deterministically."""

        def __init__(self, gate):
            self.gate = gate

        def batch_blind_sign(self, sig_requests, sigkey, params):
            assert self.gate.release.wait(60.0)
            from coconut_tpu.signature import batch_blind_sign

            return batch_blind_sign(sig_requests, sigkey, params, backend=py)

    backends = [
        py,
        FaultyBackend(py, corrupt_partial_on=(0,)),  # authority 2 corrupt
        py,
        GatedReal(gates[0]),
        GatedReal(gates[1]),
    ]
    svc = IssuanceService(
        world.signers,
        world.params,
        3,
        backend="python",
        backends=backends,
        max_batch=2,
        max_wait_ms=5.0,
        health_policy=_health.HealthPolicy(suspect_after=1, quarantine_after=1),
    ).start()
    try:
        orders = [world.order() for _ in range(2)]
        futs = [svc.submit(req, msgs, sk) for req, msgs, sk in orders]
        # the corrupt round happens on subset {1, 2, 3}; releasing
        # authority 4 lets the clean subset complete
        def _attributed():
            return metrics.get_count("issue_corrupt_partials") == 1

        _wait(_attributed, timeout=60.0, msg="corrupt-partial attribution")
        gates[0].release.set()
        creds = [fut.result(timeout=120.0) for fut in futs]
    finally:
        for g in gates:
            g.release.set()
        svc.drain(timeout=30.0)
    vk = _agg_vk(world, {1, 3, 4})
    assert all(
        c.verify(msgs, vk, world.params)
        for c, (_, msgs, _) in zip(creds, orders)
    )
    assert backends[1].corrupted_partials == 1
    assert metrics.get_count("issue_corrupt_partials") == 1
    assert svc._health_of("2").state == _health.QUARANTINED
    assert metrics.get_count("issue_minted") == 2


# --- mixed-workload loadgen -------------------------------------------------


def test_loadgen_mixed_workload_reports_issue_section():
    from coconut_tpu.serve import CredentialService, run_loadgen

    class VerifyStub:
        def batch_verify(self, sigs, msgs, vk, params):
            return [s.sigma_1 is not None and s.ok for s in sigs]

    vsvc = CredentialService(
        VerifyStub(), None, None, max_batch=4, max_wait_ms=1.0,
        watchdog_interval_s=None,
    ).start()
    isvc, _, _ = _svc(clk=time.monotonic, max_batch=4, max_wait_ms=1.0)
    isvc.start()
    try:
        cred = SimpleNamespace(sigma_1=1, sigma_2=1, ok=True)
        report = run_loadgen(
            vsvc,
            [(cred, [0], True)],
            duration_s=0.3,
            arrival="closed",
            concurrency=4,
            issue_service=isvc,
            issue_pool=[("req", ["m"], "esk")],
            issue_fraction=0.5,
        )
    finally:
        vsvc.drain(timeout=10.0)
        isvc.drain(timeout=10.0)
    assert report["issue_fraction"] == 0.5
    issue = report["issue"]
    assert issue["minted"] > 0 and report["completed"] > 0  # both workloads ran
    assert issue["dropped_futures"] == 0
    assert issue["mint_mismatches"] == 0
    assert issue["errors"] == 0
    assert issue["minted"] == metrics.get_count("issue_minted")
    assert report["verdict_mismatches"] == 0


def test_loadgen_issue_fraction_validation():
    from coconut_tpu.serve import run_loadgen

    with pytest.raises(ValueError):
        run_loadgen(None, [1], issue_fraction=0.5)  # no issue_service
    with pytest.raises(ValueError):
        run_loadgen(None, [1], issue_fraction=1.5, issue_service=object(),
                    issue_pool=[1])
