"""Generate the known-answer vectors (VERDICT round-1 item 8).

Pins golden bytes for the primitives every future backend (C++/TPU) must
reproduce verbatim: expand_message_xmd, hash_to_g1/g2, a fixed-label params
blob, field-arithmetic identities, one full credential transcript (issuance
through verification with a fixed RNG seed), and pairing values.

Run from the repo root:  python tests/vectors/generate.py
Output: tests/vectors/*.json (committed; tests/test_vectors.py replays them).
"""

import json
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from coconut_tpu.ops import serialize as ser
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import P, R, fp12_mul
from coconut_tpu.ops.hashing import (
    expand_message_xmd,
    hash_to_fr,
    hash_to_g1,
    hash_to_g2,
)
from coconut_tpu.ops.pairing import pairing
from coconut_tpu.params import Params
from coconut_tpu.ps import ps_verify
from coconut_tpu.signature import Signature, Sigkey, Verkey

OUT = os.path.dirname(os.path.abspath(__file__))


def write(name, obj):
    path = os.path.join(OUT, name)
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
    print("wrote", path)


def gen_hashing():
    cases = []
    for msg, dst, n in [
        (b"", b"CTH-v1-TEST", 32),
        (b"abc", b"CTH-v1-TEST", 64),
        (b"coconut", b"CTH-v1-G1", 96),
    ]:
        cases.append(
            {
                "msg": msg.hex(),
                "dst": dst.hex(),
                "len": n,
                "out": expand_message_xmd(msg, dst, n).hex(),
            }
        )
    h2f = [
        {"msg": m.hex(), "fr": hex(hash_to_fr(m))}
        for m in (b"", b"fiat-shamir", b"x" * 100)
    ]
    h2g1 = [
        {"msg": m.hex(), "point": ser.g1_to_compressed(hash_to_g1(m)).hex()}
        for m in (b"", b"label : g", b"test vector 2")
    ]
    h2g2 = [
        {"msg": m.hex(), "point": ser.g2_to_compressed(hash_to_g2(m)).hex()}
        for m in (b"", b"label : g_tilde")
    ]
    write(
        "hashing.json",
        {
            "expand_message_xmd": cases,
            "hash_to_fr": h2f,
            "hash_to_g1": h2g1,
            "hash_to_g2": h2g2,
        },
    )


def gen_params():
    params = Params.new(3, b"kat-params-v1")
    write(
        "params.json",
        {"label": b"kat-params-v1".hex(), "msg_count": 3, "blob": params.to_bytes().hex()},
    )


def gen_curve():
    rng = random.Random(0x60D)
    cases = []
    for _ in range(4):
        a, b = rng.randrange(1, R), rng.randrange(1, R)
        pa, pb = g1.mul(G1_GEN, a), g1.mul(G1_GEN, b)
        cases.append(
            {
                "a": hex(a),
                "b": hex(b),
                "g1_a": ser.g1_to_bytes(pa).hex(),
                "g1_add": ser.g1_to_bytes(g1.add(pa, pb)).hex(),
                "g1_msm": ser.g1_to_bytes(g1.msm([pa, pb], [b, a])).hex(),
                "g2_a": ser.g2_to_bytes(g2.mul(G2_GEN, a)).hex(),
            }
        )
    write("curve.json", {"cases": cases})


def gen_pairing():
    rng = random.Random(0xA1)
    a, b = rng.randrange(1, R), rng.randrange(1, R)
    e = pairing(g1.mul(G1_GEN, a), g2.mul(G2_GEN, b))
    e2 = pairing(G1_GEN, G2_GEN)
    # serialize GT (Fp12 nested tuples) as flat hex list of 12 Fp ints
    def flat(x):
        out = []

        def rec(t):
            if isinstance(t, tuple):
                for u in t:
                    rec(u)
            else:
                out.append(hex(t))

        rec(x)
        return out

    write(
        "pairing.json",
        {
            "a": hex(a),
            "b": hex(b),
            "e_aG1_bG2": flat(e),
            "e_G1_G2": flat(e2),
            "bilinearity_ab": flat(
                pairing(g1.mul(G1_GEN, a * b % R), G2_GEN)
            ),
        },
    )


def gen_transcript():
    """Full credential lifecycle with fixed randomness (seeded), recorded at
    the wire level: params, keys, messages, signature, verify bit."""
    rng = random.Random(0x7EA)
    params = Params.new(4, b"kat-transcript-v1")
    sk = Sigkey(rng.randrange(1, R), [rng.randrange(1, R) for _ in range(4)])
    ops = params.ctx.other
    vk = Verkey(
        ops.mul(params.g_tilde, sk.x),
        [ops.mul(params.g_tilde, y) for y in sk.y],
    )
    msgs = [rng.randrange(R) for _ in range(4)]
    t = rng.randrange(1, R)
    s1 = params.ctx.sig.mul(params.g, t)
    expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
    sig = Signature(s1, params.ctx.sig.mul(s1, expo))
    assert ps_verify(sig, msgs, vk, params)
    bad_msgs = list(msgs)
    bad_msgs[0] = (bad_msgs[0] + 1) % R
    assert not ps_verify(sig, bad_msgs, vk, params)
    write(
        "transcript.json",
        {
            "label": b"kat-transcript-v1".hex(),
            "sk_x": hex(sk.x),
            "sk_y": [hex(y) for y in sk.y],
            "vk": vk.to_bytes(params.ctx).hex(),
            "msgs": [hex(m) for m in msgs],
            "sig": sig.to_bytes(params.ctx).hex(),
            "verifies": True,
            "bad_msgs": [hex(m) for m in bad_msgs],
            "bad_verifies": False,
        },
    )


def gen_fields():
    rng = random.Random(0xF1E1D)
    cases = []
    for _ in range(4):
        a, b = rng.randrange(P), rng.randrange(P)
        cases.append(
            {
                "a": hex(a),
                "b": hex(b),
                "add": hex((a + b) % P),
                "mul": hex(a * b % P),
                "inv_a": hex(pow(a, -1, P)) if a else "0x0",
            }
        )
    write("fields.json", {"p": hex(P), "r": hex(R), "fp_cases": cases})


if __name__ == "__main__":
    gen_fields()
    gen_hashing()
    gen_params()
    gen_curve()
    gen_pairing()
    gen_transcript()
