"""Application-scenario layer suite (PR 19).

Three tiers, matching the subsystem's layering:

  - WORKFLOW UNITS on a fake clock and hand-rolled futures: retry
    classification (retryable vs expected-typed vs unattributed),
    per-workflow deadline expiry (at submit time, at park time, and
    via the driver's expire hook), and the no-dangling-futures-on-
    drain invariant (a late future settle against a cancelled/expired
    run is a no-op). Zero real sleeps.
  - TRAFFIC-MODEL determinism: seeded diurnal/flash/Zipf arrival
    streams are BIT-STABLE (pinned sha256 over the exact offsets),
    the population's tenant assignment is a pure function of
    (seed, uid), and users materialize lazily.
  - END-TO-END over loopback RPC against a real ProtocolEngine with a
    durable state store: petition re-sign and e-cash double-spend
    (exact transcript replay AND fresh re-randomized re-show) surface
    as typed `rejected`/double_spend terminals; an access session of
    re-randomized shows is accepted in full.

Everything runs on the python backend with 3-message params.
"""

import hashlib
import random
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics
from coconut_tpu.backend import get_backend
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import (
    DoubleSpendError,
    GeneralError,
    ServiceOverloadedError,
)
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.net import rpc, wire
from coconut_tpu.params import Params
from coconut_tpu.scenarios import (
    CANCELLED,
    COMPLETED,
    DEADLINE,
    FAILED,
    REJECTED,
    RETRY_EXHAUSTED,
    AccessScenario,
    DiurnalCurve,
    EcashScenario,
    FlashCrowd,
    PetitionScenario,
    Population,
    RateSchedule,
    ScenarioReport,
    Step,
    Workflow,
    WorkflowRun,
    arrival_times,
    run_workflow,
    zipf_cdf,
    zipf_pick,
)
from coconut_tpu.state import StateStore

pytestmark = pytest.mark.scenarios

MSGS = 3
HIDDEN = 1
REVEALED = [1, 2]
THRESHOLD, TOTAL = 2, 3


# --- workflow units (fake clock, fake futures, zero real sleeps) ------------


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += max(0.0, s)


class FakeFuture:
    """Future double: resolve/fail now or later; callbacks fire inline
    when already settled (the ServeFuture contract the runtime leans
    on)."""

    def __init__(self):
        self._value = None
        self._exc = None
        self._settled = False
        self._cbs = []

    def resolve(self, value=None):
        self._value, self._settled = value, True
        for cb in self._cbs:
            cb(self)

    def fail(self, exc):
        self._exc, self._settled = exc, True
        for cb in self._cbs:
            cb(self)

    def result(self, timeout=None):
        if self._exc is not None:
            raise self._exc
        return self._value

    def add_done_callback(self, fn):
        if self._settled:
            fn(self)
        else:
            self._cbs.append(fn)


class OneStep(Workflow):
    name = "unit"
    deadline_s = 10.0

    def __init__(self, submit, max_retries=4):
        self._submit = submit
        self._max_retries = max_retries
        self.result = None

    def script(self):
        self.result = yield Step(
            "s", self._submit, max_retries=self._max_retries
        )


def _run_unit(wf, clock=None):
    clock = clock or FakeClock()
    run = WorkflowRun(wf, clock=clock, sleep=clock.sleep, seed=1)
    run.start()
    return run, clock


def test_retryable_errors_are_retried_then_complete():
    calls = []

    def submit():
        fut = FakeFuture()
        calls.append(fut)
        if len(calls) <= 2:
            fut.fail(ServiceOverloadedError(1, 1, retry_after_s=0.1))
        else:
            fut.resolve("ok")
        return fut

    run, clock = _run_unit(OneStep(submit))
    assert run.outcome == COMPLETED
    assert run.wf.result == "ok"
    assert run.retries == 2 and len(calls) == 3
    assert clock.t > 0.0  # the backoff sleeps consumed fake time


def test_retry_budget_exhausts_typed():
    def submit():
        fut = FakeFuture()
        fut.fail(ServiceOverloadedError(1, 1, retry_after_s=0.01))
        return fut

    run, _ = _run_unit(OneStep(submit, max_retries=3))
    assert run.outcome == RETRY_EXHAUSTED
    assert run.retries == 3
    assert run.error_code == "overloaded"


def test_expected_typed_terminal_is_rejected_with_label():
    class Expecting(OneStep):
        def classify(self, step, exc):
            if isinstance(exc, DoubleSpendError):
                return "double_spend"
            return None

    def submit():
        fut = FakeFuture()
        fut.fail(DoubleSpendError("ab" * 32, 0))
        return fut

    run, _ = _run_unit(Expecting(submit))
    assert run.outcome == REJECTED
    assert run.outcome_label == "double_spend"
    assert run.error_code == "double_spend"
    assert run.retries == 0  # terminal: never retried


def test_unattributed_error_is_failed():
    def submit():
        fut = FakeFuture()
        fut.fail(GeneralError("script bug"))
        return fut

    run, _ = _run_unit(OneStep(submit))
    assert run.outcome == FAILED
    assert run.error_code == "general"


def test_deadline_expires_on_retry_past_budget():
    # the retry hint lands past the 10 s workflow deadline: the run
    # seals `deadline`, not a useless park
    def submit():
        fut = FakeFuture()
        fut.fail(ServiceOverloadedError(1, 1, retry_after_s=100.0))
        return fut

    run, clock = _run_unit(OneStep(submit))
    assert run.outcome == DEADLINE
    assert clock.t < 10.0  # sealed immediately, no sleep to the hint


def test_deadline_expire_hook_while_waiting_on_future():
    pending = FakeFuture()
    run, clock = _run_unit(OneStep(lambda: pending))
    assert run.outcome is None  # waiting on the future
    clock.t = 11.0
    run.expire_if_past_deadline(clock.t)
    assert run.outcome == DEADLINE
    # the late settle is a no-op (no dangling-future transition)
    pending.resolve("late")
    assert run.outcome == DEADLINE
    assert run.steps_done == 0


def test_drain_cancel_leaves_no_dangling_futures():
    pending = FakeFuture()
    run, _ = _run_unit(OneStep(lambda: pending))
    run.cancel()
    assert run.outcome == CANCELLED
    pending.fail(GeneralError("late failure"))  # no-op, not FAILED
    assert run.outcome == CANCELLED
    assert run._gen is None and run._step is None  # frames dropped


def test_parked_retry_resubmits_via_owner():
    parked = []
    calls = []

    def submit():
        fut = FakeFuture()
        calls.append(fut)
        if len(calls) == 1:
            fut.fail(ServiceOverloadedError(1, 1, retry_after_s=0.2))
        else:
            fut.resolve("ok")
        return fut

    clock = FakeClock()
    run = WorkflowRun(
        OneStep(submit), clock=clock, sleep=clock.sleep, seed=1,
        on_park=lambda r, at: parked.append((r, at)),
    )
    run.start()
    assert run.outcome is None and len(parked) == 1
    r, ready_at = parked[0]
    assert ready_at > 0.0
    clock.t = ready_at
    r.resubmit()
    assert run.outcome == COMPLETED and run.retries == 1


def test_terminal_hooks_fire_exactly_once():
    seen = []
    run = WorkflowRun(
        OneStep(lambda: FakeFuture()), clock=FakeClock(),
        on_terminal=lambda r: seen.append(r.outcome),
    )
    run.start()
    run.cancel()
    run.cancel()  # idempotent
    assert seen == [CANCELLED]


# --- traffic model: bit-stable seeded streams --------------------------------


def _sched():
    return RateSchedule(
        DiurnalCurve(2.0, 10.0, 60.0),
        [FlashCrowd(30.0, 10.0, 3.0, ramp_s=5.0)],
    )


def test_arrival_stream_bit_stable():
    a = list(arrival_times(_sched(), 60.0, random.Random(7)))
    b = list(arrival_times(_sched(), 60.0, random.Random(7)))
    assert a == b
    assert a == sorted(a) and all(0.0 <= t < 60.0 for t in a)
    digest = hashlib.sha256(
        ",".join("%.12f" % t for t in a).encode()
    ).hexdigest()
    assert len(a) == 659
    assert digest == (
        "7b8264c22c1acbf0114014ce7b84d07e4f350acda58b61f466a8a0bf830d7a75"
    )


def test_diurnal_and_flash_shapes():
    c = DiurnalCurve(2.0, 10.0, 60.0)
    assert c.rate(0.0) == pytest.approx(2.0)
    assert c.rate(30.0) == pytest.approx(10.0)
    assert c.rate(60.0) == pytest.approx(2.0)
    f = FlashCrowd(30.0, 10.0, 3.0, ramp_s=5.0)
    assert f.factor(0.0) == 1.0
    assert f.factor(27.5) == pytest.approx(2.0)  # mid-ramp
    assert f.factor(35.0) == 3.0
    assert f.factor(50.0) == 1.0
    assert f.window() == (30.0, 40.0)
    # the composed schedule's arrivals cluster where the rate is high
    a = list(arrival_times(_sched(), 60.0, random.Random(7)))
    in_flash = sum(1 for t in a if 30.0 <= t <= 40.0)
    head = sum(1 for t in a if t <= 10.0)
    assert in_flash > 3 * head


def test_zipf_skew_and_determinism():
    cdf = zipf_cdf(8, 1.2)
    assert len(cdf) == 8 and cdf[-1] == 1.0
    assert all(b > a for a, b in zip(cdf, cdf[1:]))
    rng = random.Random(3)
    picks = [zipf_pick(rng, cdf) for _ in range(20)]
    assert picks == [0, 1, 0, 1, 2, 0, 0, 4, 0, 0,
                     7, 1, 4, 1, 2, 0, 2, 4, 1, 3]
    counts = [0] * 8
    rng = random.Random(9)
    for _ in range(4000):
        counts[zipf_pick(rng, cdf)] += 1
    assert counts[0] > counts[1] > counts[7]  # rank skew


def test_population_lazy_and_deterministic():
    p1 = Population(1_000_000, n_tenants=8, seed=3)
    p2 = Population(1_000_000, n_tenants=8, seed=3)
    assert p1.materialized() == 0  # millions of users cost nothing
    uids = [0, 1, 17, 999_999]
    assert [p1.tenant_of(u) for u in uids] == [
        p2.tenant_of(u) for u in uids
    ]
    u = p1.user(17)
    assert p1.user(17) is u and p1.materialized() == 1
    assert u.seed == p2.user(17).seed
    # a different population seed shuffles tenants
    p3 = Population(1_000_000, n_tenants=8, seed=4)
    assert any(
        p1.tenant_of(u) != p3.tenant_of(u) for u in range(64)
    )


def test_report_attributes_outcomes():
    rep = ScenarioReport(slo_s=2.0, flash_window=(5.0, 8.0))
    rep.t0 = 100.0

    def fake_run(outcome, name="petition", label=None, code=None,
                 t_end=101.0, dur=0.5):
        return SimpleNamespace(
            wf=SimpleNamespace(name=name), outcome=outcome,
            outcome_label=label, error_code=code, retries=1,
            t_start=t_end - dur, t_end=t_end,
        )

    rep.record(fake_run(COMPLETED))
    rep.record(fake_run(COMPLETED, t_end=106.5))  # inside flash window
    rep.record(fake_run(REJECTED, label="double_spend"))
    rep.record(fake_run(FAILED, code="general"))
    rep.sample(0.0, in_flight=3, active_executors=2)
    out = rep.build(100.0, 10.0)
    assert out["totals"]["completed"] == 2
    assert out["totals"]["rejected_expected"] == 1
    assert out["totals"]["failed"] == 1
    assert out["rejections"]["petition"]["double_spend"] == 1
    assert out["error_codes"]["general"] == 1
    assert out["slo"]["attainment"] == 1.0
    assert out["slo"]["flash_completed"] == 1
    assert out["timeline"][0]["active_executors"] == 2
    # rejections are neither goodput nor errors
    avail = out["availability"]
    assert sum(avail["per_second_goodput"]) == 2
    assert sum(avail["per_second_errors"]) == 1


# --- end-to-end over loopback RPC -------------------------------------------


@pytest.fixture(scope="module")
def world():
    params = Params.new(MSGS, b"test-scenarios")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    return SimpleNamespace(
        params=params,
        signers=signers,
        backend=get_backend("python"),
        codec=wire.WireCodec(params),
    )


@pytest.fixture()
def loop(world, tmp_path):
    store = StateStore(str(tmp_path / "wal"), replica_id="rA")
    engine = ProtocolEngine(
        world.signers,
        world.params,
        THRESHOLD,
        count_hidden=HIDDEN,
        revealed_msg_indices=REVEALED,
        backend=world.backend,
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
        state_store=store,
    ).start()
    replica = rpc.Replica(engine, world.codec, replica_id="rA")
    client = rpc.GatewayClient(
        rpc.LoopbackTransport(replica), world.codec
    )
    yield SimpleNamespace(client=client, engine=engine, store=store)
    replica.close()
    assert engine.drain(timeout=60.0)
    store.close()


def test_petition_sign_resign_and_second_campaign_e2e(loop, world):
    sc = PetitionScenario(
        loop.client, world.params, campaigns=2, resign_p=0.0
    )
    user = Population(8, seed=11).user(0)
    r1 = run_workflow(sc.workflow(user, random.Random(1)))
    assert r1.outcome == COMPLETED, r1.error_code
    assert len(user.signed) == 1 and user.credential is not None

    # same credential, OTHER campaign: allowed (different domain)
    r2 = run_workflow(sc.workflow(user, random.Random(2)))
    assert r2.outcome == COMPLETED, r2.error_code
    assert user.signed == {0, 1}

    # both campaigns signed -> the script deliberately re-signs one;
    # the FRESH re-randomized show must be caught by the campaign-
    # scoped spend tag and surface as the typed expected rejection
    r3 = run_workflow(sc.workflow(user, random.Random(3)))
    assert r3.outcome == REJECTED
    assert r3.outcome_label == "double_spend"
    assert r3.error_code == "double_spend"
    assert user.signed == {0, 1}  # rejection did not grow the set


def test_ecash_double_spend_rejected_e2e(loop, world):
    sc = EcashScenario(loop.client, world.params, double_spend_p=1.0)
    user = Population(8, seed=12).user(1)
    # first run: honest spend, then a FRESH re-show of the spent coin
    # (shows_done parity 1 -> odd branch)
    r1 = run_workflow(sc.workflow(user, random.Random(5)))
    assert r1.outcome == REJECTED
    assert r1.outcome_label == "double_spend"
    assert user.coin is None  # the honest spend consumed the coin
    assert user.spent_show is not None
    # second run: new coin, honest spend, then an EXACT transcript
    # replay (parity 2 -> even branch) — also caught
    r2 = run_workflow(sc.workflow(user, random.Random(6)))
    assert r2.outcome == REJECTED
    assert r2.outcome_label == "double_spend"


def test_ecash_honest_spend_completes_e2e(loop, world):
    sc = EcashScenario(loop.client, world.params, double_spend_p=0.0)
    user = Population(8, seed=13).user(2)
    r = run_workflow(sc.workflow(user, random.Random(8)))
    assert r.outcome == COMPLETED, r.error_code
    assert user.coin is None and user.shows_done == 1


def test_access_session_rerandomized_shows_all_accepted_e2e(loop, world):
    metrics.reset()
    sc = AccessScenario(
        loop.client, world.params, session_range=(3, 3)
    )
    user = Population(8, seed=14).user(3)
    r = run_workflow(sc.workflow(user, random.Random(9)))
    assert r.outcome == COMPLETED, r.error_code
    assert user.shows_done == 3
    # prepare + mint + 3 x (show_prove + show_verify)
    assert r.steps_done == 8
    assert metrics.get_count("scenario_completed") == 1
    assert metrics.get_count("scenario_failed") == 0
