"""Differential test harness for CurveBackend implementations.

Any registered backend plugs into this module (VERDICT round-1, item 3): the
fixtures parametrize every test over all available backends, and every
assertion compares against the pure-Python spec ops bit-for-bit — affine
coordinates for MSM results, booleans for pairing products and verification.

Credentials here are built directly from master PS keys (sigma_1 = g^t,
sigma_2 = sigma_1^{x + sum y_j m_j}) rather than through the threshold
issuance protocol — same verification math (reference signature.rs:472-478),
much faster fixtures. The full-protocol path is covered in test_protocol.py.
"""

import os
import random

import pytest

from coconut_tpu.backend import PythonBackend, get_backend
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.ops.pairing import pairing_check
from coconut_tpu.params import Params
from coconut_tpu.ps import batch_verify, ps_verify
from coconut_tpu.signature import Signature, Sigkey, Verkey

rng = random.Random(0xBAC0)

MSG_COUNT = 6
BATCH = 8


def available_backends():
    names = ["python"]
    try:
        import jax  # noqa: F401

        from coconut_tpu.tpu import backend as _jb  # noqa: F401

        names.append("jax")
    except ImportError:
        pass
    from coconut_tpu import native

    if native.available():
        names.append("cpp")
    return names


@pytest.fixture(params=available_backends(), scope="module")
def backend(request):
    return get_backend(request.param)


@pytest.fixture(scope="module")
def params():
    return Params.new(MSG_COUNT, b"backend-test")


@pytest.fixture(scope="module")
def keypair(params):
    sk = Sigkey(rng.randrange(1, R), [rng.randrange(1, R) for _ in range(MSG_COUNT)])
    ops = params.ctx.other
    vk = Verkey(
        ops.mul(params.g_tilde, sk.x),
        [ops.mul(params.g_tilde, y) for y in sk.y],
    )
    return sk, vk


def direct_sign(sk, msgs, params, t=None):
    """PS signature straight from the master key (the output shape of
    unblind+aggregate, signature.rs:435-470)."""
    ops = params.ctx.sig
    t = t if t is not None else rng.randrange(1, R)
    sigma_1 = ops.mul(params.g, t)
    expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
    return Signature(sigma_1, ops.mul(sigma_1, expo))


@pytest.fixture(scope="module")
def mixed_batch(params, keypair):
    """BATCH credentials: some valid, some corrupted in distinct ways.
    Returns (sigs, messages_list, expected_bits)."""
    sk, vk = keypair
    sigs, msgs_list, expect = [], [], []
    for i in range(BATCH):
        msgs = [rng.randrange(R) for _ in range(MSG_COUNT)]
        sig = direct_sign(sk, msgs, params)
        kind = i % 4
        if kind == 1:  # tampered sigma_2
            sig = Signature(sig.sigma_1, params.ctx.sig.mul(sig.sigma_2, 2))
            expect.append(False)
        elif kind == 2:  # wrong message
            msgs = list(msgs)
            msgs[0] = (msgs[0] + 1) % R
            expect.append(False)
        elif kind == 3 and i == 3:  # identity sigma_1 forgery (ps.py guard)
            sig = Signature(None, None)
            expect.append(False)
        else:
            expect.append(True)
        sigs.append(sig)
        msgs_list.append(msgs)
    return sigs, msgs_list, expect


class TestPrimitives:
    def test_msm_g1_shared(self, backend):
        k = 4
        bases = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(k)]
        scalars = [[rng.randrange(R) for _ in range(k)] for _ in range(5)]
        got = backend.msm_g1_shared(bases, scalars)
        want = [g1.msm(bases, row) for row in scalars]
        assert got == want

    def test_msm_g2_shared(self, backend):
        k = 3
        bases = [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(k)]
        scalars = [[rng.randrange(R) for _ in range(k)] for _ in range(5)]
        got = backend.msm_g2_shared(bases, scalars)
        want = [g2.msm(bases, row) for row in scalars]
        assert got == want

    def test_msm_zero_and_identity_scalars(self, backend):
        bases = [G1_GEN, g1.mul(G1_GEN, 7)]
        scalars = [[0, 0], [1, 0], [0, 1], [R - 1, 1]]
        got = backend.msm_g1_shared(bases, scalars)
        want = [g1.msm(bases, row) for row in scalars]
        assert got == want

    def test_msm_g1_distinct(self, backend):
        k = 3
        pts = [
            [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(k)]
            for _ in range(4)
        ]
        scal = [[rng.randrange(R) for _ in range(k)] for _ in range(4)]
        scal[2][1] = 0  # zero scalar lane
        pts[3][0] = None  # identity base lane
        got = backend.msm_g1_distinct(pts, scal)
        want = [g1.msm(p, s) for p, s in zip(pts, scal)]
        assert got == want

    def test_msm_g2_distinct(self, backend):
        k = 2
        pts = [
            [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(k)]
            for _ in range(3)
        ]
        scal = [[rng.randrange(R) for _ in range(k)] for _ in range(3)]
        got = backend.msm_g2_distinct(pts, scal)
        want = [g2.msm(p, s) for p, s in zip(pts, scal)]
        assert got == want

    def test_pairing_product_is_one(self, backend):
        b = rng.randrange(1, R)
        good = [(G1_GEN, g2.mul(G2_GEN, b)), (g1.neg(g1.mul(G1_GEN, b)), G2_GEN)]
        bad = [(G1_GEN, g2.mul(G2_GEN, b)), (g1.neg(G1_GEN), G2_GEN)]
        got = backend.pairing_product_is_one([good, bad])
        assert [bool(x) for x in got] == [True, False]
        assert pairing_check(good) and not pairing_check(bad)


class TestBatchVerify:
    def test_matches_sequential_spec(self, backend, params, keypair, mixed_batch):
        _, vk = keypair
        sigs, msgs_list, expect = mixed_batch
        got = batch_verify(sigs, msgs_list, vk, params, backend=backend)
        seq = [ps_verify(s, m, vk, params) for s, m in zip(sigs, msgs_list)]
        assert [bool(x) for x in got] == seq == expect

    def test_backend_by_name(self, params, keypair, mixed_batch):
        _, vk = keypair
        sigs, msgs_list, expect = mixed_batch
        got = batch_verify(
            sigs[:4], msgs_list[:4], vk, params, backend="python"
        )
        assert [bool(x) for x in got] == expect[:4]


_heavy_skip = pytest.mark.skipif(
    os.environ.get("COCONUT_TEST_HEAVY") != "1",
    reason="multi-minute XLA compile on the 1-core CPU mesh; "
    "set COCONUT_TEST_HEAVY=1 (validated on the real chip by bench.py)",
)


def heavy(fn):
    """Gate + marker: skipped unless COCONUT_TEST_HEAVY=1, and tagged
    `heavy` so ci.sh's separate heavy-lane process selects exactly these
    tests file-agnostically (pytest -m heavy)."""
    return pytest.mark.heavy(_heavy_skip(fn))


class TestCombinedVerify:
    """Small-exponents combined/grouped batch verification (one bool)."""

    @heavy
    def test_combined_matches_all(self, params, keypair, mixed_batch):
        from coconut_tpu.backend import get_backend

        be = get_backend("jax")
        _, vk = keypair
        sigs, msgs_list, expect = mixed_batch
        ok = be.batch_verify_combined(sigs[:4], msgs_list[:4], vk, params)
        assert ok == all(expect[:4])
        good = [i for i, e in enumerate(expect) if e]
        ok2 = be.batch_verify_combined(
            [sigs[i] for i in good], [msgs_list[i] for i in good], vk, params
        )
        assert ok2 is True

    @heavy
    def test_grouped_matches_all(self, params, keypair, mixed_batch):
        from coconut_tpu.backend import get_backend

        be = get_backend("jax")
        _, vk = keypair
        sigs, msgs_list, expect = mixed_batch
        ok = be.batch_verify_grouped(sigs[:4], msgs_list[:4], vk, params)
        assert ok == all(expect[:4])
        good = [i for i, e in enumerate(expect) if e]
        ok2 = be.batch_verify_grouped(
            [sigs[i] for i in good], [msgs_list[i] for i in good], vk, params
        )
        assert ok2 is True

    @pytest.mark.parametrize("ctx_name", ["G1", "G2"])
    def test_forgery_rejected_tiny_shapes(self, ctx_name):
        """Soundness of the probabilistic one-bool paths in the DEFAULT
        suite (VERDICT r2 weak #1): B=2 / q=1 keeps the XLA compile to
        seconds on the CPU mesh while exercising the combiner algebra's
        reject behavior end to end — under BOTH group assignments (the
        grouped kernel's sig_fl/oth_fl roles flip with the ctx)."""
        from coconut_tpu.backend import get_backend
        from coconut_tpu.params import GroupContext

        be = get_backend("jax")
        tiny = Params.new(1, b"tiny-soundness", ctx=GroupContext(ctx_name))
        sk = Sigkey(rng.randrange(1, R), [rng.randrange(1, R)])
        ops = tiny.ctx.other
        vk = Verkey(
            ops.mul(tiny.g_tilde, sk.x),
            [ops.mul(tiny.g_tilde, y) for y in sk.y],
        )
        msgs = [[rng.randrange(R)] for _ in range(2)]
        sigs = [direct_sign(sk, m, tiny) for m in msgs]
        assert be.batch_verify_grouped(sigs, msgs, vk, tiny) is True
        assert be.batch_verify_combined(sigs, msgs, vk, tiny) is True
        # forge credential 1: tampered sigma_2 must fail the whole batch
        forged = [
            sigs[0],
            Signature(sigs[1].sigma_1, tiny.ctx.sig.mul(sigs[1].sigma_2, 2)),
        ]
        assert be.batch_verify_grouped(forged, msgs, vk, tiny) is False
        assert be.batch_verify_combined(forged, msgs, vk, tiny) is False
        # wrong message must fail too (exercises the grouped m_ij rows)
        wrong = [msgs[0], [(msgs[1][0] + 1) % R]]
        assert be.batch_verify_grouped(sigs, wrong, vk, tiny) is False

    def test_combined_empty_and_identity(self, params, keypair):
        import jax  # noqa: F401 (jax-only path)

        from coconut_tpu.backend import get_backend

        be = get_backend("jax")
        _, vk = keypair
        assert be.batch_verify_combined([], [], vk, params) is True
        assert be.batch_verify_grouped([], [], vk, params) is True
        bad = [Signature(None, None)]
        assert be.batch_verify_combined(bad, [[1] * MSG_COUNT], vk, params) is False
        assert be.batch_verify_grouped(bad, [[1] * MSG_COUNT], vk, params) is False


class TestBatchShowVerify:
    """Batched selective-disclosure verification (config 3) vs sequential."""

    def _make(self, params, keypair, n):
        from coconut_tpu.pok_sig import show

        sk, vk = keypair
        proofs, rmls = [], []
        for i in range(n):
            msgs = [rng.randrange(R) for _ in range(MSG_COUNT)]
            sig = direct_sign(sk, msgs, params)
            proof, chal, revealed = show(sig, vk, params, msgs, {1, 4})
            if i % 3 == 1:  # wrong revealed value
                revealed = dict(revealed)
                revealed[1] = (revealed[1] + 1) % R
            if i % 3 == 2:  # corrupted Schnorr response
                proof.proof_vc.responses[0] = (
                    proof.proof_vc.responses[0] + 1
                ) % R
            proofs.append(proof)
            rmls.append(revealed)
        return proofs, rmls

    def test_sequential_fallback(self, params, keypair):
        from coconut_tpu.ps import batch_show_verify

        proofs, rmls = self._make(params, keypair, 3)
        bits = batch_show_verify(proofs, keypair[1], params, rmls)
        assert bits == [True, False, False]

    @heavy
    def test_jax_matches_sequential(self, params, keypair):
        from coconut_tpu.ps import batch_show_verify

        proofs, rmls = self._make(params, keypair, 4)
        seq = batch_show_verify(proofs, keypair[1], params, rmls)
        got = batch_show_verify(proofs, keypair[1], params, rmls, backend="jax")
        assert got == seq

    @heavy
    def test_jax_combined_matches_sequential(self, params, keypair):
        """mode="batched" through the fused RLC show kernel
        (fused_show_verify_combined): the mixed batch (one valid, one
        wrong-revealed, one corrupted-Schnorr lane) must attribute each
        bad lane exactly as the sequential spec path does, and an
        all-valid batch must accept through the ONE-final-exp fold."""
        from coconut_tpu.ps import batch_show_verify

        proofs, rmls = self._make(params, keypair, 4)
        seq = batch_show_verify(proofs, keypair[1], params, rmls)
        got = batch_show_verify(
            proofs, keypair[1], params, rmls, backend="jax", mode="batched"
        )
        assert got == seq
        # all-valid lanes only: the combined check passes first try
        good = [i for i, b in enumerate(seq) if b]
        assert batch_show_verify(
            [proofs[i] for i in good],
            keypair[1],
            params,
            [rmls[i] for i in good],
            backend="jax",
            mode="batched",
        ) == [True] * len(good)


class TestBatchProver:
    """Batched prover side (VERDICT r2 item 4): batch_show and
    batch_prepare_blind_sign must produce proofs/requests indistinguishable
    from the sequential path to every verifier."""

    def test_batch_show_proofs_verify(self, backend, params, keypair):
        from coconut_tpu.pok_sig import batch_show, show_verify
        from coconut_tpu.ps import batch_show_verify

        sk, vk = keypair
        msgs_list, sigs = [], []
        for _ in range(4):
            msgs = [rng.randrange(R) for _ in range(MSG_COUNT)]
            sigs.append(direct_sign(sk, msgs, params))
            msgs_list.append(msgs)
        proofs, chals, rmls = batch_show(
            sigs, vk, params, msgs_list, {1, 4}, backend=backend
        )
        # every proof passes the sequential spec verifier (challenge
        # recomputed from the transcript — the secure FS path)
        for p, rm in zip(proofs, rmls):
            assert show_verify(p, vk, params, rm)
        seq = batch_show_verify(proofs, vk, params, rmls)
        assert seq == [True] * len(proofs)
        # tampered revealed message fails
        bad = dict(rmls[0])
        bad[1] = (bad[1] + 1) % R
        assert not show_verify(proofs[0], vk, params, bad)

    def test_batch_prepare_blind_sign_round_trip(self, backend, params, keypair):
        from coconut_tpu.elgamal import elgamal_keygen
        from coconut_tpu.ps import ps_verify
        from coconut_tpu.signature import (
            SignatureRequest,
            SignatureRequestPoK,
            batch_blind_sign,
            batch_prepare_blind_sign,
            batch_unblind,
            fiat_shamir_challenge,
        )

        sk, vk = keypair
        elg_sk, elg_pk = elgamal_keygen(params.ctx.sig, params.g)
        msgs_list = [
            [rng.randrange(R) for _ in range(MSG_COUNT)] for _ in range(3)
        ]
        hidden = 2
        out = batch_prepare_blind_sign(
            msgs_list, hidden, elg_pk, params, backend=backend
        )
        reqs = [r for r, _ in out]
        # the batched requests are structurally identical to sequential ones
        # (same h derivation, same wire encoding shape) and their PoKs verify
        for (req, rand), msgs in zip(out, msgs_list):
            assert req.get_h(params.ctx) == SignatureRequest.compute_h(
                req.commitment, req.known_messages, params.ctx
            )
            pok = SignatureRequestPoK.init(req, elg_pk, params)
            chal = fiat_shamir_challenge(pok.to_bytes())
            proof = pok.gen_proof(msgs[:hidden], rand, elg_sk, chal)
            assert proof.verify(req, elg_pk, chal, params)
        # and they round-trip through blind-sign + unblind to valid creds
        blinded = batch_blind_sign(reqs, sk, params, backend=backend)
        sigs = batch_unblind(blinded, elg_sk, params.ctx, backend=backend)
        for sig, msgs in zip(sigs, msgs_list):
            assert ps_verify(sig, msgs, vk, params)

    def test_batch_prepare_blind_sign_g2_assignment(self):
        """The SIGNATURES_IN_G2 prepare path through the jax backend: the
        fused ElGamal/commitment programs and the offset-fused c2 kernel
        run in Fp2 there (the reference tests both group assignments,
        .travis.yml:8-9). Ciphertexts must decrypt to h^m exactly."""
        pytest.importorskip("jax")
        from coconut_tpu.elgamal import elgamal_decrypt, elgamal_keygen
        from coconut_tpu.params import SIGNATURES_IN_G2, Params
        from coconut_tpu.signature import batch_prepare_blind_sign

        params = Params.new(3, b"backend-test-g2", ctx=SIGNATURES_IN_G2)
        ops = params.ctx.sig
        elg_sk, elg_pk = elgamal_keygen(ops, params.g)
        msgs_list = [[rng.randrange(R) for _ in range(3)] for _ in range(2)]
        out = batch_prepare_blind_sign(
            msgs_list, 2, elg_pk, params, backend=get_backend("jax")
        )
        for (req, rand), msgs in zip(out, msgs_list):
            h = req.get_h(params.ctx)
            for j, (c1, c2) in enumerate(req.ciphertexts):
                assert elgamal_decrypt(ops, c1, c2, elg_sk) == ops.mul(
                    h, msgs[j] % R
                )


class TestBatchIssuance:
    """batch_blind_sign / batch_unblind vs the sequential per-request path
    (BASELINE config 4; reference signature.rs:396-443)."""

    @pytest.mark.parametrize(
        "hidden,batch_prepare",
        [(2, False), (0, True), (1, True), (MSG_COUNT, True)],
    )
    def test_matches_sequential(
        self, backend, params, keypair, hidden, batch_prepare
    ):
        """Batched blind-sign/unblind parity with the sequential path
        (signature.rs:124-207, 380-443), over the standard split
        (hidden=2, sequentially-prepared requests) and the boundary
        splits through the batched prepare: hidden=0 (no ciphertexts ->
        c_tilde_1 is the identity, the unfused fallback's dedicated
        branch), hidden=1, and all-hidden (no known messages in the h
        derivation / c_tilde_2 exponent)."""
        from coconut_tpu.elgamal import elgamal_keygen
        from coconut_tpu.signature import (
            BlindSignature,
            SignatureRequest,
            batch_blind_sign,
            batch_prepare_blind_sign,
            batch_unblind,
        )

        sk, vk = keypair
        elg_sk, elg_pk = elgamal_keygen(params.ctx.sig, params.g)
        msgs_list = [
            [rng.randrange(R) for _ in range(MSG_COUNT)]
            for _ in range(4 if not batch_prepare else 2)
        ]
        if batch_prepare:
            out = batch_prepare_blind_sign(
                msgs_list, hidden, elg_pk, params, backend=backend
            )
            reqs = [r for r, _ in out]
        else:
            reqs = [
                SignatureRequest.new(m, hidden, elg_pk, params)[0]
                for m in msgs_list
            ]
        for req in reqs:
            assert len(req.ciphertexts) == hidden
            assert len(req.known_messages) == MSG_COUNT - hidden
        got = batch_blind_sign(reqs, sk, params, backend=backend)
        want = [BlindSignature.new(r, sk, params) for r in reqs]
        assert [(b.h, b.blinded) for b in got] == [
            (b.h, b.blinded) for b in want
        ]
        sigs = batch_unblind(got, elg_sk, params.ctx, backend=backend)
        for sig, msgs in zip(sigs, msgs_list):
            assert ps_verify(sig, msgs, vk, params)


class TestPippenger:
    """Native Pippenger bucket MSM (reference multi_scalar_mul_var_time,
    signature.rs:513,521) vs the spec, across the crossover and edge
    lanes."""

    def test_matches_spec(self):
        from coconut_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        for n in (1, 3, 97, 200):
            pts = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(n)]
            ss = [rng.randrange(R) for _ in range(n)]
            if n > 2:
                pts[1] = None  # identity lane
                ss[2] = 0  # zero scalar lane
            assert native.msm_g1_single(pts, ss) == g1.msm(pts, ss)
            assert native.msm_g1_single(
                pts, ss, force_pippenger=True
            ) == g1.msm(pts, ss)
        p2 = [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(100)]
        s2 = [rng.randrange(R) for _ in range(100)]
        assert native.msm_g2_single(p2, s2) == g2.msm(p2, s2)


class TestNativeSss:
    """Native Fr Lagrange/Shamir (the secret_sharing crate surface,
    keygen.rs:58,248, signature.rs:460,502) vs the Python sss module —
    including the gap-id edge cases the reference tests hardest."""

    def test_matches_python_sss(self):
        from coconut_tpu import native, sss

        if not native.available():
            pytest.skip("native library unavailable")
        # lagrange over gap-containing id sets
        from coconut_tpu.errors import GeneralError

        for ids in ({1, 2, 3}, {2, 5, 7}, {1, 4, 9, 11, 30}):
            for i in ids:
                assert native.lagrange_basis_at_0(
                    ids, i
                ) == sss.lagrange_basis_at_0(ids, i)
        with pytest.raises(GeneralError):
            native.lagrange_basis_at_0({1, 2}, 3)
        with pytest.raises(GeneralError):  # uint32 ABI range guard
            native.lagrange_basis_at_0({1, 1 << 33}, 1)
        # poly eval + full shamir round trip through the native side
        coeffs = sss.poly_random(3)
        for x in (1, 2, 77):
            assert native.poly_eval(coeffs, x) == sss.poly_eval(coeffs, x)
        secret, shares = sss.get_shared_secret(3, 5)
        sub = {i: shares[i] for i in (1, 3, 5)}
        assert native.reconstruct_secret(3, sub) == secret
        assert sss.reconstruct_secret(3, sub) == secret


class TestNativePedersenVss:
    """Native Pedersen VSS/DVSS (keygen.rs:74-205 surface) vs the Python
    sss module — same coefficients must produce bit-identical commitments
    and shares, and the two participant implementations must interoperate."""

    def _gens(self):
        from coconut_tpu import sss

        return sss.PedersenVSS.gens(b"native-vss-test")

    def test_deal_from_coeffs_matches_python(self):
        from coconut_tpu import native, sss

        if not native.available():
            pytest.skip("native library unavailable")
        g, h = self._gens()
        t, n = 3, 5
        fc = [rng.randrange(R) for _ in range(t)]
        gc = [rng.randrange(R) for _ in range(t)]
        comms, ss_, ts = native.pedersen_deal_from_coeffs(t, n, g, h, fc, gc)
        want_comms = {
            j: g1.add(g1.mul(g, fc[j]), g1.mul(h, gc[j])) for j in range(t)
        }
        assert comms == want_comms
        assert ss_ == {i: sss.poly_eval(fc, i) for i in range(1, n + 1)}
        assert ts == {i: sss.poly_eval(gc, i) for i in range(1, n + 1)}

    def test_verify_share_cross_implementation(self):
        from coconut_tpu import native, sss

        if not native.available():
            pytest.skip("native library unavailable")
        g, h = self._gens()
        t, n = 3, 5
        # native deal verified by BOTH verifiers; a tampered share fails both
        sec, blind, comms, s_sh, t_sh = native.pedersen_deal(t, n, g, h)
        for i in range(1, n + 1):
            share = (s_sh[i], t_sh[i])
            assert native.pedersen_verify_share(t, i, share, comms, g, h)
            assert sss.PedersenVSS.verify_share(t, i, share, comms, g, h)
        bad = ((s_sh[2] + 1) % R, t_sh[2])
        assert not native.pedersen_verify_share(t, 2, bad, comms, g, h)
        assert not sss.PedersenVSS.verify_share(t, 2, bad, comms, g, h)
        # python deal verified by the native verifier
        psec, pblind, pcomms, ps_sh, pt_sh = sss.PedersenVSS.deal(t, n, g, h)
        for i in (1, 4):
            assert native.pedersen_verify_share(
                t, i, (ps_sh[i], pt_sh[i]), pcomms, g, h
            )
        # dealt secret is reconstructable from any t shares
        assert sss.reconstruct_secret(
            t, {i: s_sh[i] for i in (1, 3, 5)}
        ) == sec

    def test_dvss_native_matches_python_protocol(self):
        from coconut_tpu import native, sss
        from coconut_tpu.errors import GeneralError

        if not native.available():
            pytest.skip("native library unavailable")
        g, h = self._gens()
        t, n = 2, 4
        ps = native.share_secret_dvss(t, n, g, h)
        # the distributed secret (sum of the per-participant dealt secrets)
        # reconstructs from any t final shares — same oracle the reference
        # asserts in check_reconstructed_keys (keygen.rs:231-297)
        shares = {p.id: p.secret_share for p in ps}
        for sub in ({1, 2}, {2, 4}, {1, 3}):
            got = sss.reconstruct_secret(t, {i: shares[i] for i in sub})
            first = sss.reconstruct_secret(t, dict(list(shares.items())[:t]))
            assert got == first
        # all participants agree on the combined coefficient commitments
        for p in ps[1:]:
            assert p.final_comm_coeffs == ps[0].final_comm_coeffs
        # combined commitments verify each final share (python-side check)
        for p in ps:
            assert sss.PedersenVSS.verify_share(
                t,
                p.id,
                (p.secret_share, p.t_secret_share),
                p.final_comm_coeffs,
                g,
                h,
            )
        # a native participant interoperates inside the python protocol
        py = sss.PedersenDVSSParticipant(1, t, 3, g, h)
        nat = native.DvssParticipant(2, t, 3, g, h)
        py3 = sss.PedersenDVSSParticipant(3, t, 3, g, h)
        group = [py, nat, py3]
        for recv in group:
            for sender in group:
                if sender.id == recv.id:
                    continue
                recv.received_share(
                    sender.id,
                    sender.comm_coeffs,
                    (sender.s_shares[recv.id], sender.t_shares[recv.id]),
                    t,
                    3,
                    g,
                    h,
                )
        for p in group:
            p.compute_final_comm_coeffs_and_shares(t, 3, g, h)
        assert nat.final_comm_coeffs == py.final_comm_coeffs
        rec_a = sss.reconstruct_secret(
            t, {1: py.secret_share, 2: nat.secret_share}
        )
        rec_b = sss.reconstruct_secret(
            t, {2: nat.secret_share, 3: py3.secret_share}
        )
        assert rec_a == rec_b
        # duplicate + self-share rejection on the native state machine
        with pytest.raises(GeneralError):
            nat.received_share(
                1, py.comm_coeffs, (py.s_shares[2], py.t_shares[2])
            )
        with pytest.raises(GeneralError):
            nat.received_share(
                2, nat.comm_coeffs, (nat.s_shares[2], nat.t_shares[2])
            )
        # a corrupted pairwise share is detected (the malicious-dealer
        # fault-tolerance story, README.md:52-68)
        fresh = native.DvssParticipant(3, t, 3, g, h)
        with pytest.raises(GeneralError):
            fresh.received_share(
                1,
                py.comm_coeffs,
                ((py.s_shares[3] + 1) % R, py.t_shares[3]),
            )


class TestConstTimeMsm:
    """The native masked-lookup MSM (ct=True): complete-formula path must be
    bit-identical to the var-time path on adversarial digit patterns, and
    its schedule must not depend on the scalars (VERDICT r2 item 7)."""

    def test_ct_matches_var_time_on_edge_scalars(self):
        from coconut_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        ct = native.CppBackend(ct=True)
        vt = native.CppBackend(ct=False)
        bases = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(3)]
        rows = [
            [0, 0, 0],
            [1, 1, 1],
            [R - 1, R - 1, R - 1],
            [1 << 128, (1 << 255) % R, 0xF0F0F0F0],
            [rng.randrange(R) for _ in range(3)],
        ]
        want = [g1.msm(bases, r) for r in rows]
        assert ct.msm_g1_shared(bases, rows) == want
        assert vt.msm_g1_shared(bases, rows) == want
        b2 = [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(2)]
        rows2 = [[0, 1], [R - 1, 0], [rng.randrange(R), rng.randrange(R)]]
        want2 = [g2.msm(b2, r) for r in rows2]
        assert ct.msm_g2_shared(b2, rows2) == want2

    @pytest.mark.skipif(
        os.environ.get("COCONUT_TIMING_TEST") != "1",
        reason="statistical timing check; flaky on loaded shared hosts "
        "(set COCONUT_TIMING_TEST=1)",
    )
    def test_ct_timing_independent_of_scalars(self):
        """Smoke check: all-zero vs all-max scalars must take comparable
        time through the ct schedule (every table entry read, every add a
        complete-formula add). Generous 1.5x tolerance for scheduler
        noise."""
        import time

        from coconut_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        ct = native.CppBackend(ct=True)
        bases = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(2)]
        zeros = [[0, 0]] * 8
        maxes = [[R - 1, R - 1]] * 8
        ct.msm_g1_shared(bases, zeros)  # warm
        t0 = time.perf_counter()
        ct.msm_g1_shared(bases, zeros)
        tz = time.perf_counter() - t0
        t0 = time.perf_counter()
        ct.msm_g1_shared(bases, maxes)
        tm = time.perf_counter() - t0
        assert max(tz, tm) / min(tz, tm) < 1.5, (tz, tm)

    @pytest.mark.skipif(
        os.environ.get("COCONUT_TIMING_TEST") != "1",
        reason="statistical timing check; flaky on loaded shared hosts "
        "(set COCONUT_TIMING_TEST=1)",
    )
    def test_jax_distinct_timing_independent_of_scalars(self):
        """The device issuance path (CONSTTIME.md): the distinct-base MSM
        program is a static XLA schedule whose one data-dependent input
        is gather indices — digit-extreme scalar patterns must take
        comparable time. Same tolerance/style as the cpp_ct smoke."""
        import time

        from coconut_tpu.backend import get_backend

        be = get_backend("jax")
        bases = [
            [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(2)]
            for _ in range(4)
        ]
        dense = sum(16 * (32**i) for i in range(51)) % R
        patterns = {
            "zeros": [[0, 0]] * 4,
            "dense": [[dense, dense]] * 4,
            "rm1": [[R - 1, R - 1]] * 4,
        }
        times = {}
        for name, rows in patterns.items():
            be.msm_g1_distinct(bases, rows)  # warm/compile
            best = None
            for _ in range(5):
                t0 = time.perf_counter()
                be.msm_g1_distinct(bases, rows)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            times[name] = best
        assert max(times.values()) / min(times.values()) < 1.5, times


class TestGlv:
    """GLV endomorphism constants and decomposition (tpu/glv.py) vs the
    spec ops: phi's eigenvalue, exactness of the Euclidean split, and the
    reassembled scalar mul."""

    def test_phi_eigenvalue_and_decomposition(self):
        from coconut_tpu.tpu import glv

        for _ in range(5):
            pt = g1.mul(G1_GEN, rng.randrange(1, R))
            assert glv.phi(pt) == g1.mul(pt, glv.LAMBDA)
        assert glv.phi(None) is None
        for k in (0, 1, glv.LAMBDA - 1, glv.LAMBDA, R - 1,
                  rng.randrange(R), rng.randrange(R)):
            k1, k2 = glv.decompose(k)
            assert 0 <= k1 < 1 << 128 and 0 <= k2 < 1 << 128
            assert (k1 + k2 * glv.LAMBDA) % R == k % R
            pt = g1.mul(G1_GEN, 0xBEEF)
            assert g1.mul(pt, k) == g1.add(
                g1.mul(pt, k1), g1.mul(glv.phi(pt), k2)
            )


def test_python_backend_is_default_registry():
    assert isinstance(get_backend("python"), PythonBackend)
    with pytest.raises(ValueError):
        get_backend("no-such-backend")


class TestSignedWindowRecoding:
    """fr_digits_signed_np: the grouped verify's MSM window schedule."""

    def test_roundtrip_and_bounds(self):
        from coconut_tpu.ops.fields import R
        from coconut_tpu.tpu.limbs import fr_digits_signed_np

        ks = [rng.randrange(R) for _ in range(64)] + [0, 1, 16, 17, 31, 32, R - 1]
        mag, neg = fr_digits_signed_np(ks)
        assert mag.shape == (len(ks), 52) and int(mag.max()) <= 16
        for k, m_row, n_row in zip(ks, mag, neg):
            v = 0
            for w in range(52):
                v = v * 32 + int(m_row[w]) * (-1 if n_row[w] else 1)
            assert v == k % R
        # mag 0 never carries a sign (gathered identity must not Y-flip)
        assert not (neg & (mag == 0)).any()

    def test_128bit_rows_have_zero_top_windows(self):
        import secrets as _s

        from coconut_tpu.tpu.limbs import fr_digits_signed_np

        mag, _ = fr_digits_signed_np([_s.randbits(128) for _ in range(32)])
        assert not mag[:, : 52 - 27].any()


class TestCycloSq:
    """fp12_cyclo_sq (Granger-Scott) vs generic fp12_sq on GT elements —
    the final-exponentiation squaring-chain workhorse."""

    def test_matches_generic_square_on_gt(self):
        import jax
        from coconut_tpu.ops.pairing import pairing
        from coconut_tpu.tpu import tower as tw

        p1 = g1.mul(G1_GEN, rng.randrange(1, R))
        q2 = g2.mul(G2_GEN, rng.randrange(1, R))
        gt = pairing(p1, q2)  # cyclotomic by construction
        e = tw.encode_batch([gt, gt])  # leading [2] batch
        got, want = jax.jit(
            lambda x: (tw.fp12_cyclo_sq(x), tw.fp12_sq(x))
        )(e)
        # chained: 8th power through repeated cyclo squarings stays exact
        eighth = jax.jit(
            lambda x: tw.fp12_cyclo_sq(
                tw.fp12_cyclo_sq(tw.fp12_cyclo_sq(x))
            )
        )(e)
        dg = tw.decode_batch(got)
        dw = tw.decode_batch(want)
        assert dg == dw
        d8 = tw.decode_batch(eighth)
        from coconut_tpu.ops import fields as F

        w = gt
        for _ in range(3):
            w = F.fp12_sq(w)
        assert d8[0] == w


class TestGroupedMsms:
    """_grouped_msms (signed 6-bit schedule) vs the spec MSM — the whole
    per-credential arithmetic of the headline grouped verify."""

    def test_signed6_recode_roundtrip(self):
        from coconut_tpu.tpu.limbs import fr_digits_signed_np

        ks = [rng.randrange(R) for _ in range(32)] + [0, 1, 32, 33, 63, 64, R - 1]
        mag, neg = fr_digits_signed_np(ks, nwin=43, window=6)
        assert mag.shape == (len(ks), 43) and int(mag.max()) <= 32
        for k, m_row, n_row in zip(ks, mag, neg):
            v = 0
            for w in range(43):
                v = v * 64 + int(m_row[w]) * (-1 if n_row[w] else 1)
            assert v == k % R
        assert not (neg & (mag == 0)).any()

    def test_matches_spec(self):
        import jax.numpy as jnp
        import numpy as np

        import jax
        from coconut_tpu.tpu import curve as cv, tower as tw
        from coconut_tpu.tpu.backend import _grouped_msms
        from coconut_tpu.tpu.limbs import fr_digits_signed_np

        B = 16
        pts = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(B)]
        x = tw.encode_batch([p[0] for p in pts])
        y = tw.encode_batch([p[1] for p in pts])
        inf = jnp.zeros(B, dtype=bool)
        rows = [[rng.randrange(R) for _ in range(B)] for _ in range(2)]
        rows[1][3] = 0  # zero-scalar lane
        rec = [fr_digits_signed_np(r, nwin=43, window=6) for r in rows]
        mag = jnp.asarray(np.stack([m for m, _ in rec]))
        sgn = jnp.asarray(np.stack([s for _, s in rec]))
        ax, ay, ainf = jax.jit(
            lambda x, y, i, m, s: cv.to_affine(
                cv.FP, _grouped_msms(cv.FP, x, y, i, m, s)
            )
        )(x, y, inf, mag, sgn)
        gx = tw.decode_batch(ax)
        gy = tw.decode_batch(ay)
        gi = np.asarray(ainf)
        for m, row in enumerate(rows):
            want = g1.msm(pts, row)
            got = None if gi[m] else (gx[m], gy[m])
            assert got == want


class TestCombCacheLru:
    """_COMB_CACHE eviction: least-recently-used, never wholesale."""

    def test_lru_eviction_keeps_hot_entries(self, monkeypatch):
        from coconut_tpu.tpu import backend as be

        monkeypatch.setattr(be, "_COMB_CACHE", {})
        monkeypatch.setattr(be, "_COMB_CACHE_MAX", 4)
        builds = []
        monkeypatch.setattr(be, "_build_tables", lambda *_a, **_k: None)
        monkeypatch.setattr(
            be, "_comb_build_kernel", lambda *_a: builds.append(1) or len(builds)
        )

        def tables(i):
            return be._comb_tables(None, False, ((i, i),))

        hot = tables(0)
        for i in range(1, 4):
            tables(i)  # fill: cache = {0, 1, 2, 3}
        assert tables(0) == hot and len(builds) == 4  # hit refreshes recency
        tables(4)  # evicts 1 (LRU), NOT the just-touched 0
        assert tables(0) == hot and len(builds) == 5
        tables(1)  # 1 was evicted: rebuild
        assert len(builds) == 6
        # the hot entry survived every eviction (key = (window, fp2, bases))
        window = be._comb_schedule()[0]
        assert ((window, False, ((0, 0),)) in be._COMB_CACHE)


class TestBenchShapeHeavy:
    """The driver-bench shapes in-repo (VERDICT r4 item 4): four rounds
    running, a width/shape-dependent wrong-bits bug existed that only the
    bench asserts on the real chip could see. This compiles the EXACT
    bench-shape per-credential program — B=1024, q=6, the chip's 9-bit
    comb schedule — in the heavy lane and asserts the forged lane flips."""

    @heavy
    def test_percred_b1024_bench_shape_rejects_forged_lane(self, monkeypatch):
        import numpy as np

        import __graft_entry__ as ge
        from coconut_tpu.tpu import backend as tbe

        # force the chip's comb schedule on the CPU mesh (the default
        # CPU window is 6; the bench runs 9) — _C_SCHED re-derives from
        # the env, and the cache key carries the window
        monkeypatch.setenv("COCONUT_COMB_WINDOW", "9")
        monkeypatch.setattr(tbe, "_C_SCHED", None)
        params, _, vk, sigs, msgs_list = ge._fixture(batch=1024)
        be = tbe.JaxBackend()
        forged = list(sigs)
        mid = len(sigs) // 2
        forged[mid] = Signature(
            sigs[mid].sigma_1, params.ctx.sig.mul(sigs[mid].sigma_2, 2)
        )
        operands = be.encode_verify_batch(forged, msgs_list, vk, params)
        bits = np.asarray(
            tbe._fused_verify_kernel(params.ctx.name == "G1", *operands)
        )
        assert not bits[mid] and int(bits.sum()) == len(sigs) - 1
        # monkeypatch teardown restores _C_SCHED and the env var


def test_comb_window_guard_rejects_unsupported_widths(monkeypatch):
    """COCONUT_COMB_WINDOW outside [1, 9] must fail loudly — 10 is
    blocked by the probed axon Fp2 table-build miscompile, not algebra
    (probes/README.md), and silently wrong G2 MSMs are the alternative."""
    from coconut_tpu.tpu import backend as tbe

    for bad in ("0", "10", "11"):
        monkeypatch.setenv("COCONUT_COMB_WINDOW", bad)
        with pytest.raises(ValueError, match="capped at 9"):
            tbe._comb_window_default()
    monkeypatch.setenv("COCONUT_COMB_WINDOW", "9")
    assert tbe._comb_window_default() == 9
