"""Protocol-layer tests: the reference's full test matrix (SURVEY.md §4) —
every property under all three keygen modes, id-gap and cross-subset
aggregation — plus the negative and serialization coverage the reference
lacked (SURVEY.md §4 'gaps to improve on')."""

import pytest

from coconut_tpu.elgamal import elgamal_decrypt, elgamal_encrypt, elgamal_keygen
from coconut_tpu.errors import GeneralError, UnsupportedNoOfMessages
from coconut_tpu.keygen import (
    dvss_keygen,
    trusted_party_PVSS_keygen,
    trusted_party_SSS_keygen,
)
from coconut_tpu.params import DEFAULT_CTX, SIGNATURES_IN_G2, Params
from coconut_tpu.pok_sig import show, show_verify
from coconut_tpu.ps import batch_verify
from coconut_tpu.signature import (
    BlindSignature,
    Signature,
    SignatureRequest,
    SignatureRequestPoK,
    Verkey,
    fiat_shamir_challenge,
)
from coconut_tpu.sss import (
    PedersenVSS,
    lagrange_basis_at_0,
    rand_fr,
    reconstruct_secret,
)

THRESHOLD, TOTAL = 3, 5


@pytest.fixture(scope="module")
def params7():
    return Params.new(7, b"test")


@pytest.fixture(scope="module")
def params6():
    return Params.new(6, b"test")


@pytest.fixture(scope="module")
def pvss_gens():
    return PedersenVSS.gens(b"testPVSS")


# --- shared check helpers (reference: signature.rs:537-638) -----------------


def check_key_aggregation(threshold, msg_count, secret_x, secret_y, signers, params):
    aggr_vk = Verkey.aggregate(
        threshold,
        [(s.id, s.verkey) for s in signers[:threshold]],
        params.ctx,
    )
    assert aggr_vk.X_tilde == params.ctx.other.mul(params.g_tilde, secret_x)
    for i in range(msg_count):
        assert aggr_vk.Y_tilde[i] == params.ctx.other.mul(
            params.g_tilde, secret_y[i]
        )


def check_reconstructed_keys(threshold, msg_count, secret_x, secret_y, signers, params):
    """keygen.rs:231-297: reconstruct master secret from t shares and
    re-derive the master pubkey by Lagrange-MSM."""
    shares_x = {s.id: s.sigkey.x for s in signers[:threshold]}
    assert reconstruct_secret(threshold, shares_x) == secret_x
    for j in range(msg_count):
        shares_y = {s.id: s.sigkey.y[j] for s in signers[:threshold]}
        assert reconstruct_secret(threshold, shares_y) == secret_y[j]
    ids = {s.id for s in signers[:threshold]}
    ops = params.ctx.other
    ls = {i: lagrange_basis_at_0(ids, i) for i in ids}
    x_recon = ops.msm(
        [s.verkey.X_tilde for s in signers[:threshold]],
        [ls[s.id] for s in signers[:threshold]],
    )
    assert x_recon == ops.mul(params.g_tilde, secret_x)


def run_issuance(threshold, msg_count, count_hidden, signers, params,
                 signer_indices=None, vk_indices=None):
    """The full credential lifecycle (signature.rs:582-638). Returns
    (msgs, aggregated signature, aggregated verkey)."""
    msgs = [rand_fr() for _ in range(msg_count)]
    elg_sk, elg_pk = elgamal_keygen(params.ctx.sig, params.g)
    sig_req, randomness = SignatureRequest.new(msgs, count_hidden, elg_pk, params)
    pok = SignatureRequestPoK.init(sig_req, elg_pk, params)
    challenge = fiat_shamir_challenge(pok.to_bytes())
    hidden = msgs[:count_hidden]
    proof = pok.gen_proof(hidden, randomness, elg_sk, challenge)

    signer_indices = signer_indices or list(range(threshold))
    unblinded = []
    for idx in signer_indices:
        s = signers[idx]
        # each signer verifies the PoK before signing (signature.rs:613-616),
        # recomputing the Fiat-Shamir challenge itself
        chal = fiat_shamir_challenge(
            proof.to_bytes_for_challenge(sig_req, elg_pk, params)
        )
        assert chal == challenge
        assert proof.verify(sig_req, elg_pk, chal, params)
        blind_sig = BlindSignature.new(sig_req, s.sigkey, params)
        unblinded_sig = blind_sig.unblind(elg_sk, params.ctx)
        assert unblinded_sig.verify(msgs, s.verkey, params)
        unblinded.append((s.id, unblinded_sig))

    aggr_sig = Signature.aggregate(threshold, unblinded, params.ctx)
    vk_indices = vk_indices or signer_indices
    aggr_vk = Verkey.aggregate(
        threshold,
        [(signers[i].id, signers[i].verkey) for i in vk_indices],
        params.ctx,
    )
    assert aggr_sig.verify(msgs, aggr_vk, params)
    return msgs, aggr_sig, aggr_vk


# --- elgamal (elgamal.rs tests) --------------------------------------------


@pytest.mark.parametrize("ctx", [DEFAULT_CTX, SIGNATURES_IN_G2])
def test_elgamal_roundtrip(ctx):
    ops = ctx.sig
    g = ctx.hash_to_sig(b"elgamal test base")
    sk, pk = elgamal_keygen(ops, g)
    msg = ops.mul(g, rand_fr())
    c1, c2, _k = elgamal_encrypt(ops, g, pk, msg)
    assert elgamal_decrypt(ops, c1, c2, sk) == msg


# --- keygen (keygen.rs tests) ----------------------------------------------


def test_keygen_shapes(params7):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params7)
    assert len(signers) == TOTAL
    for i, s in enumerate(signers):
        assert s.id == i + 1
        assert len(s.sigkey.y) == 7
        assert len(s.verkey.Y_tilde) == 7


def test_keygen_reconstruction_shamir(params7):
    sx, sy, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params7)
    check_reconstructed_keys(THRESHOLD, 7, sx, sy, signers, params7)


def test_keygen_reconstruction_pvss(params7, pvss_gens):
    g, h = pvss_gens
    out = trusted_party_PVSS_keygen(THRESHOLD, TOTAL, params7, g, h)
    # every signer verifies its share against the dealer's commitments
    # (keygen.rs:333-352)
    for i in range(1, TOTAL + 1):
        assert PedersenVSS.verify_share(
            THRESHOLD,
            i,
            (out.x_shares[i], out.x_t_shares[i]),
            out.comm_coeff_x,
            g,
            h,
        )
        for j in range(7):
            assert PedersenVSS.verify_share(
                THRESHOLD,
                i,
                (out.y_shares[j][i], out.y_t_shares[j][i]),
                out.comm_coeff_y[j],
                g,
                h,
            )
    check_reconstructed_keys(
        THRESHOLD, 7, out.secret_x, out.secret_y, out.signers, params7
    )


def test_keygen_reconstruction_dvss(params7, pvss_gens):
    g, h = pvss_gens
    sx, sy, signers = dvss_keygen(THRESHOLD, TOTAL, params7, g, h)
    check_reconstructed_keys(THRESHOLD, 7, sx, sy, signers, params7)


# --- verkey aggregation (signature.rs:640-666,710-759) ----------------------


def test_verkey_aggregation_shamir(params7):
    sx, sy, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params7)
    check_key_aggregation(THRESHOLD, 7, sx, sy, signers, params7)


def test_verkey_aggregation_pvss(params7, pvss_gens):
    g, h = pvss_gens
    out = trusted_party_PVSS_keygen(THRESHOLD, TOTAL, params7, g, h)
    check_key_aggregation(
        THRESHOLD, 7, out.secret_x, out.secret_y, out.signers, params7
    )


@pytest.mark.parametrize("mode", ["shamir", "pvss"])
def test_verkey_aggregation_gaps_in_ids(params7, pvss_gens, mode):
    if mode == "shamir":
        sx, sy, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params7)
    else:
        g, h = pvss_gens
        out = trusted_party_PVSS_keygen(THRESHOLD, TOTAL, params7, g, h)
        sx, sy, signers = out.secret_x, out.secret_y, out.signers
    keys = [(signers[i].id, signers[i].verkey) for i in (0, 2, 4)]
    aggr_vk = Verkey.aggregate(THRESHOLD, keys, params7.ctx)
    assert aggr_vk.X_tilde == params7.ctx.other.mul(params7.g_tilde, sx)
    for i in range(7):
        assert aggr_vk.Y_tilde[i] == params7.ctx.other.mul(
            params7.g_tilde, sy[i]
        )


# --- full lifecycle under all three keygen modes (signature.rs:668-708) -----


def test_sign_verify_shamir(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    run_issuance(THRESHOLD, 6, 2, signers, params6)


def test_sign_verify_pvss(params6, pvss_gens):
    g, h = pvss_gens
    out = trusted_party_PVSS_keygen(THRESHOLD, TOTAL, params6, g, h)
    run_issuance(THRESHOLD, 6, 2, out.signers, params6)


def test_sign_verify_dvss(params6, pvss_gens):
    g, h = pvss_gens
    _, _, signers = dvss_keygen(THRESHOLD, TOTAL, params6, g, h)
    run_issuance(THRESHOLD, 6, 2, signers, params6)


def test_sign_verify_different_vk_subset(params6):
    """Sign with signers {1,3,5}, aggregate verkey from {2,4,6}
    (signature.rs:761-822)."""
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, 6, params6)
    run_issuance(
        THRESHOLD, 6, 2, signers, params6,
        signer_indices=[0, 2, 4], vk_indices=[1, 3, 5],
    )


def test_sign_verify_no_hidden(params6):
    """count_hidden = 0: no ciphertexts, empty hidden-message sub-proofs."""
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    run_issuance(THRESHOLD, 6, 0, signers, params6)


def test_sign_verify_all_hidden(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    run_issuance(THRESHOLD, 6, 6, signers, params6)


# --- selective disclosure (pok_sig.rs:18-106) -------------------------------


def test_pok_sig_selective_disclosure(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    proof, challenge, revealed = show(
        aggr_sig, aggr_vk, params6, msgs, revealed_msg_indices={3, 5}
    )
    assert revealed == {3: msgs[3], 5: msgs[5]}
    # interactive-style verify with explicit challenge (reference test shape)
    assert show_verify(proof, aggr_vk, params6, revealed, challenge)
    # non-interactive verify recomputing the Fiat-Shamir challenge
    assert show_verify(proof, aggr_vk, params6, revealed)


def test_pok_sig_wrong_revealed_value_fails(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    proof, challenge, revealed = show(
        aggr_sig, aggr_vk, params6, msgs, revealed_msg_indices={3, 5}
    )
    bad = dict(revealed)
    bad[3] = (bad[3] + 1) % (2**255)
    assert not show_verify(proof, aggr_vk, params6, bad, challenge)


def test_pok_sig_reveal_nothing_and_everything(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 0, signers, params6)
    for revealed_set in (set(), set(range(6))):
        proof, challenge, revealed = show(
            aggr_sig, aggr_vk, params6, msgs, revealed_msg_indices=revealed_set
        )
        assert show_verify(proof, aggr_vk, params6, revealed, challenge)


# --- negative tests (rebuild additions; SURVEY.md §4 gaps) ------------------


def test_wrong_message_fails_verify(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    bad_msgs = list(msgs)
    bad_msgs[0] = (bad_msgs[0] + 1) % (2**255)
    assert not aggr_sig.verify(bad_msgs, aggr_vk, params6)


def test_below_threshold_aggregation_fails(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    with pytest.raises(GeneralError):
        Signature.aggregate(THRESHOLD, [(1, aggr_sig)], params6.ctx)


def test_forged_identity_signature_rejected(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs = [rand_fr() for _ in range(6)]
    aggr_vk = Verkey.aggregate(
        THRESHOLD, [(s.id, s.verkey) for s in signers], params6.ctx
    )
    forged = Signature(None, None)
    assert not forged.verify(msgs, aggr_vk, params6)


def test_tampered_request_proof_fails(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs = [rand_fr() for _ in range(6)]
    elg_sk, elg_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    sig_req, randomness = SignatureRequest.new(msgs, 2, elg_pk, params6)
    pok = SignatureRequestPoK.init(sig_req, elg_pk, params6)
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(msgs[:2], randomness, elg_sk, challenge)
    # flip a response in the commitment sub-proof: linkage check must fail
    proof.proof_commitment.responses[0] = (
        proof.proof_commitment.responses[0] + 1
    ) % (2**255)
    assert not proof.verify(sig_req, elg_pk, challenge, params6)
    # wrong challenge also fails
    assert not proof.verify(sig_req, elg_pk, (challenge + 1) % 2**255, params6)


def test_message_count_mismatch_raises(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs = [rand_fr() for _ in range(5)]
    elg_sk, elg_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    with pytest.raises(UnsupportedNoOfMessages):
        SignatureRequest.new(msgs, 2, elg_pk, params6)


def test_batch_verify_mixed(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs1, sig1, vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    msgs2, sig2, _ = run_issuance(THRESHOLD, 6, 2, signers, params6)
    bad_msgs = list(msgs2)
    bad_msgs[1] = (bad_msgs[1] + 1) % (2**255)
    results = batch_verify(
        [sig1, sig2, sig2], [msgs1, msgs2, bad_msgs], vk, params6
    )
    assert results == [True, True, False]


# --- serialization round trips (rebuild additions) --------------------------


def test_params_roundtrip(params6):
    blob = params6.to_bytes()
    assert Params.from_bytes(blob) == params6
    # label-determinism: same label -> identical params (signature.rs:22-31)
    assert Params.new(6, b"test") == params6


def test_signature_and_verkey_roundtrip(params6):
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    ctx = params6.ctx
    sig2 = Signature.from_bytes(aggr_sig.to_bytes(ctx), ctx)
    assert sig2 == aggr_sig
    vk2 = Verkey.from_bytes(aggr_vk.to_bytes(ctx), ctx)
    assert vk2 == aggr_vk
    assert sig2.verify(msgs, vk2, params6)


def test_signature_request_roundtrip(params6):
    msgs = [rand_fr() for _ in range(6)]
    _, elg_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    sig_req, _ = SignatureRequest.new(msgs, 2, elg_pk, params6)
    blob = sig_req.to_bytes(params6.ctx)
    back = SignatureRequest.from_bytes(blob, params6.ctx)
    assert back.known_messages == sig_req.known_messages
    assert back.commitment == sig_req.commitment
    assert back.ciphertexts == sig_req.ciphertexts


# --- G2-signature group assignment (reference feature SignatureG2) ----------


def test_lifecycle_signatures_in_g2():
    params = Params.new(4, b"testG2", ctx=SIGNATURES_IN_G2)
    _, _, signers = trusted_party_SSS_keygen(2, 3, params)
    run_issuance(2, 4, 1, signers, params)


def test_fiat_shamir_binds_statement(params6):
    """Regression: the issuance PoK challenge must bind the full statement
    (request bytes incl. ciphertexts + ElGamal pk). Without this, ciphertext
    sub-proofs are forgeable non-interactively (weak Fiat-Shamir)."""
    msgs = [rand_fr() for _ in range(6)]
    elg_sk, elg_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    sig_req, randomness = SignatureRequest.new(msgs, 2, elg_pk, params6)
    pok = SignatureRequestPoK.init(sig_req, elg_pk, params6)
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(msgs[:2], randomness, elg_sk, challenge)

    # splice a different ciphertext into the request: the recomputed
    # Fiat-Shamir challenge must change, so the old proof cannot be replayed
    tampered = SignatureRequest(
        sig_req.known_messages,
        sig_req.commitment,
        [(sig_req.ciphertexts[0][1], sig_req.ciphertexts[0][0])]
        + sig_req.ciphertexts[1:],
    )
    chal_honest = fiat_shamir_challenge(
        proof.to_bytes_for_challenge(sig_req, elg_pk, params6)
    )
    chal_tampered = fiat_shamir_challenge(
        proof.to_bytes_for_challenge(tampered, elg_pk, params6)
    )
    assert chal_honest == challenge
    assert chal_tampered != challenge
    # and a different ElGamal pk changes the challenge too
    _, other_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    assert (
        fiat_shamir_challenge(
            proof.to_bytes_for_challenge(sig_req, other_pk, params6)
        )
        != challenge
    )


def test_malformed_subproof_shapes_rejected(params6):
    """Regression: truncated ciphertext sub-proofs must be a clean False,
    not an IndexError, in the signer's verification path."""
    from coconut_tpu.pok_vc import Proof

    msgs = [rand_fr() for _ in range(6)]
    elg_sk, elg_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    sig_req, randomness = SignatureRequest.new(msgs, 2, elg_pk, params6)
    pok = SignatureRequestPoK.init(sig_req, elg_pk, params6)
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(msgs[:2], randomness, elg_sk, challenge)
    p1, p2 = proof.proof_ciphertexts[0]
    proof.proof_ciphertexts[0] = (p1, Proof(p2.t, p2.responses[:1]))
    assert not proof.verify(sig_req, elg_pk, challenge, params6)


def test_proof_wire_roundtrips(params6):
    """Both proof structs (user->signer and prover->verifier) have canonical
    wire encodings that verify after a round trip."""
    from coconut_tpu.ps import PoKOfSignatureProof
    from coconut_tpu.signature import SignatureRequestProof

    ctx = params6.ctx
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params6)
    msgs = [rand_fr() for _ in range(6)]
    elg_sk, elg_pk = elgamal_keygen(ctx.sig, params6.g)
    sig_req, randomness = SignatureRequest.new(msgs, 2, elg_pk, params6)
    pok = SignatureRequestPoK.init(sig_req, elg_pk, params6)
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(msgs[:2], randomness, elg_sk, challenge)
    back = SignatureRequestProof.from_bytes(proof.to_bytes(ctx), ctx)
    assert back.verify(sig_req, elg_pk, challenge, params6)

    msgs2, aggr_sig, aggr_vk = run_issuance(THRESHOLD, 6, 2, signers, params6)
    prf, chal, revealed = show(aggr_sig, aggr_vk, params6, msgs2, {3, 5})
    back2 = PoKOfSignatureProof.from_bytes(prf.to_bytes(ctx), ctx)
    assert show_verify(back2, aggr_vk, params6, revealed)


def test_malformed_elgamal_subproof_clean_false(params6):
    """A wrong-arity elgamal-sk sub-proof is a clean False, not an exception."""
    from coconut_tpu.pok_vc import Proof

    msgs = [rand_fr() for _ in range(6)]
    elg_sk, elg_pk = elgamal_keygen(params6.ctx.sig, params6.g)
    sig_req, randomness = SignatureRequest.new(msgs, 2, elg_pk, params6)
    pok = SignatureRequestPoK.init(sig_req, elg_pk, params6)
    challenge = fiat_shamir_challenge(pok.to_bytes())
    proof = pok.gen_proof(msgs[:2], randomness, elg_sk, challenge)
    sk_proof = proof.proof_elgamal_sk
    proof.proof_elgamal_sk = Proof(sk_proof.t, sk_proof.responses * 2)
    assert proof.verify(sig_req, elg_pk, challenge, params6) is False
