"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path and bench.py uses the real chip). Two mechanisms, both
needed:

  - XLA_FLAGS must carry --xla_force_host_platform_device_count=8 before the
    CPU client initializes;
  - the platform must be forced via jax.config *after* import: in this
    environment a sitecustomize hook registers the tunneled TPU ("axon")
    PJRT plugin and pins JAX_PLATFORMS=axon at interpreter start, so the env
    var alone is overridden. config.update wins over both.
"""

import faulthandler
import os

import pytest

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the jax-backend differential tests compile
    # multi-minute XLA programs on the CPU mesh; cache them across runs
    # (one shared definition — see coconut_tpu/tpu/__init__.py)
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import coconut_tpu.tpu

    coconut_tpu.tpu.enable_compile_cache()
except ImportError:  # pragma: no cover - jax is baked into this image
    pass

try:
    from coconut_tpu.analysis import lockcheck as _lockcheck
except ImportError:  # pragma: no cover - analysis rides with the package
    _lockcheck = None


def pytest_configure(config):
    # Hang diagnosis: the driver's tier-1 run is killed at a hard wall
    # (timeout -k 10 870) with no stacks. Dump EVERY thread's traceback
    # shortly before that wall so a wedged run names its culprit (a
    # stuck Condition.wait, a hung dispatch) instead of dying silent.
    # COCONUT_TEST_DUMP_S=0 disables; exit=False — diagnose, don't kill.
    faulthandler.enable()
    try:
        _dump_s = float(os.environ.get("COCONUT_TEST_DUMP_S", "840"))
    except ValueError:
        _dump_s = 840.0
    if _dump_s > 0:
        faulthandler.dump_traceback_later(_dump_s, exit=False)

    # Runtime lock-order tracking (ISSUE 20): COCONUT_LOCK_CHECK=1
    # patches threading.Lock/RLock so every lock allocated by
    # coconut_tpu code records the global acquisition-order graph; the
    # autouse guard below fails any test that recorded an inversion.
    # Opt-in via env so the default tier-1 run is byte-identical.
    if _lockcheck is not None and _lockcheck.env_enabled():
        config._coconut_lock_tracker = _lockcheck.install()

    config.addinivalue_line(
        "markers",
        "heavy: multi-minute at-scale fused-kernel tests, run by ci.sh's "
        "separate heavy-lane process (COCONUT_TEST_HEAVY=1, -m heavy)",
    )
    config.addinivalue_line(
        "markers",
        "faults: fault-supervision suite (retry/fallback/bisection/"
        "checkpoint hardening), also run explicitly by ci.sh's fault lane",
    )
    config.addinivalue_line(
        "markers",
        "pipeline: encode-pipeline suite (verify_stream prefetch worker, "
        "static-operand cache, raw-wire Montgomery parity), also run "
        "explicitly by ci.sh's pipeline lane",
    )
    config.addinivalue_line(
        "markers",
        "serve: online serving layer suite (dynamic batching, deadline "
        "coalescing, admission control, demux/drain invariants, loadgen), "
        "also run explicitly by ci.sh's serve lane",
    )
    config.addinivalue_line(
        "markers",
        "obs: observability suite (request-scoped tracing, Chrome-trace/"
        "Perfetto export, flight recorder, percentile edge cases), also "
        "run explicitly by ci.sh's obs lane",
    )
    config.addinivalue_line(
        "markers",
        "chaos: self-healing pool suite (crash containment, hung-dispatch "
        "watchdog, quarantine/probation ladder, brownout shedding, chaos "
        "schedules), also run explicitly by ci.sh's chaos lane",
    )
    config.addinivalue_line(
        "markers",
        "issue: threshold-issuance suite (quorum fan-out, first-t-of-n "
        "aggregation, straggler hedging, corrupt-partial attribution), "
        "also run explicitly by ci.sh's issue lane",
    )
    config.addinivalue_line(
        "markers",
        "engine: unified execution-engine suite (program registration, "
        "cross-program placement, per-program jit-shape caches, typed "
        "error hierarchy, online/offline show parity, full-session "
        "pipeline), also run explicitly by ci.sh's engine lane",
    )
    config.addinivalue_line(
        "markers",
        "gateway: fleet-gateway suite (wire-format golden vectors, typed "
        "error envelopes, per-tenant admission, health gossip, consistent-"
        "hash routing, replica failover), also run explicitly by ci.sh's "
        "gateway lane",
    )
    config.addinivalue_line(
        "markers",
        "lifecycle: zero-downtime lifecycle suite (shape-manifest warm "
        "boot, WARMING/DRAINING readiness gating, drain-and-handoff, "
        "elastic pool sizing, rolling-restart drill), also run "
        "explicitly by ci.sh's lifecycle lane",
    )
    config.addinivalue_line(
        "markers",
        "keylife: dealerless key-lifecycle suite (online DKG with "
        "complaint attribution, proactive refresh, t/n reshare, epoch "
        "registry window/pinning, epoch-keyed wire + cache behavior, "
        "fake-clock rollover chaos drill), also run explicitly by "
        "ci.sh's keylife lane",
    )
    config.addinivalue_line(
        "markers",
        "batchverify: RLC combined-pairing batch verification suite "
        "(deterministic combiner derivation, pad-lane contract, "
        "adversarial soundness + bisection attribution, engine batched "
        "mode), also run explicitly by ci.sh's batchverify lane",
    )
    config.addinivalue_line(
        "markers",
        "state: durable state plane suite (WAL framing/torn-tail "
        "recovery, snapshot+replay StateStore, crash-point enumeration, "
        "anti-entropy replication, nullifier double-spend detection "
        "with the deterministic kill-the-witness drill), also run "
        "explicitly by ci.sh's state lane",
    )
    config.addinivalue_line(
        "markers",
        "hashmsm: device hash-to-curve + bucketed-MSM suite (SvdW map "
        "parity vs the spec/native oracle including adversarial vectors, "
        "Pippenger bucket schedule bit-parity across window sizes, GLV "
        "on/off, knob/counter routing), also run explicitly by ci.sh's "
        "hashmsm lane",
    )
    config.addinivalue_line(
        "markers",
        "scenarios: application-scenario suite (workflow state-machine "
        "runtime on a fake clock, bit-stable seeded arrival streams, "
        "petition/e-cash/access flows end-to-end over loopback RPC with "
        "typed double-spend rejections), also run explicitly by ci.sh's "
        "scenarios lane",
    )
    config.addinivalue_line(
        "markers",
        "analysis: invariant lint suite (static checkers' seeded-bad "
        "fixtures + clean-tree gate, runtime lock-order tracker, "
        "dead-letter schema validator), also run explicitly by ci.sh's "
        "analysis lane",
    )
    config.addinivalue_line(
        "markers",
        "slow: multi-minute tests (virtual-mesh program tracing/execution) "
        "excluded from the driver's bounded tier-1 run (-m 'not slow'); "
        "ci.sh's full-suite pass still runs them",
    )


def pytest_unconfigure(config):
    faulthandler.cancel_dump_traceback_later()
    tracker = getattr(config, "_coconut_lock_tracker", None)
    if tracker is not None and _lockcheck is not None:
        _lockcheck.uninstall()


@pytest.fixture(autouse=True)
def _lock_order_guard(request):
    """With COCONUT_LOCK_CHECK=1, fail any test during which coconut_tpu
    code acquired locks in an order that inverts a previously observed
    order (the two paths can deadlock under the right interleaving)."""
    tracker = getattr(request.config, "_coconut_lock_tracker", None)
    if tracker is None:
        yield
        return
    tracker.drain_inversions()  # don't blame this test for earlier ones
    yield
    inversions = tracker.drain_inversions()
    assert not inversions, (
        "lock acquisition-order inversion(s) recorded during this test "
        "(COCONUT_LOCK_CHECK): %r" % (inversions,)
    )
