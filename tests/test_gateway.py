"""Fleet-gateway suite (ISSUE 13, marker `gateway`).

Covers the PR-13 contract surface:

  - WIRE GOLDENS: byte-exact round-trips for every program
    request/response payload plus pinned golden vectors (hex for
    crypto-free frames, sha256 for deterministic crypto payloads) —
    CTS-RPC/1 is a compatibility promise, so any byte drift fails here;
  - STRICT DECODE: unknown versions, bad magic, truncated frames,
    trailing bytes, over-cap lengths, and non-canonical fields all
    raise DeserializationError instead of half-parsing;
  - TYPED ERROR ENVELOPES: errors.py's stable `code` map, the
    always-finite retry_after_s invariant, and wire round-trips that
    reconstruct the ORIGINAL exception classes;
  - TENANT ADMISSION: fake-clock token-bucket refill, quota exhaustion,
    auth rejection, and the over-quota-tenant-only isolation property;
  - GOSSIP + ROUTING: UP/DEGRADED/DOWN transitions on beacons and
    misses, consistent-hash session affinity, least-loaded spill off a
    demoted primary, data-path failover onto survivors with zero
    dangling futures, and beacon-driven rejoin;
  - END TO END: a full prepare -> mint -> show session through a real
    engine behind a loopback replica, plus both loadgen drivers in
    transport="rpc" mode reporting rpc_overhead_s.

Real crypto on small parameters only where the payload demands it;
everything routing-related runs on stub engines and fake clocks with
zero real sleeps."""

import hashlib
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics, net
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import (
    WIRE_ERROR_CODES,
    DeserializationError,
    DkgAbortedError,
    DoubleSpendError,
    EpochRetiredError,
    EpochUnknownError,
    GeneralError,
    QuorumUnreachableError,
    ServiceBrownoutError,
    ServiceClosedError,
    ServiceOverloadedError,
    ServiceRetryableError,
    ShareVerificationError,
    TenantAuthError,
    TenantQuotaError,
    TenantRateLimitError,
    TransientBackendError,
    error_from_wire,
)
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.net import gossip, rpc, wire
from coconut_tpu.net.router import ReplicaRouter
from coconut_tpu.net.tenant import TenantTable, TokenBucket
from coconut_tpu.params import Params
from coconut_tpu.retry import RetryPolicy
from coconut_tpu.serve.loadgen import run_loadgen, run_session_loadgen
from coconut_tpu.serve.queue import ServeFuture
from coconut_tpu.signature import Signature
from coconut_tpu.sss import rand_fr

pytestmark = pytest.mark.gateway

MSGS = 3
HIDDEN = 1
REVEALED = [1, 2]
THRESHOLD, TOTAL = 2, 3


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def world():
    params = Params.new(MSGS, b"test-gateway")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    return SimpleNamespace(
        params=params,
        signers=signers,
        backend=get_backend("python"),
        codec=wire.WireCodec(params),
    )


@pytest.fixture(scope="module")
def engine(world):
    eng = ProtocolEngine(
        world.signers,
        world.params,
        THRESHOLD,
        count_hidden=HIDDEN,
        revealed_msg_indices=REVEALED,
        backend=world.backend,
        devices=1,
        max_batch=4,
        max_wait_ms=5.0,
    ).start()
    yield eng
    eng.drain(timeout=60.0)


@pytest.fixture(scope="module")
def session_objects(world, engine):
    """One real full session's crypto artifacts, for codec round-trips:
    (messages, elgamal pk/sk, SignatureRequest, randomness, credential,
    proof, challenge, revealed map)."""
    msgs = [rand_fr() for _ in range(MSGS)]
    esk, epk = elgamal_keygen(world.params.ctx.sig, world.params.g)
    sig_req, randomness = engine.submit_prepare(msgs, epk).result(120.0)
    cred = engine.submit_mint(sig_req, msgs, esk).result(120.0)
    proof, challenge, revealed = engine.submit_show_prove(
        cred, msgs
    ).result(120.0)
    return SimpleNamespace(
        msgs=msgs,
        esk=esk,
        epk=epk,
        sig_req=sig_req,
        randomness=randomness,
        cred=cred,
        proof=proof,
        challenge=challenge,
        revealed=revealed,
    )


# --- satellite: wire-format golden vectors ----------------------------------


def test_frame_header_golden():
    """The 12-byte header layout is a compatibility promise — pinned."""
    frame = wire.encode_frame(0x01, b"abc", seq=7)
    # version byte is 04 since PR 19 (scenario nullifier scope on
    # show_verify requests)
    assert frame.hex() == "c0c704010000000700000003616263"
    msg_type, seq, payload = wire.decode_frame(frame)
    assert (msg_type, seq, payload) == (0x01, 7, b"abc")


def test_error_envelope_golden():
    e = ServiceBrownoutError(
        "bulk", 0.5, depth=3, capacity_fraction=0.25, program="prepare"
    )
    env = wire.encode_error(e)
    assert env.hex() == (
        "000862726f776e6f75740007707265706172653fe00000000000000100"
        "4e736572766963652062726f776e6f757420286361706163697479203235"
        "252c2064657074682033293a2062756c6b206c616e65207368656420"
        "e28094207265747279206166746572207e302e3573"
    )
    d = wire.decode_error(env)
    assert type(d) is ServiceBrownoutError
    assert d.code == "brownout"
    assert d.program == "prepare"
    assert d.retry_after_s == 0.5
    assert d.wire_retryable is True


def test_beacon_golden():
    b = wire.Beacon("r2", "brownout", 0.5, 17, True, 2, 4, 12.25)
    assert wire.encode_beacon(b).hex() == (
        "00027232000862726f776e6f75743fe000000000000000000011"
        "01000000020000000440288000"
        "00000000"
        "0000"  # v2: empty epoch window (no key lifecycle)
        "0000"  # v3: empty state-mark set (no StateStore)
    )
    d = wire.decode_beacon(wire.encode_beacon(b))
    assert d.as_dict() == b.as_dict()
    assert d.admissible()  # brownout is DEGRADED, not unroutable
    assert not wire.Beacon(
        "r2", "quarantined", 0.0, 0, False, 0, 4, 0.0
    ).admissible()


def test_beacon_epoch_window_golden():
    """v2 beacons advertise the live key-epoch window: u16 count +
    (u32 epoch, u8 state) entries, ascending epoch order — pinned."""
    b = wire.Beacon(
        "r2", "healthy", 1.0, 0, False, 1, 1, 0.0,
        epochs=((1, "retiring"), (2, "active")),
    )
    enc = wire.encode_beacon(b)
    assert enc.hex().endswith(
        "0002"  # two live epochs
        "0000000102"  # epoch 1: retiring (code 2)
        "0000000201"  # epoch 2: active (code 1)
        "0000"  # v3: empty state-mark set follows the window
    )
    d = wire.decode_beacon(enc)
    assert d.epochs == ((1, "retiring"), (2, "active"))
    assert d.as_dict() == b.as_dict()
    bad = bytearray(enc)
    # the epoch-state byte now sits 2 bytes before the (empty) v3
    # state-mark count — still must refuse, not misparse
    bad[-3] = 0xEE
    with pytest.raises(DeserializationError, match="epoch state"):
        wire.decode_beacon(bytes(bad))


def test_verify_request_golden_digest():
    """Deterministic params + fixed scalars pin the canonical verify
    request payload byte-for-byte (as a digest)."""
    params = Params.new(3, b"gateway-golden")
    codec = wire.WireCodec(params)
    sig = Signature(params.g, params.g)
    payload = codec.encode_request(
        "verify", (sig, [1, 2, 3]), lane="interactive",
        api_key="k", session="s",
    )
    # +4 over v1: the trailing u32 mint epoch (0 here — unpinned sig)
    assert len(payload) == 301
    assert hashlib.sha256(payload).hexdigest() == (
        "c1f36595386d398c6b73b84d97c5c78a1a7a1a4cb0ba68b26adfc1e7c4e30ba5"
    )
    assert codec.encode_response("verify", True).hex() == "01"
    assert codec.encode_response("verify", False).hex() == "00"


def test_all_request_payloads_roundtrip_byte_exact(world, session_objects):
    """encode -> decode -> re-encode is the identity for EVERY program
    request, and decode hands back the engine's exact submit args."""
    so = session_objects
    codec = world.codec
    cases = {
        "verify": (so.cred, so.msgs),
        "prepare": (so.msgs, so.epk),
        "mint": (so.sig_req, so.msgs, so.esk),
        "show_prove": (so.cred, so.msgs),
        "show_verify": (so.proof, so.revealed, so.challenge),
    }
    for program, args in cases.items():
        payload = codec.encode_request(
            program, args, lane="bulk", api_key="ak", session="sess-9"
        )
        prog, lane, api_key, session, dec_args = codec.decode_request(
            wire.REQUEST_TYPES[program], payload
        )
        assert (prog, lane, api_key, session) == (
            program, "bulk", "ak", "sess-9",
        )
        again = codec.encode_request(
            program, dec_args, lane=lane, api_key=api_key, session=session
        )
        assert again == payload, program


def test_all_response_payloads_roundtrip_byte_exact(world, session_objects):
    so = session_objects
    codec = world.codec
    cases = {
        "verify": True,
        "prepare": (so.sig_req, so.randomness),
        "mint": so.cred,
        "show_prove": (so.proof, so.challenge, so.revealed),
        "show_verify": False,
    }
    for program, result in cases.items():
        payload = codec.encode_response(program, result)
        decoded = codec.decode_response(program, payload)
        again = codec.encode_response(program, decoded)
        assert again == payload, program


def test_show_verify_request_none_challenge(world, session_objects):
    """challenge=None (the stranger-verifier path) survives the wire."""
    so = session_objects
    payload = world.codec.encode_request(
        "show_verify", (so.proof, so.revealed, None)
    )
    _, _, _, _, args = world.codec.decode_request(
        wire.REQUEST_TYPES["show_verify"], payload
    )
    assert args[2] is None


# --- satellite: strict decode rejection -------------------------------------


def test_decode_rejects_unknown_version():
    frame = wire.encode_frame(0x01, b"", version=wire.WIRE_VERSION + 1)
    with pytest.raises(DeserializationError, match="version"):
        wire.parse_header(frame)


def test_decode_rejects_bad_magic():
    frame = bytearray(wire.encode_frame(0x01, b""))
    frame[0] ^= 0xFF
    with pytest.raises(DeserializationError, match="magic"):
        wire.parse_header(bytes(frame))


def test_decode_rejects_truncated_header():
    with pytest.raises(DeserializationError, match="truncated"):
        wire.parse_header(wire.encode_frame(0x01, b"")[:-1][:11])


def test_decode_rejects_length_mismatch():
    frame = wire.encode_frame(0x01, b"abcdef")
    with pytest.raises(DeserializationError, match="mismatch"):
        wire.decode_frame(frame[:-2])
    with pytest.raises(DeserializationError, match="mismatch"):
        wire.decode_frame(frame + b"zz")


def test_decode_rejects_over_cap_length():
    import struct

    header = struct.pack(
        ">HBBII", wire.MAGIC, wire.WIRE_VERSION, 0x01, 0,
        wire.MAX_FRAME_BYTES + 1,
    )
    with pytest.raises(DeserializationError, match="cap"):
        wire.parse_header(header)


def test_decode_rejects_trailing_bytes_in_payloads(world):
    env = wire.encode_error(GeneralError("x"))
    with pytest.raises(DeserializationError, match="trailing"):
        wire.decode_error(env + b"\x00")
    beacon = wire.encode_beacon(
        wire.Beacon("r", "healthy", 1.0, 0, False, 1, 1, 0.0)
    )
    with pytest.raises(DeserializationError, match="trailing"):
        wire.decode_beacon(beacon + b"\x00")
    sig = Signature(world.params.g, world.params.g)
    req = world.codec.encode_request("verify", (sig, [1, 2]))
    with pytest.raises(DeserializationError, match="trailing"):
        world.codec.decode_request(
            wire.REQUEST_TYPES["verify"], req + b"\x00"
        )


def test_decode_rejects_truncated_request(world):
    sig = Signature(world.params.g, world.params.g)
    req = world.codec.encode_request("verify", (sig, [1, 2]))
    with pytest.raises(DeserializationError):
        world.codec.decode_request(wire.REQUEST_TYPES["verify"], req[:-5])


def test_decode_rejects_noncanonical_fr(world):
    from coconut_tpu.ops.fields import R

    sig = Signature(world.params.g, world.params.g)
    req = bytearray(world.codec.encode_request("verify", (sig, [R - 1])))
    req[-32:] = b"\xff" * 32  # >= R: non-canonical scalar
    with pytest.raises(DeserializationError, match="non-canonical"):
        world.codec.decode_request(
            wire.REQUEST_TYPES["verify"], bytes(req)
        )


def test_decode_rejects_duplicate_revealed_index():
    payload = (
        (2).to_bytes(2, "big")
        + (1).to_bytes(4, "big") + (5).to_bytes(32, "big")
        + (1).to_bytes(4, "big") + (6).to_bytes(32, "big")
    )
    with pytest.raises(DeserializationError, match="duplicate"):
        wire._read_revealed(payload, 0)


# --- satellite: typed error codes + wire envelopes --------------------------


def test_error_codes_stable_and_unique():
    expected = {
        GeneralError: "general",
        DeserializationError: "bad_request",
        TransientBackendError: "transient",
        ServiceRetryableError: "retryable",
        ServiceOverloadedError: "overloaded",
        ServiceBrownoutError: "brownout",
        QuorumUnreachableError: "quorum_unreachable",
        ServiceClosedError: "closed",
        TenantAuthError: "tenant_auth",
        TenantQuotaError: "tenant_quota",
        TenantRateLimitError: "tenant_rate_limited",
        # PR 15: key-lifecycle refusals travel the same envelope
        ShareVerificationError: "share_rejected",
        DkgAbortedError: "dkg_aborted",
        EpochUnknownError: "epoch_unknown",
        EpochRetiredError: "epoch_retired",
        # PR 17: the replicated nullifier set's terminal rejection
        DoubleSpendError: "double_spend",
    }
    for cls, code in expected.items():
        assert cls.code == code
        assert WIRE_ERROR_CODES[code] is cls
    assert len(WIRE_ERROR_CODES) == len(expected)


def test_retry_after_always_finite():
    """The wire invariant: retry_after_s is a finite float >= 0, never
    None — whatever hint the constructor was handed."""
    for hint, want in (
        (None, 0.0),
        (-1.0, 0.0),
        (float("nan"), 0.0),
        (float("inf"), 0.0),
        (0.0, 0.0),
        (0.25, 0.25),
        (3, 3.0),
    ):
        err = ServiceOverloadedError(1, 1, retry_after_s=hint)
        assert isinstance(err.retry_after_s, float)
        assert err.retry_after_s == want


def test_error_from_wire_reconstructs_classes():
    originals = [
        ServiceOverloadedError(4, 4, program="verify", retry_after_s=0.1),
        ServiceBrownoutError("bulk", 0.7, program="prepare"),
        QuorumUnreachableError(3, 1, live=1, program="mint"),
        TenantRateLimitError("acme", 0.5, program="verify"),
        TenantAuthError("unknown API key"),
        TenantQuotaError("acme", 10, 10),
        ServiceClosedError("drained"),
        TransientBackendError("hiccup"),
        DeserializationError("garbage"),
        GeneralError("boom"),
        # PR 15: key-lifecycle refusals
        ShareVerificationError(
            "dealer 2 share for recipient 4 failed Pedersen check",
            dealer_id=2, round="dkg",
        ),
        DkgAbortedError(3, 2, excluded=(1,), program="mint",
                        retry_after_s=0.5),
        EpochUnknownError(9, live=(1, 2)),
        EpochRetiredError(1, live=(2, 3)),
    ]
    for orig in originals:
        decoded = wire.decode_error(wire.encode_error(orig))
        assert type(decoded) is type(orig), orig
        assert decoded.code == orig.code
        assert str(decoded) == str(orig)
        if isinstance(orig, ServiceRetryableError):
            assert decoded.retry_after_s == orig.retry_after_s
            assert decoded.program == orig.program
            assert decoded.wire_retryable


def test_error_from_wire_unknown_code_degrades():
    err = error_from_wire("flux_capacitor", "future error", program="verify")
    assert type(err) is GeneralError
    assert err.code == "flux_capacitor"  # preserved on the instance
    assert GeneralError.code == "general"  # class untouched


# --- satellite: per-tenant admission (fake clock) ---------------------------


def test_token_bucket_refill_horizon():
    clock = FakeClock()
    bucket = TokenBucket(rate_per_s=2.0, burst=2, clock=clock)
    assert bucket.take() == 0.0
    assert bucket.take() == 0.0
    wait = bucket.take()  # empty: 1 token at 2/s -> 0.5s horizon
    assert wait == pytest.approx(0.5)
    clock.advance(0.25)
    assert bucket.take() == pytest.approx(0.25)  # partial refill
    clock.advance(0.25)
    assert bucket.take() == 0.0  # one token back
    clock.advance(100.0)
    assert bucket.take() == 0.0
    assert bucket.take() == 0.0
    assert bucket.take() > 0.0  # capped at burst, not 200 tokens


def test_tenant_admission_gates():
    metrics.reset()
    clock = FakeClock()
    table = TenantTable(clock=clock)
    table.provision("acme", "key-a", rate_per_s=1.0, burst=2, quota=3)
    table.provision("bob", "key-b")  # unmetered

    with pytest.raises(TenantAuthError):
        table.admit("key-zzz")
    assert metrics.get_count("gateway_auth_failures") == 1

    assert table.admit("key-a").tenant_id == "acme"
    assert table.admit("key-a").tenant_id == "acme"
    with pytest.raises(TenantRateLimitError) as exc:
        table.admit("key-a", program="verify")
    assert exc.value.retry_after_s == pytest.approx(1.0)
    assert exc.value.program == "verify"
    assert exc.value.tenant == "acme"
    # the throttled tenant does NOT touch its neighbors
    assert table.admit("key-b").tenant_id == "bob"

    clock.advance(2.0)
    assert table.admit("key-a").used == 3
    clock.advance(10.0)
    with pytest.raises(TenantQuotaError) as exc:  # quota, not bucket
        table.admit("key-a")
    assert (exc.value.used, exc.value.quota) == (3, 3)

    assert metrics.get_count("gateway_tenant_acme_admitted") == 3
    assert metrics.get_count("gateway_tenant_acme_throttled") == 1
    assert metrics.get_count("gateway_tenant_acme_quota_rejected") == 1
    assert metrics.get_count("gateway_tenant_bob_admitted") == 1
    assert metrics.get_count("gateway_tenant_bob_throttled") == 0


def test_duplicate_api_key_rejected():
    table = TenantTable()
    table.provision("a", "same-key")
    with pytest.raises(ValueError, match="duplicate"):
        table.provision("b", "same-key")


# --- satellite: health gossip -----------------------------------------------


def _beacon(rid, state="healthy", depth=0, brownout=False):
    return wire.Beacon(rid, state, 1.0, depth, brownout, 1, 1, 0.0)


def test_directory_transitions():
    metrics.reset()
    d = gossip.HealthDirectory(["r0", "r1"], miss_threshold=2)
    # PR 14: fresh registrations start WARMING, not optimistic-UP — a
    # replica that has never beaconed must not receive traffic
    assert d.states() == {"r0": gossip.WARMING, "r1": gossip.WARMING}
    assert metrics.get_gauge("gateway_up_replicas") == 0
    assert not d.routable("r0") and not d.usable("r0")

    d.observe(_beacon("r0", state="quarantined"))
    assert d.state("r0") == gossip.DEGRADED
    assert not d.routable("r0")
    assert d.usable("r0")
    # WARMING -> DEGRADED is the first beacon landing, not a demotion
    assert metrics.get_count("gateway_demoted") == 0
    assert metrics.get_count("gateway_warmed") == 1

    d.observe(_beacon("r0", brownout=True))
    assert d.state("r0") == gossip.DEGRADED  # browned-out stays demoted

    d.observe(_beacon("r0"))
    assert d.state("r0") == gossip.UP
    assert metrics.get_count("gateway_readmitted") == 1

    d.miss("r1")
    assert d.state("r1") == gossip.WARMING  # below threshold
    d.miss("r1")
    assert d.state("r1") == gossip.DOWN
    assert not d.usable("r1")
    assert metrics.get_gauge("gateway_up_replicas") == 1

    # a fresh admissible beacon readmits a DOWN replica instantly
    d.observe(_beacon("r1", depth=5))
    assert d.state("r1") == gossip.UP
    assert d.queue_depth("r1") == 5
    assert d.queue_depth("rX") == float("inf")

    # lifecycle self-reports pin the view: draining/warming beacons
    # take the replica out of BOTH the routable and spill pools
    d.observe(_beacon("r0", state="draining"))
    assert d.state("r0") == gossip.DRAINING
    assert not d.routable("r0") and not d.usable("r0")
    assert metrics.get_count("gateway_drain_observed") == 1
    d.observe(_beacon("r0", state="warming"))
    assert d.state("r0") == gossip.WARMING
    assert not d.routable("r0") and not d.usable("r0")
    d.observe(_beacon("r0"))
    assert d.state("r0") == gossip.UP


def test_note_draining_soft_demotes():
    metrics.reset()
    d = gossip.HealthDirectory(["r0", "r1"], miss_threshold=3)
    d.observe(_beacon("r0"))
    assert d.state("r0") == gossip.UP
    d.note_draining("r0")
    assert d.state("r0") == gossip.DRAINING
    assert not d.routable("r0") and not d.usable("r0")
    # softer than note_failure: no DOWN, and a fresh healthy beacon
    # (the restarted successor) brings it straight back
    d.observe(_beacon("r0"))
    assert d.state("r0") == gossip.UP
    # note_draining on a DOWN replica must not resurrect it
    d.note_failure("r1")
    d.note_draining("r1")
    assert d.state("r1") == gossip.DOWN


def test_note_failure_is_immediate():
    d = gossip.HealthDirectory(["r0"], miss_threshold=3)
    d.note_failure("r0")
    assert d.state("r0") == gossip.DOWN


def test_gossip_loop_step():
    d = gossip.HealthDirectory(["r0", "r1"], miss_threshold=1)
    beacons = {"r0": _beacon("r0")}

    def poll(rid):
        def _p():
            if rid not in beacons:
                raise ConnectionError("dead")
            return beacons[rid]

        return _p

    loop = gossip.GossipLoop(
        d, {r: poll(r) for r in ("r0", "r1")}, clock=FakeClock()
    )
    loop.step()
    assert d.state("r0") == gossip.UP
    assert d.state("r1") == gossip.DOWN  # miss_threshold=1
    beacons["r1"] = _beacon("r1")
    loop.step()
    assert d.state("r1") == gossip.UP


# --- tentpole: router affinity / spill / failover ---------------------------


class StubEngine:
    """Inline-resolving verify-only engine: deterministic futures, a
    settable queue depth, and a per-replica call count."""

    def __init__(self, verdict=True):
        self.verdict = verdict
        self.calls = 0
        self.depth_value = 0

    def depth(self):
        return self.depth_value

    def submit_verify(self, sig, messages, lane="interactive",
                      max_wait_ms=None):
        self.calls += 1
        fut = ServeFuture()
        fut.set_result(self.verdict)
        return fut


def _stub_fleet(world, n=3, tenants=None):
    """n stub replicas behind loopback transports + a router over them."""
    replicas, transports, clients = {}, {}, {}
    for i in range(n):
        rid = "r%d" % i
        rep = rpc.Replica(
            StubEngine(), world.codec, tenants=tenants, replica_id=rid
        )
        t = rpc.LoopbackTransport(rep)
        replicas[rid] = rep
        transports[rid] = t
        clients[rid] = rpc.GatewayClient(
            t, world.codec, api_key="key-a"
        )
    router = ReplicaRouter(
        clients,
        retry_policy=RetryPolicy(
            max_attempts=n + 1,
            base_delay=0.0,
            jitter=0.0,
            retryable=(TransientBackendError,),
            sleep=lambda s: None,
        ),
    )
    return router, replicas, transports


def _sig(world):
    return Signature(world.params.g, world.params.g)


def test_session_affinity_and_spread(world):
    router, replicas, _ = _stub_fleet(world)
    sig = _sig(world)
    # same session -> same replica, every time
    for session in ("alpha", "beta", "gamma"):
        primary = router.candidates(session)[0]
        for _ in range(5):
            fut = router.submit_verify(sig, [1], session=session)
            assert fut.replica_id == primary
            assert fut.result(5.0) is True
    # many sessions -> more than one replica does work
    for i in range(48):
        router.submit_verify(sig, [1], session="s%d" % i).result(5.0)
    busy = [rid for rid, rep in replicas.items() if rep.engine.calls > 0]
    assert len(busy) >= 2, "consistent hash degenerated onto one replica"


def test_demoted_primary_spills_least_loaded(world):
    metrics.reset()
    router, replicas, _ = _stub_fleet(world)
    session = "sticky"
    ring = router.candidates(session)
    primary, others = ring[0], ring[1:]
    # beacons: primary quarantined, others healthy with distinct depths
    router.directory.observe(_beacon(primary, state="quarantined"))
    router.directory.observe(_beacon(others[0], depth=7))
    router.directory.observe(_beacon(others[1], depth=2))
    chosen = router.route(session)
    assert chosen == others[1]  # least-loaded routable
    assert metrics.get_count("gateway_spills") == 1
    assert metrics.get_count("gateway_affinity_hits") == 0
    # primary readmits -> affinity returns
    router.directory.observe(_beacon(primary))
    assert router.route(session) == primary
    assert metrics.get_count("gateway_affinity_hits") == 1


def test_failover_settles_on_survivor(world):
    metrics.reset()
    router, replicas, transports = _stub_fleet(world)
    sig = _sig(world)
    session = "doomed"
    primary = router.candidates(session)[0]
    transports[primary].kill()
    fut = router.submit_verify(sig, [1], session=session)
    assert fut.result(5.0) is True  # settled via retry on a survivor
    assert fut.replica_id != primary
    assert router.directory.state(primary) == gossip.DOWN
    assert metrics.get_count("gateway_failovers") >= 1


def test_all_replicas_down_raises_typed(world):
    router, _, transports = _stub_fleet(world)
    for t in transports.values():
        t.kill()
    fut = router.submit_verify(_sig(world), [1], session="x")
    with pytest.raises(TransientBackendError):
        fut.result(5.0)


def test_fleet_chaos_zero_dangling_futures(world):
    """Mixed traffic across 3 replicas while one is killed mid-run:
    every future settles (verdict or typed error), the dead replica is
    demoted, and it rejoins via a fresh beacon after revival."""
    router, replicas, transports = _stub_fleet(world)
    loop = router.gossip_loop(clock=FakeClock())
    sig = _sig(world)
    victim = router.candidates("sess-0")[0]

    futures = []
    for i in range(60):
        if i == 20:
            transports[victim].kill()
        futures.append(
            router.submit_verify(sig, [1], session="sess-%d" % (i % 7))
        )
    settled = 0
    for fut in futures:
        try:
            assert fut.result(5.0) is True
        except TransientBackendError:
            pass  # typed, loud — but never dangling
        settled += 1
    assert settled == len(futures)
    loop.step()
    assert router.directory.state(victim) == gossip.DOWN

    transports[victim].revive()
    loop.step()  # fresh healthy beacon readmits
    assert router.directory.state(victim) == gossip.UP
    before = replicas[victim].engine.calls
    for _ in range(5):
        router.submit_verify(sig, [1], session="sess-0").result(5.0)
    assert replicas[victim].engine.calls > before  # traffic returned


def test_tenant_rate_limit_over_the_wire(world):
    """A throttled tenant's refusal crosses the wire as a typed
    retry-after response; other tenants on the SAME replica sail on."""
    clock = FakeClock()
    tenants = TenantTable(clock=clock)
    tenants.provision("slow", "key-slow", rate_per_s=1.0, burst=1)
    tenants.provision("fast", "key-fast")
    rep = rpc.Replica(StubEngine(), world.codec, tenants=tenants)
    t = rpc.LoopbackTransport(rep)
    slow = rpc.GatewayClient(t, world.codec, api_key="key-slow")
    fast = rpc.GatewayClient(t, world.codec, api_key="key-fast")
    sig = _sig(world)

    assert slow.submit_verify(sig, [1]).result(5.0) is True
    with pytest.raises(TenantRateLimitError) as exc:
        slow.submit_verify(sig, [1]).result(5.0)
    assert exc.value.retry_after_s == pytest.approx(1.0)
    for _ in range(5):
        assert fast.submit_verify(sig, [1]).result(5.0) is True
    clock.advance(1.5)
    assert slow.submit_verify(sig, [1]).result(5.0) is True


def test_unknown_program_and_garbage_frames(world):
    rep = rpc.Replica(StubEngine(), world.codec, replica_id="rg")
    # unknown message type -> typed bad_request envelope, not a hang
    resp = rep.handle_frame(wire.encode_frame(0x3F, b"", seq=9))
    msg_type, seq, payload = wire.decode_frame(resp)
    assert (msg_type, seq) == (wire.MSG_ERROR, 9)
    assert type(wire.decode_error(payload)) is DeserializationError
    # undecodable frame -> error envelope with seq 0
    resp = rep.handle_frame(b"\x00" * wire.HEADER_BYTES)
    msg_type, seq, payload = wire.decode_frame(resp)
    assert (msg_type, seq) == (wire.MSG_ERROR, 0)


# --- end to end: real crypto through a loopback replica ---------------------


def test_full_session_over_loopback_rpc(world, engine):
    tenants = TenantTable()
    tenants.provision("acme", "key-acme")
    rep = rpc.Replica(engine, world.codec, tenants=tenants, replica_id="r0")
    client = rpc.GatewayClient(
        rpc.LoopbackTransport(rep), world.codec,
        api_key="key-acme", session="e2e",
    )
    beacon = client.poll_beacon()
    assert beacon.state == "healthy"
    assert beacon.replica_id == "r0"
    assert beacon.executors == 1

    msgs = [rand_fr() for _ in range(MSGS)]
    esk, epk = elgamal_keygen(world.params.ctx.sig, world.params.g)
    sig_req, _rand = client.submit_prepare(msgs, epk).result(120.0)
    cred = client.submit_mint(sig_req, msgs, esk).result(120.0)
    assert client.submit_verify(cred, msgs).result(120.0) is True
    proof, challenge, revealed = client.submit_show_prove(
        cred, msgs
    ).result(120.0)
    assert client.submit_show_verify(
        proof, revealed, challenge
    ).result(120.0) is True
    # a forged credential still verdicts False (not an error) over RPC
    forged = Signature(world.params.g, world.params.g)
    assert client.submit_verify(forged, msgs).result(120.0) is False


def test_loadgen_rpc_transport(world, engine, session_objects):
    so = session_objects
    rep = rpc.Replica(engine, world.codec, replica_id="lg")
    client = rpc.GatewayClient(
        rpc.LoopbackTransport(rep), world.codec
    )
    report = run_loadgen(
        client,
        [(so.cred, so.msgs, True)],
        duration_s=0.4,
        concurrency=2,
        transport="rpc",
    )
    assert report["transport"] == "rpc"
    assert report["completed"] > 0
    assert report["errors"] == 0
    assert report["dropped_futures"] == 0
    assert report["verdict_mismatches"] == 0
    assert report["rpc_overhead_s"] is not None
    assert report["rpc_overhead_s"] >= 0.0


def test_session_loadgen_rpc_transport(world, engine):
    rep = rpc.Replica(engine, world.codec, replica_id="slg")
    client = rpc.GatewayClient(
        rpc.LoopbackTransport(rep), world.codec
    )
    esk, epk = elgamal_keygen(world.params.ctx.sig, world.params.g)
    pool = [([rand_fr() for _ in range(MSGS)], epk, esk)]
    report = run_session_loadgen(
        client, pool, duration_s=0.5, concurrency=2, transport="rpc"
    )
    assert report["transport"] == "rpc"
    assert report["sessions_completed"] > 0
    assert report["errors"] == 0
    assert report["failed_shows"] == 0
    assert report["rpc_overhead_s"] is not None
