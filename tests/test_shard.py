"""In-suite multi-chip tests for the sharded programs (VERDICT r3 items 1-2).

Runs in the DEFAULT suite on the 8-device virtual CPU mesh (conftest.py) —
multi-chip correctness of `coconut_tpu.tpu.shard` no longer rests on the
driver's dryrun probe alone. The reference's test strategy simulates all
parties in one process (/root/reference/src/keygen.rs:126-165); the
framework's analogue is simulating all chips on one host.

Shapes here are EXACTLY `__graft_entry__.dryrun_multichip(8)`'s — batch=4
(one lane per dp slice) on the (dp=4, tp=2) mesh for the per-credential
program, batch=8 (one lane per device) on the (dp=8, tp=1) mesh for the
grouped program — so a default pytest (or ci.sh) run also seeds the
persistent compile cache (.jax_cache) with the very programs the driver's
dryrun compiles: after any suite run the dryrun skips its cold compiles
(the round-3 MULTICHIP timeout failure mode).
"""

import random

import pytest

jax = pytest.importorskip("jax")

import __graft_entry__ as ge  # noqa: E402
from coconut_tpu.ps import ps_verify  # noqa: E402
from coconut_tpu.signature import Signature  # noqa: E402


@pytest.fixture(scope="module")
def mesh_devices():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest.py)")
    return devices[:8]


@pytest.fixture(scope="module")
def fixture8():
    # Same shape as the dryrun's fixture (batch = n_devices); different
    # seed — cache keys on the program, not the data.
    return ge._fixture(batch=8, seed=0x51A2D)


def test_sharded_percred_verify_accept_and_reject(mesh_devices, fixture8):
    """dp+tp sharded per-credential verify: bits match the spec path,
    including a forged credential in the batch (batch=4, one lane per dp
    slice — the dryrun's phase-1 shape)."""
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import batch_verify_sharded, default_mesh

    params, _, vk, sigs, msgs_list = fixture8
    sigs, msgs_list = list(sigs[:4]), msgs_list[:4]
    sigs[1] = Signature(
        sigs[1].sigma_1, params.ctx.sig.mul(sigs[1].sigma_2, 2)
    )
    mesh = default_mesh(ndp=4, ntp=2, devices=mesh_devices)
    bits = batch_verify_sharded(
        JaxBackend(), sigs, msgs_list, vk, params, mesh
    )
    want = [ps_verify(s, m, vk, params) for s, m in zip(sigs, msgs_list)]
    assert want == [True, False, True, True]
    assert bits == want


def test_sharded_grouped_verify_accept(mesh_devices, fixture8):
    """dp-sharded grouped (headline) verify accepts a valid batch
    (batch=8 on the (8,1) mesh — the dryrun's phase-2 shape)."""
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import (
        batch_verify_grouped_sharded,
        default_mesh,
    )

    params, _, vk, sigs, msgs_list = fixture8
    gmesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    ok = batch_verify_grouped_sharded(
        JaxBackend(), sigs, msgs_list, vk, params, gmesh, pad_batch_to=8
    )
    assert ok is True


def test_sharded_grouped_verify_rejects_forgery(mesh_devices, fixture8):
    """One tampered credential anywhere in the batch flips the grouped
    whole-batch boolean (2^-128 soundness check on the sharded path)."""
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import (
        batch_verify_grouped_sharded,
        default_mesh,
    )

    params, _, vk, sigs, msgs_list = fixture8
    rng = random.Random(7)
    forged = list(sigs)
    i = rng.randrange(len(forged))
    forged[i] = Signature(
        forged[i].sigma_1, params.ctx.sig.mul(forged[i].sigma_2, 3)
    )
    gmesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    bad = batch_verify_grouped_sharded(
        JaxBackend(), forged, msgs_list, vk, params, gmesh, pad_batch_to=8
    )
    assert bad is False


def test_sharded_show_verify(mesh_devices, fixture8):
    """dp-sharded batched selective-disclosure verify (config 3 on a mesh):
    bits match the single-chip fused path and the sequential spec, with one
    tampered proof in the batch."""
    from coconut_tpu.pok_sig import batch_show, show_verify
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import (
        batch_show_verify_sharded,
        default_mesh,
    )

    params, _, vk, sigs, msgs_list = fixture8
    be = JaxBackend()
    proofs, chals, rmls = batch_show(
        sigs, vk, params, msgs_list, {2, 3}, backend=be
    )
    # tamper one proof's response vector -> its Schnorr check must fail
    from coconut_tpu.ops.fields import R

    proofs[5].proof_vc.responses[0] = (
        proofs[5].proof_vc.responses[0] + 1
    ) % R
    mesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    bits = batch_show_verify_sharded(
        be, proofs, vk, params, rmls, chals, mesh
    )
    want = [
        show_verify(p, vk, params, rm, c)
        for p, rm, c in zip(proofs, rmls, chals)
    ]
    assert want == [True] * 5 + [False] + [True] * 2
    assert bits == want


def test_sharded_grouped_stream(mesh_devices, fixture8, tmp_path):
    """verify_stream on a mesh (config 5 multi-chip): grouped mode with the
    batch dp-sharded, honest batch accounting, checkpoint intact."""
    from coconut_tpu.stream import verify_stream
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import default_mesh

    params, _, vk, sigs, msgs_list = fixture8
    be = JaxBackend()
    mesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    forged = list(sigs)
    forged[3] = Signature(
        forged[3].sigma_1, params.ctx.sig.mul(forged[3].sigma_2, 2)
    )

    def source(i):
        return (sigs, msgs_list) if i != 1 else (forged, msgs_list)

    state = verify_stream(
        source,
        3,
        vk,
        params,
        be,
        state_path=str(tmp_path / "stream.json"),
        mode="grouped",
        mesh=mesh,
    )
    assert state.batches_ok == 2 and state.batches_failed == 1
    assert state.verified == 16 and state.failed == 8
    assert state.next_batch == 3


def test_sharded_issuance(mesh_devices, fixture8):
    """Config 4 on a mesh: batch_prepare_blind_sign + batch_blind_sign +
    batch_unblind run with every issuance-shape MSM program dp-sharded
    (ShardedIssuanceBackend), bit-identical to the spec per-request path
    (BlindSignature.new is deterministic given a request) and yielding
    credentials that verify (reference signature.rs:124-207, 380-443)."""
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.signature import (
        BlindSignature,
        batch_blind_sign,
        batch_prepare_blind_sign,
        batch_unblind,
    )
    from coconut_tpu.tpu.shard import ShardedIssuanceBackend, default_mesh

    params, sk, vk, _, msgs_list = fixture8
    mesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    be = ShardedIssuanceBackend(mesh)
    esk, epk = elgamal_keygen(params.ctx.sig, params.g)
    out = batch_prepare_blind_sign(msgs_list, 2, epk, params, backend=be)
    reqs = [r for r, _ in out]
    blinded = batch_blind_sign(reqs, sk, params, backend=be)
    for req, bs in zip(reqs, blinded):
        want = BlindSignature.new(req, sk, params)
        assert (bs.h, bs.blinded) == (want.h, want.blinded)
    unblinded = batch_unblind(blinded, esk, params.ctx, backend=be)
    for sig, msgs in zip(unblinded, msgs_list):
        assert ps_verify(sig, msgs, vk, params)


def test_sharded_percred_stream(mesh_devices, fixture8, tmp_path):
    """verify_stream(mode='per_credential') on a mesh: per-credential
    verdict bits at ledger scale, dp+tp sharded (the r4 restriction to
    grouped-only mesh streaming is lifted). Reuses the (4,2)-mesh percred
    program test_sharded_percred_verify compiles."""
    from coconut_tpu.stream import verify_stream
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import default_mesh

    params, _, vk, sigs, msgs_list = fixture8
    sigs, msgs_list = list(sigs[:4]), msgs_list[:4]
    forged = list(sigs)
    forged[2] = Signature(
        forged[2].sigma_1, params.ctx.sig.mul(forged[2].sigma_2, 2)
    )
    mesh = default_mesh(ndp=4, ntp=2, devices=mesh_devices)

    def source(i):
        return (sigs, msgs_list) if i != 1 else (forged, msgs_list)

    state = verify_stream(
        source,
        3,
        vk,
        params,
        JaxBackend(),
        state_path=str(tmp_path / "pc.json"),
        mode="per_credential",
        mesh=mesh,
    )
    assert state.verified == 11 and state.failed == 1
    assert state.next_batch == 3


def test_sharded_percred_ragged_batch_pads_with_identity_lanes(
    mesh_devices, fixture8
):
    """A final batch NOT divisible by ndp pads with identity lanes
    (shard.PAD_LANE, sigma_1 is None) up to a multiple of ndp and slices
    the verdict bits back to len(sigs) — the ragged tail of a ledger
    stream verifies on the mesh instead of raising, and a pad lane can
    never flip a real lane's verdict. B=3 on the (4,2) mesh pads to 4:
    the exact program shape the tests above already compile."""
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import batch_verify_sharded_async, default_mesh

    params, _, vk, sigs, msgs_list = fixture8
    sigs, msgs_list = list(sigs[:3]), msgs_list[:3]
    sigs[1] = Signature(
        sigs[1].sigma_1, params.ctx.sig.mul(sigs[1].sigma_2, 2)
    )
    mesh = default_mesh(ndp=4, ntp=2, devices=mesh_devices)
    bits = batch_verify_sharded_async(
        JaxBackend(), sigs, msgs_list, vk, params, mesh
    )()
    want = [ps_verify(s, m, vk, params) for s, m in zip(sigs, msgs_list)]
    assert want == [True, False, True]
    assert bits == want
    assert len(bits) == 3


def test_sharded_issuance_rejects_indivisible_batch(mesh_devices, fixture8):
    """ShardedIssuanceBackend fails fast (before any device work) when a
    row count does not divide the dp extent."""
    from coconut_tpu.elgamal import elgamal_keygen
    from coconut_tpu.signature import batch_prepare_blind_sign
    from coconut_tpu.tpu.shard import ShardedIssuanceBackend, default_mesh

    params, _, _, _, msgs_list = fixture8
    mesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    be = ShardedIssuanceBackend(mesh)
    _, epk = elgamal_keygen(params.ctx.sig, params.g)
    with pytest.raises(ValueError, match="not divisible"):
        batch_prepare_blind_sign(msgs_list[:3], 2, epk, params, backend=be)


def test_mesh_stream_mode_and_backend_validation(mesh_devices):
    """verify_stream(mesh=...) rejects unknown modes and backends without
    the encode surface the chosen mode needs — with the mode's own
    attribute named in the error (stream.py capability probe)."""
    from coconut_tpu.backend import get_backend
    from coconut_tpu.stream import _dispatchers
    from coconut_tpu.tpu.shard import default_mesh

    mesh = default_mesh(ndp=8, ntp=1, devices=mesh_devices)
    py = get_backend("python")
    with pytest.raises(ValueError, match="grouped.*per_credential|per_credential"):
        _dispatchers(py, "combined", mesh=mesh)
    with pytest.raises(ValueError, match="encode_verify_batch"):
        _dispatchers(py, "per_credential", mesh=mesh)
    with pytest.raises(ValueError, match="encode_grouped_batch"):
        _dispatchers(py, "grouped", mesh=mesh)
