"""Invariant lint suite tests (ISSUE 20).

Two halves, mirroring the suite's contract:

  - NON-VACUITY: each checker fires on a seeded-bad fixture tree (an
    ABBA lock pair, an un-wired exception raise, a secret-tainted
    branch, a bare durable write, an undocumented counter) — proving
    the pass that runs clean on the real tree actually looks;
  - CLEAN TREE: one cached ``run_all`` over the repo itself must report
    zero NEW findings against the committed baseline — the same gate
    ci.sh's analysis lane enforces with ``--fail-on-new``.

Plus the runtime half (analysis/lockcheck.py): the patched-factory
tracker must catch a real ABBA interleaving, survive Condition wait /
notify and interpreter thread bootstrap (the current_thread() recursion
regression), and uninstall cleanly. And the structured dead-letter
schema validator that replaced ci.sh's grep chain.

Everything here is host-only AST/threading work — no device, no jit —
so the file stays cheap even though it sorts first in tier-1.
"""

import json
import os
import textwrap
import threading
import time

import pytest

from coconut_tpu import errors
from coconut_tpu.analysis import core, lockcheck, run_all, schema
from coconut_tpu.analysis import (
    consttime,
    durability,
    lockorder,
    metricsdoc,
    wirecontract,
)
from coconut_tpu.analysis.__main__ import main as analysis_main

pytestmark = pytest.mark.analysis

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_tree(tmp_path, files):
    """Materialize {relpath: source} under tmp_path; returns the root."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


# -- lock-order (static) ----------------------------------------------------


LOCK_ABBA = """
    import threading

    class Pool:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
    """


def test_lockorder_fires_on_abba(tmp_path):
    root = make_tree(tmp_path, {"coconut_tpu/pool.py": LOCK_ABBA})
    findings = lockorder.run(core.Context(root))
    assert len(findings) == 1
    f = findings[0]
    assert f.checker == "lock-order" and f.rule == "cycle"
    assert "_a" in f.message and "_b" in f.message
    assert "fwd" in f.message and "rev" in f.message


def test_lockorder_clean_on_consistent_order(tmp_path):
    consistent = LOCK_ABBA.replace(
        "with self._b:\n                with self._a:",
        "with self._a:\n                with self._b:",
    )
    root = make_tree(tmp_path, {"coconut_tpu/pool.py": consistent})
    assert lockorder.run(core.Context(root)) == []


def test_lockorder_real_tree_graph_is_acyclic():
    ctx = core.Context(REPO_ROOT)
    edges, attr_owners, _mods = lockorder.build_graph(ctx)
    # the tree defines real locks; the pass must SEE them (non-vacuous)
    assert len(attr_owners) >= 5
    assert lockorder.run(ctx) == []


# -- wire-contract ----------------------------------------------------------


RAISES_UNWIRED = """
    from . import errors

    def handler(n):
        if n > 2:
            raise errors.UnsupportedNoOfMessages(
                "valid for 2 messages but given %d" % n
            )
    """


def test_wirecontract_fires_on_unwired_raise(tmp_path, monkeypatch):
    # simulate the pre-fix tree: the class exists but its code was never
    # registered in WIRE_ERROR_CODES
    monkeypatch.delitem(errors.WIRE_ERROR_CODES, "unsupported_messages")
    root = make_tree(tmp_path, {"coconut_tpu/rpcmod.py": RAISES_UNWIRED})
    findings = wirecontract.check_raised_classes(core.Context(root))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "missing-code"
    assert "UnsupportedNoOfMessages" in f.message


def test_wirecontract_skips_non_rpc_paths(tmp_path, monkeypatch):
    monkeypatch.delitem(errors.WIRE_ERROR_CODES, "unsupported_messages")
    root = make_tree(
        tmp_path, {"coconut_tpu/serve/loadgen.py": RAISES_UNWIRED}
    )
    assert wirecontract.check_raised_classes(core.Context(root)) == []


def test_wirecontract_round_trip_clean_on_real_module():
    # every registered code decodes as its class, preserves the message,
    # survives repr() (class-level defaults), and normalizes junk
    # retry_after_s — the executable half of the contract
    assert wirecontract.check_round_trip(core.Context(REPO_ROOT)) == []


# -- const-time -------------------------------------------------------------


SECRET_BRANCH = """
    def poly_eval(coeffs, x):
        if len(coeffs) == 0:   # len() sanitizes: sizes are public
            return 0
        acc = 0
        for c in coeffs:
            if c:              # secret-branch: c is tainted via coeffs
                acc += int(c)  # secret-cast: big-int cost leaks bits
        return acc
    """


def test_consttime_fires_on_tainted_branch_and_cast(tmp_path):
    root = make_tree(tmp_path, {"coconut_tpu/sss.py": SECRET_BRANCH})
    findings = consttime.run(core.Context(root))
    rules = sorted(f.rule for f in findings)
    assert rules == ["secret-branch", "secret-cast"]
    assert all("poly_eval" in f.message for f in findings)
    # the len() guard on line 2 must NOT be among the flagged lines
    assert all(f.line != 2 for f in findings)


def test_consttime_secret_call_results_are_tainted(tmp_path):
    src = """
    def blind(params):
        r = rand_fr(params)
        if r:
            return 1
        return 0
    """
    root = make_tree(tmp_path, {"coconut_tpu/signature.py": src})
    findings = consttime.run(core.Context(root))
    assert [f.rule for f in findings] == ["secret-branch"]


def test_consttime_out_of_scope_files_are_ignored(tmp_path):
    root = make_tree(tmp_path, {"coconut_tpu/serve/queue.py": SECRET_BRANCH})
    assert consttime.run(core.Context(root)) == []


# -- durability -------------------------------------------------------------


BARE_WRITE = """
    import json

    def save(path, doc):
        with open(path, "w") as f:
            json.dump(doc, f)

    def save_logged(path, doc):
        # lint: allow(durability, test fixture: append-only artifact)
        with open(path, "a") as f:
            json.dump(doc, f)

    def load(path):
        with open(path) as f:
            return json.load(f)
    """


def test_durability_fires_on_bare_write_and_respects_pragma(tmp_path):
    root = make_tree(tmp_path, {"coconut_tpu/store.py": BARE_WRITE})
    ctx = core.Context(root)
    findings = durability.run(ctx)
    # both write-mode opens are findings; the read-mode open is not
    assert len(findings) == 2
    assert all(f.rule == "bare-write" for f in findings)
    new = core.apply_suppressions(findings, ctx, {})
    # the pragma'd append is suppressed; the bare "w" open is NEW
    assert len(new) == 1
    assert new[0].line == min(f.line for f in findings)
    assert "open(path" in new[0].message


def test_durability_blessed_modules_exempt(tmp_path):
    root = make_tree(tmp_path, {"coconut_tpu/state/atomic.py": BARE_WRITE})
    assert durability.run(core.Context(root)) == []


# -- metrics-doc ------------------------------------------------------------


METRICS_FIXTURE = {
    "coconut_tpu/mod.py": """
    from . import metrics

    def work(i):
        metrics.count("zz_alive_total")
        metrics.count("zz_rogue_counter")
        metrics.count("zz_dev%d_load" % i)
    """,
    "README.md": """
    # fixture

    Metric glossary: counters `zz_alive_total`, `zz_dev<d>_load` and
    `zz_gone_counter`.
    """,
}


def test_metricsdoc_fires_both_directions(tmp_path):
    root = make_tree(tmp_path, METRICS_FIXTURE)
    findings = metricsdoc.run(core.Context(root))
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # zz_rogue_counter emitted but undocumented
    assert len(by_rule.get("undocumented", [])) == 1
    assert "zz_rogue_counter" in by_rule["undocumented"][0].message
    # zz_gone_counter documented but never emitted (family zz IS emitted)
    assert len(by_rule.get("stale", [])) == 1
    assert "zz_gone_counter" in by_rule["stale"][0].message


def test_metricsdoc_wildcard_matches_placeholder():
    norm = metricsdoc._normalize_doc_token("serve_dev<d>_busy_s")
    assert metricsdoc.patterns_match("serve_dev*_busy_s", norm)
    assert metricsdoc.patterns_match("serve_dev*_busy_s", "serve_dev3_busy_s")
    assert not metricsdoc.patterns_match("serve_dev*_busy_s", "serve_depth")


# -- fingerprints / pragmas / runner ---------------------------------------


def test_fingerprint_ignores_line_numbers():
    a = core.Finding("durability", "bare-write", "coconut_tpu/x.py", 10,
                     "msg", key="bare-write:open:path")
    b = core.Finding("durability", "bare-write", "coconut_tpu/x.py", 99,
                     "other msg", key="bare-write:open:path")
    assert a.fingerprint == b.fingerprint


def test_pragma_reason_may_wrap(tmp_path):
    src = """
    def f(path):
        # lint: allow(durability, a long justification that wraps onto
        # the following comment line and keeps wrapping a little more)
        with open(path, "w") as f:
            f.write("x")
    """
    root = make_tree(tmp_path, {"coconut_tpu/m.py": src})
    ctx = core.Context(root)
    findings = durability.run(ctx)
    assert len(findings) == 1
    assert core.apply_suppressions(findings, ctx, {}) == []
    assert findings[0].suppressed_by == "pragma"


def test_cli_gate_and_write_baseline(tmp_path, capsys):
    root = make_tree(
        tmp_path,
        {
            "coconut_tpu/store.py": """
            def save(path, doc):
                with open(path, "w") as f:
                    f.write(doc)
            """
        },
    )
    baseline = str(tmp_path / "baseline.json")
    args = ["--root", root, "--baseline", baseline,
            "--checkers", "durability"]
    assert analysis_main(args + ["--fail-on-new"]) == 1
    assert analysis_main(args + ["--write-baseline"]) == 0
    with open(baseline) as f:
        doc = json.load(f)
    assert len(doc["suppressions"]) == 1
    # baselined finding no longer fails the gate
    assert analysis_main(args + ["--fail-on-new"]) == 0
    capsys.readouterr()


@pytest.fixture(scope="module")
def repo_run():
    baseline = os.path.join(REPO_ROOT, core.DEFAULT_BASELINE)
    return run_all(REPO_ROOT, baseline_path=baseline)


def test_clean_tree_zero_new_findings(repo_run):
    findings, new = repo_run
    assert new == [], "NEW findings (fix or justify with a pragma):\n%s" % (
        "\n".join(repr(f) for f in new)
    )


def test_remaining_suppressions_are_pragmas_with_reasons(repo_run):
    findings, _new = repo_run
    # the shipped baseline is empty: every accepted exception lives as an
    # inline pragma next to the code it excuses
    with open(os.path.join(REPO_ROOT, core.DEFAULT_BASELINE)) as f:
        doc = json.load(f)
    assert doc["suppressions"] == []
    assert all(f.suppressed_by == "pragma" for f in findings
               if f.suppressed_by is not None)


# -- runtime lock-order tracker --------------------------------------------


@pytest.fixture
def tracked(request):
    """A track-all tracker patched in for this test only — saving and
    restoring any session tracker a COCONUT_LOCK_CHECK=1 run installed."""
    prior = lockcheck._installed
    if prior is not None:
        lockcheck.uninstall()
    tracker = lockcheck.install(track_all=True)
    try:
        yield tracker
    finally:
        lockcheck.uninstall()
        if prior is not None:
            request.config._coconut_lock_tracker = lockcheck.install(
                track_all=prior.track_all
            )


def test_lockcheck_detects_abba_inversion(tracked):
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    inv = tracked.drain_inversions()
    assert len(inv) == 1
    assert inv[0]["held"] != inv[0]["acquiring"]
    assert "->" in inv[0]["prior_edge"]


def test_lockcheck_condition_and_thread_bootstrap(tracked):
    # regression: current_thread() inside note_acquire used to recurse
    # infinitely when thread bootstrap touched a tracked Condition lock
    cond = threading.Condition()
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        cond.notify()
    t.join(5)
    assert hits == [1] and not t.is_alive()
    assert tracked.drain_inversions() == []


def test_lockcheck_rlock_reentry_is_not_an_edge(tracked):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert tracked.edges == {}
    assert tracked.drain_inversions() == []


def test_lockcheck_consistent_order_records_no_inversion(tracked):
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        with a:
            with b:
                pass
    assert tracked.drain_inversions() == []
    assert len(tracked.edges) == 1


def test_lockcheck_uninstall_restores_factories(request):
    prior = lockcheck._installed
    if prior is not None:
        lockcheck.uninstall()
    lockcheck.install(track_all=True)
    lockcheck.uninstall()
    assert threading.Lock is lockcheck._ORIG_LOCK
    assert threading.RLock is lockcheck._ORIG_RLOCK
    if prior is not None:
        request.config._coconut_lock_tracker = lockcheck.install(
            track_all=prior.track_all
        )


# -- dead-letter schema validator ------------------------------------------


def _rec(**kw):
    rec = {
        "schema": 4,
        "batch": 1,
        "credential": 2,
        "reason": "forged",
        "attempts": [{"attempt": 1}],
        "trace_id": None,
        "span_id": None,
        "program": "verify",
        "nullifier": None,
    }
    rec.update(kw)
    return rec


def test_schema_valid_record():
    assert schema.validate_record(_rec()) == []


@pytest.mark.parametrize(
    "mutation, needle",
    [
        ({"schema": 3}, "schema"),
        ({"batch": "one"}, "type"),
        ({"batch": True}, "type"),  # bool is not an index
        ({"reason": None}, "null"),
        ({"credential": -1}, "negative"),
        ({"surprise": 1}, "unexpected"),
    ],
)
def test_schema_catches_bad_records(mutation, needle):
    problems = schema.validate_record(_rec(**mutation))
    assert problems and any(needle in p for p in problems)


def test_schema_missing_key():
    rec = _rec()
    del rec["nullifier"]
    problems = schema.validate_record(rec)
    assert any("missing key 'nullifier'" in p for p in problems)


def test_schema_file_torn_line_and_expectations(tmp_path):
    p = tmp_path / "dead.jsonl"
    p.write_text(
        json.dumps(_rec())
        + "\n"
        + json.dumps(_rec(batch=2, credential=0))
        + "\n"
        + '{"schema": 4, "ba'  # torn tail: crash mid-append
    )
    records, problems = schema.validate_file(str(p), [("batch", 1)])
    assert len(records) == 2
    assert any("unparseable" in x for x in problems)
    _records, problems = schema.validate_file(str(p), [("batch", 99)])
    assert any("no record with" in x for x in problems)


def test_schema_cli_gate(tmp_path, capsys):
    p = tmp_path / "dead.jsonl"
    p.write_text(json.dumps(_rec()) + "\n")
    assert schema.main([str(p), "--expect", "batch=1",
                        "--expect", "credential=2"]) == 0
    assert schema.main([str(p), "--expect", "batch=7"]) == 1
    capsys.readouterr()
