"""Online serving layer suite (ISSUE 4): dynamic batching, deadline
coalescing, admission control, demux, drain/shutdown, and the loadgen.

Economics mirror tests/test_faults.py: everything runs on stub backends
(SimpleNamespace credentials carrying their own verdict) with injected
clocks — deadline logic is proven by ADVANCING a fake clock, never by
sleeping in an assert. The only real waiting is millisecond-scale
drain/flush latency inside the service's own machinery."""

import threading
import time
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics
from coconut_tpu.errors import (
    ServiceClosedError,
    ServiceOverloadedError,
    TransientBackendError,
)
from coconut_tpu.faults import DeadLetterLog, FaultyBackend
from coconut_tpu.retry import RetryPolicy
from coconut_tpu.serve import CredentialService, RequestQueue, run_loadgen
from coconut_tpu.serve.batcher import Batcher, pad_batch
from coconut_tpu.serve.queue import ServeFuture

pytestmark = pytest.mark.serve


# --- stub world ------------------------------------------------------------


def _cred(ok=True):
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)


def _lane_bit(s):
    """Stub verdict for one lane: its own ok flag, identity lanes False —
    the same identity-lane semantics every real backend has."""
    return s.sigma_1 is not None and bool(getattr(s, "ok", False))


class StubPerCred:
    """Per-credential stub; records every dispatched batch size so the
    cache-hot-shape (padding) invariant is assertable."""

    def __init__(self):
        self.batch_sizes = []

    def batch_verify(self, sigs, msgs, vk, params):
        self.batch_sizes.append(len(sigs))
        return [_lane_bit(s) for s in sigs]


class StubGrouped:
    def batch_verify_grouped(self, sigs, msgs, vk, params):
        return all(_lane_bit(s) for s in sigs)


class GatedPerCred(StubPerCred):
    """Blocks inside verify until released — holds the supervisor busy so
    admission-control tests can fill the queue deterministically."""

    def __init__(self):
        super().__init__()
        self.entered = threading.Event()
        self.release = threading.Event()

    def batch_verify(self, sigs, msgs, vk, params):
        self.entered.set()
        assert self.release.wait(10.0), "gate never released"
        return super().batch_verify(sigs, msgs, vk, params)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _policy(**kw):
    kw.setdefault("max_attempts", 3)
    kw.setdefault("base_delay", 0.0)
    return RetryPolicy(**kw)


def _service(backend, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 2.0)
    return CredentialService(backend, None, None, **kw)


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# --- futures ---------------------------------------------------------------


def test_future_single_assignment_first_wins():
    f = ServeFuture()
    assert not f.done()
    f.set_result(True)
    f.set_result(False)  # ignored
    f.set_exception(RuntimeError("late"))  # ignored
    assert f.done() and f.result(0) is True and f.exception(0) is None


def test_future_exception_and_timeout():
    f = ServeFuture()
    with pytest.raises(TimeoutError):
        f.result(timeout=0.001)
    f.set_exception(RuntimeError("boom"))
    assert isinstance(f.exception(0), RuntimeError)
    with pytest.raises(RuntimeError):
        f.result(0)


# --- queue: admission control + priority lanes -----------------------------


def test_admission_control_rejects_loudly_at_capacity():
    q = RequestQueue(max_depth=2, clock=FakeClock())
    q.submit(_cred(), [0])
    q.submit(_cred(), [0])
    with pytest.raises(ServiceOverloadedError) as ei:
        q.submit(_cred(), [0])
    assert ei.value.depth == 2 and ei.value.max_depth == 2
    assert metrics.get_count("serve_rejected") == 1
    assert metrics.get_count("serve_admitted") == 2
    assert q.depth() == 2  # the rejected request never entered


def test_submit_after_close_raises_typed():
    q = RequestQueue(max_depth=4, clock=FakeClock())
    q.close()
    with pytest.raises(ServiceClosedError):
        q.submit(_cred(), [0])


def test_interactive_lane_pops_before_bulk():
    clock = FakeClock()
    q = RequestQueue(max_depth=8, clock=clock)
    b = Batcher(q, max_batch=3, clock=clock)
    c_bulk = [_cred() for _ in range(2)]
    c_int = [_cred() for _ in range(2)]
    q.submit(c_bulk[0], [0], lane="bulk")
    q.submit(c_bulk[1], [1], lane="bulk")
    q.submit(c_int[0], [2], lane="interactive")
    q.submit(c_int[1], [3], lane="interactive")
    batch = b.next_batch(block=False)  # full: 4 queued >= max_batch 3
    assert [r.sig for r in batch] == [c_int[0], c_int[1], c_bulk[0]]
    assert [r.messages for r in batch] == [[2], [3], [0]]


def test_unknown_lane_rejected():
    q = RequestQueue(max_depth=4, clock=FakeClock())
    with pytest.raises(ValueError):
        q.submit(_cred(), [0], lane="vip")


# --- batcher: flush policy (fake clock, zero sleeps) ------------------------


def test_full_batch_flushes_immediately_before_any_deadline():
    clock = FakeClock()
    q = RequestQueue(max_depth=8, clock=clock)
    b = Batcher(q, max_batch=2, clock=clock)
    q.submit(_cred(), [0], max_wait_ms=10_000)
    q.submit(_cred(), [0], max_wait_ms=10_000)
    batch = b.next_batch(block=False)
    assert batch is not None and len(batch) == 2


def test_deadline_flush_fires_when_oldest_deadline_expires():
    clock = FakeClock()
    q = RequestQueue(max_depth=8, clock=clock)
    b = Batcher(q, max_batch=4, clock=clock)
    q.submit(_cred(), [0], max_wait_ms=50)  # oldest: deadline t=0.050
    clock.advance(0.010)
    q.submit(_cred(), [0], max_wait_ms=500)
    assert b.next_batch(block=False) is None  # nothing expired yet
    clock.advance(0.039)  # t=0.049 < 0.050
    assert b.next_batch(block=False) is None
    clock.advance(0.002)  # t=0.051: oldest deadline expired
    batch = b.next_batch(block=False)
    assert batch is not None and len(batch) == 2  # partial flush takes all
    assert metrics.get_count("serve_batches") == 1
    assert metrics.get_count("serve_batched_requests") == 2


def test_blocking_deadline_flush_fires_within_tolerance():
    # real clock, one ~10 ms coalescing window: the wait must not return
    # EARLY (deadline honored) and must fire well within tolerance
    q = RequestQueue(max_depth=8)
    b = Batcher(q, max_batch=4)
    q.submit(_cred(), [0], max_wait_ms=10)
    t0 = time.monotonic()
    batch = b.next_batch(block=True)
    dt = time.monotonic() - t0
    assert batch is not None and len(batch) == 1
    assert 0.005 <= dt < 2.0, dt


def test_closed_queue_flushes_remainder_then_signals_exit():
    clock = FakeClock()
    q = RequestQueue(max_depth=8, clock=clock)
    b = Batcher(q, max_batch=4, clock=clock)
    q.submit(_cred(), [0], max_wait_ms=10_000)
    q.close()
    batch = b.next_batch(block=True)  # no deadline wait: close flushes
    assert batch is not None and len(batch) == 1
    assert b.next_batch(block=True) is None  # closed + empty: exit signal


def test_pad_batch_identity_lanes_to_cache_hot_shape():
    clock = FakeClock()
    q = RequestQueue(max_depth=8, clock=clock)
    q.submit(_cred(), [7, 8], max_wait_ms=0)
    q.submit(_cred(), [9, 10], max_wait_ms=0)
    batch = Batcher(q, max_batch=8, clock=clock).next_batch(block=False)
    sigs, messages_list, n_pad = pad_batch(batch, 8)
    assert len(sigs) == len(messages_list) == 8 and n_pad == 6
    assert all(s.sigma_1 is None and s.sigma_2 is None for s in sigs[2:])
    # pad rows reuse a real message vector, so per-lane shape is unchanged
    assert all(m == [7, 8] for m in messages_list[2:])
    assert metrics.get_count("serve_pad_lanes") == 6


# --- service: end-to-end demux, padding, lifecycle --------------------------


def test_service_demux_per_credential_exactly_forged_future_fails():
    be = StubPerCred()
    with _service(be) as svc:
        futs = [
            svc.submit(_cred(ok=(i != 2)), [i]) for i in range(6)
        ]
    verdicts = [f.result(5.0) for f in futs]
    assert verdicts == [True, True, False, True, True, True]
    assert metrics.get_count("serve_valid") == 5
    assert metrics.get_count("serve_invalid") == 1
    snap = metrics.snapshot()["histograms"]["serve_latency_s"]
    assert snap["count"] == 6 and snap["p99_s"] is not None


def test_service_pads_partial_batches_to_constant_shape():
    be = StubPerCred()
    with _service(be, max_batch=8) as svc:
        futs = [svc.submit(_cred(), [0]) for _ in range(3)]
    assert [f.result(5.0) for f in futs] == [True] * 3
    # every dispatched program saw the SAME shape: jit stays cache-hot
    assert be.batch_sizes and set(be.batch_sizes) == {8}
    assert metrics.get_count("serve_pad_lanes") >= 5


def test_service_drain_resolves_every_inflight_future():
    be = GatedPerCred()
    svc = _service(be, max_batch=4, max_depth=64).start()
    futs = [svc.submit(_cred(), [i]) for i in range(11)]
    assert be.entered.wait(5.0)
    be.release.set()
    assert svc.drain(timeout=10.0)
    assert all(f.done() for f in futs)
    assert [f.result(0) for f in futs] == [True] * 11


def test_service_admission_control_live_then_recovers():
    # long deadline so the gated pair flushes as ONE full batch and the
    # backlog sits untouched while the supervisor is held at the gate
    be = GatedPerCred()
    svc = _service(be, max_batch=2, max_depth=3, max_wait_ms=5_000.0).start()
    first = [svc.submit(_cred(), [i]) for i in range(2)]
    assert be.entered.wait(5.0)  # supervisor holds these two in flight
    backlog = [svc.submit(_cred(), [i]) for i in range(3)]
    with pytest.raises(ServiceOverloadedError):
        svc.submit(_cred(), [99])
    assert metrics.get_count("serve_rejected") == 1
    be.release.set()
    assert svc.drain(timeout=10.0)
    assert [f.result(0) for f in first + backlog] == [True] * 5


def test_service_shutdown_without_drain_fails_queued_typed():
    be = GatedPerCred()
    svc = _service(be, max_batch=2, max_depth=64, max_wait_ms=5_000.0).start()
    inflight = [svc.submit(_cred(), [i]) for i in range(2)]
    assert be.entered.wait(5.0)
    queued = [svc.submit(_cred(), [i]) for i in range(3)]

    # release the gate only after shutdown() has swept the backlog (the
    # supervisor is held inside the in-flight batch until then), so the
    # queued futures deterministically cancel instead of completing
    def _release_when_swept():
        while svc.depth() > 0:
            time.sleep(0.001)
        be.release.set()

    releaser = threading.Thread(target=_release_when_swept)
    releaser.start()
    assert svc.shutdown(drain=False, timeout=10.0)
    releaser.join(5.0)
    assert [f.result(5.0) for f in inflight] == [True, True]
    for f in queued:
        assert isinstance(f.exception(5.0), ServiceClosedError)
    assert metrics.get_count("serve_cancelled") == 3
    with pytest.raises(ServiceClosedError):
        svc.submit(_cred(), [0])


def test_service_batch_failure_fails_only_that_batchs_futures():
    # permanent (non-retryable) fault on the FIRST dispatch only: its
    # cohabitants resolve exceptionally, the next batch is unaffected
    be = FaultyBackend(StubPerCred(), raise_on={0}, error=RuntimeError)
    svc = _service(be, max_batch=2).start()
    bad = [svc.submit(_cred(), [i]) for i in range(2)]
    for f in bad:
        assert isinstance(f.exception(5.0), RuntimeError)
    good = [svc.submit(_cred(), [i]) for i in range(2)]
    svc.drain(timeout=10.0)
    assert [f.result(0) for f in good] == [True, True]
    assert metrics.get_count("serve_failed_requests") == 2


def test_service_retry_ladder_recovers_transient_dispatch_fault():
    be = FaultyBackend(StubPerCred(), raise_on={0})
    with _service(be, retry_policy=_policy()) as svc:
        futs = [svc.submit(_cred(), [i]) for i in range(2)]
    assert [f.result(5.0) for f in futs] == [True, True]
    assert metrics.get_count("retries") >= 1


def test_service_falls_back_after_retries_exhaust():
    be = FaultyBackend(StubPerCred(), raise_every=1)  # primary always dies
    with _service(
        be,
        retry_policy=_policy(max_attempts=2),
        fallback_backend=StubPerCred(),
    ) as svc:
        futs = [svc.submit(_cred(ok=(i != 1)), [i]) for i in range(3)]
    assert [f.result(5.0) for f in futs] == [True, False, True]
    assert metrics.get_count("fallbacks") >= 1


# --- the demux invariant (ISSUE satellite): grouped + bisection -------------


def test_grouped_demux_invariant_one_forged_one_dead_letter(tmp_path):
    dlq = str(tmp_path / "serve_dead.jsonl")
    be = StubGrouped()
    svc = _service(
        be, mode="grouped", dead_letter_path=dlq, retry_policy=_policy()
    ).start()
    futs = [svc.submit(_cred(ok=(i != 2)), [i]) for i in range(4)]
    assert svc.drain(timeout=10.0)
    # exactly the forged request's future resolves invalid...
    assert [f.result(0) for f in futs] == [True, True, False, True]
    # ...and exactly it is dead-lettered, keyed by batch seq + lane index
    records = DeadLetterLog.read(dlq)
    assert len(records) == 1
    assert records[0]["batch"] == 0 and records[0]["credential"] == 2
    assert metrics.get_count("dead_letters") == 1
    assert metrics.get_count("bisections") >= 1


def test_grouped_demux_invariant_across_transient_retry_ladder(tmp_path):
    # the coalesced batch's FIRST dispatch raises transiently, and so does
    # the first bisection probe: the retry ladder rides through both and
    # the demux invariant still holds exactly
    dlq = str(tmp_path / "serve_dead_retry.jsonl")
    be = FaultyBackend(StubGrouped(), raise_on={0, 2})
    svc = _service(
        be,
        mode="grouped",
        dead_letter_path=dlq,
        retry_policy=_policy(max_attempts=3),
    ).start()
    futs = [svc.submit(_cred(ok=(i != 1)), [i]) for i in range(4)]
    assert svc.drain(timeout=10.0)
    assert [f.result(0) for f in futs] == [True, False, True, True]
    records = DeadLetterLog.read(dlq)
    assert len(records) == 1
    assert records[0]["batch"] == 0 and records[0]["credential"] == 1
    # the batch's transient dispatch fault is in the attempt history
    assert records[0]["attempts"] and records[0]["attempts"][0]["error"] == (
        "TransientBackendError"
    )
    assert metrics.get_count("retries") >= 1


def test_grouped_all_valid_no_bisection_no_dead_letters(tmp_path):
    dlq = str(tmp_path / "serve_dead_clean.jsonl")
    be = StubGrouped()
    with _service(be, mode="grouped", dead_letter_path=dlq) as svc:
        futs = [svc.submit(_cred(), [i]) for i in range(5)]
    assert all(f.result(5.0) for f in futs)
    assert DeadLetterLog.read(dlq) == []
    assert metrics.get_count("bisections") == 0


# --- metrics satellites -----------------------------------------------------


def test_histogram_percentiles_and_bounded_window():
    for ms in range(1, 101):
        metrics.observe("lat", ms / 1000.0)
    h = metrics.snapshot()["histograms"]["lat"]
    assert h["count"] == 100
    assert h["p50_s"] == pytest.approx(0.050)
    assert h["p95_s"] == pytest.approx(0.095)
    assert h["p99_s"] == pytest.approx(0.099)
    assert h["max_s"] == pytest.approx(0.100)
    assert h["mean_s"] == pytest.approx(0.0505)
    # bounded: a long run retains a window but exact count/max
    for _ in range(2 * metrics.HIST_WINDOW):
        metrics.observe("lat", 0.001)
    h = metrics.snapshot()["histograms"]["lat"]
    assert h["count"] == 100 + 2 * metrics.HIST_WINDOW
    assert h["max_s"] == pytest.approx(0.100)  # exact over the full run
    assert h["p99_s"] == pytest.approx(0.001)  # window: recent behavior


def test_metrics_mutations_are_thread_safe():
    n_threads, n_iter = 8, 2000

    def work():
        for _ in range(n_iter):
            metrics.count("ts_smoke")
            metrics.observe("ts_hist", 0.001)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.get_count("ts_smoke") == n_threads * n_iter
    assert (
        metrics.snapshot()["histograms"]["ts_hist"]["count"]
        == n_threads * n_iter
    )


# --- faults satellite: deterministic latency injection ----------------------


def test_faultybackend_latency_injection_is_deterministic():
    slept = []
    inner = StubPerCred()
    be = FaultyBackend(
        inner, delay_every=2, delay_on={4}, delay_s=1.5, sleep=slept.append
    )
    for _ in range(6):
        be.batch_verify([_cred()], [[0]], None, None)
    # delay_every=2 hits indices 1,3,5; delay_on adds 4 — never 0 or 2
    assert slept == [1.5, 1.5, 1.5, 1.5]
    assert inner.batch_sizes == [1] * 6  # delays never drop dispatches
    # same schedule, fresh wrapper: bitwise-identical injection
    slept2 = []
    be2 = FaultyBackend(
        inner, delay_every=2, delay_on={4}, delay_s=1.5, sleep=slept2.append
    )
    for _ in range(6):
        be2.batch_verify([_cred()], [[0]], None, None)
    assert slept2 == slept


def test_faultybackend_delay_then_fault_compose():
    slept = []
    be = FaultyBackend(
        StubPerCred(),
        raise_on={1},
        delay_on={0, 1},
        delay_s=0.25,
        sleep=slept.append,
    )
    be.batch_verify([_cred()], [[0]], None, None)  # idx 0: slow, succeeds
    with pytest.raises(TransientBackendError):
        be.batch_verify([_cred()], [[0]], None, None)  # idx 1: fails fast
    # the dispatch-time fault preempts the sleep (a dead device does not
    # also get slower): only the first dispatch slept
    assert slept == [0.25]


# --- loadgen ----------------------------------------------------------------


def test_loadgen_closed_loop_zero_dropped_and_sane_report():
    be = StubPerCred()
    svc = _service(be, max_batch=4, max_depth=256).start()
    pool = [(_cred(), [0], True), (_cred(ok=False), [1], False)]
    report = run_loadgen(
        svc, pool, duration_s=0.25, arrival="closed", concurrency=4
    )
    assert svc.drain(timeout=10.0)
    assert report["dropped_futures"] == 0
    assert report["errors"] == 0
    assert report["verdict_mismatches"] == 0
    assert report["completed"] > 0
    assert report["completed"] == report["valid"] + report["invalid"]
    assert report["latency_s"]["p99"] is not None
    assert report["latency_s"]["p50"] <= report["latency_s"]["p99"]
    assert report["goodput_per_s"] > 0
    assert report["mean_batch_occupancy"] is not None
    assert 0.0 < report["mean_batch_occupancy"] <= 1.0


def test_loadgen_open_loop_poisson_arrivals():
    be = StubPerCred()
    svc = _service(be, max_batch=4, max_depth=256).start()
    pool = [(_cred(), [0], True)]
    report = run_loadgen(
        svc,
        pool,
        duration_s=0.15,
        arrival="open",
        rate_per_s=400.0,
    )
    assert svc.drain(timeout=10.0)
    assert report["dropped_futures"] == 0 and report["errors"] == 0
    assert report["submitted"] > 0
    assert report["rejection_rate"] in (0.0, None) or (
        0.0 <= report["rejection_rate"] <= 1.0
    )


def test_loadgen_reports_rejections_under_overload():
    # tiny admission bound + gated backend: the closed loop must observe
    # typed rejections, count them, and still drop zero futures
    be = GatedPerCred()
    svc = _service(be, max_batch=2, max_depth=2, max_wait_ms=0.0).start()
    pool = [(_cred(), [0], True)]
    t = threading.Timer(0.15, be.release.set)
    t.start()
    report = run_loadgen(
        svc, pool, duration_s=0.1, arrival="closed", concurrency=6
    )
    assert svc.drain(timeout=10.0)
    t.cancel()
    assert report["rejected"] > 0
    assert report["rejection_rate"] > 0
    assert report["dropped_futures"] == 0


# --- the dispatcher pool (ISSUE 8): placement, routing, scaling -------------


def _requests(n, lane="interactive", ok=True, clock=None):
    """Build n bare queue.Request objects (no queue, no service) for
    driving _route/_place directly — zero threads, zero sleeps."""
    from coconut_tpu.serve.queue import Request

    t = clock() if clock is not None else 0.0
    return [Request(_cred(ok=ok), [i], lane, 2.0, t) for i in range(n)]


def test_placement_least_loaded_picks_min_load_executor():
    clock = FakeClock()
    svc = _service(StubPerCred(), devices=3, clock=clock)
    ex0, ex1, ex2 = svc._executors
    ex0._load, ex1._load, ex2._load = 5, 1, 3
    assert svc._place(_requests(2, clock=clock)) is ex1
    # ties break by index (deterministic placement)
    ex1._load = 5
    ex2._load = 5
    assert svc._place(_requests(2, clock=clock)) is ex0
    assert metrics.get_count("serve_placed_single") == 2
    assert metrics.get_count("serve_placed_sharded") == 0


def test_placement_capacity_bound_skips_full_executor():
    clock = FakeClock()
    svc = _service(StubPerCred(), devices=2, clock=clock)
    ex0, ex1 = svc._executors
    # sync dispatch => one unsettled batch per executor; ex0 is full
    ex0._batches_out = 1
    ex1._load = 100  # heavier, but the only one with capacity
    assert not ex0.can_accept() and ex1.can_accept()
    assert svc._place(_requests(2, clock=clock)) is ex1
    # both full: the ready() gate would hold the backlog in the queue
    ex1._batches_out = 1
    assert not svc._has_capacity()


def test_adaptive_route_sharded_vs_single():
    from coconut_tpu.serve.service import _DeviceExecutor

    clock = FakeClock()
    svc = _service(StubPerCred(), devices=2, max_batch=4, clock=clock)
    mesh_ex = _DeviceExecutor(
        svc, 99, label="mesh", dispatch=None, is_async=True,
        placement="sharded",
    )
    svc._mesh_executor = mesh_ex
    bulk4 = _requests(4, lane="bulk", clock=clock)
    # full bulk batch -> the mesh
    assert svc._route(bulk4) == "sharded"
    assert svc._place(bulk4) is mesh_ex
    # below sharded_min_lanes (defaults to max_batch) -> single device
    assert svc._route(bulk4[:3]) == "single"
    # ANY interactive request keeps the batch off the collective path
    mixed = bulk4[:3] + _requests(1, lane="interactive", clock=clock)
    assert svc._route(mixed) == "single"
    assert metrics.get_count("serve_placed_sharded") == 1


def test_adaptive_placement_spills_when_preferred_lane_is_full():
    from coconut_tpu.serve.service import _DeviceExecutor

    clock = FakeClock()
    svc = _service(StubPerCred(), devices=2, max_batch=4, clock=clock)
    mesh_ex = _DeviceExecutor(
        svc, 99, label="mesh", dispatch=None, is_async=True,
        placement="sharded",
    )
    svc._mesh_executor = mesh_ex
    mesh_ex._batches_out = 2  # async capacity bound reached
    bulk4 = _requests(4, lane="bulk", clock=clock)
    chosen = svc._place(bulk4)
    assert chosen in svc._executors  # spilled to a single device
    assert metrics.get_count("serve_placed_sharded") == 1
    assert metrics.get_count("serve_placed_spill") == 1
    # and the reverse spill: singles full, mesh free -> mesh takes it
    for ex in svc._executors:
        ex._batches_out = 1
    mesh_ex._batches_out = 0
    small = _requests(2, lane="bulk", clock=clock)
    assert svc._place(small) is mesh_ex
    assert metrics.get_count("serve_placed_spill") == 2


def test_pool_fault_containment_dead_letters_only_one_devices_culprit(
    tmp_path,
):
    """A fault + forgery on device 0's batch bisects and dead-letters ONLY
    its culprit; device 1's concurrently dispatched batch resolves all-True
    — per-batch containment is per-device containment."""
    dlq = str(tmp_path / "pool_dead.jsonl")
    be = FaultyBackend(StubGrouped(), raise_on={0})
    from coconut_tpu.obs import trace as otrace

    otrace.enable(ring=256)
    try:
        svc = _service(
            be,
            mode="grouped",
            max_batch=2,
            devices=2,
            dead_letter_path=dlq,
            retry_policy=_policy(max_attempts=3),
        )
        # submit BEFORE start so coalescing is deterministic: batch A =
        # requests 0-1 (forged at lane 1) -> device 0; batch B = requests
        # 2-3 (all valid) -> device 1 (least-loaded, and device 0 is at
        # capacity). Batch SEQ numbers are assigned launch-side on the
        # executor threads, so which batch is seq 0 is a scheduling race
        # — the culprit is pinned via its request's trace_id instead.
        futs = [svc.submit(_cred(ok=(i != 1)), [i]) for i in range(4)]
        svc.start()
        assert svc.drain(timeout=10.0)
    finally:
        otrace.disable()
    assert [f.result(0) for f in futs] == [True, False, True, True]
    records = DeadLetterLog.read(dlq)
    assert len(records) == 1
    assert records[0]["trace_id"] == futs[1].trace_id
    assert records[0]["credential"] == 1
    # both devices actually dispatched, one batch each
    assert metrics.get_count("serve_dev0_dispatches") == 1
    assert metrics.get_count("serve_dev1_dispatches") == 1
    assert metrics.get_count("dead_letters") == 1


class SleepyPerCred:
    """Models a device: each dispatch holds the executor for `delay_s` in
    time.sleep (which releases the GIL — so a pool of executor threads
    genuinely overlaps, the way real device dispatches do)."""

    def __init__(self, delay_s):
        self.delay_s = delay_s

    def batch_verify(self, sigs, msgs, vk, params):
        time.sleep(self.delay_s)
        return [_lane_bit(s) for s in sigs]


def _saturate(n_devices, duration_s=0.35):
    metrics.reset()
    svc = _service(
        SleepyPerCred(0.010),
        max_batch=4,
        max_wait_ms=1.0,
        max_depth=256,
        devices=n_devices,
    ).start()
    pool = [(_cred(), [0], True)]
    report = run_loadgen(
        svc, pool, duration_s=duration_s, arrival="closed", concurrency=32
    )
    assert svc.drain(timeout=10.0)
    assert report["dropped_futures"] == 0 and report["errors"] == 0
    return report


def test_pool_goodput_scales_with_device_count():
    """The acceptance bar: at saturation, 8 executors deliver >= 3x the
    goodput of 1 (near-linear is the ideal; >=3x is the floor on a
    GIL-shared CPU host), every device sees work, and no future drops."""
    solo = _saturate(1)
    pooled = _saturate(8)
    assert pooled["goodput_per_s"] >= 3.0 * solo["goodput_per_s"], (
        solo["goodput_per_s"],
        pooled["goodput_per_s"],
    )
    # every device executor reported nonzero dispatches
    for d in range(8):
        assert metrics.get_count("serve_dev%d_dispatches" % d) > 0, d
    devices = pooled["devices"]
    assert set(devices) == {str(d) for d in range(8)}
    for dev in devices.values():
        assert dev["dispatches"] > 0 and dev["busy_s"] > 0
        assert 0.0 < dev["occupancy"] <= 1.0
    assert pooled["placement"]["single"] == sum(
        d["dispatches"] for d in devices.values()
    )


def test_pool_drain_resolves_every_future_across_devices():
    svc = _service(StubPerCred(), max_batch=3, devices=4).start()
    futs = [svc.submit(_cred(ok=i % 3 != 1), [i]) for i in range(23)]
    assert svc.drain(timeout=10.0)
    assert [f.result(0) for f in futs] == [i % 3 != 1 for i in range(23)]
    total = sum(
        metrics.get_count("serve_dev%d_dispatches" % d) for d in range(4)
    )
    assert total == metrics.get_count("serve_batches")
    assert metrics.get_count("serve_dev0_requests") + sum(
        metrics.get_count("serve_dev%d_requests" % d) for d in range(1, 4)
    ) == 23


@pytest.mark.slow
def test_mesh_serve_integration_sharded_routing_correct_bits():
    """End-to-end on the 8-device CPU mesh: bulk batches route through the
    dp-sharded mesh dispatch and every future resolves with ITS lane's
    verdict. Reuses the (dp=4, tp=2) per-credential program shape
    tests/test_shard.py compiles (program cache keys on mesh+shape; in a
    full-suite run this test traces it first and test_shard reuses the
    in-process program cache). Marked slow: virtual-mesh tracing +
    execution is multi-minute — ci.sh's full-suite pass runs it, the
    driver's bounded tier-1 (-m 'not slow') does not."""
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest.py)")
    import __graft_entry__ as ge
    from coconut_tpu.signature import Signature
    from coconut_tpu.tpu.backend import JaxBackend
    from coconut_tpu.tpu.shard import default_mesh

    params, _, vk, sigs, msgs_list = ge._fixture(batch=8, seed=0x51A2D)
    sigs = list(sigs)
    sigs[5] = Signature(
        sigs[5].sigma_1, params.ctx.sig.mul(sigs[5].sigma_2, 2)
    )
    mesh = default_mesh(ndp=4, ntp=2, devices=jax.devices()[:8])
    svc = CredentialService(
        JaxBackend(),
        vk,
        params,
        mode="per_credential",
        max_batch=4,
        max_wait_ms=20.0,
        mesh=mesh,
    )
    # all-bulk, submitted before start: two full batches of 4, both of
    # which the adaptive policy routes sharded across the mesh
    futs = [
        svc.submit(s, m, lane="bulk") for s, m in zip(sigs, msgs_list)
    ]
    svc.start()
    assert svc.drain(timeout=1200.0)
    want = [i != 5 for i in range(8)]
    assert [f.result(0) for f in futs] == want
    assert metrics.get_count("serve_placed_sharded") == 2
    assert metrics.get_count("serve_devmesh_dispatches") == 2
    assert metrics.get_count("serve_devmesh_requests") == 8
    snap = metrics.snapshot()
    assert snap["counters"]["serve_devmesh_dispatches"] == 2
    assert "serve_devmesh_busy_s" in snap["timers_s"]


# --- self-healing pool (ISSUE 9): crash / hang / quarantine / brownout -----


def _chaos_service(backend, clock, **kw):
    """A pool service wired for deterministic chaos: fake clock, a
    fake-clock watchdog with a 1s initial budget, and NO watchdog thread —
    the test drives health_tick() by hand after advancing time."""
    from coconut_tpu.serve.health import HealthPolicy, Watchdog

    kw.setdefault("devices", 2)
    kw.setdefault("max_batch", 2)
    kw.setdefault(
        "watchdog",
        Watchdog(clock=clock, k=2.0, min_timeout_s=1.0, initial_timeout_s=1.0),
    )
    kw.setdefault("watchdog_interval_s", None)
    kw.setdefault(
        "health_policy", HealthPolicy(probe_after_s=5.0, probe_successes=1)
    )
    return _service(backend, clock=clock, **kw)


@pytest.mark.chaos
def test_watchdog_timeout_quarantines_and_redistributes_the_hung_batch():
    """A sync dispatch that never returns is invisible to the retry
    ladder; the watchdog expires it on a FAKE clock, abandons the stuck
    worker, quarantines the executor, and the batch settles on the
    survivor — no real sleeps, every future resolves."""
    from coconut_tpu.serve.health import QUARANTINED

    clock = FakeClock()
    be = FaultyBackend(StubPerCred(), hang_on={0})
    svc = _chaos_service(be, clock)
    futs = [svc.submit(_cred(), [i]) for i in range(2)]  # one full batch
    svc.start()
    try:
        # device 0's worker is now wedged INSIDE the dispatch
        assert be.hang_entered.wait(5.0), "hang injection never reached"
        clock.advance(2.0)  # past the 1s initial watchdog budget
        svc.health_tick()
        # the hung batch was redistributed to device 1 and settles there
        assert [f.result(10.0) for f in futs] == [True, True]
        assert metrics.get_count("serve_watchdog_timeouts") == 1
        assert metrics.get_count("serve_quarantined") == 1
        assert metrics.get_count("serve_redistributed_batches") == 1
        assert metrics.get_count("serve_redistributed_requests") == 2
        assert metrics.get_gauge("serve_dev0_health") == QUARANTINED
        assert metrics.get_gauge("serve_healthy_executors") == 1
    finally:
        be.hang_release.set()  # un-wedge the abandoned worker
    assert svc.drain(timeout=10.0)
    # the late return of the timed-out dispatch was discarded (stale
    # settle), not double-delivered — and nothing else ever hung
    assert metrics.get_count("serve_failed_requests") == 0


@pytest.mark.chaos
def test_executor_crash_contained_to_one_device():
    """An executor-loop crash (an InjectedCrash BaseException escaping the
    per-batch containment) quarantines ONLY its executor; its batch
    settles on the survivor and the pool keeps serving."""
    from coconut_tpu.faults import InjectedCrash
    from coconut_tpu.serve.health import HEALTHY, QUARANTINED

    clock = FakeClock()
    be = FaultyBackend(StubPerCred(), crash_on={0})
    svc = _chaos_service(be, clock)
    futs = [svc.submit(_cred(), [i]) for i in range(2)]
    svc.start()
    assert [f.result(10.0) for f in futs] == [True, True]
    assert be.crashes == 1
    assert metrics.get_count("serve_executor_crashes") == 1
    assert metrics.get_count("serve_quarantined") == 1
    assert metrics.get_count("serve_redistributed_batches") == 1
    assert metrics.get_gauge("serve_dev0_health") == QUARANTINED
    assert metrics.get_gauge("serve_dev1_health") == HEALTHY
    # the pool is degraded, not dead: new work still settles
    futs2 = [svc.submit(_cred(), [i]) for i in range(2)]
    assert [f.result(10.0) for f in futs2] == [True, True]
    assert svc.drain(timeout=10.0)
    assert isinstance(svc._crashed, type(None)) and not isinstance(
        svc._crashed, InjectedCrash
    )


@pytest.mark.chaos
def test_quarantine_probation_recovery_ladder_readmits_the_executor():
    """The full ladder: crash -> QUARANTINED -> (cooldown on the fake
    clock) -> PROBATION with a respawned worker -> one successful probe
    batch -> HEALTHY again."""
    from coconut_tpu.serve.health import HEALTHY, PROBATION, QUARANTINED

    clock = FakeClock()
    be = FaultyBackend(StubPerCred(), crash_on={0})
    svc = _chaos_service(be, clock)
    futs = [svc.submit(_cred(), [i]) for i in range(2)]
    svc.start()
    assert [f.result(10.0) for f in futs] == [True, True]
    assert metrics.get_gauge("serve_dev0_health") == QUARANTINED
    assert not svc._executors[0].has_worker()  # abandoned
    # cooldown not elapsed: the tick changes nothing
    clock.advance(1.0)
    svc.health_tick()
    assert metrics.get_gauge("serve_dev0_health") == QUARANTINED
    # cooldown elapsed: half-open probe window, fresh worker spawned
    clock.advance(5.0)
    svc.health_tick()
    assert metrics.get_gauge("serve_dev0_health") == PROBATION
    assert svc._executors[0].has_worker()
    # next batch is the probe: load-tie placement picks device 0 first
    probe = [svc.submit(_cred(), [i]) for i in range(2)]
    assert [f.result(10.0) for f in probe] == [True, True]
    assert metrics.get_count("serve_probes") >= 1
    assert metrics.get_count("serve_recovered") == 1
    assert metrics.get_gauge("serve_dev0_health") == HEALTHY
    assert metrics.get_gauge("serve_healthy_executors") == 2
    assert svc.drain(timeout=10.0)


@pytest.mark.chaos
def test_all_executors_dead_poisons_service_with_no_dangling_futures():
    """Crash containment's floor: when EVERY executor has died, the
    service poisons — each accepted future resolves with the crash
    exception (none dangle) and new submissions are refused, typed."""
    from coconut_tpu.faults import InjectedCrash

    clock = FakeClock()
    be = FaultyBackend(StubPerCred(), crash_on=set(range(16)))
    svc = _chaos_service(be, clock)
    futs = [svc.submit(_cred(), [i]) for i in range(2)]
    svc.start()
    for f in futs:
        assert isinstance(f.exception(10.0), InjectedCrash)
    assert svc._crashed is not None
    with pytest.raises(ServiceClosedError):
        svc.submit(_cred(), [0])
    assert metrics.get_count("serve_executor_crashes") == 2
    assert svc.drain(timeout=10.0)


@pytest.mark.chaos
def test_redispatch_hop_cap_fails_a_poisonous_batch_loudly():
    """A batch whose dispatch crashes every executor it lands on fails ITS
    OWN futures after max_redispatch hops instead of serially killing the
    whole pool: device 2 survives."""
    from coconut_tpu.faults import InjectedCrash
    from coconut_tpu.serve.health import HEALTHY

    clock = FakeClock()
    be = FaultyBackend(StubPerCred(), crash_on={0, 1})
    svc = _chaos_service(be, clock, devices=3, max_redispatch=1)
    futs = [svc.submit(_cred(), [i]) for i in range(2)]
    svc.start()
    for f in futs:
        assert isinstance(f.exception(10.0), InjectedCrash)
    assert metrics.get_count("serve_redispatch_exhausted") == 1
    assert svc._crashed is None  # the SERVICE survived the poison batch
    assert metrics.get_gauge("serve_dev2_health") == HEALTHY
    futs2 = [svc.submit(_cred(), [i]) for i in range(2)]
    assert [f.result(10.0) for f in futs2] == [True, True]
    assert svc.drain(timeout=10.0)


@pytest.mark.chaos
def test_brownout_sheds_bulk_admits_interactive():
    """With half the pool quarantined (below a 0.9 capacity threshold),
    bulk submissions shed with the typed retriable error + hint while
    interactive requests ride through and resolve."""
    from coconut_tpu.errors import ServiceBrownoutError
    from coconut_tpu.serve.health import BrownoutPolicy

    clock = FakeClock()
    svc = _chaos_service(
        StubPerCred(),
        clock,
        brownout=BrownoutPolicy(capacity_threshold=0.9, retry_after_s=0.25),
    )
    svc._health_of("0").on_crash("injected for the brownout test")
    with pytest.raises(ServiceBrownoutError) as ei:
        svc.submit(_cred(), [0], lane="bulk")
    assert ei.value.retry_after_s > 0 and ei.value.lane == "bulk"
    assert ei.value.capacity_fraction == 0.5
    assert metrics.get_count("serve_shed_bulk") == 1
    assert metrics.get_gauge("serve_brownout") == 1
    # interactive stays live: admitted, dispatched on the survivor
    svc.start()
    futs = [svc.submit(_cred(), [i]) for i in range(2)]
    assert [f.result(10.0) for f in futs] == [True, True]
    assert metrics.get_count("serve_dev1_dispatches") == 1
    assert metrics.get_count("serve_dev0_dispatches") == 0
    assert svc.drain(timeout=10.0)


@pytest.mark.chaos
def test_drain_timeout_is_one_shared_deadline_not_per_thread():
    """drain(timeout=0.5) against four executors all wedged in a gated
    dispatch returns False in ~one timeout's worth of wall clock — the old
    per-thread join semantics would have taken >= 4x. A later drain after
    the gate opens still settles everything."""
    be = GatedPerCred()
    svc = _service(be, max_batch=1, devices=4).start()
    futs = [svc.submit(_cred(), [i]) for i in range(4)]
    assert be.entered.wait(5.0)
    t0 = time.monotonic()
    assert svc.drain(timeout=0.5) is False
    elapsed = time.monotonic() - t0
    assert elapsed < 1.5, elapsed  # shared deadline, not 4 x 0.5s of joins
    be.release.set()
    assert svc.drain(timeout=10.0) is True
    assert [f.result(0) for f in futs] == [True] * 4
