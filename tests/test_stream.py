"""Streamed verification + checkpoint/resume (BASELINE config 5 driver)."""

import random

from coconut_tpu.backend import PythonBackend
from coconut_tpu.ops.curve import G1_GEN, G2_GEN
from coconut_tpu.ops.fields import R
from coconut_tpu.params import Params, SIGNATURES_IN_G1
from coconut_tpu.signature import Signature, Sigkey, Verkey
from coconut_tpu.stream import StreamState, verify_stream

MSG_COUNT = 2
BATCH = 3


def _setup():
    rng = random.Random(0x57E4)
    ctx = SIGNATURES_IN_G1
    g = ctx.sig.mul(G1_GEN, rng.randrange(1, R))
    g_tilde = ctx.other.mul(G2_GEN, rng.randrange(1, R))
    h = [ctx.sig.mul(G1_GEN, rng.randrange(1, R)) for _ in range(MSG_COUNT)]
    params = Params(g, g_tilde, h, ctx)
    sk = Sigkey(
        rng.randrange(1, R), [rng.randrange(1, R) for _ in range(MSG_COUNT)]
    )
    vk = Verkey(
        ctx.other.mul(g_tilde, sk.x),
        [ctx.other.mul(g_tilde, y) for y in sk.y],
    )
    return rng, params, sk, vk


def _source_factory(rng, params, sk, corrupt_at=None):
    def source(i):
        sigs, msgs_list = [], []
        for j in range(BATCH):
            msgs = [rng.randrange(R) for _ in range(MSG_COUNT)]
            t = rng.randrange(1, R)
            s1 = params.ctx.sig.mul(params.g, t)
            expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
            s2 = params.ctx.sig.mul(s1, expo)
            if corrupt_at == (i, j):
                s2 = params.ctx.sig.mul(s2, 2)
            sigs.append(Signature(s1, s2))
            msgs_list.append(msgs)
        return sigs, msgs_list

    return source


def test_stream_counts_and_mixed_bits():
    rng, params, sk, vk = _setup()
    source = _source_factory(rng, params, sk, corrupt_at=(1, 2))
    seen = []
    state = verify_stream(
        source,
        3,
        vk,
        params,
        PythonBackend(),
        on_batch=lambda i, bits: seen.append((i, bits)),
    )
    assert state.next_batch == 3
    assert state.verified == 8 and state.failed == 1
    assert seen[1][1] == [True, True, False]


def test_stream_grouped_mode_accounting():
    """mode='grouped' records batch-level verdicts honestly (VERDICT r2
    weak #3): no fabricated per-credential bits — a rejected batch counts
    wholesale in `failed` and `batches_failed`."""
    from coconut_tpu.ps import ps_verify

    rng, params, sk, vk = _setup()
    source = _source_factory(rng, params, sk, corrupt_at=(1, 2))

    class GroupedPy:
        """Batch-level oracle with the grouped path's semantics."""

        def batch_verify_grouped(self, s, m, v, p):
            return all(ps_verify(si, mi, v, p) for si, mi in zip(s, m))

    seen = []
    state = verify_stream(
        source,
        3,
        vk,
        params,
        GroupedPy(),
        on_batch=lambda i, ok: seen.append((i, ok)),
        mode="grouped",
    )
    assert state.batches_ok == 2 and state.batches_failed == 1
    assert state.verified == 2 * BATCH and state.failed == BATCH
    assert seen == [(0, True), (1, False), (2, True)]


def _events_backend(events):
    class AsyncBk:
        def batch_verify_async(self, s, m, v, p):
            i = len([e for e in events if e[0] == "dispatch"])
            events.append(("dispatch", i))

            def fin():
                events.append(("settle", i))
                return [True] * len(s)

            return fin

    return AsyncBk()


def test_stream_pipeline_overlaps_dispatch_and_settle():
    """With an async-capable backend, `pipeline_depth` batches are
    DISPATCHED before the oldest result is read back (the in-flight queue
    that hides the device round trip, SURVEY §2.3 pipeline row), and
    results still settle in order."""
    rng, params, sk, vk = _setup()
    source = _source_factory(rng, params, sk)
    events = []
    state = verify_stream(
        source, 3, vk, params, _events_backend(events), pipeline_depth=2
    )
    assert state.verified == 3 * BATCH
    assert events == [
        ("dispatch", 0),
        ("dispatch", 1),
        ("settle", 0),
        ("dispatch", 2),
        ("settle", 1),
        ("settle", 2),
    ]


def test_stream_pipeline_default_depth_keeps_queue_full():
    """Default depth (3): all of the first 3 batches dispatch before any
    settles; settling stays in order and checkpoint lag is bounded."""
    rng, params, sk, vk = _setup()
    source = _source_factory(rng, params, sk)
    events = []
    state = verify_stream(source, 5, vk, params, _events_backend(events))
    assert state.verified == 5 * BATCH
    assert events[:3] == [("dispatch", i) for i in range(3)]
    settles = [i for kind, i in events if kind == "settle"]
    assert settles == list(range(5))
    # every settle of batch i happens only after dispatch of batch i+depth-1
    for i in range(5):
        s_at = events.index(("settle", i))
        d_count = len([e for e in events[:s_at] if e[0] == "dispatch"])
        assert d_count >= min(i + 3, 5)


def test_stream_resume_from_checkpoint(tmp_path):
    rng, params, sk, vk = _setup()
    path = str(tmp_path / "stream.json")
    # deterministic source: independent rng per batch so the resumed run
    # regenerates identical credentials
    def source(i):
        r = random.Random(1000 + i)
        sigs, msgs_list = [], []
        for _ in range(BATCH):
            msgs = [r.randrange(R) for _ in range(MSG_COUNT)]
            t = r.randrange(1, R)
            s1 = params.ctx.sig.mul(params.g, t)
            expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
            sigs.append(Signature(s1, params.ctx.sig.mul(s1, expo)))
            msgs_list.append(msgs)
        return sigs, msgs_list

    calls = []

    def counting_source(i):
        calls.append(i)
        return source(i)

    # first run: interrupt after 2 of 4 batches (simulate by running 2)
    verify_stream(counting_source, 2, vk, params, PythonBackend(), path)
    st = StreamState(path)
    assert st.next_batch == 2 and st.verified == 2 * BATCH

    # resume: only batches 2 and 3 are fetched
    calls.clear()
    state = verify_stream(counting_source, 4, vk, params, PythonBackend(), path)
    assert calls == [2, 3]
    assert state.verified == 4 * BATCH and state.failed == 0
