"""Unified execution-engine suite (ISSUE 12).

Covers the PR-12 contract surface:

  - the typed retriable-error hierarchy: every loud-but-retriable
    refusal (overload, brownout, quorum loss) is ONE isinstance branch
    (ServiceRetryableError) and carries `program` + `retry_after_s`;
  - online/offline parity: show-verify and show-prove through the
    engine's batched lanes are bit-identical to the direct
    ps.batch_show_verify / pok_sig.batch_show calls — including the
    clone-first-proof pad convention and ragged final batches;
  - the full-session pipeline: prepare -> mint -> verify -> show_prove
    -> show_verify composes on ONE engine, and the per-program
    jit-shape counters stay flat after warmup (the no-cross-program-
    recompile proof).

Real crypto on small parameters (3 messages, t=2-of-3) over the python
backend — seconds, not minutes. ci.sh's engine lane runs this suite
plus probes/probe_engine.py (the crash-injection acceptance smoke)."""

from types import SimpleNamespace

import pytest

from coconut_tpu import metrics, pok_sig, ps
from coconut_tpu.backend import get_backend
from coconut_tpu.elgamal import elgamal_keygen
from coconut_tpu.engine import ProtocolEngine
from coconut_tpu.errors import (
    CoconutError,
    QuorumUnreachableError,
    ServiceBrownoutError,
    ServiceOverloadedError,
    ServiceRetryableError,
)
from coconut_tpu.keygen import trusted_party_SSS_keygen
from coconut_tpu.ops.fields import R
from coconut_tpu.params import Params
from coconut_tpu.signature import Verkey
from coconut_tpu.sss import rand_fr

pytestmark = pytest.mark.engine

MSGS = 3
HIDDEN = 1
REVEALED = [1, 2]
THRESHOLD, TOTAL = 2, 3
NAMESPACES = ("serve", "prep", "prove", "showv")


@pytest.fixture(scope="module")
def world():
    params = Params.new(MSGS, b"test-engine")
    _, _, signers = trusted_party_SSS_keygen(THRESHOLD, TOTAL, params)
    vk = Verkey.aggregate(
        THRESHOLD, [(s.id, s.verkey) for s in signers], ctx=params.ctx
    )
    return SimpleNamespace(
        params=params,
        signers=signers,
        vk=vk,
        backend=get_backend("python"),
    )


def _engine(world, **kw):
    kw.setdefault("devices", 1)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 10.0)
    return ProtocolEngine(
        world.signers,
        world.params,
        THRESHOLD,
        count_hidden=HIDDEN,
        revealed_msg_indices=REVEALED,
        backend=world.backend,
        **kw
    ).start()


@pytest.fixture(scope="module")
def creds(world):
    """Five minted (credential, messages) pairs — minted ONCE through a
    real engine (prepare + mint lanes), shared by the parity tests."""
    eng = _engine(world)
    out = []
    try:
        for _ in range(5):
            msgs = [rand_fr() for _ in range(MSGS)]
            esk, epk = elgamal_keygen(world.params.ctx.sig, world.params.g)
            req, _ = eng.submit_prepare(msgs, epk).result(timeout=120.0)
            sig = eng.submit_mint(req, msgs, esk).result(timeout=120.0)
            out.append((sig, msgs))
    finally:
        assert eng.drain(timeout=60.0)
    return out


# --- satellite: the typed retriable-error hierarchy ------------------------


def test_retryable_error_hierarchy():
    """One isinstance branch covers every loud-but-retriable refusal,
    and each subclass carries the program name + retry-after hint."""
    for cls in (
        ServiceOverloadedError,
        ServiceBrownoutError,
        QuorumUnreachableError,
    ):
        assert issubclass(cls, ServiceRetryableError)
        assert issubclass(cls, CoconutError)

    over = ServiceOverloadedError(8, 8, program="verify", retry_after_s=0.25)
    assert over.program == "verify"
    assert over.retry_after_s == 0.25
    assert (over.depth, over.max_depth) == (8, 8)

    brown = ServiceBrownoutError(
        "bulk", 0.5, depth=3, capacity_fraction=0.5, program="prepare"
    )
    assert brown.program == "prepare"
    assert brown.retry_after_s == 0.5
    assert brown.lane == "bulk"

    quorum = QuorumUnreachableError(
        3, 1, live=1, program="mint", retry_after_s=1.0
    )
    assert quorum.program == "mint"
    assert quorum.retry_after_s == 1.0
    assert (quorum.needed, quorum.have, quorum.live) == (3, 1, 1)

    # clients branch on the ONE base type, reading the shared fields
    for err in (over, brown, quorum):
        assert isinstance(err, ServiceRetryableError)
        assert err.program is not None
        assert err.retry_after_s is not None

    # legacy single-program call sites default program to None; the
    # retry-after hint normalizes to 0.0 (PR 13: always a finite float
    # >= 0, never None — wire envelopes and backoff math rely on it)
    legacy = ServiceOverloadedError(1, 1)
    assert legacy.program is None
    assert legacy.retry_after_s == 0.0


# --- online/offline parity -------------------------------------------------


@pytest.mark.parametrize("showv_mode", ["exact", "batched"])
def test_show_verify_parity_ragged_and_padded(world, creds, showv_mode):
    """Five proofs through a max_batch=4 engine lane — one full batch
    plus a ragged final batch padded clone-first-proof — must produce
    verdict bits identical to ONE direct ps.batch_show_verify call,
    including a tampered (False) lane. Runs in both show-verify modes:
    the PR-16 batched (RLC combined pairing) lane must match the exact
    path bit-for-bit through the same clone-first padding."""
    sigs = [s for s, _ in creds]
    msgs = [m for _, m in creds]
    proofs, challenges, revealed_list = pok_sig.batch_show(
        sigs, world.vk, world.params, msgs, REVEALED, backend=world.backend
    )
    # tamper one lane's revealed message: structurally valid, must fail
    revealed_list = [dict(d) for d in revealed_list]
    revealed_list[2][REVEALED[0]] = (revealed_list[2][REVEALED[0]] + 1) % R

    direct = ps.batch_show_verify(
        proofs,
        world.vk,
        world.params,
        revealed_list,
        challenges=challenges,
        backend=world.backend,
    )
    assert list(direct) == [True, True, False, True, True]

    metrics.reset()
    eng = _engine(
        world, max_batch=4, max_wait_ms=10.0, showv_mode=showv_mode
    )
    try:
        futs = [
            eng.submit_show_verify(p, rev, chal)
            for p, rev, chal in zip(proofs, revealed_list, challenges)
        ]
        online = [f.result(timeout=120.0) for f in futs]
    finally:
        assert eng.drain(timeout=60.0)

    assert online == list(direct)
    # the ragged final batch (1 request) really was padded to max_batch
    assert metrics.get_count("showv_pad_lanes") == 3
    assert metrics.get_count("showv_valid") == 4
    assert metrics.get_count("showv_invalid") == 1


def test_show_verify_challenge_recompute_parity(world, creds):
    """challenge=None (the stranger-verifier path) recomputes the
    Fiat-Shamir challenge at assemble time and agrees with the direct
    explicit-challenge verdict."""
    sig, msgs = creds[0]
    (proof,), (chal,), (rev,) = pok_sig.batch_show(
        [sig], world.vk, world.params, [msgs], REVEALED,
        backend=world.backend,
    )
    assert ps.batch_show_verify(
        [proof], world.vk, world.params, [rev], challenges=[chal],
        backend=world.backend,
    ) == [True]

    eng = _engine(world)
    try:
        assert eng.submit_show_verify(proof, rev).result(timeout=120.0)
    finally:
        assert eng.drain(timeout=60.0)


def test_show_prove_parity_bit_identical(world, creds, monkeypatch):
    """With the randomness stream pinned, one engine show_prove batch is
    bit-identical to the direct pok_sig.batch_show call: same proofs
    (transcript bytes), same challenges, same revealed maps. Draw-order
    sensitivity is the point — pad_partial=False and max_batch=2 make
    the engine dispatch EXACTLY the direct call."""
    draws = [rand_fr() for _ in range(64)]

    def replayer():
        it = iter(draws)
        return lambda: next(it)

    sigs = [creds[0][0], creds[1][0]]
    msgs = [creds[0][1], creds[1][1]]

    monkeypatch.setattr(pok_sig, "rand_fr", replayer())
    d_proofs, d_chals, d_revealed = pok_sig.batch_show(
        sigs, world.vk, world.params, msgs, REVEALED, backend=world.backend
    )

    monkeypatch.setattr(pok_sig, "rand_fr", replayer())
    eng = _engine(world, max_batch=2, max_wait_ms=500.0, pad_partial=False)
    try:
        futs = [
            eng.submit_show_prove(s, m) for s, m in zip(sigs, msgs)
        ]
        online = [f.result(timeout=120.0) for f in futs]
    finally:
        assert eng.drain(timeout=60.0)

    for i, (proof, chal, rev) in enumerate(online):
        assert chal == d_chals[i]
        assert rev == d_revealed[i]
        assert proof.to_bytes_for_challenge(
            world.vk, world.params
        ) == d_proofs[i].to_bytes_for_challenge(world.vk, world.params)
    # and the online proofs verify
    assert ps.batch_show_verify(
        [p for p, _, _ in online],
        world.vk,
        world.params,
        [r for _, _, r in online],
        challenges=[c for _, c, _ in online],
        backend=world.backend,
    ) == [True, True]


# --- the full-session pipeline + jit-shape stability -----------------------


def test_full_session_pipeline_and_jit_stability(world):
    """All five phases compose on ONE engine, and after a one-session
    warmup the per-program jit-shape counters never move again — mixed
    heterogeneous traffic causes zero cross-program recompiles."""
    metrics.reset()
    eng = _engine(world, devices=2, max_batch=4, max_wait_ms=5.0)

    def session():
        msgs = [rand_fr() for _ in range(MSGS)]
        esk, epk = elgamal_keygen(world.params.ctx.sig, world.params.g)
        req, _ = eng.submit_prepare(msgs, epk).result(timeout=120.0)
        cred = eng.submit_mint(req, msgs, esk).result(timeout=120.0)
        assert eng.submit_verify(cred, msgs).result(timeout=120.0)
        proof, chal, rev = eng.submit_show_prove(cred, msgs).result(
            timeout=120.0
        )
        assert eng.submit_show_verify(proof, rev, chal).result(
            timeout=120.0
        )

    try:
        session()  # warmup: compiles every pool program's serving shape
        warm = {
            ns: metrics.get_count("%s_jit_shapes" % ns) for ns in NAMESPACES
        }
        assert all(v >= 1 for v in warm.values()), warm
        for _ in range(2):
            session()
        end = {
            ns: metrics.get_count("%s_jit_shapes" % ns) for ns in NAMESPACES
        }
    finally:
        assert eng.drain(timeout=60.0)

    assert end == warm, "cross-program recompile: %r -> %r" % (warm, end)
    assert metrics.get_count("issue_minted") == 3
