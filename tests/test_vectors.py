"""Known-answer (golden) vector replay — VERDICT round-1 item 8.

The vectors in tests/vectors/*.json are generated once
(tests/vectors/generate.py) and committed; these tests replay them against
the live code so the spec can't silently drift — and any backend (C++/TPU)
can consume the same files verbatim. Without pinned vectors, spec and
backend could drift *together* and algebraic self-consistency tests would
still pass.
"""

import json
import os

import pytest

from coconut_tpu.ops import serialize as ser
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.hashing import (
    expand_message_xmd,
    hash_to_fr,
    hash_to_g1,
    hash_to_g2,
)
from coconut_tpu.ops.pairing import pairing
from coconut_tpu.params import Params
from coconut_tpu.ps import ps_verify
from coconut_tpu.signature import Signature, Verkey

VECDIR = os.path.join(os.path.dirname(__file__), "vectors")


def load(name):
    path = os.path.join(VECDIR, name)
    if not os.path.exists(path):
        pytest.skip("vectors not generated (run tests/vectors/generate.py)")
    with open(path) as f:
        return json.load(f)


def _flat(x):
    out = []

    def rec(t):
        if isinstance(t, tuple):
            for u in t:
                rec(u)
        else:
            out.append(hex(t))

    rec(x)
    return out


def test_field_vectors():
    v = load("fields.json")
    from coconut_tpu.ops.fields import P, R

    assert hex(P) == v["p"] and hex(R) == v["r"]
    for c in v["fp_cases"]:
        a, b = int(c["a"], 16), int(c["b"], 16)
        assert hex((a + b) % P) == c["add"]
        assert hex(a * b % P) == c["mul"]
        assert hex(pow(a, -1, P)) == c["inv_a"]


def test_expand_message_xmd_vectors():
    v = load("hashing.json")
    for c in v["expand_message_xmd"]:
        got = expand_message_xmd(
            bytes.fromhex(c["msg"]), bytes.fromhex(c["dst"]), c["len"]
        )
        assert got.hex() == c["out"]


def test_hash_to_fr_vectors():
    v = load("hashing.json")
    for c in v["hash_to_fr"]:
        assert hex(hash_to_fr(bytes.fromhex(c["msg"]))) == c["fr"]


def test_hash_to_group_vectors():
    v = load("hashing.json")
    for c in v["hash_to_g1"]:
        got = ser.g1_to_compressed(hash_to_g1(bytes.fromhex(c["msg"])))
        assert got.hex() == c["point"]
    for c in v["hash_to_g2"]:
        got = ser.g2_to_compressed(hash_to_g2(bytes.fromhex(c["msg"])))
        assert got.hex() == c["point"]


def test_native_hashing_matches_vectors():
    """The C++ core's CTH-v2 hashing (cc_hash_to_fr/g1/g2) against the same
    golden vectors the spec replays — VERDICT r2 item 6: the native core
    can now derive Params end-to-end (amcl from_msg_hash call sites,
    reference signature.rs:23-29,205)."""
    from coconut_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    v = load("hashing.json")
    for c in v["hash_to_fr"]:
        assert hex(native.hash_to_fr(bytes.fromhex(c["msg"]))) == c["fr"]
    for c in v["hash_to_g1"]:
        got = ser.g1_to_compressed(native.hash_to_g1(bytes.fromhex(c["msg"])))
        assert got.hex() == c["point"]
    for c in v["hash_to_g2"]:
        got = ser.g2_to_compressed(native.hash_to_g2(bytes.fromhex(c["msg"])))
        assert got.hex() == c["point"]
    # Params derivation entirely through the native core == spec Params
    pv = load("params.json")
    g, gt, hs = native.derive_params(pv["msg_count"], bytes.fromhex(pv["label"]))
    params = Params(g, gt, hs)
    assert params.to_bytes().hex() == pv["blob"]


def test_params_blob_vector():
    v = load("params.json")
    params = Params.new(v["msg_count"], bytes.fromhex(v["label"]))
    assert params.to_bytes().hex() == v["blob"]
    assert Params.from_bytes(bytes.fromhex(v["blob"])) == params


def test_curve_vectors():
    v = load("curve.json")
    for c in v["cases"]:
        a, b = int(c["a"], 16), int(c["b"], 16)
        pa = g1.mul(G1_GEN, a)
        pb = g1.mul(G1_GEN, b)
        assert ser.g1_to_bytes(pa).hex() == c["g1_a"]
        assert ser.g1_to_bytes(g1.add(pa, pb)).hex() == c["g1_add"]
        assert ser.g1_to_bytes(g1.msm([pa, pb], [b, a])).hex() == c["g1_msm"]
        assert ser.g2_to_bytes(g2.mul(G2_GEN, a)).hex() == c["g2_a"]


def test_pairing_vectors():
    v = load("pairing.json")
    a, b = int(v["a"], 16), int(v["b"], 16)
    assert _flat(pairing(g1.mul(G1_GEN, a), g2.mul(G2_GEN, b))) == v["e_aG1_bG2"]
    assert _flat(pairing(G1_GEN, G2_GEN)) == v["e_G1_G2"]
    # bilinearity pin: e(aP, bQ) == e(abP, Q)
    assert v["e_aG1_bG2"] == v["bilinearity_ab"]


def test_transcript_vector():
    v = load("transcript.json")
    params = Params.new(len(v["msgs"]), bytes.fromhex(v["label"]))
    vk = Verkey.from_bytes(bytes.fromhex(v["vk"]), params.ctx)
    sig = Signature.from_bytes(bytes.fromhex(v["sig"]), params.ctx)
    msgs = [int(m, 16) for m in v["msgs"]]
    assert ps_verify(sig, msgs, vk, params) is v["verifies"]
    bad = [int(m, 16) for m in v["bad_msgs"]]
    assert ps_verify(sig, bad, vk, params) is v["bad_verifies"]
