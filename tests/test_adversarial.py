"""Adversarial tests — the rejection paths (VERDICT round-1 item 7).

The reference tests only happy paths (SURVEY.md §4 "gaps"); the protocol's
fault-tolerance story rests on the rejection paths actually rejecting:
`PedersenVSS::verify_share` detecting a malicious dealer (README.md:52-68,
keygen.rs:334-351), DVSS participants refusing bad shares
(keygen.rs:141-158), and every wire decoder refusing malformed bytes.
"""

import random

import pytest

from coconut_tpu.errors import DeserializationError, GeneralError
from coconut_tpu.ops import serialize as ser
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.params import Params, SIGNATURES_IN_G1
from coconut_tpu.signature import Signature, Verkey
from coconut_tpu.sss import (
    PedersenDVSSParticipant,
    PedersenVSS,
    share_secret_dvss,
)

rng = random.Random(0xADC0)


@pytest.fixture(scope="module")
def gens():
    return PedersenVSS.gens(b"adversarial-test")


class TestPVSSRejection:
    def test_tampered_s_share_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(3, 5, g, h)
        sid = 2
        bad = ((s_shares[sid] + 1) % R, t_shares[sid])
        assert PedersenVSS.verify_share(3, sid, (s_shares[sid], t_shares[sid]), comms, g, h)
        assert not PedersenVSS.verify_share(3, sid, bad, comms, g, h)

    def test_tampered_t_share_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(2, 4, g, h)
        sid = 4
        bad = (s_shares[sid], (t_shares[sid] + R - 1) % R)
        assert not PedersenVSS.verify_share(2, sid, bad, comms, g, h)

    def test_tampered_commitment_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(3, 5, g, h)
        bad_comms = dict(comms)
        bad_comms[1] = PedersenVSS.ops.add(bad_comms[1], g)
        assert not PedersenVSS.verify_share(
            3, 1, (s_shares[1], t_shares[1]), bad_comms, g, h
        )

    def test_share_for_wrong_id_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(3, 5, g, h)
        # share evaluated at id 1 presented as id 2
        assert not PedersenVSS.verify_share(
            3, 2, (s_shares[1], t_shares[1]), comms, g, h
        )


class TestDVSSRejection:
    def test_received_bad_share_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        p2 = PedersenDVSSParticipant(2, 2, 3, g, h)
        bad = ((p1.s_shares[2] + 1) % R, p1.t_shares[2])
        with pytest.raises(GeneralError):
            p2.received_share(1, p1.comm_coeffs, bad, 2, 3, g, h)

    def test_received_own_share_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        with pytest.raises(GeneralError):
            p1.received_share(
                1, p1.comm_coeffs, (p1.s_shares[1], p1.t_shares[1]), 2, 3, g, h
            )

    def test_duplicate_share_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        p2 = PedersenDVSSParticipant(2, 2, 3, g, h)
        share = (p1.s_shares[2], p1.t_shares[2])
        p2.received_share(1, p1.comm_coeffs, share, 2, 3, g, h)
        with pytest.raises(GeneralError):
            p2.received_share(1, p1.comm_coeffs, share, 2, 3, g, h)

    def test_finalize_with_missing_shares_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        with pytest.raises(GeneralError):
            p1.compute_final_comm_coeffs_and_shares(2, 3, g, h)

    def test_full_protocol_still_works(self, gens):
        g, h = gens
        participants = share_secret_dvss(2, 3, g, h)
        assert all(p.secret_share is not None for p in participants)


class TestWireFuzz:
    """Truncation, flag-bit corruption, and off-curve bytes must raise
    DeserializationError — never return garbage structs."""

    def test_g1_compressed_roundtrip_and_flags(self):
        p = g1.mul(G1_GEN, rng.randrange(1, R))
        b = ser.g1_to_compressed(p)
        assert ser.g1_from_compressed(b) == p
        # clear the compression flag bit
        bad = bytes([b[0] & 0x7F]) + b[1:]
        with pytest.raises(DeserializationError):
            ser.g1_from_compressed(bad)

    def test_g2_compressed_flags(self):
        p = g2.mul(G2_GEN, rng.randrange(1, R))
        b = ser.g2_to_compressed(p)
        assert ser.g2_from_compressed(b) == p
        bad = bytes([b[0] | 0x40]) + b[1:]  # infinity flag on non-zero body
        with pytest.raises(DeserializationError):
            ser.g2_from_compressed(bad)
        # y-sign flip is NOT an error — it decodes the negated point
        flipped = ser.g2_from_compressed(bytes([b[0] ^ 0x20]) + b[1:])
        assert flipped == g2.neg(p)

    @pytest.mark.parametrize("cut", [1, 10, 47])
    def test_truncated_g1_raises(self, cut):
        p = g1.mul(G1_GEN, rng.randrange(1, R))
        b = ser.g1_to_bytes(p)
        with pytest.raises(DeserializationError):
            ser.g1_from_bytes(b[:-cut])

    def test_off_curve_g1_raises(self):
        p = g1.mul(G1_GEN, rng.randrange(1, R))
        x, y = p
        bad = ser.fp_to_bytes(x) + ser.fp_to_bytes((y + 1) % (2**381))
        with pytest.raises(DeserializationError):
            ser.g1_from_bytes(bad)

    def test_truncated_signature_raises(self):
        ctx = SIGNATURES_IN_G1
        p = g1.mul(G1_GEN, 5)
        sig = Signature(p, g1.mul(G1_GEN, 7))
        b = sig.to_bytes(ctx)
        with pytest.raises(DeserializationError):
            Signature.from_bytes(b[:-3], ctx)

    def test_truncated_verkey_raises(self):
        ctx = SIGNATURES_IN_G1
        vk = Verkey(
            g2.mul(G2_GEN, 3), [g2.mul(G2_GEN, i + 2) for i in range(2)]
        )
        b = vk.to_bytes(ctx)
        with pytest.raises(DeserializationError):
            Verkey.from_bytes(b[:-1], ctx)

    def test_truncated_params_raises(self):
        # hand-built params avoid the slow hash-to-group setup
        ctx = SIGNATURES_IN_G1
        params = Params(
            g1.mul(G1_GEN, 11),
            g2.mul(G2_GEN, 13),
            [g1.mul(G1_GEN, 17)],
            ctx,
        )
        b = params.to_bytes()
        with pytest.raises(DeserializationError):
            Params.from_bytes(b[:-5], ctx)


# --- RLC batch-verification soundness (PR 16) -------------------------------


class TestRLCSoundness:
    """Adversarial suite for the combined (random-linear-combination)
    batch verifier. The many-draw sweeps run against an ALGEBRAIC model
    of the combined predicate — a batch of lane defects delta_i in Z_r
    passes iff sum_i r_i * delta_i == 0 mod r, which is exactly the
    GT-exponent-group condition the real pairing product evaluates —
    driven through a faithful mirror of ps._rlc_verify_bits' bisection
    ladder (fresh derived exponents per sub-transcript). Real-crypto
    single-draw attribution runs at B=16 on the python backend; the
    cancellation pair demonstrates, on real pairings, that the all-ones
    combination is NOT a verifier while the derived RLC is."""

    pytestmark = pytest.mark.batchverify

    # -- the algebraic mirror ------------------------------------------------

    @staticmethod
    def _sim_bits(defects, seed):
        """Mirror of ps._rlc_verify_bits over defect exponents."""
        import hashlib

        from coconut_tpu.batchverify import derive_combiners

        B = len(defects)

        def combined(lo, hi):
            t = hashlib.sha256(
                b"sim|%d|%d|%d|" % (seed, lo, hi)
                + b"".join(d.to_bytes(32, "big") for d in defects[lo:hi])
            ).digest()
            rs = derive_combiners(t, hi - lo)
            return (
                sum(r * d for r, d in zip(rs, defects[lo:hi])) % R == 0
            )

        bits = [True] * B
        if B == 0 or combined(0, B):
            return bits

        def rec(lo, hi):
            if hi - lo == 1:
                bits[lo] = False
                return
            mid = (lo + hi) // 2
            left_ok = combined(lo, mid)
            right_ok = combined(mid, hi)
            if left_ok and right_ok:
                for i in range(lo, hi):
                    bits[i] = defects[i] == 0
                return
            if not left_ok:
                rec(lo, mid)
            if not right_ok:
                rec(mid, hi)

        rec(0, B)
        return bits

    @pytest.mark.parametrize("B", [16, 256])
    def test_forged_lanes_attributed_across_100_draws(self, B):
        # >= 100 independent seeded exponent draws per batch width; every
        # draw must reject AND name exactly the forged lanes
        local = random.Random(0x51C)
        for draw in range(100):
            n_bad = local.randrange(1, min(6, B))
            bad = set(local.sample(range(B), n_bad))
            defects = [
                local.randrange(1, R) if i in bad else 0 for i in range(B)
            ]
            bits = self._sim_bits(defects, seed=draw)
            assert bits == [i not in bad for i in range(B)], (
                "draw %d misattributed" % draw
            )

    @pytest.mark.parametrize("B", [16, 256])
    def test_all_valid_accepts_every_draw(self, B):
        for draw in range(100):
            assert self._sim_bits([0] * B, seed=draw) == [True] * B

    def test_cancellation_pair_simulated(self):
        # defects d and R-d cancel under the all-ones combination but
        # not under any draw with r_0 != r_1
        local = random.Random(0xCA7)
        for draw in range(100):
            d = local.randrange(1, R)
            defects = [d, R - d] + [0] * 14
            assert (defects[0] + defects[1]) % R == 0  # all-ones blind
            bits = self._sim_bits(defects, seed=draw)
            assert bits == [False, False] + [True] * 14, (
                "draw %d: cancellation pair survived" % draw
            )


class TestRLCSoundnessRealCrypto:
    """Single-draw real-pairing attribution at B=16 on the python
    backend, plus the real cancellation pair."""

    pytestmark = pytest.mark.batchverify

    B = 16
    Q = 2

    @pytest.fixture(scope="class")
    def world(self):
        from coconut_tpu.backend import get_backend
        from coconut_tpu.signature import Sigkey, Verkey

        local = random.Random(0xF06)
        params = Params.new(self.Q, b"rlc-adversarial")
        sk = Sigkey(
            local.randrange(1, R),
            [local.randrange(1, R) for _ in range(self.Q)],
        )
        ops = params.ctx.other
        vk = Verkey(
            ops.mul(params.g_tilde, sk.x),
            [ops.mul(params.g_tilde, y) for y in sk.y],
        )

        def sign(msgs):
            t = local.randrange(1, R)
            s1 = params.ctx.sig.mul(params.g, t)
            expo = (sk.x + sum(y * m for y, m in zip(sk.y, msgs))) % R
            return Signature(s1, params.ctx.sig.mul(s1, expo))

        msgs_list = [
            [local.randrange(R) for _ in range(self.Q)]
            for _ in range(self.B)
        ]
        sigs = [sign(m) for m in msgs_list]
        return get_backend("python"), params, vk, sigs, msgs_list

    def test_forged_sigma_and_wrong_message_attributed(self, world):
        from coconut_tpu import ps

        be, params, vk, sigs, msgs_list = world
        bad = list(sigs)
        bad[7] = Signature(
            bad[7].sigma_1, params.ctx.sig.mul(bad[7].sigma_2, 5)
        )
        wrong = [list(m) for m in msgs_list]
        wrong[11][0] = (wrong[11][0] + 1) % R
        bits = ps.batch_verify(
            bad, wrong, vk, params, backend=be, mode="batched"
        )
        assert bits == [i not in (7, 11) for i in range(self.B)]

    def test_tampered_show_proof_attributed(self, world):
        from coconut_tpu.pok_sig import batch_show_verify, show

        be, params, vk, sigs, msgs_list = world
        n = 8
        proofs, challenges, revealed = [], [], []
        for s, m in zip(sigs[:n], msgs_list[:n]):
            p, c, rv = show(s, vk, params, m, [0])
            proofs.append(p)
            challenges.append(c)
            revealed.append(rv)
        # tamper lane 5's proof: swap in a different lane's challenge so
        # its Schnorr equation still holds per-lane but the transcript
        # binding breaks -> exact path False; batched must agree
        rv2 = [dict(r) for r in revealed]
        rv2[5][0] = (rv2[5][0] + 1) % R
        bits = batch_show_verify(
            proofs, vk, params, rv2, challenges=challenges,
            backend=be, mode="batched",
        )
        exact = batch_show_verify(
            proofs, vk, params, rv2, challenges=challenges,
            backend=be, mode="exact",
        )
        assert bits == exact == [i != 5 for i in range(n)]

    def test_cancellation_pair_real_pairings(self, world):
        from coconut_tpu import ps

        be, params, vk, sigs, msgs_list = world
        ops = params.ctx.sig
        P = ops.mul(params.g, 0xD15EA5E)
        tampered = [
            Signature(sigs[0].sigma_1, ops.add(sigs[0].sigma_2, P)),
            Signature(sigs[1].sigma_1, ops.add(sigs[1].sigma_2, ops.neg(P))),
        ]
        pair_msgs = msgs_list[:2]
        # under the all-ones combination the two defects cancel: the
        # combined product accepts a batch with TWO forged lanes — the
        # blind spot that makes fixed combiners a non-verifier
        assert be.batch_verify_combined(
            tampered, pair_msgs, vk, params, rs=[1, 1]
        ) is True
        # both lanes are genuinely forged
        assert ps.batch_verify(tampered, pair_msgs, vk, params) == (
            [False, False]
        )
        # the derived RLC draw catches and attributes both
        assert ps.batch_verify(
            tampered, pair_msgs, vk, params, backend=be, mode="batched"
        ) == [False, False]
