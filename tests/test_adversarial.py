"""Adversarial tests — the rejection paths (VERDICT round-1 item 7).

The reference tests only happy paths (SURVEY.md §4 "gaps"); the protocol's
fault-tolerance story rests on the rejection paths actually rejecting:
`PedersenVSS::verify_share` detecting a malicious dealer (README.md:52-68,
keygen.rs:334-351), DVSS participants refusing bad shares
(keygen.rs:141-158), and every wire decoder refusing malformed bytes.
"""

import random

import pytest

from coconut_tpu.errors import DeserializationError, GeneralError
from coconut_tpu.ops import serialize as ser
from coconut_tpu.ops.curve import G1_GEN, G2_GEN, g1, g2
from coconut_tpu.ops.fields import R
from coconut_tpu.params import Params, SIGNATURES_IN_G1
from coconut_tpu.signature import Signature, Verkey
from coconut_tpu.sss import (
    PedersenDVSSParticipant,
    PedersenVSS,
    share_secret_dvss,
)

rng = random.Random(0xADC0)


@pytest.fixture(scope="module")
def gens():
    return PedersenVSS.gens(b"adversarial-test")


class TestPVSSRejection:
    def test_tampered_s_share_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(3, 5, g, h)
        sid = 2
        bad = ((s_shares[sid] + 1) % R, t_shares[sid])
        assert PedersenVSS.verify_share(3, sid, (s_shares[sid], t_shares[sid]), comms, g, h)
        assert not PedersenVSS.verify_share(3, sid, bad, comms, g, h)

    def test_tampered_t_share_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(2, 4, g, h)
        sid = 4
        bad = (s_shares[sid], (t_shares[sid] + R - 1) % R)
        assert not PedersenVSS.verify_share(2, sid, bad, comms, g, h)

    def test_tampered_commitment_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(3, 5, g, h)
        bad_comms = dict(comms)
        bad_comms[1] = PedersenVSS.ops.add(bad_comms[1], g)
        assert not PedersenVSS.verify_share(
            3, 1, (s_shares[1], t_shares[1]), bad_comms, g, h
        )

    def test_share_for_wrong_id_fails(self, gens):
        g, h = gens
        _, _, comms, s_shares, t_shares = PedersenVSS.deal(3, 5, g, h)
        # share evaluated at id 1 presented as id 2
        assert not PedersenVSS.verify_share(
            3, 2, (s_shares[1], t_shares[1]), comms, g, h
        )


class TestDVSSRejection:
    def test_received_bad_share_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        p2 = PedersenDVSSParticipant(2, 2, 3, g, h)
        bad = ((p1.s_shares[2] + 1) % R, p1.t_shares[2])
        with pytest.raises(GeneralError):
            p2.received_share(1, p1.comm_coeffs, bad, 2, 3, g, h)

    def test_received_own_share_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        with pytest.raises(GeneralError):
            p1.received_share(
                1, p1.comm_coeffs, (p1.s_shares[1], p1.t_shares[1]), 2, 3, g, h
            )

    def test_duplicate_share_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        p2 = PedersenDVSSParticipant(2, 2, 3, g, h)
        share = (p1.s_shares[2], p1.t_shares[2])
        p2.received_share(1, p1.comm_coeffs, share, 2, 3, g, h)
        with pytest.raises(GeneralError):
            p2.received_share(1, p1.comm_coeffs, share, 2, 3, g, h)

    def test_finalize_with_missing_shares_raises(self, gens):
        g, h = gens
        p1 = PedersenDVSSParticipant(1, 2, 3, g, h)
        with pytest.raises(GeneralError):
            p1.compute_final_comm_coeffs_and_shares(2, 3, g, h)

    def test_full_protocol_still_works(self, gens):
        g, h = gens
        participants = share_secret_dvss(2, 3, g, h)
        assert all(p.secret_share is not None for p in participants)


class TestWireFuzz:
    """Truncation, flag-bit corruption, and off-curve bytes must raise
    DeserializationError — never return garbage structs."""

    def test_g1_compressed_roundtrip_and_flags(self):
        p = g1.mul(G1_GEN, rng.randrange(1, R))
        b = ser.g1_to_compressed(p)
        assert ser.g1_from_compressed(b) == p
        # clear the compression flag bit
        bad = bytes([b[0] & 0x7F]) + b[1:]
        with pytest.raises(DeserializationError):
            ser.g1_from_compressed(bad)

    def test_g2_compressed_flags(self):
        p = g2.mul(G2_GEN, rng.randrange(1, R))
        b = ser.g2_to_compressed(p)
        assert ser.g2_from_compressed(b) == p
        bad = bytes([b[0] | 0x40]) + b[1:]  # infinity flag on non-zero body
        with pytest.raises(DeserializationError):
            ser.g2_from_compressed(bad)
        # y-sign flip is NOT an error — it decodes the negated point
        flipped = ser.g2_from_compressed(bytes([b[0] ^ 0x20]) + b[1:])
        assert flipped == g2.neg(p)

    @pytest.mark.parametrize("cut", [1, 10, 47])
    def test_truncated_g1_raises(self, cut):
        p = g1.mul(G1_GEN, rng.randrange(1, R))
        b = ser.g1_to_bytes(p)
        with pytest.raises(DeserializationError):
            ser.g1_from_bytes(b[:-cut])

    def test_off_curve_g1_raises(self):
        p = g1.mul(G1_GEN, rng.randrange(1, R))
        x, y = p
        bad = ser.fp_to_bytes(x) + ser.fp_to_bytes((y + 1) % (2**381))
        with pytest.raises(DeserializationError):
            ser.g1_from_bytes(bad)

    def test_truncated_signature_raises(self):
        ctx = SIGNATURES_IN_G1
        p = g1.mul(G1_GEN, 5)
        sig = Signature(p, g1.mul(G1_GEN, 7))
        b = sig.to_bytes(ctx)
        with pytest.raises(DeserializationError):
            Signature.from_bytes(b[:-3], ctx)

    def test_truncated_verkey_raises(self):
        ctx = SIGNATURES_IN_G1
        vk = Verkey(
            g2.mul(G2_GEN, 3), [g2.mul(G2_GEN, i + 2) for i in range(2)]
        )
        b = vk.to_bytes(ctx)
        with pytest.raises(DeserializationError):
            Verkey.from_bytes(b[:-1], ctx)

    def test_truncated_params_raises(self):
        # hand-built params avoid the slow hash-to-group setup
        ctx = SIGNATURES_IN_G1
        params = Params(
            g1.mul(G1_GEN, 11),
            g2.mul(G2_GEN, 13),
            [g1.mul(G1_GEN, 17)],
            ctx,
        )
        b = params.to_bytes()
        with pytest.raises(DeserializationError):
            Params.from_bytes(b[:-5], ctx)
