"""Encode-pipeline suite (ISSUE-3): static-operand cache, raw-wire device
Montgomery conversion, batched native hashing, and the verify_stream
prefetch worker.

Marker layout (this host pays MINUTES to trace/execute each new device
program shape, so the `pipeline` lane must stay lean):

  - `pipeline`-marked: host-only or small-jit tests — the fp-level
    Montgomery parity suite, the cache fingerprint/counter tests, the
    prefetch-worker suite, batched native hashing. `pytest -m pipeline`
    finishes in minutes.
  - unmarked (default suite only): tests that materialize comb-build /
    fused-kernel executions (`test_pad_lanes...`, `test_vk_swap...`) —
    correct but minutes-each; they ride the full suite where the shapes
    amortize across the process.
  - `heavy`-gated: the sharded pad-path end-to-end regression — it
    traces the (4,2)-mesh pjit program, multi-minute standalone, so it
    lives in ci.sh's heavy lane like every other at-scale shape.
"""

import json
import os
import random
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from coconut_tpu import metrics  # noqa: E402
from coconut_tpu.ops.fields import R  # noqa: E402
from coconut_tpu.params import GroupContext, Params  # noqa: E402
from coconut_tpu.signature import Sigkey, Signature, Verkey  # noqa: E402
from coconut_tpu.stream import verify_stream  # noqa: E402
from coconut_tpu.tpu import backend as tbe  # noqa: E402
from coconut_tpu.tpu import fp, limbs  # noqa: E402

pipeline = pytest.mark.pipeline

_heavy_skip = pytest.mark.skipif(
    os.environ.get("COCONUT_TEST_HEAVY") != "1",
    reason="multi-minute pjit trace on the 1-core CPU mesh; "
    "set COCONUT_TEST_HEAVY=1 (ci.sh heavy lane)",
)


def heavy(fn):
    return pytest.mark.heavy(_heavy_skip(fn))


VECDIR = os.path.join(os.path.dirname(__file__), "vectors")


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# --- device-side Montgomery conversion parity ------------------------------


def _parity_cases():
    P = limbs.P
    rng = random.Random(0xC0FFEE)
    xs = [0, 1, 2, P - 1, P - 2, (1 << 380) + 12345, 1 << 255]
    xs += [rng.randrange(P) for _ in range(64)]
    path = os.path.join(VECDIR, "fields.json")
    if os.path.exists(path):
        with open(path) as f:
            vec = json.load(f)
        for case in vec["fp_cases"]:
            xs += [
                int(case[k], 16) for k in ("a", "b", "add", "mul", "inv_a")
            ]
    return xs


@pipeline
class TestDeviceMontgomeryParity:
    """fp.to_mont(raw uint8 wire) must be bit-identical (same decoded
    field element) to the host Montgomery encode it replaces. Fp-level:
    the only jitted program is the Montgomery multiply itself."""

    def test_to_mont_matches_host_encode(self):
        xs = _parity_cases()
        raw = limbs.fp_encode_raw_batch(xs)
        assert raw.dtype == np.uint8
        assert raw.shape == (len(xs), limbs.RAW_BYTES)
        dev = limbs.fp_decode_batch(np.asarray(fp.to_mont(jnp.asarray(raw))))
        host = limbs.fp_decode_batch(limbs.fp_encode_batch(xs))
        want = [x % limbs.P for x in xs]
        assert dev == host == want

    def test_raw_wire_env_override_and_cpu_default(self, monkeypatch):
        monkeypatch.setenv("COCONUT_RAW_WIRE", "1")
        monkeypatch.setattr(tbe, "_RAW_WIRE", None)
        assert tbe._raw_wire_enabled() is True
        monkeypatch.setenv("COCONUT_RAW_WIRE", "0")
        monkeypatch.setattr(tbe, "_RAW_WIRE", None)
        assert tbe._raw_wire_enabled() is False
        monkeypatch.delenv("COCONUT_RAW_WIRE")
        monkeypatch.setattr(tbe, "_RAW_WIRE", None)
        # this suite runs on the CPU mesh: raw wire defaults OFF (the
        # conversion is platform-gated, not correctness-gated)
        assert tbe._raw_wire_enabled() is False
        # monkeypatch teardown leaves the module cache for a re-derive
        monkeypatch.setattr(tbe, "_RAW_WIRE", None)

    def _leaves_decode_equal(self, a_tree, b_tree):
        la = jax.tree_util.tree_leaves(tbe._pts_f32(a_tree))
        lb = jax.tree_util.tree_leaves(tbe._pts_f32(b_tree))
        assert len(la) == len(lb)
        for a, b in zip(la, lb):
            assert a.dtype == jnp.float32 and b.dtype == jnp.float32
            assert limbs.fp_decode_batch(
                np.asarray(a)
            ) == limbs.fp_decode_batch(np.asarray(b))

    def test_pts_f32_converts_raw_g1_wire(self, monkeypatch):
        from coconut_tpu.ops.curve import G1_GEN, g1

        rng = random.Random(11)
        pts = [g1.mul(G1_GEN, rng.randrange(1, R)) for _ in range(5)]
        pts.append(None)  # identity lane rides the inf mask
        monkeypatch.setattr(tbe, "_RAW_WIRE", True)
        (xr, yr), inf_r = tbe.JaxBackend._encode_g1_points(pts)
        assert xr.dtype == jnp.uint8 and xr.shape[-1] == limbs.RAW_BYTES
        monkeypatch.setattr(tbe, "_RAW_WIRE", False)
        (xi, yi), inf_i = tbe.JaxBackend._encode_g1_points(pts)
        assert xi.dtype == jnp.int16
        np.testing.assert_array_equal(np.asarray(inf_r), np.asarray(inf_i))
        self._leaves_decode_equal((xr, yr), (xi, yi))
        monkeypatch.setattr(tbe, "_RAW_WIRE", None)

    def test_pts_f32_converts_raw_g2_wire(self, monkeypatch):
        from coconut_tpu.ops.curve import G2_GEN, g2

        rng = random.Random(12)
        pts = [g2.mul(G2_GEN, rng.randrange(1, R)) for _ in range(3)]
        monkeypatch.setattr(tbe, "_RAW_WIRE", True)
        (xr, yr), inf_r = tbe.JaxBackend._encode_g2_points(pts)
        for leaf in jax.tree_util.tree_leaves((xr, yr)):
            assert leaf.dtype == jnp.uint8
        monkeypatch.setattr(tbe, "_RAW_WIRE", False)
        (xi, yi), inf_i = tbe.JaxBackend._encode_g2_points(pts)
        np.testing.assert_array_equal(np.asarray(inf_r), np.asarray(inf_i))
        self._leaves_decode_equal((xr, yr), (xi, yi))
        monkeypatch.setattr(tbe, "_RAW_WIRE", None)


# --- static-operand cache --------------------------------------------------


def _tiny_setup(label, seed, ctx_name="G1", nmsgs=2):
    rng = random.Random(seed)
    tiny = Params.new(1, label, ctx=GroupContext(ctx_name))
    sk = Sigkey(rng.randrange(1, R), [rng.randrange(1, R)])
    ops = tiny.ctx.other
    vk = Verkey(
        ops.mul(tiny.g_tilde, sk.x),
        [ops.mul(tiny.g_tilde, y) for y in sk.y],
    )
    msgs = [[rng.randrange(R)] for _ in range(nmsgs)]
    sigs = []
    for m in msgs:
        t = rng.randrange(1, R)
        s1 = tiny.ctx.sig.mul(tiny.g, t)
        expo = (sk.x + sum(y * mi for y, mi in zip(sk.y, m))) % R
        sigs.append(Signature(s1, tiny.ctx.sig.mul(s1, expo)))
    return tiny, sk, vk, sigs, msgs


def _cache_counts():
    return (
        metrics.get_count("encode_cache_hits"),
        metrics.get_count("encode_cache_misses"),
    )


class TestStaticOperandCache:
    @pipeline
    def test_fingerprint_separates_verkeys_and_params(self):
        _, _, vk1, _, _ = _tiny_setup(b"pipeline-fp-a", 0xA1)
        pa, _, vk2, _, _ = _tiny_setup(b"pipeline-fp-a", 0xA2)
        pb, _, _, _, _ = _tiny_setup(b"pipeline-fp-b", 0xA1)
        # two verkeys under the same params never share
        assert tbe._static_fingerprint(vk1, pa) != tbe._static_fingerprint(
            vk2, pa
        )
        # the SAME verkey under a different params context never shares
        # (g/g_tilde differ even though the vk bytes are identical)
        assert tbe._static_fingerprint(vk1, pa) != tbe._static_fingerprint(
            vk1, pb
        )
        # and the digest is deterministic
        assert tbe._static_fingerprint(vk1, pa) == tbe._static_fingerprint(
            vk1, pa
        )

    @pipeline
    def test_hit_reuses_tables_and_counts(self):
        tiny, _, vk, sigs, msgs = _tiny_setup(b"pipeline-cache", 0xB1)
        _, _, vk2, _, _ = _tiny_setup(b"pipeline-cache", 0xB2)
        be = tbe.JaxBackend()
        tbe._STATIC_CACHE.clear()
        h0, m0 = _cache_counts()
        o1 = be.encode_verify_batch(sigs, msgs, vk, tiny)
        h1, m1 = _cache_counts()
        assert (h1, m1) == (h0, m0 + 1)
        o2 = be.encode_verify_batch(sigs, msgs, vk, tiny)
        h2, m2 = _cache_counts()
        assert (h2, m2) == (h0 + 1, m0 + 1)
        # a hit serves the SAME device tables object — no rebuild at all
        assert o2[0] is o1[0]
        # a different verkey is a miss and must not share tables
        o3 = be.encode_verify_batch(sigs, msgs, vk2, tiny)
        _, m3 = _cache_counts()
        assert m3 == m0 + 2
        assert o3[0] is not o1[0]

    @pipeline
    def test_pad_variants_are_distinct_entries(self):
        tiny, _, vk, sigs, msgs = _tiny_setup(b"pipeline-pad-key", 0xB3)
        be = tbe.JaxBackend()
        tbe._STATIC_CACHE.clear()
        plain = be.encode_verify_batch(sigs, msgs, vk, tiny)
        padded = be.encode_verify_batch(sigs, msgs, vk, tiny, pad_bases_to=4)
        _, misses = _cache_counts()
        assert misses == 2  # pad_bases_to is part of the cache key
        assert np.asarray(plain[1]).shape[1] == 2
        assert np.asarray(padded[1]).shape[1] == 4

    def test_vk_swap_mid_process_rejects_forged(self):
        """A verifier that rotates verkeys in one process must reject
        credentials issued under the OLD key even when the new key's
        encode is cache-hot — stale cached tables would accept them.
        B=2/q=1 grouped: the same program shape test_backends' tiny
        soundness test compiles, so in a full-suite run this reuses the
        in-process jit (standalone it re-traces: minutes on this host —
        which is why it is NOT in the lean `pipeline` lane)."""
        from coconut_tpu.backend import get_backend

        tiny, _, vk1, sigs, msgs = _tiny_setup(b"pipeline-vkswap", 0xC1)
        rng = random.Random(0xC2)
        sk2 = Sigkey(rng.randrange(1, R), [rng.randrange(1, R)])
        ops = tiny.ctx.other
        vk2 = Verkey(
            ops.mul(tiny.g_tilde, sk2.x),
            [ops.mul(tiny.g_tilde, y) for y in sk2.y],
        )
        be = get_backend("jax")
        assert be.batch_verify_grouped(sigs, msgs, vk1, tiny) is True
        # swap: sigs are forgeries w.r.t. vk2 — the cached vk1 operands
        # must not leak into vk2's verify
        assert be.batch_verify_grouped(sigs, msgs, vk2, tiny) is False
        # swap back: cache-hot vk1 still accepts
        assert be.batch_verify_grouped(sigs, msgs, vk1, tiny) is True


# --- pad_bases_to regression (the sharded pad path) ------------------------


class TestPadBasesEncode:
    def test_pad_lanes_are_explicit_identity_and_zero_digits(self):
        tiny, _, vk, sigs, msgs = _tiny_setup(b"pipeline-pad", 0xD1)
        _, _, vk2, _, _ = _tiny_setup(b"pipeline-pad", 0xD2)
        be = tbe.JaxBackend()
        k = 1 + len(vk.Y_tilde)
        padded = be.encode_verify_batch(sigs, msgs, vk, tiny, pad_bases_to=4)
        plain = be.encode_verify_batch(sigs, msgs, vk, tiny)
        mag_p, sgn_p = np.asarray(padded[1]), np.asarray(padded[2])
        mag_u, sgn_u = np.asarray(plain[1]), np.asarray(plain[2])
        # pad scalars are exactly zero digits
        assert mag_p.shape[1] == 4 and not mag_p[:, k:].any()
        # real lanes are bit-identical to the unpadded encode
        np.testing.assert_array_equal(mag_p[:, :k], mag_u)
        np.testing.assert_array_equal(sgn_p[:, :k], sgn_u)
        for a, b in zip(
            jax.tree_util.tree_leaves(padded[0]),
            jax.tree_util.tree_leaves(plain[0]),
        ):
            a, b = np.asarray(a), np.asarray(b)
            assert a.shape[0] == 4 and b.shape[0] == k
            np.testing.assert_array_equal(a[:k], b)
        # pad table rows encode the identity EXPLICITLY, independent of
        # the bases: the same rows under a different verkey
        padded2 = be.encode_verify_batch(
            sigs, msgs, vk2, tiny, pad_bases_to=4
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(padded[0]),
            jax.tree_util.tree_leaves(padded2[0]),
        ):
            np.testing.assert_array_equal(np.asarray(a)[k:], np.asarray(b)[k:])

    @heavy
    def test_sharded_pad_path_cache_hot(self):
        """The consumer of pad_bases_to end to end: the dp+tp sharded
        per-credential verify pads k=7 up to 8 for the tp axis. Runs the
        EXACT program test_shard compiles (batch=4 on the (4,2) mesh,
        fixture8's shapes) twice — the second pass is static-cache-hot —
        and the forged lane must flip both times."""
        import __graft_entry__ as ge
        from coconut_tpu.ps import ps_verify
        from coconut_tpu.tpu.shard import batch_verify_sharded, default_mesh

        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh (conftest.py)")
        params, _, vk, sigs, msgs_list = ge._fixture(batch=8, seed=0x51A2D)
        sigs, msgs_list = list(sigs[:4]), msgs_list[:4]
        sigs[2] = Signature(
            sigs[2].sigma_1, params.ctx.sig.mul(sigs[2].sigma_2, 2)
        )
        mesh = default_mesh(ndp=4, ntp=2, devices=devices[:8])
        be = tbe.JaxBackend()
        want = [ps_verify(s, m, vk, params) for s, m in zip(sigs, msgs_list)]
        assert want == [True, True, False, True]
        cold = batch_verify_sharded(be, sigs, msgs_list, vk, params, mesh)
        h0, _ = _cache_counts()
        hot = batch_verify_sharded(be, sigs, msgs_list, vk, params, mesh)
        h1, _ = _cache_counts()
        assert cold == hot == want
        assert h1 > h0  # the second pass served cached padded tables


# --- batched native hashing ------------------------------------------------


@pipeline
def test_native_batched_hash_matches_per_message():
    from coconut_tpu import native
    from coconut_tpu.params import SIGNATURES_IN_G1

    if not native.available():
        pytest.skip("native library unavailable")
    msgs = [b"", b"a", b"pipeline" * 40, bytes(range(33)), b"\x00" * 7]
    got = native.hash_to_g1_batch(msgs)
    assert got == [native.hash_to_g1(m) for m in msgs]
    # and both match the Python spec (the same DST wiring)
    assert got == [SIGNATURES_IN_G1.hash_to_sig(m) for m in msgs]
    assert native.hash_to_g1_batch([]) == []


# --- verify_stream prefetch worker -----------------------------------------

BATCH = 3


def _stub_source(calls=None):
    def source(i):
        if calls is not None:
            calls.append(i)
        sigs = [
            SimpleNamespace(sigma_1=1, sigma_2=1, ok=True)
            for _ in range(BATCH)
        ]
        return sigs, [[0]] * BATCH

    return source


class _AsyncStub:
    """Async-capable fake recording dispatch/settle interleave."""

    def __init__(self):
        self.events = []

    def batch_verify_async(self, sigs, msgs, vk, params):
        i = len([e for e in self.events if e[0] == "dispatch"])
        self.events.append(("dispatch", i))

        def fin():
            self.events.append(("settle", i))
            return [bool(s.ok) for s in sigs]

        return fin


def _no_prefetch_threads():
    return not any(
        t.name == "coconut-encode-prefetch" and t.is_alive()
        for t in threading.enumerate()
    )


@pipeline
class TestPrefetchWorker:
    def test_order_counts_and_occupancy_metrics(self, tmp_path):
        calls = []
        seen = []
        bk = _AsyncStub()
        state = verify_stream(
            _stub_source(calls),
            6,
            None,
            None,
            bk,
            state_path=str(tmp_path / "s.json"),
            on_batch=lambda i, r: seen.append(i),
            pipeline_depth=2,
            prefetch_depth=2,
        )
        assert state.verified == 6 * BATCH and state.failed == 0
        # the worker produces sequentially: every batch sourced exactly
        # once, in order, and results settle in order
        assert calls == list(range(6))
        assert seen == list(range(6))
        settles = [i for kind, i in bk.events if kind == "settle"]
        assert settles == list(range(6))
        assert metrics.get_count("prefetched_batches") == 6
        # the occupancy denominator exists (main-thread queue wait)
        assert "prefetch_wait" in metrics.snapshot()["timers_s"]

    def test_depth_zero_disables_worker(self):
        bk = _AsyncStub()
        state = verify_stream(
            _stub_source(), 4, None, None, bk, prefetch_depth=0
        )
        assert state.verified == 4 * BATCH
        assert metrics.get_count("prefetched_batches") == 0

    def test_negative_depth_rejected(self):
        with pytest.raises(ValueError, match="prefetch_depth"):
            verify_stream(
                _stub_source(), 1, None, None, _AsyncStub(), prefetch_depth=-1
            )

    def test_source_exception_propagates_and_worker_stops(self):
        def bad_source(i):
            if i == 2:
                raise ValueError("source exploded")
            return _stub_source()(i)

        with pytest.raises(ValueError, match="source exploded"):
            verify_stream(
                bad_source, 5, None, None, _AsyncStub(), prefetch_depth=2
            )
        deadline = time.monotonic() + 5.0
        while not _no_prefetch_threads():
            assert time.monotonic() < deadline, "prefetch worker leaked"
            time.sleep(0.01)

    def test_prefetch_composes_with_retry_and_fallback(self):
        from coconut_tpu.faults import FaultyBackend
        from coconut_tpu.retry import RetryPolicy

        faulty = FaultyBackend(_AsyncStub(), corrupt_finalizer_on={1})
        state = verify_stream(
            _stub_source(),
            4,
            None,
            None,
            faulty,
            retry_policy=RetryPolicy(max_attempts=3, base_delay=0.0),
            pipeline_depth=2,
            prefetch_depth=2,
        )
        assert state.verified == 4 * BATCH and state.failed == 0
        assert metrics.get_count("retries") == 1

    def test_prefetch_checkpoint_resume(self, tmp_path):
        path = str(tmp_path / "stream.json")
        verify_stream(
            _stub_source(), 2, None, None, _AsyncStub(),
            state_path=path, prefetch_depth=2,
        )
        calls = []
        state = verify_stream(
            _stub_source(calls), 4, None, None, _AsyncStub(),
            state_path=path, prefetch_depth=2,
        )
        # resume starts the WORKER at the checkpoint, not at zero
        assert calls == [2, 3]
        assert state.verified == 4 * BATCH and state.next_batch == 4

    def test_settle_failure_abandons_worker_cleanly(self):
        """A non-retryable settle error propagates while the worker may
        be blocked mid-put; the generator teardown must stop and join it
        (no leaked thread, no deadlock)."""

        class DiesOnSettle:
            def batch_verify_async(self, sigs, msgs, vk, params):
                def fin():
                    raise RuntimeError("readback wedged")

                return fin

        with pytest.raises(RuntimeError, match="readback wedged"):
            verify_stream(
                _stub_source(),
                8,
                None,
                None,
                DiesOnSettle(),
                pipeline_depth=1,
                prefetch_depth=2,
            )
        deadline = time.monotonic() + 5.0
        while not _no_prefetch_threads():
            assert time.monotonic() < deadline, "prefetch worker leaked"
            time.sleep(0.01)
