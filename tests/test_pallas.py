"""CPU-runnable differential coverage for the Pallas Montgomery kernel.

The fused TPU multiply (tpu/pallas_fp.py) is normally exercised only on a
real chip (fp.mul routes to it when jax.default_backend() == "tpu"), so a
bound error in its Karatsuba assembly or carry pipeline would merge green
and surface only as wrong verify bits at bench time (ADVICE r4). These
tests execute the exact kernel logic on the CPU suite's backend:

  - the lifted `_school_vpu` limb product (Karatsuba on vs off) over
    random and adversarial all-limbs-±132 inputs — exact coefficient
    equality, since every coefficient is an exact f32 integer;
  - the full `_mul_kernel` via the Pallas interpreter
    (pl.pallas_call(..., interpret=True)) against the XLA fp.mul path —
    bit-identical limbs, and value-identical decode against the Python
    spec (ops/fields.py).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from coconut_tpu.ops.fields import P
from coconut_tpu.tpu import fp
from coconut_tpu.tpu import pallas_fp
from coconut_tpu.tpu.limbs import (
    MONT_R,
    NLIMBS,
    balanced_limbs_batch,
    fp_decode_batch,
)

_rng = np.random.default_rng(0xC0C0)


def _rand_normalized(n):
    """[n, 52] f32 limbs in the NORMALIZED class (|v| <= 132)."""
    return _rng.integers(-132, 133, size=(n, NLIMBS)).astype(np.float32)


def _transpose_lanes(a):
    return jnp.asarray(a.T)  # kernel layout: [limbs, lanes]


class TestSchoolVpu:
    """_school_vpu: Karatsuba assembly vs the plain comb schoolbook."""

    @pytest.mark.parametrize("n", [1, 7, 64])
    @pytest.mark.parametrize("levels", [1, 2])
    def test_karatsuba_matches_plain_comb_random(self, n, levels):
        x = _transpose_lanes(_rand_normalized(n))
        y = _transpose_lanes(_rand_normalized(n))
        plain = pallas_fp._school_vpu(x, y, pallas_fp._OUT2, karatsuba=0)
        kara = pallas_fp._school_vpu(x, y, pallas_fp._OUT2, karatsuba=levels)
        np.testing.assert_array_equal(np.asarray(plain), np.asarray(kara))

    def test_karatsuba_matches_at_adversarial_extremes(self):
        # all-limbs at the normalized bound, both signs, and mixed-sign
        # worst cases for the (x0+x1)(y0+y1) middle product
        rows = np.array(
            [
                np.full(NLIMBS, 132.0),
                np.full(NLIMBS, -132.0),
                np.tile([132.0, -132.0], NLIMBS // 2),
                np.concatenate(
                    [np.full(NLIMBS // 2, 132.0), np.full(NLIMBS // 2, -132.0)]
                ),
            ],
            dtype=np.float32,
        )
        for xi in range(len(rows)):
            for yi in range(len(rows)):
                x = _transpose_lanes(rows[xi : xi + 1])
                y = _transpose_lanes(rows[yi : yi + 1])
                plain = pallas_fp._school_vpu(
                    x, y, pallas_fp._OUT2, karatsuba=0
                )
                for levels in (1, 2):
                    kara = pallas_fp._school_vpu(
                        x, y, pallas_fp._OUT2, karatsuba=levels
                    )
                    np.testing.assert_array_equal(
                        np.asarray(plain), np.asarray(kara)
                    )

    def test_coefficients_match_python_bignum(self):
        # ground truth: exact integer polynomial product
        x = _rand_normalized(4)
        y = _rand_normalized(4)
        out = np.asarray(
            pallas_fp._school_vpu(
                _transpose_lanes(x), _transpose_lanes(y), pallas_fp._OUT2
            )
        ).T
        for lane in range(4):
            want = np.zeros(pallas_fp._OUT2)
            for i in range(NLIMBS):
                for j in range(NLIMBS):
                    want[i + j] += x[lane, i] * y[lane, j]
            np.testing.assert_array_equal(out[lane], want)


class TestInterpretedKernel:
    """Full _mul_kernel through the Pallas interpreter on the CPU backend."""

    def _mul_interpret(self, a, b):
        return np.asarray(pallas_fp.mul(jnp.asarray(a), jnp.asarray(b), interpret=True))

    def test_bit_identical_to_xla_path_random(self):
        vals = [int(_rng.integers(0, 2**63)) * P // 2**63 for _ in range(8)]
        vals += [0, 1, P - 1, P // 2]
        a = balanced_limbs_batch([v * MONT_R % P for v in vals])
        b = balanced_limbs_batch([(v * 7 + 3) % P * MONT_R % P for v in vals])
        got = self._mul_interpret(a, b)
        want = np.asarray(fp.mul(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(got, want)

    def test_lazy_inputs_decode_to_spec_product(self):
        # LAZY inputs: sums of normalized values (the hot-path shape).
        # Montgomery: mul(aR, bR) = abR mod p, so decode gives a*b mod p.
        ints = [int(_rng.integers(1, 2**60)) % P for _ in range(6)]
        am = [v * MONT_R % P for v in ints]
        bm = [(v * v + 5) % P * MONT_R % P for v in ints]
        a = balanced_limbs_batch(am) * 3.0  # lazy: 3x a normalized value
        b = balanced_limbs_batch(bm) - balanced_limbs_batch(am)
        got = fp_decode_batch(self._mul_interpret(a, b))
        # inputs were (3a)R and (b-a)R; the product decodes to 3a(b-a) mod p
        for g, ai, bi in zip(got, ints, [(v * v + 5) % P for v in ints]):
            assert g == 3 * ai % P * ((bi - ai) % P) % P

    def test_all_limbs_at_lazy_extreme(self):
        # adversarial: every limb at +/- a large lazy magnitude (vacant top
        # two limbs preserved, as the element classes require)
        a = np.full((2, NLIMBS), 1024.0, dtype=np.float32)
        a[:, -2:] = 0.0
        a[1] = -a[1]
        b = np.full((2, NLIMBS), -1024.0, dtype=np.float32)
        b[:, -2:] = 0.0
        got = self._mul_interpret(a, b)
        want = np.asarray(fp.mul(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(got, want)
        assert np.abs(got).max() <= 132  # NORMALIZED output class


class TestPackCanon48:
    """fp.pack_canon48 + the uint8 decode path: exact round-trip."""

    def test_roundtrip_extremes(self):
        import jax

        from coconut_tpu.tpu.limbs import balanced_limbs_batch

        # representatives with negative values and extreme limbs: scale
        # balanced encodings by +/-3 (lazy class, |value| < 2p after the
        # 3x of a < 0.66p... use values < p/2 to stay inside the bound)
        ints = [0, 1, P - 1, P // 2, 12345, (P - 5) // 3]
        mont = [v * MONT_R % P for v in ints]
        base = balanced_limbs_batch(mont)
        cases = {
            "plain": (base, 1),
            "neg": (-base, -1),
        }
        for name, (arr, sign) in cases.items():
            packed = jax.jit(fp.pack_canon48)(jnp.asarray(arr))
            got = fp_decode_batch(np.asarray(packed))
            for g, v in zip(got, ints):
                assert g == (sign * v) % P, name

    def test_lazy_combination_roundtrip(self):
        import jax

        from coconut_tpu.tpu.limbs import balanced_limbs_batch

        a = [v % P for v in (7, P - 3, 2**200)]
        b = [v % P for v in (P - 1, 5, 2**380)]
        ea = balanced_limbs_batch([v * MONT_R % P for v in a])
        eb = balanced_limbs_batch([v * MONT_R % P for v in b])
        lazy = ea - eb  # 2-term lazy combination, possibly negative
        packed = jax.jit(fp.pack_canon48)(jnp.asarray(lazy))
        got = fp_decode_batch(np.asarray(packed))
        for g, ai, bi in zip(got, a, b):
            assert g == (ai - bi) % P
