"""Observability suite (ISSUE 6): request-scoped tracing, Perfetto
export, the fault flight recorder, and the metrics percentile edge cases.

Economics mirror tests/test_serve.py: stub backends, injected clocks,
zero real sleeps — span durations are proven by ADVANCING a fake clock.
Every test that enables tracing does so through the `_traced` fixture so
the global tracer never leaks into other suites (tracing must stay a
zero-cost no-op everywhere else)."""

import json
import os
import sys
import threading
from types import SimpleNamespace

import pytest

from coconut_tpu import metrics
from coconut_tpu.faults import DeadLetterLog, FaultyBackend
from coconut_tpu.obs import export as oexport
from coconut_tpu.obs import flight as oflight
from coconut_tpu.obs import trace as otrace
from coconut_tpu.retry import RetryPolicy, call_with_retry
from coconut_tpu.serve.batcher import Batcher, demux, fail_all
from coconut_tpu.serve.queue import RequestQueue
from coconut_tpu.serve.service import CredentialService
from coconut_tpu.stream import verify_stream

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "probes")
)
import probe_trace  # noqa: E402  (the CI validator doubles as a test helper)

pytestmark = pytest.mark.obs


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _cred(ok=True):
    return SimpleNamespace(sigma_1=1, sigma_2=1, ok=ok)


class StubGrouped:
    def batch_verify_grouped(self, sigs, msgs, vk, params):
        return all(s.sigma_1 is not None and getattr(s, "ok", False) for s in sigs)


class StubPerCred:
    def batch_verify(self, sigs, msgs, vk, params):
        return [
            s.sigma_1 is not None and bool(getattr(s, "ok", False)) for s in sigs
        ]


@pytest.fixture(autouse=True)
def _clean_state():
    otrace.disable()
    metrics.reset()
    yield
    otrace.disable()
    metrics.reset()


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def _traced(clock):
    """Tracing enabled on a fake clock; yields the tracer."""
    yield otrace.enable(clock=clock, ring=256)


# --- zero-cost no-op path --------------------------------------------------


def test_disabled_returns_shared_noop_singleton():
    assert not otrace.enabled() and otrace.get_tracer() is None
    s = otrace.span("x", attr=1)
    assert s is otrace.NOOP and s is otrace.start_span("y")
    with s as inner:
        assert inner is otrace.NOOP
        assert otrace.current() is None
    s.set(a=1).event("e").end()
    otrace.event("nothing")  # no active span, no tracer: silent
    assert otrace.NOOP.trace_id is None and not otrace.NOOP


def test_disabled_path_never_allocates_a_span(monkeypatch):
    """The no-op path must not even construct a Span: poison the class
    and walk every entry point."""

    def boom(*a, **k):
        raise AssertionError("Span allocated while tracing disabled")

    monkeypatch.setattr(otrace, "Span", boom)
    with otrace.span("a"):
        otrace.event("e", k=1)
    otrace.start_span("b", root=True)
    otrace.end_span(otrace.NOOP)
    with otrace.use(otrace.NOOP):
        pass


def test_disabled_pool_dispatch_path_never_allocates_a_span(monkeypatch):
    """ISSUE 8 extension of the poison walk: a full submit -> place ->
    per-device dispatch -> settle -> demux cycle through the dispatcher
    POOL (two executors) allocates zero Spans while tracing is off. A
    poisoned allocation would crash an executor loop, sweep the futures
    with the AssertionError, and fail the result() asserts below."""

    def boom(*a, **k):
        raise AssertionError("Span allocated while tracing disabled")

    monkeypatch.setattr(otrace, "Span", boom)
    svc = CredentialService(StubPerCred(), None, None, max_batch=2, devices=2)
    with svc:
        futs = [svc.submit(_cred(), [0]) for _ in range(6)]
        assert all(f.result(10.0) for f in futs)


def test_env_flag_parse():
    for off in (None, "", "0", "false", "OFF", "no"):
        assert not otrace._env_enabled(off)
    for on in ("1", "jsonl", "true", "chrome"):
        assert otrace._env_enabled(on)


def test_disabled_serve_path_untouched():
    """With tracing off the serve path still works and futures carry a
    null trace_id."""
    svc = CredentialService(StubPerCred(), None, None, max_batch=2)
    with svc:
        f = svc.submit(_cred(), [0])
        assert f.result(10.0) is True
    assert f.trace_id is None


# --- span mechanics --------------------------------------------------------


def test_nesting_ids_and_contextvar(_traced):
    with otrace.span("a") as a:
        assert otrace.current() is a
        with otrace.span("b") as b:
            assert otrace.current() is b
            assert b.parent_id == a.span_id
            assert b.trace_id == a.trace_id
        assert otrace.current() is a
    assert otrace.current() is None
    assert a.parent_id is None and a.span_id != b.span_id


def test_root_forces_new_trace(_traced):
    with otrace.span("outer") as outer:
        inner = otrace.start_span("batch", root=True)
        assert inner.trace_id != outer.trace_id and inner.parent_id is None
        inner.end()


def test_exact_durations_with_fake_clock(_traced, clock):
    s = otrace.start_span("work")
    clock.advance(2.5)
    s.end()
    assert s.dur == 2.5
    assert s.t0 == 0.0 and s.t1 == 2.5


def test_end_is_idempotent_first_wins(_traced, clock):
    s = otrace.start_span("once")
    clock.advance(1.0)
    s.end(verdict=True)
    clock.advance(5.0)
    s.end(verdict=False)
    assert s.dur == 1.0 and s.attrs["verdict"] is True


def test_events_timestamped_on_fake_clock(_traced, clock):
    with otrace.span("s") as s:
        clock.advance(0.25)
        otrace.event("retry", attempt=1)
        clock.advance(0.25)
        s.event("split", lo=0, hi=4)
    assert s.events == [
        {"ts": 0.25, "name": "retry", "attempt": 1},
        {"ts": 0.5, "name": "split", "lo": 0, "hi": 4},
    ]


def test_use_activates_without_owning_lifetime(_traced):
    s = otrace.start_span("handoff")
    with otrace.use(s):
        assert otrace.current() is s
        with otrace.span("child") as c:
            assert c.parent_id == s.span_id
    assert otrace.current() is None
    assert s.t1 is None  # use() never ends the span
    s.end()


def test_error_attr_recorded_on_raise(_traced):
    with pytest.raises(RuntimeError):
        with otrace.span("bad") as s:
            raise RuntimeError("boom")
    assert s.attrs["error"] == "RuntimeError" and s.t1 is not None


def test_ring_buffer_bounded(clock):
    tracer = otrace.enable(clock=clock, ring=8)
    for i in range(20):
        tracer.start("s%d" % i).end()
    tail = tracer.tail()
    assert len(tail) == 8
    assert [s.name for s in tail] == ["s%d" % i for i in range(12, 20)]
    assert tracer.tail(3) == tail[-3:]


def test_cross_thread_start_and_end(_traced):
    s = otrace.start_span("xthread", root=True)
    t = threading.Thread(target=lambda: s.end(done=True))
    t.start()
    t.join()
    assert s.t1 is not None and s in _traced.tail()


def test_spans_for_follows_batch_link(_traced):
    req = otrace.start_span("request", root=True)
    batch = otrace.start_span("batch", root=True)
    req.set(batch_trace=batch.trace_id)
    child = otrace.start_span("device", parent=batch)
    child.end()
    batch.end()
    req.end()
    names = {s.name for s in _traced.spans_for(req.trace_id)}
    assert names == {"request", "batch", "device"}
    # live spans included: a still-open span of the trace is in the tree
    live = otrace.start_span("queue_wait", parent=req)
    assert live in _traced.spans_for(req.trace_id)


def test_stage_summary_in_metrics_snapshot(_traced, clock):
    with otrace.span("device"):
        clock.advance(2.0)
    with otrace.span("device"):
        clock.advance(1.0)
    stages = metrics.snapshot()["trace_stages"]
    assert stages["device"] == {"count": 2, "total_s": 3.0, "mean_s": 1.5}
    otrace.disable()
    assert "trace_stages" not in metrics.snapshot()


def test_reenable_replaces_tracer(clock):
    t1 = otrace.enable(clock=clock)
    t1.start("old").end()
    t2 = otrace.enable(clock=clock)
    assert t2 is not t1 and t2.tail() == []


# --- export ----------------------------------------------------------------


def test_chrome_export_structure_and_validation(tmp_path, _traced, clock):
    with otrace.span("request") as r:
        clock.advance(0.001)
        with otrace.span("queue_wait"):
            clock.advance(0.002)
            otrace.event("retry", attempt=1)
        with otrace.span("dispatch"):
            clock.advance(0.003)
        clock.advance(0.001)
    path = str(tmp_path / "trace.json")
    n = oexport.export_chrome(path)
    doc = json.load(open(path))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert n == len(doc["traceEvents"]) == 4 and len(xs) == 3
    by_name = {e["name"]: e for e in xs}
    # microsecond denomination, exact on the fake clock
    assert by_name["queue_wait"]["dur"] == pytest.approx(2000.0)
    assert by_name["request"]["dur"] == pytest.approx(7000.0)
    assert by_name["request"]["args"]["span_id"] == r.span_id
    assert by_name["queue_wait"]["args"]["parent_id"] == r.span_id
    assert instants[0]["name"] == "queue_wait.retry"
    assert instants[0]["s"] == "t"
    ts = [e["ts"] for e in doc["traceEvents"]]
    assert ts == sorted(ts)
    stats = probe_trace.validate(path)
    assert stats["spans"] == 3 and stats["nested"] == 2


def test_chrome_export_skips_live_spans(tmp_path, _traced):
    otrace.start_span("live", root=True)
    otrace.start_span("done", root=True).end()
    path = str(tmp_path / "t.json")
    oexport.write_chrome(_traced.tail() + _traced.live_snapshot(), path)
    names = [e["name"] for e in json.load(open(path))["traceEvents"]]
    assert names == ["done"]


def test_jsonl_export_roundtrip(tmp_path, _traced, clock):
    with otrace.span("a", k="v"):
        clock.advance(1.0)
        otrace.event("e", n=1)
    path = str(tmp_path / "spans.jsonl")
    assert oexport.export_jsonl(path) == 1
    (rec,) = oexport.read_jsonl(path)
    assert rec["name"] == "a" and rec["dur"] == 1.0
    assert rec["attrs"] == {"k": "v"}
    assert rec["events"] == [{"ts": 1.0, "name": "e", "n": 1}]


def test_probe_rejects_non_monotonic_and_escaping_children(tmp_path):
    bad = {
        "traceEvents": [
            {"name": "a", "ph": "X", "ts": 10.0, "dur": 1.0, "pid": 1, "tid": 1},
            {"name": "b", "ph": "X", "ts": 5.0, "dur": 1.0, "pid": 1, "tid": 1},
        ]
    }
    p = str(tmp_path / "bad.json")
    json.dump(bad, open(p, "w"))
    with pytest.raises(AssertionError, match="monotonic"):
        probe_trace.validate(p)
    escape = {
        "traceEvents": [
            {
                "name": "parent",
                "ph": "X",
                "ts": 0.0,
                "dur": 5.0,
                "pid": 1,
                "tid": 1,
                "args": {"span_id": 1, "parent_id": None},
            },
            {
                "name": "child",
                "ph": "X",
                "ts": 4.0,
                "dur": 50.0,
                "pid": 1,
                "tid": 1,
                "args": {"span_id": 2, "parent_id": 1},
            },
        ]
    }
    json.dump(escape, open(p, "w"))
    with pytest.raises(AssertionError, match="escapes parent"):
        probe_trace.validate(p)


# --- serve-path instrumentation --------------------------------------------


def test_admission_starts_trace_and_stamps_future(_traced, clock):
    q = RequestQueue(max_depth=4, clock=clock)
    fut = q.submit(_cred(), [0], lane="bulk")
    assert fut.trace_id is not None
    (req,) = q._lanes["bulk"]
    assert req.span.trace_id == fut.trace_id
    assert req.span.attrs["lane"] == "bulk"
    assert req.queue_span.parent_id == req.span.span_id
    # queue_wait ends with exactly the coalescing delay on the fake clock
    clock.advance(0.75)
    batcher = Batcher(q, max_batch=1, clock=clock)
    (popped,) = batcher.next_batch(block=False)
    assert popped.queue_span.dur == 0.75


def test_rejected_submission_allocates_no_trace(_traced):
    from coconut_tpu.errors import ServiceOverloadedError

    q = RequestQueue(max_depth=1, clock=FakeClock())
    q.submit(_cred(), [0])
    before = len(_traced.live_snapshot())
    with pytest.raises(ServiceOverloadedError):
        q.submit(_cred(), [0])
    assert len(_traced.live_snapshot()) == before


def test_demux_ends_request_span_with_verdict(_traced, clock):
    q = RequestQueue(max_depth=4, clock=clock)
    futs = [q.submit(_cred(), [0]) for _ in range(2)]
    reqs = Batcher(q, max_batch=2, clock=clock).next_batch(block=False)
    demux(reqs, [True, False], clock=clock)
    assert [r.span.attrs["verdict"] for r in reqs] == [True, False]
    assert all(r.span.t1 is not None for r in reqs)
    assert [f.result(0) for f in futs] == [True, False]


def test_fail_all_ends_spans_with_error(_traced, clock):
    q = RequestQueue(max_depth=4, clock=clock)
    q.submit(_cred(), [0])
    reqs = q.drain_pending()
    fail_all(reqs, RuntimeError("swept"))
    (req,) = reqs
    assert req.span.attrs["error"] == "RuntimeError"
    assert req.span.t1 is not None and req.queue_span.t1 is not None


def test_serve_request_span_tree_retry_and_bisection(_traced, clock, tmp_path):
    """The satellite: exact nesting + durations for a serve request that
    survives one retry and one bisection split — fake clock, zero real
    sleeps, supervisor loop driven synchronously."""
    dlq = str(tmp_path / "dead.jsonl")
    backend = FaultyBackend(StubGrouped(), raise_on={0})
    svc = CredentialService(
        backend,
        None,
        None,
        mode="grouped",
        max_batch=4,
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.0),
        dead_letter_path=dlq,
        clock=clock,
    )
    futs = [svc.submit(_cred(ok=(i != 2)), [0]) for i in range(4)]
    clock.advance(1.0)  # queue wait before the batch is popped
    batch = svc._batcher.next_batch(block=False)
    launched = svc._launch(batch)
    svc._settle(*launched)
    assert [f.result(0) for f in futs] == [True, True, False, True]

    victim = futs[2]
    spans = {s.name: s for s in _traced.spans_for(victim.trace_id)}
    # exact nesting: request -> queue_wait; batch -> coalesce/dispatch/
    # device -> bisect under device's retry ladder context
    req_span = spans["request"]
    assert spans["queue_wait"].parent_id == req_span.span_id
    assert spans["queue_wait"].dur == 1.0
    bspan = spans["batch"]
    assert req_span.attrs["batch_trace"] == bspan.trace_id
    assert bspan.attrs["members"][2] == victim.trace_id
    for stage in ("coalesce", "dispatch", "demux"):
        assert spans[stage].parent_id == bspan.span_id, stage
    assert spans["device"].parent_id == bspan.span_id
    assert spans["bisect"].parent_id == bspan.span_id
    # fake clock never advanced during the batch: stage durs exactly 0
    assert spans["dispatch"].dur == 0.0 and spans["device"].dur == 0.0
    # one retry (injected dispatch fault), then success
    assert [e["name"] for e in spans["dispatch"].events] == ["attempt_failed"]
    retry_events = [e for e in spans["device"].events if e["name"] == "retry"]
    assert len(retry_events) == 1 and retry_events[0]["attempt"] == 2
    # bisection: splits recorded, culprit dead-lettered onto ITS span
    splits = [e for e in spans["bisect"].events if e["name"] == "split"]
    assert splits and splits[0] == {"ts": clock.t, "name": "split", "lo": 0, "hi": 4}
    assert [e["name"] for e in req_span.events] == ["dead_letter"]
    assert req_span.attrs["verdict"] is False
    assert bspan.attrs["result"] == "bisected"
    # the dead-lettered request's span tree names the device that rejected
    # it and which side of the placement policy its batch took (ISSUE 8)
    assert bspan.attrs["device"] == "0"
    assert bspan.attrs["placement"] == "single"
    assert spans["dispatch"].attrs["device"] == "0"
    assert spans["device"].attrs["device"] == "0"
    # dead-letter line joins back on the victim's trace_id
    (rec,) = DeadLetterLog.read(dlq)
    assert rec["trace_id"] == victim.trace_id and rec["schema"] == 3
    assert rec["program"] == "verify"
    # flight record rides next to the dead-letter log with the full tree
    (flight,) = oflight.read(dlq)
    assert flight["trace_id"] == victim.trace_id
    assert {s["name"] for s in flight["tree"]} >= {
        "request",
        "queue_wait",
        "batch",
        "coalesce",
        "dispatch",
        "device",
        "bisect",
    }


def test_threaded_serve_smoke_produces_valid_chrome_trace(tmp_path):
    """Real supervisor thread + real clock: spans land, export validates,
    loadgen-style stage breakdown shows up in metrics.snapshot()."""
    otrace.enable(ring=256)
    svc = CredentialService(StubPerCred(), None, None, max_batch=2)
    with svc:
        futs = [svc.submit(_cred(), [0]) for _ in range(4)]
        assert all(f.result(10.0) for f in futs)
    path = str(tmp_path / "serve_trace.json")
    assert oexport.export_chrome(path) > 0
    probe_trace.validate(path)
    stages = metrics.snapshot()["trace_stages"]
    for stage in ("request", "queue_wait", "batch", "dispatch", "device"):
        assert stages[stage]["count"] > 0, stage


# --- stream-path instrumentation -------------------------------------------


def test_stream_batch_spans_and_checkpoint_events(_traced, tmp_path):
    state = verify_stream(
        lambda i: ([_cred() for _ in range(4)], [[0]] * 4),
        3,
        None,
        None,
        StubGrouped(),
        mode="grouped",
        state_path=str(tmp_path / "state.json"),
    )
    assert state.batches_ok == 3
    batches = [s for s in _traced.tail() if s.name == "stream_batch"]
    assert [s.attrs["batch"] for s in batches] == [0, 1, 2]
    for s in batches:
        assert s.attrs["ok"] is True
        assert [e["name"] for e in s.events] == ["checkpoint"]
        kids = {
            k.name
            for k in _traced.tail()
            if k.parent_id == s.span_id and k.trace_id == s.trace_id
        }
        assert kids == {"dispatch", "device"}


def test_checkpoint_quarantine_writes_flight_record(_traced, tmp_path):
    from coconut_tpu.stream import StreamState

    path = str(tmp_path / "state.json")
    with open(path, "w") as f:
        f.write("{ corrupt")
    st = StreamState(path)
    assert st.quarantined is not None
    (rec,) = oflight.read(path)
    assert rec["reason"] == "checkpoint_quarantine"
    assert rec["quarantined_to"] == st.quarantined


def test_flight_recorder_noop_when_disabled(tmp_path):
    dlq = str(tmp_path / "dead.jsonl")
    DeadLetterLog(dlq).append(batch=0, credential=1, reason="r")
    assert not os.path.exists(oflight.flight_path(dlq))
    assert oflight.record(dlq, "dead_letter") is None


def test_flight_record_includes_recent_tail(_traced, tmp_path):
    for i in range(10):
        otrace.start_span("work%d" % i, root=True).end()
    base = str(tmp_path / "x.jsonl")
    rec = oflight.record(base, "dead_letter", trace_id=None, last_n=4)
    assert rec is not None and len(rec["recent"]) == 4
    assert rec["tree"] == [] and rec["schema"] == 1
    assert oflight.read(base)[0]["reason"] == "dead_letter"


# --- retry ladder events ---------------------------------------------------


def test_call_with_retry_narrates_onto_active_span(_traced):
    from coconut_tpu.errors import TransientBackendError

    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise TransientBackendError("hiccup %d" % calls[0])
        return "ok"

    policy = RetryPolicy(max_attempts=3, base_delay=0.0, sleep=lambda s: None)
    with otrace.span("device") as s:
        assert call_with_retry(flaky, policy, key=7) == "ok"
    names = [e["name"] for e in s.events]
    assert names == ["attempt_failed", "retry", "attempt_failed", "retry"]


def test_fallback_event_recorded(_traced):
    from coconut_tpu.errors import TransientBackendError

    def always_bad():
        raise TransientBackendError("dead")

    policy = RetryPolicy(max_attempts=2, base_delay=0.0, sleep=lambda s: None)
    with otrace.span("device") as s:
        out = call_with_retry(always_bad, policy, fallback=lambda: "degraded")
    assert out == "degraded"
    assert [e["name"] for e in s.events][-1] == "fallback"


# --- metrics percentile edge cases (satellite bugfix) -----------------------


def test_percentile_empty_is_none():
    assert metrics.percentile([], 50) is None
    assert metrics.percentile([], 0) is None
    assert metrics.percentile([], 100) is None


def test_percentile_single_sample_for_every_q():
    for q in (0, 1, 50, 95, 99, 100):
        assert metrics.percentile([3.25], q) == 3.25


def test_percentile_rejects_out_of_range_q():
    with pytest.raises(ValueError):
        metrics.percentile([1.0, 2.0], -5)
    with pytest.raises(ValueError):
        metrics.percentile([1.0, 2.0], 200)
    with pytest.raises(ValueError):
        metrics.percentile([], 101)


def test_percentile_summary_tiny_windows():
    assert metrics.percentile_summary([]) == {}
    assert metrics.percentile_summary([2.0]) == {
        "p50": 2.0,
        "p95": 2.0,
        "p99": 2.0,
    }
    two = metrics.percentile_summary([1.0, 9.0])
    assert two == {"p50": 1.0, "p95": 9.0, "p99": 9.0}


def test_hist_readout_single_observation():
    metrics.observe("edge_s", 0.5)
    h = metrics.snapshot()["histograms"]["edge_s"]
    assert h["count"] == 1
    assert h["p50_s"] == h["p95_s"] == h["p99_s"] == 0.5
    assert h["mean_s"] == 0.5 and h["max_s"] == 0.5


def test_nearest_rank_unchanged_for_larger_n():
    samples = list(range(1, 11))  # 1..10
    assert metrics.percentile(samples, 50) == 5
    assert metrics.percentile(samples, 99) == 10
    assert metrics.percentile(samples, 100) == 10
    assert metrics.percentile(samples, 0) == 1
