"""Self-healing policy units (ISSUE 9): the per-executor circuit breaker
(ExecutorHealth), the hung-dispatch Watchdog, and the BrownoutPolicy.

These are the DECISION objects serve/service.py composes; each is driven
here with a fake clock and zero threads — every transition, deadline, and
shedding decision is a pure function of advanced time. The integration
(abandon/respawn/redistribute against a live pool) lives in
tests/test_serve.py's chaos section.
"""

import pytest

from coconut_tpu import metrics
from coconut_tpu.errors import ServiceBrownoutError
from coconut_tpu.serve.health import (
    HEALTHY,
    PROBATION,
    QUARANTINED,
    SUSPECT,
    BrownoutPolicy,
    ExecutorHealth,
    HealthPolicy,
    Watchdog,
)

pytestmark = pytest.mark.chaos


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _health(clock, **kw):
    kw.setdefault("suspect_after", 1)
    kw.setdefault("quarantine_after", 3)
    kw.setdefault("probe_after_s", 5.0)
    kw.setdefault("probe_successes", 2)
    return ExecutorHealth("0", HealthPolicy(**kw), clock=clock)


# --- ExecutorHealth: the breaker ladder ------------------------------------


def test_policy_validates_knobs():
    with pytest.raises(ValueError):
        HealthPolicy(suspect_after=0)
    with pytest.raises(ValueError):
        HealthPolicy(suspect_after=3, quarantine_after=2)
    with pytest.raises(ValueError):
        HealthPolicy(probe_successes=0)


def test_failures_escalate_suspect_then_quarantine():
    h = _health(FakeClock())
    assert h.state == HEALTHY and h.admissible()
    assert h.on_failure("f1") == (HEALTHY, SUSPECT)
    assert h.state == SUSPECT and h.admissible()  # warning shot: still placed
    assert h.on_failure("f2") is None  # 2 < quarantine_after
    assert h.on_failure("f3") == (SUSPECT, QUARANTINED)
    assert not h.admissible()
    assert metrics.get_count("serve_quarantined") == 1
    assert metrics.get_gauge("serve_dev0_health") == QUARANTINED
    # further failures while quarantined are no-ops, not re-opens
    assert h.on_failure("f4") is None
    assert metrics.get_count("serve_quarantined") == 1


def test_success_resets_the_failure_count_and_clears_suspect():
    h = _health(FakeClock())
    h.on_failure()
    assert h.state == SUSPECT
    assert h.on_success() == (SUSPECT, HEALTHY)
    # the consecutive count reset with it: two more failures don't open
    h.on_failure()
    h.on_failure()
    assert h.state == SUSPECT
    h.on_success()
    assert h.state == HEALTHY and h.consecutive_failures == 0


def test_crash_quarantines_immediately_whatever_the_count():
    h = _health(FakeClock())
    assert h.on_crash("boom") == (HEALTHY, QUARANTINED)
    assert h.quarantines == 1 and not h.admissible()


def test_probation_ladder_closes_after_consecutive_probe_successes():
    clock = FakeClock()
    h = _health(clock, probe_successes=2)
    h.on_crash("boom")
    # cooldown not elapsed: stays quarantined
    assert not h.try_probation()
    clock.advance(5.0)
    assert h.try_probation()
    assert h.state == PROBATION and h.admissible()
    assert h.on_success() is None  # 1 of 2
    assert h.on_success() == (PROBATION, HEALTHY)
    assert metrics.get_count("serve_recovered") == 1
    assert metrics.get_gauge("serve_dev0_health") == HEALTHY


def test_failed_probe_requarantines_with_escalated_cooldown():
    clock = FakeClock()
    h = _health(clock, probe_after_s=5.0, cooldown_backoff=2.0)
    h.on_crash("boom")
    assert h.cooldown_s == 5.0
    clock.advance(5.0)
    assert h.try_probation()
    assert h.on_failure("probe died") == (PROBATION, QUARANTINED)
    assert metrics.get_count("serve_probe_failures") == 1
    assert h.cooldown_s == 10.0  # backed off
    clock.advance(5.0)
    assert not h.try_probation()  # old cooldown no longer enough
    clock.advance(5.0)
    assert h.try_probation()
    # crash DURING probation escalates the same way
    h.on_crash("probe crashed")
    assert h.cooldown_s == 20.0
    assert metrics.get_count("serve_probe_failures") == 2


def test_cooldown_escalation_is_bounded_and_recovery_deescalates():
    clock = FakeClock()
    h = _health(
        clock, probe_after_s=5.0, cooldown_backoff=10.0, max_cooldown_s=30.0,
        probe_successes=1,
    )
    h.on_crash("boom")
    for _ in range(3):  # 5 -> 30 (capped), stays 30
        clock.advance(100.0)
        assert h.try_probation()
        h.on_failure("still bad")
    assert h.cooldown_s == 30.0
    clock.advance(100.0)
    assert h.try_probation()
    h.on_success()  # breaker closes...
    assert h.state == HEALTHY
    assert h.cooldown_s == 5.0  # ...and the NEXT incident starts from base


# --- Watchdog: deadline budgets + expiry -----------------------------------


def test_watchdog_budget_initial_then_k_times_ema_clamped():
    clock = FakeClock()
    wd = Watchdog(
        clock=clock, k=4.0, min_timeout_s=1.0, initial_timeout_s=100.0,
        max_timeout_s=50.0, alpha=0.5,
    )
    assert wd.budget("0") == 100.0  # no EMA yet: don't shoot the jit compile
    wd.begin("0", 0, ["r"])
    clock.advance(2.0)
    assert wd.end("0", 0) == 2.0
    assert wd.ema("0") == 2.0
    assert wd.budget("0") == 8.0  # k * ema
    # EMA converges: alpha * new + (1 - alpha) * prev
    wd.begin("0", 1, ["r"])
    clock.advance(4.0)
    wd.end("0", 1)
    assert wd.ema("0") == pytest.approx(3.0)
    # clamping: a tiny EMA floors at min, a huge one caps at max
    wd._ema["0"] = 0.01
    assert wd.budget("0") == 1.0
    wd._ema["0"] = 1000.0
    assert wd.budget("0") == 50.0


def test_watchdog_expire_pops_each_hang_exactly_once():
    clock = FakeClock()
    wd = Watchdog(clock=clock, initial_timeout_s=10.0)
    reqs = ["the batch"]
    wd.begin("0", 7, reqs, span=None)
    wd.begin("1", 8, ["fine"])
    clock.advance(5.0)
    assert wd.expire() == []
    clock.advance(5.0)  # dispatch 7 and 8 both hit their deadline at t=10
    expired = wd.expire()
    assert {(e[0], e[1]) for e in expired} == {("0", 7), ("1", 8)}
    lbl, seq, got, span, overdue = [e for e in expired if e[0] == "0"][0]
    assert got is reqs and overdue == 0.0 and span is None
    assert wd.expire() == []  # popped: fires exactly once
    assert wd.inflight() == 0


def test_watchdog_late_end_after_expiry_never_pollutes_the_ema():
    clock = FakeClock()
    wd = Watchdog(clock=clock, initial_timeout_s=1.0)
    wd.begin("0", 0, ["r"])
    clock.advance(2.0)
    assert len(wd.expire()) == 1
    # the hung dispatch finally returns, hours later
    clock.advance(7200.0)
    assert wd.end("0", 0) is None
    assert wd.ema("0") is None
    # failed settles don't feed the EMA either
    wd.begin("0", 1, ["r"])
    clock.advance(0.5)
    assert wd.end("0", 1, ok=False) is None
    assert wd.ema("0") is None


def test_watchdog_forget_label_drops_a_crashed_executors_tracking():
    clock = FakeClock()
    wd = Watchdog(clock=clock, initial_timeout_s=1.0)
    wd.begin("0", 0, ["a"])
    wd.begin("0", 1, ["b"])
    wd.begin("1", 2, ["c"])
    assert wd.forget_label("0") == 2
    clock.advance(5.0)
    assert [e[0] for e in wd.expire()] == ["1"]


def test_watchdog_validates_knobs():
    with pytest.raises(ValueError):
        Watchdog(k=0)
    with pytest.raises(ValueError):
        Watchdog(alpha=1.5)


# --- BrownoutPolicy: graded load-shedding ----------------------------------


def test_brownout_sheds_bulk_on_degraded_capacity_keeps_interactive():
    bp = BrownoutPolicy(capacity_threshold=0.5, retry_after_s=0.5)
    # healthy pool, idle queue: inactive for everyone
    assert bp.check("bulk", 0, 100, 1.0) == (False, None)
    # half the pool quarantined: 0.5 is NOT below the threshold yet
    assert bp.check("bulk", 0, 100, 0.5) == (False, None)
    # below it: bulk sheds, interactive rides through
    active, hint = bp.check("bulk", 0, 100, 0.25)
    assert active and hint is not None and hint > bp.retry_after_s
    active, hint = bp.check("interactive", 0, 100, 0.25)
    assert active and hint is None


def test_brownout_sheds_bulk_on_queue_depth_pressure():
    bp = BrownoutPolicy(depth_threshold=0.75, retry_after_s=0.5)
    assert bp.check("bulk", 74, 100, 1.0) == (False, None)
    active, hint = bp.check("bulk", 75, 100, 1.0)
    assert active and hint == pytest.approx(0.5 * 1.75)
    # the hint scales with pressure: a fuller queue asks for a longer wait
    _, worse = bp.check("bulk", 100, 100, 1.0)
    assert worse > hint


def test_brownout_error_is_typed_and_carries_the_hint():
    err = ServiceBrownoutError("bulk", 0.875, depth=75, capacity_fraction=0.25)
    assert err.lane == "bulk" and err.retry_after_s == 0.875
    assert err.depth == 75 and err.capacity_fraction == 0.25
    assert "retry" in str(err) and "bulk" in str(err)


def test_brownout_validates_knobs():
    with pytest.raises(ValueError):
        BrownoutPolicy(capacity_threshold=1.5)
    with pytest.raises(ValueError):
        BrownoutPolicy(depth_threshold=0.0)
