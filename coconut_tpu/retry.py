"""Retry policy: bounded exponential backoff with deterministic jitter.

The supervision layer around `stream.verify_stream` (SURVEY §5 "failure
detection", PAPER.md's threshold-of-faulty-parties design goal applied to
our own pipeline) re-attempts a batch whose dispatch or readback raised a
`TransientBackendError`. Backoff is exponential and bounded; jitter is
DETERMINISTIC — derived from crc32((key, attempt)) rather than a PRNG — so
a checkpointed rerun replays the identical schedule (the fault-injection
suite depends on this) while distinct batches still desynchronize their
re-dispatches.

Counters (metrics.py): "retries" increments per re-attempt, "fallbacks"
per degradation to the fallback backend.

Tracing (coconut_tpu/obs): when a span is active, the ladder narrates
itself onto it — "retry" (with the backoff chosen) per re-attempt,
"attempt_failed" (with the error class) per transient failure, and
"fallback" when the ladder degrades — so a single request's trace shows
its exact attempt history, not just the run-wide counters.
"""

import time
import zlib

from . import metrics
from .errors import TransientBackendError
from .obs import trace as otrace


class RetryPolicy:
    """How many times to re-attempt a transient failure, and how to wait.

    max_attempts: TOTAL attempts per unit of work (1 = no retry);
    base_delay / max_delay: seconds; re-attempt `a` (1-indexed) waits
      min(max_delay, base_delay * 2**(a-1)) scaled by the jitter factor;
    jitter: fraction in [0, 1] — the delay is scaled into
      [(1-jitter) * raw, raw] by a crc32-derived factor of (key, attempt);
    retryable: exception classes worth re-attempting (everything else is
      permanent and propagates);
    sleep: injectable for tests (defaults to time.sleep)."""

    def __init__(
        self,
        max_attempts=4,
        base_delay=0.05,
        max_delay=5.0,
        jitter=0.5,
        retryable=(TransientBackendError,),
        sleep=time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1 (got %r)" % max_attempts)
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1] (got %r)" % jitter)
        if base_delay < 0 or max_delay < 0:
            raise ValueError("delays must be >= 0")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self.retryable = tuple(retryable)
        self.sleep = sleep

    def backoff(self, attempt, key=0):
        """Delay in seconds before re-attempt `attempt` (1-indexed) of the
        work unit `key` (e.g. a batch index). Pure: same (key, attempt) ->
        same delay."""
        raw = min(self.max_delay, self.base_delay * (2.0 ** (attempt - 1)))
        h = zlib.crc32(("%s:%s" % (key, attempt)).encode()) / 0xFFFFFFFF
        return raw * (1.0 - self.jitter * h)


def note_attempt(attempts, exc):
    """Append one attempt-history record (the dead-letter `attempts`
    format) for a failed attempt."""
    attempts.append(
        {
            "attempt": len(attempts) + 1,
            "error": type(exc).__name__,
            "detail": str(exc),
        }
    )


def call_with_retry(fn, policy, key=0, attempts=None, fallback=None):
    """Run `fn()` under `policy`'s retry ladder.

    Re-attempts (with backoff sleep and a "retries" count) while `fn`
    raises a `policy.retryable` exception and attempts remain. `attempts`
    may arrive pre-populated (the stream's pipelined dispatch consumes the
    first attempt eagerly); records for further failures are appended in
    place. On exhaustion: runs `fallback()` if given (counted under
    "fallbacks"), else re-raises the last transient error."""
    attempts = [] if attempts is None else attempts
    last = None
    while len(attempts) < policy.max_attempts:
        if attempts:
            metrics.count("retries")
            delay = policy.backoff(len(attempts), key=key)
            otrace.event(
                "retry", attempt=len(attempts) + 1, backoff_s=round(delay, 6)
            )
            policy.sleep(delay)
        try:
            return fn()
        except policy.retryable as e:
            last = e
            note_attempt(attempts, e)
            otrace.event(
                "attempt_failed",
                attempt=len(attempts),
                error=type(e).__name__,
            )
    if fallback is not None:
        metrics.count("fallbacks")
        otrace.event("fallback", after_attempts=len(attempts))
        return fallback()
    if last is None:
        # every attempt was consumed by the caller before we ran
        raise TransientBackendError(
            "retries exhausted after %d attempt(s): %r"
            % (len(attempts), attempts)
        )
    raise last
