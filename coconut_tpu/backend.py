"""CurveBackend seam — the batched-execution interface every backend implements.

This is SURVEY.md §7 stage 5 made real: the hot paths of the protocol layer
(`Signature::verify` reached via reference signature.rs:472-478, and the MSMs
at signature.rs:465,513,521) route their batch-shaped math through a
`CurveBackend`, so the same protocol code runs on the pure-Python spec ops or
on the JAX/TPU limb backend.  The north-star metric (BASELINE.json) is
`batch_verify` throughput.

Primitives are expressed in concrete (G1, G2) terms; the protocol layer maps
the abstract SignatureGroup/OtherGroup roles onto them via `GroupContext`
(params.py), exactly as `GroupContext.pairing_check` already does.

Contract (the differential test harness in tests/test_backends.py enforces
this for every registered backend):
  - results are bit-identical to the Python spec ops (`coconut_tpu.ops`) —
    same affine coordinates, same booleans — on any input the spec accepts;
  - `pairing_product_is_one` pairs are in (G1 point, G2 point) order;
  - points are the spec's affine tuples (`None` = identity), scalars are
    Python ints (canonical Fr residues).
"""

from .ops import curve as _curve
from .ops import pairing as _pairing
from .ops.fields import R


class CurveBackend:
    """Abstract batched curve backend.

    `batch_verify_pairs` has a default composition in terms of the two
    primitives; fused backends (JAX/TPU) override it to stay on-device.
    """

    name = "abstract"

    # -- primitives ---------------------------------------------------------

    def msm_g1_shared(self, bases, scalars_batch):
        """sum_j scalars[i][j] * bases[j] in G1 for each batch row i.

        bases: [k] G1 affine points (shared across the batch);
        scalars_batch: [B][k] ints. Returns [B] G1 affine points."""
        raise NotImplementedError

    def msm_g2_shared(self, bases, scalars_batch):
        """Same as msm_g1_shared, in G2."""
        raise NotImplementedError

    def pairing_product_is_one(self, pairs_batch):
        """[B][n] list of (G1 affine, G2 affine) pairs ->
        [B] bools: prod_j e(P_ij, Q_ij) == 1 per row."""
        raise NotImplementedError

    def msm_g1_distinct(self, points_batch, scalars_batch):
        """sum_j scalars[i][j] * points[i][j] in G1 per batch row i —
        per-row bases (the issuance shape: each request carries its own
        ciphertext points, reference signature.rs:400-428).

        points_batch: [B][k] G1 affine points; scalars_batch: [B][k] ints.
        Returns [B] G1 affine points."""
        raise NotImplementedError

    def msm_g2_distinct(self, points_batch, scalars_batch):
        """Same as msm_g1_distinct, in G2."""
        raise NotImplementedError

    # -- composed operations ------------------------------------------------

    def verify_accumulators(self, vk, messages_list, params):
        """The per-credential OtherGroup accumulator X_tilde * prod Y_j^{m_j}
        (SURVEY.md §3.4), batched over message vectors with one shared-base
        MSM: bases [X_tilde, Y_1..Y_q], scalars [1, m_1..m_q]."""
        bases = [vk.X_tilde] + list(vk.Y_tilde)
        scalars = [[1] + [m % R for m in msgs] for msgs in messages_list]
        if params.ctx.name == "G1":
            return self.msm_g2_shared(bases, scalars)
        return self.msm_g1_shared(bases, scalars)

    def batch_verify_pairs(self, sig_pairs, params):
        """[B] rows of [(sig_group_pt, other_group_pt), ...] -> [B] bools,
        mapping the ctx's group roles onto the concrete (G1, G2) pairing
        order (cf. GroupContext.pairing_check)."""
        if params.ctx.name == "G1":
            ordered = [[(s, o) for s, o in row] for row in sig_pairs]
        else:
            ordered = [[(o, s) for s, o in row] for row in sig_pairs]
        return self.pairing_product_is_one(ordered)

    def batch_verify(self, sigs, messages_list, vk, params):
        """[B] PS verifications under one verkey -> [B] bools.

        Same math as `ps.ps_verify` (reference: PSSignature::verify reached
        via signature.rs:477): reject identity sigma_1, then check
        e(sigma_1, X_tilde * prod Y_j^{m_j}) * e(-sigma_2, g_tilde) == 1."""
        accs = self.verify_accumulators(vk, messages_list, params)
        sig_ops = params.ctx.sig
        rows = [
            [(s.sigma_1, acc), (sig_ops.neg(s.sigma_2), params.g_tilde)]
            for s, acc in zip(sigs, accs)
        ]
        bits = self.batch_verify_pairs(rows, params)
        from . import metrics

        metrics.count("verify_final_exps", len(rows))
        return [
            bool(b) and s.sigma_1 is not None for b, s in zip(bits, sigs)
        ]

    def _msm_sig_distinct(self, params, points_batch, scalars_batch):
        """Distinct-base MSM in whichever concrete group the ctx assigns
        to signatures."""
        if params.ctx.name == "G1":
            return self.msm_g1_distinct(points_batch, scalars_batch)
        return self.msm_g2_distinct(points_batch, scalars_batch)

    def batch_verify_combined(
        self, sigs, messages_list, vk, params, rs=None, epoch=None
    ):
        """ONE bool for the whole batch via the random-linear-combination
        fold (PR 16): prod_i e(r_i sigma_1_i, acc_i) *
        e(sum_i r_i (-sigma_2_i), g_tilde) == 1 — a single (B+1)-pair
        pairing-product row instead of B independent 2-pair rows, so ONE
        shared final exponentiation. `rs=None` derives the combiner
        exponents deterministically from the domain-separated batch
        transcript (batchverify.derive_combiners); soundness: a forged
        lane survives w.p. <= 2^-lambda. Generic composition over the
        MSM/pairing primitives — fused backends (JaxBackend) override."""
        from . import metrics

        metrics.count("verify_batched_checks")
        B = len(sigs)
        if B == 0:
            return True  # empty product is 1
        if any(s.sigma_1 is None or s.sigma_2 is None for s in sigs):
            return False
        if rs is None:
            from .batchverify import derive_combiners, verify_transcript

            rs = derive_combiners(
                verify_transcript(sigs, messages_list, vk, params,
                                  epoch=epoch),
                B,
            )
        elif len(rs) != B:
            raise ValueError(
                "combiner count mismatch: %d exponents, %d lanes"
                % (len(rs), B)
            )
        accs = self.verify_accumulators(vk, messages_list, params)
        sig_ops = params.ctx.sig
        s1r = self._msm_sig_distinct(
            params, [[s.sigma_1] for s in sigs], [[r] for r in rs]
        )
        (z,) = self._msm_sig_distinct(
            params,
            [[sig_ops.neg(s.sigma_2) for s in sigs]],
            [list(rs)],
        )
        row = list(zip(s1r, accs)) + [(z, params.g_tilde)]
        ok = self.batch_verify_pairs([row], params)[0]
        metrics.count("verify_final_exps", 1)
        return bool(ok)

    def batch_show_verify_combined(
        self, proofs, vk, params, revealed_msgs_list, challenges, rs=None,
        epoch=None
    ):
        """RLC-combined batched show verify -> (per-lane Schnorr bits,
        ONE batch pairing bool). The Schnorr commitment equation stays
        per-lane (MSM-only, nothing to combine); the B pairing checks
        e(sigma'_1i, J_i * X_tilde * prod_rev Y^m) * e(-sigma'_2i,
        g_tilde) fold under the combiner exponents as in
        `batch_verify_combined`. Dead lanes (identity sigma') are
        excluded from the fold and fail their own bit, so they never
        poison the batch bool. A lane's verdict is bits[i] & pair_ok;
        ps.batch_show_verify bisects on pair_ok=False. Generic
        composition; fused backends override."""
        from . import metrics

        metrics.count("verify_batched_checks")
        B = len(proofs)
        if B == 0:
            return [], True
        ctx = params.ctx
        oth = ctx.other
        sig_ops = ctx.sig
        schnorr = []
        for p, c in zip(proofs, challenges):
            ok = (
                p.sigma_prime_1 is not None
                and p.sigma_prime_2 is not None
                and p.proof_vc.verify(oth, p._bases(vk, params), p.J, c)
            )
            schnorr.append(bool(ok))
        if rs is None:
            from .batchverify import derive_combiners, show_transcript

            rs = derive_combiners(
                show_transcript(proofs, vk, params, revealed_msgs_list,
                                challenges, epoch=epoch),
                B,
            )
        elif len(rs) != B:
            raise ValueError(
                "combiner count mismatch: %d exponents, %d lanes"
                % (len(rs), B)
            )
        # zero the combiner of dead lanes: their pairing relation is
        # excluded from the fold (they already fail via schnorr[i]=False)
        live_rs = [
            r if p.sigma_prime_1 is not None and p.sigma_prime_2 is not None
            else 0
            for r, p in zip(rs, proofs)
        ]
        # acc_i = J_i + X_tilde + sum_rev Y_tilde[j]^{m_j}
        idx_sets = [sorted(rm.keys()) for rm in revealed_msgs_list]
        bases = [vk.X_tilde] + [vk.Y_tilde[j] for j in idx_sets[0]]
        if any(s != idx_sets[0] for s in idx_sets):
            raise ValueError("combined show batch requires one revealed set")
        scalars = [
            [1] + [rm[j] % R for j in idx_sets[0]]
            for rm in revealed_msgs_list
        ]
        msm_o = (
            self.msm_g2_shared if ctx.name == "G1" else self.msm_g1_shared
        )
        accs = [
            oth.add(a, p.J)
            for a, p in zip(msm_o(bases, scalars), proofs)
        ]
        s1r = self._msm_sig_distinct(
            params,
            [[p.sigma_prime_1] for p in proofs],
            [[r] for r in live_rs],
        )
        (z,) = self._msm_sig_distinct(
            params,
            [
                [
                    None if p.sigma_prime_2 is None
                    else sig_ops.neg(p.sigma_prime_2)
                    for p in proofs
                ]
            ],
            [list(live_rs)],
        )
        row = list(zip(s1r, accs)) + [(z, params.g_tilde)]
        pair_ok = self.batch_verify_pairs([row], params)[0]
        metrics.count("verify_final_exps", 1)
        return schnorr, bool(pair_ok)


class PythonBackend(CurveBackend):
    """Reference backend: the spec ops run per-element. Slow, canonical."""

    name = "python"

    def msm_g1_shared(self, bases, scalars_batch):
        return [_curve.g1.msm(bases, row) for row in scalars_batch]

    def msm_g2_shared(self, bases, scalars_batch):
        return [_curve.g2.msm(bases, row) for row in scalars_batch]

    def msm_g1_distinct(self, points_batch, scalars_batch):
        return [
            _curve.g1.msm(pts, row)
            for pts, row in zip(points_batch, scalars_batch)
        ]

    def msm_g2_distinct(self, points_batch, scalars_batch):
        return [
            _curve.g2.msm(pts, row)
            for pts, row in zip(points_batch, scalars_batch)
        ]

    def pairing_product_is_one(self, pairs_batch):
        return [_pairing.pairing_check(row) for row in pairs_batch]


def _async_pair(backend, dispatch_name, wait_name):
    dispatch = getattr(backend, dispatch_name, None)
    wait = getattr(backend, wait_name, None)
    if dispatch is None or wait is None:
        return None
    return dispatch, wait


def async_shared_many_api(backend, group):
    """(dispatch, wait) for the optional async multi-MSM contract in
    `group` ("g1"/"g2"), or None. The dispatch half launches the fused
    comb program and returns a handle; the wait half blocks and decodes.
    Probed HERE as a unit (single place, VERDICT-advisor finding): a
    backend implementing only the dispatch side must not pass a partial
    capability check and crash at the wait call mid-protocol."""
    return _async_pair(
        backend, "msm_%s_shared_many_async" % group, "msm_shared_many_wait"
    )


def async_distinct_api(backend, group):
    """(dispatch, wait) for the optional async distinct-base MSM contract
    in `group`, or None — same unit-probe rationale as
    `async_shared_many_api`."""
    return _async_pair(
        backend, "msm_%s_distinct_async" % group, "msm_distinct_wait"
    )


def async_distinct_plus_offset_api(backend, group):
    """(dispatch, wait) for the optional offset-fused distinct MSM
    (affine(offset_i + MSM_i), offset consumed device-to-device from a
    shared-many job handle), or None — same unit-probe rationale: the
    dispatch must come paired with the wait that decodes its handles."""
    return _async_pair(
        backend,
        "msm_%s_distinct_plus_offset_async" % group,
        "msm_distinct_wait",
    )


_REGISTRY = {}


def register_backend(name, factory):
    _REGISTRY[name] = factory


def get_backend(name):
    """Instantiate a backend by name ("python", "jax")."""
    if name == "jax":  # lazy: importing jax is heavy and optional for CPU use
        from .tpu.backend import JaxBackend

        return JaxBackend()
    if name == "cpp":  # lazy: builds the native library on first use
        from .native import CppBackend

        return CppBackend()
    if name == "cpp_ct":  # const-time MSM schedule for secret scalars
        from .native import CppBackend

        return CppBackend(ct=True)
    if name in _REGISTRY:
        return _REGISTRY[name]()
    if name == "python":
        return PythonBackend()
    raise ValueError("unknown backend %r" % name)


register_backend("python", PythonBackend)
