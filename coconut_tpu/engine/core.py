"""ExecutionEngine: the shared executor fabric every online Coconut
phase runs on (PR 12).

This is the pool/placer/health/watchdog/brownout stack that PR 6-9 grew
inside serve/service.py (and PR 10 re-grew, renamed, inside
issue/service.py), lifted out once and parameterized by *programs*
(engine/program.py). The engine owns:

  ADMISSION    one bounded RequestQueue + Batcher PER PROGRAM (each with
               its own metric namespace, max_batch, deadline, depth
               bound); brownout shedding applies the program's SLO class
               before the lane check.
  THE POOL     Executor workers (engine/executor.py), one per device,
               plus the optional mesh-sharded lane. One pool serves
               every registered pool program: executors carry a
               per-program dispatch registry, and the placer routes each
               coalesced batch by ITS program's rules (mesh-capable or
               not). Per-program jit-shape keys are counted under
               "%ns_jit_shapes" — a stable counter after warmup is the
               proof that heterogeneous traffic never recompiles.
  PLACERS      one thread per program popping ITS batcher behind ITS
               capacity gate; programs with their own workers (mint)
               replace placement with fan-out via the `place` hook.
  SELF-HEALING the per-executor circuit breakers, the hung-dispatch
               watchdog (shared across programs — own-worker programs
               claim their expiries via `owns_expiry`), probation
               revival, redistribution with hop caps, and brownout —
               exactly the PR-9 ladder, now engine-wide.
  LIFECYCLE    start/drain/shutdown with ONE shared deadline across
               every join; a placer crash or the death of the last
               executor sweeps every program's futures — none dangle.

serve.CredentialService and issue.IssuanceService subclass this engine
and register one program each (VerifyProgram / MintProgram);
engine.session.ProtocolEngine registers all five phases on one instance.
The verify pool's metric names ("serve_dev*", "serve_placed_*",
"serve_healthy_executors", ...) are the POOL's names regardless of which
program a batch belongs to; per-program names use the program's own
namespace ("%ns_batch_wait_s", "%ns_admitted", ...)."""

import threading
import time

from .. import metrics
from ..errors import ServiceBrownoutError, ServiceClosedError
from ..obs import trace as otrace
from ..retry import call_with_retry, note_attempt
from ..serve import health as _health
from ..serve.batcher import Batcher, fail_all
from ..serve.queue import RequestQueue
from .executor import Executor


def _next_pow2(n):
    """Smallest power of two >= n (and >= 2) — the grouped kernel's batch
    shape convention (tpu/backend.py's Bp)."""
    return 1 << max(1, (n - 1).bit_length())


def _remaining(deadline):
    """Seconds left until `deadline` on the REAL clock (thread joins are
    wall-time waits even under an injected fake clock); None = no bound."""
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


class _Runtime:
    """One registered program's runtime state on the engine."""

    __slots__ = ("program", "queue", "batcher", "thread")

    def __init__(self, program, queue, batcher):
        self.program = program
        self.queue = queue
        self.batcher = batcher
        self.thread = None


class ExecutionEngine:
    """The shared fabric. Subclasses (CredentialService, IssuanceService,
    ProtocolEngine) register programs, build the pool, and expose their
    public submit() APIs over `submit_request`."""

    def __init__(
        self,
        name="coconut-engine",
        metric_ns="serve",
        clock=time.monotonic,
        mesh=None,
        sharded_min_lanes=None,
        health_policy=None,
        watchdog=None,
        watchdog_interval_s=0.25,
        brownout=None,
        max_redispatch=None,
    ):
        self.name = name
        self.metric_ns = metric_ns
        self.clock = clock
        self.mesh = mesh
        self.sharded_min_lanes = sharded_min_lanes
        self._runtimes = {}
        self._order = []
        self._executors = []
        self._mesh_executor = None
        self._is_async = False
        self._thread = None
        self._placers = []
        self._seq_lock = threading.Lock()
        self._batch_seq = 0  # batch ids + fan-out ids + retry jitter keys
        self._crashed = None
        self._crash_msg = "service supervisor crashed: %r"
        #: (program, placement, shape) triples already dispatched — the
        #: per-program jit-shape cache bookkeeping behind "%ns_jit_shapes"
        self._shape_keys = set()
        #: labels of pool executors the elastic sizer has PARKED: alive
        #: objects, no worker thread, excluded from placement/capacity —
        #: distinct from quarantine (parking is intentional and must not
        #: look like degradation to the brownout policy)
        self._parked = set()

        # self-healing surfaces (serve/health.py)
        self.health_policy = (
            health_policy
            if health_policy is not None
            else _health.HealthPolicy()
        )
        self._watchdog = (
            watchdog if watchdog is not None else _health.Watchdog(clock=clock)
        )
        self._watchdog_interval_s = watchdog_interval_s
        self._brownout = (
            brownout if brownout is not None else _health.BrownoutPolicy()
        )
        self._healths = {}
        #: PR 19 health-history durability: journal callable + seed
        #: records, wired by attach_health_journal(store)
        self._health_journal = None
        self._health_seed = {}
        self.max_redispatch = 1 if max_redispatch is None else max_redispatch
        self._wd_stop = threading.Event()
        self._wd_thread = None

    # -- program registry ----------------------------------------------------

    def register(self, program):
        """Register one program: bind it, give it a bounded queue and a
        batcher in ITS metric namespace. The FIRST registration is the
        engine's primary program (`_queue`/`_batcher` aliases, the bare
        placer thread name)."""
        program.bind(self)
        queue = RequestQueue(
            max_depth=program.max_depth,
            clock=self.clock,
            metric_ns=program.metric_ns,
            program=program.name,
        )
        rt = _Runtime(
            program, queue, Batcher(queue, program.max_batch, clock=self.clock)
        )
        self._runtimes[program.name] = rt
        self._order.append(rt)
        return rt

    def program(self, name):
        return self._runtimes[name].program

    @property
    def _queue(self):
        """The primary program's queue (the single-program services' —
        and their tests' — historical attribute)."""
        return self._order[0].queue

    @property
    def _batcher(self):
        return self._order[0].batcher

    def _program_of(self, requests):
        """Resolve a batch to its program runtime via the stamp the
        owning queue left on each request; bare Requests (tests build
        them directly) fall back to the primary program."""
        name = None
        if requests:
            name = getattr(requests[0], "program", None)
        rt = self._runtimes.get(name) if name is not None else None
        return rt if rt is not None else self._order[0]

    def _next_seq(self):
        with self._seq_lock:
            seq = self._batch_seq
            self._batch_seq += 1
        return seq

    # -- pool construction ---------------------------------------------------

    def _add_executor(self, device=None, dispatch=None, is_async=False):
        ex = Executor(
            self,
            len(self._executors),
            device=device,
            dispatch=dispatch,
            is_async=is_async,
        )
        self._executors.append(ex)
        return ex

    def _set_mesh_executor(self, dispatch):
        self._mesh_executor = Executor(
            self,
            len(self._executors),
            label="mesh",
            dispatch=dispatch,
            is_async=True,
            placement="sharded",
        )
        return self._mesh_executor

    def _seed_pool_program(self, program):
        """Give every pool executor `program`'s device-pinned dispatch
        closure (the cross-program multiplexing seam)."""
        for ex in self._executors:
            made = program.make_dispatch(device=ex.device)
            if made is not None:
                dispatch, _ = made
                ex.seed(program.name, dispatch)

    def _finalize_pool(self, max_redispatch=None):
        """After the pool is built: create every executor's breaker, fix
        the redispatch hop cap, publish the health gauges."""
        all_ex = self._all_executors()
        for ex in all_ex:
            self._health_of(ex.label)
        if max_redispatch is None:
            self.max_redispatch = max(1, len(all_ex) - 1)
        else:
            self.max_redispatch = max_redispatch
        if all_ex:
            self._is_async = self._executors[0].is_async
        for ex in all_ex:
            metrics.set_gauge(
                "serve_dev%s_health" % ex.label, _health.HEALTHY
            )
        self._refresh_health_gauges()

    def _all_executors(self):
        if self._mesh_executor is not None:
            return self._executors + [self._mesh_executor]
        return list(self._executors)

    # -- client side ---------------------------------------------------------

    def submit_request(
        self, program, payload, messages, lane="interactive", max_wait_ms=None
    ):
        """Admit one request on `program`'s queue; returns its ServeFuture.
        Raises ServiceBrownoutError when graded load-shedding refuses the
        program's SLO-mapped lane (retriable, carries the program name
        and a retry-after hint), ServiceOverloadedError at the admission
        bound, ServiceClosedError after drain/shutdown."""
        if self._crashed is not None:
            raise ServiceClosedError(self._crash_msg % (self._crashed,))
        rt = self._runtimes[program]
        prog = rt.program
        depth = rt.queue.depth()
        capacity = prog.capacity_fraction()
        active, retry_after = self._brownout.check(
            prog.shed_lane(lane), depth, rt.queue.max_depth, capacity
        )
        metrics.set_gauge(
            "%s_brownout" % prog.metric_ns, 1 if active else 0
        )
        if retry_after is not None:
            metrics.count("%s_shed_bulk" % prog.metric_ns)
            raise ServiceBrownoutError(
                lane,
                retry_after,
                depth=depth,
                capacity_fraction=capacity,
                program=prog.name,
            )
        return rt.queue.submit(
            payload,
            messages,
            lane=lane,
            max_wait_ms=(
                prog.max_wait_ms if max_wait_ms is None else max_wait_ms
            ),
        )

    def depth(self):
        return self._order[0].queue.depth()

    def kick(self):
        """Wake the placers to re-read the clock (fake-clock tests)."""
        self._kick_all()

    def _kick_all(self):
        for rt in self._order:
            rt.queue.kick()

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is None:
            for ex in self._all_executors():
                ex.start()
            for rt in self._order:
                rt.program.start_workers()
            self._placers = []
            for i, rt in enumerate(self._order):
                tname = (
                    self.name
                    if i == 0
                    else "%s-%s" % (self.name, rt.program.name)
                )
                rt.thread = threading.Thread(
                    target=self._run_program,
                    args=(rt,),
                    name=tname,
                    daemon=True,
                )
                self._placers.append(rt.thread)
            self._thread = self._placers[0]
            for t in self._placers:
                t.start()
            if self._watchdog_interval_s is not None:
                self._wd_thread = threading.Thread(
                    target=self._watchdog_loop,
                    name="%s-watchdog" % self.name,
                    daemon=True,
                )
                self._wd_thread.start()
        return self

    def _close_pool_and_workers(self, deadline, ok):
        """Join the pool and every program's own workers after
        intake+placement ended; every inbox batch still settles first.
        `deadline` is the drain/shutdown call's SINGLE shared deadline —
        each join gets whatever budget remains, not a fresh per-thread
        timeout. The watchdog goes LAST: it can still expire a hung
        dispatch (and redistribute its batch) while the pool drains."""
        for ex in self._all_executors():
            ex.close()
        for ex in self._all_executors():
            ok = ex.join(_remaining(deadline)) and ok
        for rt in self._order:
            rt.program.close_workers()
        for rt in self._order:
            ok = rt.program.join_workers(deadline) and ok
        for rt in self._order:
            rt.program.on_drain()
        return self._stop_watchdog(deadline) and ok

    def _stop_watchdog(self, deadline):
        thread = self._wd_thread
        if thread is None:
            return True
        self._wd_stop.set()
        thread.join(_remaining(deadline))
        return not thread.is_alive()

    def drain(self, timeout=None):
        """Close intake, settle every accepted request, join the placers,
        the executor pool, and every program's own workers. Every
        accepted future is resolved on return (True iff all threads
        exited within `timeout` — ONE deadline shared across every join,
        not a per-thread allowance)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for rt in self._order:
            rt.queue.close()
        ok = True
        if self._thread is None:
            # never started: nothing will settle the queues — fail loudly
            for rt in self._order:
                fail_all(
                    rt.queue.drain_pending(),
                    ServiceClosedError("service drained before start()"),
                    counter="%s_cancelled" % rt.program.metric_ns,
                )
        else:
            for t in self._placers:
                t.join(_remaining(deadline))
            ok = not any(t.is_alive() for t in self._placers)
        return self._close_pool_and_workers(deadline, ok)

    def shutdown(self, drain=True, timeout=None):
        """drain=True: alias for drain(). drain=False: refuse the queued
        backlog (futures fail with ServiceClosedError) but still settle
        work already placed on executors, then join — `timeout` again one
        shared deadline across all joins."""
        if drain:
            return self.drain(timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        for rt in self._order:
            rt.queue.close()
            fail_all(
                rt.queue.drain_pending(),
                ServiceClosedError(
                    "service shut down before this request ran"
                ),
                counter="%s_cancelled" % rt.program.metric_ns,
            )
        ok = True
        if self._thread is not None:
            for t in self._placers:
                t.join(_remaining(deadline))
            ok = not any(t.is_alive() for t in self._placers)
        return self._close_pool_and_workers(deadline, ok)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.drain()
        return False

    # -- health (serve/health.py integration) --------------------------------

    def _health_of(self, label):
        """The POOL breaker for `label`, created on first sight
        (executors can be injected post-init — tests stub the mesh lane
        that way). Own-worker programs keep their own registries in
        their own namespaces. With a journal attached, a new breaker
        first replays this label's journaled record — a restarted
        replica remembers which executors were flapping — and journals
        its own transitions from then on."""
        h = self._healths.get(label)
        if h is None:
            h = self._healths[label] = _health.ExecutorHealth(
                label, self.health_policy, clock=self.clock,
                journal=self._health_journal,
            )
            seed = self._health_seed.pop(label, None)
            if seed is not None:
                h.restore(seed)
        return h

    def attach_health_journal(self, store, keyspace="health"):
        """Make executor-health history durable (PR 19, ROADMAP item 4's
        other half): every breaker transition writes the breaker's
        last-writer-wins `snapshot_record()` under its label in the
        `keyspace` keyspace of `store` (a state.StateStore), and records
        already present replay into breakers as they are (or were)
        created — so a replica that restarts mid-flap re-quarantines the
        bad device and keeps its ESCALATED cooldown instead of
        re-learning the flap from scratch.

        Bounded by construction: ONE record per executor label
        (overwritten in place, never appended) with a HISTORY_CAP'd
        transition tail inside — no epoch accumulation to retire.
        Writes skip fsync: health history is best-effort durable;
        losing the last transition to a crash merely costs one
        re-learned flap, and fsync on the hot settle path would tax
        every breaker trip."""

        def _journal(label, record):
            store.put(keyspace, label, record, fsync=False)

        self._health_journal = _journal
        for label in store.keys(keyspace):
            rec = store.get(keyspace, label)
            h = self._healths.get(label)
            if h is not None:
                h.restore(rec)
            else:
                self._health_seed[label] = rec
        for h in self._healths.values():
            h.journal = _journal

    def _admits(self, ex):
        """May the placer route NEW work to `ex`? HEALTHY/SUSPECT always;
        PROBATION only while its half-open probe slot is free (one
        unsettled probe batch at a time); QUARANTINED never; PARKED
        (elastic shrink) never."""
        if ex.label in self._parked:
            return False
        h = self._health_of(ex.label)
        if not h.admissible():
            return False
        if h.state == _health.PROBATION and ex.batches_out() > 0:
            return False
        return True

    def _capacity_fraction(self):
        """Fraction of the pool the placer may still route to — the
        brownout policy's degradation signal. 1.0 with no pool (the pool
        isn't this engine's bottleneck then; own-worker programs
        override their capacity signal). Computed over the NON-PARKED
        pool: an intentional elastic shrink is not degradation and must
        never trip the brownout ladder."""
        exs = [
            ex
            for ex in self._all_executors()
            if ex.label not in self._parked
        ]
        if not exs:
            return 1.0
        ok = sum(1 for ex in exs if self._health_of(ex.label).admissible())
        return ok / len(exs)

    def _refresh_health_gauges(self):
        exs = self._all_executors()
        if exs:
            metrics.set_gauge(
                "serve_healthy_executors",
                sum(
                    1
                    for ex in exs
                    if ex.label not in self._parked
                    and self._health_of(ex.label).admissible()
                ),
            )
        for rt in self._order:
            rt.program.refresh_health_gauges()

    def _note_success(self, executor):
        change = self._health_of(executor.label).on_success()
        if change:
            self._refresh_health_gauges()
            self._kick_all()

    def _note_failure(self, executor, exc):
        """A batch failed past retry+fallback ON this executor: feed the
        circuit breaker; if that opened it (soft quarantine — the worker
        itself is alive), move the executor's queued backlog to
        survivors."""
        change = self._health_of(executor.label).on_failure(
            "batch failed past retry+fallback: %s" % type(exc).__name__
        )
        if change:
            self._refresh_health_gauges()
            self._kick_all()
            if change[1] == _health.QUARANTINED:
                self._redistribute(executor.sweep_inbox(), exc)

    def _executor_failed(self, executor, exc, batches, spans, gen):
        """Executor-loop crash containment (runs ON the dying worker's
        thread): quarantine ONLY this executor and hand its unsettled
        batches to survivors. A stale generation (the watchdog already
        abandoned this worker and redistributed its work) does nothing."""
        if not executor.is_current(gen):
            return
        metrics.count("serve_executor_crashes")
        for span in spans:
            otrace.end_span(span, error=type(exc).__name__)
        self._health_of(executor.label).on_crash(
            "executor loop crash: %s" % type(exc).__name__
        )
        swept = executor.abandon()
        self._watchdog.forget_label(executor.label)
        self._refresh_health_gauges()
        self._redistribute(list(batches) + swept, exc)
        self._kick_all()

    def _redistribute(self, batches, cause):
        """Re-place a failed executor's unsettled batches through the
        normal _route/_place seams. Each request's redispatch count is
        capped (`max_redispatch`): a poisonous batch that kills every
        executor it lands on fails ITS OWN futures after the cap instead
        of serially taking down the pool. With NO survivors — the last
        executor died — the engine poisons and every remaining future
        resolves with the crash exception: none dangle."""
        batches = [b for b in batches if b]
        for i, batch in enumerate(batches):
            survivors = [
                ex
                for ex in self._all_executors()
                if ex.label not in self._parked
                and (
                    self._health_of(ex.label).admissible() or ex.has_worker()
                )
            ]
            if not survivors and self._parked:
                # every ACTIVE executor is gone but the elastic sizer is
                # holding spares: unparking beats crashing the engine
                for label in sorted(self._parked):
                    metrics.count("elastic_emergency_unparked")
                    self.unpark_executor(label)
                survivors = [
                    ex for ex in self._all_executors() if ex.has_worker()
                ]
            if not survivors:
                self._crash(cause)
                for rest in batches[i:]:
                    fail_all(rest, cause)
                return
            for r in batch:
                r.redispatches += 1
            if max(r.redispatches for r in batch) > self.max_redispatch:
                metrics.count("serve_redispatch_exhausted")
                fail_all(batch, cause)
                continue
            metrics.count("serve_redistributed_batches")
            metrics.count("serve_redistributed_requests", len(batch))
            for r in batch:
                r.span.event("redistributed", hops=r.redispatches)
            self._place(batch).submit_batch(batch)

    def health_tick(self, now=None):
        """One self-healing sweep: expire hung dispatches (abandon the
        stuck worker, quarantine its executor, redistribute the hung
        batch), let own-worker programs claim THEIR expiries and run
        their periodic work (hedges, authority probation), and promote
        quarantined pool executors whose cooldown elapsed into half-open
        PROBATION (respawning abandoned workers). Runs periodically on
        the watchdog thread in production; fake-clock tests call it
        directly after advancing time."""
        if self._crashed is not None:
            return
        now = self.clock() if now is None else now
        expired = self._watchdog.expire(now)
        from ..errors import TransientBackendError

        pool_expired = []
        for entry in expired:
            for rt in self._order:
                if rt.program.owns_expiry(entry):
                    rt.program.handle_expired(entry, now)
                    break
            else:
                pool_expired.append(entry)
        by_label = {}
        for label, seq, requests, span, overdue_s in pool_expired:
            metrics.count("serve_watchdog_timeouts")
            if span is not None:
                span.event(
                    "watchdog_timeout",
                    seq=seq,
                    overdue_s=round(overdue_s, 6),
                )
                span.end(error="WatchdogTimeout")
            by_label.setdefault(label, []).append(requests)
        for label, hung in by_label.items():
            ex = next(
                (x for x in self._all_executors() if x.label == label), None
            )
            if ex is None:
                continue
            cause = TransientBackendError(
                "dispatch on executor %s hung past its watchdog budget"
                % (label,)
            )
            self._health_of(label).on_crash("hung dispatch: watchdog timeout")
            # the worker is STUCK inside the dispatch — abandon it (its
            # eventual return, if any, is discarded by the stale-settle
            # guard) and redistribute both the hung batches and the inbox
            swept = ex.abandon()
            self._watchdog.forget_label(label)
            self._refresh_health_gauges()
            self._redistribute(hung + swept, cause)
        # half-open promotion: cooldown elapsed -> probation probe window
        for ex in self._all_executors():
            if self._health_of(ex.label).try_probation(now):
                ex.start()  # respawn an abandoned worker; no-op otherwise
                self._refresh_health_gauges()
                self._kick_all()
        # parked-executor sweep: a placer that chose an executor just
        # before it was parked may have landed a batch in its (now
        # workerless) inbox — re-place it on active executors instead of
        # letting it sit until unpark
        for label in list(self._parked):
            ex = next(
                (x for x in self._executors if x.label == label), None
            )
            if ex is None:
                continue
            swept = ex.sweep_inbox()
            if swept:
                self._redistribute(
                    swept,
                    TransientBackendError(
                        "batch landed on parked executor %s" % (label,)
                    ),
                )
        for rt in self._order:
            rt.program.tick(now)
        if pool_expired:
            self._kick_all()

    def _watchdog_loop(self):
        while not self._wd_stop.wait(self._watchdog_interval_s):
            try:
                self.health_tick()
            except Exception:
                # the healer must never become the failure: count and
                # keep ticking
                metrics.count("%s_health_tick_errors" % self.metric_ns)

    # -- warmup: shape manifest replay (engine/lifecycle.py) -----------------

    def shape_keys(self):
        """Snapshot of the (program, placement, shape_key) triples this
        engine has dispatched or pre-warmed so far — the lifecycle
        layer's shape-manifest source."""
        return set(self._shape_keys)

    def warm_shapes(self, shapes):
        """Best-effort AOT replay of a shape manifest (lifecycle warmup):
        ask each shape's program to prime it via Program.warm(). A shape
        the program confirms primed is pre-counted under
        "%ns_jit_shapes" — the counter stays flat through live traffic,
        which is exactly the no-recompile-after-warmup proof the boot
        gate needs. Shapes for unregistered programs, shapes a program
        declines to warm, and warm() crashes are skipped, never fatal:
        a cold shape just compiles on first dispatch. Returns
        (warmed, skipped)."""
        warmed = skipped = 0
        for entry in shapes:
            try:
                prog_name, placement, shape_key = entry
            except (TypeError, ValueError):
                skipped += 1
                continue
            rt = self._runtimes.get(prog_name)
            if rt is None:
                skipped += 1
                continue
            try:
                primed = bool(rt.program.warm(shape_key))
            except Exception:
                metrics.count("lifecycle_warm_errors")
                skipped += 1
                continue
            if not primed:
                skipped += 1
                continue
            shape = (prog_name, placement, shape_key)
            if shape not in self._shape_keys:
                self._shape_keys.add(shape)
                metrics.count("%s_jit_shapes" % rt.program.metric_ns)
            warmed += 1
        return warmed, skipped

    # -- elastic pool sizing (engine/lifecycle.ElasticController) ------------

    def total_depth(self):
        """Queued requests across EVERY program — the elastic sizer's
        pressure signal (`depth()` is the primary program only)."""
        return sum(rt.queue.depth() for rt in self._order)

    def active_pool_size(self):
        """Pool executors currently accepting work (not parked); the
        mesh lane is never elastic."""
        return sum(
            1 for ex in self._executors if ex.label not in self._parked
        )

    def parked_executors(self):
        return set(self._parked)

    def park_executor(self, label=None):
        """Elastic SHRINK: take one IDLE pool executor out of placement.
        Parking reuses the PR 9 abandon path — the worker thread exits
        via the stale-generation check, the executor object stays
        restartable — but is deliberately invisible to the health ladder
        (no quarantine, no brownout pressure). Only an idle executor
        (zero unsettled batches) may park: parking mid-flight would
        strand futures behind a workerless inbox. Never parks the last
        active executor. Returns the parked label, or None when nothing
        was eligible."""
        pool = [ex for ex in self._executors if ex.label not in self._parked]
        if len(pool) <= 1:
            return None
        if label is None:
            idle = [
                ex
                for ex in pool
                if ex.batches_out() == 0
                and self._health_of(ex.label).admissible()
            ]
            if not idle:
                return None
            ex = max(idle, key=lambda e: e.index)
        else:
            ex = next((e for e in pool if e.label == label), None)
            if ex is None or ex.batches_out() > 0:
                return None
        self._parked.add(ex.label)
        if ex.batches_out() > 0:
            # raced with a placer between the idle check and the park:
            # back out rather than strand the in-flight batch
            self._parked.discard(ex.label)
            return None
        swept = ex.abandon()
        self._watchdog.forget_label(ex.label)
        if swept:
            from ..errors import TransientBackendError

            self._redistribute(
                swept,
                TransientBackendError(
                    "executor %s parked mid-submit" % (ex.label,)
                ),
            )
        metrics.count("elastic_parked")
        metrics.set_gauge(
            "elastic_active_executors", self.active_pool_size()
        )
        self._refresh_health_gauges()
        return ex.label

    def unpark_executor(self, label=None):
        """Elastic GROW: return a parked executor to placement via the
        PR 9 respawn path (Executor.start() under a fresh generation).
        Returns the unparked label, or None when nothing was parked."""
        if label is None:
            if not self._parked:
                return None
            label = min(self._parked)
        if label not in self._parked:
            return None
        self._parked.discard(label)
        ex = next((e for e in self._executors if e.label == label), None)
        if ex is not None:
            ex.start()
        metrics.count("elastic_unparked")
        metrics.set_gauge(
            "elastic_active_executors", self.active_pool_size()
        )
        self._refresh_health_gauges()
        self._kick_all()
        return label

    # -- placement -----------------------------------------------------------

    def _route(self, requests):
        """The adaptive placement policy: "sharded" (dp-sharded across the
        mesh) or "single" (whole batch to one device). The program, batch
        size, and lane decide: only mesh-capable programs' batches of at
        least `sharded_min_lanes` with NO interactive requests take the
        mesh — a turnstile request never pays a cross-chip collective on
        its latency path, while bulk backfill batches get every chip."""
        if self._mesh_executor is None:
            return "single"
        if not self._program_of(requests).program.supports_mesh:
            return "single"
        if len(requests) < self.sharded_min_lanes:
            return "single"
        if any(r.lane == "interactive" for r in requests):
            return "single"
        return "sharded"

    def _has_capacity(self):
        """ready() gate for the pool batchers: pop a batch only when some
        ADMISSIBLE executor can take it, otherwise the backlog stays in
        the bounded queue where admission control (and the brownout
        policy) can see and refuse it. Quarantined executors contribute no
        capacity."""
        return any(
            self._admits(ex) and ex.can_accept()
            for ex in self._all_executors()
        )

    def _place(self, requests):
        """Pick the executor for one coalesced batch: the policy's route
        over the ADMISSIBLE pool, with capacity spill (a full mesh lane
        falls back to the least-loaded device and vice versa — adaptive,
        never blocking a popped batch behind one hot executor). Routing a
        batch to a PROBATION executor is that executor's half-open probe
        (counted under "serve_probes")."""
        rt = self._program_of(requests)
        prog = rt.program
        route = self._route(requests)
        metrics.count(
            "serve_placed_sharded" if route == "sharded" else
            "serve_placed_single"
        )
        mesh_ex = self._mesh_executor if prog.supports_mesh else None
        if mesh_ex is not None and not self._admits(mesh_ex):
            mesh_ex = None
        admitted = [ex for ex in self._executors if self._admits(ex)]
        singles = [ex for ex in admitted if ex.can_accept()]
        singles.sort(key=lambda ex: (ex.load(), ex.index))
        if route == "sharded" and mesh_ex is not None:
            chosen = (
                mesh_ex
                if mesh_ex.can_accept()
                else (singles[0] if singles else mesh_ex)
            )
        elif singles:
            chosen = singles[0]
        elif mesh_ex is not None and mesh_ex.can_accept():
            chosen = mesh_ex
        else:
            # no admissible executor has capacity: overflow onto the
            # least-loaded admissible one (capacity is advisory;
            # quarantine is not) — or, with the WHOLE pool quarantined,
            # onto any executor whose worker is still alive: settling
            # behind a sick device beats parking a future behind a probe
            # that may never come. Mesh-incapable programs never
            # overflow onto the mesh lane.
            candidates = (
                self._all_executors()
                if prog.supports_mesh
                else list(self._executors)
            )
            candidates = [
                ex for ex in candidates if ex.label not in self._parked
            ] or candidates
            pool = (
                admitted
                or [ex for ex in candidates if ex.has_worker()]
                or [
                    ex
                    for ex in self._executors
                    if ex.label not in self._parked
                ]
                or self._executors
            )
            chosen = min(pool, key=lambda ex: (ex.load(), ex.index))
        if (route == "sharded") != (chosen.placement == "sharded"):
            metrics.count("serve_placed_spill")
        if self._health_of(chosen.label).state == _health.PROBATION:
            metrics.count("serve_probes")
        metrics.set_gauge(
            "%s_queue_depth" % prog.metric_ns, rt.queue.depth()
        )
        return chosen

    # -- batch work (runs on executor threads) -------------------------------

    def _launch(self, requests, executor=None):
        """Assemble + dispatch one coalesced batch NOW on `executor`'s
        device; return the settle closure state. Mirrors
        stream.verify_stream's launch(): the first dispatch attempt is
        consumed eagerly (pipelining), finalize() re-runs the full
        dispatch+readback cycle under the retry ladder, then the
        program's fallback."""
        rt = self._program_of(requests)
        prog = rt.program
        if executor is None:
            executor = self._executors[0]
        seq = self._next_seq()
        metrics.count("serve_dev%s_dispatches" % executor.label)
        metrics.count("serve_dev%s_requests" % executor.label, len(requests))
        bspan = otrace.start_span(
            "batch",
            root=True,
            seq=seq,
            n=len(requests),
            device=executor.label,
            placement=executor.placement,
            program=prog.name,
            members=[r.future.trace_id for r in requests]
            if otrace.enabled()
            else None,
        )
        for r in requests:
            # the request->batch join: a request's trace knows which
            # batch trace (hence which DEVICE) did its device work
            r.span.set(batch_trace=bspan.trace_id, batch_seq=seq)
        # deadline-track from BEFORE the first dispatch attempt: a sync
        # dispatch that hangs never returns from this very call, and the
        # watchdog is the only thing that can still free its batch
        self._watchdog.begin(
            executor.label, seq, requests, span=bspan, now=self.clock()
        )
        with otrace.use(bspan), metrics.timer(executor.busy_timer):
            with otrace.span("coalesce"):
                payload_a, payload_b = prog.assemble(requests, bspan)
            metrics.observe(
                "%s_batch_wait_s" % prog.metric_ns,
                self.clock() - min(r.t_submit for r in requests),
            )
            shape = (
                prog.name,
                executor.placement,
                prog.shape_key(requests, payload_a, payload_b),
            )
            if shape not in self._shape_keys:
                # a shape this program has not dispatched before — on a
                # jitted backend this is the compile; a flat counter
                # after warmup is the no-cross-program-recompile proof
                self._shape_keys.add(shape)
                metrics.count("%s_jit_shapes" % prog.metric_ns)
            attempts = []
            box = [None]
            permanent = None
            with otrace.span(
                "dispatch",
                backend=prog.backend_label(),
                device=executor.label,
            ):
                try:
                    box[0] = prog.run_dispatch(executor, payload_a, payload_b)
                except prog.retry_policy.retryable as e:
                    note_attempt(attempts, e)
                    otrace.event(
                        "attempt_failed",
                        attempt=len(attempts),
                        error=type(e).__name__,
                    )
                except Exception as e:
                    # permanent dispatch failure (bad inputs, code bug in
                    # a sync backend's compute): unlike the offline
                    # stream — where it aborts the run — the service
                    # contains it to THIS batch's futures; finalize
                    # re-raises without burning retries
                    permanent = e
                    otrace.event("permanent_failure", error=type(e).__name__)

        def cycle():
            fin, box[0] = box[0], None
            if fin is None:
                fin = prog.run_dispatch(executor, payload_a, payload_b)
            return fin()

        fallback = prog.make_fallback(payload_a, payload_b)

        def finalize():
            if permanent is not None:
                raise permanent
            return call_with_retry(
                cycle,
                prog.retry_policy,
                key=seq,
                attempts=attempts,
                fallback=fallback,
            )

        return (
            seq,
            requests,
            payload_a,
            payload_b,
            finalize,
            attempts,
            bspan,
            executor,
        )

    def _settle(
        self,
        seq,
        requests,
        payload_a,
        payload_b,
        finalize,
        attempts,
        bspan,
        executor=None,
    ):
        """Block on the batch result and resolve every request's future."""
        prog = self._program_of(requests).program
        if executor is None:
            executor = self._executors[0]
        with otrace.use(bspan), metrics.timer(executor.busy_timer):
            try:
                with otrace.span("device", device=executor.label):
                    result = finalize()
            except Exception as e:
                self._watchdog.end(
                    executor.label, seq, ok=False, now=self.clock()
                )
                if requests and all(r.future.done() for r in requests):
                    # stale settle: the watchdog timed this batch out and
                    # it was redistributed (and resolved) elsewhere — the
                    # late failure is nobody's news
                    bspan.end(result="stale")
                    return
                # batch-level failure past retry+fallback: each
                # cohabiting future gets the exception — never a silent
                # hang, and never another device's problem
                prog.fail_batch(requests, e)
                bspan.end(error=type(e).__name__)
                self._note_failure(executor, e)
                return
            self._watchdog.end(executor.label, seq, now=self.clock())
            if requests and all(r.future.done() for r in requests):
                # stale settle (watchdog fired, batch redistributed): the
                # verdicts were already delivered by the re-dispatch;
                # drop these — ServeFuture is single-assignment anyway
                bspan.end(result="stale")
                return
            self._note_success(executor)
            prog.demux(
                requests, result, payload_a, payload_b, seq, attempts, bspan
            )

    # -- placers -------------------------------------------------------------

    def _crash(self, e):
        """Placer crash, or the LAST executor died: sweep every queued and
        inbox future — across EVERY program — with the crash exception so
        no caller ever hangs."""
        self._crashed = e
        for rt in self._order:
            rt.queue.close()
        for rt in self._order:
            fail_all(
                rt.queue.drain_pending(),
                e,
                counter="%s_failed_requests" % rt.program.metric_ns,
            )
        for rt in self._order:
            rt.program.on_crash(e)
        for ex in self._all_executors():
            ex.poison(e)

    def _run_program(self, rt):
        try:
            while True:
                batch = rt.batcher.next_batch(
                    block=True, ready=rt.program.capacity_ready
                )
                if batch is None:
                    # closed and fully routed: executors drain their
                    # inboxes; drain()/shutdown() closes and joins them
                    return
                rt.program.place(batch)
        except BaseException as e:
            self._crash(e)
            raise
