"""The remaining online Coconut phases as engine programs (PR 12).

PR 6-10 put two of the protocol's five phases online (verify —
serve.VerifyProgram; blind-sign/mint — issue.MintProgram). This module
registers the other three as first-class online workloads on the SAME
executor pool, each with its own queue, metric namespace, SLO class,
pad-lane convention, and jit-shape cache key:

  PrepareProgram     user-side PrepareBlindSign, batched ("prep" ns,
                     bulk SLO): coalesced unrelated users, each
                     encrypting under their OWN ElGamal key — the
                     per-request-pk extension of
                     signature.batch_prepare_blind_sign. Pad lanes
                     repeat the last request's row (every lane is
                     independent; pad outputs are discarded).
  ShowProveProgram   prover side of Show ("prove" ns, interactive SLO):
                     pok_sig.batch_show over the coalesced credentials,
                     one shared revealed-index set per program instance.
                     Pad lanes repeat the last credential.
  ShowVerifyProgram  verifier side of Show ("showv" ns, interactive
                     SLO): ps.batch_show_verify with EXPLICIT per-lane
                     challenges. Pad lanes clone the first proof (and
                     its challenge) — a structurally valid row whose
                     verdict is discarded, keeping the fused kernel's
                     uniform revealed-index shape.

All three ride the shared device pool: engine._seed_pool_program gives
every executor a per-program dispatch closure, and the per-program
"%ns_jit_shapes" counters prove warmed-up cross-program traffic never
recompiles. engine/session.ProtocolEngine registers all five phases on
one engine instance."""

from .. import metrics
from ..obs import trace as otrace
from .program import Program


class ShowOrder:
    """One show-verify submission: the proof plus its Fiat-Shamir
    challenge (None = recompute from the transcript at assemble time)
    and the mint epoch of the credential being shown (None = the boot
    verkey; PR 15). `domain`/`tag` (PR 19) optionally scope the
    derived nullifier to an application domain (petition campaign,
    e-cash) with a deterministic spend tag — see state/nullifier.py."""

    __slots__ = ("proof", "challenge", "epoch", "domain", "tag")

    def __init__(self, proof, challenge=None, epoch=None, domain=None,
                 tag=None):
        self.proof = proof
        self.challenge = challenge
        self.epoch = epoch
        self.domain = domain
        self.tag = tag


def _group_by_epoch(epochs):
    """index lists per epoch, preserving arrival order within a group."""
    groups = {}
    for i, e in enumerate(epochs):
        groups.setdefault(e, []).append(i)
    return groups


def _demux_results(requests, results, metric_ns, clock):
    """Resolve each request's future with its own lane's output (pad
    lanes beyond len(requests) are discarded)."""
    with otrace.span("demux", n=len(requests)):
        now = clock()
        for req, out in zip(requests, results):
            metrics.observe("%s_latency_s" % metric_ns, now - req.t_submit)
            req.span.end(ok=True)
            req.future.set_result(out)
        metrics.count("%s_done" % metric_ns, len(requests))


class PrepareProgram(Program):
    """Batched user-side PrepareBlindSign: submit (messages, elgamal_pk),
    receive (SignatureRequest, randomness) — randomness = [r, k_1..k_h],
    the PoK witness. One `count_hidden` per program instance (the
    batchable shape)."""

    name = "prepare"
    metric_ns = "prep"
    slo_class = "bulk"  # throughput work: first to shed under brownout
    pad_convention = "repeat-last-row"

    def __init__(self, params, count_hidden, backend=None, max_batch=64,
                 max_wait_ms=20.0, max_depth=1024, pad_partial=True):
        self.params = params
        self.count_hidden = count_hidden
        self.backend = backend
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_depth = max_depth
        self.pad_partial = pad_partial

    def make_dispatch(self, device=None):
        from ..signature import batch_prepare_blind_sign

        params, count_hidden, backend = (
            self.params, self.count_hidden, self.backend,
        )

        def dispatch(messages_list, pks):
            out = batch_prepare_blind_sign(
                messages_list, count_hidden, list(pks), params,
                backend=backend,
            )
            return lambda: out

        return dispatch, False

    def assemble(self, requests, bspan):
        messages_list = [list(r.messages) for r in requests]
        pks = [r.sig for r in requests]
        n_pad = max(0, self.max_batch - len(requests))
        if self.pad_partial and n_pad:
            messages_list.extend([list(messages_list[-1])] * n_pad)
            pks.extend([pks[-1]] * n_pad)
            metrics.count("prep_pad_lanes", n_pad)
            bspan.set(n_pad=n_pad)
        return messages_list, pks

    def shape_key(self, requests, payload_a, payload_b):
        # the device hash-to-G1 path (PR 18) is its own jitted program
        # per batch width: key it so a knob flip mid-run shows up as a
        # NEW shape, never as a silent recompile under an old key —
        # the "%ns_jit_shapes flat after warmup" proof stays sound
        hash_path = (
            "devhash"
            if getattr(self.backend, "device_hash_enabled", None)
            is not None
            and self.backend.device_hash_enabled()
            else "hosthash"
        )
        return (len(payload_a), hash_path)

    def demux(self, requests, result, messages_list, pks, seq, attempts,
              bspan):
        _demux_results(requests, result, self.metric_ns, self.engine.clock)
        bspan.end(result="demuxed")


class ShowProveProgram(Program):
    """Batched prover side of Show: submit (credential, messages),
    receive (proof, challenge, revealed_msgs). One revealed-index set per
    program instance (pok_sig.batch_show's batchable shape)."""

    name = "show_prove"
    metric_ns = "prove"
    slo_class = "interactive"  # a user is waiting on their own proof
    pad_convention = "repeat-credential"

    def __init__(self, vk, params, revealed_msg_indices, backend=None,
                 max_batch=64, max_wait_ms=20.0, max_depth=1024,
                 pad_partial=True, keychain=None):
        self.vk = vk
        self.params = params
        self.revealed_msg_indices = list(revealed_msg_indices)
        self.backend = backend
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_depth = max_depth
        self.pad_partial = pad_partial
        #: keylife.EpochRegistry: a credential's `epoch` attribute picks
        #: the verkey its show proof is built against (PR 15)
        self.keychain = keychain

    def _vk_for(self, epoch):
        if epoch is None or self.keychain is None:
            return self.vk
        return self.keychain.resolve(epoch).vk

    def make_dispatch(self, device=None):
        from ..pok_sig import batch_show

        params, revealed, backend = (
            self.params, self.revealed_msg_indices, self.backend,
        )

        def dispatch(sigs, messages_list):
            if self.keychain is None:
                out = batch_show(
                    sigs, self.vk, params, messages_list, revealed,
                    backend=backend,
                )
                return lambda: out
            # epoch-partitioned: each group proves against ITS epoch's
            # verkey (one epoch per steady-state batch; rollovers rare)
            groups = _group_by_epoch(
                [getattr(s, "epoch", None) for s in sigs]
            )
            proofs = [None] * len(sigs)
            challenges = [None] * len(sigs)
            revealed_out = [None] * len(sigs)
            for epoch, idxs in groups.items():
                p, c, rv = batch_show(
                    [sigs[i] for i in idxs],
                    self._vk_for(epoch),
                    params,
                    [messages_list[i] for i in idxs],
                    revealed,
                    backend=backend,
                )
                for i, pi, ci, ri in zip(idxs, p, c, rv):
                    proofs[i], challenges[i], revealed_out[i] = pi, ci, ri
            out = (proofs, challenges, revealed_out)
            return lambda: out

        return dispatch, False

    def assemble(self, requests, bspan):
        sigs = [r.sig for r in requests]
        messages_list = [list(r.messages) for r in requests]
        n_pad = max(0, self.max_batch - len(requests))
        if self.pad_partial and n_pad:
            sigs.extend([sigs[-1]] * n_pad)
            messages_list.extend([list(messages_list[-1])] * n_pad)
            metrics.count("prove_pad_lanes", n_pad)
            bspan.set(n_pad=n_pad)
        return sigs, messages_list

    def shape_key(self, requests, payload_a, payload_b):
        # the distinct-base MSM behind batch_show has two device
        # schedules (PR 18): signed-Horner and the bucketed Pippenger
        # path at a cost-model window. Selection is deterministic per
        # (k, group, platform), but key the mode anyway so a forced
        # COCONUT_MSM_WINDOW flip mid-run surfaces as a new shape —
        # the "%ns_jit_shapes flat after warmup" proof stays sound
        try:
            from ..tpu import backend as tb

            tb._bucket_window(0, 255)  # k=0: resolve the knob, pick nothing
            mode = tb._BUCKET_MODE
        except Exception:  # pragma: no cover - non-jax backend stacks
            mode = None
        return (len(payload_a), "msm%s" % (mode,))

    def demux(self, requests, result, sigs, messages_list, seq, attempts,
              bspan):
        proofs, challenges, revealed_list = result
        _demux_results(
            requests,
            list(zip(proofs, challenges, revealed_list)),
            self.metric_ns,
            self.engine.clock,
        )
        bspan.end(result="demuxed")


class ShowVerifyProgram(Program):
    """Batched verifier side of Show: submit a ShowOrder (proof [+
    challenge]) with its revealed-message map, receive the verdict bool.
    Challenges are ALWAYS passed explicitly to ps.batch_show_verify —
    pad lanes clone the first proof, and a cloned lane must reuse its
    original's challenge, never re-derive one."""

    name = "show_verify"
    metric_ns = "showv"
    slo_class = "interactive"
    pad_convention = "clone-first-proof"

    def __init__(self, vk, params, backend=None, max_batch=64,
                 max_wait_ms=20.0, max_depth=1024, pad_partial=True,
                 keychain=None, mode="exact", nullifiers=None,
                 dead_letters=None):
        if mode not in ("exact", "batched"):
            raise ValueError("unknown show-verify mode %r" % (mode,))
        if mode == "batched" and backend is None:
            raise ValueError(
                "show-verify mode='batched' requires a backend"
            )
        self.vk = vk
        self.params = params
        self.backend = backend
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.max_depth = max_depth
        self.pad_partial = pad_partial
        #: "exact" re-checks every lane's two pairings; "batched" (PR 16)
        #: folds the whole batch into ONE RLC-combined pairing product
        #: with a shared final exponentiation, bisecting on rejection
        self.mode = mode
        #: keylife.EpochRegistry: each ShowOrder's `epoch` picks the
        #: verkey its proof verifies (and re-hashes) against (PR 15)
        self.keychain = keychain
        #: state.NullifierGuard (PR 17): when set, every lane derives a
        #: nullifier from its transcript, a device membership probe is
        #: fused ahead of the verify bit, and accepted nullifiers are
        #: WAL-group-committed BEFORE any future resolves — a
        #: double-spent lane resolves to a typed DoubleSpendError
        self.nullifiers = nullifiers
        #: faults.DeadLetterLog: double-spend rejections append a
        #: schema-v4 line carrying the spent nullifier
        self.dead_letters = dead_letters

    def _vk_for(self, epoch):
        if epoch is None or self.keychain is None:
            return self.vk
        return self.keychain.resolve(epoch).vk

    def make_dispatch(self, device=None):
        from ..ps import batch_show_verify

        params, backend = self.params, self.backend

        def dispatch(proofs, aux):
            revealed_list, challenges = aux[0], aux[1]
            epochs = aux[2] if len(aux) > 2 else None
            digests = aux[3] if len(aux) > 3 else None
            null_epochs = aux[4] if len(aux) > 4 else None
            null_domains = aux[5] if len(aux) > 5 else None
            if epochs is None:
                out = list(batch_show_verify(
                    proofs, self.vk, params, revealed_list,
                    challenges=challenges, backend=backend,
                    mode=self.mode,
                ))
            else:
                out = [False] * len(proofs)
                for epoch, idxs in _group_by_epoch(epochs).items():
                    bits = batch_show_verify(
                        [proofs[i] for i in idxs],
                        self._vk_for(epoch),
                        params,
                        [revealed_list[i] for i in idxs],
                        challenges=[challenges[i] for i in idxs],
                        backend=backend,
                        mode=self.mode,
                        epoch=epoch,
                    )
                    for i, b in zip(idxs, bits):
                        out[i] = bool(b)
            if digests is not None and self.nullifiers is not None:
                # fused double-spend probe: a spent lane fails ITS OWN
                # verify bit here, inside the batch computation, not in
                # a serial post-pass. Advisory — the table snapshot may
                # lag a concurrent commit; demux's check-and-set under
                # the store lock is authoritative either way, so a
                # probe failure degrades to commit-time detection.
                try:
                    spent = self.nullifiers.probe(
                        digests, null_epochs, domains=null_domains
                    )
                except Exception:
                    spent = None
                    metrics.count("nullifier_probe_errors")
                if spent is not None:
                    out = [
                        bool(b) and not s for b, s in zip(out, spent)
                    ]
            return lambda: out

        return dispatch, False

    def shape_key(self, requests, payload_a, payload_b):
        if self.mode == "batched":
            # the combined show kernel clone-pads to a power of two —
            # the jit-shape key is that padded width, not the raw count
            from .core import _next_pow2

            return ("batched", _next_pow2(max(1, len(payload_a))))
        return super().shape_key(requests, payload_a, payload_b)

    def assemble(self, requests, bspan):
        from ..signature import fiat_shamir_challenge

        proofs = [r.sig.proof for r in requests]
        revealed_list = [dict(r.messages) for r in requests]
        epochs = (
            [getattr(r.sig, "epoch", None) for r in requests]
            if self.keychain is not None
            else None
        )
        challenges = [
            r.sig.challenge
            if r.sig.challenge is not None
            else fiat_shamir_challenge(
                r.sig.proof.to_bytes_for_challenge(
                    # a stranger-verifier transcript re-hash must bind
                    # the SAME verkey the prover hashed: the mint epoch's
                    self._vk_for(getattr(r.sig, "epoch", None)),
                    self.params,
                )
            )
            for r in requests
        ]
        digests = null_epochs = null_domains = None
        if self.nullifiers is not None:
            from ..state.nullifier import nullifier_of

            # derived BEFORE padding: pad lanes clone lane 0's digest
            # below, and demux never looks past len(requests), so a
            # cloned pad digest can never masquerade as a second spend
            null_epochs = [
                getattr(r.sig, "epoch", None) for r in requests
            ]
            null_domains = [
                getattr(r.sig, "domain", None) for r in requests
            ]
            digests = [
                nullifier_of(
                    p, c, e, self.params,
                    domain=dom, tag=getattr(r.sig, "tag", None),
                )
                for p, c, e, dom, r in zip(
                    proofs, challenges, null_epochs, null_domains,
                    requests,
                )
            ]
        n_pad = max(0, self.max_batch - len(requests))
        if self.pad_partial and n_pad:
            proofs.extend([proofs[0]] * n_pad)
            revealed_list.extend([dict(revealed_list[0])] * n_pad)
            challenges.extend([challenges[0]] * n_pad)
            if epochs is not None:
                epochs.extend([epochs[0]] * n_pad)
            if digests is not None:
                digests.extend([digests[0]] * n_pad)
                null_epochs.extend([null_epochs[0]] * n_pad)
                null_domains.extend([null_domains[0]] * n_pad)
            metrics.count("showv_pad_lanes", n_pad)
            bspan.set(n_pad=n_pad)
        if digests is not None:
            return proofs, (
                revealed_list, challenges, epochs, digests, null_epochs,
                null_domains,
            )
        if epochs is not None:
            return proofs, (revealed_list, challenges, epochs)
        return proofs, (revealed_list, challenges)

    def _reject_double_spend(self, req, digest, epoch, seq, lane,
                             domain=None):
        """Resolve one lane as a typed double-spend rejection (and
        dead-letter it with the spent nullifier, schema v4)."""
        from ..errors import DoubleSpendError

        req.span.end(error="double_spend")
        req.future.set_exception(DoubleSpendError(digest, epoch, domain))
        if self.dead_letters is not None:
            try:
                self.dead_letters.append(
                    seq,
                    lane,
                    "double_spend",
                    trace_id=getattr(req.future, "trace_id", None),
                    program=self.name,
                    nullifier=digest,
                )
            except Exception:  # pragma: no cover - sink failure
                metrics.count("dead_letter_errors")

    def demux(self, requests, result, proofs, aux, seq, attempts, bspan):
        # NOTE: core._settle calls demux OUTSIDE its per-batch
        # containment — an exception escaping here would crash the
        # executor loop, so every durability failure is converted into
        # per-lane outcomes instead of being allowed to propagate.
        from ..errors import TransientBackendError

        digests = aux[3] if len(aux) > 3 else None
        null_epochs = aux[4] if len(aux) > 4 else None
        null_domains = aux[5] if len(aux) > 5 else None
        guard = self.nullifiers
        with otrace.span("demux", n=len(requests)):
            now = self.engine.clock()
            n = len(requests)
            bits = [bool(b) for b in list(result)[:n]]
            committed = commit_err = None
            if guard is not None and digests is not None:
                # authoritative check-and-set: accepted lanes re-check
                # the live set (and each other) under the store lock,
                # then ONE WAL group commit persists the batch's new
                # nullifiers BEFORE any future below resolves
                try:
                    committed = guard.commit(
                        digests[:n],
                        epochs=list(null_epochs[:n]),
                        accept=bits,
                        domains=list(null_domains[:n]),
                    )
                except Exception as e:
                    commit_err = e
                    metrics.count("nullifier_commit_errors")
            n_valid = 0
            for i, (req, ok) in enumerate(zip(requests, bits)):
                metrics.observe("showv_latency_s", now - req.t_submit)
                if guard is not None and digests is not None:
                    if ok and commit_err is not None:
                        # the WAL could not persist the acceptance —
                        # resolving True would acknowledge a fact a
                        # restart forgets. Fail the lane retryably.
                        req.span.end(error="nullifier_commit")
                        req.future.set_exception(
                            TransientBackendError(
                                "nullifier WAL commit failed: %s"
                                % (commit_err,)
                            )
                        )
                        continue
                    if ok and committed is not None and not committed[i]:
                        # lost the check-and-set: a concurrent batch
                        # (or an intra-batch duplicate) spent it first
                        self._reject_double_spend(
                            req, digests[i], null_epochs[i], seq, i,
                            domain=null_domains[i],
                        )
                        continue
                    if not ok and guard.seen(
                        digests[i], null_epochs[i], null_domains[i]
                    ):
                        # the fused probe masked the lane's verify bit:
                        # surface the TYPED rejection, not a bare False
                        metrics.count("nullifier_double_spends")
                        self._reject_double_spend(
                            req, digests[i], null_epochs[i], seq, i,
                            domain=null_domains[i],
                        )
                        continue
                n_valid += ok
                req.span.end(verdict=ok)
                req.future.set_result(ok)
            metrics.count("showv_valid", n_valid)
            metrics.count("showv_invalid", len(requests) - n_valid)
        bspan.end(result="demuxed")
