"""ProtocolEngine: all five Coconut phases online on ONE engine (PR 12).

One ExecutionEngine instance, five registered programs, one device pool:

  verify        (serve.VerifyProgram, primary)   pool
  prepare       (phases.PrepareProgram)          pool
  show_prove    (phases.ShowProveProgram)        pool
  show_verify   (phases.ShowVerifyProgram)       pool
  mint          (issue.MintProgram)              own workers (authorities)

The pool programs multiplex heterogeneous batches over the same
executors — each executor carries a per-program dispatch registry, each
program keeps its own jit-shape cache key, so a warmed-up mixed workload
never cross-program recompiles (the per-program "%ns_jit_shapes"
counters are the proof). The mint program brings the authority pool;
its labels take an "m" prefix ("m1", "m2", ...) so authority
watchdog/health keys never collide with pool executor labels
("0", "1", ..., "mesh").

A full protocol session walks one credential through four online hops:

    prepare  -> (SignatureRequest, randomness)
    mint     -> credential (threshold blind-sign, verified release)
    show_prove  -> (proof, challenge, revealed_msgs)
    show_verify -> verdict bool

serve/loadgen.run_session_loadgen drives exactly that pipeline and
reports end-to-end session latency percentiles next to per-program
goodput; probes/probe_engine.py is the mixed-program CPU smoke."""

import time

from ..issue.service import IssuanceOrder, MintProgram
from ..serve.service import VerifyProgram
from ..signature import Verkey
from .core import ExecutionEngine
from .phases import (
    PrepareProgram,
    ShowOrder,
    ShowProveProgram,
    ShowVerifyProgram,
)


class ProtocolEngine(ExecutionEngine):
    """One engine serving every online Coconut phase.

    signers/threshold: the issuing authority set (keygen.Signer list) —
    also the source of the aggregated show verkey when `vk` is None.
    count_hidden: the prepare lane's hidden-attribute count;
    revealed_msg_indices: the show lanes' shared disclosure set.
    backend: one backend (instance or name) shared by every pool
    program and the authorities. devices: the pool shape, exactly as
    CredentialService. Self-healing knobs are the engine's (see
    serve/service.py)."""

    def __init__(
        self,
        signers,
        params,
        threshold,
        count_hidden,
        revealed_msg_indices,
        vk=None,
        backend=None,
        minter=None,
        devices=None,
        max_batch=32,
        max_wait_ms=20.0,
        max_depth=1024,
        pad_partial=True,
        clock=time.monotonic,
        health_policy=None,
        watchdog=None,
        watchdog_interval_s=0.25,
        brownout=None,
        hedge=None,
        max_redispatch=None,
        keychain=None,
        showv_mode=None,
        state_store=None,
        dead_letter_path=None,
    ):
        from ..backend import get_backend
        from ..batchverify import env_batched_default

        if backend is None or isinstance(backend, str):
            backend = get_backend(backend or "python")
        if showv_mode is None:
            # COCONUT_BATCH_VERIFY=1 defaults the show-verify lane onto
            # the RLC-combined pairing path (PR 16)
            showv_mode = "batched" if env_batched_default() else "exact"
        signers = list(signers)
        if vk is None:
            vk = Verkey.aggregate(
                threshold,
                [(s.id, s.verkey) for s in signers],
                ctx=params.ctx,
            )

        super().__init__(
            name="coconut-protocol",
            metric_ns="serve",
            clock=clock,
            health_policy=health_policy,
            watchdog=watchdog,
            watchdog_interval_s=watchdog_interval_s,
            brownout=brownout,
        )
        self.backend = backend
        self.vk = vk
        self.params = params
        self.threshold = threshold
        self.count_hidden = count_hidden
        self.revealed_msg_indices = list(revealed_msg_indices)
        #: keylife.EpochRegistry (PR 15): epoch-stamped credentials
        #: resolve their verkey by mint epoch on every phase; None = the
        #: historical single-verkey engine
        self.keychain = keychain
        #: state.StateStore (PR 17): the replica's durable state plane.
        #: When set, show-verify grows the replicated nullifier/double-
        #: spend subsystem: a NullifierGuard over the store (device
        #: membership probe + WAL-group-committed check-and-set) and a
        #: store-indexed dead-letter log. The beacon (net/rpc.py)
        #: piggybacks `state_store.marks()` for anti-entropy.
        self.state_store = state_store
        self.nullifiers = None
        self.dead_letters = None
        if state_store is not None:
            from ..faults import DeadLetterLog
            from ..state.nullifier import NullifierGuard

            self.nullifiers = NullifierGuard(state_store)
            # PR 19: executor-health history rides the same store — a
            # restarted replica remembers which devices were flapping
            self.attach_health_journal(state_store)
            if keychain is not None and hasattr(
                keychain, "add_retire_hook"
            ):
                # epoch retirement drops that epoch's nullifier
                # keyspace wholesale and compacts the WAL under it —
                # submit-time _check_epoch already refuses retired
                # shows before any membership probe would run
                keychain.add_retire_hook(self.nullifiers.retire_epoch)
            if dead_letter_path is not None:
                self.dead_letters = DeadLetterLog(
                    dead_letter_path, store=state_store
                )

        common = dict(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_depth=max_depth,
        )
        self._verify = VerifyProgram(
            backend,
            vk,
            params,
            "per_credential",
            max_batch,
            max_wait_ms,
            max_depth,
            pad_partial,
            None,  # retry_policy: bind() installs the no-ladder default
            None,  # fallback_dispatch
            None,  # bisector (grouped-mode only)
            keychain=keychain,
        )
        self.register(self._verify)  # primary: the pool's seed dispatch
        self._prepare = PrepareProgram(
            params, count_hidden, backend=backend,
            pad_partial=pad_partial, **common
        )
        self._prove = ShowProveProgram(
            vk, params, self.revealed_msg_indices, backend=backend,
            pad_partial=pad_partial, keychain=keychain, **common
        )
        self._showv = ShowVerifyProgram(
            vk, params, backend=backend, pad_partial=pad_partial,
            keychain=keychain, mode=showv_mode,
            nullifiers=self.nullifiers, dead_letters=self.dead_letters,
            **common
        )
        for prog in (self._prepare, self._prove, self._showv):
            self.register(prog)

        # the shared pool: verify's device-pinned dispatch is each
        # executor's primary closure; the other pool programs seed their
        # own per-program closures on every executor
        if devices is None:
            device_list = [None]
        elif isinstance(devices, int):
            if devices < 1:
                raise ValueError("devices must be >= 1 (got %r)" % (devices,))
            device_list = [None] * devices
        else:
            device_list = list(devices)
            if not device_list:
                raise ValueError("devices list must be non-empty")
        for dev in device_list:
            dispatch, is_async = self._verify.make_dispatch(device=dev)
            self._add_executor(device=dev, dispatch=dispatch,
                               is_async=is_async)
        for prog in (self._prepare, self._prove, self._showv):
            self._seed_pool_program(prog)

        self._mint = MintProgram(
            signers,
            params,
            threshold,
            backend=backend,
            minter=minter,
            hedge=hedge,
            # non-numeric labels keep authority watchdog/health keys
            # disjoint from pool executor labels ("0", "1", ..., "mesh");
            # metrics read "issue_authm1_*" (mint authority 1)
            label_prefix="m",
            keychain=keychain,
            **common
        )
        self.register(self._mint)

        self._finalize_pool(max_redispatch)

    # -- key lifecycle (PR 15) -----------------------------------------------

    def install_keyset(self, keyset):
        """KeyLifecycleManager hook: new share sets go to the mint
        program's authorities; verify/show resolve epochs straight off
        the shared keychain."""
        self._mint.install_keyset(keyset)
        self.threshold = self._mint.threshold

    def _check_epoch(self, epoch):
        """Submit-time pre-validation: an unknown or retired mint epoch
        refuses typed (EpochUnknownError / EpochRetiredError) BEFORE
        admission, so the refusal reaches RPC callers through the
        standard error envelope instead of wasting a batch slot."""
        if self.keychain is not None and epoch is not None:
            self.keychain.resolve(epoch)

    # -- per-phase submission ------------------------------------------------

    def submit_verify(self, sig, messages, lane="interactive",
                      max_wait_ms=None):
        self._check_epoch(getattr(sig, "epoch", None))
        return self.submit_request(
            "verify", sig, messages, lane=lane, max_wait_ms=max_wait_ms
        )

    def submit_prepare(self, messages, elgamal_pk, lane="bulk",
                       max_wait_ms=None):
        """Future resolves to (SignatureRequest, randomness) — the
        request goes to mint, the randomness is the caller's PoK
        witness. Bulk lane by default: prepare is throughput work."""
        return self.submit_request(
            "prepare", elgamal_pk, messages, lane=lane,
            max_wait_ms=max_wait_ms,
        )

    def submit_mint(self, sig_request, messages, elgamal_sk,
                    lane="interactive", max_wait_ms=None):
        """Future resolves to the minted (verified, aggregated)
        credential; `messages` is the full vector (the mint program's
        verify-before-release gate needs it)."""
        return self.submit_request(
            "mint",
            IssuanceOrder(sig_request, elgamal_sk),
            messages,
            lane=lane,
            max_wait_ms=max_wait_ms,
        )

    def submit_show_prove(self, sig, messages, lane="interactive",
                          max_wait_ms=None):
        """Future resolves to (proof, challenge, revealed_msgs)."""
        self._check_epoch(getattr(sig, "epoch", None))
        return self.submit_request(
            "show_prove", sig, messages, lane=lane, max_wait_ms=max_wait_ms
        )

    def submit_show_verify(self, proof, revealed_msgs, challenge=None,
                           epoch=None, domain=None, tag=None,
                           lane="interactive", max_wait_ms=None):
        """Future resolves to the show verdict bool. Pass the prover's
        `challenge` to skip the transcript re-hash; None recomputes it
        (the stranger-verifier path). `epoch` is the shown credential's
        mint epoch (None = the boot verkey). `domain`/`tag` (PR 19)
        scope the derived nullifier to an application domain with an
        optional deterministic 32-byte spend tag — the scenario layer's
        hook for "once per campaign" / "a coin spends once" semantics
        (see state/nullifier.py; no-ops without a state store)."""
        self._check_epoch(epoch)
        return self.submit_request(
            "show_verify",
            ShowOrder(proof, challenge, epoch=epoch, domain=domain,
                      tag=tag),
            revealed_msgs,
            lane=lane,
            max_wait_ms=max_wait_ms,
        )
