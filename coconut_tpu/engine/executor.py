"""One device's serving loop: an inbox worker thread running the
launch/settle async double-buffer for ITS device — lifted verbatim out of
serve/service.py (where it was `_DeviceExecutor`) so the verify pool and
every other engine program share one executor implementation.

What the lift adds (PR 12): a per-program dispatch registry. The executor
is constructed with its PRIMARY program's dispatch closure (the verify
pool's historical shape); `seed()` registers additional programs' device
closures and `dispatch_for()` resolves a program name to its closure,
falling back to the primary. One pool thereby multiplexes heterogeneous
batches — a prepare batch and a show-verify batch ride the same inbox,
each dispatched through its own program's (cache-hot) jitted shape.
"""

import threading
from collections import deque

from .. import metrics


class Executor:
    """One device's serving loop: an inbox worker thread running the
    launch/settle async double-buffer for ITS device.

    Load accounting (`load()`: unsettled request lanes) drives the
    placer's least-loaded pick; `can_accept()` bounds unsettled batches
    to 1 (sync dispatch) or 2 (async: one in flight + one being encoded),
    which is the pool-shaped generalization of the old single supervisor's
    double buffer — anything beyond that stays in the request queue where
    admission control is. Settling kicks the engine's queues so a
    capacity-gated placer re-checks.

    GENERATIONS: the worker thread carries the generation it was spawned
    under. `abandon()` (crash containment, watchdog timeout) bumps the
    generation and drops the thread reference — the old worker, possibly
    still stuck inside a hung dispatch, becomes STALE: `_next`/`_finish`
    ignore it, and the engine's stale-settle guard discards whatever it
    eventually returns. `start()` can then respawn a FRESH worker for the
    probation probe."""

    def __init__(
        self,
        service,
        index,
        label=None,
        device=None,
        dispatch=None,
        is_async=False,
        placement="single",
    ):
        self.service = service
        self.index = index
        self.label = str(index) if label is None else label
        self.device = device
        self.dispatch = dispatch
        self.is_async = is_async
        self.placement = placement  # "single" | "sharded"
        self.busy_timer = "serve_dev%s_busy_s" % self.label
        self._prog_dispatch = {}
        self._cond = threading.Condition()
        self._inbox = deque()
        self._load = 0  # unsettled request lanes (queued + in flight)
        self._batches_out = 0  # unsettled batches (capacity bound)
        self._closed = False
        self._gen = 0
        self._thread = None

    # -- program registry ----------------------------------------------------

    def seed(self, program, dispatch):
        """Register `program`'s device dispatch closure on this executor
        (the cross-program multiplexing seam). The primary program keeps
        the bare `.dispatch` attribute — the historical verify-pool shape
        tests stub directly."""
        self._prog_dispatch[program] = dispatch

    def dispatch_for(self, program):
        """The dispatch closure for `program`, falling back to the
        primary `.dispatch` when the program was never seeded here."""
        return self._prog_dispatch.get(program, self.dispatch)

    def supports(self, program):
        return program in self._prog_dispatch or self.dispatch is not None

    # -- placer side ---------------------------------------------------------

    def load(self):
        with self._cond:
            return self._load

    def batches_out(self):
        with self._cond:
            return self._batches_out

    def can_accept(self):
        with self._cond:
            return self._batches_out < (2 if self.is_async else 1)

    def submit_batch(self, requests):
        with self._cond:
            self._inbox.append(requests)
            self._load += len(requests)
            self._batches_out += 1
            load = self._load
            self._cond.notify_all()
        metrics.set_gauge("serve_dev%s_load" % self.label, load)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Spawn the worker thread — a no-op while one is running (or
        after close()). Also the PROBATION revival path: after abandon()
        the thread slot is empty, so start() spawns a fresh worker under
        the new generation."""
        with self._cond:
            if self._closed or self._thread is not None:
                return
            gen = self._gen
            self._thread = threading.Thread(
                target=self._run,
                args=(gen,),
                name="coconut-serve-dev%s.g%d" % (self.label, gen),
                daemon=True,
            )
            thread = self._thread
        thread.start()

    def close(self):
        """Stop accepting; the loop still settles its inbox, then exits."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def join(self, timeout=None):
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def has_worker(self):
        """A live (non-abandoned) worker thread exists — the executor can
        still settle batches, even quarantined."""
        with self._cond:
            return self._thread is not None and self._thread.is_alive()

    def is_current(self, gen):
        with self._cond:
            return gen == self._gen

    def abandon(self):
        """Crash/hang containment: bump the generation (the old worker —
        possibly stuck inside a dispatch that will never return — becomes
        stale), sweep the inbox, zero the load so the placer never routes
        here until a probation probe revives it. Returns the swept
        batches; the CALLER owns redistributing them. Unlike poison(),
        the executor is NOT closed: start() can respawn it."""
        with self._cond:
            self._gen += 1
            self._thread = None
            swept = list(self._inbox)
            self._inbox.clear()
            self._load = 0
            self._batches_out = 0
            self._cond.notify_all()
        metrics.set_gauge("serve_dev%s_load" % self.label, 0)
        return swept

    def sweep_inbox(self):
        """Pull every QUEUED (not yet launched) batch back out — the soft
        quarantine path: the worker stays alive to settle what's in
        flight, but its backlog moves to survivors."""
        with self._cond:
            swept = list(self._inbox)
            self._inbox.clear()
            for batch in swept:
                self._load = max(0, self._load - len(batch))
                self._batches_out = max(0, self._batches_out - 1)
            load = self._load
            self._cond.notify_all()
        metrics.set_gauge("serve_dev%s_load" % self.label, load)
        return swept

    def poison(self, exc):
        """Crash sweep: refuse everything still queued on this device."""
        from ..serve.batcher import fail_all

        with self._cond:
            self._closed = True
            swept = list(self._inbox)
            self._inbox.clear()
            self._load = 0
            self._batches_out = 0
            self._cond.notify_all()
        for batch in swept:
            fail_all(batch, exc)

    # -- worker loop ---------------------------------------------------------

    def _next(self, gen, block):
        with self._cond:
            while True:
                if self._gen != gen:
                    return None  # abandoned: this worker is stale — exit
                if self._inbox:
                    return self._inbox.popleft()
                if self._closed or not block:
                    return None
                self._cond.wait()

    def _finish(self, gen, n_lanes):
        with self._cond:
            if self._gen != gen:
                return  # stale worker: accounting belongs to the new gen
            self._load = max(0, self._load - n_lanes)
            self._batches_out = max(0, self._batches_out - 1)
            load = self._load
        metrics.set_gauge("serve_dev%s_load" % self.label, load)
        # capacity freed: wake every placer gated on ready()
        self.service._kick_all()

    def _run(self, gen):
        svc = self.service
        pending = None  # launched, unsettled (async double-buffer slot)
        current = None  # popped from the inbox, not yet fully handled
        try:
            while True:
                current = self._next(gen, block=pending is None)
                if current is not None:
                    launched = svc._launch(current, self)
                    if pending is not None:
                        svc._settle(*pending)
                        self._finish(gen, len(pending[1]))
                        pending = None
                    if self.is_async:
                        # double-buffer: leave this batch in flight and go
                        # take the next while the device runs
                        pending = launched
                    else:
                        svc._settle(*launched)
                        self._finish(gen, len(current))
                    current = None
                    continue
                if pending is not None:
                    # nothing ready to overlap with: settle the in-flight
                    # batch now instead of holding its latency hostage
                    svc._settle(*pending)
                    self._finish(gen, len(pending[1]))
                    pending = None
                    continue
                # closed/abandoned and inbox empty: exit
                return
        except BaseException as e:  # loop-level crash (a code bug escaping
            # the per-batch containment in _launch/_settle): hand THIS
            # executor's unsettled batches — in-flight and mid-launch — to
            # the engine for quarantine + redistribution; the pool
            # survives unless this was the last executor
            batches = []
            spans = []
            if pending is not None:
                batches.append(pending[1])
                spans.append(pending[6])
            if current is not None and (
                pending is None or current is not pending[1]
            ):
                batches.append(current)
            svc._executor_failed(self, e, batches, spans, gen)
