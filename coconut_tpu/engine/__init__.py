"""The unified execution engine (PR 12): one program-agnostic executor
fabric — pool + placer + health/watchdog/brownout + metrics/tracing
seams — running every online Coconut phase as a registered *program*.

Layout:

  executor.py  Executor: one device's inbox worker thread (the PR-6
               launch/settle async double-buffer), lifted verbatim out of
               serve/service.py, plus a per-program dispatch registry so
               one pool multiplexes heterogeneous batches.
  program.py   Program: the registration contract — (assemble/encode fn,
               dispatch closure, demux fn, pad-lane convention, SLO
               class, jit-shape cache key) plus lifecycle/health hooks.
  core.py      ExecutionEngine: the fabric itself. Owns the queues (one
               bounded RequestQueue + Batcher per program), the executor
               pool, placement, the health registry, the watchdog loop,
               brownout admission, and the generic launch/settle path.
  phases.py    The three phases that had no online path before PR 12:
               PrepareProgram (batched prepare-blind-sign), ShowProve-
               Program (batched selective-disclosure prove), ShowVerify-
               Program (batched show-verify with identity-lane pads).
  session.py   ProtocolEngine: all five phases registered on ONE engine
               instance — full prepare -> mint -> show-prove ->
               show-verify sessions against a single pool.
  lifecycle.py Replica lifecycle (PR 14): ShapeManifest persistence,
               LifecycleController (WARMING -> UP -> DRAINING -> CLOSED
               with warm-boot manifest replay and readiness gating),
               and ElasticPolicy/ElasticController (hysteresis-guarded
               grow/shrink of the executor pool).

serve.CredentialService and issue.IssuanceService are thin program
registrations on this engine (VerifyProgram and MintProgram); their
public APIs, metric names, and span shapes are unchanged.
"""

from .core import ExecutionEngine
from .executor import Executor
from .lifecycle import (
    ElasticController,
    ElasticPolicy,
    LifecycleController,
    ShapeManifest,
)
from .program import Program

__all__ = [
    "ExecutionEngine",
    "Executor",
    "Program",
    "ProtocolEngine",
    "LifecycleController",
    "ShapeManifest",
    "ElasticPolicy",
    "ElasticController",
]


def __getattr__(name):
    # ProtocolEngine pulls in serve/ and issue/ (which import engine.core)
    # — resolve it lazily to keep the package import acyclic
    if name == "ProtocolEngine":
        from .session import ProtocolEngine

        return ProtocolEngine
    raise AttributeError(name)
