"""Replica lifecycle: warm restarts, readiness gating, graceful drain,
and elastic pool sizing (PR 14).

BENCH_r05 records 130-500 s of `*_compile_plus_run_s` per program: a
restarted replica that recompiles every jit shape from scratch is blind
for MINUTES — fatal for rolling a fleet under the north-star traffic.
This module makes restarts cheap and visible:

  SHAPE MANIFEST   ShapeManifest persists the engine's per-program
                   (program, placement, shape_key) set — the exact jit
                   shapes live traffic exercised — to a small JSON
                   artifact at drain time.
  WARM BOOT        LifecycleController.boot() points JAX at the
                   persistent compilation cache (`jax_compilation_cache
                   _dir`, the same knob tpu.enable_compile_cache sets),
                   replays the manifest through engine.warm_shapes()
                   (best-effort AOT priming via Program.warm), and only
                   THEN promotes WARMING -> UP. Readiness is gated on
                   the replay: a replica never advertises itself before
                   its shapes are primed.
  LIFECYCLE STATES WARMING -> UP -> DRAINING -> CLOSED, reported
                   through Replica.beacon() so the fleet's gossip
                   directory (net/gossip.py) keeps new sessions off a
                   warming or draining replica while in-flight work
                   settles.
  GRACEFUL DRAIN   begin_drain() flips DRAINING, settles every accepted
                   future via the engine's drain (ONE deadline shared
                   across every join — the same contract
                   ExecutionEngine.drain documents), saves the manifest
                   for the successor process, then reports CLOSED.
  ELASTIC SIZING   ElasticController samples queue depth and per-device
                   busy-seconds each health tick and, through
                   ElasticPolicy's consecutive-sample hysteresis, parks
                   idle executors when the pool is cold and unparks
                   them (the PR 9 respawn path) when pressure returns.

Manifest artifact format (schema 1)::

    {"schema": 1, "engine": "<engine name>",
     "shapes": [{"program": "verify", "placement": "single",
                 "shape": [8]}, ...]}

`shape` is the program's shape_key with tuples rendered as JSON lists;
loading converts them back to tuples. A corrupt or unreadable manifest
is never fatal: boot proceeds cold (counted under
"lifecycle_manifest_corrupt") and the next drain rewrites it.

Metrics: gauges "lifecycle_state" (0 warming / 1 up / 2 draining /
3 closed), "lifecycle_warmup_s", "lifecycle_manifest_shapes",
"elastic_active_executors", "elastic_depth", "elastic_busy_fraction";
counters "lifecycle_warmed_shapes", "lifecycle_warm_skipped",
"lifecycle_warm_errors", "lifecycle_manifest_corrupt",
"lifecycle_manifest_save_errors", "elastic_grown", "elastic_shrunk",
"elastic_parked", "elastic_unparked", "elastic_emergency_unparked".
"""

import json
import threading
import time

from .. import metrics

WARMING = "warming"
UP = "up"
DRAINING = "draining"
CLOSED = "closed"

#: gauge encoding for "lifecycle_state"
_STATE_GAUGE = {WARMING: 0, UP: 1, DRAINING: 2, CLOSED: 3}


def _remaining(deadline):
    """Seconds left until `deadline` on the REAL clock; None = no bound."""
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


def configure_compilation_cache(cache_dir=None):
    """Best-effort: point JAX's persistent compilation cache at
    `cache_dir` (or the repo default via tpu.enable_compile_cache when
    None). Returns True when the cache was configured, False when jax is
    unavailable or refused — warm boot proceeds either way; the cache
    only changes how much the first cold shape costs."""
    try:
        if cache_dir is None:
            from ..tpu import enable_compile_cache

            enable_compile_cache()
        else:
            import jax

            jax.config.update("jax_compilation_cache_dir", str(cache_dir))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 5.0
            )
        return True
    except Exception:
        metrics.count("lifecycle_cache_config_errors")
        return False


def _canon_shape(shape):
    """JSON round-trip canonicalization: lists -> tuples, recursively,
    so a loaded manifest entry hashes equal to the live shape_key."""
    if isinstance(shape, list) or isinstance(shape, tuple):
        return tuple(_canon_shape(s) for s in shape)
    return shape


class ShapeManifest:
    """The persisted jit-shape set: what a successor process must prime
    before advertising readiness. Plain data — (program, placement,
    shape_key) triples — with atomic save and corruption-tolerant load."""

    SCHEMA = 1

    def __init__(self, shapes=(), engine_name=""):
        self.engine_name = engine_name
        self.shapes = []
        seen = set()
        for entry in shapes:
            try:
                program, placement, shape = entry
            except (TypeError, ValueError):
                continue
            triple = (str(program), str(placement), _canon_shape(shape))
            if triple not in seen:
                seen.add(triple)
                self.shapes.append(triple)
        self.shapes.sort(key=repr)

    def __len__(self):
        return len(self.shapes)

    @classmethod
    def from_engine(cls, engine):
        """Snapshot the engine's dispatched/pre-warmed shape set."""
        return cls(
            shapes=engine.shape_keys(),
            engine_name=getattr(engine, "name", ""),
        )

    def as_dict(self):
        return {
            "schema": self.SCHEMA,
            "engine": self.engine_name,
            "shapes": [
                {"program": p, "placement": pl, "shape": list(sh)
                 if isinstance(sh, tuple) else sh}
                for p, pl, sh in self.shapes
            ],
        }

    def save(self, path):
        """Crash-atomic write (state/atomic.py: tmp + fsync +
        os.replace + dir fsync): a crash mid-save leaves the previous
        manifest intact, never a truncated one — and unlike the
        pre-PR-17 hand-rolled copy, the bytes are fsync'd before the
        rename so the manifest survives a power cut too. Shapes that
        JSON cannot express are dropped with a counter — a partial
        manifest still warms everything it names."""
        entries = []
        for p, pl, sh in self.shapes:
            entry = {
                "program": p,
                "placement": pl,
                "shape": list(sh) if isinstance(sh, tuple) else sh,
            }
            try:
                json.dumps(entry)
            except (TypeError, ValueError):
                metrics.count("lifecycle_manifest_unserializable")
                continue
            entries.append(entry)
        doc = {
            "schema": self.SCHEMA,
            "engine": self.engine_name,
            "shapes": entries,
        }
        from ..state.atomic import replace_json

        return replace_json(str(path), doc, sort_keys=True)

    @classmethod
    def load(cls, path):
        """Load a manifest; a missing, unparseable, or wrong-schema file
        degrades to an EMPTY manifest (cold boot) with
        "lifecycle_manifest_corrupt" counted — warmup is an optimization
        and must never block a boot."""
        try:
            with open(str(path)) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return cls()
        except (OSError, ValueError):
            metrics.count("lifecycle_manifest_corrupt")
            return cls()
        if not isinstance(doc, dict) or doc.get("schema") != cls.SCHEMA:
            metrics.count("lifecycle_manifest_corrupt")
            return cls()
        shapes = []
        for entry in doc.get("shapes", ()):
            if not isinstance(entry, dict):
                metrics.count("lifecycle_manifest_corrupt")
                return cls()
            shapes.append(
                (
                    entry.get("program", ""),
                    entry.get("placement", "single"),
                    _canon_shape(entry.get("shape", ())),
                )
            )
        return cls(shapes=shapes, engine_name=doc.get("engine", ""))


class LifecycleController:
    """One replica process's lifecycle state machine around an
    ExecutionEngine:

        WARMING --boot()--> UP --begin_drain()--> DRAINING --> CLOSED

    Readiness (`ready()`) is True only in UP, and boot() promotes to UP
    strictly AFTER the manifest replay completes — Replica.beacon()
    reports "warming" until then, so the router's gossip directory never
    routes a new session at a replica that would pay cold compiles.
    begin_drain() shares ONE deadline between the engine drain and
    everything after it (manifest save), mirroring the engine's own
    one-deadline join contract."""

    def __init__(
        self,
        engine,
        manifest_path=None,
        compilation_cache_dir=None,
        clock=time.monotonic,
    ):
        self.engine = engine
        self.manifest_path = manifest_path
        self.compilation_cache_dir = compilation_cache_dir
        self.clock = clock
        self._lock = threading.Lock()
        self._state = WARMING
        self.warmed = 0
        self.skipped = 0
        metrics.set_gauge("lifecycle_state", _STATE_GAUGE[WARMING])

    @property
    def state(self):
        with self._lock:
            return self._state

    def _set_state(self, state):
        with self._lock:
            self._state = state
        metrics.set_gauge("lifecycle_state", _STATE_GAUGE[state])

    def ready(self):
        """May the replica advertise itself for NEW sessions?"""
        return self.state == UP

    def boot(self):
        """Warm boot: configure the persistent compilation cache, load
        the shape manifest, replay it through engine.warm_shapes(), THEN
        promote WARMING -> UP. Returns (warmed, skipped). Idempotent
        while UP; a draining/closed controller refuses (returns None) —
        a process does not un-drain."""
        if self.state in (DRAINING, CLOSED):
            return None
        t0 = self.clock()
        configure_compilation_cache(self.compilation_cache_dir)
        manifest = (
            ShapeManifest.load(self.manifest_path)
            if self.manifest_path is not None
            else ShapeManifest()
        )
        metrics.set_gauge("lifecycle_manifest_shapes", len(manifest))
        warmed, skipped = self.engine.warm_shapes(manifest.shapes)
        self.warmed, self.skipped = warmed, skipped
        metrics.count("lifecycle_warmed_shapes", warmed)
        metrics.count("lifecycle_warm_skipped", skipped)
        metrics.set_gauge("lifecycle_warmup_s", self.clock() - t0)
        # readiness flips ONLY here: after the replay finished
        self._set_state(UP)
        return warmed, skipped

    def save_manifest(self):
        """Persist the engine's current shape set for the successor
        process; no-op without a manifest path."""
        if self.manifest_path is None:
            return None
        return ShapeManifest.from_engine(self.engine).save(
            self.manifest_path
        )

    def begin_drain(self, timeout=None):
        """Graceful shutdown: flip DRAINING (the beacon starts reporting
        it immediately; admission refusals become retryable handoffs),
        settle every accepted future via the engine's drain, save the
        shape manifest for the successor, then report CLOSED. `timeout`
        is ONE deadline shared across the engine's joins AND the
        manifest save — not a fresh allowance per stage. Returns True
        iff the engine drained within the deadline. Idempotent: a
        second call returns immediately."""
        with self._lock:
            if self._state in (DRAINING, CLOSED):
                return self._state == CLOSED
            self._state = DRAINING
        metrics.set_gauge("lifecycle_state", _STATE_GAUGE[DRAINING])
        deadline = None if timeout is None else time.monotonic() + timeout
        ok = True
        drain = getattr(self.engine, "drain", None)
        if callable(drain):
            ok = bool(drain(timeout=_remaining(deadline)))
        try:
            self.save_manifest()
        except Exception:
            # losing the manifest costs the successor a cold boot, not
            # correctness — never fail a drain over it
            metrics.count("lifecycle_manifest_save_errors")
        self._set_state(CLOSED)
        return ok


class ElasticPolicy:
    """Grow/shrink decisions with consecutive-sample hysteresis: a
    single hot (or cold) sample NEVER resizes the pool — `grow_after`
    (`shrink_after`) consecutive samples must agree, and any
    disagreeing sample resets the streak. After acting the streak
    restarts from zero, so consecutive resizes are spaced at least one
    full hysteresis window apart (no flapping).

    Signals per sample: `depth` (queued requests across every program)
    and `busy` (pool busy-fraction since the last sample, 0..1).
    GROW when depth >= grow_depth_per_active * active executors OR
    busy >= grow_busy_fraction; SHRINK when depth <= shrink_depth AND
    busy <= shrink_busy_fraction. Anything else is neutral."""

    def __init__(
        self,
        min_executors=1,
        max_executors=None,
        grow_depth_per_active=4.0,
        grow_busy_fraction=0.75,
        shrink_depth=0,
        shrink_busy_fraction=0.25,
        grow_after=2,
        shrink_after=3,
    ):
        if min_executors < 1:
            raise ValueError(
                "min_executors must be >= 1 (got %r)" % (min_executors,)
            )
        if grow_after < 1 or shrink_after < 1:
            raise ValueError("grow_after/shrink_after must be >= 1")
        self.min_executors = min_executors
        self.max_executors = max_executors
        self.grow_depth_per_active = grow_depth_per_active
        self.grow_busy_fraction = grow_busy_fraction
        self.shrink_depth = shrink_depth
        self.shrink_busy_fraction = shrink_busy_fraction
        self.grow_after = grow_after
        self.shrink_after = shrink_after
        self._grow_streak = 0
        self._shrink_streak = 0

    def observe(self, depth, busy, active):
        """Fold one sample in; returns "grow", "shrink", or None."""
        grow_signal = (
            depth >= self.grow_depth_per_active * max(1, active)
            or busy >= self.grow_busy_fraction
        )
        shrink_signal = (
            depth <= self.shrink_depth
            and busy <= self.shrink_busy_fraction
        )
        if grow_signal:
            self._grow_streak += 1
            self._shrink_streak = 0
        elif shrink_signal:
            self._shrink_streak += 1
            self._grow_streak = 0
        else:
            self._grow_streak = 0
            self._shrink_streak = 0
        if grow_signal and self._grow_streak >= self.grow_after:
            if self.max_executors is not None and active >= self.max_executors:
                return None
            self._grow_streak = 0
            return "grow"
        if shrink_signal and self._shrink_streak >= self.shrink_after:
            if active <= self.min_executors:
                return None
            self._shrink_streak = 0
            return "shrink"
        return None


class ElasticController:
    """Drives ElasticPolicy from live engine signals: queue depth
    (engine.total_depth()) and the pool's busy-fraction, derived from
    the per-device busy-seconds timers (`serve_dev<label>_busy_s`) as a
    delta over the sampling interval divided by active-executor
    wall-time. Call tick(now) periodically — production wires it into
    the engine watchdog cadence; fake-clock tests call it directly.

    Acting means parking (engine.park_executor — idle executors only,
    invisible to the health ladder) or unparking
    (engine.unpark_executor — the PR 9 respawn path). Counted under
    "elastic_grown"/"elastic_shrunk"."""

    def __init__(self, engine, policy=None, clock=time.monotonic):
        self.engine = engine
        self.policy = policy if policy is not None else ElasticPolicy()
        self.clock = clock
        self._last_t = None
        self._last_busy = None

    def _pool_busy_seconds(self):
        totals = metrics.timers_with_prefix("serve_dev")
        busy = 0.0
        for ex in getattr(self.engine, "_executors", ()):
            busy += totals.get(getattr(ex, "busy_timer", ""), 0.0)
        return busy

    def sample(self, now=None):
        """One (depth, busy_fraction, active) reading; busy_fraction is
        None on the very first call (no interval to difference over)."""
        now = self.clock() if now is None else now
        depth = self.engine.total_depth()
        active = self.engine.active_pool_size()
        busy_total = self._pool_busy_seconds()
        busy = None
        if self._last_t is not None and now > self._last_t:
            span = (now - self._last_t) * max(1, active)
            busy = max(0.0, min(1.0, (busy_total - self._last_busy) / span))
        self._last_t = now
        self._last_busy = busy_total
        return depth, busy, active

    def tick(self, now=None):
        """Sample, decide, act. Returns "grow", "shrink", or None (also
        None on the warm-up sample and when the engine had nothing to
        park/unpark)."""
        depth, busy, active = self.sample(now)
        metrics.set_gauge("elastic_depth", depth)
        if busy is None:
            return None
        metrics.set_gauge("elastic_busy_fraction", busy)
        decision = self.policy.observe(depth, busy, active)
        if decision == "grow":
            if self.engine.unpark_executor() is not None:
                metrics.count("elastic_grown")
                return "grow"
            return None
        if decision == "shrink":
            if self.engine.park_executor() is not None:
                metrics.count("elastic_shrunk")
                return "shrink"
            return None
        return None
