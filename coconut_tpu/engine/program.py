"""The engine's program contract: what a workload registers to be served
by the shared executor fabric (engine/core.ExecutionEngine).

A *program* is a bundle of
  - an ENCODE step (`assemble`: coalesced requests -> device payload,
    including the program's pad-lane convention),
  - a DISPATCH closure (`run_dispatch`: payload -> finalizer, resolved
    per executor so device pinning and per-device jit caches work),
  - a DEMUX step (`demux`: device result -> per-request futures),
  - a PAD-LANE CONVENTION (`pad_convention`, documentation + the shape
    the jit-shape cache key counts),
  - an SLO CLASS (`slo_class`, how the brownout policy treats the
    program's traffic), and
  - a JIT-SHAPE CACHE KEY (`shape_key`, fed to the engine's per-program
    "%ns_jit_shapes" counter — the proof that warmed-up cross-program
    traffic never recompiles).

plus queue sizing (max_batch / max_wait_ms / max_depth), a retry policy,
and lifecycle/health hooks for programs that bring their own workers
(the mint program's authority pool) instead of using the shared device
pool. Every hook has the single-program default, so VerifyProgram —
the lifted serve/service.py behavior — overrides only the crypto."""

from ..retry import RetryPolicy
from ..serve.batcher import fail_all

#: SLO classes — how the brownout policy sees a program's submissions:
#:   "interactive"  never shed by brownout (hard admission bound only)
#:   "bulk"         always sheddable, whatever lane the caller named
#:   "standard"     the caller's lane decides (bulk sheds, interactive not)
SLO_CLASSES = ("interactive", "bulk", "standard")


class Program:
    """Base program: subclass and override the crypto seams. One instance
    registers on ONE engine (`engine.register(program)` calls `bind`)."""

    #: registry key; also stamped on requests, batch spans, dead letters
    name = "program"
    #: metric namespace ("serve", "issue", "prep", "prove", "showv", ...)
    metric_ns = "serve"
    #: brownout SLO class (see SLO_CLASSES)
    slo_class = "standard"
    #: documentation string for the pad-lane convention (README taxonomy)
    pad_convention = "none"
    #: does this program ride the shared device pool? (False: the program
    #: brings its own workers — e.g. the mint program's authority pool)
    uses_pool = True
    #: may the engine route this program's batches to the mesh executor?
    supports_mesh = False

    max_batch = 64
    max_wait_ms = 20.0
    max_depth = 1024
    retry_policy = None

    def bind(self, engine):
        self.engine = engine
        if self.retry_policy is None:
            self.retry_policy = RetryPolicy(
                max_attempts=1, base_delay=0.0, retryable=()
            )

    # -- pool seeding --------------------------------------------------------

    def make_dispatch(self, device=None):
        """(dispatch, is_async) for one pool executor, or None to reuse
        the executor's primary dispatch closure."""
        return None

    # -- admission (engine.submit_request) -----------------------------------

    def shed_lane(self, lane):
        """The lane the brownout policy evaluates for a submission on
        `lane` — the program's SLO class applied (see SLO_CLASSES)."""
        if self.slo_class == "bulk":
            return "bulk"
        if self.slo_class == "interactive":
            return "interactive"
        return lane

    def capacity_fraction(self):
        """Degradation signal for brownout — pool programs inherit the
        engine's executor-pool fraction; own-worker programs override."""
        return self.engine._capacity_fraction()

    # -- placement (engine placer thread) ------------------------------------

    def capacity_ready(self):
        """ready() gate for this program's batcher."""
        return self.engine._has_capacity()

    def place(self, batch):
        """Route one coalesced batch; pool programs use the engine's
        adaptive placer, own-worker programs override (mint fans out)."""
        self.engine._place(batch).submit_batch(batch)

    # -- batch work (engine._launch / _settle on executor threads) -----------

    def backend_label(self):
        """Stamped on the "dispatch" span (backend=...)."""
        return type(getattr(self, "backend", None)).__name__

    def assemble(self, requests, bspan):
        """Coalesced requests -> (payload_a, payload_b), the program's
        encode + pad step. Runs under the batch's "coalesce" span."""
        raise NotImplementedError

    def shape_key(self, requests, payload_a, payload_b):
        """The jit-shape cache key for this assembled batch (counted per
        program under "%ns_jit_shapes": a stable counter after warmup is
        the no-recompile proof). Default: the padded lane count."""
        try:
            return (len(payload_a),)
        except TypeError:
            return (len(requests),)

    def warm(self, shape_key):
        """Best-effort ahead-of-time priming of ONE jit shape — the
        lifecycle warmup orchestrator's manifest-replay seam
        (engine/lifecycle.py). Return True when the shape was actually
        primed (AOT lower/compile, or a persistent-compilation-cache
        lookup) so the engine may pre-count it under "%ns_jit_shapes" and
        the first live dispatch at that shape pays no compile. The
        default returns False: programs whose dispatch cannot be
        exercised without live request payloads leave the shape to
        compile on first dispatch, still served by JAX's persistent
        compilation cache when configured."""
        return False

    def run_dispatch(self, executor, payload_a, payload_b):
        """Dispatch the assembled batch on `executor`; returns the
        finalizer the engine blocks on in _settle."""
        return executor.dispatch_for(self.name)(payload_a, payload_b)

    def make_fallback(self, payload_a, payload_b):
        """Zero-arg degraded-path callable for the retry ladder, or None."""
        return None

    def demux(self, requests, result, payload_a, payload_b, seq, attempts,
              bspan):
        """Device result -> per-request futures; must end `bspan`."""
        raise NotImplementedError

    def fail_batch(self, requests, exc):
        """Batch-level failure past retry+fallback: resolve every future
        with the exception (never a silent hang)."""
        fail_all(
            requests, exc, counter="%s_failed_requests" % self.metric_ns
        )

    # -- lifecycle / health hooks (own-worker programs) ----------------------

    def refresh_health_gauges(self):
        pass

    def start_workers(self):
        pass

    def close_workers(self):
        pass

    def join_workers(self, deadline):
        return True

    def on_drain(self):
        """After workers joined: settle whatever could not complete."""

    def on_crash(self, exc):
        """Engine-wide crash: fail anything this program still holds."""

    def owns_expiry(self, entry):
        """Does this program claim a watchdog expiry `entry`
        ((label, seq, payload, span, overdue_s))? Pool dispatches are
        handled by the engine; own-worker programs claim their own."""
        return False

    def handle_expired(self, entry, now):
        pass

    def tick(self, now):
        """Per-health-tick hook (hedge timers, own-worker probation)."""
