"""Streamed ledger-scale batch verification with checkpoint/resume.

BASELINE config 5 (1M-credential streamed verify) and the SURVEY §5
checkpoint mandate: the stream is processed in fixed-size batches through a
`CurveBackend`, and a tiny JSON state file records the last fully-verified
batch index plus running tallies — kill the process at any point and a rerun
skips straight to the first unverified batch. TPU batch verification is
stateless, so recovery is exactly "resubmit from the checkpoint" (SURVEY §5
"failure detection").

Two result modes, with HONEST accounting for each (VERDICT r2 weak #3):

  - mode="per_credential": `backend.batch_verify` returns one bool per
    credential; `verified`/`failed` count credentials.
  - mode="grouped": `backend.batch_verify_grouped` returns ONE bool per
    batch (small-exponents combination, soundness 2^-128 per forged
    credential); `batches_ok`/`batches_failed` count batches and
    `verified` counts only credentials in ACCEPTED batches — a failing
    batch is recorded in `failed` wholesale and should be bisected with the
    per-credential path.

Pipelining (SURVEY §2.3 pipeline row): when the backend exposes the
`*_async` dispatch seam (JaxBackend), batch i+1's host fetch+encode runs
while batch i executes on the device — JAX dispatch is asynchronous, so the
overlap needs no threads: dispatch batch i, fetch/encode/dispatch i+1, then
block on i's result.

The credential source is any callable `batch_index -> (sigs, messages_list)`
so 1M credentials never need to exist in memory at once.
"""

import json
import os
import tempfile


class StreamState:
    """Durable checkpoint, atomically saved. Fields: next_batch, verified,
    failed (credentials), batches_ok, batches_failed (grouped mode)."""

    def __init__(self, path):
        self.path = path
        self.next_batch = 0
        self.verified = 0
        self.failed = 0
        self.batches_ok = 0
        self.batches_failed = 0
        if path and os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            self.next_batch = d["next_batch"]
            self.verified = d["verified"]
            self.failed = d["failed"]
            self.batches_ok = d.get("batches_ok", 0)
            self.batches_failed = d.get("batches_failed", 0)

    def save(self):
        if not self.path:
            return
        d = {
            "next_batch": self.next_batch,
            "verified": self.verified,
            "failed": self.failed,
            "batches_ok": self.batches_ok,
            "batches_failed": self.batches_failed,
        }
        dirn = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=dirn, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(d, f)
        os.replace(tmp, self.path)  # atomic on POSIX


def _dispatchers(backend, mode, mesh=None):
    """(dispatch, record, is_async) for the chosen mode. dispatch(sigs,
    msgs, vk, params) -> zero-arg finalizer; record(state, result,
    batch_size). is_async=False means dispatch computes synchronously —
    pipelining such a backend would only delay checkpoints, never overlap
    work, so verify_stream settles each batch immediately.

    mesh: run the grouped mode dp-sharded over a jax Mesh (config 5 on
    multi-chip — SURVEY §2.3 PP+DP rows combined: the batch is sharded
    across devices AND host encode pipelines under device execution)."""
    if mesh is not None:
        if mode not in ("grouped", "per_credential"):
            raise ValueError(
                "mesh streaming supports mode='grouped' or "
                "'per_credential' (got %r)" % (mode,)
            )
        needed = (
            "encode_verify_batch"
            if mode == "per_credential"
            else "encode_grouped_batch"
        )
        if not hasattr(backend, needed):
            raise ValueError(
                "backend %r cannot shard over a mesh (no %s); "
                "use the jax backend" % (backend, needed)
            )
        from .tpu import shard as _shard

        if mode == "per_credential":
            # dp-sharded fused per-credential program: [B] bools per
            # batch (the reference's Signature::verify verdict semantics
            # at ledger scale on a mesh)

            def dispatch(s, m, vk, params):
                return _shard.batch_verify_sharded_async(
                    backend, s, m, vk, params, mesh
                )

            return dispatch, _record_percred, True

        def dispatch(s, m, vk, params):
            return _shard.batch_verify_grouped_sharded_async(
                backend, s, m, vk, params, mesh
            )

        return dispatch, _record_grouped, True
    if mode == "per_credential":
        async_fn = getattr(backend, "batch_verify_async", None)
        if async_fn is None:

            def dispatch(s, m, vk, params):
                bits = backend.batch_verify(s, m, vk, params)
                return lambda: bits

        else:
            dispatch = async_fn

        return dispatch, _record_percred, async_fn is not None
    if mode == "grouped":
        async_fn = getattr(backend, "batch_verify_grouped_async", None)
        if async_fn is None:
            grouped = getattr(backend, "batch_verify_grouped", None)
            if grouped is None:
                raise ValueError(
                    "backend %r has no grouped verify" % (backend,)
                )

            def dispatch(s, m, vk, params):
                ok = grouped(s, m, vk, params)
                return lambda: ok

        else:
            dispatch = async_fn

        return dispatch, _record_grouped, async_fn is not None
    raise ValueError("unknown stream mode %r" % (mode,))


def _record_percred(state, bits, _n):
    """Per-credential accounting (single-chip and mesh paths share it):
    one bool per credential."""
    state.verified += sum(1 for b in bits if b)
    state.failed += sum(1 for b in bits if not b)


def _record_grouped(state, ok, n):
    """Grouped-mode accounting (single-chip and mesh paths share it): one
    bool covers the whole batch, so tallies move batch-wholesale."""
    if ok:
        state.batches_ok += 1
        state.verified += n
    else:
        state.batches_failed += 1
        state.failed += n


def verify_stream(
    source,
    n_batches,
    vk,
    params,
    backend,
    state_path=None,
    on_batch=None,
    mode="per_credential",
    pipeline=True,
    mesh=None,
    pipeline_depth=3,
):
    """Verify `n_batches` batches from `source(i) -> (sigs, messages_list)`.

    Resumes from `state_path` if present (batch granularity). Returns the
    final StreamState. `on_batch(i, result)` is called after each batch
    with the mode's result type (bools list / one bool) — the hook for
    collecting results or metrics. `pipeline=True` overlaps host encode of
    batch i+1 with device execution of batch i when the backend supports
    async dispatch; `pipeline_depth` batches stay in flight before the
    oldest is settled, keeping the device queue non-empty across the
    result-readback round trip (on the tunneled chip the RTT is
    ~0.2 s/batch, comparable to the grouped program's own 0.21 s device
    time, so depth 1 leaves the device idle half the time: measured
    2,520 -> 4,416 -> ~4,700 creds/s at depths 1/3/4 against the ~4,875/s
    device-time ceiling). Checkpoint lag is bounded by the depth: a crash
    re-runs at most `pipeline_depth` batches (at-least-once delivery, same
    as depth 1). `mesh` dp-shards the grouped mode over a jax Mesh
    (multi-chip config 5)."""
    from .backend import get_backend

    if backend is None or isinstance(backend, str):
        backend = get_backend(backend or "python")
    dispatch, record, is_async = _dispatchers(backend, mode, mesh=mesh)
    pipeline = pipeline and is_async  # sync backends: settle immediately
    if pipeline_depth < 1:
        raise ValueError("pipeline_depth must be >= 1")
    state = StreamState(state_path)

    def settle(idx, fin, n):
        result = fin()
        record(state, result, n)
        # deliver results BEFORE persisting the checkpoint: a crash inside
        # on_batch then re-runs the batch (at-least-once delivery) instead
        # of silently dropping its verdicts
        if on_batch is not None:
            on_batch(idx, result)
        state.next_batch = idx + 1
        state.save()

    pending = []  # [(index, finalizer, batch_size)] oldest first
    for i in range(state.next_batch, n_batches):
        sigs, messages_list = source(i)
        fin = dispatch(sigs, messages_list, vk, params)
        if not pipeline:
            settle(i, fin, len(sigs))
            continue
        pending.append((i, fin, len(sigs)))
        if len(pending) >= pipeline_depth:
            settle(*pending.pop(0))
    for p in pending:
        settle(*p)
    return state
