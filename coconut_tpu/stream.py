"""Streamed ledger-scale batch verification with checkpoint/resume.

BASELINE config 5 (1M-credential streamed verify) and the SURVEY §5
checkpoint mandate: the stream is processed in fixed-size batches through a
`CurveBackend`, and a tiny JSON state file records the last fully-verified
batch index plus running tallies — kill the process at any point and a rerun
skips straight to the first unverified batch. TPU batch verification is
stateless, so recovery is exactly "resubmit from the checkpoint" (SURVEY §5
"failure detection").

The credential source is any callable `batch_index -> (sigs, messages_list)`
so 1M credentials never need to exist in memory at once; `verify_stream`
pulls batches lazily (and a fetcher can prefetch/double-buffer underneath).
"""

import json
import os
import tempfile


class StreamState:
    """Durable {next_batch, verified, failed} checkpoint, atomically saved."""

    def __init__(self, path):
        self.path = path
        self.next_batch = 0
        self.verified = 0
        self.failed = 0
        if path and os.path.exists(path):
            with open(path) as f:
                d = json.load(f)
            self.next_batch = d["next_batch"]
            self.verified = d["verified"]
            self.failed = d["failed"]

    def save(self):
        if not self.path:
            return
        d = {
            "next_batch": self.next_batch,
            "verified": self.verified,
            "failed": self.failed,
        }
        dirn = os.path.dirname(os.path.abspath(self.path))
        fd, tmp = tempfile.mkstemp(dir=dirn, suffix=".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(d, f)
        os.replace(tmp, self.path)  # atomic on POSIX


def verify_stream(
    source,
    n_batches,
    vk,
    params,
    backend,
    state_path=None,
    on_batch=None,
):
    """Verify `n_batches` batches from `source(i) -> (sigs, messages_list)`.

    Resumes from `state_path` if present (batch granularity). Returns the
    final StreamState. `on_batch(i, bits)` is called after each batch —
    the hook for collecting per-credential results or metrics."""
    from .backend import get_backend

    if backend is None or isinstance(backend, str):
        backend = get_backend(backend or "python")
    state = StreamState(state_path)
    for i in range(state.next_batch, n_batches):
        sigs, messages_list = source(i)
        bits = backend.batch_verify(sigs, messages_list, vk, params)
        state.verified += sum(1 for b in bits if b)
        state.failed += sum(1 for b in bits if not b)
        # deliver results BEFORE persisting the checkpoint: a crash inside
        # on_batch then re-runs the batch (at-least-once delivery) instead
        # of silently dropping its verdicts
        if on_batch is not None:
            on_batch(i, bits)
        state.next_batch = i + 1
        state.save()
    return state
