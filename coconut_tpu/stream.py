"""Streamed ledger-scale batch verification with checkpoint/resume and a
fault-supervision layer.

BASELINE config 5 (1M-credential streamed verify) and the SURVEY §5
checkpoint mandate: the stream is processed in fixed-size batches through a
`CurveBackend`, and a tiny JSON state file records the last fully-verified
batch index plus running tallies — kill the process at any point and a rerun
skips straight to the first unverified batch. TPU batch verification is
stateless, so recovery is exactly "resubmit from the checkpoint" (SURVEY §5
"failure detection").

Two result modes, with HONEST accounting for each (VERDICT r2 weak #3):

  - mode="per_credential": `backend.batch_verify` returns one bool per
    credential; `verified`/`failed` count credentials.
  - mode="grouped": `backend.batch_verify_grouped` returns ONE bool per
    batch (small-exponents combination, soundness 2^-128 per forged
    credential); `batches_ok`/`batches_failed` count batches and
    `verified` counts only credentials in ACCEPTED batches — a failing
    batch is recorded in `failed` wholesale, UNLESS bisection is enabled
    (below), which recovers per-credential granularity.

Pipelining (SURVEY §2.3 pipeline row): when the backend exposes the
`*_async` dispatch seam (JaxBackend), batch i+1's host fetch+encode runs
while batch i executes on the device — JAX dispatch is asynchronous, so the
overlap needs no threads: dispatch batch i, fetch/encode/dispatch i+1, then
block on i's result.

Fault supervision (PAPER.md's threshold design goal — survive faulty
parties — applied to our own pipeline):

  - a batch whose dispatch or readback raises `TransientBackendError` is
    re-attempted under a `retry.RetryPolicy` (bounded exponential backoff,
    deterministic jitter, per-batch attempt cap);
  - after retries exhaust, the batch re-dispatches on `fallback_backend`
    (e.g. the "python" reference) so the stream completes DEGRADED instead
    of dying; with no fallback the transient error propagates, and the
    checkpoint still lets a rerun resume at the failed batch;
  - in grouped mode a REJECTED batch can be bisected: grouped probes over
    recursively-halved slices (per-credential at the leaves) isolate the
    culprit credentials, which are appended to the `dead_letter_path`
    JSONL (faults.DeadLetterLog) with batch index, credential index, and
    the batch's retry attempt history; accounting then counts only the
    culprits in `failed`;
  - the checkpoint itself is integrity-checked (schema version + CRC +
    run-config fingerprint): corruption quarantines the file and restarts
    cleanly, a fingerprint mismatch refuses to resume the wrong run.

  Counters (metrics.snapshot()): "retries", "fallbacks", "bisections",
  "dead_letters", "checkpoint_quarantined".

The credential source is any callable `batch_index -> (sigs, messages_list)`
so 1M credentials never need to exist in memory at once.
"""

import binascii
import hashlib
import json
import os

from . import metrics
from .errors import (
    CheckpointCorruptError,
    CheckpointMismatchError,
    TransientBackendError,
)
from .obs import trace as otrace

STATE_SCHEMA_VERSION = 2


def run_fingerprint(mode, vk, params=None):
    """Digest binding a stream run's configuration: the result mode and
    the verkey (canonical bytes when the GroupContext can serialize it,
    repr of its components otherwise). Stored in the checkpoint so a
    resume against a DIFFERENT run fails loudly (CheckpointMismatchError)
    instead of silently merging tallies. The batch count is deliberately
    NOT part of the digest: growing a stream (resuming a 2-batch
    checkpoint with n_batches=4 to verify the next batches) is a
    first-class resume pattern — what must never change across a resume
    is WHAT is being verified (the verkey) and what the tallies mean
    (the mode)."""
    h = hashlib.sha256()
    h.update(("%s|" % (mode,)).encode())
    vkb = None
    if params is not None and vk is not None:
        try:
            vkb = vk.to_bytes(params.ctx)
        except Exception:
            vkb = None
    if vkb is None:
        vkb = repr(
            (getattr(vk, "X_tilde", None), getattr(vk, "Y_tilde", None))
        ).encode()
    h.update(vkb)
    return h.hexdigest()[:16]


def _canon_payload(payload):
    return json.dumps(payload, sort_keys=True, separators=(",", ":"))


def _payload_crc(payload):
    return binascii.crc32(_canon_payload(payload).encode()) & 0xFFFFFFFF


def _quarantine(path):
    """Move a corrupt state file aside (never overwrite an earlier
    quarantine) and return its new location."""
    dest = path + ".corrupt"
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = "%s.corrupt-%d" % (path, n)
    os.replace(path, dest)
    return dest


class StreamState:
    """Durable checkpoint, atomically saved and integrity-checked on load.

    Fields: next_batch, verified, failed (credentials), batches_ok,
    batches_failed (grouped mode).

    On-disk format (schema v2):
      {"schema": 2, "crc32": <crc32 of the canonical payload JSON>,
       "payload": {next_batch, verified, failed, batches_ok,
                   batches_failed, fingerprint}}

    Loading validates the schema version and CRC. ANY corruption —
    truncated bytes, unparseable JSON, unknown schema, CRC mismatch,
    missing tallies — quarantines the file to `<path>.corrupt*` and starts
    fresh (`quarantined` holds the new location; counter
    "checkpoint_quarantined") instead of crashing on json.load. A stored
    run fingerprint that disagrees with `fingerprint` raises
    CheckpointMismatchError: resuming the wrong run must fail loudly, not
    silently continue someone else's tallies."""

    def __init__(self, path, fingerprint=None):
        self.path = path
        self.fingerprint = fingerprint
        self.quarantined = None
        self.next_batch = 0
        self.verified = 0
        self.failed = 0
        self.batches_ok = 0
        self.batches_failed = 0
        if path and os.path.exists(path):
            try:
                payload = self._load_checked(path)
            except CheckpointCorruptError as e:
                self.quarantined = _quarantine(path)
                metrics.count("checkpoint_quarantined")
                # flight-record the quarantine next to the state file:
                # the recent-span tail shows what the stream was doing
                # when it last wrote (no-op with tracing disabled)
                from .obs import flight as _flight

                _flight.record(
                    path,
                    "checkpoint_quarantine",
                    extra={
                        "quarantined_to": self.quarantined,
                        "detail": str(e),
                    },
                )
                return
            stored = payload.get("fingerprint")
            if (
                fingerprint is not None
                and stored is not None
                and stored != fingerprint
            ):
                raise CheckpointMismatchError(stored, fingerprint)
            self.next_batch = payload["next_batch"]
            self.verified = payload["verified"]
            self.failed = payload["failed"]
            self.batches_ok = payload.get("batches_ok", 0)
            self.batches_failed = payload.get("batches_failed", 0)

    @staticmethod
    def _load_checked(path):
        """Parse + integrity-check a state file; CheckpointCorruptError on
        any structural problem (the caller quarantines)."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
            doc = json.loads(raw.decode("utf-8"))
        except (OSError, ValueError, UnicodeDecodeError) as e:
            raise CheckpointCorruptError("unparseable checkpoint: %s" % e)
        if not isinstance(doc, dict):
            raise CheckpointCorruptError("checkpoint is not an object")
        if doc.get("schema") != STATE_SCHEMA_VERSION:
            raise CheckpointCorruptError(
                "unknown checkpoint schema %r (want %d)"
                % (doc.get("schema"), STATE_SCHEMA_VERSION)
            )
        payload = doc.get("payload")
        if not isinstance(payload, dict):
            raise CheckpointCorruptError("checkpoint missing payload")
        if _payload_crc(payload) != doc.get("crc32"):
            raise CheckpointCorruptError("checkpoint CRC mismatch")
        for k in ("next_batch", "verified", "failed"):
            if not isinstance(payload.get(k), int):
                raise CheckpointCorruptError("checkpoint missing tally %r" % k)
        return payload

    def save(self):
        if not self.path:
            return
        payload = {
            "next_batch": self.next_batch,
            "verified": self.verified,
            "failed": self.failed,
            "batches_ok": self.batches_ok,
            "batches_failed": self.batches_failed,
            "fingerprint": self.fingerprint,
        }
        doc = {
            "schema": STATE_SCHEMA_VERSION,
            "crc32": _payload_crc(payload),
            "payload": payload,
        }
        # crash-atomic (state/atomic.py — the shared tmp+fsync+replace
        # dance): a kill at any point leaves either the old complete
        # file or the new complete file at `path`, never torn bytes
        # that a restart would quarantine as `.corrupt*`
        from .state.atomic import replace_json

        replace_json(self.path, doc)


def _pin_to_device(dispatch, device):
    """Wrap a dispatch callable so its host encode + launch run with
    `device` as the jax default device — the per-device executor pool's
    placement seam (serve/service.py): operands created inside commit to
    that device, so each executor's batches land on ITS chip and the jit
    executable cache stays per-device-hot. device=None is the identity
    (stub/sync backends, single-device services)."""
    if device is None:
        return dispatch

    def pinned(s, m, vk, params):
        import jax

        with jax.default_device(device):
            return dispatch(s, m, vk, params)

    return pinned


def _dispatchers(backend, mode, mesh=None, device=None, mesh_pad_to=None):
    """(dispatch, record, is_async) for the chosen mode. dispatch(sigs,
    msgs, vk, params) -> zero-arg finalizer; record(state, result,
    batch_size). is_async=False means dispatch computes synchronously —
    pipelining such a backend would only delay checkpoints, never overlap
    work, so verify_stream settles each batch immediately.

    mesh: run the grouped mode dp-sharded over a jax Mesh (config 5 on
    multi-chip — SURVEY §2.3 PP+DP rows combined: the batch is sharded
    across devices AND host encode pipelines under device execution).
    device: pin single-chip dispatch to one jax device (mutually
    exclusive with mesh — a sharded program owns its own placement).
    mesh_pad_to: fixed grouped-mode batch pad on the mesh path, so a
    serving workload with varying coalesced sizes keeps ONE cache-hot
    program shape instead of compiling per occupancy level."""
    if mesh is not None:
        if device is not None:
            raise ValueError(
                "mesh and device are mutually exclusive: a sharded "
                "program spans the mesh, it cannot also pin to one device"
            )
        if mode not in ("grouped", "per_credential"):
            raise ValueError(
                "mesh streaming supports mode='grouped' or "
                "'per_credential' (got %r)" % (mode,)
            )
        needed = (
            "encode_verify_batch"
            if mode == "per_credential"
            else "encode_grouped_batch"
        )
        if not hasattr(backend, needed):
            raise ValueError(
                "backend %r cannot shard over a mesh (no %s); "
                "use the jax backend" % (backend, needed)
            )
        from .tpu import shard as _shard

        # validate the mesh axes up front with a clear error — not a bare
        # KeyError from mesh.shape['tp'] on the first batch (ADVICE r5 #1)
        if mode == "per_credential":
            _shard.require_axes(mesh, "dp", "tp")

            # dp-sharded fused per-credential program: [B] bools per
            # batch (the reference's Signature::verify verdict semantics
            # at ledger scale on a mesh)

            def dispatch(s, m, vk, params):
                return _shard.batch_verify_sharded_async(
                    backend, s, m, vk, params, mesh
                )

            return dispatch, _record_percred, True

        _shard.require_axes(mesh, "dp")

        def dispatch(s, m, vk, params):
            return _shard.batch_verify_grouped_sharded_async(
                backend, s, m, vk, params, mesh, pad_batch_to=mesh_pad_to
            )

        return dispatch, _record_grouped, True
    if mode == "per_credential":
        async_fn = getattr(backend, "batch_verify_async", None)
        if async_fn is None:

            def dispatch(s, m, vk, params):
                bits = backend.batch_verify(s, m, vk, params)
                return lambda: bits

        else:
            dispatch = async_fn

        return (
            _pin_to_device(dispatch, device),
            _record_percred,
            async_fn is not None,
        )
    if mode == "grouped":
        async_fn = getattr(backend, "batch_verify_grouped_async", None)
        if async_fn is None:
            grouped = getattr(backend, "batch_verify_grouped", None)
            if grouped is None:
                raise ValueError(
                    "backend %r has no grouped verify" % (backend,)
                )

            def dispatch(s, m, vk, params):
                ok = grouped(s, m, vk, params)
                return lambda: ok

        else:
            dispatch = async_fn

        return (
            _pin_to_device(dispatch, device),
            _record_grouped,
            async_fn is not None,
        )
    if mode == "batched":
        # RLC-combined pairing check (PR 16): same one-bool-per-batch
        # result shape as grouped, but the verdict comes from ONE
        # multi-Miller product under deterministic per-lane combiners
        # with a single shared final exponentiation.
        async_fn = getattr(backend, "batch_verify_combined_async", None)
        if async_fn is None:
            combined = getattr(backend, "batch_verify_combined", None)
            if combined is None:
                raise ValueError(
                    "backend %r has no combined (RLC) verify" % (backend,)
                )

            def dispatch(s, m, vk, params):
                ok = combined(s, m, vk, params)
                return lambda: ok

        else:
            dispatch = async_fn

        return (
            _pin_to_device(dispatch, device),
            _record_grouped,
            async_fn is not None,
        )
    raise ValueError("unknown stream mode %r" % (mode,))


def _record_percred(state, bits, _n):
    """Per-credential accounting (single-chip and mesh paths share it):
    one bool per credential."""
    state.verified += sum(1 for b in bits if b)
    state.failed += sum(1 for b in bits if not b)


def _record_grouped(state, ok, n):
    """Grouped-mode accounting (single-chip and mesh paths share it): one
    bool covers the whole batch, so tallies move batch-wholesale."""
    if ok:
        state.batches_ok += 1
        state.verified += n
    else:
        state.batches_failed += 1
        state.failed += n


def _fallback_dispatcher(backend, mode):
    """Synchronous dispatch on the fallback backend, in the primary mode's
    result shape. A fallback without a grouped entry point (the python
    reference) emulates the grouped verdict as all(per-credential bits) —
    same semantics, deterministic instead of 2^-128-probabilistic."""
    if mode == "grouped":
        grouped = getattr(backend, "batch_verify_grouped", None)
        if grouped is not None:
            return lambda s, m, vk, p: (lambda: bool(grouped(s, m, vk, p)))
        return lambda s, m, vk, p: (
            lambda: all(backend.batch_verify(s, m, vk, p))
        )
    if mode == "batched":
        combined = getattr(backend, "batch_verify_combined", None)
        if combined is not None:
            return lambda s, m, vk, p: (
                lambda: bool(combined(s, m, vk, p))
            )
        return lambda s, m, vk, p: (
            lambda: all(backend.batch_verify(s, m, vk, p))
        )
    return lambda s, m, vk, p: (lambda: backend.batch_verify(s, m, vk, p))


def _group_oracle(backend, vk, params, predicate="grouped"):
    """slice -> bool probe for bisection. predicate="grouped" prefers the
    backend's grouped verify; predicate="combined" prefers the RLC
    combined check (PR 16) — each sub-slice gets FRESH exponents derived
    from its own transcript, so a cancellation pair that fooled the
    parent draw cannot survive both child draws except w.p. <= 2^-lam.
    Either falls back to all() over per-credential bits; None if the
    backend can do neither."""
    if backend is None:
        return None
    if predicate == "combined":
        combined = getattr(backend, "batch_verify_combined", None)
        if combined is not None:
            return lambda s, m: bool(combined(s, m, vk, params))
    grouped = getattr(backend, "batch_verify_grouped", None)
    if grouped is not None:
        return lambda s, m: bool(grouped(s, m, vk, params))
    bv = getattr(backend, "batch_verify", None)
    if bv is not None:
        return lambda s, m: all(bv(s, m, vk, params))
    return None


def _make_bisector(
    backend, fallback_backend, vk, params, policy, dead_letter_path,
    program=None, predicate="grouped",
):
    """bisect(sigs, msgs, batch_index, attempts) -> culprit indices.

    A rejected grouped (or RLC-combined, predicate="combined") batch is
    recursively halved; each slice is probed with a grouped check
    (per-credential at single-credential leaves — a 1-slice grouped
    check IS the per-credential verify), probes riding the same
    retry/fallback ladder as regular dispatches. Culprits are appended
    to the dead-letter JSONL with the batch's attempt history.
    Counters: "bisections" per split, "dead_letters" per culprit."""
    from .retry import call_with_retry

    primary = _group_oracle(backend, vk, params, predicate=predicate)
    fb = _group_oracle(fallback_backend, vk, params, predicate=predicate)
    if primary is None:
        primary, fb = fb, None
    if primary is None:
        return None
    from .faults import DeadLetterLog

    log = DeadLetterLog(dead_letter_path) if dead_letter_path else None

    def check(s, m, key):
        fallback = (lambda: fb(s, m)) if fb is not None else None
        return call_with_retry(
            lambda: primary(s, m), policy, key=key, fallback=fallback
        )

    def bisect(sigs, msgs, batch_index, attempts, trace_ids=None):
        """trace_ids: optional per-credential trace ids (the serve path's
        request traces) so each dead-letter line carries ITS request's
        trace_id; None (the offline stream) falls back to the active
        bisection span's trace."""
        culprits = []

        with otrace.span("bisect", batch=batch_index, n=len(sigs)) as bspan:

            def rec(lo, hi, known_bad):
                if not known_bad and check(
                    sigs[lo:hi], msgs[lo:hi], batch_index
                ):
                    return
                if hi - lo == 1:
                    culprits.append(lo)
                    return
                metrics.count("bisections")
                mid = (lo + hi) // 2
                bspan.event("split", lo=lo, hi=hi)
                rec(lo, mid, False)
                rec(mid, hi, False)

            rec(0, len(sigs), True)
            if log is not None:
                for c in culprits:
                    log.append(
                        batch=batch_index,
                        credential=c,
                        reason="grouped batch rejected; culprit isolated by "
                        "bisection",
                        attempts=attempts,
                        trace_id=(
                            trace_ids[c]
                            if trace_ids is not None and c < len(trace_ids)
                            else None
                        ),
                        program=program,
                    )
                    metrics.count("dead_letters")
        return culprits

    return bisect


def _prefetch_launches(produce, depth):
    """Run `produce()` — a generator yielding launched batches — on a
    background worker thread, buffering at most `depth` items in a bounded
    queue: batch i+1 (and i+2, ...) encodes and dispatches while the main
    thread blocks on batch i's readback (the blocking wait releases the
    GIL, so the host-side encode genuinely overlaps it).

    Yields items in production order (the queue is FIFO, so the settle
    order and checkpoint sequence are identical to the serial path). A
    producer exception is re-raised here at the point of consumption —
    matching the serial path, where a non-retryable launch error
    propagates before later batches run. When the consumer abandons the
    generator (e.g. a settle raised), the worker is told to stop and the
    queue drained so a blocked put can finish.

    Observability: the "prefetch_wait" timer accumulates main-thread
    seconds blocked on the queue (near zero = the worker keeps the device
    fed) and "prefetched_batches" counts deliveries."""
    import queue as queue_mod
    import threading

    from . import metrics

    q = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()
    done = object()

    def _put(item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue_mod.Full:
                continue

    def work():
        try:
            for item in produce():
                if stop.is_set():
                    return
                _put((None, item))
            _put((None, done))
        except BaseException as e:  # re-raised on the consuming thread
            _put((e, None))

    t = threading.Thread(
        target=work, name="coconut-encode-prefetch", daemon=True
    )
    t.start()
    try:
        while True:
            with metrics.timer("prefetch_wait"):
                exc, item = q.get()
            if exc is not None:
                raise exc
            if item is done:
                return
            metrics.count("prefetched_batches")
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue_mod.Empty:
            pass
        t.join(timeout=5.0)


def verify_stream(
    source,
    n_batches,
    vk,
    params,
    backend,
    state_path=None,
    on_batch=None,
    mode="per_credential",
    pipeline=True,
    mesh=None,
    pipeline_depth=3,
    prefetch_depth=2,
    retry_policy=None,
    fallback_backend=None,
    dead_letter_path=None,
    bisect_failures=None,
):
    """Verify `n_batches` batches from `source(i) -> (sigs, messages_list)`.

    Resumes from `state_path` if present (batch granularity). Returns the
    final StreamState. `on_batch(i, result)` is called after each batch
    with the mode's result type (bools list / one bool) — the hook for
    collecting results or metrics. `pipeline=True` overlaps host encode of
    batch i+1 with device execution of batch i when the backend supports
    async dispatch; `pipeline_depth` batches stay in flight before the
    oldest is settled, keeping the device queue non-empty across the
    result-readback round trip (on the tunneled chip the RTT is
    ~0.2 s/batch, comparable to the grouped program's own 0.21 s device
    time, so depth 1 leaves the device idle half the time: measured
    2,520 -> 4,416 -> ~4,700 creds/s at depths 1/3/4 against the ~4,875/s
    device-time ceiling). Checkpoint lag is bounded by the depth: a crash
    re-runs at most `pipeline_depth` batches (at-least-once delivery, same
    as depth 1). `prefetch_depth` (when pipelining) moves `source(i)` and
    the host encode+dispatch onto a bounded background worker so batch
    i+1 encodes while the main thread blocks on batch i's readback —
    see _prefetch_launches; 0 disables the worker (encode stays on the
    calling thread, still overlapped with device execution by async
    dispatch alone). Checkpoint-lag and delivery semantics are unchanged:
    the worker only ENCODES ahead; settle order, retry accounting, and
    checkpoint writes stay on the calling thread, so a crash still re-runs
    at most `pipeline_depth` batches. `mesh` dp-shards the grouped mode
    over a jax Mesh (multi-chip config 5).

    Fault tolerance (module docstring for the full story):
      retry_policy      — retry.RetryPolicy; a batch whose dispatch or
                          readback raises TransientBackendError re-runs
                          the full dispatch+readback cycle with backoff,
                          up to the policy's attempt cap. None = one
                          attempt.
      fallback_backend  — backend instance or registry name ("python");
                          after retries exhaust, the batch re-dispatches
                          here synchronously so the stream completes
                          degraded. None = exhaustion propagates (the
                          checkpoint still allows resuming at the failed
                          batch).
      dead_letter_path  — JSONL file receiving culprit credentials from
                          grouped-failure bisection.
      bisect_failures   — force grouped-failure bisection on/off; default
                          (None) enables it in grouped and batched (RLC
                          combined, PR 16) modes when a dead_letter_path
                          is given. When a rejected
                          grouped batch is bisected, `failed` counts only
                          the culprits (granular accounting) while
                          `batches_failed` still counts the batch; the
                          raw grouped verdict (False) is what on_batch
                          sees.

    The checkpoint at `state_path` carries a schema version, a payload
    CRC, and this run's fingerprint (mode, vk digest): corrupt
    files are quarantined to `<state_path>.corrupt*` and the stream
    restarts cleanly; a fingerprint mismatch raises
    CheckpointMismatchError."""
    from .backend import get_backend
    from .retry import RetryPolicy, call_with_retry, note_attempt

    if backend is None or isinstance(backend, str):
        backend = get_backend(backend or "python")
    dispatch, record, is_async = _dispatchers(backend, mode, mesh=mesh)
    pipeline = pipeline and is_async  # sync backends: settle immediately
    if pipeline_depth < 1:
        raise ValueError("pipeline_depth must be >= 1")
    if prefetch_depth < 0:
        raise ValueError("prefetch_depth must be >= 0")
    if isinstance(fallback_backend, str):
        fallback_backend = get_backend(fallback_backend)
    fallback_dispatch = (
        _fallback_dispatcher(fallback_backend, mode)
        if fallback_backend is not None
        else None
    )
    policy = retry_policy
    if policy is None:
        # no retry ladder: transient errors go straight to the fallback
        # when one exists, else propagate exactly as they always did
        policy = RetryPolicy(
            max_attempts=1,
            base_delay=0.0,
            retryable=(
                (TransientBackendError,)
                if fallback_dispatch is not None
                else ()
            ),
        )
    if bisect_failures is None:
        bisect_failures = (
            mode in ("grouped", "batched") and dead_letter_path is not None
        )
    bisector = None
    if bisect_failures and mode in ("grouped", "batched"):
        bisector = _make_bisector(
            backend, fallback_backend, vk, params, policy, dead_letter_path,
            predicate="combined" if mode == "batched" else "grouped",
        )

    fingerprint = None
    if state_path:
        fingerprint = run_fingerprint(mode, vk, params)
    state = StreamState(state_path, fingerprint=fingerprint)

    def launch(i, sigs, msgs):
        """Dispatch batch i now (pipelining) and return (finalize,
        attempts, span). finalize() re-runs the whole dispatch+readback
        cycle under the retry ladder, then the fallback, before giving
        up. The batch's "stream_batch" trace starts here (possibly on the
        prefetch worker thread) and is handed to settle() with the rest
        of the launch state."""
        attempts = []
        box = [None]
        bspan = otrace.start_span(
            "stream_batch", root=True, batch=i, n=len(sigs)
        )
        with otrace.use(bspan):
            with otrace.span("dispatch", backend=type(backend).__name__):
                try:
                    box[0] = dispatch(sigs, msgs, vk, params)
                except policy.retryable as e:
                    note_attempt(attempts, e)
                    otrace.event(
                        "attempt_failed",
                        attempt=len(attempts),
                        error=type(e).__name__,
                    )

        def cycle():
            fin, box[0] = box[0], None
            if fin is None:
                fin = dispatch(sigs, msgs, vk, params)
            return fin()

        fallback = (
            (lambda: fallback_dispatch(sigs, msgs, vk, params)())
            if fallback_dispatch is not None
            else None
        )

        def finalize():
            return call_with_retry(
                cycle, policy, key=i, attempts=attempts, fallback=fallback
            )

        return finalize, attempts, bspan

    def settle(idx, finalize, n, sigs, msgs, attempts, bspan):
        with otrace.use(bspan):
            try:
                with otrace.span("device"):
                    result = finalize()
            except BaseException as e:
                bspan.end(error=type(e).__name__)
                raise
            if bisector is not None and not result:
                culprits = bisector(sigs, msgs, idx, attempts)
                state.batches_failed += 1
                state.failed += len(culprits)
                state.verified += n - len(culprits)
            else:
                record(state, result, n)
            # deliver results BEFORE persisting the checkpoint: a crash
            # inside on_batch then re-runs the batch (at-least-once
            # delivery) instead of silently dropping its verdicts
            if on_batch is not None:
                on_batch(idx, result)
            state.next_batch = idx + 1
            state.save()
            bspan.event("checkpoint", next_batch=idx + 1)
        bspan.end(
            ok=bool(result) if not isinstance(result, list) else None
        )

    def _launched():
        for i in range(state.next_batch, n_batches):
            sigs, messages_list = source(i)
            finalize, attempts, bspan = launch(i, sigs, messages_list)
            yield (
                i,
                finalize,
                len(sigs),
                sigs,
                messages_list,
                attempts,
                bspan,
            )

    launched = (
        _prefetch_launches(_launched, prefetch_depth)
        if pipeline and prefetch_depth > 0
        else _launched()
    )
    pending = []  # [(index, finalize, batch_size, sigs, msgs, attempts)]
    try:
        for item in launched:
            if not pipeline:
                settle(*item)
                continue
            pending.append(item)
            if len(pending) >= pipeline_depth:
                settle(*pending.pop(0))
    finally:
        # a settle error must tear the prefetch worker down NOW, not at
        # GC (the propagating traceback pins this frame — and with it the
        # generator — alive), so the worker never lingers blocked on a
        # full queue
        launched.close()
    for p in pending:
        settle(*p)
    return state
