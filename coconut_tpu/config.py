"""FrameworkConfig — the single explicit configuration struct.

SURVEY.md §5 ("config / flag system"): the reference scatters its knobs
across cargo features (group assignment, with a non-forwarding quirk —
SURVEY §1) and bare function parameters. The rebuild centralizes them:
group assignment is a runtime value (GroupContext, params.py), and the
execution knobs live here. `resolve_backend()` is the one place a backend
name becomes an instance.

Env overrides (useful for benches/CI): COCONUT_BACKEND, COCONUT_BATCH.
"""

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass
class FrameworkConfig:
    # protocol shape (reference README.md:11-15)
    msg_count: int = 6
    threshold: int = 3
    total_signers: int = 5
    count_hidden: int = 2
    label: bytes = b"coconut-tpu"
    # group assignment: "G1" = signatures in G1 (default, mirrors ps_sig's
    # default feature; SURVEY §1 wiring quirk made real config here)
    signature_group: str = "G1"
    # execution
    backend: str = field(
        default_factory=lambda: os.environ.get("COCONUT_BACKEND", "python")
    )
    batch_size: int = field(
        default_factory=lambda: int(os.environ.get("COCONUT_BATCH", "1024"))
    )
    # multi-chip mesh shape (dp, tp) for the sharded path (tpu/shard.py);
    # None = single device
    mesh_shape: Optional[Tuple[int, int]] = None

    def group_context(self):
        from .params import SIGNATURES_IN_G1, SIGNATURES_IN_G2

        if self.signature_group == "G1":
            return SIGNATURES_IN_G1
        if self.signature_group == "G2":
            return SIGNATURES_IN_G2
        raise ValueError("signature_group must be 'G1' or 'G2'")

    def make_params(self):
        from .params import Params

        return Params.new(self.msg_count, self.label, ctx=self.group_context())

    def resolve_backend(self):
        from .backend import get_backend

        return get_backend(self.backend)

    def make_mesh(self):
        if self.mesh_shape is None:
            return None
        from .tpu.shard import default_mesh

        ndp, ntp = self.mesh_shape
        return default_mesh(ndp=ndp, ntp=ntp)
